"""Deprecated shim: the tile sweep is now a first-class bench stage.

Run ``python bench.py --tile-sweep [--tile-sweep-shape PxN]`` instead —
it sweeps BOTH Pallas kernels' tiles (the in-kernel score AND the
priced min2 reduction the warm repair rides), emits one parseable JSON
artifact naming the winning combination, and degrades to interpret-mode
smoke sizes on cpu-only hosts instead of requiring a device tunnel.
This file forwards there so existing invocations keep working.
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main() -> int:
    args = [sys.executable, os.path.join(REPO, "bench.py"), "--tile-sweep"]
    if len(sys.argv) > 2:
        args += ["--tile-sweep-shape", f"{sys.argv[1]}x{sys.argv[2]}"]
    elif len(sys.argv) > 1:
        args += ["--tile-sweep-shape", f"{sys.argv[1]}x10000"]
    print("docs/bench_tile_sweep.py is a shim; running:",
          " ".join(args[1:]), file=sys.stderr)
    return subprocess.call(args)


if __name__ == "__main__":
    sys.exit(main())
