"""Fused-kernel tile sweep on real TPU hardware (BASELINE.md roofline).

Sweeps BLANCE_FUSED_TILE_P/N over aligned candidates at the north-star
shape, one subprocess per combination (the tiles are read once at import
— see ops/score_fused.py), timing the converged fused solve exactly like
bench.py's bench_tpu.  Run only with a healthy device tunnel; each
subprocess compiles (~40 s) then times RUNS solves.

Usage: python docs/bench_tile_sweep.py [P] [N]
Prints one JSON line per tile combination.
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHILD = r"""
import json, sys, time
sys.path.insert(0, {repo!r})
import numpy as np
import bench
import jax.numpy as jnp
from blance_tpu.plan.tensor import solve_dense_converged
from blance_tpu.ops import score_fused
P, N = {P}, {N}
args = bench.build_dense(P, N)
(prev, pweights, nweights, valid, stickiness, gids, gid_valid,
 constraints, rules) = args
dev = [jnp.asarray(a) for a in
       (prev, pweights, nweights, valid, stickiness, gids, gid_valid)]
def run():
    out = solve_dense_converged(*dev, constraints, rules, fused_score="on")
    np.asarray(out[:, 0, 0])  # force completion (axon block_until_ready quirk)
    return out
t0 = time.perf_counter(); run(); compile_s = time.perf_counter() - t0
times = []
for _ in range(4):
    t0 = time.perf_counter(); run(); times.append(time.perf_counter() - t0)
print(json.dumps({{
    "tile_p": score_fused._TILE_P, "tile_n": score_fused._TILE_N,
    "compile_s": round(compile_s, 1),
    "solve_ms_min": round(min(times) * 1000, 2),
    "solve_ms_runs": [round(t * 1000, 2) for t in times]}}))
"""


def main():
    P = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    N = int(sys.argv[2]) if len(sys.argv) > 2 else 10_000
    child = _CHILD.format(repo=REPO, P=P, N=N)
    for tile_p in (128, 256, 512):
        for tile_n in (1024, 2048, 4096):
            env = dict(os.environ,
                       BLANCE_FUSED_TILE_P=str(tile_p),
                       BLANCE_FUSED_TILE_N=str(tile_n))
            t0 = time.time()
            try:
                r = subprocess.run(
                    [sys.executable, "-c", child], env=env, timeout=600,
                    capture_output=True, text=True, check=True)
                lines = r.stdout.strip().splitlines()
                print(lines[-1] if lines else json.dumps(
                    {"tile_p": tile_p, "tile_n": tile_n,
                     "error": "no output"}), flush=True)
            except subprocess.TimeoutExpired:
                print(json.dumps({"tile_p": tile_p, "tile_n": tile_n,
                                  "error": "timeout",
                                  "elapsed_s": round(time.time() - t0)}),
                      flush=True)
            except subprocess.CalledProcessError as e:
                err = (e.stderr or "").strip().splitlines()
                print(json.dumps({
                    "tile_p": tile_p, "tile_n": tile_n,
                    "error": err[-1][-200:] if err else "failed"}),
                    flush=True)


if __name__ == "__main__":
    main()
