"""Scheduler-throughput measurement for BASELINE.md's orchestrator table.

Drives the asyncio CSP orchestrator with a no-op assign callback (data
plane instant, so scheduling overhead is the whole cost) in BOTH
semantics modes:

  - interrupt_on_first_feed=True  — the DEFAULT, reference-fidelity mode
    (re-runs move selection after every accepted feed,
    /root/reference/orchestrate.go:566-580)
  - interrupt_on_first_feed=False — throughput mode (commit the whole
    feasible batch per round)

Usage: python docs/bench_scheduler.py [--quick]
Prints one JSON line per (mode, size) with ops/s.
"""

import argparse
import asyncio
import json
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from blance_tpu import Partition, PartitionModelState
from blance_tpu.orchestrate import OrchestratorOptions, orchestrate_moves

MODEL = {
    "primary": PartitionModelState(priority=0, constraints=1),
    "replica": PartitionModelState(priority=1, constraints=1),
}


def shifted_maps(P, nodes):
    """Every partition moves primary/replica one node to the right."""
    beg, end = {}, {}
    n = len(nodes)
    for i in range(P):
        name = str(i)
        beg[name] = Partition(name, {"primary": [nodes[i % n]],
                                     "replica": [nodes[(i + 1) % n]]})
        end[name] = Partition(name, {"primary": [nodes[(i + 1) % n]],
                                     "replica": [nodes[(i + 2) % n]]})
    return beg, end


async def drive(options, beg, end, nodes, counter):
    def assign(stop_ch, node, partitions, states, ops):
        counter[0] += len(partitions)
        return None

    o = orchestrate_moves(MODEL, options, nodes, beg, end, assign)
    async for _ in o.progress_ch():
        pass
    o.stop()


def measure(P, N, interrupt):
    nodes = [f"n{i}" for i in range(N)]
    beg, end = shifted_maps(P, nodes)
    counter = [0]
    opts = OrchestratorOptions(
        max_concurrent_partition_moves_per_node=4,
        interrupt_on_first_feed=interrupt)
    t0 = time.perf_counter()
    asyncio.run(drive(opts, beg, end, nodes, counter))
    dt = time.perf_counter() - t0
    row = {"P": P, "N": N,
           "mode": "default" if interrupt else "throughput",
           "interrupt_on_first_feed": interrupt,
           "ops": counter[0], "seconds": round(dt, 2),
           "ops_per_s": round(counter[0] / dt)}
    print(json.dumps(row), flush=True)
    return row


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    args = ap.parse_args()
    sizes = [(1000, 50)] if args.quick else [(8_000, 200), (32_000, 800)]
    for P, N in sizes:
        for interrupt in (True, False):
            measure(P, N, interrupt)
