"""Measure the five BASELINE.json configs: native C++ CPU planner vs the
batched TPU solver, plus the delta-rebalance churn metric.

Usage: python bench_configs.py [--json out.json]

Unlike bench.py (the driver's single-line benchmark), this is the full
baseline table generator for BASELINE.md.
"""

import argparse
import json
import sys
import time

import numpy as np

import blance_tpu as bt
from blance_tpu.moves.batch import calc_all_moves


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def make_cluster(P, N, model, rng, weights=False, racks=0):
    nodes = [f"n{i:05d}" for i in range(N)]
    parts = {str(i): bt.Partition(str(i), {}) for i in range(P)}
    opts_kwargs = {}
    if weights:
        opts_kwargs["partition_weights"] = {
            str(i): int(rng.integers(1, 5)) for i in range(0, P, 7)}
        opts_kwargs["node_weights"] = {
            nodes[i]: int(rng.integers(1, 4)) for i in range(0, N, 5)}
        opts_kwargs["state_stickiness"] = {"primary": 100}
    if racks:
        hier = {n: f"r{i % racks}" for i, n in enumerate(nodes)}
        hier.update({f"r{i}": "z0" for i in range(racks)})
        opts_kwargs["node_hierarchy"] = hier
        opts_kwargs["hierarchy_rules"] = {
            "replica": [bt.HierarchyRule(2, 1)]}
    return nodes, parts, bt.PlanOptions(**opts_kwargs)


def time_backend(backend, prev, parts, nodes, removes, adds, model, opts,
                 repeats=1):
    best = None
    result = None
    for _ in range(repeats):
        t0 = time.perf_counter()
        result = bt.plan_next_map(prev, parts, nodes, removes, adds, model,
                                  opts, backend=backend)
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    return best, result


def run_config(name, P, N, model, rng, weights=False, racks=0,
               delta=0.0, skip_cpu=False, tpu_repeats=2):
    nodes, parts, opts = make_cluster(P, N, model, rng, weights, racks)
    empty = {k: v.copy() for k, v in parts.items()}

    # Warm prev map via the TPU backend (also warms the jit cache).
    _, (prev, _w) = time_backend("tpu", empty, parts, nodes, [], nodes,
                                 model, opts)

    removes, adds = [], []
    if delta:
        k = int(N * delta)
        removes = list(rng.choice(nodes, k, replace=False))
        adds = None

    row = {"config": name, "P": P, "N": N}

    t_tpu, (tpu_map, tpu_warn) = time_backend(
        "tpu", prev, prev, nodes, removes, adds, model, opts,
        repeats=tpu_repeats)
    row["tpu_s"] = round(t_tpu, 4)
    row["tpu_warnings"] = sum(len(v) for v in tpu_warn.values())

    if not skip_cpu:
        t_cpu, (cpu_map, _) = time_backend(
            "native", prev, prev, nodes, removes, adds, model, opts)
        row["cpu_native_s"] = round(t_cpu, 4)
        row["speedup"] = round(t_cpu / t_tpu, 1)

    if delta:
        t0 = time.perf_counter()
        moves = calc_all_moves(prev, tpu_map, model)
        row["diff_s"] = round(time.perf_counter() - t0, 3)
        total_ops = sum(len(v) for v in moves.values())
        # Lower bound: copies on removed nodes must move (one op each) and
        # pair with an add.
        removed = set(removes)
        displaced = sum(
            1 for p in prev.values() for ns in p.nodes_by_state.values()
            for n in ns if n in removed)
        row["churn_ops"] = total_ops
        row["churn_lower_bound"] = 2 * displaced
    log(f"{name}: {row}")
    return row


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--json", default=None)
    args = ap.parse_args()
    rng = np.random.default_rng(0)

    m_1p1r = bt.model(primary=(0, 1), replica=(1, 1))
    m_1p2r = bt.model(primary=(0, 1), replica=(1, 2))
    m_multi = bt.model(primary=(0, 2), replica=(1, 1), read_only=(2, 1))

    rows = [
        run_config("1: 1024x8 primary+1 replica flat",
                   1024, 8, m_1p1r, rng),
        run_config("2: 4096x64 primary+2 replicas rack/zone rules",
                   4096, 64, m_1p2r, rng, racks=8),
        run_config("3: heterogeneous weights+stickiness 16k x 256",
                   16384, 256, m_1p1r, rng, weights=True),
        run_config("4: multi-primary + read-only 100k x 1k",
                   100_000, 1000, m_multi, rng),
        run_config("5: delta rebalance -20% of 10k nodes, churn",
                   32_768, 10_000, m_1p1r, rng, delta=0.2),
    ]
    print(json.dumps(rows, indent=1))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(rows, f, indent=1)


if __name__ == "__main__":
    main()
