/* blance_tpu native marshalling layer (CPython extension).
 *
 * The planner's compute runs on TPU; at 100k partitions the end-to-end
 * wall-clock is dominated by the host-side conversion between the app's
 * string-keyed PartitionMap (the reference's data model, api.go:24-36) and
 * the dense int32 tensors the solver consumes (BASELINE.md names this the
 * next optimization after the on-device solve).  These two loops touch
 * every (partition, state, slot) cell once and are pure dict/list
 * traversal, so they live here in C:
 *
 *   fill_prev:  PartitionMap -> assign[P, S, R] int32 node ids
 *   build_map:  per-state name rows -> {name: Partition} result map
 *
 * Loaded as a real extension module (see blance_tpu/core/marshal.py), not
 * ctypes — it must traverse Python objects.  Any structural surprise
 * (non-dict nodes_by_state, non-list rows) raises, and the caller falls
 * back to the pure-Python path.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>

/* Cached attribute name "nodes_by_state". */
static PyObject *str_nodes_by_state = NULL;
/* Cached attribute name "name" + a shared empty args tuple for tp_new. */
static PyObject *str_name_attr = NULL;
static PyObject *empty_args = NULL;

/* Partition construction bypasses the Python-level dataclass __init__
 * (measured: ~55% of build_map wall-clock at 100k partitions is those
 * 100k __init__ frames) when — and only when — the class is shaped like
 * the plain dataclass we ship: object's __new__, generic setattr (no
 * __slots__, not frozen), and no __post_init__ hook that skipping
 * __init__ would silence.  Anything else takes the normal call. */
static int
fast_ctor_ok(PyTypeObject *tp)
{
    if (tp->tp_new != PyBaseObject_Type.tp_new ||
        tp->tp_setattro != PyObject_GenericSetAttr)
        return 0;
    if (PyObject_HasAttrString((PyObject *)tp, "__post_init__"))
        return 0;
    /* The __init__ the normal call would run must be dataclass-generated:
     * the first class in the MRO providing __init__ must have gotten it
     * from its own @dataclass decoration (i.e. that same class's __dict__
     * also holds __dataclass_fields__).  A subclass overriding __init__
     * by hand inherits __dataclass_fields__ but defines __init__ in its
     * own __dict__ alone — skipping its validation would be silent. */
    PyObject *mro = tp->tp_mro;
    int generated = 0;
    if (mro != NULL && PyTuple_Check(mro)) {
        for (Py_ssize_t i = 0; i < PyTuple_GET_SIZE(mro); i++) {
            PyObject *c = PyTuple_GET_ITEM(mro, i);
            if (!PyType_Check(c))
                break;
            PyObject *d = ((PyTypeObject *)c)->tp_dict;
            if (d == NULL || !PyDict_Check(d))
                break;
            if (PyDict_GetItemString(d, "__init__") != NULL) {
                generated =
                    PyDict_GetItemString(d, "__dataclass_fields__") != NULL;
                break;
            }
        }
    }
    if (!generated)
        return 0;
    /* The bypass writes exactly {name, nodes_by_state}; a subclass with
     * more dataclass fields (or none — a hand-rolled class) would come
     * out partially initialized, so require that exact field set. */
    PyObject *fields =
        PyObject_GetAttrString((PyObject *)tp, "__dataclass_fields__");
    if (fields == NULL) {
        PyErr_Clear();
        return 0;
    }
    int ok = PyDict_Check(fields) && PyDict_GET_SIZE(fields) == 2 &&
             PyDict_GetItemWithError(fields, str_name_attr) != NULL &&
             PyDict_GetItemWithError(fields, str_nodes_by_state) != NULL;
    if (PyErr_Occurred()) {
        Py_DECREF(fields);
        return -1;
    }
    Py_DECREF(fields);
    return ok;
}

static PyObject *
make_partition(PyObject *cls, int fast, PyObject *name, PyObject *nbs)
{
    if (fast) {
        PyTypeObject *tp = (PyTypeObject *)cls;
        PyObject *part = tp->tp_new(tp, empty_args, NULL);
        if (part == NULL)
            return NULL;
        if (PyObject_SetAttr(part, str_name_attr, name) < 0 ||
            PyObject_SetAttr(part, str_nodes_by_state, nbs) < 0) {
            Py_DECREF(part);
            return NULL;
        }
        return part;
    }
    return PyObject_CallFunctionObjArgs(cls, name, nbs, NULL);
}

/* fill_prev(buf, P, S, R, partitions, prev_map, pta, state_index,
 *           node_index) -> None
 *
 * buf: writable C-contiguous int32 buffer of P*S*R elements; filled with
 * node ids (-1 = empty).  For each partition name, the source Partition is
 * prev_map.get(name) or pta.get(name); states absent from state_index and
 * nodes absent from node_index are skipped (the Python encoder's exact
 * behavior, core/encode.py).
 */
static PyObject *
fill_prev(PyObject *self, PyObject *args)
{
    PyObject *buf_obj, *partitions, *prev_map, *pta, *state_index, *node_index;
    Py_ssize_t P, S, R;

    if (!PyArg_ParseTuple(args, "OnnnOOOOO", &buf_obj, &P, &S, &R,
                          &partitions, &prev_map, &pta, &state_index,
                          &node_index))
        return NULL;

    if (!PyList_Check(partitions) || !PyDict_Check(prev_map) ||
        !PyDict_Check(pta) || !PyDict_Check(state_index) ||
        !PyDict_Check(node_index)) {
        PyErr_SetString(PyExc_TypeError, "fill_prev: unexpected arg types");
        return NULL;
    }
    if (PyList_GET_SIZE(partitions) != P) {
        PyErr_SetString(PyExc_ValueError, "fill_prev: len(partitions) != P");
        return NULL;
    }

    Py_buffer view;
    if (PyObject_GetBuffer(buf_obj, &view,
                           PyBUF_C_CONTIGUOUS | PyBUF_WRITABLE) < 0)
        return NULL;
    if (view.len != (Py_ssize_t)(P * S * R * 4) || view.itemsize != 4) {
        PyBuffer_Release(&view);
        PyErr_SetString(PyExc_ValueError, "fill_prev: buffer shape mismatch");
        return NULL;
    }
    int32_t *out = (int32_t *)view.buf;
    for (Py_ssize_t i = 0; i < P * S * R; i++)
        out[i] = -1;

    for (Py_ssize_t pi = 0; pi < P; pi++) {
        PyObject *name = PyList_GET_ITEM(partitions, pi); /* borrowed */
        PyObject *src = PyDict_GetItemWithError(prev_map, name);
        if (src == NULL) {
            if (PyErr_Occurred())
                goto fail;
            src = PyDict_GetItemWithError(pta, name);
            if (src == NULL) {
                if (PyErr_Occurred())
                    goto fail;
                continue;
            }
        }
        PyObject *nbs = PyObject_GetAttr(src, str_nodes_by_state); /* new */
        if (nbs == NULL)
            goto fail;
        if (!PyDict_Check(nbs)) {
            Py_DECREF(nbs);
            PyErr_SetString(PyExc_TypeError,
                            "fill_prev: nodes_by_state is not a dict");
            goto fail;
        }
        PyObject *state, *nodes;
        Py_ssize_t pos = 0;
        while (PyDict_Next(nbs, &pos, &state, &nodes)) {
            PyObject *si_obj = PyDict_GetItemWithError(state_index, state);
            if (si_obj == NULL) {
                if (PyErr_Occurred()) {
                    Py_DECREF(nbs);
                    goto fail;
                }
                continue;
            }
            Py_ssize_t si = PyLong_AsSsize_t(si_obj);
            if (si == -1 && PyErr_Occurred()) {
                Py_DECREF(nbs);
                goto fail; /* non-int index: propagate (caller falls back) */
            }
            if (si < 0 || si >= S)
                continue;
            if (!PyList_Check(nodes)) {
                Py_DECREF(nbs);
                PyErr_SetString(PyExc_TypeError,
                                "fill_prev: node list is not a list");
                goto fail;
            }
            Py_ssize_t nn = PyList_GET_SIZE(nodes);
            if (nn > R)
                nn = R;
            int32_t *row = out + (pi * S + si) * R;
            for (Py_ssize_t ri = 0; ri < nn; ri++) {
                PyObject *node = PyList_GET_ITEM(nodes, ri); /* borrowed */
                PyObject *ni_obj = PyDict_GetItemWithError(node_index, node);
                if (ni_obj == NULL) {
                    if (PyErr_Occurred()) {
                        Py_DECREF(nbs);
                        goto fail;
                    }
                    continue; /* unknown node name -> stays -1 */
                }
                long ni = PyLong_AsLong(ni_obj);
                if (ni == -1 && PyErr_Occurred()) {
                    Py_DECREF(nbs);
                    goto fail;
                }
                if (ni >= 0 && ni < INT32_MAX)
                    row[ri] = (int32_t)ni;
            }
        }
        Py_DECREF(nbs);
    }

    PyBuffer_Release(&view);
    Py_RETURN_NONE;

fail:
    PyBuffer_Release(&view);
    return NULL;
}

/* build_map(partition_cls, partitions, mod_names, rows_per_state, pta,
 *           solved_states, removed_set) -> dict
 *
 * partitions: list[str] (P names, result order)
 * mod_names:  list[str] (M modeled state names)
 * rows_per_state: list of M lists, each P node-name lists (pre-trimmed)
 * pta:        dict name -> source Partition (for unmodeled-state passthrough)
 * solved_states: set of modeled state names
 * removed_set: set of removed node names (stripped from passthrough lists)
 *
 * Returns {name: partition_cls(name, nodes_by_state_dict)}.  The fast path
 * (source has only modeled states) never allocates intermediates beyond the
 * per-partition dict.
 */
static PyObject *
build_map(PyObject *self, PyObject *args)
{
    PyObject *cls, *partitions, *mod_names, *rows_per_state, *pta;
    PyObject *solved_states, *removed_set;

    if (!PyArg_ParseTuple(args, "OOOOOOO", &cls, &partitions, &mod_names,
                          &rows_per_state, &pta, &solved_states,
                          &removed_set))
        return NULL;

    if (!PyList_Check(partitions) || !PyList_Check(mod_names) ||
        !PyList_Check(rows_per_state) || !PyDict_Check(pta) ||
        !PyAnySet_Check(solved_states) || !PyAnySet_Check(removed_set)) {
        PyErr_SetString(PyExc_TypeError, "build_map: unexpected arg types");
        return NULL;
    }

    Py_ssize_t P = PyList_GET_SIZE(partitions);
    Py_ssize_t M = PyList_GET_SIZE(mod_names);
    if (PyList_GET_SIZE(rows_per_state) != M) {
        PyErr_SetString(PyExc_ValueError,
                        "build_map: len(rows_per_state) != len(mod_names)");
        return NULL;
    }
    for (Py_ssize_t m = 0; m < M; m++) {
        PyObject *rows = PyList_GET_ITEM(rows_per_state, m);
        if (!PyList_Check(rows) || PyList_GET_SIZE(rows) != P) {
            PyErr_SetString(PyExc_ValueError,
                            "build_map: rows_per_state shape mismatch");
            return NULL;
        }
    }

    PyObject *result = PyDict_New();
    if (result == NULL)
        return NULL;

    int fast = PyType_Check(cls) ? fast_ctor_ok((PyTypeObject *)cls) : 0;
    if (fast < 0) { /* error during the probe */
        Py_DECREF(result);
        return NULL;
    }

    for (Py_ssize_t pi = 0; pi < P; pi++) {
        PyObject *name = PyList_GET_ITEM(partitions, pi); /* borrowed */
        PyObject *nbs = PyDict_New();                     /* new */
        if (nbs == NULL)
            goto fail;

        /* Passthrough: source states outside the solved set survive, with
         * removed nodes stripped (order-preserving). */
        PyObject *src = PyDict_GetItemWithError(pta, name);
        if (src == NULL && PyErr_Occurred()) {
            Py_DECREF(nbs);
            goto fail;
        }
        if (src != NULL) {
            PyObject *src_nbs = PyObject_GetAttr(src, str_nodes_by_state);
            if (src_nbs == NULL) {
                Py_DECREF(nbs);
                goto fail;
            }
            if (!PyDict_Check(src_nbs)) {
                Py_DECREF(src_nbs);
                Py_DECREF(nbs);
                PyErr_SetString(PyExc_TypeError,
                                "build_map: nodes_by_state is not a dict");
                goto fail;
            }
            PyObject *state, *nodes;
            Py_ssize_t pos = 0;
            while (PyDict_Next(src_nbs, &pos, &state, &nodes)) {
                int solved = PySet_Contains(solved_states, state);
                if (solved < 0) {
                    Py_DECREF(src_nbs);
                    Py_DECREF(nbs);
                    goto fail;
                }
                if (solved)
                    continue;
                if (!PyList_Check(nodes)) {
                    Py_DECREF(src_nbs);
                    Py_DECREF(nbs);
                    PyErr_SetString(PyExc_TypeError,
                                    "build_map: node list is not a list");
                    goto fail;
                }
                Py_ssize_t nn = PyList_GET_SIZE(nodes);
                PyObject *kept = PyList_New(0); /* new */
                if (kept == NULL) {
                    Py_DECREF(src_nbs);
                    Py_DECREF(nbs);
                    goto fail;
                }
                for (Py_ssize_t i = 0; i < nn; i++) {
                    PyObject *node = PyList_GET_ITEM(nodes, i);
                    int rem = PySet_Contains(removed_set, node);
                    if (rem < 0 || (rem == 0 &&
                                    PyList_Append(kept, node) < 0)) {
                        Py_DECREF(kept);
                        Py_DECREF(src_nbs);
                        Py_DECREF(nbs);
                        goto fail;
                    }
                }
                if (PyDict_SetItem(nbs, state, kept) < 0) {
                    Py_DECREF(kept);
                    Py_DECREF(src_nbs);
                    Py_DECREF(nbs);
                    goto fail;
                }
                Py_DECREF(kept);
            }
            Py_DECREF(src_nbs);
        }

        /* Solved states overwrite any same-named passthrough. */
        for (Py_ssize_t m = 0; m < M; m++) {
            PyObject *sname = PyList_GET_ITEM(mod_names, m);
            PyObject *rows = PyList_GET_ITEM(rows_per_state, m);
            PyObject *row = PyList_GET_ITEM(rows, pi); /* borrowed */
            if (PyDict_SetItem(nbs, sname, row) < 0) {
                Py_DECREF(nbs);
                goto fail;
            }
        }

        PyObject *part = make_partition(cls, fast, name, nbs); /* new */
        Py_DECREF(nbs);
        if (part == NULL)
            goto fail;
        if (PyDict_SetItem(result, name, part) < 0) {
            Py_DECREF(part);
            goto fail;
        }
        Py_DECREF(part);
    }

    return result;

fail:
    Py_DECREF(result);
    return NULL;
}

/* max_slots(partitions, prev_map, pta, state_index) -> int
 *
 * The widest modeled-state node list across all source partitions — the
 * R dimension scan the Python encoder does before allocating (encode.py).
 */
static PyObject *
max_slots(PyObject *self, PyObject *args)
{
    PyObject *partitions, *prev_map, *pta, *state_index;

    if (!PyArg_ParseTuple(args, "OOOO", &partitions, &prev_map, &pta,
                          &state_index))
        return NULL;
    if (!PyList_Check(partitions) || !PyDict_Check(prev_map) ||
        !PyDict_Check(pta) || !PyDict_Check(state_index)) {
        PyErr_SetString(PyExc_TypeError, "max_slots: unexpected arg types");
        return NULL;
    }

    Py_ssize_t P = PyList_GET_SIZE(partitions);
    Py_ssize_t r_max = 0;
    for (Py_ssize_t pi = 0; pi < P; pi++) {
        PyObject *name = PyList_GET_ITEM(partitions, pi);
        PyObject *src = PyDict_GetItemWithError(prev_map, name);
        if (src == NULL) {
            if (PyErr_Occurred())
                return NULL;
            src = PyDict_GetItemWithError(pta, name);
            if (src == NULL) {
                if (PyErr_Occurred())
                    return NULL;
                continue;
            }
        }
        PyObject *nbs = PyObject_GetAttr(src, str_nodes_by_state);
        if (nbs == NULL)
            return NULL;
        if (!PyDict_Check(nbs)) {
            Py_DECREF(nbs);
            PyErr_SetString(PyExc_TypeError,
                            "max_slots: nodes_by_state is not a dict");
            return NULL;
        }
        PyObject *state, *nodes;
        Py_ssize_t pos = 0;
        while (PyDict_Next(nbs, &pos, &state, &nodes)) {
            int modeled = PyDict_Contains(state_index, state);
            if (modeled < 0) {
                Py_DECREF(nbs);
                return NULL;
            }
            if (!modeled || !PyList_Check(nodes))
                continue;
            Py_ssize_t nn = PyList_GET_SIZE(nodes);
            if (nn > r_max)
                r_max = nn;
        }
        Py_DECREF(nbs);
    }
    return PyLong_FromSsize_t(r_max);
}

static PyMethodDef marshal_methods[] = {
    {"max_slots", max_slots, METH_VARARGS,
     "Widest modeled-state node list across all source partitions."},
    {"fill_prev", fill_prev, METH_VARARGS,
     "Fill a dense [P, S, R] int32 buffer from a PartitionMap."},
    {"build_map", build_map, METH_VARARGS,
     "Build a {name: Partition} map from per-state name rows."},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef marshal_module = {
    PyModuleDef_HEAD_INIT,
    "_blance_marshal",
    "Native PartitionMap <-> dense array marshalling.",
    -1,
    marshal_methods,
};

PyMODINIT_FUNC
PyInit__blance_marshal(void)
{
    str_nodes_by_state = PyUnicode_InternFromString("nodes_by_state");
    if (str_nodes_by_state == NULL)
        return NULL;
    str_name_attr = PyUnicode_InternFromString("name");
    if (str_name_attr == NULL)
        return NULL;
    empty_args = PyTuple_New(0);
    if (empty_args == NULL)
        return NULL;
    return PyModule_Create(&marshal_module);
}
