// Native exact greedy planner — the hot loop of the "greedy" backend in C++.
//
// Replicates blance_tpu/plan/greedy.py's inner pass (itself a faithful
// reimplementation of the reference's planNextMapInnerEx,
// /root/reference/plan.go:60-331) over dense ids, so results are
// bit-identical to the Python planner: same double-precision score
// arithmetic in the same order, same (score, node-position) ordering, same
// hierarchy include/exclude semantics, same warning conditions.
//
// The Python side (blance_tpu/plan/native.py) interns names, computes the
// per-state partition orderings (the partitionSorter, which is string-key
// based), seeds the state-node counts, and decodes results; this file owns
// the O(states * partitions * nodes) scoring loop.
//
// Build: g++ -O3 -shared -fPIC -o _native_planner.so planner.cpp

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <unordered_map>
#include <vector>

namespace {

struct Ctx {
  int32_t P, N, S, R;
  int32_t num_partitions;          // len(prev_map), the score normalizer
  const int32_t* constraints;     // [S]
  const int32_t* state_priority;  // [S]
  const double* pweights;         // [P]
  const double* nweights;         // [N]  (default 1.0)
  const uint8_t* nweight_set;     // [N]  1 iff the caller specified a weight
  const uint8_t* valid;           // [N]  0 for removed nodes
  const double* stickiness;       // [P*S]
  // Hierarchy: globally interned ancestor ids per level; -1 = missing.
  int32_t levels;                 // number of levels incl. level 0
  const int32_t* aid;             // [levels*N]
  const uint8_t* is_leaf;         // [N] 0 iff the node has hierarchy children
  // Rules per state: offsets into (inc, exc) pair array.
  const int32_t* rule_off;        // [S+1]
  const int32_t* rule_inc;        // [total_rules]
  const int32_t* rule_exc;        // [total_rules]
  uint8_t use_booster;            // cbgt booster: max(-w, stickiness)
  uint8_t has_hierarchy;          // hierarchy_rules option was non-null

  // Partition ordering inputs (the partitionSorter, plan.go:519-562).
  // static_rank: rank by (weight key, name key, name) — a static total
  // order.  cat0[s*P+p]: prev holders of state s sit on removed nodes.
  // The category-1 test (not yet on any added node) depends on the
  // partition's CURRENT assignment, so visit order is recomputed per state.
  const int32_t* static_rank;     // [P]
  const uint8_t* cat0;            // [S*P]
  const uint8_t* add_mask;        // [N]
  uint8_t has_adds;               // nodes_to_add was non-nil

  int32_t* assign;                // [P*S*R] in/out, -1 padded
  double* counts;                 // [S*N] state-node counts (seeded)
  int32_t* shortfall;             // [P*S] out: missing copies per (p,s)
};

struct NodeScore {
  double score;
  int32_t node;  // == position tie-break (ids are nodes_all order)
};

inline bool score_less(const NodeScore& a, const NodeScore& b) {
  if (a.score < b.score) return true;
  if (a.score > b.score) return false;
  return a.node < b.node;
}

// Is candidate c inside anchor a's level-`inc` subtree?  True iff some
// ancestor of c equals a's inc-level ancestor (handles non-uniform depth).
inline bool under(const Ctx& c, int32_t cand, int32_t anc_id) {
  if (anc_id < 0) return false;
  for (int32_t l = 0; l < c.levels; ++l) {
    if (c.aid[l * c.N + cand] == anc_id) return true;
  }
  return false;
}

class Planner {
 public:
  explicit Planner(const Ctx& c) : c_(c) {
    node_partition_counts_.assign(c_.N, 0.0);
    for (int32_t s = 0; s < c_.S; ++s)
      for (int32_t n = 0; n < c_.N; ++n)
        node_partition_counts_[n] += c_.counts[s * c_.N + n];
    held_.assign(c_.N, 0);
    in_flat_.assign(c_.N, 0);
  }

  void run() {
    for (int32_t s = 0; s < c_.S; ++s) {
      if (c_.constraints[s] <= 0) continue;
      assign_state(s);
    }
  }

 private:
  const Ctx& c_;
  std::vector<double> node_partition_counts_;  // maintained incrementally
  // node -> (top-priority-node -> count); reset per state.
  std::unordered_map<int64_t, double> node_to_node_;
  std::vector<uint8_t> held_;   // scratch: nodes of this partition, state s
  std::vector<uint8_t> in_flat_;

  inline double& count_ref(int32_t s, int32_t n) {
    return c_.counts[s * c_.N + n];
  }

  void adjust(int32_t s, int32_t node, double amt) {
    count_ref(s, node) += amt;
    node_partition_counts_[node] += amt;
  }

  // The node score formula (greedy.py default_node_score, plan.go:634-689).
  double score_node(int32_t node, int32_t p, int32_t s, int32_t top_node,
                    double stick) const {
    double lower = 0.0;
    if (c_.num_partitions > 0 && top_node >= -1) {
      auto it = node_to_node_.find(key(top_node, node));
      if (it != node_to_node_.end())
        lower = it->second / static_cast<double>(c_.num_partitions);
    }
    double filled = 0.0;
    if (c_.num_partitions > 0) {
      filled = (0.001 * node_partition_counts_[node]) /
               static_cast<double>(c_.num_partitions);
    }
    double current = 0.0;
    const int32_t* row = &c_.assign[(static_cast<int64_t>(p) * c_.S + s) * c_.R];
    for (int32_t r = 0; r < c_.R; ++r)
      if (row[r] == node) current = stick;

    double v = c_.counts[s * c_.N + node];
    v += lower;
    v += filled;
    if (c_.nweight_set[node]) {
      double w = c_.nweights[node];
      if (w > 0) {
        v /= w;
      } else if (w < 0 && c_.use_booster) {
        double boost = -w;
        if (boost < current) boost = current;  // cbgt: max(-w, stickiness)
        v += boost;
      }
    }
    return v - current;
  }

  static inline int64_t key(int32_t a, int32_t b) {
    return (static_cast<int64_t>(a + 1) << 32) | static_cast<uint32_t>(b);
  }

  // Visit order for one state: ORDER BY category (0: on removed nodes,
  // 1: not yet on any added node, 2: rest), then the static rank.
  std::vector<int32_t> state_order(int32_t s) const {
    std::vector<int64_t> keys(c_.P);
    for (int32_t p = 0; p < c_.P; ++p) {
      int32_t cat = 2;
      if (c_.cat0[s * c_.P + p]) {
        cat = 0;
      } else if (c_.has_adds) {
        bool on_added = false;
        const int32_t* prow =
            &c_.assign[static_cast<int64_t>(p) * c_.S * c_.R];
        for (int32_t i = 0; i < c_.S * c_.R && !on_added; ++i)
          if (prow[i] >= 0 && c_.add_mask[prow[i]]) on_added = true;
        if (!on_added) cat = 1;
      }
      keys[p] = (static_cast<int64_t>(cat) << 40) | c_.static_rank[p];
    }
    std::vector<int32_t> order(c_.P);
    for (int32_t p = 0; p < c_.P; ++p) order[p] = p;
    std::sort(order.begin(), order.end(),
              [&](int32_t a, int32_t b) { return keys[a] < keys[b]; });
    return order;
  }

  void assign_state(int32_t s) {
    node_to_node_.clear();
    const int32_t k = c_.constraints[s];
    const int32_t prio = c_.state_priority[s];
    std::vector<NodeScore> flat;
    std::vector<int32_t> picks;
    flat.reserve(c_.N);
    const std::vector<int32_t> order = state_order(s);

    for (int32_t oi = 0; oi < c_.P; ++oi) {
      const int32_t p = order[oi];
      const double pw = c_.pweights[p];
      int32_t* prow =
          &c_.assign[static_cast<int64_t>(p) * c_.S * c_.R];

      // Top-priority node: first entry of state index 0 (states arrive
      // priority-then-name sorted, matching _top_priority_state_name).
      int32_t top_node = prow[0] >= 0 ? prow[0] : -1;
      const double stick = c_.stickiness[p * c_.S + s];

      // Mark nodes holding an equal-or... strictly higher-priority state
      // of this partition (excludeHigherPriorityNodes, plan.go:146-156).
      std::fill(held_.begin(), held_.end(), 0);
      for (int32_t sj = 0; sj < c_.S; ++sj) {
        if (c_.state_priority[sj] >= prio) continue;
        const int32_t* r2 = &prow[sj * c_.R];
        for (int32_t r = 0; r < c_.R; ++r)
          if (r2[r] >= 0) held_[r2[r]] = 1;
      }

      // Flat candidates: valid nodes minus higher-priority holders, fully
      // ordered by (score, position).
      flat.clear();
      for (int32_t n = 0; n < c_.N; ++n) {
        if (!c_.valid[n] || held_[n]) continue;
        flat.push_back({score_node(n, p, s, top_node, stick), n});
      }
      std::sort(flat.begin(), flat.end(), score_less);

      picks.clear();
      if (c_.has_hierarchy) {
        hierarchy_pass(s, p, k, top_node, stick, flat, &picks);
      }

      // dedupe(picks + flat), truncate to k (plan.go:224-235).
      std::fill(in_flat_.begin(), in_flat_.end(), 0);
      std::vector<int32_t> chosen;
      chosen.reserve(k);
      for (int32_t n : picks) {
        if (!in_flat_[n]) {
          in_flat_[n] = 1;
          if (static_cast<int32_t>(chosen.size()) < k) chosen.push_back(n);
        }
      }
      for (const auto& ns : flat) {
        if (static_cast<int32_t>(chosen.size()) >= k) break;
        if (!in_flat_[ns.node]) {
          in_flat_[ns.node] = 1;
          chosen.push_back(ns.node);
        }
      }
      if (static_cast<int32_t>(chosen.size()) < k)
        c_.shortfall[p * c_.S + s] = k - static_cast<int32_t>(chosen.size());

      // Keep nodeToNodeCounts updated (plan.go:238-245).
      for (int32_t n : chosen) node_to_node_[key(top_node, n)] += 1.0;

      // Uninstall the state's old holders and the newly chosen nodes from
      // every state, adjusting counts (plan.go:290-301).
      remove_from_all_states(p, &prow[s * c_.R], c_.R, pw);
      for (int32_t n : chosen) remove_node_from_all_states(p, n, pw);

      int32_t* srow = &prow[s * c_.R];
      for (int32_t r = 0; r < c_.R; ++r)
        srow[r] = r < static_cast<int32_t>(chosen.size()) ? chosen[r] : -1;
      for (int32_t n : chosen) adjust(s, n, pw);
    }
  }

  // Remove every node currently listed in `nodes` (a state row snapshot)
  // from all states of partition p, decrementing counts for the ones
  // actually present.
  void remove_from_all_states(int32_t p, const int32_t* nodes, int32_t count,
                              double pw) {
    // Snapshot first: the row is about to be mutated.
    int32_t snap[64];
    std::vector<int32_t> heap_snap;
    const int32_t* src = nodes;
    if (count <= 64) {
      std::memcpy(snap, nodes, count * sizeof(int32_t));
      src = snap;
    } else {
      heap_snap.assign(nodes, nodes + count);
      src = heap_snap.data();
    }
    for (int32_t i = 0; i < count; ++i)
      if (src[i] >= 0) remove_node_from_all_states(p, src[i], pw);
  }

  void remove_node_from_all_states(int32_t p, int32_t node, double pw) {
    int32_t* prow = &c_.assign[static_cast<int64_t>(p) * c_.S * c_.R];
    for (int32_t sj = 0; sj < c_.S; ++sj) {
      int32_t* row = &prow[sj * c_.R];
      int32_t w = 0;
      bool removed = false;
      for (int32_t r = 0; r < c_.R; ++r) {
        if (row[r] == node) {
          adjust(sj, node, -pw);
          removed = true;
        } else if (row[r] >= 0) {
          row[w++] = row[r];
        }
      }
      if (removed || w < c_.R) {
        for (int32_t r = w; r < c_.R; ++r) row[r] = -1;
      }
    }
  }

  // The hierarchy pass (plan.go:174-226): per rule, pick k nodes anchored
  // on the primary + picks so far, intersecting include/exclude subtrees.
  void hierarchy_pass(int32_t s, int32_t p, int32_t k, int32_t top_node,
                      double stick, const std::vector<NodeScore>& flat,
                      std::vector<int32_t>* picks) {
    std::vector<NodeScore> hcand;
    const int32_t rb = c_.rule_off[s], re = c_.rule_off[s + 1];
    for (int32_t ri = rb; ri < re; ++ri) {
      const int32_t inc = c_.rule_inc[ri], exc = c_.rule_exc[ri];
      int32_t anchor0 = top_node;
      if (anchor0 < 0 && !picks->empty()) anchor0 = (*picks)[0];
      for (int32_t i = 0; i < k; ++i) {
        hcand.clear();
        const int32_t prio = c_.state_priority[s];
        for (int32_t n = 0; n < c_.N; ++n) {
          if (!c_.valid[n]) continue;
          if (!member(n, anchor0, inc, exc)) continue;
          bool ok = true;
          for (int32_t a : *picks)
            if (!member(n, a, inc, exc)) { ok = false; break; }
          if (!ok) continue;
          // Exclude higher-priority holders.
          bool held = false;
          const int32_t* prow =
              &c_.assign[static_cast<int64_t>(p) * c_.S * c_.R];
          for (int32_t sj = 0; sj < c_.S && !held; ++sj) {
            if (c_.state_priority[sj] >= prio) continue;
            const int32_t* r2 = &prow[sj * c_.R];
            for (int32_t r = 0; r < c_.R; ++r)
              if (r2[r] == n) { held = true; break; }
          }
          if (held) continue;
          hcand.push_back({score_node(n, p, s, top_node, stick), n});
        }
        if (!hcand.empty()) {
          picks->push_back(
              std::min_element(hcand.begin(), hcand.end(), score_less)->node);
        } else if (!flat.empty()) {
          picks->push_back(flat[0].node);
        }
      }
    }
  }

  // Candidate n in include_exclude_nodes(anchor) per api.go:76-105: inside
  // the anchor's inc-level subtree but outside its exc-level subtree.
  // find_leaves (plan.go:764-774) yields leaves only, so interior nodes of
  // the hierarchy never qualify.
  bool member(int32_t n, int32_t anchor, int32_t inc, int32_t exc) const {
    if (anchor < 0 || !c_.is_leaf[n]) return false;
    const int32_t inc_id =
        inc < c_.levels ? c_.aid[inc * c_.N + anchor] : -1;
    const int32_t exc_id =
        exc < c_.levels ? c_.aid[exc * c_.N + anchor] : -1;
    if (!under(c_, n, inc_id)) return false;
    if (exc_id >= 0 && under(c_, n, exc_id)) return false;
    return true;
  }
};

}  // namespace

extern "C" {

void blance_plan_inner(
    int32_t P, int32_t N, int32_t S, int32_t R, int32_t num_partitions,
    const int32_t* constraints, const int32_t* state_priority,
    const double* pweights, const double* nweights,
    const uint8_t* nweight_set, const uint8_t* valid,
    const double* stickiness, int32_t levels, const int32_t* aid,
    const uint8_t* is_leaf, const int32_t* rule_off, const int32_t* rule_inc,
    const int32_t* rule_exc, uint8_t use_booster, uint8_t has_hierarchy,
    const int32_t* static_rank, const uint8_t* cat0, const uint8_t* add_mask,
    uint8_t has_adds, int32_t* assign, double* counts, int32_t* shortfall) {
  Ctx c{P, N, S, R, num_partitions, constraints, state_priority,
        pweights, nweights, nweight_set, valid, stickiness, levels, aid,
        is_leaf, rule_off, rule_inc, rule_exc, use_booster, has_hierarchy,
        static_rank, cat0, add_mask, has_adds, assign, counts, shortfall};
  Planner planner(c);
  planner.run();
}

}  // extern "C"
