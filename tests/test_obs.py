"""The obs layer: Recorder spans/counters/histograms, sinks, Chrome
export, the PhaseTimer shim, and the pipeline instrumentation contract
(plan + moves + orchestrate all reporting into one recorder)."""

import asyncio
import json

import pytest

from blance_tpu.obs import (
    ChromeTraceSink,
    InMemorySink,
    JsonlSink,
    Recorder,
    get_recorder,
    percentile,
    phase_span,
    use_recorder,
    write_chrome_trace,
)
from blance_tpu.utils.trace import PhaseTimer


# ---------------------------------------------------------------------------
# Recorder core
# ---------------------------------------------------------------------------


def test_span_nesting_and_aggregates_sync():
    rec = Recorder()
    sink = InMemorySink()
    rec.add_sink(sink)
    with rec.span("outer", kind="test") as outer:
        with rec.span("inner") as inner:
            assert inner.parent_id == outer.span_id
        with rec.span("inner"):
            pass
    assert outer.parent_id is None
    assert rec.span_counts == {"outer": 1, "inner": 2}
    assert rec.span_totals["outer"] >= rec.span_totals["inner"] > 0
    names = [sp.name for sp in sink.spans]
    # Children finish before their parent.
    assert names == ["inner", "inner", "outer"]
    assert sink.spans[-1].attrs == {"kind": "test"}


def test_span_nesting_under_asyncio_concurrency():
    """Sibling tasks run concurrently, each with its own span stack: every
    child parents on the span that was current when the task was created,
    and concurrent siblings never corrupt each other's parenthood."""
    rec = Recorder()
    sink = InMemorySink()
    rec.add_sink(sink)

    async def child(i):
        with rec.span(f"child{i}") as sp:
            await asyncio.sleep(0.01 * (i % 3))
            with rec.span("grand", idx=i) as g:
                await asyncio.sleep(0.005)
                assert g.parent_id == sp.span_id
        return sp

    async def main():
        with rec.span("root") as root:
            spans = await asyncio.gather(*(child(i) for i in range(8)))
        return root, spans

    root, children = asyncio.run(main())
    assert all(sp.parent_id == root.span_id for sp in children)
    grands = sink.by_name("grand")
    assert len(grands) == 8
    child_ids = {sp.span_id: sp for sp in children}
    for g in grands:
        parent = child_ids[g.parent_id]
        assert parent.name == f"child{g.attrs['idx']}"
    # Totals accumulated for every name despite interleaving.
    assert rec.span_counts["root"] == 1
    assert sum(rec.span_counts[f"child{i}"] for i in range(8)) == 8


def test_record_span_backdated_and_counters():
    rec = Recorder()
    sink = InMemorySink()
    rec.add_sink(sink)
    with rec.span("parent") as p:
        sp = rec.record_span("waited", 1.0, 3.5, task="lane-x", node="n1")
    assert sp.parent_id == p.span_id
    assert sp.duration_s == pytest.approx(2.5)
    assert sp.task == "lane-x"
    rec.count("hits")
    rec.count("hits", 4)
    assert rec.counters == {"hits": 5}


def test_set_attr_outside_span_is_noop():
    rec = Recorder()
    rec.set_attr("k", "v")  # must not raise
    with rec.span("s") as sp:
        rec.set_attr("k", "v")
    assert sp.attrs == {"k": "v"}


# ---------------------------------------------------------------------------
# Histogram percentile math
# ---------------------------------------------------------------------------


def test_percentile_nearest_rank():
    vals = list(range(1, 101))  # 1..100
    assert percentile(vals, 50) == 50
    assert percentile(vals, 95) == 95
    assert percentile(vals, 0) == 1
    assert percentile(vals, 100) == 100
    assert percentile([7.0], 50) == 7.0
    assert percentile([3, 1], 50) == 1  # rank ceil(1.0)=1 -> min
    assert percentile([3, 1], 51) == 3
    with pytest.raises(ValueError):
        percentile([], 50)


def test_histogram_sample_bounded_with_exact_stats():
    """Past the cap the percentile sample is decimated + subsampled, but
    count/sum/min/max stay exact and percentiles stay representative."""
    from blance_tpu.obs.recorder import _HIST_CAP

    rec = Recorder()
    n = _HIST_CAP * 4
    for v in range(n):
        rec.observe("lat", v)
    assert len(rec.histograms["lat"]) <= _HIST_CAP
    s = rec.histogram_summary("lat")
    assert s["count"] == n
    assert s["sum"] == n * (n - 1) / 2
    assert s["min"] == 0 and s["max"] == n - 1
    # Systematic subsample of a monotone series: percentiles within a few
    # strides of the exact values.
    assert abs(s["p50"] - n / 2) <= n * 0.02
    assert abs(s["p95"] - n * 0.95) <= n * 0.02


def test_histogram_summary():
    rec = Recorder()
    for v in (5, 1, 9, 3, 7):
        rec.observe("lat", v)
    s = rec.histogram_summary("lat")
    assert s == {"count": 5, "sum": 25.0, "min": 1.0, "max": 9.0,
                 "p50": 5.0, "p95": 9.0}
    assert rec.histogram_summary("missing") is None
    full = rec.summary()
    assert full["histograms"]["lat"]["p95"] == 9.0
    assert full["spans"] == {} and full["counters"] == {}


# ---------------------------------------------------------------------------
# Sinks & Chrome export
# ---------------------------------------------------------------------------


def test_jsonl_sink(tmp_path):
    rec = Recorder()
    path = tmp_path / "spans.jsonl"
    sink = JsonlSink(str(path), t0=rec.t0)
    rec.add_sink(sink)
    with rec.span("a", answer=42):
        with rec.span("b"):
            pass
    sink.close()
    lines = [json.loads(line) for line in path.read_text().splitlines()]
    assert [entry["name"] for entry in lines] == ["b", "a"]
    assert lines[1]["attrs"] == {"answer": 42}
    assert lines[0]["parent_id"] == lines[1]["span_id"]
    assert all(entry["duration_s"] >= 0 for entry in lines)


def test_chrome_trace_schema_valid(tmp_path):
    """The exported file must be loadable trace-event JSON: an object with
    a traceEvents list of 'X' complete events (µs ts/dur) for live spans,
    nestable async 'b'/'e' pairs for overlappable (backdated) spans, 'M'
    lane metadata, and 'C' counter events — the subset chrome://tracing
    and Perfetto both accept."""
    rec = Recorder()
    sink = ChromeTraceSink(rec)
    rec.add_sink(sink)
    with rec.span("plan.encode"):
        with rec.span("plan.solve", engine="matrix"):
            pass
    # A manufactured span (queue wait): may overlap live slices on its
    # lane, so it must ship as an async pair, not an "X" slice.
    rec.record_span("orchestrate.move.wait", rec.t0, rec.t0 + 0.25,
                    task="mover:n1", node="n1")
    rec.count("orchestrate.tot_mover_loop", 3)

    path = tmp_path / "trace.json"
    write_chrome_trace(str(path), sink, rec)

    doc = json.loads(path.read_text())
    assert isinstance(doc, dict)
    events = doc["traceEvents"]
    assert isinstance(events, list) and events
    phases = {}
    for ev in events:
        assert isinstance(ev["name"], str)
        assert ev["ph"] in ("X", "M", "C", "b", "e")
        assert isinstance(ev["pid"], int)
        phases.setdefault(ev["ph"], []).append(ev)
        if ev["ph"] == "X":
            assert ev["ts"] >= 0 and ev["dur"] >= 0
            assert isinstance(ev["tid"], int)
            assert isinstance(ev["args"], dict)
            assert "span_id" in ev["args"]
        if ev["ph"] in ("b", "e"):
            # Nestable async events require cat + id for pairing.
            assert ev["cat"] and isinstance(ev["id"], str)
            assert ev["ts"] >= 0
    x_names = {ev["name"] for ev in phases["X"]}
    assert {"plan.encode", "plan.solve"} <= x_names
    # Nesting survives: solve's ts window sits inside encode's.
    enc = next(ev for ev in phases["X"] if ev["name"] == "plan.encode")
    sol = next(ev for ev in phases["X"] if ev["name"] == "plan.solve")
    assert enc["ts"] <= sol["ts"]
    assert sol["ts"] + sol["dur"] <= enc["ts"] + enc["dur"] + 1e-3
    # The backdated wait is a matched b/e pair, 0.25s apart.
    (b,) = phases["b"]
    (e,) = phases["e"]
    assert b["name"] == e["name"] == "orchestrate.move.wait"
    assert (b["id"], b["cat"]) == (e["id"], e["cat"])
    assert e["ts"] - b["ts"] == pytest.approx(0.25e6, rel=1e-6)
    # Lane metadata names the mover lane; counters carried as C events.
    lanes = {ev["args"]["name"] for ev in phases["M"]}
    assert "mover:n1" in lanes
    counters = {ev["name"]: ev["args"]["value"] for ev in phases["C"]}
    assert counters["orchestrate.tot_mover_loop"] == 3


# ---------------------------------------------------------------------------
# PhaseTimer compatibility shim
# ---------------------------------------------------------------------------


def test_phase_timer_report_unchanged_through_shim():
    """PhaseTimer.report() keeps the exact pre-obs shape: per-phase
    {total_s, count} plus an optional 'annotations' block — while every
    phase is ALSO recorded as a span on the process recorder."""
    rec = Recorder()
    with use_recorder(rec):
        t = PhaseTimer()
        with t.phase("encode"):
            pass
        with t.phase("solve"):
            with t.phase("encode"):
                pass
        t.annotate("engine", "matrix")

    report = t.report()
    assert set(report) == {"encode", "solve", "annotations"}
    assert report["encode"]["count"] == 2
    assert report["solve"]["count"] == 1
    assert isinstance(report["encode"]["total_s"], float)
    assert report["encode"]["total_s"] > 0
    assert report["annotations"] == {"engine": "matrix"}
    # No annotations -> no annotations key (legacy shape).
    assert "annotations" not in PhaseTimer().report()
    # str() still renders.
    assert "engine=matrix" in str(t)
    # The shim recorded the same phases as spans.
    assert rec.span_counts == {"encode": 2, "solve": 1}


def test_phase_timer_annotate_lands_on_current_span():
    rec = Recorder()
    with use_recorder(rec):
        sink = InMemorySink()
        rec.add_sink(sink)
        t = PhaseTimer()
        with t.phase("solve"):
            t.annotate("engine", "fused")
    (sp,) = sink.by_name("solve")
    assert sp.attrs["engine"] == "fused"
    assert t.annotations == {"engine": "fused"}


def test_phase_span_dual_view():
    """phase_span times once, publishing a hierarchical span name AND the
    short phase key into the timer (no double-recorded span)."""
    rec = Recorder()
    with use_recorder(rec):
        t = PhaseTimer()
        with phase_span("plan.encode", timer=t):
            pass
        with phase_span("plan.solve", timer=t, phase="the-solve"):
            pass
    assert set(t.totals) == {"encode", "the-solve"}
    assert set(rec.span_counts) == {"plan.encode", "plan.solve"}


# ---------------------------------------------------------------------------
# Pipeline instrumentation contracts
# ---------------------------------------------------------------------------


def _mini_model():
    from blance_tpu import model

    return model(primary=(0, 1), replica=(1, 1))


def _mini_maps(p=24, n=6):
    from blance_tpu import Partition

    nodes = [f"n{i}" for i in range(n)]
    prev = {
        str(i): Partition(str(i), {"primary": [nodes[i % n]],
                                   "replica": [nodes[(i + 1) % n]]})
        for i in range(p)
    }
    return prev, nodes


def test_plan_tpu_emits_phase_spans_and_sweeps():
    from blance_tpu.plan.api import plan_next_map

    rec = Recorder()
    with use_recorder(rec):
        prev, nodes = _mini_maps()
        plan_next_map(prev, prev, nodes, [nodes[0]], [], _mini_model(),
                      None, backend="tpu")
    for name in ("plan.plan_next_map", "plan.encode", "plan.solve",
                 "plan.solve.attempt", "plan.decode"):
        assert rec.span_counts.get(name, 0) >= 1, (name, rec.span_counts)
    assert rec.counters["plan.solve.calls"] >= 1
    assert rec.counters["plan.solve.sweeps"] >= 1
    assert rec.histograms["plan.solve.sweeps"]


def test_plan_greedy_emits_candidate_histogram():
    from blance_tpu.plan.api import plan_next_map

    rec = Recorder()
    with use_recorder(rec):
        prev, nodes = _mini_maps()
        plan_next_map(prev, prev, nodes, [], [], _mini_model(),
                      None, backend="greedy")
    assert rec.span_counts["plan.greedy"] == 1
    h = rec.histogram_summary("plan.greedy.candidates")
    assert h is not None and h["count"] > 0 and h["max"] <= 6


def test_node_sorter_output_validated():
    """A node_sorter hook that drops, duplicates, or invents nodes is
    rejected with a ValueError naming the hook (ADVICE round 5)."""
    from blance_tpu import PlanOptions
    from blance_tpu.plan.api import plan_next_map

    prev, nodes = _mini_maps()

    def dropping_sorter(ctx, candidates):
        return candidates[:-1]  # loses one node

    def duplicating_sorter(ctx, candidates):
        return [candidates[0]] * len(candidates)

    for bad in (dropping_sorter, duplicating_sorter):
        with pytest.raises(ValueError, match="node_sorter"):
            plan_next_map(prev, prev, nodes, [], [], _mini_model(),
                          PlanOptions(node_sorter=bad), backend="greedy")

    # A well-behaved sorter (any permutation) still works.
    def reversing_sorter(ctx, candidates):
        return list(reversed(candidates))

    out, _ = plan_next_map(prev, prev, nodes, [], [], _mini_model(),
                           PlanOptions(node_sorter=reversing_sorter),
                           backend="greedy")
    assert len(out) == len(prev)


def test_moves_batch_spans_and_counters():
    from blance_tpu.moves.batch import calc_all_moves

    rec = Recorder()
    with use_recorder(rec):
        prev, nodes = _mini_maps()
        end, _ = _mini_maps()
        # Shift every primary by one node to force ops.
        from blance_tpu import Partition

        end = {
            name: Partition(name, {
                "primary": [nodes[(int(name) + 2) % len(nodes)]],
                "replica": p.nodes_by_state["replica"],
            })
            for name, p in prev.items()
        }
        moves = calc_all_moves(prev, end, _mini_model())
    assert any(moves.values())
    for name in ("moves.calc_all_moves", "moves.encode",
                 "moves.device_diff", "moves.materialize"):
        assert rec.span_counts.get(name, 0) == 1, (name, rec.span_counts)
    assert rec.counters["moves.total_ops"] > 0
    assert rec.counters["moves.diff_partitions"] == len(prev)


def test_orchestrator_mirrors_progress_and_records_move_lifecycle():
    """One sink sees everything: progress counters mirrored 1:1 into the
    recorder, and each fed batch becomes an orchestrate.move span whose
    wait/exec children split queue time from callback time."""
    from blance_tpu.orchestrate.orchestrator import (
        OrchestratorOptions, orchestrate_moves)
    from blance_tpu.plan.api import plan_next_map

    rec = Recorder()
    sink = InMemorySink()
    rec.add_sink(sink)
    with use_recorder(rec):
        prev, nodes = _mini_maps()
        end, _ = plan_next_map(prev, prev, nodes, [nodes[0]], [],
                               _mini_model(), None, backend="greedy")

        async def assign(stop_ch, node, partitions, states, ops):
            await asyncio.sleep(0.001)

        async def run():
            o = orchestrate_moves(
                _mini_model(),
                OrchestratorOptions(max_concurrent_partition_moves_per_node=4),
                nodes, prev, end, assign)
            final = None
            async for p in o.progress_ch():
                final = p
            o.stop()
            return final

        final = asyncio.run(run())

    assert final.tot_mover_assign_partition_ok > 0
    for field in ("tot_run_mover", "tot_mover_loop",
                  "tot_mover_assign_partition",
                  "tot_mover_assign_partition_ok",
                  "tot_run_supply_moves_loop", "tot_progress_close"):
        assert rec.counters["orchestrate." + field] == \
            getattr(final, field), field

    assert rec.span_counts["orchestrate.plan_moves"] == 1
    moves = sink.by_name("orchestrate.move")
    assert len(moves) == final.tot_mover_assign_partition
    for mv in moves:
        kids = [sp for sp in sink.spans if sp.parent_id == mv.span_id]
        assert {"orchestrate.move.wait", "orchestrate.move.exec"} == \
            {sp.name for sp in kids}
        assert mv.attrs["wait_s"] >= 0 and mv.attrs["exec_s"] > 0
        assert mv.task == f"mover:{mv.attrs['node']}"
        # Lifecycle covers wait + exec.
        assert mv.duration_s + 1e-6 >= \
            mv.attrs["wait_s"] + mv.attrs["exec_s"] - 1e-4

    lat = rec.histogram_summary("orchestrate.move_latency_s")
    assert lat is not None
    assert lat["p95"] >= lat["p50"] > 0
    # One observation per partition move, the batch's exec time amortized
    # across its moves: count is total moves fed and the sum is real
    # callback wall-clock, not batch-size-weighted batch latency.
    assert lat["count"] == sum(mv.attrs["moves"] for mv in moves)
    assert lat["count"] >= final.tot_mover_assign_partition_ok
    assert lat["sum"] == pytest.approx(
        sum(mv.attrs["exec_s"] for mv in moves), rel=1e-9)


def test_recorder_without_sink_retains_no_spans():
    """Aggregate-only by default: a long-running service with no sink
    attached must not accumulate span objects."""
    rec = Recorder()
    with rec.span("s"):
        pass
    assert rec.span_counts["s"] == 1
    assert not hasattr(rec, "spans")
    assert rec.sinks == []


# ---------------------------------------------------------------------------
# Injectable clock, gauges, exact histogram buckets (the live-telemetry
# plane's Recorder extensions)
# ---------------------------------------------------------------------------


def test_recorder_injectable_clock_drives_all_timestamps():
    """Every timestamp — t0, span endpoints, now() — comes from the
    injected clock, so virtual-time tests control telemetry time."""
    t = [100.0]
    rec = Recorder(clock=lambda: t[0])
    assert rec.t0 == 100.0
    assert rec.now() == 100.0
    with rec.span("s") as sp:
        t[0] = 101.5
    assert sp.t_start == 100.0 and sp.t_end == 101.5
    assert sp.duration_s == pytest.approx(1.5)
    assert rec.span_totals["s"] == pytest.approx(1.5)
    t[0] = 103.25
    assert rec.now() == 103.25


def test_recorder_gauges_last_value_wins_and_summary():
    rec = Recorder()
    rec.set_gauge("slo.partition_availability", 0.5)
    rec.set_gauge("slo.partition_availability", 0.75)
    rec.set_gauge('slo.quarantine_exposure_s{node="n1"}', 2.5)
    assert rec.gauges["slo.partition_availability"] == 0.75
    s = rec.summary()
    assert s["gauges"]["slo.partition_availability"] == 0.75
    assert s["gauges"]['slo.quarantine_exposure_s{node="n1"}'] == 2.5


def test_histogram_bucket_counts_exact_le_semantics():
    """Bucket counts are exact with `le` (<=) boundary semantics: a
    value equal to a bound lands in that bound's bucket; the implicit
    final slot is +Inf."""
    rec = Recorder()
    rec.set_hist_bounds("lat", (0.01, 0.1, 1.0))
    for v in (0.01, 0.05, 0.5, 5.0):
        rec.observe("lat", v)
    bounds, cum, count, total = rec.histogram_buckets("lat")
    assert bounds == (0.01, 0.1, 1.0)
    assert cum == [1, 2, 3, 4]  # cumulative; 0.01 counted at le=0.01
    assert count == 4
    assert total == pytest.approx(5.56)
    # Re-binning after data exists is refused (counts are exact, not
    # reconstructible).
    with pytest.raises(ValueError, match="before the first observe"):
        rec.set_hist_bounds("lat", (1.0,))
    assert rec.histogram_buckets("never") is None


def test_histogram_default_buckets_cover_outliers():
    from blance_tpu.obs.recorder import DEFAULT_BUCKETS

    rec = Recorder()
    rec.observe("big", 1e9)  # beyond every bound: +Inf bucket only
    bounds, cum, count, total = rec.histogram_buckets("big")
    assert bounds == DEFAULT_BUCKETS
    assert cum[-2] == 0 and cum[-1] == 1 and count == 1


def test_histogram_buckets_consistent_with_exact_stats_at_scale():
    """Bucket count stays exact (== stats count) even past the
    percentile sample's decimation cap."""
    from blance_tpu.obs.recorder import _HIST_CAP

    rec = Recorder()
    n = _HIST_CAP * 3
    for v in range(n):
        rec.observe("lat", v / 1000.0)
    _bounds, cum, count, _total = rec.histogram_buckets("lat")
    assert count == n == cum[-1]
    assert len(rec.histograms["lat"]) <= _HIST_CAP


# ---------------------------------------------------------------------------
# JsonlSink rotation (size-capped, keep-N)
# ---------------------------------------------------------------------------


def _spin_spans(rec, n, name="s"):
    for _ in range(n):
        with rec.span(name, pad="x" * 64):
            pass


def test_jsonl_sink_rotation_boundary(tmp_path):
    """Crossing max_bytes rotates AFTER the triggering line: no record
    is ever split across files, every file is valid JSONL, the cap is
    overshot by at most one record, and only `keep` rotated files
    survive."""
    rec = Recorder()
    path = tmp_path / "spans.jsonl"
    sink = JsonlSink(str(path), t0=rec.t0, max_bytes=512, keep=2)
    rec.add_sink(sink)
    _spin_spans(rec, 40)
    sink.close()

    rotated = sorted(p.name for p in tmp_path.iterdir())
    assert "spans.jsonl.1" in rotated and "spans.jsonl.2" in rotated
    assert "spans.jsonl.3" not in rotated  # keep=2 drops older files
    line_len = None
    for p in tmp_path.iterdir():
        text = p.read_text()
        lines = text.splitlines()
        for line in lines:  # every record whole and parseable
            entry = json.loads(line)
            assert entry["name"] == "s"
            line_len = len(line) + 1
        if p.name != "spans.jsonl":
            # A rotated file crossed the cap by at most one record.
            assert 512 <= len(text) < 512 + line_len
    # The live file was reopened fresh (below the cap).
    assert len(path.read_text()) < 512


def test_jsonl_sink_rotation_boundary_exact_hit(tmp_path):
    """A write landing exactly ON the cap rotates too (>= semantics)."""
    rec = Recorder()
    path = tmp_path / "s.jsonl"
    sink = JsonlSink(str(path), t0=rec.t0, max_bytes=1, keep=1)
    rec.add_sink(sink)
    _spin_spans(rec, 3)
    sink.close()
    # Every span rotated its file: the live file is empty, .1 has the
    # last record whole.
    assert path.read_text() == ""
    assert len((tmp_path / "s.jsonl.1").read_text().splitlines()) == 1


def test_jsonl_sink_rotation_rejects_file_objects(tmp_path):
    import io

    with pytest.raises(ValueError, match="path-owned"):
        JsonlSink(io.StringIO(), max_bytes=100)


# ---------------------------------------------------------------------------
# Chrome counter tracks (live "C" samples)
# ---------------------------------------------------------------------------


def test_chrome_counter_track_time_series():
    """Each count() becomes a time-stamped "C" sample carrying the
    cumulative value, so Perfetto renders an evolving counter track on
    the span timeline (plus the final-value sample at the trace end)."""
    from blance_tpu.obs import ChromeTraceSink

    t = [10.0]
    rec = Recorder(clock=lambda: t[0])
    sink = ChromeTraceSink(rec)
    rec.add_sink(sink)
    rec.count("orchestrate.retries")
    t[0] = 11.0
    rec.count("orchestrate.retries", 2)
    t[0] = 12.0
    with rec.span("work"):
        pass
    events = sink.events(counters=dict(rec.counters))
    c_events = [ev for ev in events if ev["ph"] == "C"]
    live = [ev for ev in c_events if ev["name"] == "orchestrate.retries"]
    # Two live samples (cumulative 1 then 3) + the final-value sample.
    assert [ev["args"]["value"] for ev in live] == [1, 3, 3]
    assert live[0]["ts"] == pytest.approx(0.0)
    assert live[1]["ts"] == pytest.approx(1e6)  # 1 virtual second later
    assert live[0]["ts"] <= live[1]["ts"] <= live[2]["ts"]
