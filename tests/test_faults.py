"""Deterministic chaos tests: fault injection, retries, deadlines,
quarantine, and failure-aware recovery replans.

Everything here is CPU-only, seeded, and wall-clock-free apart from
millisecond-scale timeouts/backoffs (hangs are virtual: a parked
callback cancelled by the move deadline).  The 3-seed scenario
parametrization is what the CI chaos-smoke job runs on every PR.
"""

import asyncio

import pytest

from blance_tpu import Partition, PartitionModelState, model
from blance_tpu.obs import Recorder, use_recorder
from blance_tpu.orchestrate import (
    FaultPlan,
    HealthTracker,
    MissingMoverError,
    MoveFailure,
    MoveTimeoutError,
    NodeFaults,
    OrchestratorOptions,
    orchestrate_moves,
)
from blance_tpu.rebalance import rebalance

MR_MODEL = {
    "primary": PartitionModelState(priority=0, constraints=0),
    "replica": PartitionModelState(priority=0, constraints=1),
}
M = model(primary=(0, 1), replica=(1, 1))

SEEDS = [3, 11, 42]  # the CI chaos-smoke matrix


def pm(d):
    return {name: Partition(name, {s: list(ns) for s, ns in nbs.items()})
            for name, nbs in d.items()}


def round_robin_map(n_parts, nodes):
    return pm({
        f"{i:02d}": {"primary": [nodes[i % len(nodes)]],
                     "replica": [nodes[(i + 1) % len(nodes)]]}
        for i in range(n_parts)
    })


def ft_opts(**kw):
    base = dict(move_timeout_s=0.25, max_retries=4, backoff_base_s=0.002,
                backoff_jitter=0.25, quarantine_after=3, probe_after_s=60.0)
    base.update(kw)
    return OrchestratorOptions(**base)


def make_cluster_tracker(beg):
    """An assign callback applying ops to a dict cluster model, so the
    app's view can be cross-checked against achieved_map."""
    cluster = {p: {s: list(ns) for s, ns in part.nodes_by_state.items()}
               for p, part in beg.items()}

    def assign(stop_ch, node, partitions, states, ops):
        for p, s, _op in zip(partitions, states, ops):
            for ns in cluster[p].values():
                if node in ns:
                    ns.remove(node)
            if s:
                cluster[p].setdefault(s, []).append(node)

    return cluster, assign


def assert_map_complete(pmap, allowed_nodes, label=""):
    """Zero unassigned and zero duplicated placements, on live nodes."""
    for name, part in pmap.items():
        nbs = part.nodes_by_state if hasattr(part, "nodes_by_state") else part
        placed = [n for ns in nbs.values() for n in ns]
        assert len(placed) == len(set(placed)), \
            f"{label}: duplicate placement in {name}: {placed}"
        assert len(nbs.get("primary", [])) == 1, \
            f"{label}: {name} primaries: {nbs.get('primary')}"
        assert len(nbs.get("replica", [])) == 1, \
            f"{label}: {name} replicas: {nbs.get('replica')}"
        assert all(n in allowed_nodes for n in placed), \
            f"{label}: {name} placed on dead node: {placed}"


# --- the acceptance scenario: flaky 30% + one dead node ---------------------


def run_chaos_rebalance(seed):
    """Flaky node at 30% + one dead node; recovery bounded at 2 rounds.
    Returns (result, plan, recorder, cluster)."""
    live = ["a", "b", "c", "d"]
    nodes = live + ["e"]  # e joins the cluster... and is dead on arrival
    beg = round_robin_map(16, live)
    cluster, assign = make_cluster_tracker(beg)
    plan = FaultPlan(seed=seed, nodes={
        "b": NodeFaults(fail_rate=0.3),
        "e": NodeFaults(dead=True),
    })
    rec = Recorder()
    with use_recorder(rec):
        result = rebalance(
            M, beg, nodes, [], ["e"], plan.wrap(assign),
            orchestrator_options=ft_opts(),
            max_recovery_rounds=2,
        )
    return result, plan, rec, cluster


@pytest.mark.parametrize("seed", SEEDS)
def test_flaky_plus_dead_node_recovers(seed):
    result, plan, rec, cluster = run_chaos_rebalance(seed)

    # The dead node tripped quarantine and caused structured failures.
    assert plan.injected.get("fail", 0) > 0
    assert result.failures, "chaos produced no MoveFailures?"
    assert all(isinstance(f, MoveFailure) for f in result.failures)
    assert any(f.node == "e" for f in result.failures)
    assert "e" in result.quarantined_nodes
    assert rec.counters.get("orchestrate.quarantine_trips", 0) >= 1

    # Recovery ran (bounded) and the final reconstructed map is whole:
    # every partition fully placed on live nodes, no duplicates.
    assert len(result.rounds) >= 2
    assert rec.counters.get("rebalance.recovery_rounds", 0) >= 1
    last = result.rounds[-1]
    assert last.failures == 0, \
        f"final round still failing: {result.failures[-3:]}"
    quarantined = set(result.quarantined_nodes)
    allowed = set("abcd") - quarantined
    assert_map_complete(result.achieved_map, allowed, f"seed={seed}")
    # The app's own cluster view agrees with the reconstruction.
    for name, part in result.achieved_map.items():
        got = {s: sorted(ns) for s, ns in cluster[name].items() if ns}
        want = {s: sorted(ns) for s, ns in part.nodes_by_state.items() if ns}
        assert got == want, (name, got, want)
    # Retries happened (the flaky node) and the failure history is full:
    # every failure names a (node, partition, state, op, attempts, cause).
    assert rec.counters.get("orchestrate.retries", 0) > 0
    for f in result.failures:
        assert f.partition and f.node and f.op and f.attempts >= 0
        assert f.cause is not None


@pytest.mark.parametrize("seed", SEEDS)
def test_same_seed_reproduces_identical_counters(seed):
    keys = ("orchestrate.retries", "orchestrate.timeouts",
            "orchestrate.quarantine_trips", "orchestrate.move_failures",
            "orchestrate.missing_mover", "rebalance.recovery_rounds")

    def run():
        result, plan, rec, _cluster = run_chaos_rebalance(seed)
        counters = {k: rec.counters.get(k, 0) for k in keys}
        return counters, dict(plan.injected), len(result.failures)

    assert run() == run()


def test_recovery_with_planner_session_warm_carry():
    """Recovery replans through a PlannerSession: the dead node's rows
    are re-placed (warm off the promoted carry when the gates allow,
    cold otherwise), the session adopts the recovery proposal, and the
    final map is whole on the surviving nodes."""
    from blance_tpu.plan.session import PlannerSession

    live = ["a", "b", "c", "d"]
    nodes = live + ["e"]
    beg = round_robin_map(16, live)
    session = PlannerSession(M, nodes, sorted(beg))
    cluster, assign = make_cluster_tracker(beg)
    plan = FaultPlan(seed=7, nodes={"e": NodeFaults(dead=True)})
    rec = Recorder()
    with use_recorder(rec):
        # d decommissions while e joins — so the plan MUST route load
        # onto e, which is dead on arrival.
        result = rebalance(
            M, beg, nodes, ["d"], ["e"], plan.wrap(assign),
            orchestrator_options=ft_opts(),
            max_recovery_rounds=2,
            session=session,
        )

    assert result.quarantined_nodes == ["e"]
    assert result.rounds[-1].failures == 0
    assert rec.counters.get("rebalance.recovery_rounds", 0) >= 1
    assert_map_complete(result.achieved_map, {"a", "b", "c"},
                        "session recovery")
    # The session adopted the recovery proposal as its current state.
    current, _warns = session.to_map("current")
    assert current == result.next_map
    # Failures were confined to the dead node, so the session path kept
    # its carry alive across the recovery replan (warm attempt or a
    # gated cold fallback — either way the solve ran through the
    # session, visible as carry accounting).
    assert any(k.startswith("plan.solve.carry") or
               k == "plan.solve.warm_fallback" for k in rec.counters)


# --- deadlines: hung callbacks are cancelled, not waited on forever ---------


def test_hung_node_hits_move_deadline():
    nodes = ["a", "h"]
    beg = pm({"00": {"primary": ["a"]}, "01": {"primary": ["a"]}})
    end = pm({"00": {"primary": ["h"]}, "01": {"primary": ["a"]}})
    plan = FaultPlan(seed=5, nodes={"h": NodeFaults(dead=True, hang=True)})
    rec = Recorder()

    async def go():
        async def assign(stop_ch, node, partitions, states, ops):
            await asyncio.sleep(0)

        o = orchestrate_moves(
            MR_MODEL,
            ft_opts(move_timeout_s=0.02, max_retries=1, quarantine_after=2),
            nodes, beg, end, plan.wrap(assign))
        async for _ in o.progress_ch():
            pass
        o.stop()
        return o

    with use_recorder(rec):
        o = asyncio.run(asyncio.wait_for(go(), timeout=30))

    assert plan.injected.get("hang", 0) > 0
    assert rec.counters.get("orchestrate.timeouts", 0) > 0
    fails = o.move_failures()
    assert fails and all(f.node == "h" for f in fails)
    assert any(isinstance(f.cause, MoveTimeoutError) for f in fails)
    # The untouched partition's plan had no moves; the hung one was
    # abandoned — either way the stream closed and nothing wedged.


def test_repeat_rebalance_through_session_keeps_carry_warm():
    """A second rebalance through the same (adopted) session must not
    cold-reload: the session's current state already matches, so the
    primary plan warm-starts off the carry the first call promoted."""
    from blance_tpu.plan.session import PlannerSession

    nodes = ["a", "b", "c", "d"]
    beg = round_robin_map(12, nodes)
    session = PlannerSession(M, nodes, sorted(beg))
    _cluster, assign = make_cluster_tracker(beg)

    first = rebalance(M, beg, nodes, [], [], assign,
                      orchestrator_options=ft_opts(), session=session)
    assert not first.failures
    assert session._carry is not None, "clean pass did not promote carry"

    rec = Recorder()
    with use_recorder(rec):
        second = rebalance(M, first.next_map, nodes, [], [], assign,
                           orchestrator_options=ft_opts(), session=session)
    assert not second.failures
    # No cold reload: the fixpoint replan consumed the carry (hit), and
    # load_map's invalidate (a guaranteed carry_miss) never ran.
    assert rec.counters.get("plan.solve.carry_hit", 0) >= 1
    assert rec.counters.get("plan.solve.carry_miss", 0) == 0


def test_app_raised_timeout_error_is_not_rebranded():
    """On 3.11+ asyncio.TimeoutError IS builtin TimeoutError: an app
    data plane raising its own timeout must surface as the APP's error
    (cause preserved, no orchestrate.timeouts bump), in both modes."""
    nodes = ["a", "b"]
    beg = pm({"00": {"primary": ["a"]}})
    end = pm({"00": {"primary": ["b"]}})
    the_err = TimeoutError("socket recv timed out")

    async def assign(stop_ch, node, partitions, states, ops):
        raise the_err

    async def go(options):
        o = orchestrate_moves(MR_MODEL, options, nodes, beg, end, assign)
        last = None
        async for p in o.progress_ch():
            last = p
        o.stop()
        return o, last

    # Legacy: aborts with the app's exception, zero timeout accounting.
    rec = Recorder()
    with use_recorder(rec):
        _o, last = asyncio.run(
            asyncio.wait_for(go(OrchestratorOptions()), timeout=30))
    assert the_err in last.errors
    assert last.tot_mover_assign_partition_timeout == 0
    assert rec.counters.get("orchestrate.timeouts", 0) == 0

    # Fault-tolerant with a deadline: the MoveFailure cause is the app's
    # TimeoutError, not a MoveTimeoutError rebranding.
    rec = Recorder()
    with use_recorder(rec):
        o, last = asyncio.run(asyncio.wait_for(
            go(ft_opts(max_retries=0)), timeout=30))
    fails = o.move_failures()
    assert fails and all(f.cause is the_err for f in fails)
    assert not any(isinstance(f.cause, MoveTimeoutError) for f in fails)
    assert rec.counters.get("orchestrate.timeouts", 0) == 0


# --- quarantine breaker: state machine + half-open healing ------------------


def test_health_tracker_state_machine_virtual_time():
    t = [0.0]
    h = HealthTracker(threshold=2, probe_after_s=10.0, clock=lambda: t[0])
    assert h.admit("n") == "ok"
    assert h.record_failure("n") is False
    assert h.record_failure("n") is True  # second consecutive: trip
    assert h.state("n") == "quarantined"
    assert h.admit("n") == "reject"
    assert h.quarantined_nodes() == ["n"]

    t[0] = 10.0  # dwell elapsed: exactly one probe admitted
    assert h.admit("n") == "probe"
    assert h.admit("n") == "reject"  # probe in flight
    assert h.record_failure("n") is True  # probe failed: re-trip
    assert h.admit("n") == "reject"  # dwell restarted

    t[0] = 20.0
    assert h.admit("n") == "probe"
    h.record_success("n")  # probe succeeded: healed
    assert h.state("n") == "healthy"
    assert h.admit("n") == "ok"
    assert h.quarantined_nodes() == []
    assert h.total_trips() == 2


def test_recovered_node_readmitted_via_probe():
    """A node that fails its first attempts then heals: the breaker
    trips, the dwell elapses (probe_after_s=0 keeps it virtual), and the
    half-open probe re-admits it — moves complete on the node itself."""
    nodes = ["a", "f"]
    beg = pm({f"{i:02d}": {"primary": ["a"], "replica": []}
              for i in range(6)})
    end = pm({f"{i:02d}": {"primary": ["a"], "replica": ["f"]}
              for i in range(6)})
    plan = FaultPlan(seed=2, nodes={"f": NodeFaults(dead=True, heal_after=4)})

    async def go():
        async def assign(stop_ch, node, partitions, states, ops):
            await asyncio.sleep(0)

        o = orchestrate_moves(
            MR_MODEL,
            ft_opts(max_retries=0, quarantine_after=2, probe_after_s=0.0),
            nodes, beg, end, plan.wrap(assign))
        async for _ in o.progress_ch():
            pass
        o.stop()
        return o

    o = asyncio.run(asyncio.wait_for(go(), timeout=30))
    assert o.health.state("f") == "healthy"
    assert plan.injected.get("ok", 0) > 0  # post-heal moves executed
    # Some moves landed after healing: not every partition failed.
    failed = {f.partition for f in o.move_failures()}
    assert len(failed) < 6


# --- missing mover: surfaced, and fail-fast under a deadline ----------------


def test_missing_mover_fails_fast_with_deadline():
    nodes = ["a"]  # "ghost" deliberately absent
    beg = pm({"00": {"primary": ["a"]}, "01": {"primary": ["a"]}})
    end = pm({"00": {"primary": ["ghost"]}, "01": {"primary": ["a"]}})
    rec = Recorder()

    async def go():
        def assign(stop_ch, node, partitions, states, ops):
            return None

        o = orchestrate_moves(MR_MODEL, ft_opts(), nodes, beg, end, assign)
        async for _ in o.progress_ch():
            pass
        o.stop()
        return o

    with use_recorder(rec):
        with pytest.warns(UserWarning, match="no mover"):
            o = asyncio.run(asyncio.wait_for(go(), timeout=30))

    assert rec.counters.get("orchestrate.missing_mover", 0) >= 1
    fails = o.move_failures()
    assert fails and all(isinstance(f.cause, MissingMoverError)
                         for f in fails)
    assert all(f.node == "ghost" for f in fails)


def test_missing_mover_legacy_stall_is_surfaced():
    """Default options keep the reference's wedge-until-stop semantics,
    but the stall is no longer silent: counter + one-time warning."""
    nodes = ["a"]
    beg = pm({"00": {"primary": ["a"]}})
    end = pm({"00": {"primary": ["ghost"]}})
    rec = Recorder()

    async def go():
        def assign(stop_ch, node, partitions, states, ops):
            return None

        o = orchestrate_moves(
            MR_MODEL, OrchestratorOptions(), nodes, beg, end, assign)
        # The ghost feeder blocks; stop() must still wind everything down.
        await o.progress_ch().get()
        o.stop()
        async for _ in o.progress_ch():
            pass

    with use_recorder(rec):
        with pytest.warns(UserWarning, match="no mover"):
            asyncio.run(asyncio.wait_for(go(), timeout=30))

    assert rec.counters.get("orchestrate.missing_mover", 0) >= 1


# --- default options: FaultPlan with no faults is a pass-through ------------


def test_faultplan_without_faults_is_transparent():
    nodes = ["a", "b"]
    beg = round_robin_map(4, nodes)
    end = pm({f"{i:02d}": {"primary": [nodes[(i + 1) % 2]],
                           "replica": [nodes[i % 2]]} for i in range(4)})
    recs = []
    plan = FaultPlan(seed=9)

    async def go(callback):
        o = orchestrate_moves(
            MR_MODEL, OrchestratorOptions(), nodes, beg, end, callback)
        log = []
        async for p in o.progress_ch():
            log.append((p.tot_mover_assign_partition_ok,
                        p.tot_mover_assign_partition_err, len(p.errors)))
        o.stop()
        return log

    def assign(stop_ch, node, partitions, states, ops):
        recs.append((node, tuple(partitions), tuple(ops)))

    direct = asyncio.run(asyncio.wait_for(go(assign), timeout=30))
    executed_direct = list(recs)
    recs.clear()
    wrapped = asyncio.run(asyncio.wait_for(go(plan.wrap(assign)), timeout=30))
    # The wrapper makes the callback async, which may interleave rounds
    # differently — the SET of executed moves and the final counters must
    # be identical, fault-free.
    assert sorted(recs) == sorted(executed_direct)
    assert wrapped[-1] == direct[-1]
    assert plan.injected.get("fail", 0) == 0 and \
        plan.injected.get("hang", 0) == 0
