"""Test configuration.

Forces JAX onto the host CPU platform with 8 virtual devices BEFORE jax is
imported anywhere, so multi-chip sharding tests (jax.sharding.Mesh over 8
devices) run on machines with no TPU attached.  Real-TPU benchmarking happens
in bench.py, not in the test suite.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The axon TPU plugin overrides JAX_PLATFORMS from the environment, so pin
# the platform through the config API as well (must happen before any
# computation runs).
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

# The fast-CI tier (pytest -m smoke): every data-model / moves / planner
# golden module plus the cheap orchestrator goldens — the suites most
# likely to catch a regression per second of runtime.  The heavy tiers
# (fuzz parametrizations, 8-device sharding, orchestrator stress, Pallas
# interpret runs) stay full-suite-only.  Module-level so the list is one
# place, applied at collection time.
SMOKE_MODULES = {
    "test_setops",
    "test_hierarchy",
    "test_moves",
    "test_moves_batch",
    "test_marshal",
    "test_plan_helpers",
    "test_plan",
    "test_control",
    "test_rebalance",
    "test_orchestrate",
    "test_plan_vis",
    "test_plan_hierarchy",
    "test_session",
    "test_native",
    "test_ops_reduce2",
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        module = item.nodeid.split("::", 1)[0].rsplit("/", 1)[-1]
        if module.removesuffix(".py") in SMOKE_MODULES:
            item.add_marker(pytest.mark.smoke)


def planner_backends():
    """Parametrize golden suites over every planner backend: the Python
    greedy oracle and the native C++ core run the goldens bit-for-bit
    (native.py's stated contract); the batched "tpu" backend runs the
    same corpus in CONTRACT mode (testing/vis.py assert_contract: zero
    audit violations, weighted balance within the golden oracle + 1,
    warnings-count equality) — it solves globally and is deliberately
    not bit-identical."""
    from blance_tpu.plan.native import native_available

    return [
        "greedy",
        pytest.param("native", marks=pytest.mark.skipif(
            not native_available(),
            reason="native toolchain unavailable")),
        "tpu",
    ]
