"""Test configuration.

Forces JAX onto the host CPU platform with 8 virtual devices BEFORE jax is
imported anywhere, so multi-chip sharding tests (jax.sharding.Mesh over 8
devices) run on machines with no TPU attached.  Real-TPU benchmarking happens
in bench.py, not in the test suite.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The axon TPU plugin overrides JAX_PLATFORMS from the environment, so pin
# the platform through the config API as well (must happen before any
# computation runs).
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import logging  # noqa: E402

import pytest  # noqa: E402

# The fast-CI tier (pytest -m smoke): every data-model / moves / planner
# golden module plus the cheap orchestrator goldens — the suites most
# likely to catch a regression per second of runtime.  The heavy tiers
# (fuzz parametrizations, 8-device sharding, orchestrator stress, Pallas
# interpret runs) stay full-suite-only.  Module-level so the list is one
# place, applied at collection time.
SMOKE_MODULES = {
    "test_setops",
    "test_hierarchy",
    "test_moves",
    "test_moves_batch",
    "test_marshal",
    "test_plan_helpers",
    "test_plan",
    "test_control",
    "test_rebalance",
    "test_orchestrate",
    "test_plan_vis",
    "test_plan_hierarchy",
    "test_session",
    "test_native",
    "test_ops_reduce2",
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        module = item.nodeid.split("::", 1)[0].rsplit("/", 1)[-1]
        if module.removesuffix(".py") in SMOKE_MODULES:
            item.add_marker(pytest.mark.smoke)


_EXIT_STATUS = [0]


@pytest.hookimpl(trylast=True)
def pytest_sessionfinish(session, exitstatus):
    _EXIT_STATUS[0] = int(exitstatus)


@pytest.hookimpl(trylast=True)
def pytest_unconfigure(config):
    """Skip interpreter teardown after the summary is written.

    A full tier-1 process accumulates thousands of compiled XLA
    executables; their destructor cascade (plus the final GC of the
    multi-GB object graph) burns tens of seconds AFTER the last test —
    wall-clock the CI/driver timeout still charges to the suite, with
    zero verification value.  Once pytest has printed its terminal
    summary (unconfigure runs after the sessionfinish wrapper's tail),
    hard-exit with pytest's own status.  Set BLANCE_FAST_EXIT=0 to
    keep normal teardown (e.g. when profiling shutdown or running
    under coverage tools that finalize at exit)."""
    if os.environ.get("BLANCE_FAST_EXIT", "1") == "0":
        return
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(_EXIT_STATUS[0])


# -- static-contract fixtures (docs/STATIC_ANALYSIS.md) ---------------------

# Transfer-guard allowlist contract: the pure solver paths convert at the
# boundaries EXPLICITLY (jnp.asarray in / np.asarray-device_get out), so
# under jax.transfer_guard("disallow") — which blocks only IMPLICIT
# transfers — an accidental host sync inside solve_dense /
# solve_dense_warm (a raw numpy operand reaching a jit call, a silent
# device round-trip between dispatches) fails the test instead of
# silently eating a sync.  The known host prechecks (the O(N) carry
# routing check in PlannerSession._capacity_shrank, the tier-band guard)
# already read through explicit np.asarray, which the guard permits.
_TRANSFER_GUARD_MODULES = {"test_warm_replan", "test_pipeline"}


@pytest.fixture(autouse=True)
def _solver_transfer_guard(request):
    """Autouse for the pure-solver suites: any implicit host<->device
    transfer inside the solve is a test failure.  Opt in elsewhere with
    the named ``no_implicit_transfers`` fixture."""
    module = request.node.nodeid.split("::", 1)[0] \
        .rsplit("/", 1)[-1].removesuffix(".py")
    if module not in _TRANSFER_GUARD_MODULES:
        yield
        return
    with jax.transfer_guard("disallow"):
        yield


@pytest.fixture
def no_implicit_transfers():
    """Opt-in: run one test under jax.transfer_guard("disallow")."""
    with jax.transfer_guard("disallow"):
        yield


# Recompile-count regression budgets (the PR-2 shape-bucketing
# guarantee): per module, the maximum number of XLA compilations the
# suite may trigger when run standalone (a shared-process run prewarms
# caches and compiles strictly less).  Counted from jax's own
# log_compiles stream, so shard_map-level compiles are included.
# Calibrated standalone values, with ~30% headroom for jax-internal
# helper jits; a solver entry point growing a new retrace per call site
# blows well past these.  Recalibrate with
# BLANCE_RECOMPILE_CALIBRATE=1 pytest tests/<module>.py.
# Standalone calibration (jax 0.4.37 / CPU, 8 virtual devices):
#   test_warm_replan  total=166 (impl 9, warm 6, carry 3; the '<unnamed'
#                     bulk is eager-op + shard_map programs, inflated by
#                     transfer-guard state flips busting the eager cache)
#   test_sharded      total=190 (shard_map bodies log as '<unnamed')
#   test_sharded_2d   total=171 (shared-process; standalone runs higher)
#   test_fleet        total=35  (impl 10, fleet_cold 9, fleet_warm 4 —
#                     the fleet/service suites ride the same bucketed
#                     batch programs, so the budget is tight by design)
# The per-ENTRY-POINT companion to these per-module budgets is the
# declarative RETRACE_BUDGETS table in blance_tpu/analysis/retrace.py,
# checked by `python -m blance_tpu.analysis --ci` with compiles
# attributed to their owning dispatch site (obs/device.py).
_RECOMPILE_BUDGETS = {
    "test_warm_replan": 220,
    "test_sharded": 260,
    "test_sharded_2d": 260,
    "test_fleet": 50,
    #   test_encode_resident total=20 standalone (fleet_cold 11,
    #                     fleet_warm 9 — the residency layer adds ZERO
    #                     new device programs by design: every cycle
    #                     rides the existing bucketed fleet batch
    #                     entries, so the budget pins exactly that)
    "test_encode_resident": 28,
    #   test_pipeline     total=360 standalone (impl 8+7, solve 7, diff 7,
    #                     '<unnamed' bulk = eager ops + the memoized
    #                     sharded-pipeline programs across 5 meshes)
    "test_pipeline": 470,
}


class _CompileCounter(logging.Handler):
    def __init__(self) -> None:
        super().__init__()
        self.by_name: dict = {}

    def emit(self, record: logging.LogRecord) -> None:
        msg = record.getMessage()
        if not msg.startswith("Compiling "):
            return
        name = msg.split(" ", 2)[1]
        self.by_name[name] = self.by_name.get(name, 0) + 1

    @property
    def total(self) -> int:
        return sum(self.by_name.values())


@pytest.fixture(scope="module", autouse=True)
def _recompile_budget(request):
    """Module-scoped retrace budget for the solver suites: snapshots XLA
    compile events across the module and fails teardown when the count
    exceeds the pinned budget — so a change that breaks the jit-cache
    contract (new dynamic shape, a traced value becoming static, a
    static becoming traced) cannot land silently."""
    module = request.node.nodeid.split("::", 1)[0] \
        .rsplit("/", 1)[-1].removesuffix(".py")
    budget = _RECOMPILE_BUDGETS.get(module)
    calibrate = bool(os.environ.get("BLANCE_RECOMPILE_CALIBRATE"))
    if budget is None and not calibrate:
        yield
        return
    counter = _CompileCounter()
    logger = logging.getLogger("jax._src.interpreters.pxla")
    prev_log_compiles = jax.config.jax_log_compiles
    jax.config.update("jax_log_compiles", True)
    logger.addHandler(counter)
    try:
        yield
    finally:
        logger.removeHandler(counter)
        jax.config.update("jax_log_compiles", prev_log_compiles)
    if calibrate:
        print(f"\n[recompile-calibrate] {module}: total={counter.total} "
              f"by_name={dict(sorted(counter.by_name.items()))}")
        return
    assert counter.total <= budget, (
        f"{module} triggered {counter.total} XLA compilations, over its "
        f"pinned budget of {budget}: a solver entry point is retracing "
        f"more than the shape-bucketing/static-args contract allows "
        f"(per function: {dict(sorted(counter.by_name.items()))}); if "
        f"the extra compiles are intended, recalibrate with "
        f"BLANCE_RECOMPILE_CALIBRATE=1 and raise the budget")


def planner_backends():
    """Parametrize golden suites over every planner backend: the Python
    greedy oracle and the native C++ core run the goldens bit-for-bit
    (native.py's stated contract); the batched "tpu" backend runs the
    same corpus in CONTRACT mode (testing/vis.py assert_contract: zero
    audit violations, weighted balance within the golden oracle + 1,
    warnings-count equality) — it solves globally and is deliberately
    not bit-identical."""
    from blance_tpu.plan.native import native_available

    return [
        "greedy",
        pytest.param("native", marks=pytest.mark.skipif(
            not native_available(),
            reason="native toolchain unavailable")),
        "tpu",
    ]
