"""Test configuration.

Forces JAX onto the host CPU platform with 8 virtual devices BEFORE jax is
imported anywhere, so multi-chip sharding tests (jax.sharding.Mesh over 8
devices) run on machines with no TPU attached.  Real-TPU benchmarking happens
in bench.py, not in the test suite.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The axon TPU plugin overrides JAX_PLATFORMS from the environment, so pin
# the platform through the config API as well (must happen before any
# computation runs).
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def planner_backends():
    """Parametrize golden suites over the exact planner backends: the
    Python greedy oracle and the native C++ core, which must be
    bit-identical on every golden case (native.py's stated contract)."""
    from blance_tpu.plan.native import native_available

    return [
        "greedy",
        pytest.param("native", marks=pytest.mark.skipif(
            not native_available(),
            reason="native toolchain unavailable")),
    ]
