"""Ports of the reference's orchestrator tests (orchestrate_test.go:41-1811):
validation, error propagation, pause/resume, early stop, concurrent batch
sizes, and the 13 end-to-end exact-op-sequence scenarios."""

import asyncio

import pytest

from blance_tpu import Partition, PartitionModelState
from blance_tpu.orchestrate import (
    Chan,
    Orchestrator,
    OrchestratorOptions,
    lowest_weight_partition_move_for_node,
    orchestrate_moves,
)

MR_MODEL = {
    "primary": PartitionModelState(priority=0, constraints=0),
    "replica": PartitionModelState(priority=0, constraints=1),
}

OPTIONS1 = OrchestratorOptions(max_concurrent_partition_moves_per_node=1)


def pm(d):
    return {name: Partition(name, {s: list(ns) for s, ns in nbs.items()})
            for name, nbs in d.items()}


def mk_funcs():
    """In-memory fake backend (orchestrate_test.go:130-164): records
    (partition, node, state, op) per partition and tracks current states."""
    curr_states = {}
    recs = {}

    def assign(stop_ch, node, partitions, states, ops):
        recs.setdefault(partitions[0], []).append(
            (partitions[0], node, states[0], ops[0]))
        curr_states.setdefault(partitions[0], {})[node] = states[0]
        return None

    return curr_states, recs, assign


def run(coro):
    return asyncio.run(coro)


def test_orchestrate_bad_moves():
    async def go():
        with pytest.raises(ValueError):
            orchestrate_moves(
                MR_MODEL, OPTIONS1, None,
                pm({"00": {}, "01": {}}),
                pm({"01": {}}),
                lambda *a: None,
            )
        with pytest.raises(ValueError):
            orchestrate_moves(MR_MODEL, OPTIONS1, None, pm({}), pm({}), None)
    run(go())


def test_orchestrate_err_assign_partition_func():
    the_err = RuntimeError("theErr")

    async def go():
        o = orchestrate_moves(
            MR_MODEL, OrchestratorOptions(), ["a", "b"],
            pm({"00": {"primary": ["a"]}}),
            pm({"00": {"primary": ["b"]}}),
            lambda *a: the_err,
        )
        got_progress = 0
        last = None
        async for progress in o.progress_ch():
            got_progress += 1
            last = progress
        o.stop()
        assert got_progress > 0
        assert len(last.errors) > 0
        seen = {}
        o.visit_next_moves(lambda x: seen.update(x))
        assert seen
    run(go())


@pytest.mark.parametrize("num_progress", [1, 2])
def test_orchestrate_pause_resume(num_progress):
    """orchestrate_test.go:166-280."""
    _, _, assign = mk_funcs()

    async def go():
        pause_gate = Chan()

        async def slow_assign(stop_ch, node, partitions, states, ops):
            await pause_gate.get()
            return assign(stop_ch, node, partitions, states, ops)

        three = {
            name: {"primary": ["a"], "replica": ["b"]}
            for name in ("00", "01", "02")
        }
        three_flipped = {
            name: {"primary": ["b"], "replica": ["a"]}
            for name in ("00", "01", "02")
        }
        o = orchestrate_moves(
            MR_MODEL, OrchestratorOptions(), ["a", "b"],
            pm(three), pm(three_flipped),
            slow_assign,
        )
        for _ in range(num_progress):
            await o.progress_ch().get()

        o.pause_new_assignments()
        o.pause_new_assignments()
        o.pause_new_assignments()

        o.resume_new_assignments()
        o.resume_new_assignments()

        pause_gate.close()

        got_progress = 0
        last = None
        async for progress in o.progress_ch():
            got_progress += 1
            last = progress
            o.resume_new_assignments()
        o.stop()

        assert got_progress > 0
        assert not last.errors
        assert last.tot_pause_new_assignments == 1
        assert last.tot_resume_new_assignments == 1
    run(go())


def test_orchestrate_pause_resume_into_moves_supplier():
    """orchestrate_test.go:284-393."""
    _, _, assign = mk_funcs()

    async def go():
        slow_gate = Chan()
        n_calls = 0

        async def slow_assign(stop_ch, node, partitions, states, ops):
            nonlocal n_calls
            n_calls += 1
            if n_calls > 1:
                await slow_gate.get()
            return assign(stop_ch, node, partitions, states, ops)

        o = orchestrate_moves(
            MR_MODEL, OrchestratorOptions(), ["a", "b", "c"],
            pm({"00": {"primary": ["a"], "replica": ["b"]},
                "01": {"primary": ["b"], "replica": ["c"]}}),
            pm({"00": {"primary": ["b"], "replica": ["c"]},
                "01": {"primary": ["c"], "replica": ["a"]}}),
            slow_assign,
        )
        for _ in range(2):
            await o.progress_ch().get()

        o.pause_new_assignments()
        o.pause_new_assignments()
        o.pause_new_assignments()
        o.resume_new_assignments()
        o.resume_new_assignments()

        slow_gate.close()

        got_progress = 0
        last = None
        async for progress in o.progress_ch():
            got_progress += 1
            last = progress
            o.resume_new_assignments()
        o.stop()

        assert got_progress > 0
        assert not last.errors
        assert last.tot_pause_new_assignments == 1
        assert last.tot_resume_new_assignments == 1
    run(go())


def test_orchestrate_early_stop():
    _, _, assign = mk_funcs()

    async def go():
        o = orchestrate_moves(
            MR_MODEL, OrchestratorOptions(), ["a", "b"],
            pm({"00": {"primary": ["a"]}}),
            pm({"00": {"primary": ["b"]}}),
            assign,
        )
        await o.progress_ch().get()

        o.stop()
        o.stop()
        o.stop()

        got_progress = 0
        last = None
        async for progress in o.progress_ch():
            got_progress += 1
            last = progress

        assert got_progress > 0
        assert not last.errors
        assert last.tot_stop == 1
    run(go())


# --- TestOrchestrateConcurrentMoves (orchestrate_test.go:452-1047) ----------

CONCURRENT_CASES = [
    dict(
        label="2 node, 2 partition movement",
        max_concurrent=2, num_progress=1,
        nodes=["a", "b"],
        beg={"00": {"primary": ["a"], "replica": []},
             "01": {"primary": ["a"], "replica": []},
             "02": {"primary": ["a"], "replica": []},
             "03": {"primary": ["a"], "replica": []}},
        end={"00": {"primary": ["a"], "replica": []},
             "01": {"primary": ["a"], "replica": []},
             "02": {"primary": ["b"], "replica": []},
             "03": {"primary": ["b"], "replica": []}},
        exp_node="b", exp_count=2,
        exp_partitions=["02", "03"],
        exp_states=["primary", "primary"],
        exp_ops=["add", "add"],
    ),
    dict(
        label="1 node, 4 partition movement",
        max_concurrent=4, num_progress=1,
        nodes=["a"],
        beg={"00": {}, "01": {}, "02": {}, "03": {}},
        end={name: {"primary": ["a"], "replica": []}
             for name in ("00", "01", "02", "03")},
        exp_node="a", exp_count=4,
        exp_partitions=["00", "01", "02", "03"],
        exp_states=["primary"] * 4,
        exp_ops=["add"] * 4,
    ),
    dict(
        label="1 node delete, 2 partition promote",
        max_concurrent=4, num_progress=1,
        nodes=["a"],
        beg={"00": {"primary": ["a"], "replica": ["b"]},
             "01": {"primary": ["a"], "replica": ["b"]},
             "02": {"primary": ["b"], "replica": ["a"]},
             "03": {"primary": ["b"], "replica": ["a"]}},
        end={name: {"primary": ["a"], "replica": []}
             for name in ("00", "01", "02", "03")},
        exp_node="a", exp_count=2,
        exp_partitions=["02", "03"],
        exp_states=["primary", "primary"],
        exp_ops=["promote", "promote"],
    ),
    dict(
        label="1 node delete, 2 partition del",
        max_concurrent=2, num_progress=2,
        nodes=["a", "b"],
        beg={"00": {"primary": ["a"], "replica": ["b"]},
             "01": {"primary": ["a"], "replica": ["b"]},
             "02": {"primary": ["b"], "replica": ["a"]},
             "03": {"primary": ["b"], "replica": ["a"]}},
        end={name: {"primary": ["a"], "replica": []}
             for name in ("00", "01", "02", "03")},
        exp_node="b", exp_count=2,
        exp_partitions=["00", "01"],
        exp_states=["", ""],
        exp_ops=["del", "del"],
    ),
    dict(
        label="2 node deletions out of 3 node cluster (concurrency 2)",
        max_concurrent=2, num_progress=6,
        nodes=["a", "b", "c"],
        beg={"00": {"primary": ["a"], "replica": ["b"]},
             "01": {"primary": ["a"], "replica": ["c"]},
             "02": {"primary": ["b"], "replica": ["a"]},
             "03": {"primary": ["b"], "replica": ["c"]},
             "04": {"primary": ["c"], "replica": ["a"]},
             "05": {"primary": ["c"], "replica": ["b"]}},
        end={name: {"primary": ["a"], "replica": []}
             for name in ("00", "01", "02", "03", "04", "05")},
        exp_node="a", exp_count=2, skip_callbacks=1,
        exp_partitions=["03", "05"],
        exp_states=["primary", "primary"],
        exp_ops=["add", "add"],
    ),
    dict(
        label="2 node deletions out of 3 node cluster (concurrency 4)",
        max_concurrent=4, num_progress=6,
        nodes=["a", "b", "c"],
        beg={"00": {"primary": ["a"], "replica": ["b"]},
             "01": {"primary": ["a"], "replica": ["c"]},
             "02": {"primary": ["b"], "replica": ["a"]},
             "03": {"primary": ["b"], "replica": ["c"]},
             "04": {"primary": ["c"], "replica": ["a"]},
             "05": {"primary": ["c"], "replica": ["b"]}},
        end={name: {"primary": ["a"], "replica": []}
             for name in ("00", "01", "02", "03", "04", "05")},
        exp_node="a", exp_count=4,
        exp_partitions=["02", "03", "04", "05"],
        exp_states=["primary"] * 4,
        exp_ops=["promote", "promote", "add", "add"],
    ),
]


@pytest.mark.parametrize("case", CONCURRENT_CASES,
                         ids=[c["label"] for c in CONCURRENT_CASES])
def test_orchestrate_concurrent_moves(case):
    _, _, record_assign = mk_funcs()
    failures = []

    async def go():
        skip_callbacks = case.get("skip_callbacks", 0)

        def assign(stop_ch, node, partitions, states, ops):
            nonlocal skip_callbacks
            if case["exp_node"] != node:
                return None
            if skip_callbacks > 0:
                skip_callbacks -= 1
                return None
            if len(partitions) != case["exp_count"]:
                failures.append(
                    f"batch size {len(partitions)} != {case['exp_count']}")
            if sorted(partitions) != case["exp_partitions"]:
                failures.append(f"partitions {sorted(partitions)}")
            if sorted(states) != case["exp_states"]:
                failures.append(f"states {sorted(states)}")
            if ops != case["exp_ops"]:
                failures.append(f"ops {ops}")
            record_assign(stop_ch, node, partitions, states, ops)
            return None

        o = orchestrate_moves(
            MR_MODEL,
            OrchestratorOptions(
                max_concurrent_partition_moves_per_node=case["max_concurrent"]),
            case["nodes"], pm(case["beg"]), pm(case["end"]),
            assign,
        )
        while True:
            prog, ok = await o.progress_ch().get()
            if not ok:
                break
            if prog.tot_mover_assign_partition_ok >= case["num_progress"]:
                break
        o.stop()
        # Drain to completion so all tasks wind down.
        async for _ in o.progress_ch():
            pass

    run(go())
    assert not failures, failures


# --- TestOrchestrateMoves: 13 end-to-end scenarios (orchestrate_test.go:1049) --

MOVES_CASES = [
    dict(label="do nothing", nodes=None, beg={}, end={}, expect={}),
    dict(label="1 node, no assignments or changes", nodes=["a"],
         beg={}, end={}, expect={}),
    dict(label="no nodes, but some partitions", nodes=None,
         beg={"00": {}, "01": {}}, end={"00": {}, "01": {}}, expect={}),
    dict(
        label="add node a, 1 partition",
        nodes=["a"], beg={"00": {}}, end={"00": {"primary": ["a"]}},
        expect={"00": [("00", "a", "primary")]},
    ),
    dict(
        label="add node a & b, 1 partition",
        nodes=["a", "b"], beg={"00": {}},
        end={"00": {"primary": ["a"], "replica": ["b"]}},
        expect={"00": [("00", "a", "primary"), ("00", "b", "replica")]},
    ),
    dict(
        label="add node a & b & c, 1 partition",
        nodes=["a", "b", "c"], beg={"00": {}},
        end={"00": {"primary": ["a"], "replica": ["b"]}},
        expect={"00": [("00", "a", "primary"), ("00", "b", "replica")]},
    ),
    dict(
        label="del node a, 1 partition",
        nodes=["a"], beg={"00": {"primary": ["a"]}}, end={"00": {}},
        expect={"00": [("00", "a", "")]},
    ),
    dict(
        label="swap a to b, 1 partition",
        nodes=["a", "b"],
        beg={"00": {"primary": ["a"]}}, end={"00": {"primary": ["b"]}},
        expect={"00": [("00", "b", "primary"), ("00", "a", "")]},
    ),
    dict(
        label="swap a to b, 1 partition, c unchanged",
        nodes=["a", "b", "c"],
        beg={"00": {"primary": ["a"], "replica": ["c"]}},
        end={"00": {"primary": ["b"], "replica": ["c"]}},
        expect={"00": [("00", "b", "primary"), ("00", "a", "")]},
    ),
    dict(
        label="1 partition from a|b to c|a",
        nodes=["a", "b", "c"],
        beg={"00": {"primary": ["a"], "replica": ["b"]}},
        end={"00": {"primary": ["c"], "replica": ["a"]}},
        expect={"00": [("00", "c", "primary"), ("00", "a", "replica"),
                       ("00", "b", "")]},
    ),
    dict(
        label="add node a & b, 2 partitions",
        nodes=["a", "b"],
        beg={"00": {}, "01": {}},
        end={"00": {"primary": ["a"], "replica": ["b"]},
             "01": {"primary": ["b"], "replica": ["a"]}},
        expect={"00": [("00", "a", "primary"), ("00", "b", "replica")],
                "01": [("01", "b", "primary"), ("01", "a", "replica")]},
    ),
    dict(
        label="swap ab to cd, 2 partitions",
        nodes=["a", "b", "c", "d"],
        beg={"00": {"primary": ["a"], "replica": ["b"]},
             "01": {"primary": ["b"], "replica": ["a"]}},
        end={"00": {"primary": ["c"], "replica": ["d"]},
             "01": {"primary": ["d"], "replica": ["c"]}},
        expect={"00": [("00", "c", "primary"), ("00", "a", ""),
                       ("00", "d", "replica"), ("00", "b", "")],
                "01": [("01", "d", "primary"), ("01", "b", ""),
                       ("01", "c", "replica"), ("01", "a", "")]},
    ),
    dict(
        # The reference marks this case intermittent (a goroutine race,
        # orchestrate_test.go:1455-1459, TODO-level known gap).  Here it
        # runs deterministically: the asyncio orchestrator serializes on
        # one loop, so the MoveOpWeight inner branch it was written to
        # cover is hit every run.
        label="concurrent moves on b, 2 partitions",
        nodes=["a", "b", "c"],
        beg={"00": {"primary": ["b"], "replica": ["a"]},
             "01": {"primary": ["b"], "replica": ["a"]}},
        end={"00": {"primary": ["a"], "replica": ["b"]},
             "01": {"primary": ["c"], "replica": ["a"]}},
        expect={"00": [("00", "a", "primary"), ("00", "b", "replica")],
                "01": [("01", "c", "primary"), ("01", "b", "")]},
    ),
    dict(
        label="nodes with not much work",
        nodes=["a", "b", "c", "d", "e"],
        beg={"00": {"primary": ["b"], "replica": ["a", "d", "e"]},
             "01": {"primary": ["b"], "replica": ["a", "d", "e"]}},
        end={"00": {"primary": ["a"], "replica": ["b", "d", "e"]},
             "01": {"primary": ["c"], "replica": ["a", "d", "e"]}},
        expect={"00": [("00", "a", "primary"), ("00", "b", "replica")],
                "01": [("01", "c", "primary"), ("01", "b", "")]},
    ),
    dict(
        label="more concurrent moves",
        nodes=["a", "b", "c", "d", "e", "f", "g"],
        beg={"00": {"primary": ["a"], "replica": ["b"]},
             "01": {"primary": ["b"], "replica": ["c"]},
             "02": {"primary": ["c"], "replica": ["d"]},
             "03": {"primary": ["d"], "replica": ["e"]},
             "04": {"primary": ["e"], "replica": ["f"]},
             "05": {"primary": ["f"], "replica": ["g"]}},
        end={"00": {"primary": ["b"], "replica": ["c"]},
             "01": {"primary": ["c"], "replica": ["d"]},
             "02": {"primary": ["d"], "replica": ["e"]},
             "03": {"primary": ["e"], "replica": ["f"]},
             "04": {"primary": ["f"], "replica": ["g"]},
             "05": {"primary": ["g"], "replica": ["a"]}},
        expect={"00": [("00", "b", "primary"), ("00", "a", ""),
                       ("00", "c", "replica")],
                "01": [("01", "c", "primary"), ("01", "b", ""),
                       ("01", "d", "replica")],
                "02": [("02", "d", "primary"), ("02", "c", ""),
                       ("02", "e", "replica")],
                "03": [("03", "e", "primary"), ("03", "d", ""),
                       ("03", "f", "replica")],
                "04": [("04", "f", "primary"), ("04", "e", ""),
                       ("04", "g", "replica")],
                "05": [("05", "g", "primary"), ("05", "f", ""),
                       ("05", "a", "replica")]},
    ),
]


@pytest.mark.parametrize("case", MOVES_CASES,
                         ids=[c["label"] for c in MOVES_CASES])
def test_orchestrate_moves(case):
    _, recs, assign = mk_funcs()

    async def go():
        o = orchestrate_moves(
            MR_MODEL, OPTIONS1, case["nodes"],
            pm(case["beg"]), pm(case["end"]),
            assign,
            lowest_weight_partition_move_for_node,
        )
        async for _ in o.progress_ch():
            pass
        o.stop()

    run(go())

    assert len(recs) == len(case["expect"]), (recs, case["expect"])
    for partition, exp_seq in case["expect"].items():
        got = [(p, n, s) for (p, n, s, _op) in recs[partition]]
        assert got == exp_seq, f"{case['label']}: {partition}: {got} != {exp_seq}"


def test_orchestrate_custom_find_move_views():
    """A NON-default FindMoveFunc takes the PartitionMove-materializing
    path (the default policy short-circuits past it): the views handed to
    the callback must carry the cursor's exact (partition, node, state,
    op), and the returned index must be honored — exercised with a
    highest-weight-first policy, the reverse of the default."""
    from blance_tpu.orchestrate import MOVE_OP_WEIGHT, PartitionMove

    seen = []

    def heaviest_first(node, moves):
        for m in moves:
            assert isinstance(m, PartitionMove)
            seen.append((m.partition, m.node, m.state, m.op))
        r = 0
        for i, m in enumerate(moves):
            if MOVE_OP_WEIGHT.get(m.op, 0) > MOVE_OP_WEIGHT.get(moves[r].op, 0):
                r = i
        return r

    _, recs, assign = mk_funcs()

    async def go():
        o = orchestrate_moves(
            MR_MODEL, OPTIONS1, ["a", "b", "c"],
            pm({"00": {"replica": ["a"]}, "01": {"replica": ["a"]}}),
            pm({"00": {"replica": ["b"]}, "01": {"replica": ["c"]}}),
            assign,
            heaviest_first,
        )
        async for _ in o.progress_ch():
            pass
        o.stop()

    run(go())

    # Every move executed exactly once, adds before dels per partition.
    for p, dst in (("00", "b"), ("01", "c")):
        ops = [(n, s, op) for (_p, n, s, op) in recs[p]]
        assert (dst, "replica", "add") in ops and ("a", "", "del") in ops
        assert ops.index((dst, "replica", "add")) < ops.index(("a", "", "del"))
    # The callback saw well-formed views for every candidate it was shown.
    assert seen and all(
        p in ("00", "01") and n in ("a", "b", "c") and op in ("add", "del")
        for (p, n, _s, op) in seen)
