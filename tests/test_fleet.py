"""Fleet tier: batched multi-tenant solves, carry cache, plan service.

The acceptance contract this module pins (ISSUE 7):

- batched ``[B, P, S, N]`` fleet solves are BIT-IDENTICAL to running
  each tenant through the existing single-problem path on the same
  padded arrays — across ≥ 2 bucket classes, cold AND warm-carry, on
  and off the batch-sharding mesh;
- bucket-boundary bit-neutrality: the inert-padding recipe cannot
  perturb real rows (unpadded-with-p_real == bucket-padded-with-p_real
  on the real rows), and tenants straddling a ``bucket_size`` boundary
  land in different classes yet each still matches its sequential
  solve;
- the keyed :class:`plan.carry.CarryCache` preserves the session's
  carry lifecycle (identity/value matching, pending promotion,
  dirty-mask routing, node padding) under an LRU byte budget whose
  evictions only ever cost a cold solve;
- the asyncio :class:`plan.service.PlanService` coalesces concurrent
  submits into per-class batches, reuses per-tenant carries across
  rounds (warm), applies backpressure via its bounded queue, fails
  cleanly on stop, and emits only registry-declared metrics.
"""

import asyncio

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from blance_tpu.core.encode import bucket_size, pad_problem_arrays, pad_to
from blance_tpu.obs import Recorder, use_recorder
from blance_tpu.plan.carry import (
    CarryCache,
    capacity_shrank,
    effective_dirty,
    pad_carry_nodes,
)
from blance_tpu.plan.fleet import (
    TenantProblem,
    batch_class_of,
    solve_fleet,
)
from blance_tpu.plan.service import PlanService, PlanServiceClosed
from blance_tpu.plan.session import PlannerSession
from blance_tpu.plan.tensor import (
    SolveCarry,
    _solve_dense_converged_impl,
    solve_dense_converged,
    solve_dense_warm,
)

CONSTRAINTS = (1, 1)
RULES = ((), ((2, 1),))  # replica on another rack


def make_tenant(P, N, seed, key=None, weights=False):
    rng = np.random.default_rng(seed)
    prev = np.full((P, 2, 1), -1, np.int32)
    prev[:, 0, 0] = rng.integers(0, N, P)
    prev[:, 1, 0] = (prev[:, 0, 0] + 1 + rng.integers(0, N - 1, P)) % N
    pw = rng.integers(1, 3, P).astype(np.float32) if weights \
        else np.ones(P, np.float32)
    return TenantProblem(
        key=key or f"t{P}x{N}s{seed}", prev=prev,
        partition_weights=pw,
        node_weights=np.ones(N, np.float32),
        valid_node=np.ones(N, bool),
        stickiness=np.full((P, 2), 1.5, np.float32),
        gids=np.stack([np.arange(N, dtype=np.int32),
                       np.arange(N, dtype=np.int32) // 4,
                       np.zeros(N, np.int32)]),
        gid_valid=np.ones((3, N), bool),
        constraints=CONSTRAINTS, rules=RULES)


def solve_sequential(t):
    """The existing single-problem path on the tenant's class-padded
    arrays (bucketed solve_dense_converged + real-P fill denominator):
    the fleet solver's bit-identity reference.  Returns (real-row
    assign, padded carry)."""
    k = batch_class_of(t)
    arrs = pad_problem_arrays(
        t.prev, t.partition_weights, t.node_weights, t.valid_node,
        t.stickiness, t.gids, t.gid_valid, k.p, k.n)
    out, carry = solve_dense_converged(
        *[jnp.asarray(a) for a in arrs], t.constraints, t.rules,
        max_iterations=10, fused_score="off", record=False,
        return_carry=True,
        p_real=jax.device_put(np.float32(t.prev.shape[0])))
    return np.asarray(out)[:t.prev.shape[0]], carry


def delta_tenant(t, result, victim_rank=0):
    """Round-2 tenant: remove one held node, session-style dirty mask,
    carry from round 1."""
    held = np.unique(result.assign[result.assign >= 0])
    v = held[victim_rank % len(held)]
    valid2 = t.valid_node.copy()
    valid2[v] = False
    dirty = (result.assign == v).any(axis=(1, 2))
    return TenantProblem(
        key=t.key, prev=result.assign,
        partition_weights=t.partition_weights,
        node_weights=t.node_weights, valid_node=valid2,
        stickiness=t.stickiness, gids=t.gids, gid_valid=t.gid_valid,
        constraints=t.constraints, rules=t.rules,
        carry=result.carry, dirty=dirty), int(v)


# Two bucket classes ([16, 32) octave buckets are 2 wide): P 17/18 ->
# class 18, P 19/20 -> class 20.  Module-scoped so every test shares
# the compiled batch programs.
@pytest.fixture(scope="module")
def fleet_round1():
    tenants = [make_tenant(17 + (i % 4), 8, seed=i, weights=i % 3 == 0)
               for i in range(12)]
    results = solve_fleet(tenants)
    return tenants, results


# -- batch classes -----------------------------------------------------------


def test_batch_classes_follow_shape_buckets():
    same_a = batch_class_of(make_tenant(17, 8, 0))
    same_b = batch_class_of(make_tenant(18, 8, 1))
    other_p = batch_class_of(make_tenant(19, 8, 2))
    other_n = batch_class_of(make_tenant(17, 9, 3))
    assert same_a == same_b  # straddles nothing: one padded program
    assert same_a != other_p  # crosses the P bucket boundary
    assert same_a != other_n  # crosses the N bucket boundary
    assert same_a.p == bucket_size(17) == 18


def test_fleet_rejects_underdeep_slots():
    t = make_tenant(8, 4, 0)
    bad = TenantProblem(
        key="bad", prev=t.prev, partition_weights=t.partition_weights,
        node_weights=t.node_weights, valid_node=t.valid_node,
        stickiness=t.stickiness, gids=t.gids, gid_valid=t.gid_valid,
        constraints=(2, 1), rules=t.rules)  # R=1 < max constraint 2
    with pytest.raises(ValueError, match="slot depth"):
        solve_fleet([bad])


# -- cold bit-identity -------------------------------------------------------


def test_cold_batch_bit_identical_across_two_classes(fleet_round1):
    tenants, results = fleet_round1
    classes = {batch_class_of(t) for t in tenants}
    assert len(classes) == 2
    for t, r in zip(tenants, results):
        ref, ref_carry = solve_sequential(t)
        assert np.array_equal(ref, r.assign), t.key
        n = t.node_weights.shape[0]
        # The rebuilt carry must seed the next warm solve exactly like
        # the sequential path's: bit-equal used table (real columns).
        assert np.array_equal(np.asarray(ref_carry.used)[:, :n],
                              np.asarray(r.carry.used)), t.key
        assert not r.warm and r.sweeps >= 1


def test_fleet_results_keep_input_order_and_keys(fleet_round1):
    tenants, results = fleet_round1
    assert [r.key for r in results] == [t.key for t in tenants]


def test_degenerate_tenant_passes_through():
    t = make_tenant(6, 4, 0)
    empty = TenantProblem(
        key="empty", prev=np.zeros((0, 2, 1), np.int32),
        partition_weights=np.zeros(0, np.float32),
        node_weights=t.node_weights, valid_node=t.valid_node,
        stickiness=np.zeros((0, 2), np.float32), gids=t.gids,
        gid_valid=t.gid_valid, constraints=CONSTRAINTS, rules=RULES)
    res = solve_fleet([empty, t])
    assert res[0].klass is None and res[0].assign.shape == (0, 2, 1)
    assert np.array_equal(res[1].assign, solve_sequential(t)[0])


# -- bucket-boundary bit-neutrality ------------------------------------------


def test_bucket_padding_is_bit_neutral_on_real_rows():
    """The inert-padding recipe: solving the unpadded problem (with the
    traced real-P denominator) and the bucket-padded problem must agree
    bit-for-bit on the real rows — padding can never perturb a solve."""
    for P, N, seed in [(17, 9, 0), (19, 9, 1), (15, 10, 2)]:
        t = make_tenant(P, N, seed, weights=True)
        args_u = (t.prev, t.partition_weights, t.node_weights,
                  t.valid_node, t.stickiness, t.gids, t.gid_valid)
        out_u, _ = _solve_dense_converged_impl(
            *[jnp.asarray(a) for a in args_u], t.constraints, t.rules,
            max_iterations=10, fused_score="off",
            p_real=jax.device_put(np.float32(P)))
        k = batch_class_of(t)
        arrs_p = pad_problem_arrays(
            t.prev, t.partition_weights, t.node_weights, t.valid_node,
            t.stickiness, t.gids, t.gid_valid, k.p, k.n)
        out_p, _ = _solve_dense_converged_impl(
            *[jnp.asarray(a) for a in arrs_p], t.constraints, t.rules,
            max_iterations=10, fused_score="off",
            p_real=jax.device_put(np.float32(P)))
        assert np.array_equal(np.asarray(out_u),
                              np.asarray(out_p)[:P]), (P, N)


def test_boundary_straddling_tenants_solve_identically():
    """P just below vs just above a bucket boundary (16 | 17 -> buckets
    16 | 18) lands in different classes; the batched solve of BOTH must
    still match each tenant's sequential solve bit-for-bit."""
    below = make_tenant(16, 8, 5)
    above = make_tenant(17, 8, 6)
    kb, ka = batch_class_of(below), batch_class_of(above)
    assert (kb.p, ka.p) == (16, 18)
    for t, r in zip([below, above], solve_fleet([below, above])):
        assert np.array_equal(r.assign, solve_sequential(t)[0])


# -- warm bit-identity -------------------------------------------------------


def test_warm_batch_bit_identical_and_accepted(fleet_round1):
    tenants, results = fleet_round1
    round2 = [delta_tenant(t, r)[0] for t, r in zip(tenants, results)]
    res2 = solve_fleet(round2)
    assert all(r.warm for r in res2), "confined deltas must ride warm"
    for t, r in zip(round2, res2):
        k = batch_class_of(t)
        arrs = pad_problem_arrays(
            t.prev, t.partition_weights, t.node_weights, t.valid_node,
            t.stickiness, t.gids, t.gid_valid, k.p, k.n)
        cu = pad_to(np.asarray(t.carry.used, np.float32), 1, k.n, 0.0)
        dirty_p = pad_to(
            effective_dirty(t.dirty, t.prev, t.constraints), 0, k.p,
            True)
        wout, wcarry = solve_dense_warm(
            *arrs, t.constraints, t.rules,
            dirty=dirty_p,
            carry=SolveCarry(prices=cu.sum(axis=0), assign=arrs[0],
                             used=cu),
            fused_score="off", record=False, donate=False,
            p_real=jax.device_put(np.float32(t.prev.shape[0])))
        assert wout is not None, f"{t.key}: sequential warm declined"
        p, n = t.prev.shape[0], t.node_weights.shape[0]
        assert np.array_equal(wout[:p], r.assign), t.key
        assert np.array_equal(np.asarray(wcarry.used)[:, :n],
                              np.asarray(r.carry.used)), t.key
        assert r.sweeps == 1


def _under_marked(tenants, results):
    """A node-removal delta whose dirty mask lies (all-False): the
    removed node's holders MUST move, so a warm repair ripples."""
    t0, r0 = tenants[0], results[0]
    with_delta, _v = delta_tenant(t0, r0)
    return TenantProblem(
        key=t0.key, prev=with_delta.prev,
        partition_weights=with_delta.partition_weights,
        node_weights=with_delta.node_weights,
        valid_node=with_delta.valid_node,
        stickiness=with_delta.stickiness, gids=with_delta.gids,
        gid_valid=with_delta.gid_valid,
        constraints=with_delta.constraints, rules=with_delta.rules,
        carry=with_delta.carry,
        dirty=np.zeros(with_delta.prev.shape[0], bool))


def test_capacity_precheck_demotes_unmarkable_delta(fleet_round1):
    """Session parity: a shrink the dirty mask doesn't cover is caught
    by the host precheck BEFORE wasting a repair sweep (carry_miss,
    no warm attempt), and the cold result is the sequential one."""
    lying = _under_marked(*fleet_round1)
    rec = Recorder()
    with use_recorder(rec):
        res = solve_fleet([lying])[0]
    assert not res.warm
    assert rec.counters.get("plan.solve.carry_miss", 0) == 1
    assert rec.counters.get("plan.solve.warm_fallback", 0) == 0
    assert np.array_equal(res.assign, solve_sequential(lying)[0])


def test_warm_decline_falls_back_to_cold_identically(
        fleet_round1, monkeypatch):
    """The in-graph acceptance flags: with the host precheck bypassed,
    the batched repair itself must detect the ripple, decline per
    element, and fall back to the identical cold fixpoint — exactly
    like the sequential solve_dense_warm -> cold chain."""
    import blance_tpu.plan.fleet as fleet_mod

    lying = _under_marked(*fleet_round1)
    monkeypatch.setattr(fleet_mod, "capacity_shrank",
                        lambda *a, **k: False)
    rec = Recorder()
    with use_recorder(rec):
        res = solve_fleet([lying])[0]
    assert not res.warm
    assert rec.counters.get("plan.solve.warm_fallback", 0) == 1
    assert np.array_equal(res.assign, solve_sequential(lying)[0])


def test_mesh_sharded_fleet_bit_identical(fleet_round1):
    from blance_tpu.parallel.sharded import make_mesh

    tenants, results = fleet_round1
    res_m = solve_fleet(tenants, mesh=make_mesh())
    for r0, rm in zip(results, res_m):
        assert np.array_equal(r0.assign, rm.assign)
        assert np.array_equal(np.asarray(r0.carry.used),
                              np.asarray(rm.carry.used))


# -- CarryCache --------------------------------------------------------------


def _toy_carry(p=4, s=2, n=3, fill=1.0):
    used = np.full((s, n), fill, np.float32)
    return SolveCarry(prices=used.sum(axis=0),
                      assign=np.zeros((p, s, 1), np.int32), used=used)


def test_carry_cache_consume_matching_modes():
    cache = CarryCache()
    cur = np.zeros((4, 2, 1), np.int32)
    cache.store("a", _toy_carry(), cur)
    # Value-equal but different object: identity match misses, value
    # match hits (the service's mode — callers rebuild prev arrays).
    clone = cur.copy()
    carry, _ = cache.consume("a", clone, match="identity")
    assert carry is None
    cache.store("a", _toy_carry(), cur)
    carry, _ = cache.consume("a", clone, match="equal")
    assert carry is not None
    # Consumed: a second consume misses until the next store/promote.
    carry2, _ = cache.consume("a", clone, match="equal")
    assert carry2 is None


def test_carry_cache_pending_promotion_and_dirty_routing():
    cache = CarryCache()
    cur = np.zeros((4, 2, 1), np.int32)
    e = cache.entry("a", 4)
    cache.mark_dirty("a", np.array([1, 0, 0, 0], bool), pending=False)
    cache.store_pending("a", _toy_carry())
    # A delta landing while the proposal is pending must carry forward
    # through promote, not clear with the absorbed marks.
    cache.mark_dirty("a", np.array([0, 0, 1, 0], bool), pending=True)
    cache.promote("a", cur)
    assert e.carry is not None and e.pending is None
    carry, dirty = cache.consume("a", cur)
    assert carry is not None
    assert dirty.tolist() == [False, False, True, False]


def test_carry_cache_pad_nodes_grows_both_carries():
    cache = CarryCache()
    cur = np.zeros((4, 2, 1), np.int32)
    cache.store("a", _toy_carry(n=3), cur)
    cache.store_pending("a", _toy_carry(n=3, fill=2.0))
    cache.pad_nodes("a", 5)
    e = cache.peek("a")
    assert e.carry.used.shape == (2, 5)
    assert e.pending.used.shape == (2, 5)
    assert (np.asarray(e.carry.used)[:, 3:] == 0).all()
    assert np.allclose(np.asarray(e.carry.prices),
                       np.asarray(e.carry.used).sum(axis=0))
    assert pad_carry_nodes(None, 9) is None


def test_carry_cache_lru_byte_budget_evicts_oldest():
    one = _toy_carry()
    per_entry = sum(np.asarray(a).nbytes
                    for a in (one.prices, one.assign, one.used))
    cache = CarryCache(max_bytes=2 * per_entry)
    cur = np.zeros((4, 2, 1), np.int32)
    for key in ("a", "b", "c"):
        cache.store(key, _toy_carry(), cur)
    assert cache.nbytes() <= 2 * per_entry
    # Oldest ("a") lost its carry; the entry (and its masks) survive.
    assert cache.peek("a").carry is None
    assert cache.peek("b").carry is not None
    assert cache.peek("c").carry is not None
    # Touching "b" then adding "d" evicts "c" (LRU, not insertion).
    cache.consume("b", cur)
    cache.store("b", _toy_carry(), cur)
    cache.store("d", _toy_carry(), cur)
    assert cache.peek("c").carry is None
    assert cache.peek("b").carry is not None


def test_carry_cache_entry_resets_on_shape_change():
    cache = CarryCache()
    cache.store("a", _toy_carry(p=4), np.zeros((4, 2, 1), np.int32))
    e = cache.entry("a", 6)  # the tenant's P changed: stale by shape
    assert e.carry is None and e.dirty.shape == (6,)


def test_eviction_only_costs_a_cold_solve():
    """A budget-evicted carry demotes the tenant to cold — results stay
    identical to the never-cached run (eviction is always safe)."""
    t = make_tenant(18, 8, 11)
    r1 = solve_fleet([t])[0]
    t2, _ = delta_tenant(t, r1)
    # Warm (cache intact) vs cold (carry stripped) must agree because
    # the warm repair is bit-identical to the cold fixpoint by contract.
    warm_res = solve_fleet([t2])[0]
    cold_only = TenantProblem(
        key=t2.key, prev=t2.prev, partition_weights=t2.partition_weights,
        node_weights=t2.node_weights, valid_node=t2.valid_node,
        stickiness=t2.stickiness, gids=t2.gids, gid_valid=t2.gid_valid,
        constraints=t2.constraints, rules=t2.rules)
    cold_res = solve_fleet([cold_only])[0]
    assert warm_res.warm and not cold_res.warm
    assert np.array_equal(warm_res.assign, cold_res.assign)


def test_sessions_share_a_keyed_cache():
    """Two sessions on one CarryCache under distinct keys: both carry
    warm state independently (the ROADMAP refactor unlock)."""
    nodes = [f"n{i:02d}" for i in range(8)]
    parts = [str(i) for i in range(24)]
    from blance_tpu import model

    m = model(primary=(0, 1), replica=(1, 1))
    cache = CarryCache()
    s1 = PlannerSession(m, nodes, parts, carry_cache=cache,
                        cache_key="tenant-1")
    s2 = PlannerSession(m, nodes, parts, carry_cache=cache,
                        cache_key="tenant-2")
    for s in (s1, s2):
        s.replan()
        s.apply()
    assert set(cache.keys()) == {"tenant-1", "tenant-2"}
    rec = Recorder()
    with use_recorder(rec):
        s1.remove_nodes([nodes[0]])
        s1.replan()
        s2.remove_nodes([nodes[1]])
        s2.replan()
    assert rec.counters.get("plan.solve.carry_hit", 0) == 2


# -- capacity precheck parity ------------------------------------------------


def test_capacity_shrank_matches_session_behavior():
    used = np.array([[4.0, 0.0], [0.0, 4.0]], np.float32)
    current = np.zeros((4, 2, 1), np.int32)
    current[:, 1, 0] = 1
    pw = np.ones(4, np.float32)
    nw = np.ones(2, np.float32)
    valid = np.ones(2, bool)
    dirty = np.zeros(4, bool)
    # Balanced: rail = ceil(1*4*0.5) = 2, held 4 > 2 + allowance 1.
    assert capacity_shrank(used, current, pw, nw, valid, (1, 1), dirty)
    # Everything dirty: held weight cannot pin, no shrink.
    assert not capacity_shrank(used, current, pw, nw, valid, (1, 1),
                               np.ones(4, bool))


# -- the plan service --------------------------------------------------------


def _run(coro):
    return asyncio.run(coro)


def test_service_coalesces_and_matches_direct_solve():
    tenants = [make_tenant(17 + (i % 2), 8, seed=40 + i, key=f"svc{i}")
               for i in range(8)]
    rec = Recorder()

    async def drive():
        svc = PlanService(admission_window_s=0.05, recorder=rec)
        await svc.start()
        results = await asyncio.gather(
            *[svc.submit(t) for t in tenants])
        await svc.stop()
        return results

    with use_recorder(rec):
        results = _run(drive())
        direct = solve_fleet(tenants)
    for got, want in zip(results, direct):
        assert np.array_equal(got.assign, want.assign)
    # 8 concurrent submits coalesced into one batch per class.
    assert rec.counters["fleet.requests"] == 8
    assert rec.counters["fleet.batches"] <= 2
    assert rec._hist_stats["fleet.batch_tenants"][3] >= 4  # max
    assert rec._hist_stats["fleet.admission_latency_s"][0] == 8


def test_service_warm_carry_across_rounds():
    tenants = [make_tenant(18, 8, seed=60 + i, key=f"warm{i}")
               for i in range(4)]
    rec = Recorder()

    async def drive():
        svc = PlanService(admission_window_s=0.02, recorder=rec)
        await svc.start()
        r1 = await asyncio.gather(*[svc.submit(t) for t in tenants])
        round2 = []
        for t, r in zip(tenants, r1):
            t2, _ = delta_tenant(
                t, solve_fleet([t])[0])  # same delta derivation
            # Build the round-2 request WITHOUT a carry: the service's
            # cache must supply it (prev == cached assign by value).
            round2.append(TenantProblem(
                key=t.key, prev=r.assign,
                partition_weights=t.partition_weights,
                node_weights=t2.node_weights, valid_node=t2.valid_node,
                stickiness=t.stickiness, gids=t.gids,
                gid_valid=t.gid_valid, constraints=t.constraints,
                rules=t.rules, dirty=t2.dirty))
        r2 = await asyncio.gather(*[svc.submit(t) for t in round2])
        await svc.stop()
        return r1, r2

    with use_recorder(rec):
        _r1, r2 = _run(drive())
    assert all(r.warm for r in r2)
    assert rec.counters.get("plan.solve.carry_hit", 0) == 4


def test_service_without_dirty_mask_solves_cold():
    t = make_tenant(18, 8, seed=70, key="colder")
    rec = Recorder()

    async def drive():
        svc = PlanService(admission_window_s=0.0, recorder=rec)
        await svc.start()
        r1 = await svc.submit(t)
        # Same prev again, but no dirty statement: must not warm.
        r2 = await svc.submit(TenantProblem(
            key=t.key, prev=r1.assign,
            partition_weights=t.partition_weights,
            node_weights=t.node_weights, valid_node=t.valid_node,
            stickiness=t.stickiness, gids=t.gids, gid_valid=t.gid_valid,
            constraints=t.constraints, rules=t.rules))
        await svc.stop()
        return r2

    with use_recorder(rec):
        r2 = _run(drive())
    assert not r2.warm
    assert rec.counters.get("plan.solve.carry_hit", 0) == 0


def test_service_stop_and_closed_semantics():
    t = make_tenant(17, 8, seed=80, key="stopme")

    async def drive():
        svc = PlanService(admission_window_s=0.0)
        await svc.start()
        await svc.start()  # idempotent
        r = await svc.submit(t)
        await svc.stop()
        await svc.stop()  # idempotent
        with pytest.raises(PlanServiceClosed):
            await svc.submit(t)
        with pytest.raises(PlanServiceClosed):
            await svc.start()
        return r

    r = _run(drive())
    assert np.array_equal(r.assign, solve_sequential(t)[0])


def test_service_submit_before_start_raises():
    async def drive():
        svc = PlanService()
        with pytest.raises(PlanServiceClosed):
            await svc.submit(make_tenant(17, 8, 0))

    _run(drive())


def test_service_backpressure_bounds_queue():
    """With max_pending=2 and a dispatcher held busy, a third submit
    must block until the queue drains (bounded backlog)."""
    tenants = [make_tenant(17, 8, seed=90 + i, key=f"bp{i}")
               for i in range(6)]

    async def drive():
        svc = PlanService(admission_window_s=0.0, max_pending=2)
        await svc.start()
        subs = [asyncio.create_task(svc.submit(t)) for t in tenants]
        # The queue can hold at most 2 un-admitted requests at any
        # instant, so all six only complete because submits kept
        # unblocking as the dispatcher drained — and every future must
        # resolve despite the bound.
        results = await asyncio.gather(*subs)
        await svc.stop()
        assert len(results) == 6
        return results

    results = _run(drive())
    assert all(r.assign is not None for r in results)


def test_service_malformed_request_fails_alone():
    """A request that dies in batch preparation (here: prev as a plain
    list, which the cache lookup rejects) fails only its own future —
    co-batched neighbors still solve, and the service stays up."""
    good = make_tenant(17, 8, seed=95, key="good")
    bad = TenantProblem(
        key="bad", prev=[[0]],  # type: ignore[arg-type]
        partition_weights=good.partition_weights,
        node_weights=good.node_weights, valid_node=good.valid_node,
        stickiness=good.stickiness, gids=good.gids,
        gid_valid=good.gid_valid, constraints=good.constraints,
        rules=good.rules)

    async def drive():
        svc = PlanService(admission_window_s=0.05)
        await svc.start()
        good_fut = asyncio.ensure_future(svc.submit(good))
        bad_fut = asyncio.ensure_future(svc.submit(bad))
        done = await asyncio.gather(good_fut, bad_fut,
                                    return_exceptions=True)
        # Still serving after the failure.
        again = await svc.submit(make_tenant(17, 8, seed=96, key="ok2"))
        await svc.stop()
        return done, again

    (good_res, bad_res), again = _run(drive())
    assert isinstance(bad_res, Exception)
    assert np.array_equal(good_res.assign, solve_sequential(good)[0])
    assert again.assign is not None


def test_service_stop_after_dispatcher_crash_cleans_up(monkeypatch):
    """A crashed dispatcher must be observable (warning + counter) and
    a subsequent stop() must still release the executor thread."""
    rec = Recorder()

    async def drive():
        svc = PlanService(recorder=rec)
        await svc.start()

        async def boom():
            raise RuntimeError("boom")

        monkeypatch.setattr(svc._queue, "get", boom)
        with pytest.warns(UserWarning, match="dispatcher died"):
            for _ in range(10):
                await asyncio.sleep(0)  # let the crash + callback land
        with pytest.raises(PlanServiceClosed):
            await svc.submit(make_tenant(17, 8, 0))
        await svc.stop()  # cleanup must run despite _closed being set
        assert svc._executor is None and svc._task is None

    _run(drive())
    assert rec.counters.get("fleet.dispatcher_crashes", 0) == 1


def test_solve_fleet_record_false_emits_nothing():
    """record=False silences every counter/histogram the fleet path
    owns — the micro-timing contract solve_dense_converged documents."""
    t = make_tenant(18, 8, seed=97)
    rec = Recorder()
    with use_recorder(rec):
        r1 = solve_fleet([t], record=False)[0]
        t2, _ = delta_tenant(t, r1)
        solve_fleet([t2], record=False)
        solve_fleet([TenantProblem(  # carry-miss path (shape mismatch)
            key="m", prev=t.prev,
            partition_weights=t.partition_weights,
            node_weights=t.node_weights, valid_node=t.valid_node,
            stickiness=t.stickiness, gids=t.gids,
            gid_valid=t.gid_valid, constraints=t.constraints,
            rules=t.rules, carry=_toy_carry(p=18, s=2, n=5),
            dirty=np.zeros(18, bool))], record=False)
    assert rec.counters == {}
    assert rec._hist_stats == {}


def test_fleet_results_are_not_batch_tensor_views(fleet_round1):
    """Results copy out of the [B, ...] batch tensors: a per-tenant
    view would pin the whole batch in memory while the carry cache's
    byte accounting sees only the slice."""
    tenants, results = fleet_round1
    for r in results:
        assert r.assign.base is None
        assert np.asarray(r.carry.used).base is None


def test_service_invalid_tenant_fails_alone_not_the_batch():
    """Per-request validation runs before batching: a tenant whose
    slot depth cannot satisfy its constraints fails its own future,
    while the co-batched valid tenant still solves."""
    good = make_tenant(17, 8, seed=98, key="good2")
    t = make_tenant(8, 4, 0)
    bad = TenantProblem(
        key="bad2", prev=t.prev, partition_weights=t.partition_weights,
        node_weights=t.node_weights, valid_node=t.valid_node,
        stickiness=t.stickiness, gids=t.gids, gid_valid=t.gid_valid,
        constraints=(2, 1), rules=t.rules)  # R=1 < max constraint 2

    async def drive():
        svc = PlanService(admission_window_s=0.05)
        await svc.start()
        res = await asyncio.gather(svc.submit(good), svc.submit(bad),
                                   return_exceptions=True)
        await svc.stop()
        return res

    good_res, bad_res = _run(drive())
    assert isinstance(bad_res, ValueError)
    assert "slot depth" in str(bad_res)
    assert np.array_equal(good_res.assign, solve_sequential(good)[0])


def test_carry_cache_max_entries_drops_churned_keys():
    cache = CarryCache(max_entries=3)
    cur = np.zeros((4, 2, 1), np.int32)
    for i in range(10):
        cache.consume(f"k{i}", cur)  # consume-only churn creates entries
    assert len(cache.keys()) == 3
    # The most recent keys survive (LRU drop of the oldest).
    assert set(cache.keys()) == {"k7", "k8", "k9"}
    cache.store("k9", _toy_carry(), cur)
    assert cache.peek("k9").carry is not None


def test_carry_cache_incremental_bytes_track_ground_truth():
    """nbytes() is maintained incrementally (O(1) per store); it must
    equal the O(entries) recount after every lifecycle mutation."""
    cache = CarryCache(max_bytes=None, max_entries=4)
    cur = np.zeros((4, 2, 1), np.int32)

    def check(step):
        assert cache.nbytes() == cache._recount(), step

    for i in range(6):  # entry churn through the max_entries bound
        cache.store(f"k{i}", _toy_carry(), cur)
        check(f"store k{i}")
    cache.consume("k5", cur)
    check("consume")
    cache.store_pending("k5", _toy_carry(n=4))
    check("store_pending")
    cache.pad_nodes("k5", 7)
    check("pad_nodes")
    cache.promote("k5", cur)
    check("promote")
    cache.invalidate("k4")
    check("invalidate")
    cache.drop("k3")
    check("drop")
    cache.entry("k5", 9)  # shape reset replaces the entry
    check("entry reset")
    small = CarryCache(max_bytes=1)  # every store immediately evicts
    small.store("a", _toy_carry(), cur)
    assert small.nbytes() == small._recount() == 0


def test_submit_blocked_on_full_queue_fails_after_crash(monkeypatch):
    """A submit() suspended on a full queue when the dispatcher dies
    must resolve into PlanServiceClosed, not hang: the post-put closed
    check drains its own re-enqueued request."""
    t = make_tenant(17, 8, 0)

    async def drive():
        svc = PlanService(max_pending=1)
        await svc.start()
        gate = asyncio.Event()

        async def parked_get():
            await gate.wait()
            raise RuntimeError("parked dispatcher released")

        monkeypatch.setattr(svc._queue, "get", parked_get)
        t1 = asyncio.ensure_future(svc.submit(t))
        await asyncio.sleep(0)  # t1 enqueued; queue now full
        t2 = asyncio.ensure_future(svc.submit(t))
        await asyncio.sleep(0)  # t2 suspended inside queue.put
        # Simulate the dispatcher-crash callback's effect.
        svc._closed = True
        svc._drain_pending()
        for _ in range(5):
            await asyncio.sleep(0)
        with pytest.raises(PlanServiceClosed):
            await t1
        with pytest.raises(PlanServiceClosed):
            await t2
        task = svc._task
        task.cancel()
        await asyncio.gather(task, return_exceptions=True)

    _run(drive())


def test_service_routes_solve_metrics_to_its_recorder():
    """A service built with its own Recorder gets ALL fleet/solve
    metrics on that recorder — including the ones emitted from the
    executor thread — and none leak to the process-global one."""
    from blance_tpu.obs import get_recorder

    t = make_tenant(18, 8, seed=99, key="routed")
    rec = Recorder()

    async def drive():
        svc = PlanService(admission_window_s=0.0, recorder=rec)
        await svc.start()
        r = await svc.submit(t)
        await svc.stop()
        return r

    global_before = dict(get_recorder().counters)
    _run(drive())  # NOT under use_recorder: the param must do the work
    assert rec.counters.get("fleet.batches", 0) >= 1
    assert rec.counters.get("plan.solve.calls", 0) >= 1
    assert "fleet.batch_tenants" in rec._hist_stats
    global_after = get_recorder().counters
    for name in ("fleet.batches", "fleet.requests"):
        assert global_after.get(name, 0) == global_before.get(name, 0)


def test_service_emissions_all_declared():
    """Everything the fleet tier emits is a declared registry metric
    (the PR-6 drift guard, extended over the new group)."""
    from blance_tpu.obs.expo import default_registry

    tenants = [make_tenant(17 + (i % 4), 8, seed=20 + i, key=f"reg{i}")
               for i in range(6)]
    rec = Recorder()

    async def drive():
        svc = PlanService(admission_window_s=0.01, recorder=rec)
        await svc.start()
        r1 = await asyncio.gather(*[svc.submit(t) for t in tenants])
        await svc.stop()
        return r1

    with use_recorder(rec):
        _run(drive())
    assert default_registry().undeclared(rec) == []
