"""Native marshalling layer (native/marshal.c): parity vs the pure-Python
encode/decode paths on randomized problems, including the awkward cases —
unmodeled passthrough states, unknown node names, removed nodes, empty
partitions."""

import numpy as np
import pytest

import blance_tpu.core.encode as enc
import blance_tpu.core.marshal as marshal
from blance_tpu.core.types import Partition, PartitionModelState, PlanOptions

pytestmark = pytest.mark.skipif(
    not marshal.available(), reason="native marshal unavailable")


def _random_problem(seed, P=200, N=16):
    rng = np.random.default_rng(seed)
    nodes = [f"n{i}" for i in range(N)]
    model = {
        "primary": PartitionModelState(0, 2),
        "replica": PartitionModelState(1, 1),
    }
    prev = {}
    for i in range(P):
        name = str(i)
        nbs = {}
        if rng.random() < 0.9:
            k = int(rng.integers(1, 4))
            nbs["primary"] = [nodes[j] for j in rng.choice(N, k, replace=False)]
        if rng.random() < 0.7:
            nbs["replica"] = [nodes[int(rng.integers(0, N))]]
        if rng.random() < 0.1:
            nbs["unmodeled"] = [nodes[0], "ghost-node", nodes[1]]
        if rng.random() < 0.05:
            nbs["primary"] = ["ghost-node"]  # unknown name -> -1 / skipped
        prev[name] = Partition(name, nbs)
    return prev, nodes, model


def _with_native(flag):
    """Flip the loader so the same call takes the native or Python path."""
    marshal._MOD = None
    marshal._FAILED = not flag
    if flag:
        assert marshal.available()


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_encode_parity(seed):
    prev, nodes, model = _random_problem(seed)
    opts = PlanOptions()
    removed = [nodes[1]]
    try:
        _with_native(True)
        a = enc.encode_problem(prev, prev, nodes, removed, model, opts)
        _with_native(False)
        b = enc.encode_problem(prev, prev, nodes, removed, model, opts)
    finally:
        _with_native(True)
    assert a.partitions == b.partitions
    assert a.prev.shape == b.prev.shape
    assert (a.prev == b.prev).all()
    assert (a.valid_node == b.valid_node).all()


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_decode_parity(seed):
    prev, nodes, model = _random_problem(seed)
    opts = PlanOptions()
    removed = [nodes[2]]
    problem = enc.encode_problem(prev, prev, nodes, removed, model, opts)
    # Decode the previous assignment itself (plus some -1 holes).
    assign = problem.prev.copy()
    assign[::7, 0, -1] = -1
    try:
        _with_native(True)
        map_n, warn_n = enc.decode_assignment(problem, assign, prev, removed)
        _with_native(False)
        map_p, warn_p = enc.decode_assignment(problem, assign, prev, removed)
    finally:
        _with_native(True)
    assert warn_n == warn_p
    assert set(map_n) == set(map_p)
    for k in map_p:
        assert map_n[k].name == map_p[k].name
        assert map_n[k].nodes_by_state == map_p[k].nodes_by_state


def test_empty_problem():
    _with_native(True)
    model = {"primary": PartitionModelState(0, 1)}
    problem = enc.encode_problem({}, {}, [], None, model, PlanOptions())
    assert problem.P == 0
    m, w = enc.decode_assignment(
        problem, np.full((0, 1, 1), -1, np.int32), {}, None)
    assert m == {} and w == {}


def test_structural_surprise_falls_back():
    """Tuple node lists / odd containers take the pure-Python path instead
    of crashing (marshal.c is stricter than the fallback by design)."""
    _with_native(True)
    model = {"primary": PartitionModelState(0, 1)}
    prev = {"p": Partition("p", {"primary": ("n0", "n1")})}  # tuple, not list
    problem = enc.encode_problem(prev, prev, ["n0", "n1"], None, model,
                                 PlanOptions())
    assert problem.prev[0, 0, 0] == 0 and problem.prev[0, 0, 1] == 1
    m, w = enc.decode_assignment(problem, problem.prev, prev, None)
    assert m["p"].nodes_by_state["primary"] == ["n0", "n1"]


def test_none_in_prev_map_falls_back():
    """A None value in prev_map raises AttributeError inside marshal.c;
    the Python path tolerates it (`prev_map.get(p) or ...` falls through
    to partitions_to_assign) — the native try block must catch it too."""
    _with_native(True)
    model = {"primary": PartitionModelState(0, 1)}
    parts = {"a": Partition("a", {}), "b": Partition("b", {})}
    prev = {"a": None, "b": Partition("b", {"primary": ["n0"]})}
    problem = enc.encode_problem(prev, parts, ["n0", "n1"], None, model,
                                 PlanOptions())
    assert problem.prev[0, 0, 0] == -1 and problem.prev[1, 0, 0] == 0


def test_fast_ctor_parity_and_post_init_fallback():
    """build_map's __init__-bypassing constructor produces objects
    indistinguishable from normal construction, and a Partition subclass
    with __post_init__ (whose hook skipping __init__ would silence) takes
    the ordinary-call path so the hook still runs."""
    _with_native(True)
    native = marshal.get()
    assert native is not None

    parts = ["a", "b"]
    rows = [[["n0"], ["n1"]]]
    pta = {"a": Partition("a", {}), "b": Partition("b", {})}
    out = native.build_map(Partition, parts, ["primary"], rows, pta,
                           {"primary"}, set())
    normal = Partition("a", {"primary": ["n0"]})
    got = out["a"]
    assert type(got) is Partition
    assert got == normal  # dataclass __eq__ over all fields
    assert got.copy().nodes_by_state == {"primary": ["n0"]}

    import dataclasses

    @dataclasses.dataclass
    class Hooked(Partition):
        def __post_init__(self):
            self.hooked = True

    out = native.build_map(Hooked, parts, ["primary"], rows, pta,
                           {"primary"}, set())
    assert out["b"].hooked  # hook ran => the bypass was NOT taken

    @dataclasses.dataclass
    class Tagged(Partition):
        tags: list = dataclasses.field(default_factory=list)

    out = native.build_map(Tagged, parts, ["primary"], rows, pta,
                           {"primary"}, set())
    assert out["a"].tags == []  # extra field initialized => normal __init__

    class Custom(Partition):
        # Hand-written __init__, NO @dataclass redecoration: inherits
        # __dataclass_fields__ untouched — the gate must still take the
        # ordinary-call path so this normalization runs.
        def __init__(self, name, nodes_by_state):
            super().__init__(name.upper(), nodes_by_state)

    out = native.build_map(Custom, parts, ["primary"], rows, pta,
                           {"primary"}, set())
    assert out["a"].name == "A"  # custom __init__ ran
