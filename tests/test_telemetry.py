"""The live telemetry plane (PR 6): Prometheus exposition + registry,
online SLO accounting, the calibrated move-cost model, and the
determinism of all three under DeterministicLoop virtual time.

Includes the metric-name drift guard: the MetricsRegistry table, the
names actually emitted during a plan→diff→orchestrate pipeline run, and
the docs/OBSERVABILITY.md metric table must stay mutually consistent.
"""

import asyncio
import json
import os
import re

import pytest

from blance_tpu.core.types import Partition, PartitionModelState
from blance_tpu.obs import (
    CostModel,
    MetricsServer,
    Recorder,
    SloTracker,
    default_registry,
    parse_prometheus,
    render_prometheus,
    scrape,
    use_recorder,
)
from blance_tpu.orchestrate.faults import FaultPlan, NodeFaults
from blance_tpu.orchestrate.orchestrator import (
    OrchestratorOptions,
    PartitionMove,
    orchestrate_moves,
)

DOCS_PATH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "docs", "OBSERVABILITY.md")


def _pm(d):
    return {name: Partition(name, {s: list(ns) for s, ns in nbs.items()})
            for name, nbs in d.items()}


_MODEL2 = {"primary": PartitionModelState(priority=0, constraints=1),
           "replica": PartitionModelState(priority=1, constraints=1)}


# ---------------------------------------------------------------------------
# Registry + rendering
# ---------------------------------------------------------------------------


def test_registry_declares_every_progress_counter():
    from blance_tpu.orchestrate.orchestrator import OrchestratorProgress

    reg = default_registry()
    for field in OrchestratorProgress().__dict__:
        if field == "errors":
            continue
        assert reg.declared("orchestrate." + field, "counter"), field


def test_registry_rejects_duplicates_and_collisions():
    from blance_tpu.obs import Metric, MetricsRegistry

    with pytest.raises(ValueError, match="duplicate"):
        MetricsRegistry([Metric("a.b", "counter", "x"),
                         Metric("a.b", "counter", "y")])
    with pytest.raises(ValueError, match="already taken"):
        # Same prom name from two internal spellings.
        MetricsRegistry([Metric("a.b", "gauge", "x"),
                         Metric("a_b", "gauge", "y")])
    with pytest.raises(ValueError, match="unknown kind"):
        Metric("a.b", "summary", "x")


def test_render_includes_every_declared_metric_and_parses():
    rec = Recorder()
    text = render_prometheus(rec)
    samples, types = parse_prometheus(text)
    reg = default_registry()
    for m in reg.metrics():
        pname = reg.prom_name(m)
        assert types[pname] == m.kind, pname
    # Empty recorder: every counter/gauge sample present and zero.
    assert samples["blance_plan_solve_calls_total"] == 0
    assert samples["blance_slo_partition_availability"] == 0
    assert samples["blance_orchestrate_move_latency_s_count"] == 0
    assert samples['blance_orchestrate_move_latency_s_bucket{le="+Inf"}'] == 0


def test_render_histogram_buckets_cumulative_and_consistent():
    rec = Recorder()
    for v in (0.0004, 0.004, 0.004, 4.0):
        rec.observe("orchestrate.move_latency_s", v)
    samples, _ = parse_prometheus(render_prometheus(rec))
    pre = "blance_orchestrate_move_latency_s"
    assert samples[f'{pre}_bucket{{le="0.0005"}}'] == 1
    assert samples[f'{pre}_bucket{{le="0.005"}}'] == 3
    assert samples[f'{pre}_bucket{{le="+Inf"}}'] == 4
    assert samples[f"{pre}_count"] == 4
    assert samples[f"{pre}_sum"] == pytest.approx(4.0084)
    # Buckets are monotone non-decreasing in le order.
    buckets = [(float(m.group(1)), v) for k, v in samples.items()
               if (m := re.match(rf'{pre}_bucket{{le="([0-9.e+-]+)"}}', k))]
    buckets.sort()
    assert all(a[1] <= b[1] for a, b in zip(buckets, buckets[1:]))


def test_render_counter_and_labeled_gauge_samples():
    rec = Recorder()
    rec.count("orchestrate.retries", 7)
    rec.set_gauge("slo.partition_availability", 0.25)
    rec.set_gauge('slo.quarantine_exposure_s{node="n1"}', 1.5)
    rec.set_gauge('slo.quarantine_exposure_s{node="n2"}', 2.5)
    samples, _ = parse_prometheus(render_prometheus(rec))
    assert samples["blance_orchestrate_retries_total"] == 7
    assert samples["blance_slo_partition_availability"] == 0.25
    assert samples['blance_slo_quarantine_exposure_s{node="n1"}'] == 1.5
    assert samples['blance_slo_quarantine_exposure_s{node="n2"}'] == 2.5


def test_parse_prometheus_rejects_garbage():
    with pytest.raises(ValueError):
        parse_prometheus("not a metric line at all\n")
    with pytest.raises(ValueError):
        parse_prometheus("name notanumber\n")


# ---------------------------------------------------------------------------
# The asyncio endpoint (real loop: DeterministicLoop has no sockets)
# ---------------------------------------------------------------------------


def test_metrics_server_scrape_and_cache():
    rec = Recorder()
    rec.count("plan.solve.calls", 2)

    async def main():
        server = MetricsServer(recorder=rec, min_interval_s=0.0)
        await server.start()
        try:
            text = await scrape("127.0.0.1", server.port)
            s1, _ = parse_prometheus(text)
            assert s1["blance_plan_solve_calls_total"] == 2
            rec.count("plan.solve.calls", 3)
            s2, _ = parse_prometheus(
                await scrape("127.0.0.1", server.port))
            assert s2["blance_plan_solve_calls_total"] == 5
            with pytest.raises(RuntimeError, match="404"):
                await scrape("127.0.0.1", server.port, path="/nope")
        finally:
            await server.stop()

    asyncio.run(main())


def test_metrics_server_snapshot_throttling():
    """Scrapes inside min_interval_s serve the cached snapshot; the
    next snapshot after the interval sees the new values."""
    t = [0.0]
    rec = Recorder(clock=lambda: t[0])
    rec.count("plan.solve.calls", 1)

    async def main():
        server = MetricsServer(recorder=rec, min_interval_s=10.0)
        await server.start()
        try:
            s1, _ = parse_prometheus(
                await scrape("127.0.0.1", server.port))
            rec.count("plan.solve.calls", 1)
            s2, _ = parse_prometheus(
                await scrape("127.0.0.1", server.port))
            assert s2["blance_plan_solve_calls_total"] == \
                s1["blance_plan_solve_calls_total"] == 1  # cached
            t[0] = 11.0
            s3, _ = parse_prometheus(
                await scrape("127.0.0.1", server.port))
            assert s3["blance_plan_solve_calls_total"] == 2
        finally:
            await server.stop()

    asyncio.run(main())


def test_metrics_server_collectors_run_per_snapshot():
    rec = Recorder()
    calls = []

    def collector():
        calls.append(1)
        rec.set_gauge("slo.churn_ratio", float(len(calls)))

    async def main():
        server = MetricsServer(recorder=rec, min_interval_s=0.0,
                               collectors=(collector,))
        await server.start()
        try:
            s1, _ = parse_prometheus(
                await scrape("127.0.0.1", server.port))
            s2, _ = parse_prometheus(
                await scrape("127.0.0.1", server.port))
            assert s1["blance_slo_churn_ratio"] == 1
            assert s2["blance_slo_churn_ratio"] == 2
        finally:
            await server.stop()

    asyncio.run(main())


# ---------------------------------------------------------------------------
# SLO accounting
# ---------------------------------------------------------------------------


def _mv(partition, node, state, op="add"):
    return PartitionMove(partition=partition, node=node, state=state, op=op)


def test_slo_availability_incremental_math():
    t = [0.0]
    beg = _pm({"p0": {"primary": ["a"], "replica": ["b"]},
               "p1": {"primary": ["a"]},
               "p2": {"primary": []}})
    slo = SloTracker(beg, primary_states=("primary",), clock=lambda: t[0],
                     recorder=Recorder())
    assert slo.availability() == pytest.approx(2 / 3)
    # p2 gains a primary -> available.
    slo.on_batch("b", [_mv("p2", "b", "primary")], ok=True, now=1.0)
    assert slo.availability() == pytest.approx(1.0)
    assert slo.moves_executed == 1
    # p1's only primary demoted away -> unavailable.
    slo.on_batch("a", [_mv("p1", "a", "replica", op="demote")],
                 ok=True, now=2.0)
    assert slo.availability() == pytest.approx(2 / 3)
    # A removal ("" state, del op) on p0's primary; replica remains ->
    # unavailable (no serving primary).
    slo.on_batch("a", [_mv("p0", "a", "", op="del")], ok=True, now=3.0)
    assert slo.availability() == pytest.approx(1 / 3)
    # Failed batches change nothing but the failure count.
    before = slo.availability()
    slo.on_batch("b", [_mv("p1", "b", "primary")], ok=False, now=4.0)
    assert slo.availability() == before
    assert slo.moves_failed == 1 and slo.moves_executed == 3


def test_slo_churn_and_lag_formulas():
    t = [0.0]
    beg = _pm({"p0": {"primary": ["a"]}})
    slo = SloTracker(beg, clock=lambda: t[0], recorder=Recorder())
    slo.set_min_moves(4)
    slo.set_min_moves(99)  # first call wins (the PRIMARY plan)
    assert slo.churn_ratio() == 0.0
    slo.on_batch("b", [_mv("p0", "b", "primary"),
                       _mv("p0", "a", "", op="del")], ok=True, now=2.0)
    assert slo.churn_ratio() == pytest.approx(0.5)
    t[0] = 7.5
    assert slo.convergence_lag_s() == pytest.approx(5.5)
    summary = slo.summary()
    assert summary.moves_executed == 2 and summary.min_moves == 4
    assert summary.convergence_lag_s == pytest.approx(5.5)


def test_slo_strip_nodes_drops_availability():
    beg = _pm({"p0": {"primary": ["dead"]},
               "p1": {"primary": ["live"], "replica": ["dead"]}})
    slo = SloTracker(beg, clock=lambda: 0.0, recorder=Recorder())
    assert slo.availability() == 1.0
    slo.strip_nodes({"dead"})
    assert slo.availability() == pytest.approx(0.5)
    assert slo.summary().available_partitions == 1


def test_slo_publishes_gauges_to_recorder():
    rec = Recorder()
    beg = _pm({"p0": {"primary": ["a"]}})
    slo = SloTracker(beg, clock=lambda: 0.0, recorder=rec)
    slo.set_min_moves(1)
    slo.on_batch("b", [_mv("p0", "b", "primary")], ok=True, now=0.0)
    assert rec.gauges["slo.partition_availability"] == 1.0
    assert rec.gauges["slo.churn_ratio"] == 1.0
    assert rec.gauges["slo.moves_executed"] == 1.0


def test_rebalance_result_carries_slo_summary():
    """rebalance_async wires a tracker automatically; the clean-run
    summary shows full availability and churn == 1."""
    from blance_tpu.rebalance import rebalance

    beg = _pm({f"p{i}": {"primary": ["a"]} for i in range(4)})
    model = {"primary": PartitionModelState(priority=0, constraints=1)}

    async def assign(stop_ch, node, partitions, states, ops):
        await asyncio.sleep(0)

    rec = Recorder()
    with use_recorder(rec):
        res = rebalance(model, beg, ["a", "b"], ["a"], [], assign,
                        backend="greedy")
    assert res.slo is not None
    assert res.slo.availability == 1.0
    assert res.slo.churn_ratio == pytest.approx(1.0)
    assert res.slo.moves_executed == res.slo.min_moves > 0
    assert rec.gauges["slo.partition_availability"] == 1.0


def test_health_tracker_quarantine_exposure_accumulates():
    from blance_tpu.orchestrate.health import HealthTracker

    t = [0.0]
    h = HealthTracker(threshold=1, probe_after_s=5.0, clock=lambda: t[0])
    h.record_failure("n1")  # trips at t=0
    t[0] = 3.0
    assert h.exposure_s("n1") == pytest.approx(3.0)
    t[0] = 6.0
    assert h.admit("n1") == "probe"  # half-open still counts as exposed
    h.record_failure("n1")  # re-trip at t=6: closes 6s into the total
    t[0] = 8.0
    assert h.exposure_s("n1") == pytest.approx(8.0)
    h.record_success("n1")  # heal at t=8
    t[0] = 100.0
    assert h.exposure_s("n1") == pytest.approx(8.0)  # closed for good
    assert h.exposures() == {"n1": pytest.approx(8.0)}
    assert h.exposure_s("never") == 0.0


# ---------------------------------------------------------------------------
# Cost model
# ---------------------------------------------------------------------------


def _exec_span(rec, node, ops, seconds):
    """Manufacture one orchestrate.move.exec span of a given duration."""
    t0 = rec.now()
    rec.record_span("orchestrate.move.exec", t0, t0 + seconds,
                    task=f"mover:{node}", node=node, ops=",".join(ops))


def test_costmodel_ewma_update_and_prediction_order():
    rec = Recorder()
    cm = CostModel(alpha=0.5, default_s=0.123, recorder=rec)
    rec.add_sink(cm)
    assert cm.predict("n1", "add") == 0.123  # cold start
    _exec_span(rec, "n1", ["add"], 0.1)
    assert cm.predict("n1", "add") == pytest.approx(0.1)
    _exec_span(rec, "n1", ["add"], 0.2)
    # ewma = 0.5*0.2 + 0.5*0.1
    assert cm.predict("n1", "add") == pytest.approx(0.15)
    # Unseen node falls back to the op aggregate, unseen op to global.
    assert cm.predict("n9", "add") == pytest.approx(cm.predict("n9", "add"))
    assert cm.predict("n9", "promote") > 0
    assert rec.counters["costmodel.updates"] == 2
    # The second update scored the first prediction's error.
    cal = cm.calibration()
    assert cal["scored"] == 1
    assert cal["p50_rel_err"] == pytest.approx(abs(0.1 - 0.2) / 0.2)
    assert rec.histogram_buckets("costmodel.rel_err")[2] == 1


def test_costmodel_batch_amortizes_across_ops():
    rec = Recorder()
    cm = CostModel(recorder=rec)
    rec.add_sink(cm)
    _exec_span(rec, "n1", ["add", "del"], 0.2)  # 0.1 per move
    assert cm.predict("n1", "add") == pytest.approx(0.1)
    assert cm.predict("n1", "del") == pytest.approx(0.1)
    assert cm.observations() == 2


def test_costmodel_persistence_roundtrip(tmp_path):
    rec = Recorder()
    cm = CostModel(alpha=0.4, default_s=0.07, recorder=rec)
    rec.add_sink(cm)
    for node, op, s in (("n1", "add", 0.05), ("n1", "add", 0.09),
                        ("n2", "del", 0.01), ("n3", "promote", 0.3)):
        _exec_span(rec, node, [op], s)
    path = str(tmp_path / "costs.json")
    cm.save(path)
    loaded = CostModel.load(path)
    for node, op in [("n1", "add"), ("n2", "del"), ("n3", "promote"),
                     ("n9", "add"), ("n9", "never")]:
        assert loaded.predict(node, op) == cm.predict(node, op), (node, op)
    # The file is the documented format.
    data = json.load(open(path))
    assert data["version"] == 1 and data["alpha"] == 0.4
    assert {e["node"] for e in data["estimates"]} == {"n1", "n2", "n3"}
    # A wrong version is a hard error.
    data["version"] = 99
    with pytest.raises(ValueError, match="version"):
        CostModel.from_json(data)


def test_costmodel_predict_move_duck_typing():
    cm = CostModel(recorder=Recorder())
    mv = _mv("p0", "n1", "primary")
    assert cm.predict_move(mv) == cm.predict("n1", "add")


def test_costmodel_learns_from_live_orchestration():
    """End to end: attach the sink, orchestrate with per-node latency,
    and the learned estimates reflect the structure."""
    rec = Recorder()
    cm = CostModel(alpha=0.5, recorder=rec)
    rec.add_sink(cm)
    beg = _pm({f"p{i}": {"primary": ["a"]} for i in range(6)})
    end = _pm({f"p{i}": {"primary": ["b" if i % 2 else "c"]}
               for i in range(6)})

    async def assign(stop_ch, node, partitions, states, ops):
        await asyncio.sleep(0.02 if node == "b" else 0.001)

    async def run():
        with use_recorder(rec):
            o = orchestrate_moves(
                {"primary": PartitionModelState(priority=0, constraints=1)},
                OrchestratorOptions(), ["a", "b", "c"], beg, end, assign)
            async for _ in o.progress_ch():
                pass
            o.stop()

    asyncio.run(run())
    assert cm.observations() > 0
    # The slow node costs measurably more than the fast one.
    assert cm.predict("b", "add") > cm.predict("c", "add")


# ---------------------------------------------------------------------------
# Metric-name drift guard (registry <-> emissions <-> docs)
# ---------------------------------------------------------------------------


def _doc_metric_rows():
    """Parse the docs/OBSERVABILITY.md 'Metric reference' table into
    (name_or_wildcard, kind) rows."""
    text = open(DOCS_PATH).read()
    section = text.split("### Metric reference", 1)[1]
    rows = []
    for line in section.splitlines():
        m = re.match(r"\|\s*`([a-z0-9_.*]+)`\s*\|\s*(\w+)\s*\|", line)
        if m:
            rows.append((m.group(1), m.group(2)))
        elif rows and line.strip() and not line.startswith("|"):
            break  # table ended
    return rows


def _row_matches(row_name, metric_name):
    if row_name.endswith("*"):
        return metric_name.startswith(row_name[:-1])
    return row_name == metric_name


def test_drift_guard_docs_table_matches_registry():
    """No stale doc rows; no undocumented registry metrics."""
    reg = default_registry()
    rows = _doc_metric_rows()
    assert rows, "docs metric table not found"
    names_by_kind = {(m.name, m.kind) for m in reg.metrics()}
    for row_name, row_kind in rows:
        hits = [(n, k) for (n, k) in names_by_kind
                if k == row_kind and _row_matches(row_name, n)]
        assert hits, f"stale docs row: {row_name} ({row_kind}) matches " \
                     f"no registry metric"
    for name, kind in names_by_kind:
        documented = any(k == kind and _row_matches(rn, name)
                         for rn, k in rows)
        assert documented, f"registry metric {name} ({kind}) missing " \
                           f"from the docs table"


def test_drift_guard_pipeline_emissions_all_declared():
    """A full plan→diff→orchestrate(+chaos rebalance, SLO, cost model)
    run emits ONLY declared metric names — no undeclared emissions."""
    from blance_tpu.moves.batch import calc_all_moves
    from blance_tpu.plan.api import plan_next_map
    from blance_tpu.rebalance import rebalance

    rec = Recorder()
    cm = CostModel(recorder=rec)
    rec.add_sink(cm)
    nodes = [f"n{i}" for i in range(6)]
    beg = _pm({str(i): {"primary": [nodes[i % 5]],
                        "replica": [nodes[(i + 1) % 5]]}
               for i in range(24)})
    with use_recorder(rec):
        # plan: both the tensor path (plan.* spans/counters) and greedy
        # (plan.greedy.*), then the batched device diff (moves.*).
        end, _ = plan_next_map(beg, beg, nodes, [nodes[0]], [], _MODEL2,
                               None, backend="tpu")
        plan_next_map(beg, beg, nodes, [], [], _MODEL2, None,
                      backend="greedy")
        calc_all_moves(beg, end, _MODEL2)

        plan = FaultPlan(seed=3, nodes={
            nodes[5]: NodeFaults(dead=True),
            nodes[1]: NodeFaults(fail_rate=0.3)})

        async def assign(stop_ch, node, partitions, states, ops):
            await asyncio.sleep(0)

        rebalance(_MODEL2, beg, nodes, [nodes[2]], [nodes[5]],
                  plan.wrap(assign),
                  orchestrator_options=OrchestratorOptions(
                      move_timeout_s=0.25, max_retries=3,
                      backoff_base_s=0.001, quarantine_after=2,
                      probe_after_s=60.0),
                  max_recovery_rounds=2, backend="greedy")

    reg = default_registry()
    assert reg.undeclared(rec) == []
    # And the run actually exercised the fault + slo + costmodel groups,
    # so the check above had teeth.
    assert rec.counters.get("orchestrate.move_failures", 0) > 0
    assert rec.counters.get("costmodel.updates", 0) > 0
    assert "slo.partition_availability" in rec.gauges


# ---------------------------------------------------------------------------
# Virtual-time determinism (DeterministicLoop + injectable clock)
# ---------------------------------------------------------------------------


def _vt_chaos_scenario():
    """A chaos rebalance whose ENTIRE telemetry runs on virtual time;
    returns (exposition text, slo summary dict) for bit-comparison."""

    async def scenario():
        import dataclasses

        from blance_tpu.rebalance import rebalance_async

        loop = asyncio.get_running_loop()
        rec = Recorder(clock=loop.time)
        nodes = [f"n{i}" for i in range(5)]
        beg = _pm({f"{i:02d}": {"primary": [nodes[i % 3]],
                                "replica": [nodes[(i + 1) % 3]]}
                   for i in range(12)})
        plan = FaultPlan(seed=21, nodes={
            nodes[4]: NodeFaults(dead=True),
            nodes[0]: NodeFaults(fail_rate=0.3)})

        async def assign(stop_ch, node, partitions, states, ops):
            await asyncio.sleep(0.002)  # virtual-time data plane

        with use_recorder(rec):
            slo = SloTracker(beg, primary_states=("primary",),
                             clock=rec.now, recorder=rec)
            result = await rebalance_async(
                _MODEL2, beg, nodes, [nodes[1]], [nodes[4]],
                plan.wrap(assign),
                orchestrator_options=OrchestratorOptions(
                    move_timeout_s=0.25, max_retries=3,
                    backoff_base_s=0.002, quarantine_after=2,
                    probe_after_s=60.0),
                max_recovery_rounds=2, backend="greedy", slo=slo)
            text = render_prometheus(rec)
        assert result.slo is not None
        return text, dataclasses.asdict(result.slo)

    return scenario()


@pytest.mark.parametrize("seed", [11, 23])
def test_slo_gauges_bit_identical_across_seeded_runs(seed):
    """The acceptance contract: under DeterministicLoop, two runs of
    the same seed reproduce the SLO gauges — and the ENTIRE rendered
    exposition text, histograms included — bit-identically."""
    from blance_tpu.testing.sched import RandomWalkPolicy, run_controlled

    out_a = run_controlled(_vt_chaos_scenario, RandomWalkPolicy(seed))
    out_b = run_controlled(_vt_chaos_scenario, RandomWalkPolicy(seed))
    assert out_a.ok, out_a.describe()
    assert out_b.ok, out_b.describe()
    text_a, slo_a = out_a.result
    text_b, slo_b = out_b.result
    assert slo_a == slo_b
    assert text_a == text_b
    # The gauges are meaningful, not vacuously equal.
    samples, _ = parse_prometheus(text_a)
    assert 0.0 <= samples["blance_slo_partition_availability"] <= 1.0
    assert samples["blance_slo_moves_executed"] > 0
    assert samples["blance_orchestrate_move_latency_s_count"] > 0
    # The dead node's quarantine exposure is real VIRTUAL dwell, not a
    # cross-clock subtraction clamped to zero (the breaker shares the
    # recorder's injected clock).
    assert any(v > 0 for v in slo_a["quarantine_exposure_s"].values()), \
        slo_a["quarantine_exposure_s"]


def test_vt_exposition_snapshot_deterministic_mid_run():
    """Exposition snapshots taken DURING the run (not just at the end)
    are schedule-deterministic too: same seed, same mid-run text."""
    from blance_tpu.testing.sched import RandomWalkPolicy, run_controlled

    def factory():
        async def scenario():
            loop = asyncio.get_running_loop()
            rec = Recorder(clock=loop.time)
            beg = _pm({f"p{i}": {"primary": ["a"]} for i in range(4)})
            end = _pm({f"p{i}": {"primary": ["b"]} for i in range(4)})
            snapshots = []

            async def assign(stop_ch, node, partitions, states, ops):
                await asyncio.sleep(0.001)

            with use_recorder(rec):
                slo = SloTracker(beg, clock=rec.now, recorder=rec)
                server = MetricsServer(recorder=rec, min_interval_s=0.0,
                                       collectors=(slo.publish,))
                o = orchestrate_moves(
                    {"primary": PartitionModelState(priority=0,
                                                    constraints=1)},
                    OrchestratorOptions(), ["a", "b"], beg, end, assign,
                    move_observers=(slo,))
                o.visit_next_moves(lambda m: slo.set_min_moves(
                    sum(len(nm.moves) for nm in m.values())))
                async for _ in o.progress_ch():
                    snapshots.append(server.render())
                o.stop()
            return snapshots

        return scenario()

    a = run_controlled(factory, RandomWalkPolicy(37))
    b = run_controlled(factory, RandomWalkPolicy(37))
    assert a.ok and b.ok
    assert a.result == b.result
    assert len(a.result) > 3
