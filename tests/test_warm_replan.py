"""Incremental (warm-started) replanning: PlannerSession carry lifecycle.

The warm path's contract (docs/DESIGN.md "Incremental replanning"):
a delta replan seeded from the previous solve's carry must produce a map
BIT-IDENTICAL to a cold solve of the same problem, while executing
measurably fewer solver sweeps (the plan.solve.sweeps counter).  These
tests pin both halves property-style across delta shapes — node removal,
addition, combined, weight changes, rack rules — plus the carry
lifecycle rules (promotion on apply, invalidation on load_map/weights,
single-use consumption).
"""

import numpy as np
import pytest

from blance_tpu import HierarchyRule, PlanOptions, model
from blance_tpu.obs import Recorder, use_recorder
from blance_tpu.plan.session import PlannerSession
from blance_tpu.plan.tensor import check_assignment

MODEL = model(primary=(0, 1), replica=(1, 1))
NODES = [f"n{i}" for i in range(8)]
PARTS = [str(i) for i in range(64)]


def rack_opts(nodes, racks_of=4):
    hier = {n: f"r{i // racks_of}" for i, n in enumerate(nodes)}
    hier.update({f"r{i}": "z0"
                 for i in range((len(nodes) + racks_of - 1) // racks_of)})
    return PlanOptions(node_hierarchy=hier,
                       hierarchy_rules={"replica": [HierarchyRule(2, 1)]})


def warmed_session(opts=None, mesh=None, nodes=NODES, parts=PARTS):
    """A session whose next replan is warm (solve + apply promoted the
    carry)."""
    s = PlannerSession(MODEL, list(nodes), list(parts), opts=opts,
                       mesh=mesh)
    s.replan()
    s.apply()
    return s


def cold_twin(session, opts=None, mesh=None):
    """A fresh session holding the same map and removed-node set as
    ``session`` but NO carry — its replan is the cold reference."""
    m, _ = session.to_map()
    s = PlannerSession(MODEL, session.nodes, list(PARTS), opts=opts,
                       mesh=mesh)
    s.load_map(m)
    if session.removed_nodes:
        s.remove_nodes(session.removed_nodes)
    return s


def apply_delta(s, delta):
    if "remove" in delta:
        s.remove_nodes(delta["remove"])
    if "add" in delta:
        s.add_nodes(delta["add"])


DELTAS = [
    pytest.param({"remove": ["n3"]}, id="remove-1"),
    pytest.param({"remove": ["n1", "n6"]}, id="remove-2"),
    pytest.param({"add": ["x0"]}, id="add-1"),
    pytest.param({"remove": ["n2"], "add": ["x0", "x1"]}, id="mixed"),
]


@pytest.mark.parametrize("delta", DELTAS)
@pytest.mark.parametrize("opts_fn", [lambda n: None, rack_opts],
                         ids=["flat", "rack-rules"])
def test_warm_replan_identical_to_cold(delta, opts_fn):
    """Property: warm replan == cold solve of the same problem, for
    every delta shape, with and without hierarchy rules.  (Deltas the
    warm path declines — e.g. adds, where capacity shrinks under held
    load — must fall back to the cold solve and still match.)"""
    rec = Recorder()
    with use_recorder(rec):
        opts = opts_fn(NODES)
        s = warmed_session(opts=opts)
        apply_delta(s, delta)
        warm = s.replan().copy()

        # Same opts OBJECT: the problems must be identical (added nodes
        # outside the hierarchy stay outside it in both sessions).
        c = PlannerSession(MODEL, s.nodes, list(PARTS), opts=opts)
        m, _ = s.to_map()
        c.load_map(m)
        if s.removed_nodes:
            c.remove_nodes(s.removed_nodes)
        cold = c.replan()
    assert np.array_equal(warm, cold)
    report = check_assignment(s.problem, warm)
    assert not any(report.values()), report


def test_warm_remove_halves_sweeps():
    """The acceptance bar: a 1-node-remove warm replan records >= 2x
    fewer plan.solve.sweeps than the cold solve of the same delta, and
    still matches it bit-for-bit."""
    rec = Recorder()
    with use_recorder(rec):
        s = warmed_session()
        twin = cold_twin(s)
        s.remove_nodes(["n3"])
        twin.remove_nodes(["n3"])

        c0 = rec.counters.get("plan.solve.sweeps", 0)
        warm = s.replan().copy()
        warm_sweeps = rec.counters["plan.solve.sweeps"] - c0
        assert rec.counters.get("plan.solve.carry_hit", 0) == 1

        c1 = rec.counters["plan.solve.sweeps"]
        cold = twin.replan()
        cold_sweeps = rec.counters["plan.solve.sweeps"] - c1

    assert np.array_equal(warm, cold)
    assert warm_sweeps * 2 <= cold_sweeps, (warm_sweeps, cold_sweeps)


def test_weight_change_invalidates_carry_and_matches_cold():
    """A node-weight change re-prices everything: the carry must drop
    (cold replan), and the result must match a cold session configured
    with the same weights from scratch."""
    rec = Recorder()
    with use_recorder(rec):
        s = warmed_session()
        m0, _ = s.to_map()
        s.set_node_weights({"n0": 3})
        s.remove_nodes(["n5"])
        out = s.replan().copy()
        assert rec.counters.get("plan.solve.carry_hit", 0) == 0

        c = PlannerSession(MODEL, NODES, list(PARTS),
                           opts=PlanOptions(node_weights={"n0": 3}))
        c.load_map(m0)
        c.remove_nodes(["n5"])
        cold = c.replan()
    assert np.array_equal(out, cold)


def test_carry_promoted_only_on_apply():
    """replan() without apply() leaves ``current`` unchanged; the carry
    built by that replan must not activate until apply() adopts the
    proposal.  A second replan without apply still matches the cold
    answer (the consumed carry forces a cold solve)."""
    rec = Recorder()
    with use_recorder(rec):
        s = warmed_session()
        s.remove_nodes(["n2"])
        first = s.replan().copy()
        hits = rec.counters.get("plan.solve.carry_hit", 0)
        second = s.replan().copy()  # no apply in between
        # No NEW carry hit: the carry was consumed by the first replan.
        assert rec.counters.get("plan.solve.carry_hit", 0) == hits
    assert np.array_equal(first, second)


def test_carry_survives_steady_state_loop():
    """Successive delta cycles each warm-start from the previous apply:
    every replan after the first is a carry hit, and the audits stay
    clean throughout."""
    rec = Recorder()
    with use_recorder(rec):
        s = warmed_session()
        for i, victim in enumerate(["n1", "n4", "n6"]):
            s.remove_nodes([victim])
            s.replan()
            s.apply()
            assert rec.counters.get("plan.solve.carry_hit", 0) == i + 1
            report = check_assignment(s.problem, s.current)
            assert not any(report.values()), report
            vid = s.nodes.index(victim)
            assert not (s.current == vid).any()


def test_add_nodes_between_replan_and_apply():
    """Regression: a node added while a proposal is pending must pad the
    PENDING carry too — apply() promotes it into the grown problem, and
    the next replan used to crash on the [S, N_old] vs [N_new] shape
    mismatch inside the capacity precheck."""
    rec = Recorder()
    with use_recorder(rec):
        s = warmed_session()
        s.replan()               # pending proposal + pending carry
        s.add_nodes(["x0"])      # delta lands before apply()
        s.apply()
        out = s.replan()         # must not raise; falls back cleanly
        report = check_assignment(s.problem, out)
        assert not any(report.values()), report


def test_remove_between_replan_and_apply_keeps_dirty():
    """Regression: a removal recorded after replan() but before apply()
    is NOT absorbed by the adopted proposal — its dirty marks (computed
    against the proposal, which may have moved load onto the victim)
    must survive apply(), and the following replan must drain the
    victim and match a cold solve of the same problem."""
    s = warmed_session()
    s.replan()                   # proposal pending
    s.remove_nodes(["n4"])       # delta after the solve
    s.apply()                    # adopts a map that still uses n4
    out = s.replan().copy()
    n4 = s.nodes.index("n4")
    assert not (out == n4).any()

    c = cold_twin(s)
    cold = c.replan()
    assert np.array_equal(out, cold)


def test_load_map_invalidates_carry():
    rec = Recorder()
    with use_recorder(rec):
        s = warmed_session()
        m, _ = s.to_map()
        s.load_map(m)
        s.remove_nodes(["n3"])
        s.replan()
        assert rec.counters.get("plan.solve.carry_hit", 0) == 0
        assert rec.counters.get("plan.solve.carry_miss", 0) >= 1


def test_dirty_fraction_histogram_records():
    rec = Recorder()
    with use_recorder(rec):
        s = warmed_session()
        s.remove_nodes(["n3"])
        s.replan()
    hist = rec.histogram_summary("plan.solve.dirty_fraction")
    assert hist is not None and hist["count"] == 1
    # A 1-of-8-node removal dirties a minority of partitions.
    assert 0.0 < hist["max"] < 1.0


def test_warm_replan_on_mesh_matches_cold():
    """The sharded path threads the carry (prices/used replicated,
    assignment partition-sharded): warm mesh replan == cold mesh replan,
    with a recorded carry hit."""
    from blance_tpu.parallel.sharded import make_mesh

    rec = Recorder()
    with use_recorder(rec):
        s = warmed_session(mesh=make_mesh(8))
        twin = cold_twin(s, mesh=make_mesh(8))
        s.remove_nodes(["n3"])
        twin.remove_nodes(["n3"])
        warm = s.replan().copy()
        assert rec.counters.get("plan.solve.carry_hit", 0) == 1
        cold = twin.replan()
    assert np.array_equal(warm, cold)


def test_sweeps_counter_drops_across_full_loop():
    """End-to-end sweep accounting through repeated deltas: the steady
    warm loop spends 1 sweep per replan where the cold baseline spends
    >= 2."""
    rec = Recorder()
    with use_recorder(rec):
        s = warmed_session()
        base = rec.counters.get("plan.solve.sweeps", 0)
        for victim in ["n0", "n7"]:
            s.remove_nodes([victim])
            s.replan()
            s.apply()
        warm_total = rec.counters["plan.solve.sweeps"] - base
    assert warm_total == 2  # one sweep per delta replan


def test_shape_bucketing_contract_equivalent():
    """PlanOptions.shape_bucketing pads P and N to the next bucket with
    inert rows/columns: the solve must stay deterministic, audit-clean,
    never emit a pad node, and balance as tightly as the unbucketed
    solve.  (Bit-identity with the unbucketed program is explicitly NOT
    the contract: the traced real-P fill denominator compiles to
    different low-bit arithmetic than the unbucketed constant —
    docs/DESIGN.md "Incremental replanning".)"""
    from blance_tpu import Partition, plan_next_map
    from blance_tpu.core.encode import encode_problem

    nodes = [f"n{i}" for i in range(13)]  # deliberately off-bucket
    parts = {str(i): Partition(str(i), {}) for i in range(100)}
    opts_b = PlanOptions(shape_bucketing=True)
    bucketed, warn = plan_next_map(
        parts, parts, nodes, [], [], MODEL, opts_b, backend="tpu")
    assert not warn
    # Determinism: same call, same map.
    again, _ = plan_next_map(
        parts, parts, nodes, [], [], MODEL, opts_b, backend="tpu")
    assert {p: m.nodes_by_state for p, m in bucketed.items()} == \
        {p: m.nodes_by_state for p, m in again.items()}
    # Pad nodes are inert: only real node names appear.
    placed = {n for p in bucketed.values()
              for ns in p.nodes_by_state.values() for n in ns}
    assert placed <= set(nodes)
    # Audit-clean, and balance as tight as the unbucketed solve's.
    prob = encode_problem(parts, parts, nodes, [], MODEL, PlanOptions())
    nidx = {n: i for i, n in enumerate(nodes)}
    assign = np.full((100, prob.S, prob.R), -1, np.int32)
    order = {p: i for i, p in enumerate(prob.partitions)}
    for pname, part in bucketed.items():
        for s, ns in part.nodes_by_state.items():
            si = prob.states.index(s)
            for ri, node in enumerate(ns):
                assign[order[pname], si, ri] = nidx[node]
    report = check_assignment(prob, assign)
    assert not any(report.values()), report
    counts = np.bincount(assign[assign >= 0], minlength=13)
    plain, _ = plan_next_map(
        parts, parts, nodes, [], [], MODEL, PlanOptions(), backend="tpu")
    pc = np.zeros(13, int)
    for p in plain.values():
        for ns in p.nodes_by_state.values():
            for n in ns:
                pc[nidx[n]] += 1
    assert counts.max() - counts.min() <= (pc.max() - pc.min()) + 2


def test_bucket_size_ladder():
    from blance_tpu.core.encode import bucket_size

    # Values within one bucket collapse to the same padded size...
    assert bucket_size(1000) == bucket_size(1007) == bucket_size(998)
    # ...the padding overhead stays within one octave step (12.5%)...
    for x in (9, 100, 513, 12_345, 100_000):
        b = bucket_size(x)
        assert x <= b <= x * 1.125 + 1, (x, b)
    # ...and tiny/degenerate sizes pass through untouched.
    assert bucket_size(0) == 0
    assert bucket_size(5) == 5


def test_auto_threshold_override_routes_backend():
    """PlanOptions.auto_tpu_threshold steers backend="auto": a threshold
    at/below P*N routes to the batched device solver, one above it to
    the exact path — visible through the phase spans each path emits."""
    from blance_tpu import Partition, plan_next_map

    nodes = [f"n{i}" for i in range(4)]
    parts = {str(i): Partition(str(i), {}) for i in range(8)}

    def spans_for(opts):
        rec = Recorder()
        with use_recorder(rec):
            plan_next_map(parts, parts, nodes, [], [], MODEL, opts,
                          backend="auto")
        return set(rec.summary()["spans"])

    low = spans_for(PlanOptions(auto_tpu_threshold=1))
    high = spans_for(PlanOptions(auto_tpu_threshold=10 ** 9))
    # Low threshold: device path (encode/solve/decode phase spans).
    assert "plan.solve" in low
    # High threshold: exact path — no device phase spans.
    assert "plan.solve" not in high and "plan.encode" not in high
