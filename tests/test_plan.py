"""Port of the reference's TestPlanNextMap golden cases (plan_test.go:392-1609).

Each case fully specifies inputs and the exact expected map plus the total
number of warnings.  Exact-match expectations are only possible because the
planner is deterministic.
"""

import pytest

from blance_tpu import Partition, PartitionModelState, PlanOptions, plan_next_map

from conftest import planner_backends


def pm(d):
    """{"0": {"primary": ["a"]}} -> PartitionMap"""
    return {name: Partition(name, {s: list(ns) for s, ns in nbs.items()})
            for name, nbs in d.items()}


def mdl(**states):
    return {name: PartitionModelState(priority=pc[0], constraints=pc[1])
            for name, pc in states.items()}


M_1P_0R = mdl(primary=(0, 1), replica=(1, 0))
M_1P_1R = mdl(primary=(0, 1), replica=(1, 1))
M_2P_1R = mdl(primary=(0, 2), replica=(1, 1))

EMPTY2 = {"0": {}, "1": {}}

CASES = [
    dict(
        about="single node, simple assignment of primary",
        prev={}, assign=EMPTY2, nodes=["a"], remove=[], add=["a"],
        model=M_1P_0R,
        exp={"0": {"primary": ["a"]}, "1": {"primary": ["a"]}},
        warnings=0,
    ),
    dict(
        about="single node, not enough to assign replicas",
        prev={}, assign=EMPTY2, nodes=["a"], remove=[], add=["a"],
        model=M_1P_1R,
        exp={"0": {"primary": ["a"], "replica": []},
             "1": {"primary": ["a"], "replica": []}},
        warnings=2,
    ),
    dict(
        about="no partitions case",
        prev={}, assign={}, nodes=["a"], remove=[], add=["a"],
        model=M_1P_1R, exp={}, warnings=0,
    ),
    dict(
        about="no model states case",
        prev={}, assign=EMPTY2, nodes=["a"], remove=[], add=["a"],
        model={}, exp={"0": {}, "1": {}}, warnings=0,
    ),
    dict(
        about="2 nodes, enough for clean primary & replica",
        prev={}, assign=EMPTY2, nodes=["a", "b"], remove=[], add=["a", "b"],
        model=M_1P_1R,
        exp={"0": {"primary": ["a"], "replica": ["b"]},
             "1": {"primary": ["b"], "replica": ["a"]}},
        warnings=0,
    ),
    dict(
        about="2 nodes, remove 1",
        prev={"0": {"primary": ["a"], "replica": ["b"]},
              "1": {"primary": ["b"], "replica": ["a"]}},
        assign=EMPTY2, nodes=["a", "b"], remove=["b"], add=[],
        model=M_1P_1R,
        exp={"0": {"primary": ["a"], "replica": []},
             "1": {"primary": ["a"], "replica": []}},
        warnings=2,
    ),
    dict(
        about="2 nodes, remove 2",
        prev={"0": {"primary": ["a"], "replica": ["b"]},
              "1": {"primary": ["b"], "replica": ["a"]}},
        assign=EMPTY2, nodes=["a", "b"], remove=["b", "a"], add=[],
        model=M_1P_1R,
        exp={"0": {"primary": [], "replica": []},
             "1": {"primary": [], "replica": []}},
        warnings=4,
    ),
    dict(
        about="2 nodes, remove 3",
        prev={"0": {"primary": ["a"], "replica": ["b"]},
              "1": {"primary": ["b"], "replica": ["a"]}},
        assign=EMPTY2, nodes=["a", "b", "c"], remove=["c", "b", "a"], add=[],
        model=M_1P_1R,
        exp={"0": {"primary": [], "replica": []},
             "1": {"primary": [], "replica": []}},
        warnings=4,
    ),
    dict(
        about="2 nodes, nothing to add or remove",
        prev={"0": {"primary": ["a"], "replica": ["b"]},
              "1": {"primary": ["b"], "replica": ["a"]}},
        assign={"0": {"primary": ["a"], "replica": ["b"]},
                "1": {"primary": ["b"], "replica": ["a"]}},
        nodes=["a", "b", "c"], remove=[], add=[],
        model=M_1P_1R,
        exp={"0": {"primary": ["a"], "replica": ["b"]},
             "1": {"primary": ["b"], "replica": ["a"]}},
        warnings=0,
    ),
    dict(
        about="2 nodes, swap node a",
        prev={"0": {"primary": ["a"], "replica": ["b"]},
              "1": {"primary": ["b"], "replica": ["a"]}},
        assign=EMPTY2, nodes=["a", "b", "c"], remove=["a"], add=["c"],
        model=M_1P_1R,
        exp={"0": {"primary": ["c"], "replica": ["b"]},
             "1": {"primary": ["b"], "replica": ["c"]}},
        warnings=0,
    ),
    dict(
        about="2 nodes, swap node b",
        prev={"0": {"primary": ["a"], "replica": ["b"]},
              "1": {"primary": ["b"], "replica": ["a"]}},
        assign=EMPTY2, nodes=["a", "b", "c"], remove=["b"], add=["c"],
        model=M_1P_1R,
        exp={"0": {"primary": ["a"], "replica": ["c"]},
             "1": {"primary": ["c"], "replica": ["a"]}},
        warnings=0,
    ),
    dict(
        about="2 nodes, swap nodes a & b for c & d",
        prev={"0": {"primary": ["a"], "replica": ["b"]},
              "1": {"primary": ["b"], "replica": ["a"]}},
        assign=EMPTY2, nodes=["a", "b", "c", "d"],
        remove=["a", "b"], add=["c", "d"],
        model=M_1P_1R,
        exp={"0": {"primary": ["c"], "replica": ["d"]},
             "1": {"primary": ["d"], "replica": ["c"]}},
        warnings=0,
    ),
    dict(
        about="add 2 nodes, 2 primaries, 1 replica",
        prev={}, assign=EMPTY2, nodes=["a", "b"], remove=[], add=["a", "b"],
        model=M_2P_1R,
        exp={"0": {"primary": ["a", "b"], "replica": []},
             "1": {"primary": ["a", "b"], "replica": []}},
        warnings=2,
    ),
    dict(
        about="add 3 nodes, 2 primaries, 1 replica",
        prev={}, assign=EMPTY2, nodes=["a", "b", "c"], remove=[],
        add=["a", "b", "c"],
        model=M_2P_1R,
        exp={"0": {"primary": ["b", "a"], "replica": ["c"]},
             "1": {"primary": ["c", "a"], "replica": ["b"]}},
        warnings=0,
    ),
    dict(
        about="model state constraint override",
        prev={}, assign=EMPTY2, nodes=["a", "b"], remove=[], add=["a", "b"],
        model=mdl(primary=(0, 0), replica=(1, 0)),
        constraints={"primary": 1, "replica": 1},
        exp={"0": {"primary": ["a"], "replica": ["b"]},
             "1": {"primary": ["b"], "replica": ["a"]}},
        warnings=0,
    ),
    dict(
        about="partition weight of 3 for partition 0",
        prev={}, assign={str(i): {} for i in range(4)},
        nodes=["a", "b"], remove=[], add=["a", "b"],
        model=M_1P_0R, pweights={"0": 3},
        exp={"0": {"primary": ["a"]}, "1": {"primary": ["b"]},
             "2": {"primary": ["b"]}, "3": {"primary": ["b"]}},
        warnings=0,
    ),
    dict(
        about="partition weight of 3 for partition 0, with 4 partitions",
        prev={}, assign={str(i): {} for i in range(5)},
        nodes=["a", "b"], remove=[], add=["a", "b"],
        model=M_1P_0R, pweights={"0": 3},
        exp={"0": {"primary": ["a"]}, "1": {"primary": ["b"]},
             "2": {"primary": ["b"]}, "3": {"primary": ["b"]},
             "4": {"primary": ["a"]}},
        warnings=0,
    ),
    dict(
        about="partition weight of 3 for partition 1, with 5 partitions",
        prev={}, assign={str(i): {} for i in range(6)},
        nodes=["a", "b"], remove=[], add=["a", "b"],
        model=M_1P_0R, pweights={"1": 3},
        exp={"0": {"primary": ["b"]}, "1": {"primary": ["a"]},
             "2": {"primary": ["b"]}, "3": {"primary": ["b"]},
             "4": {"primary": ["a"]}, "5": {"primary": ["b"]}},
        warnings=0,
    ),
    dict(
        about="node weight of 3 for node a",
        prev={}, assign={str(i): {} for i in range(6)},
        nodes=["a", "b"], remove=[], add=["a", "b"],
        model=M_1P_0R, nweights={"a": 3},
        exp={"0": {"primary": ["a"]}, "1": {"primary": ["b"]},
             "2": {"primary": ["a"]}, "3": {"primary": ["a"]},
             "4": {"primary": ["a"]}, "5": {"primary": ["b"]}},
        warnings=0,
    ),
    dict(
        about="node weight of 3 for node b",
        prev={}, assign={str(i): {} for i in range(6)},
        nodes=["a", "b"], remove=[], add=["a", "b"],
        model=M_1P_0R, nweights={"b": 3},
        exp={"0": {"primary": ["a"]}, "1": {"primary": ["b"]},
             "2": {"primary": ["b"]}, "3": {"primary": ["b"]},
             "4": {"primary": ["a"]}, "5": {"primary": ["b"]}},
        warnings=0,
    ),
]


@pytest.mark.parametrize("backend", planner_backends())
@pytest.mark.parametrize("case", CASES, ids=[c["about"] for c in CASES])
def test_plan_next_map(case, backend):
    opts = PlanOptions(
        model_state_constraints=case.get("constraints"),
        partition_weights=case.get("pweights"),
        state_stickiness=case.get("sstick"),
        node_weights=case.get("nweights"),
        node_hierarchy=case.get("hierarchy"),
        hierarchy_rules=case.get("rules"),
    )
    result, warnings = plan_next_map(
        pm(case["prev"]), pm(case["assign"]), case["nodes"],
        case["remove"], case["add"], case["model"], opts, backend=backend,
    )
    if backend == "tpu":
        # The batched solver is deliberately not bit-identical; assert
        # the contract (clean audit, balance within the golden's + 1)
        # instead of the exact map — see testing/vis.py assert_contract.
        from blance_tpu.testing.vis import assert_contract

        assert_contract(
            case["about"], pm(case["prev"]), pm(case["assign"]),
            pm(case["exp"]), result, case["nodes"], case["remove"],
            case["model"], opts)
    else:
        got = {name: p.nodes_by_state for name, p in result.items()}
        exp = {name: dict(nbs) for name, nbs in case["exp"].items()}
        assert got == exp, f"{case['about']}: got {got}, exp {exp}"
    total = sum(len(w) for w in warnings.values())
    assert total == case["warnings"], f"{case['about']}: warnings {warnings}"
