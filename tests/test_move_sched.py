"""Critical-path move scheduler (orchestrate/sched/, docs/SCHEDULER.md).

Covers the move-DAG builder (chain slicing, lifecycle validation,
machine model), the upward-rank sweep (host values, host/device parity,
engine counters), HEFT-style list scheduling (precedence, lane capacity,
stalled chains, determinism), the orchestrator binding (legacy default
extraction, mutual exclusion with a custom find_move, sched.* metrics,
online reschedule on quarantine), the identity contract — scheduled
execution produces the bit-identical final map and move SET as the
legacy app-weight order, cold, warm (session-backed) and under chaos —
plus the cost-model cold-start priors and the SloTracker per-incident
makespan satellites (ISSUE 12)."""

import asyncio
import json
import types

import pytest

from blance_tpu import Partition, PartitionModelState, model
from blance_tpu.obs import Recorder, use_recorder
from blance_tpu.obs.costmodel import CostModel, default_op_priors
from blance_tpu.obs.slo import SloTracker
from blance_tpu.orchestrate import FaultPlan, NodeFaults
from blance_tpu.orchestrate.orchestrator import (
    OrchestratorOptions,
    lowest_weight_partition_move_for_node,
    orchestrate_moves,
)
from blance_tpu.orchestrate.sched import (
    CriticalPathScheduler,
    LegacyWeightOrder,
    MoveDagError,
    build_move_dag,
    list_schedule,
    upward_ranks,
)
from blance_tpu.orchestrate.sched.policy import (
    _LEGACY_BOUND,
    _CriticalPathBound,
)
from blance_tpu.rebalance import (
    ClusterDelta,
    RebalanceController,
    rebalance_async,
)

MR_MODEL = {
    "primary": PartitionModelState(priority=0, constraints=0),
    "replica": PartitionModelState(priority=0, constraints=1),
}


def pm(d):
    return {name: Partition(name, {s: list(ns) for s, ns in nbs.items()})
            for name, nbs in d.items()}


def mv(node, state="primary", op="add"):
    return types.SimpleNamespace(node=node, state=state, op=op)


def cursor(partition, moves, next=0, failed_at=None):
    return types.SimpleNamespace(partition=partition, moves=moves,
                                 next=next, failed_at=failed_at)


# -- the move-DAG builder -----------------------------------------------------


def test_dag_chains_levels_machines():
    cursors = {
        "p0": cursor("p0", [mv("b", op="add"), mv("a", "", op="del")]),
        "p1": cursor("p1", [mv("c", op="add")]),
    }
    dag = build_move_dag(cursors, nodes_all=["a", "b", "c"],
                         max_concurrent=2)
    assert set(dag.chains) == {"p0", "p1"}
    assert [m.op for m in dag.chains["p0"]] == ["add", "del"]
    assert [m.level for m in dag.chains["p0"]] == [0, 1]
    # The chain's indices are ABSOLUTE move-list coordinates.
    assert [m.index for m in dag.chains["p0"]] == [0, 1]
    # levels[k] holds every chain's k-th remaining move.
    assert {m.partition for m in dag.levels[0]} == {"p0", "p1"}
    assert [m.partition for m in dag.levels[1]] == ["p0"]
    assert dag.machines == {"a": 2, "b": 2, "c": 2}
    # predecessor() walks the chain edge.
    assert dag.predecessor(dag.chains["p0"][1]) == dag.chains["p0"][0]
    assert dag.predecessor(dag.chains["p0"][0]) is None


def test_dag_slices_from_cursor_and_skips_abandoned():
    cursors = {
        "done": cursor("done", [mv("a")], next=1),
        "mid": cursor("mid", [mv("a"), mv("b"), mv("c")], next=1),
        "dead": cursor("dead", [mv("a"), mv("b")], failed_at=0),
    }
    dag = build_move_dag(cursors, nodes_all=["a", "b", "c"])
    # Finished and abandoned partitions contribute nothing; the live
    # chain starts AT the cursor with absolute indices preserved.
    assert set(dag.chains) == {"mid"}
    assert [(m.index, m.level, m.node) for m in dag.chains["mid"]] == \
        [(1, 0, "b"), (2, 1, "c")]


def test_dag_validates_nothing_after_del():
    cursors = {"p": cursor(
        "p", [mv("a", "", op="del"), mv("a", op="promote")])}
    with pytest.raises(MoveDagError, match="after its removal"):
        build_move_dag(cursors, nodes_all=["a"])


def test_dag_validates_add_before_use():
    cursors = {"p": cursor(
        "p", [mv("b", op="promote"), mv("b", op="add")])}
    with pytest.raises(MoveDagError, match="make before"):
        build_move_dag(cursors, nodes_all=["b"])


def test_dag_accepts_reference_lifecycle():
    cursors = {"p": cursor("p", [
        mv("b", "replica", op="add"), mv("b", "primary", op="promote"),
        mv("a", "replica", op="demote"), mv("a", "", op="del")])}
    dag = build_move_dag(cursors, nodes_all=["a", "b"])
    assert len(dag.chains["p"]) == 4


# -- upward ranks -------------------------------------------------------------


def test_upward_ranks_are_suffix_sums():
    ranks = upward_ranks([[1.0, 2.0, 3.0], [5.0], []])
    assert ranks == [[6.0, 5.0, 3.0], [5.0], []]


def test_upward_ranks_host_device_parity():
    pytest.importorskip("jax")
    chain_costs = [[0.125 * (i + j + 1) for j in range(1 + i % 4)]
                   for i in range(12)]
    rec = Recorder()
    host = upward_ranks(chain_costs, device_threshold=10**9, recorder=rec)
    dev = upward_ranks(chain_costs, device_threshold=0, recorder=rec)
    assert rec.counters["sched.host_ranks"] == 1
    assert rec.counters["sched.device_ranks"] == 1
    for h, d in zip(host, dev):
        assert len(h) == len(d)
        for a, b in zip(h, d):
            assert abs(a - b) < 1e-5  # float32 device sweep vs host


# -- HEFT-style list scheduling ----------------------------------------------


def _plan(cursors, nodes, lanes=1):
    dag = build_move_dag(cursors, nodes_all=nodes, max_concurrent=lanes)
    chains = list(dag.chains.values())
    costs = {}
    ranks = {}
    for chain, cranks in zip(
            chains, upward_ranks([[1.0] * len(c) for c in chains])):
        for m, r in zip(chain, cranks):
            costs[(m.partition, m.index)] = 1.0
            ranks[(m.partition, m.index)] = r
    return dag, list_schedule(dag, costs, ranks)


def test_list_schedule_respects_precedence_and_lanes():
    cursors = {
        f"p{i}": cursor(f"p{i}", [mv("j", op="add"), mv("a", "", op="del")])
        for i in range(4)}
    dag, plan = _plan(cursors, ["a", "j"], lanes=1)
    assert plan.scheduled_keys() == {(m.partition, m.index)
                                     for m in dag.moves()}
    assert plan.stalled == ()
    by_key = {(m.partition, m.index): m for m in plan.moves}
    for p in cursors:
        add, dele = by_key[(p, 0)], by_key[(p, 1)]
        assert dele.start_s >= add.finish_s  # chain edge honored
    # One joiner lane: its adds serialize; makespan covers them plus a
    # trailing del.
    assert plan.makespan_s == 5.0
    assert plan.critical_path_s == 2.0
    assert 0.0 < plan.lane_utilization <= 1.0


def test_list_schedule_stalls_machineless_chains():
    cursors = {
        "ok": cursor("ok", [mv("a", op="add")]),
        "stuck": cursor("stuck", [mv("q", op="add"),
                                  mv("a", "", op="del")]),
    }
    _dag, plan = _plan(cursors, ["a"])  # "q" has no machine
    assert plan.scheduled_keys() == {("ok", 0)}
    # The machineless move AND its chain successor both stall — every
    # remaining move appears exactly once across moves+stalled.
    assert set(plan.stalled) == {("stuck", 0), ("stuck", 1)}
    # A stalled chain's tail must not inflate the critical path past
    # the predicted makespan — the gauge is a makespan LOWER bound.
    assert plan.critical_path_s <= plan.makespan_s


def test_list_schedule_is_deterministic():
    cursors = {f"p{i}": cursor(f"p{i}", [mv("n", op="add")])
               for i in range(6)}
    _dag, a = _plan(cursors, ["n"], lanes=2)
    _dag, b = _plan(cursors, ["n"], lanes=2)
    assert a == b


# -- orchestrator binding -----------------------------------------------------


def _run_orchestration(make):
    """Build the orchestrator INSIDE the running loop (it spawns its
    supplier/mover tasks at construction), drain it, hand it back."""
    async def go():
        o = make()
        async for _ in o.progress_ch():
            pass
        o.stop()
        return o
    return asyncio.run(go())


def test_default_options_bind_the_legacy_policy():
    async def assign(stop_ch, node, partitions, states, ops):
        await asyncio.sleep(0)

    o = _run_orchestration(lambda: orchestrate_moves(
        MR_MODEL, OrchestratorOptions(), ["a", "b"],
        pm({"00": {"primary": ["a"]}}), pm({"00": {"primary": ["b"]}}),
        assign))
    assert o.sched is _LEGACY_BOUND


def test_legacy_bound_selects_like_the_weight_rule():
    cands = [cursor("x", [mv("n", op="del")]),
             cursor("y", [mv("n", op="promote")]),
             cursor("z", [mv("n", op="add")])]
    assert _LEGACY_BOUND.select("n", cands) == 1
    assert LegacyWeightOrder().bind([], {}, 1, Recorder()) is _LEGACY_BOUND
    # And the module-level rule is still importable from the orchestrator
    # (the extraction is a move, not an API break).
    moves = [c.moves[0] for c in cands]
    assert lowest_weight_partition_move_for_node("n", moves) == 1


def test_scheduler_and_custom_find_move_are_mutually_exclusive():
    async def go():
        with pytest.raises(ValueError, match="mutually exclusive"):
            orchestrate_moves(
                MR_MODEL,
                OrchestratorOptions(scheduler=CriticalPathScheduler()),
                ["a", "b"],
                pm({"00": {"primary": ["a"]}}),
                pm({"00": {"primary": ["b"]}}),
                lambda *a: None,
                lambda node, moves: 0)
    asyncio.run(go())


def test_scheduled_run_publishes_sched_metrics():
    async def assign(stop_ch, node, partitions, states, ops):
        await asyncio.sleep(0)

    rec = Recorder()
    with use_recorder(rec):
        o = _run_orchestration(lambda: orchestrate_moves(
            MR_MODEL,
            OrchestratorOptions(scheduler=CriticalPathScheduler()),
            ["a", "b", "c"],
            pm({f"p{i}": {"primary": ["a"]} for i in range(4)}),
            pm({f"p{i}": {"primary": ["b" if i % 2 else "c"]}
                for i in range(4)}),
            assign))
    assert isinstance(o.sched, _CriticalPathBound)
    plan = o.sched.plan
    assert plan.makespan_s > 0.0
    assert plan.critical_path_s > 0.0
    # 4 adds + 4 dels, none stalled — no quarantine happened, so the
    # bound still holds the initial build's plan.
    assert len(plan.moves) == 8 and plan.stalled == ()
    assert len(plan.moves) == len(o.sched.last_remaining)
    assert rec.gauges["sched.makespan_predicted_s"] > 0.0
    assert rec.gauges["sched.critical_path_s"] > 0.0
    assert 0.0 < rec.gauges["sched.lane_utilization"] <= 1.0
    assert "sched.makespan_actual_s" in rec.gauges
    assert rec.histograms.get("sched.makespan_rel_err")
    # Priors-only model: every prediction was a cold fallback.
    assert rec.counters["costmodel.cold_predictions"] >= 8


def test_truncated_run_is_not_scored():
    """finish() on a cancelled/superseded orchestration (live moves
    still pending) must NOT record makespan_actual_s or a rel-err
    sample — a supersede 1s into a 100s plan is not 99x prediction
    error, and mixed_week's overlapping supersedes would otherwise
    drown the histogram in truncation noise."""
    rec = Recorder()
    cursors = {"p0": cursor("p0", [mv("b", op="add"),
                                   mv("a", "", op="del")])}
    bound = CriticalPathScheduler().bind(["a", "b"], cursors, 1, rec)
    assert bound.plan.makespan_s > 0.0
    bound.finish(rec.now())  # cursor still at 0: truncated wind-down
    assert "sched.makespan_actual_s" not in rec.gauges
    assert not rec.histograms.get("sched.makespan_rel_err")
    # The same wind-down with the chain complete DOES score.
    rec2 = Recorder()
    done = cursor("p0", [mv("b", op="add")])
    done.next = 1
    bound2 = CriticalPathScheduler().bind(["a", "b"], {"p0": done},
                                          1, rec2)
    bound2.on_batch("b", [], ok=True, now=rec2.now())
    bound2.finish(rec2.now())
    assert "sched.makespan_actual_s" in rec2.gauges


def test_quarantine_triggers_online_reschedule():
    plan = FaultPlan(seed=9, nodes={"dead": NodeFaults(dead=True)})

    async def assign(stop_ch, node, partitions, states, ops):
        await asyncio.sleep(0)

    rec = Recorder()
    with use_recorder(rec):
        o = _run_orchestration(lambda: orchestrate_moves(
            MR_MODEL,
            OrchestratorOptions(
                scheduler=CriticalPathScheduler(), move_timeout_s=0.25,
                max_retries=0, quarantine_after=1, probe_after_s=600.0),
            ["a", "b", "dead"],
            pm({"p0": {"primary": ["a"]}, "p1": {"primary": ["a"]}}),
            pm({"p0": {"primary": ["dead"]}, "p1": {"primary": ["b"]}}),
            plan.wrap(assign)))
        bound = o.sched
    assert bound.reschedules >= 1
    assert "dead" in bound.quarantined()
    assert rec.counters["sched.reschedules"] == bound.reschedules
    # Post-reschedule plan: nothing sits on the quarantined node's lanes.
    assert all(m.node != "dead" for m in bound.plan.moves)


def test_heal_restores_lanes_and_reschedules():
    """A half-open probe heal must rebuild the schedule with the
    node's lanes back in the machine model — a heal-blind plan would
    keep the healed node's chains 'stalled' (and the makespan gauges
    wrong) for the rest of the run."""
    # Attempt 1 on "flaky" faults (tripping the quarantine_after=1
    # breaker); the probe is due immediately (probe_after_s=0) and
    # heal_after=1 makes it succeed — the heal transition mid-run.
    plan = FaultPlan(seed=9, nodes={"flaky": NodeFaults(dead=True,
                                                        heal_after=1)})

    async def assign(stop_ch, node, partitions, states, ops):
        await asyncio.sleep(0)

    rec = Recorder()
    with use_recorder(rec):
        o = _run_orchestration(lambda: orchestrate_moves(
            MR_MODEL,
            OrchestratorOptions(
                scheduler=CriticalPathScheduler(), move_timeout_s=0.25,
                max_retries=0, quarantine_after=1, probe_after_s=0.0),
            ["a", "b", "flaky"],
            pm({f"p{i}": {"primary": ["a"]} for i in range(4)}),
            pm({f"p{i}": {"primary": ["flaky"]} for i in range(4)}),
            plan.wrap(assign)))
        bound = o.sched
    # Trip then heal: two rebuilds, and the healed node is out of the
    # bound's quarantine set (its lanes rejoined the machine model).
    assert bound.reschedules >= 2
    assert "flaky" not in bound.quarantined()
    # Only the tripping partition was sacrificed; the rest flowed onto
    # the healed node after the probe re-admitted it.
    assert len({f.partition for f in o.failures}) <= 1
    assert o._progress.tot_mover_assign_partition_ok > 0


# -- the identity contract: same map, same move set, only the clock ----------


def _hetero_assign(recs):
    async def assign(stop_ch, node, partitions, states, ops):
        recs.append((partitions[0], node, states[0], ops[0]))
        await asyncio.sleep(0)
    return assign


def _scheduler_for(kind):
    return None if kind == "legacy" else CriticalPathScheduler()


@pytest.mark.parametrize("chaos", [False, True],
                         ids=["cold", "chaos"])
def test_final_map_and_move_set_identical_to_legacy(chaos):
    """The scheduler chooses ORDER only: the rebalance result (final
    map, convergence, residuals) and the executed move SET must be
    bit-identical to the legacy app-weight order — with and without a
    dead node tripping the breaker mid-run."""
    m = model(primary=(0, 1), replica=(1, 1))
    nodes = ["a", "b", "c", "d"]
    beg = pm({f"p{i}": {"primary": [nodes[i % 3]],
                        "replica": [nodes[(i + 1) % 3]]}
              for i in range(9)})

    def run_one(kind):
        recs = []
        faults = FaultPlan(
            seed=13, nodes={"c": NodeFaults(dead=True)} if chaos else {})
        opts = OrchestratorOptions(
            scheduler=_scheduler_for(kind), move_timeout_s=0.25,
            max_retries=0, quarantine_after=1, probe_after_s=600.0)
        r = asyncio.run(rebalance_async(
            m, beg, nodes, ["a"], [], faults.wrap(_hetero_assign(recs)),
            orchestrator_options=opts, max_recovery_rounds=2,
            backend="greedy"))
        return r, recs

    r_leg, recs_leg = run_one("legacy")
    r_crit, recs_crit = run_one("critical_path")
    assert {k: v.nodes_by_state for k, v in r_leg.next_map.items()} == \
        {k: v.nodes_by_state for k, v in r_crit.next_map.items()}
    assert r_leg.converged == r_crit.converged
    assert r_leg.residual_failures == r_crit.residual_failures
    # Same move SET (the order legitimately differs).
    assert sorted(recs_leg) == sorted(recs_crit)
    if not chaos:
        assert r_leg.converged


def test_session_backed_controller_identical_final_map():
    """Warm path: a session-backed controller (warm carry across
    cycles) lands on the identical final map whether its orchestrations
    run legacy or critical-path order."""
    pytest.importorskip("jax")
    from blance_tpu.plan.session import PlannerSession

    def drive(kind):
        async def go():
            m = model(primary=(0, 1))
            nodes = ["a", "b", "c"]
            parts = [f"p{i}" for i in range(8)]
            cur = pm({p: {"primary": [nodes[i % 3]]}
                      for i, p in enumerate(parts)})
            session = PlannerSession(m, nodes, parts)
            session.load_map(cur)
            recs = []
            ctl = RebalanceController(
                m, nodes, cur, _hetero_assign(recs), session=session,
                debounce_s=0.001,
                orchestrator_options=OrchestratorOptions(
                    scheduler=_scheduler_for(kind)))
            ctl.start()
            ctl.submit(ClusterDelta(remove=("a",)))
            await asyncio.wait_for(ctl.quiesce(), 30)
            ctl.submit(ClusterDelta(add=("a",)))
            final = await asyncio.wait_for(ctl.quiesce(), 30)
            await ctl.stop()
            return final, recs
        return asyncio.run(go())

    final_leg, recs_leg = drive("legacy")
    final_crit, recs_crit = drive("critical_path")
    assert {k: v.nodes_by_state for k, v in final_leg.items()} == \
        {k: v.nodes_by_state for k, v in final_crit.items()}
    assert sorted(recs_leg) == sorted(recs_crit)


# -- cost-model cold-start priors ---------------------------------------------


def test_committed_priors_load_and_are_non_uniform():
    priors = default_op_priors()
    assert set(priors) == {"add", "del", "promote", "demote"}
    assert all(s > 0.0 for s in priors.values())
    # The committed calibration prices a del cheaper than an add — the
    # non-uniformity the scheduler needs on a fresh cluster.
    assert priors["del"] < priors["add"]


def test_priors_version_mismatch_raises(tmp_path):
    p = tmp_path / "stale.json"
    p.write_text(json.dumps({"version": 0, "op_priors_s": {"add": 1.0}}))
    with pytest.raises(ValueError, match="priors version"):
        default_op_priors(str(p))


def test_seed_priors_never_overwrites_learned_estimates():
    rec = Recorder()
    cm = CostModel(recorder=rec)
    cm.seed_priors({"add": 5.0})
    assert cm.predict("anywhere", "add") == 5.0
    # An op aggregate learned from real observations survives a reseed.
    cm._op_est["add"] = [0.25, 4]
    cm.seed_priors({"add": 5.0})
    assert cm.predict("anywhere", "add") == 0.25


def test_cold_predictions_counter_and_with_priors():
    rec = Recorder()
    cm = CostModel.with_priors(recorder=rec)
    a = cm.predict("fresh-node", "add")
    d = cm.predict("fresh-node", "del")
    assert a != d  # priors, not the flat default
    assert rec.counters["costmodel.cold_predictions"] == 2
    # An exact (node, op) estimate is NOT a cold prediction.
    cm._est[("fresh-node", "add")] = [0.5, 3]
    assert cm.predict("fresh-node", "add") == 0.5
    assert rec.counters["costmodel.cold_predictions"] == 2


def test_predict_move_uses_priors():
    cm = CostModel.with_priors()
    priors = default_op_priors()
    assert cm.predict_move(mv("nowhere", op="add")) == priors["add"]


# -- SloTracker per-incident makespan ----------------------------------------


class _Mv:
    def __init__(self, partition, node, state="primary", op="add"):
        self.partition, self.node = partition, node
        self.state, self.op = state, op


def test_incident_lag_measures_to_last_executed_move():
    t = {"now": 0.0}
    rec = Recorder(clock=lambda: t["now"])
    slo = SloTracker(pm({"p0": {"primary": ["a"]}}),
                     clock=lambda: t["now"], recorder=rec)
    slo.open_incident()
    t["now"] = 3.0
    slo.on_batch("b", [_Mv("p0", "b")], ok=True, now=3.0)
    # A long idle tail after the last move (debounce, planner time)
    # must NOT inflate the makespan sample.
    t["now"] = 60.0
    assert slo.close_incident() == 3.0
    assert slo.first_converged_lags() == [3.0]
    assert rec.gauges["slo.first_converged_lag_s"] == 3.0
    assert slo.summary().first_converged_lag_s == 3.0


def test_incident_open_is_first_wins_and_zero_move_incidents_are_zero():
    t = {"now": 10.0}
    slo = SloTracker(pm({"p0": {"primary": ["a"]}}),
                     clock=lambda: t["now"])
    assert slo.close_incident() is None  # nothing open
    slo.open_incident()
    t["now"] = 25.0
    slo.open_incident()  # a coalesced burst: the FIRST event anchors
    t["now"] = 30.0
    slo.on_batch("b", [_Mv("p0", "b")], ok=True, now=30.0)
    assert slo.close_incident() == 20.0
    # An incident that needed no moves converged instantly.
    slo.open_incident()
    assert slo.close_incident() == 0.0
    assert slo.first_converged_lags() == [20.0, 0.0]


def test_incident_with_only_failures_reports_the_whole_window():
    # An incident whose moves all FAILED never converged: its lag is
    # the open-to-close window (a lower bound), never a 0.0 that would
    # deflate the makespan p95 with "instant" unconverged incidents.
    t = {"now": 0.0}
    slo = SloTracker(pm({"p0": {"primary": ["a"]}}),
                     clock=lambda: t["now"])
    slo.open_incident()
    t["now"] = 4.0
    slo.on_batch("b", [_Mv("p0", "b")], ok=False, now=4.0)
    t["now"] = 9.0
    assert slo.close_incident() == 9.0
    assert slo.first_converged_lags() == [9.0]


def test_incident_with_failure_tail_reports_the_whole_window():
    # Executes, THEN fails until close (a dead node exhausting
    # recovery): the incident never converged, so the lag is the whole
    # window — not the deflating time-to-last-execute.  A failure a
    # retry then executed PAST still reads as converged.
    t = {"now": 0.0}
    slo = SloTracker(pm({f"p{i}": {"primary": ["a"]} for i in range(2)}),
                     clock=lambda: t["now"])
    slo.open_incident()
    t["now"] = 3.0
    slo.on_batch("b", [_Mv("p0", "b")], ok=True, now=3.0)
    t["now"] = 5.0
    slo.on_batch("c", [_Mv("p1", "c")], ok=False, now=5.0)
    t["now"] = 40.0
    assert slo.close_incident() == 40.0  # fail tail: whole window
    slo.open_incident()
    t["now"] = 41.0
    slo.on_batch("c", [_Mv("p1", "c")], ok=False, now=41.0)
    t["now"] = 43.0
    slo.on_batch("c", [_Mv("p1", "c")], ok=True, now=43.0)  # retry lands
    t["now"] = 60.0
    assert slo.close_incident() == 3.0  # converged at the retry
    assert slo.first_converged_lags() == [40.0, 3.0]


def test_rebalance_records_one_incident():
    async def assign(stop_ch, node, partitions, states, ops):
        await asyncio.sleep(0)

    m = model(primary=(0, 1))
    beg = pm({f"p{i}": {"primary": ["a"]} for i in range(4)})
    rec = Recorder()
    with use_recorder(rec):
        r = asyncio.run(rebalance_async(
            m, beg, ["a", "b"], ["a"], [], assign, backend="greedy"))
    assert r.converged
    assert "slo.first_converged_lag_s" in rec.gauges
    assert rec.gauges["slo.first_converged_lag_s"] >= 0.0


def test_raised_rebalance_never_leaves_a_stale_open_incident():
    """A rebalance call that RAISES (validation error here) must
    discard its open incident: a reused tracker's next episode opens
    fresh instead of inheriting the failed call's start time and
    recording an arbitrarily inflated makespan sample."""
    async def assign(stop_ch, node, partitions, states, ops):
        await asyncio.sleep(0)

    m = model(primary=(0, 1))
    beg = pm({f"p{i}": {"primary": ["a"]} for i in range(4)})
    t = {"now": 100.0}
    rec = Recorder(clock=lambda: t["now"])
    slo = SloTracker(beg, clock=lambda: t["now"], recorder=rec)
    with use_recorder(rec):
        with pytest.raises(ValueError):
            # max_recovery_rounds without fault-tolerant options raises
            # AFTER open_incident.
            asyncio.run(rebalance_async(
                m, beg, ["a", "b"], ["a"], [], assign, backend="greedy",
                max_recovery_rounds=2, slo=slo))
        assert slo._incident_t0 is None  # discarded, not left open
        t["now"] = 500.0  # a gap that must NOT enter the next sample
        r = asyncio.run(rebalance_async(
            m, beg, ["a", "b"], ["a"], [], assign, backend="greedy",
            slo=slo))
    assert r.converged
    # Measured from the SECOND call's open (500.0), not the failed
    # call's stale 100.0 (which would read 400.0).
    assert slo.first_converged_lags() == [0.0]


def test_controller_stop_mid_episode_discards_the_incident():
    """A stop during a busy episode is not a quiesce: the open incident
    dies unrecorded instead of closing as a converged-looking lag
    sample polluting first_converged_lags."""
    async def drive():
        gate = asyncio.Event()

        async def assign(stop_ch, node, partitions, states, ops):
            await gate.wait()  # hold the episode in flight

        m = model(primary=(0, 1))
        beg = pm({f"p{i}": {"primary": ["a"]} for i in range(4)})
        slo = SloTracker(beg)
        ctl = RebalanceController(m, ["a", "b"], beg, assign,
                                  backend="greedy", slo=slo)
        ctl.start()
        ctl.submit(ClusterDelta(add=("c",)))
        await asyncio.sleep(0.05)  # let the episode reach the mover
        gate.set()
        await ctl.stop()
        return slo

    slo = asyncio.run(drive())
    assert slo._incident_t0 is None  # nothing left open
    assert slo.first_converged_lags() == []  # and nothing recorded
