"""Tests for the deterministic schedule explorer itself
(blance_tpu/testing/sched.py): the loop's determinism contract, the
bounded-exhaustive enumeration's completeness on a toy with a known
injected race, and the trace-file replay round trip."""

import asyncio

import pytest

from blance_tpu.testing.sched import (
    DeadlockError,
    DeterministicLoop,
    FifoPolicy,
    InvariantViolation,
    PrefixPolicy,
    RandomWalkPolicy,
    ReplayDivergence,
    StepLimitExceeded,
    Trace,
    explore,
    load_trace,
    replay,
    run_controlled,
    save_trace,
)


class _Cell:
    def __init__(self) -> None:
        self.x = 0


def racy_factory():
    """Two tasks doing an unprotected read-modify-write across an await:
    the classic lost update.  Some interleavings end with x == 1."""

    async def scenario():
        cell = _Cell()

        async def incr():
            tmp = cell.x
            await asyncio.sleep(0)
            cell.x = tmp + 1

        t1 = asyncio.ensure_future(incr())
        t2 = asyncio.ensure_future(incr())
        await t1
        await t2
        if cell.x != 2:
            raise InvariantViolation(f"lost update: x={cell.x}")
        return cell.x

    return scenario()


def fixed_factory():
    """The same increments serialized by a lock: no schedule loses one."""

    async def scenario():
        cell = _Cell()
        lock = asyncio.Lock()

        async def incr():
            async with lock:
                tmp = cell.x
                await asyncio.sleep(0)
                cell.x = tmp + 1

        t1 = asyncio.ensure_future(incr())
        t2 = asyncio.ensure_future(incr())
        await t1
        await t2
        assert cell.x == 2
        return cell.x

    return scenario()


# -- loop basics -------------------------------------------------------------


def test_virtual_time_no_wall_clock():
    """A 500 s sleep and a wait_for timeout both complete instantly in
    virtual time; the loop clock advances to the timer deadlines."""

    async def scenario():
        loop = asyncio.get_running_loop()
        t0 = loop.time()
        await asyncio.sleep(500.0)
        try:
            await asyncio.wait_for(asyncio.sleep(1000.0), timeout=2.5)
            raise AssertionError("wait_for did not time out")
        except asyncio.TimeoutError:
            pass
        return loop.time() - t0

    out = run_controlled(lambda: scenario())
    assert out.ok
    assert out.result == pytest.approx(502.5)


def test_deadlock_detection():
    async def scenario():
        await asyncio.get_running_loop().create_future()  # never set

    out = run_controlled(lambda: scenario())
    assert not out.ok and out.deadlock
    assert isinstance(out.error, DeadlockError)


def test_step_limit_detects_livelock():
    async def scenario():
        while True:
            await asyncio.sleep(0)

    out = run_controlled(lambda: scenario(), max_steps=500)
    assert not out.ok and isinstance(out.error, StepLimitExceeded)


def test_loop_local_task_names_are_deterministic():
    """Task labels (and thus schedule signatures) must not depend on
    asyncio's process-global Task-N counter."""

    async def scenario():
        async def child():
            await asyncio.sleep(0)
        await asyncio.ensure_future(child())

    sig1 = run_controlled(lambda: scenario()).signature
    # Burn some global Task names in a plain asyncio loop in between.
    async def noise():
        await asyncio.ensure_future(asyncio.sleep(0))
    asyncio.run(noise())
    sig2 = run_controlled(lambda: scenario()).signature
    assert sig1 == sig2


# -- determinism -------------------------------------------------------------


def test_seeded_walk_same_seed_same_schedule():
    a = run_controlled(racy_factory, RandomWalkPolicy(5))
    b = run_controlled(racy_factory, RandomWalkPolicy(5))
    assert (a.choices, a.signature, a.steps, a.ok) == \
        (b.choices, b.signature, b.steps, b.ok)


def test_seeded_walks_differ_across_seeds():
    outs = [run_controlled(racy_factory, RandomWalkPolicy(s))
            for s in range(8)]
    assert len({o.signature for o in outs}) > 1


def test_prefix_policy_replays_exact_schedule():
    walk = run_controlled(racy_factory, RandomWalkPolicy(3))
    again = run_controlled(racy_factory, PrefixPolicy(walk.choices))
    assert again.signature == walk.signature
    assert again.ok == walk.ok


# -- exhaustive completeness -------------------------------------------------


def test_exhaustive_finds_injected_race_and_clean_twin_passes():
    rep = explore(racy_factory, branch_budget=None, max_schedules=1000)
    assert rep.complete and not rep.capped
    assert rep.violations, "exhaustive enumeration missed the lost update"
    assert all(v.error_type == "InvariantViolation"
               for v in rep.violations)

    rep2 = explore(fixed_factory, branch_budget=None, max_schedules=1000)
    assert rep2.complete and rep2.violations == []


def test_exhaustive_enumerates_distinct_schedules():
    rep = explore(racy_factory, branch_budget=None, max_schedules=1000)
    # FIFO + every deviation: the toy's full tree, each run distinct.
    assert rep.schedules >= 4
    # The FIFO baseline is always schedule #1; a violating schedule's
    # choices replay to the same violation.
    v = rep.violations[0]
    out = run_controlled(racy_factory, PrefixPolicy(v.choices))
    assert not out.ok and out.signature == v.signature


def test_branch_budget_bounds_the_enumeration():
    unbounded = explore(racy_factory, branch_budget=None,
                        max_schedules=1000)
    budget0 = explore(racy_factory, branch_budget=0, max_schedules=1000)
    assert budget0.schedules == 1  # FIFO only
    budget1 = explore(racy_factory, branch_budget=1, max_schedules=1000)
    assert 1 < budget1.schedules <= unbounded.schedules


def test_explore_cap_reports_incomplete():
    rep = explore(racy_factory, branch_budget=None, max_schedules=2)
    assert rep.capped and not rep.complete
    assert rep.schedules == 2


# -- trace round trip --------------------------------------------------------


def test_trace_save_load_replay_round_trip(tmp_path):
    rep = explore(racy_factory, branch_budget=None, max_schedules=1000)
    v = rep.violations[0]
    path = str(tmp_path / "toy.json")
    save_trace(v.to_trace("toy", note="lost update"), path)

    tr = load_trace(path)
    assert tr.scenario == "toy"
    assert tr.choices == v.choices
    assert tr.candidate_counts == v.candidate_counts
    assert "lost update" in tr.note

    out = replay(racy_factory, tr, strict=True)
    assert not out.ok
    assert out.signature == v.signature
    assert isinstance(out.error, InvariantViolation)


def test_trace_version_and_key_validation(tmp_path):
    import json

    path = str(tmp_path / "bad.json")
    with open(path, "w") as f:
        json.dump({"scenario": "s", "choices": [], "candidate_counts": [],
                   "version": 999}, f)
    with pytest.raises(ValueError, match="version"):
        load_trace(path)
    with open(path, "w") as f:
        json.dump({"scenario": "s", "choices": [], "candidate_counts": [],
                   "version": 1, "bogus": 1}, f)
    with pytest.raises(ValueError, match="unknown trace keys"):
        load_trace(path)


def test_replay_divergence_on_structural_drift():
    """A trace whose recorded choice exceeds the live candidate count
    must raise ReplayDivergence (stale trace), not silently run."""
    walk = run_controlled(racy_factory, RandomWalkPolicy(1))
    bogus = Trace(scenario="toy", choices=[99],
                  candidate_counts=[100])
    with pytest.raises(ReplayDivergence):
        replay(racy_factory, bogus)
    # Strict replay with drifted candidate counts also raises.
    drifted = Trace(scenario="toy", choices=list(walk.choices),
                    candidate_counts=[c + 1 for c in
                                      walk.candidate_counts])
    if drifted.choices:  # toy has at least one choice point
        with pytest.raises(ReplayDivergence):
            replay(racy_factory, drifted, strict=True)


def test_policy_base_and_fifo_choose_head():
    loop = DeterministicLoop(FifoPolicy())
    assert loop.time() == 0.0
    assert FifoPolicy().choose(5) == 0
