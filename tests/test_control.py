"""Port of the reference's placement-control contract tests
(control_test.go:18-416): the node-score-booster hook plus negative node
weights let applications pin or steer placements.  In blance_tpu the booster
is a PlanOptions field, not a package global."""

import pytest

from blance_tpu import HierarchyRule, Partition, PlanOptions, model, plan_next_map

from conftest import planner_backends


# The booster couchbase/cbgt installs (control_test.go:19-29); the library
# exports it with the native-compat marker, so both the greedy and the C++
# parametrizations exercise the exact same formula.
from blance_tpu.plan.native import cbgt_node_score_booster as cbgt_booster


M = model(primary=(0, 1), replica=(1, 1))


def nbs(result):
    return {name: p.nodes_by_state for name, p in result.items()}


def _boost_mass(result, node_weights):
    """Total boosted-away weight carried: sum of max(0, -w) per placed
    copy — the quantity the booster exists to minimize."""
    return sum(
        max(0, -(node_weights or {}).get(n, 1))
        for p in result.values()
        for ns in p.nodes_by_state.values()
        for n in ns)


def check(backend, result, exp, prev, parts, nodes, remove, add, opts):
    """Exact-map equality on the exact backends; on the batch (tpu)
    backend, the POLICY contract instead: clean audit, balance within
    the golden's band, and boosted-node avoidance at least as good as
    the golden's (the batch solver is deliberately not bit-identical —
    see testing/vis.py assert_contract)."""
    if backend != "tpu":
        assert nbs(result) == exp
        return
    from blance_tpu.testing.vis import assert_contract

    exp_map = {k: Partition(k, {s: list(v) for s, v in d.items()})
               for k, d in exp.items()}
    assert_contract("control", prev, parts, exp_map, result, nodes,
                    remove or [], M, opts)
    got_mass = _boost_mass(result, opts.node_weights)
    exp_mass = _boost_mass(exp_map, opts.node_weights)
    assert got_mass <= exp_mass, (
        f"tpu placement carries boost mass {got_mass} > golden's "
        f"{exp_mass}: {nbs(result)}")


@pytest.mark.parametrize("backend", planner_backends())
def test_control_case1_pin_primary_to_c_replica_to_b(backend):
    parts = {"X": Partition("X", {})}
    r, warnings = plan_next_map(
        {}, parts, ["a", "b", "c", "d", "e"], None, None, M,
        PlanOptions(
            node_weights={"a": -2, "b": -1, "d": -2, "e": -2},
            node_score_booster=cbgt_booster,
        ),
        backend=backend,
    )
    assert not warnings
    check(backend, r, {"X": {"primary": ["c"], "replica": ["b"]}},
          {}, parts, ["a", "b", "c", "d", "e"], None, None,
          PlanOptions(node_weights={"a": -2, "b": -1, "d": -2, "e": -2},
                      node_score_booster=cbgt_booster))


@pytest.mark.parametrize("backend", planner_backends())
def test_control_case2_no_relocation_on_node_add(backend):
    parts = {
        "X": Partition("X", {"primary": ["a"], "replica": ["b"]}),
        "Y": Partition("Y", {"primary": ["b"], "replica": ["a"]}),
        "Z": Partition("Z", {"primary": ["a"], "replica": ["b"]}),
    }
    r, warnings = plan_next_map(
        {}, parts, ["a", "b"], None, ["c"], M,
        PlanOptions(node_score_booster=cbgt_booster),
        backend=backend,
    )
    assert not warnings
    check(backend, r, {
        "X": {"primary": ["a"], "replica": ["b"]},
        "Y": {"primary": ["b"], "replica": ["a"]},
        "Z": {"primary": ["a"], "replica": ["b"]},
    }, {}, parts, ["a", "b"], None, ["c"],
        PlanOptions(node_score_booster=cbgt_booster))


@pytest.mark.parametrize("backend", planner_backends())
def test_control_case3_steer_new_partition(backend):
    parts = {
        "X": Partition("X", {"primary": ["a"], "replica": ["b"]}),
        "Y": Partition("Y", {"primary": ["b"], "replica": ["a"]}),
        "Z": Partition("Z", {}),
    }
    r, warnings = plan_next_map(
        {}, parts, ["a", "b", "c"], None, None, M,
        PlanOptions(
            node_weights={"c": -3, "a": -1},
            node_score_booster=cbgt_booster,
        ),
        backend=backend,
    )
    assert not warnings
    check(backend, r, {
        "X": {"primary": ["a"], "replica": ["b"]},
        "Y": {"primary": ["b"], "replica": ["a"]},
        "Z": {"primary": ["b"], "replica": ["a"]},
    }, {}, parts, ["a", "b", "c"], None, None,
        PlanOptions(node_weights={"c": -3, "a": -1},
                    node_score_booster=cbgt_booster))


@pytest.mark.parametrize("backend", planner_backends())
def test_control_case4_hierarchy_plus_booster(backend):
    prev = {"X": Partition("X", {"primary": ["a"], "replica": ["b"]})}
    parts = {
        "X": Partition("X", {"primary": ["a"], "replica": ["b"]}),
        "Y": Partition("Y", {}),
    }
    r, warnings = plan_next_map(
        prev, parts, ["a", "b"], None, None, M,
        PlanOptions(
            node_weights={"a": -1, "b": -1},
            node_hierarchy={"a": "Group 1", "b": "Group 2"},
            hierarchy_rules={"replica": [HierarchyRule(2, 1)]},
            node_score_booster=cbgt_booster,
        ),
        backend=backend,
    )
    assert not warnings
    check(backend, r, {
        "X": {"primary": ["a"], "replica": ["b"]},
        "Y": {"primary": ["b"], "replica": ["a"]},
    }, prev, parts, ["a", "b"], None, None,
        PlanOptions(node_weights={"a": -1, "b": -1},
                    node_hierarchy={"a": "Group 1", "b": "Group 2"},
                    hierarchy_rules={"replica": [HierarchyRule(2, 1)]},
                    node_score_booster=cbgt_booster))
