"""Multi-device sharded planning tests on the virtual 8-device CPU mesh."""

import numpy as np

import jax

from blance_tpu import Partition, PlanOptions, model
from blance_tpu.core.encode import decode_assignment, encode_problem
from blance_tpu.parallel.sharded import make_mesh, solve_problem_sharded
from blance_tpu.plan.tensor import check_assignment

M_1P_1R = model(primary=(0, 1), replica=(1, 1))


def empty_parts(n):
    return {str(i): Partition(str(i), {}) for i in range(n)}


def test_eight_virtual_devices_available():
    assert len(jax.devices()) == 8


def test_sharded_solve_matches_contract():
    nodes = [f"n{i}" for i in range(8)]
    parts = empty_parts(100)  # deliberately not divisible by 8
    problem = encode_problem(empty_parts(100), parts, nodes, [], M_1P_1R,
                             PlanOptions())
    mesh = make_mesh(8)
    assign = solve_problem_sharded(mesh, problem)
    assert assign.shape[0] == 100

    counts = check_assignment(problem, assign)
    assert counts == {"duplicates": 0, "on_removed_nodes": 0,
                      "unfilled_feasible_slots": 0}

    result, warnings = decode_assignment(problem, assign, parts, [])
    assert not warnings
    loads = {}
    for p in result.values():
        for ns in p.nodes_by_state.values():
            for n in ns:
                loads[n] = loads.get(n, 0) + 1
    # 200 total assignments over 8 nodes: ideal 25 each; sharded capacity
    # splitting costs a little tightness vs single-device, bound the spread.
    assert max(loads.values()) - min(loads.values()) <= 8, loads


def test_sharded_node_removal():
    nodes = [f"n{i}" for i in range(8)]
    parts = empty_parts(64)
    problem = encode_problem(empty_parts(64), parts, nodes, [], M_1P_1R,
                             PlanOptions())
    mesh = make_mesh(8)
    assign = solve_problem_sharded(mesh, problem)
    beg, _ = decode_assignment(problem, assign, parts, [])

    problem2 = encode_problem(beg, beg, nodes, ["n0"], M_1P_1R, PlanOptions())
    assign2 = solve_problem_sharded(mesh, problem2)
    end, warnings = decode_assignment(problem2, assign2, beg, ["n0"])
    assert not warnings
    for p in end.values():
        for ns in p.nodes_by_state.values():
            assert "n0" not in ns


def test_sharded_growth_migrates_pinned_load():
    """Cluster growth under shard_map: warm-start pins must judge capacity
    GLOBALLY (shard-local holder weight says nothing about a node being
    full), so new nodes attract load instead of staying empty."""
    old_nodes = [f"n{i}" for i in range(8)]
    all_nodes = old_nodes + ["x0", "x1", "x2", "x3", "x4", "x5", "x6", "x7"]
    parts = empty_parts(128)
    mesh = make_mesh(8)

    # Steady placement on the 8 old nodes.
    prob1 = encode_problem(empty_parts(128), parts, old_nodes, [], M_1P_1R,
                           PlanOptions())
    a1 = solve_problem_sharded(mesh, prob1)
    m1, _ = decode_assignment(prob1, a1, parts, [])

    # Double the cluster; replan from the warm map.
    prob2 = encode_problem(m1, parts, all_nodes, [], M_1P_1R, PlanOptions())
    a2 = solve_problem_sharded(mesh, prob2)
    counts = np.bincount(a2[a2 >= 0], minlength=16)
    # Every new node ends up holding something (256 copies / 16 nodes = 16).
    assert (counts[8:] > 0).all(), counts
    assert counts.max() - counts.min() <= 6, counts
    report = check_assignment(prob2, a2)
    assert report == {"duplicates": 0, "on_removed_nodes": 0,
                      "unfilled_feasible_slots": 0}


def test_hybrid_mesh_single_slice_fallback():
    """On hosts without multiple slices, the hybrid helper degrades to the
    plain mesh (virtual CPU devices report no slice_index)."""
    from blance_tpu.parallel.sharded import make_hybrid_mesh

    mesh = make_hybrid_mesh()
    assert mesh.devices.size == 8
    assert mesh.axis_names == ("parts",)
