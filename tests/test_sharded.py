"""Multi-device sharded planning tests on the virtual 8-device CPU mesh."""

import numpy as np

import jax

from blance_tpu import Partition, PlanOptions, model
from blance_tpu.core.encode import decode_assignment, encode_problem
from blance_tpu.parallel.sharded import make_mesh, solve_problem_sharded
from blance_tpu.plan.tensor import check_assignment

M_1P_1R = model(primary=(0, 1), replica=(1, 1))


def empty_parts(n):
    return {str(i): Partition(str(i), {}) for i in range(n)}


def test_eight_virtual_devices_available():
    assert len(jax.devices()) == 8


def test_sharded_solve_matches_contract():
    nodes = [f"n{i}" for i in range(8)]
    parts = empty_parts(100)  # deliberately not divisible by 8
    problem = encode_problem(empty_parts(100), parts, nodes, [], M_1P_1R,
                             PlanOptions())
    mesh = make_mesh(8)
    assign = solve_problem_sharded(mesh, problem)
    assert assign.shape[0] == 100

    counts = check_assignment(problem, assign)
    assert counts == {"duplicates": 0, "on_removed_nodes": 0,
                      "unfilled_feasible_slots": 0, "hierarchy_misses": 0}

    result, warnings = decode_assignment(problem, assign, parts, [])
    assert not warnings
    loads = {}
    for p in result.values():
        for ns in p.nodes_by_state.values():
            for n in ns:
                loads[n] = loads.get(n, 0) + 1
    # 200 total assignments over 8 nodes: ideal 25 each; sharded capacity
    # splitting costs a little tightness vs single-device, bound the spread.
    assert max(loads.values()) - min(loads.values()) <= 8, loads


def test_sharded_node_removal():
    nodes = [f"n{i}" for i in range(8)]
    parts = empty_parts(64)
    problem = encode_problem(empty_parts(64), parts, nodes, [], M_1P_1R,
                             PlanOptions())
    mesh = make_mesh(8)
    assign = solve_problem_sharded(mesh, problem)
    beg, _ = decode_assignment(problem, assign, parts, [])

    problem2 = encode_problem(beg, beg, nodes, ["n0"], M_1P_1R, PlanOptions())
    assign2 = solve_problem_sharded(mesh, problem2)
    end, warnings = decode_assignment(problem2, assign2, beg, ["n0"])
    assert not warnings
    for p in end.values():
        for ns in p.nodes_by_state.values():
            assert "n0" not in ns


def test_sharded_growth_migrates_pinned_load():
    """Cluster growth under shard_map: warm-start pins must judge capacity
    GLOBALLY (shard-local holder weight says nothing about a node being
    full), so new nodes attract load instead of staying empty."""
    old_nodes = [f"n{i}" for i in range(8)]
    all_nodes = old_nodes + ["x0", "x1", "x2", "x3", "x4", "x5", "x6", "x7"]
    parts = empty_parts(128)
    mesh = make_mesh(8)

    # Steady placement on the 8 old nodes.
    prob1 = encode_problem(empty_parts(128), parts, old_nodes, [], M_1P_1R,
                           PlanOptions())
    a1 = solve_problem_sharded(mesh, prob1)
    m1, _ = decode_assignment(prob1, a1, parts, [])

    # Double the cluster; replan from the warm map.
    prob2 = encode_problem(m1, parts, all_nodes, [], M_1P_1R, PlanOptions())
    a2 = solve_problem_sharded(mesh, prob2)
    counts = np.bincount(a2[a2 >= 0], minlength=16)
    # Every new node ends up holding something (256 copies / 16 nodes = 16).
    assert (counts[8:] > 0).all(), counts
    assert counts.max() - counts.min() <= 6, counts
    report = check_assignment(prob2, a2)
    assert report == {"duplicates": 0, "on_removed_nodes": 0,
                      "unfilled_feasible_slots": 0, "hierarchy_misses": 0}


def test_hybrid_mesh_single_slice_fallback():
    """On hosts without multiple slices, the hybrid helper degrades to the
    plain mesh (virtual CPU devices report no slice_index)."""
    from blance_tpu.parallel.sharded import make_hybrid_mesh

    mesh = make_hybrid_mesh()
    assert mesh.devices.size == 8
    assert mesh.axis_names == ("parts",)


def test_hybrid_mesh_slice_major_ordering():
    """The multi-slice ordering contract: devices grouped by slice
    (contiguous => their psum segment rides ICI), runtime order stable
    within a slice.  Exercised with plain ints since multi-slice hardware
    isn't available here — make_hybrid_mesh feeds slice_index values
    straight in."""
    from blance_tpu.parallel.sharded import slice_major_order

    # A 2-slice arrival order interleaved by the runtime.
    assert slice_major_order([1, 0, 1, 0]) == [1, 3, 0, 2]
    # Already slice-major: identity.
    assert slice_major_order([0, 0, 1, 1]) == [0, 1, 2, 3]
    # Three slices, stable within each.
    assert slice_major_order([2, 0, 1, 0, 2, 1]) == [1, 3, 2, 5, 0, 4]
    # Single slice: identity (the make_mesh fallback path's premise).
    assert slice_major_order([0] * 5) == list(range(5))


def _rack_problem(P=64, N=8, prev_map=None):
    from blance_tpu import HierarchyRule

    nodes = [f"n{i}" for i in range(N)]
    hier = {n: f"r{i // 2}" for i, n in enumerate(nodes)}
    hier.update({f"r{i}": "z0" for i in range(N // 2)})
    opts = PlanOptions(
        node_hierarchy=hier,
        hierarchy_rules={"replica": [HierarchyRule(2, 1)]})
    m = model(primary=(0, 1), replica=(1, 2))
    parts = empty_parts(P)
    problem = encode_problem(prev_map or {}, parts, nodes, [], m, opts)
    return problem, parts, m, opts


def _rule_violations(problem, assign):
    """Co-racked copies under the (2,1) replica rule (vs primary or pair)."""
    rack = problem.gids[1]
    pr = rack[assign[:, 0, 0]]
    r0, r1 = rack[assign[:, 1, 0]], rack[assign[:, 1, 1]]
    bad = (pr == r0) | (pr == r1) | (r0 == r1)
    bad |= (assign[:, 1, 0] < 0) | (assign[:, 1, 1] < 0)
    return int(bad.sum())


def test_shard_count_contract_invariance():
    """The same problem on 1 vs 8 shards: identical contract (zero
    violations, rack-rule conformant, same tight balance).  Exact equality
    is out of reach by design — per-shard capacity quotas change auction
    acceptance order — but each mesh's output must be a fixpoint of its
    own operator, and re-solving either output on the other mesh may only
    repair imbalance (bounded churn), never violate rules."""
    problem, parts, m, opts = _rack_problem()
    a1 = solve_problem_sharded(make_mesh(1), problem)
    a8 = solve_problem_sharded(make_mesh(8), problem)

    for a in (a1, a8):
        assert _rule_violations(problem, a) == 0
        assert check_assignment(problem, a) == {
            "duplicates": 0, "on_removed_nodes": 0,
            "unfilled_feasible_slots": 0, "hierarchy_misses": 0}
        for si in range(2):
            ids = a[:, si, :].ravel()
            loads = np.bincount(ids[ids >= 0], minlength=8)
            assert loads.max() - loads.min() <= 3, (si, loads)

    # Determinism: the same mesh re-solve is bit-identical.
    assert np.array_equal(a8, solve_problem_sharded(make_mesh(8), problem))

    # Own-operator fixpoint: replanning an output on its own mesh is a
    # no-op (everything pins).
    p8 = encode_problem({}, parts, problem.nodes, [], m, opts)
    p8.prev[...] = a8
    assert np.array_equal(solve_problem_sharded(make_mesh(8), p8), a8)

    # Cross-operator: re-solving the 8-shard output on 1 shard may only
    # repair residual imbalance — zero violations, churn pinned at the
    # measured value (0/64) plus slack 2 so a regression toward the old
    # ~10% drift surfaces here instead of passing silently.
    f1 = solve_problem_sharded(make_mesh(1), p8)
    assert _rule_violations(problem, f1) == 0
    churned = int((f1 != a8).any(axis=(1, 2)).sum())
    assert churned <= 2, churned


def test_sharded_rack_rules_zero_violations():
    """Regression: with per-shard capacity slices, rule-satisfying nodes
    close early and phase A's priced argmin used to fall through to a
    rule-missing node (round-1: 4/64 co-racked under shard_map)."""
    problem, _, _, _ = _rack_problem()
    assign = solve_problem_sharded(make_mesh(8), problem)
    assert _rule_violations(problem, assign) == 0


def test_sharded_fused_engine_contract():
    """The fused in-kernel score engine under shard_map (interpret mode
    on the virtual mesh): same contract as the matrix engine — zero
    violations, rack-rule conformant, tight balance, deterministic —
    so multi-chip deployments can use the engine that fits the
    north-star shape on each shard."""
    problem, parts, m, opts = _rack_problem(P=32, N=8)
    af = solve_problem_sharded(make_mesh(4), problem,
                               fused_score="interpret")
    assert _rule_violations(problem, af) == 0
    assert check_assignment(problem, af) == {
        "duplicates": 0, "on_removed_nodes": 0,
        "unfilled_feasible_slots": 0, "hierarchy_misses": 0}
    for si in range(2):
        ids = af[:, si, :].ravel()
        loads = np.bincount(ids[ids >= 0], minlength=8)
        want = (si + 1) * 32 // 8
        assert loads.max() - loads.min() <= 3, (si, loads)
        assert loads.sum() == want * 8
    # Deterministic re-solve.
    assert np.array_equal(
        af, solve_problem_sharded(make_mesh(4), problem,
                                  fused_score="interpret"))


def test_hybrid_mesh_solves_end_to_end():
    """The multi-slice (DCN) path actually SOLVES, not just orders
    devices: a synthetic 2-slice x 4-device hybrid mesh (slice ids
    interleaved the way a multi-host runtime enumerates them) must
    produce the bit-identical assignment of the flat 8-device mesh —
    shard i owns rows [i*P/8, (i+1)*P/8) regardless of which physical
    device hosts it, so slice-major reordering may not change the
    logical result (SURVEY §2.6 ICI/DCN row)."""
    from blance_tpu.parallel.sharded import make_hybrid_mesh

    devices = jax.devices()
    # Runtime-interleaved arrival: slices alternate device-by-device.
    slice_ids = [i % 2 for i in range(8)]
    hybrid = make_hybrid_mesh(devices=devices, slice_ids=slice_ids)
    assert hybrid.axis_names == ("parts",)
    # Slice-major: all slice-0 devices first, then slice-1, stable within.
    got = [d.id for d in hybrid.devices.ravel()]
    assert got == [0, 2, 4, 6, 1, 3, 5, 7], got

    problem, parts, m, opts = _rack_problem()
    a_hybrid = solve_problem_sharded(hybrid, problem)
    a_flat = solve_problem_sharded(make_mesh(8), problem)
    assert np.array_equal(a_hybrid, a_flat)
    assert _rule_violations(problem, a_hybrid) == 0
    assert check_assignment(problem, a_hybrid) == {
        "duplicates": 0, "on_removed_nodes": 0,
        "unfilled_feasible_slots": 0, "hierarchy_misses": 0}


def test_hybrid_mesh_fused_engine_and_2d():
    """The hybrid (DCN) ordering composes with the fused engine; and a
    2-D (parts x nodes) mesh built over slice-major devices solves to
    the same result as the flat 2-D mesh."""
    from blance_tpu.parallel.sharded import (
        NODE_AXIS, PARTITION_AXIS, make_hybrid_mesh, make_mesh_2d)
    from jax.sharding import Mesh

    devices = jax.devices()
    slice_ids = [i % 2 for i in range(8)]
    hybrid = make_hybrid_mesh(devices=devices, slice_ids=slice_ids)

    problem, _, _, _ = _rack_problem(P=32, N=8)
    a_h = solve_problem_sharded(hybrid, problem, fused_score="interpret")
    a_f = solve_problem_sharded(make_mesh(8), problem,
                                fused_score="interpret")
    assert np.array_equal(a_h, a_f)
    assert _rule_violations(problem, a_h) == 0

    # 2-D over the slice-major order: partition axis major so each
    # slice's 4 devices form rows; node axis (the chatty per-round
    # all_gather) stays intra-slice = on ICI.
    ordered = list(hybrid.devices.ravel())
    mesh2d_h = Mesh(np.asarray(ordered).reshape(4, 2),
                    (PARTITION_AXIS, NODE_AXIS))
    mesh2d_f = make_mesh_2d(4, 2)
    a2_h = solve_problem_sharded(mesh2d_h, problem)
    a2_f = solve_problem_sharded(mesh2d_f, problem)
    assert np.array_equal(a2_h, a2_f)
    assert _rule_violations(problem, a2_h) == 0
