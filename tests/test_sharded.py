"""Multi-device sharded planning tests on the virtual 8-device CPU mesh."""

import numpy as np

import jax

from blance_tpu import Partition, PlanOptions, model
from blance_tpu.core.encode import decode_assignment, encode_problem
from blance_tpu.parallel.sharded import make_mesh, solve_problem_sharded
from blance_tpu.plan.tensor import check_assignment

M_1P_1R = model(primary=(0, 1), replica=(1, 1))


def empty_parts(n):
    return {str(i): Partition(str(i), {}) for i in range(n)}


def test_eight_virtual_devices_available():
    assert len(jax.devices()) == 8


def test_sharded_solve_matches_contract():
    nodes = [f"n{i}" for i in range(8)]
    parts = empty_parts(100)  # deliberately not divisible by 8
    problem = encode_problem(empty_parts(100), parts, nodes, [], M_1P_1R,
                             PlanOptions())
    mesh = make_mesh(8)
    assign = solve_problem_sharded(mesh, problem)
    assert assign.shape[0] == 100

    counts = check_assignment(problem, assign)
    assert counts == {"duplicates": 0, "on_removed_nodes": 0,
                      "unfilled_feasible_slots": 0}

    result, warnings = decode_assignment(problem, assign, parts, [])
    assert not warnings
    loads = {}
    for p in result.values():
        for ns in p.nodes_by_state.values():
            for n in ns:
                loads[n] = loads.get(n, 0) + 1
    # 200 total assignments over 8 nodes: ideal 25 each; sharded capacity
    # splitting costs a little tightness vs single-device, bound the spread.
    assert max(loads.values()) - min(loads.values()) <= 8, loads


def test_sharded_node_removal():
    nodes = [f"n{i}" for i in range(8)]
    parts = empty_parts(64)
    problem = encode_problem(empty_parts(64), parts, nodes, [], M_1P_1R,
                             PlanOptions())
    mesh = make_mesh(8)
    assign = solve_problem_sharded(mesh, problem)
    beg, _ = decode_assignment(problem, assign, parts, [])

    problem2 = encode_problem(beg, beg, nodes, ["n0"], M_1P_1R, PlanOptions())
    assign2 = solve_problem_sharded(mesh, problem2)
    end, warnings = decode_assignment(problem2, assign2, beg, ["n0"])
    assert not warnings
    for p in end.values():
        for ns in p.nodes_by_state.values():
            assert "n0" not in ns
