"""Ports of TestPlanNextMapHierarchy, TestMultiPrimary, Test2Replicas and
TestPlanNextMapHierarchyMultiRackFailureCases (plan_test.go:2208-2863)."""

import pytest

from blance_tpu import HierarchyRule, model
from blance_tpu.testing.vis import VisCase, run_vis_cases

from conftest import planner_backends

M_1P_1R = model(primary=(0, 1), replica=(1, 1))
M_1P_2R = model(primary=(0, 1), replica=(1, 2))
M_1P_3R = model(primary=(0, 1), replica=(1, 3))
M_2P = model(primary=(0, 2))

HIERARCHY_2RACK = {
    "a": "r0", "b": "r0", "c": "r1", "d": "r1", "e": "r1",
    "r0": "z0", "r1": "z0",
}
WANT_SAME_RACK = {"replica": [HierarchyRule(include_level=1, exclude_level=0)]}
WANT_OTHER_RACK = {"replica": [HierarchyRule(include_level=2, exclude_level=1)]}


@pytest.mark.parametrize("backend", planner_backends())
def test_plan_next_map_hierarchy(backend):
    run_vis_cases(backend=backend, cases=[
        VisCase(
            about="2 racks, but nil hierarchy rules",
            from_to=[
                #     abcd
                ("", "ms  "),
                ("", "sm  "),
                ("", "  ms"),
                ("", "  sm"),
                ("", "m s "),
                ("", " m s"),
                ("", "s m "),
                ("", " s m"),
            ],
            nodes=["a", "b", "c", "d"], nodes_to_add=["a", "b", "c", "d"],
            model=M_1P_1R, node_hierarchy=HIERARCHY_2RACK,
        ),
        VisCase(
            about="2 racks, favor same rack for replica",
            from_to=[
                ("", "ms  "),
                ("", "sm  "),
                ("", "  ms"),
                ("", "  sm"),
                ("", "ms  "),
                ("", "sm  "),
                ("", "  ms"),
                ("", "  sm"),
            ],
            nodes=["a", "b", "c", "d"], nodes_to_add=["a", "b", "c", "d"],
            model=M_1P_1R, node_hierarchy=HIERARCHY_2RACK,
            hierarchy_rules=WANT_SAME_RACK,
        ),
        VisCase(
            about="2 racks, favor other rack for replica",
            from_to=[
                ("", "m s "),
                ("", " m s"),
                ("", "s m "),
                ("", " s m"),
                ("", "m  s"),
                ("", " ms "),
                ("", " sm "),
                ("", "s  m"),
            ],
            nodes=["a", "b", "c", "d"], nodes_to_add=["a", "b", "c", "d"],
            model=M_1P_1R, node_hierarchy=HIERARCHY_2RACK,
            hierarchy_rules=WANT_OTHER_RACK,
        ),
        VisCase(
            about="2 racks, add node to 2nd rack",
            from_to=[
                # abcd    abcde
                ("m s ", "s   m"),
                (" m s", " m  s"),
                ("s m ", "s m  "),
                (" s m", " s m "),
                ("m  s", "m  s "),
                (" ms ", " ms  "),
                (" sm ", " sm  "),
                ("s  m", "s  m "),
            ],
            nodes=["a", "b", "c", "d", "e"], nodes_to_add=["e"],
            model=M_1P_1R, node_hierarchy=HIERARCHY_2RACK,
            hierarchy_rules=WANT_OTHER_RACK,
        ),
        VisCase(
            about="2 racks, remove 1 node from rack 1",
            from_to=[
                # abcd    abcd
                ("m s ", "m s "),
                (" m s", "m  s"),
                ("s m ", "s m "),
                (" s m", "s  m"),
                ("m  s", "m  s"),
                (" ms ", "s m "),
                (" sm ", "s m "),
                ("s  m", "s  m"),
            ],
            nodes=["a", "b", "c", "d"], nodes_to_remove=["b"],
            model=M_1P_1R, node_hierarchy=HIERARCHY_2RACK,
            hierarchy_rules=WANT_OTHER_RACK,
        ),
    ])


@pytest.mark.parametrize("backend", planner_backends())
def test_multi_primary(backend):
    run_vis_cases(backend=backend, cases=[
        VisCase(
            about="1 node",
            from_to=[("", "m")] * 8,
            nodes=["a"], nodes_to_add=["a"], model=M_2P,
            exp_num_warnings=8,
        ),
        VisCase(
            about="4 nodes",
            from_to=[
                ("", "mm  "),
                ("", "  mm"),
            ] * 4,
            nodes=["a", "b", "c", "d"], nodes_to_add=["a", "b", "c", "d"],
            model=M_2P,
        ),
        VisCase(
            about="4 node stability",
            from_to=[
                ("mm  ", "mm  "),
                ("  mm", "  mm"),
            ] * 4,
            nodes=["a", "b", "c", "d"], nodes_to_add=["a", "b", "c", "d"],
            model=M_2P,
        ),
        # The reference Ignores its "remove 1/2 nodes" multi-primary cases:
        # the vis harness cannot express order-ambiguous [c,d]-vs-[d,c]
        # results (plan_test.go:2421-2466).  Carried forward as ignored.
        VisCase(
            ignore=True,
            about="4 node remove 1 node",
            from_to=[],
            nodes=["a", "b", "c", "d"], nodes_to_remove=["a"], model=M_2P,
        ),
    ])


@pytest.mark.parametrize("backend", planner_backends())
def test_2_replicas(backend):
    run_vis_cases(backend=backend, cases=[
        VisCase(
            about="8 partitions, 1 primary, 2 replicas, from 0 to 4 nodes",
            from_to=[
                #     a b c d
                ("", "m0s0s1  "),
                ("", "s0m0  s1"),
                ("", "s0s1m0  "),
                ("", "s0  s1m0"),
                ("", "m0s1  s0"),
                ("", "  m0s0s1"),
                ("", "s1  m0s0"),
                ("", "  s0s1m0"),
            ],
            from_to_priority=True,
            nodes=["a", "b", "c", "d"], nodes_to_add=["a", "b", "c", "d"],
            model=M_1P_2R,
        ),
        VisCase(
            about="8 partitions, reconverge 1 primary, 2 replicas, 4 to 4 nodes",
            from_to=[
                ("m0s0s1  ", "m0s0s1  "),
                ("s0m0  s1", "s0m0  s1"),
                ("s0s1m0  ", "s0s1m0  "),
                ("s1  s0m0", "s0  s1m0"),  # Flipped replicas reconverge.
                ("m0s1  s0", "m0s1  s0"),
                ("  m0s0s1", "  m0s0s1"),
                ("s1  m0s0", "s1  m0s0"),
                ("  s0s1m0", "  s0s1m0"),
            ],
            from_to_priority=True,
            nodes=["a", "b", "c", "d"], model=M_1P_2R,
        ),
        VisCase(
            about="7 partitions, 1 primary, 2 replicas, from 0 to 4 nodes",
            from_to=[
                ("", "m0s0  s1"),
                ("", "s1m0s0  "),
                ("", "s1  m0s0"),
                ("", "  s0s1m0"),
                ("", "m0  s0s1"),
                ("", "s1m0  s0"),
                ("", "s1s0m0  "),
            ],
            from_to_priority=True,
            nodes=["a", "b", "c", "d"], nodes_to_add=["a", "b", "c", "d"],
            model=M_1P_2R,
        ),
        VisCase(
            about="7 partitions, reconverge 1 primary, 2 replicas, 4 to 4 nodes",
            from_to=[
                ("m0s0  s1", "m0s0  s1"),
                ("s1m0s0  ", "s1m0s0  "),
                ("s1  m0s0", "s1  m0s0"),
                ("  s0s1m0", "  s0s1m0"),
                ("m0  s0s1", "m0  s0s1"),
                ("s1m0  s0", "s1m0  s0"),
                ("s1s0m0  ", "s1s0m0  "),
            ],
            from_to_priority=True,
            nodes=["a", "b", "c", "d"], model=M_1P_2R,
        ),
        VisCase(
            about="16 partitions, 1 primary, 2 replicas, from 0 to 4 nodes",
            from_to=[
                ("", "m0s0s1  "),
                ("", "s0m0  s1"),
                ("", "  s0m0s1"),
                ("", "s0  s1m0"),
                ("", "m0s1  s0"),
                ("", "  m0s0s1"),
                ("", "s0  m0s1"),
                ("", "  s0s1m0"),
                ("", "m0  s0s1"),
                ("", "s0m0s1  "),
                ("", "  s0m0s1"),
                ("", "s0s1  m0"),
                ("", "m0s0s1  "),
                ("", "s0m0  s1"),
                ("", "s0s1m0  "),
                ("", "s0  s1m0"),
            ],
            from_to_priority=True,
            nodes=["a", "b", "c", "d"], nodes_to_add=["a", "b", "c", "d"],
            model=M_1P_2R,
        ),
        VisCase(
            about="re-feed 16 partitions, 1 primary, 2 replicas, 4 to 4 nodes",
            from_to=[
                ("m0s0s1  ", "m0s0s1  "),
                ("s0m0  s1", "s0m0  s1"),
                ("  s0m0s1", "  s0m0s1"),
                ("s0  s1m0", "s0  s1m0"),
                ("m0s1  s0", "m0s1  s0"),
                ("  m0s0s1", "  m0s0s1"),
                ("s0  m0s1", "s0  m0s1"),
                ("  s0s1m0", "  s0s1m0"),
                ("m0  s0s1", "m0  s0s1"),
                ("s0m0s1  ", "s0m0s1  "),
                ("  s0m0s1", "  s0m0s1"),
                ("s0s1  m0", "s0s1  m0"),
                ("m0s0s1  ", "m0s0s1  "),
                ("s0m0  s1", "s0m0  s1"),
                ("s0s1m0  ", "s0s1m0  "),
                ("s0  s1m0", "s0  s1m0"),
            ],
            from_to_priority=True,
            nodes=["a", "b", "c", "d"], model=M_1P_2R,
        ),
    ])


@pytest.mark.parametrize("backend", planner_backends())
def test_hierarchy_multi_rack_failure_cases(backend):
    hierarchy_3x3 = {
        "a": "r0", "b": "r0", "c": "r0",
        "d": "r1", "e": "r1", "f": "r1",
        "g": "r2", "h": "r2", "i": "r2",
        "r0": "z0", "r1": "z0", "r2": "z0",
    }
    hierarchy_4x1 = {
        "a": "r0", "b": "r1", "c": "r2", "d": "r3",
        "r0": "z0", "r1": "z0", "r2": "z0", "r3": "z0",
    }
    hierarchy_4x1_e = dict(hierarchy_4x1, e="r0")
    hierarchy_2x2 = {
        "a": "r0", "b": "r0", "c": "r1", "d": "r1",
        "r0": "z0", "r1": "z0",
    }
    run_vis_cases(backend=backend, cases=[
        VisCase(
            about="3 racks, 3 nodes from each rack",
            from_to=[
                #     abc def ghi
                ("", "m0    s1        s0"),
                ("", "  m0    s0  s1    "),
                ("", "    m0    s0  s1  "),
                ("", "s1    m0        s0"),
                ("", "  s0    m0  s1    "),
                ("", "    s0    m0  s1  "),
                ("", "s0    s1    m0    "),
                ("", "  s0    s1    m0  "),
            ],
            from_to_priority=True,
            nodes=list("abcdefghi"),
            model=M_1P_2R, node_hierarchy=hierarchy_3x3,
            hierarchy_rules=WANT_OTHER_RACK,
        ),
        VisCase(
            about="Out of 3 racks, remove 2 racks completely",
            from_to=[
                ("m0    s1        s0", "m0s1s0"),
                ("  m0    s0  s1    ", "s0m0s1"),
                ("    m0    s0  s1  ", "s0s1m0"),
                ("s1    m0        s0", "s0s1m0"),
                ("  s0    m0  s1    ", "m0s1s0"),
                ("    s0    m0  s1  ", "s0m0s1"),
                ("s0    s1    m0    ", "s0s1m0"),
                ("  s0    s1    m0  ", "m0s1s0"),
            ],
            from_to_priority=True,
            nodes=list("abcdefghi"),
            nodes_to_remove=list("defghi"),
            model=M_1P_2R, node_hierarchy=hierarchy_3x3,
            hierarchy_rules=WANT_OTHER_RACK,
        ),
        VisCase(
            about="4 racks, 1 node on each rack",
            from_to=[
                ("", "m0s0s1s2"),
                ("", "s0m0s1s2"),
                ("", "s0s1m0s2"),
                ("", "s0s1s2m0"),
            ],
            from_to_priority=True,
            nodes=["a", "b", "c", "d"],
            model=M_1P_3R, node_hierarchy=hierarchy_4x1,
            hierarchy_rules=WANT_OTHER_RACK,
        ),
        VisCase(
            about="3 out of 4 racks down with an additional node in rack r1",
            from_to=[
                # a b c d       a        e
                ("m0s0s1s2", "m0      s0"),
                ("s0m0s1s2", "s0      m0"),
                ("s0s1m0s2", "m0      s0"),
                ("s0s1s2m0", "s0      m0"),
            ],
            from_to_priority=True,
            nodes=["a", "b", "c", "d", "e"],
            nodes_to_remove=["b", "c", "d"], nodes_to_add=["e"],
            model=M_1P_3R, node_hierarchy=hierarchy_4x1_e,
            hierarchy_rules=WANT_OTHER_RACK,
            exp_num_warnings=4,
        ),
        VisCase(
            about="2 racks, 2 nodes in each rack",
            from_to=[
                ("", "m0  s0  "),
                ("", "  m0  s0"),
                ("", "s0  m0  "),
                ("", "  s0  m0"),
            ],
            from_to_priority=True,
            nodes=["a", "b", "c", "d"],
            model=M_1P_1R, node_hierarchy=hierarchy_2x2,
            hierarchy_rules=WANT_OTHER_RACK,
        ),
        VisCase(
            about="1 rack down out of 2 racks",
            from_to=[
                ("m0  s0  ", "    m0s0"),
                ("  m0  s0", "    s0m0"),
                ("s0  m0  ", "    m0s0"),
                ("  s0  m0", "    s0m0"),
            ],
            from_to_priority=True,
            nodes=["a", "b", "c", "d"], nodes_to_remove=["a", "b"],
            model=M_1P_1R, node_hierarchy=hierarchy_2x2,
            hierarchy_rules=WANT_OTHER_RACK,
        ),
        VisCase(
            about="just 1 rack, 3 nodes",
            from_to=[
                ("", "m0s0  "),
                ("", "s0m0  "),
                ("", "s0  m0"),
                ("", "m0  s0"),
                ("", "  m0s0"),
                ("", "  s0m0"),
            ],
            from_to_priority=True,
            nodes=["a", "b", "c"],
            model=M_1P_1R,
            node_hierarchy={"a": "r0", "b": "r0", "c": "r0", "r0": "z0"},
            hierarchy_rules=WANT_OTHER_RACK,
        ),
    ])
