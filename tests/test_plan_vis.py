"""Port of TestPlanNextMapVis — flat-model golden scenarios
(plan_test.go:1746-2205)."""

import pytest

from blance_tpu import model
from blance_tpu.testing.vis import VisCase, run_vis_cases

from conftest import planner_backends

M_1P_0R = model(primary=(0, 1), replica=(1, 0))
M_1P_1R = model(primary=(0, 1), replica=(1, 1))


@pytest.mark.parametrize("backend", planner_backends())
def test_plan_next_map_vis(backend):
    run_vis_cases(backend=backend, cases=[
        VisCase(
            about="single node, simple assignment of primary",
            from_to=[("", "m"), ("", "m")],
            nodes=["a"], nodes_to_add=["a"], model=M_1P_0R,
        ),
        VisCase(
            about="added nodes a & b",
            from_to=[("", "ms"), ("", "sm")],
            nodes=["a", "b"], nodes_to_add=["a", "b"], model=M_1P_1R,
        ),
        VisCase(
            about="single node to 2 nodes",
            from_to=[("m", "sm"), ("m", "ms")],
            nodes=["a", "b"], nodes_to_add=["b"], model=M_1P_1R,
        ),
        VisCase(
            about="single node to 3 nodes",
            from_to=[("m", "sm "), ("m", "m s")],
            nodes=["a", "b", "c"], nodes_to_add=["b", "c"], model=M_1P_1R,
        ),
        VisCase(
            about="2 unbalanced nodes to balanced'ness",
            from_to=[("ms", "sm"), ("ms", "ms")],
            nodes=["a", "b"], model=M_1P_1R,
        ),
        VisCase(
            about="2 unbalanced nodes to 3 balanced nodes",
            from_to=[("ms", " sm"), ("ms", "m s")],
            nodes=["a", "b", "c"], nodes_to_add=["c"], model=M_1P_1R,
        ),
        VisCase(
            about="4 partitions, 1 to 4 nodes",
            from_to=[
                ("m", "sm  "),
                ("m", "  ms"),
                ("m", "  sm"),
                ("m", "ms  "),
            ],
            nodes=["a", "b", "c", "d"], nodes_to_add=["b", "c", "d"],
            model=M_1P_1R,
        ),
        VisCase(
            about="8 partitions, 1 to 4 nodes",
            from_to=[
                #      abcd
                ("m", "sm  "),
                ("m", "  ms"),
                ("m", "s  m"),
                ("m", " ms "),
                ("m", "  ms"),
                ("m", " s m"),
                ("m", "ms  "),
                ("m", "m s "),
            ],
            nodes=["a", "b", "c", "d"], nodes_to_add=["b", "c", "d"],
            model=M_1P_1R,
        ),
        VisCase(
            about="8 partitions, 4 nodes don't change, 1 replica moved",
            from_to=[
                # abcd    abcd
                ("sm  ", "sm  "),
                ("  ms", "  ms"),
                ("s  m", "s  m"),
                (" ms ", " ms "),
                (" sm ", "  ms"),  # Replica moved to d for balance.
                (" s m", " s m"),
                ("ms  ", "ms  "),
                ("m s ", "m s "),
            ],
            nodes=["a", "b", "c", "d"], model=M_1P_1R,
        ),
        VisCase(
            about="8 partitions, 4 nodes don't change, so no changes",
            from_to=[
                ("sm  ", "sm  "),
                ("  ms", "  ms"),
                ("s  m", "s  m"),
                (" ms ", " ms "),
                ("  ms", "  ms"),
                (" s m", " s m"),
                ("ms  ", "ms  "),
                ("m s ", "m s "),
            ],
            nodes=["a", "b", "c", "d"], model=M_1P_1R,
        ),
        VisCase(
            about="single node swap, from node b to node e",
            from_to=[
                # abcd    abcde
                (" m s", "   sm"),
                ("  ms", "  ms "),
                ("s  m", "s  m "),
                (" ms ", "  s m"),
                (" sm ", "  m s"),
                ("s  m", "s  m "),
                ("ms  ", "m   s"),
                ("m s ", "m s  "),
            ],
            nodes=["a", "b", "c", "d", "e"],
            nodes_to_remove=["b"], nodes_to_add=["e"], model=M_1P_1R,
        ),
        VisCase(
            about="4 nodes to 3 nodes, remove node d",
            from_to=[
                # abcd    abc
                (" m s", "sm "),
                ("  ms", "s m"),
                ("s  m", "m s"),
                (" ms ", " ms"),
                (" sm ", " sm"),
                ("s  m", "sm "),
                ("ms  ", "ms "),
                ("m s ", "m s"),
            ],
            nodes=["a", "b", "c", "d"], nodes_to_remove=["d"], model=M_1P_1R,
        ),
        VisCase(
            ignore=True,  # Known gap carried from the reference
            # (plan_test.go:1949-1971): shrinking constraints does not clear
            # stale replicas.
            about="change constraints from 1 replica to 0 replicas",
            from_to=[
                (" m s", " m  "),
                ("  ms", "  m "),
                ("s  m", "   m"),
                (" ms ", " m  "),
                (" sm ", "  m "),
                ("s  m", "   m"),
                ("ms  ", "m   "),
                ("m s ", "m   "),
            ],
            nodes=["a", "b", "c", "d"], model=M_1P_0R,
        ),
        VisCase(
            about="8 partitions, 1 to 8 nodes",
            from_to=[
                #      abcdefgh
                ("m", "sm      "),
                ("m", "  ms    "),
                ("m", "  sm    "),
                ("m", "    ms  "),
                ("m", "    sm  "),
                ("m", "      ms"),
                ("m", "      sm"),
                ("m", "ms      "),
            ],
            nodes=list("abcdefgh"), nodes_to_add=list("bcdefgh"),
            model=M_1P_1R,
        ),
        VisCase(
            about="8 partitions, 1 to 8 nodes, 0 replicas",
            from_to=[
                ("m", " m      "),
                ("m", "  m     "),
                ("m", "   m    "),
                ("m", "    m   "),
                ("m", "     m  "),
                ("m", "      m "),
                ("m", "       m"),
                ("m", "m       "),
            ],
            nodes=list("abcdefgh"), nodes_to_add=list("bcdefgh"),
            model=M_1P_0R,
        ),
        VisCase(
            about="8 partitions, 4 nodes, increase partition 000 weight",
            from_to=[
                # abcd    abcd
                ("sm  ", " m s"),
                ("  ms", "s m "),
                ("s  m", "s  m"),
                (" ms ", "  sm"),
                (" sm ", " sm "),
                (" s m", " s m"),
                ("ms  ", "ms  "),
                ("m s ", "m s "),
            ],
            nodes=["a", "b", "c", "d"],
            partition_weights={"000": 100}, model=M_1P_1R,
        ),
        VisCase(
            about="8 partitions, 4 nodes, increase partition 004 weight",
            from_to=[
                ("sm  ", "sm  "),
                ("  ms", "s  m"),
                ("s  m", "s  m"),
                (" ms ", " ms "),
                (" sm ", "  ms"),
                (" s m", " s m"),
                ("ms  ", "ms  "),
                ("m s ", "m s "),
            ],
            nodes=["a", "b", "c", "d"],
            partition_weights={"004": 100}, model=M_1P_1R,
        ),
        VisCase(
            about="8 partitions, 4 nodes, increase partition 000, 004 weight",
            from_to=[
                ("sm  ", " m s"),  # partition 000.
                ("  ms", " s m"),
                ("s  m", "  sm"),
                (" ms ", "m s "),
                (" sm ", "s m "),  # partition 004.
                (" s m", " s m"),
                ("ms  ", "ms  "),
                ("m s ", "m s "),
            ],
            nodes=["a", "b", "c", "d"],
            partition_weights={"000": 100, "004": 100}, model=M_1P_1R,
        ),
        VisCase(
            about="4 nodes to 3 nodes, remove node d, high stickiness",
            from_to=[
                (" m s", "sm "),
                ("  ms", "s m"),
                ("s  m", "m s"),
                (" ms ", " ms"),
                (" sm ", " sm"),
                ("s  m", "sm "),
                ("ms  ", "ms "),
                ("m s ", "m s"),
            ],
            nodes=["a", "b", "c", "d"], nodes_to_remove=["d"],
            state_stickiness={"primary": 1000000}, model=M_1P_1R,
        ),
        VisCase(
            about="3 partitions, 2 nodes add 1 node, sm first",
            from_to=[
                # ab    abc
                ("sm", "s m"),
                ("ms", "ms "),
                ("sm", " ms"),
            ],
            nodes=["a", "b", "c"], model=M_1P_1R,
        ),
        VisCase(
            about="3 partitions, 2 nodes add 1 node, ms first",
            from_to=[
                ("ms", " sm"),
                ("sm", "sm "),
                ("ms", "m s"),
            ],
            nodes=["a", "b", "c"], model=M_1P_1R,
        ),
        VisCase(
            # Known gap carried from the reference (plan_test.go:2140-2143):
            # "ISSUE: result does not have 2nd order of balance'd-ness" —
            # the golden output bakes the imperfection in.
            about="8 partitions, 2 nodes add 1 node",
            from_to=[
                ("sm", "s m"),
                ("sm", "s m"),
                ("sm", " ms"),
                ("sm", " ms"),
                ("ms", "s m"),
                ("ms", "ms "),
                ("ms", "ms "),
                ("ms", "ms "),
            ],
            nodes=["a", "b", "c"], model=M_1P_1R,
        ),
        VisCase(
            # Known gap carried from the reference (plan_test.go:2160-2162):
            # same 2nd-order balance imperfection, flipped orientation.
            about="8 partitions, 2 nodes add 1 node, flipped ms",
            from_to=[
                ("ms", " sm"),
                ("ms", " sm"),
                ("ms", "m s"),
                ("ms", "m s"),
                ("sm", " sm"),
                ("sm", "sm "),
                ("sm", "sm "),
                ("sm", "sm "),
            ],
            nodes=["a", "b", "c"], model=M_1P_1R,
        ),
        VisCase(
            # Known gap carried from the reference (plan_test.go:2181-2184):
            # "ISSUE: not enough partitions moved: c has less than a & b,
            # especially replicas; but it has some 2nd order balance'd-ness."
            about="8 partitions, 2 nodes add 1 node, interleaved m's",
            from_to=[
                ("ms", " sm"),
                ("sm", "s m"),
                ("ms", "m s"),
                ("sm", " ms"),
                ("ms", "ms "),
                ("sm", "sm "),
                ("ms", "ms "),
                ("sm", "sm "),
            ],
            nodes=["a", "b", "c"], model=M_1P_1R,
        ),
        VisCase(
            # Known gap carried from the reference (plan_test.go:2203-2206):
            # same not-enough-moved imperfection, s/m interleaving flipped.
            about="8 partitions, 2 nodes add 1 node, interleaved s'm",
            from_to=[
                ("sm", "s m"),
                ("ms", " sm"),
                ("sm", " ms"),
                ("ms", "m s"),
                ("sm", "sm "),
                ("ms", "ms "),
                ("sm", "sm "),
                ("ms", "ms "),
            ],
            nodes=["a", "b", "c"], model=M_1P_1R,
        ),
    ])
