"""Durability tier: atomic writes, the WAL, torn tails, epoch fencing,
snapshot/restore round-trips, and recovery folding (docs/DURABILITY.md).

Covers the crash-atomic write recipe (utils/atomicio.py — the one shared
copy of temp+fsync+rename with the directory fsync'd too), journal
framing/CRC/rotation, torn-tail truncation to the last valid prefix
(counted, never a crash loop), in-process and cross-process epoch
fencing, HealthTracker/SloTracker serialization round-trips (clock
re-based), and recover()'s per-tenant fold.  The end-to-end
crash-injection matrix lives in tests/test_crash.py.
"""

import json
import os
import random
import zlib

import pytest

from blance_tpu.core.types import Partition
from blance_tpu.durability.epoch import (
    EPOCH_FILE,
    EpochFence,
    fence_for,
    reset_fences,
)
from blance_tpu.durability.journal import (
    Journal,
    encode_record,
    list_segments,
    map_digest,
    read_journal,
    read_segment,
)
from blance_tpu.durability.recover import recover
from blance_tpu.obs import Recorder, use_recorder
from blance_tpu.obs.slo import SloTracker
from blance_tpu.orchestrate.health import (
    HALF_OPEN,
    HEALTHY,
    QUARANTINED,
    HealthTracker,
)
from blance_tpu.utils.atomicio import atomic_write_json, atomic_write_text


@pytest.fixture(autouse=True)
def _durability_env(monkeypatch):
    """Fast, isolated durability tests: fsync gated off (atomicity and
    rename ordering still exercised — only the disk barrier is skipped)
    and the process-level fence registry cleared between tests."""
    monkeypatch.setenv("BLANCE_WAL_FSYNC", "0")
    reset_fences()
    yield
    reset_fences()


def _pmap(d):
    return {name: Partition(name, {s: list(ns) for s, ns in nbs.items()})
            for name, nbs in d.items()}


# -- atomicio: the one copy of the crash-atomic recipe -----------------------


def test_atomic_write_text_creates_and_replaces(tmp_path):
    path = str(tmp_path / "state.json")
    atomic_write_text(path, "first")
    assert open(path).read() == "first"
    atomic_write_text(path, "second")
    assert open(path).read() == "second"
    # No temp litter: the rename consumed it.
    assert [n for n in os.listdir(tmp_path) if n.endswith(".tmp")] == []


def test_atomic_write_preserves_target_mode(tmp_path):
    """mkstemp creates 0600; the recipe must re-stamp the EXISTING
    target's mode or unprivileged readers of a world-readable
    checkpoint break after the first rewrite."""
    path = str(tmp_path / "map.json")
    atomic_write_text(path, "v1")
    os.chmod(path, 0o644)
    atomic_write_text(path, "v2")
    assert os.stat(path).st_mode & 0o777 == 0o644
    # A fresh file gets the umask default, not mkstemp's 0600.
    fresh = str(tmp_path / "fresh.json")
    atomic_write_text(fresh, "x")
    umask = os.umask(0)
    os.umask(umask)
    assert os.stat(fresh).st_mode & 0o777 == (0o666 & ~umask)


def test_atomic_write_failure_leaves_previous_file(tmp_path):
    """Any failure before the rename must leave the old bytes intact
    and unlink the temp — the previous checkpoint survives."""
    path = str(tmp_path / "snap.json")
    atomic_write_json(path, {"ok": 1})
    with pytest.raises(TypeError):
        atomic_write_json(path, {"bad": object()})
    assert json.load(open(path)) == {"ok": 1}
    assert [n for n in os.listdir(tmp_path) if n.endswith(".tmp")] == []


def test_atomic_write_json_matches_plain_dump(tmp_path):
    path = str(tmp_path / "j.json")
    obj = {"b": [1, 2], "a": {"x": None}}
    atomic_write_json(path, obj, sort_keys=True)
    assert open(path).read() == json.dumps(obj, sort_keys=True)


# -- journal framing ---------------------------------------------------------


def test_encode_record_is_canonical_and_crc_framed():
    line = encode_record(7, 2, "delta", 1.5, "t0", {"b": 1, "a": 2})
    crc_hex, payload = line[:8], line[9:-1]
    assert line.endswith("\n") and line[8] == " "
    assert int(crc_hex, 16) == zlib.crc32(payload.encode()) & 0xFFFFFFFF
    # Canonical JSON: sorted keys, no whitespace — byte-stable framing.
    assert payload == json.dumps(json.loads(payload), sort_keys=True,
                                 separators=(",", ":"))
    obj = json.loads(payload)
    assert (obj["seq"], obj["epoch"], obj["kind"], obj["tenant"]) == \
        (7, 2, "delta", "t0")


def test_map_digest_ignores_dict_order():
    a = _pmap({"p0": {"primary": ["n0"]}, "p1": {"primary": ["n1"]}})
    b = dict(reversed(list(a.items())))
    assert map_digest(a) == map_digest(b)
    c = _pmap({"p0": {"primary": ["n1"]}, "p1": {"primary": ["n1"]}})
    assert map_digest(a) != map_digest(c)


def test_journal_appends_replay_in_order(tmp_path):
    j = Journal(str(tmp_path), clock=lambda: 3.0)
    j.append("genesis", {"n": 0})
    j.append("delta", {"n": 1})
    j.append("quiesce", {"n": 2}, t=9.0)
    j.close()
    records, stats = read_journal(str(tmp_path))
    assert [r.kind for r in records] == ["genesis", "delta", "quiesce"]
    assert [r.seq for r in records] == [1, 2, 3]
    assert [r.t for r in records] == [3.0, 3.0, 9.0]
    assert stats.torn_segments == 0 and stats.stale_dropped == 0


def test_journal_rotation_is_seamless(tmp_path):
    rec = Recorder()
    with use_recorder(rec):
        j = Journal(str(tmp_path), rotate_records=2)
        for i in range(5):
            j.append("delta", {"i": i})
        j.close()
    segs = list_segments(str(tmp_path))
    assert len(segs) == 3
    # Indices globally monotone == replay order.
    assert [index for index, _epoch, _name in segs] == [1, 2, 3]
    records, _stats = read_journal(str(tmp_path))
    assert [r.data["i"] for r in records] == list(range(5))
    assert [r.seq for r in records] == [1, 2, 3, 4, 5]
    assert rec.counters["durability.segments_rotated"] == 2
    assert rec.counters["durability.journal_records"] == 5


def test_journal_bytes_are_pure_function_of_content(tmp_path):
    """Same appends => byte-identical segments — the determinism the
    committed crash traces stand on."""
    def write(d):
        j = Journal(str(d), clock=lambda: 1.0)
        j.append("genesis", {"map": {"p0": {"primary": ["a"]}}})
        j.append("delta", {"add": ["b"]})
        j.close()
        name = list_segments(str(d))[0][2]
        return open(os.path.join(str(d), name), "rb").read()

    a, b = tmp_path / "a", tmp_path / "b"
    a.mkdir(), b.mkdir()
    assert write(a) == write(b)


# -- torn tails --------------------------------------------------------------


def _seg_path(journal_dir):
    name = list_segments(journal_dir)[0][2]
    return os.path.join(journal_dir, name)


def _write_three(journal_dir):
    j = Journal(journal_dir)
    j.append("delta", {"n": 0})
    j.append("delta", {"n": 1})
    j.append("delta", {"n": 2})
    j.close()


def test_truncated_final_record_recovers_prefix(tmp_path):
    """Power loss mid-append: the half-written final record is dropped,
    replay continues from the last valid prefix, counted once."""
    _write_three(str(tmp_path))
    path = _seg_path(str(tmp_path))
    raw = open(path, "rb").read()
    open(path, "wb").write(raw[:-7])  # tear the last record mid-line
    rec = Recorder()
    with use_recorder(rec):
        records, stats = read_journal(str(tmp_path))
    assert [r.data["n"] for r in records] == [0, 1]
    assert stats.torn_segments == 1
    assert rec.counters["durability.torn_tail"] == 1


def test_missing_trailing_newline_is_torn_even_if_parseable(tmp_path):
    _write_three(str(tmp_path))
    path = _seg_path(str(tmp_path))
    raw = open(path, "rb").read()
    open(path, "wb").write(raw[:-1])  # exact bytes, newline lost
    records, stats = read_journal(str(tmp_path))
    assert [r.data["n"] for r in records] == [0, 1]
    assert stats.torn_segments == 1


def test_crc_corrupted_record_truncates_to_prefix(tmp_path):
    """A flipped bit mid-file fails the CRC; the record AND everything
    after it are dropped (prefix semantics — order is meaningless past
    a gap), and recovery still proceeds: no crash loop."""
    _write_three(str(tmp_path))
    path = _seg_path(str(tmp_path))
    lines = open(path, "rb").read().splitlines(keepends=True)
    corrupt = lines[1][:12] + b"X" + lines[1][13:]
    open(path, "wb").write(lines[0] + corrupt + lines[2])
    rec = Recorder()
    with use_recorder(rec):
        records, torn = read_segment(path)
        assert [r.data["n"] for r in records] == [0]
        assert torn
        state = recover(str(tmp_path))
    assert state.torn_segments == 1
    assert state.records_replayed == 1
    assert rec.counters["durability.torn_tail"] == 1
    assert rec.counters["durability.recoveries"] == 1


def test_empty_and_garbage_segments_do_not_block_recovery(tmp_path):
    j = Journal(str(tmp_path))
    j.append("delta", {"n": 0})
    j.close()
    open(os.path.join(str(tmp_path), "wal-000000-000002.log"),
         "wb").write(b"not a journal record at all\n")
    records, stats = read_journal(str(tmp_path))
    assert [r.data["n"] for r in records] == [0]
    assert stats.torn_segments == 1


# -- snapshots ---------------------------------------------------------------


def test_snapshot_pointer_written_after_file(tmp_path):
    j = Journal(str(tmp_path), tenant="t0", snapshot_every=2)
    assert not j.should_snapshot()
    j.append("delta", {"n": 1})
    j.append("delta", {"n": 2})
    assert j.should_snapshot()
    name = j.write_snapshot({"version": 1, "x": 42})
    assert not j.should_snapshot()  # cadence counter reset
    j.close()
    assert json.load(open(os.path.join(str(tmp_path), name)))["x"] == 42
    records, _stats = read_journal(str(tmp_path))
    assert records[-1].kind == "snapshot"
    assert records[-1].data["file"] == name
    assert records[-1].tenant == "t0"


def test_missing_snapshot_file_never_blocks_recovery(tmp_path):
    """Defense in depth: a pointer whose file is gone (or torn) is
    skipped and the fold continues from what it already has."""
    j = Journal(str(tmp_path))
    j.record_genesis(_pmap({"p0": {"primary": ["a"]}}), ["a"], [], [],
                     {}, {})
    j.append("snapshot", {"file": "snap-does-not-exist.json"})
    j.close()
    state = recover(str(tmp_path))
    t0 = state.tenants[None]
    assert sorted(t0.pmap) == ["p0"]
    assert t0.nodes == ["a"]


# -- epoch fencing -----------------------------------------------------------


def test_recover_bumps_and_persists_epoch(tmp_path):
    j = Journal(str(tmp_path))
    j.append("delta", {"n": 0})
    j.close()
    state = recover(str(tmp_path))
    assert state.epoch == 1
    assert json.load(
        open(os.path.join(str(tmp_path), EPOCH_FILE)))["epoch"] == 1
    # The persisted epoch survives a registry wipe (a "new process").
    reset_fences()
    assert fence_for(str(tmp_path)).current == 1
    state2 = recover(str(tmp_path))
    assert state2.epoch == 2


def test_in_process_zombie_append_dropped_and_counted(tmp_path):
    """A journal handle that predates a recovery shares the bumped
    fence object: every further append is dropped, counted, and
    reported False — the zombie cannot write at all."""
    rec = Recorder()
    with use_recorder(rec):
        zombie = Journal(str(tmp_path))
        assert zombie.append("delta", {"n": 0})
        recover(str(tmp_path))
        assert not zombie.append("delta", {"n": 1})
        assert not zombie.append("delta", {"n": 2})
    assert rec.counters["durability.stale_epoch_rejections"] == 2


def test_cross_process_zombie_truncated_by_fence_record(tmp_path):
    """A stale WRITER IN ANOTHER PROCESS (simulated with a private
    fence object the recovery bump cannot reach) keeps appending to its
    old segment after a recovery.  The fence record froze that
    segment's valid count, so replay truncates the zombie's appends and
    counts them — they are never part of recovered state."""
    zombie = Journal(str(tmp_path), fence=EpochFence(str(tmp_path), 0))
    zombie.append("delta", {"n": 0})
    zombie.append("delta", {"n": 1})
    recover(str(tmp_path))
    # The zombie's private fence still says epoch 0 — its appends land.
    assert zombie.append("delta", {"n": 99})
    assert zombie.append("delta", {"n": 100})
    zombie.close()
    rec = Recorder()
    with use_recorder(rec):
        records, stats = read_journal(str(tmp_path))
    assert [r.data.get("n") for r in records if r.kind != "fence"] == [0, 1]
    assert stats.stale_dropped == 2
    assert rec.counters["durability.stale_epoch_rejections"] == 2


# -- health tracker round-trip ----------------------------------------------


def test_health_round_trip_rebases_open_interval():
    t = [100.0]
    h = HealthTracker(threshold=2, probe_after_s=5.0, clock=lambda: t[0])
    h.record_failure("n1")
    h.record_failure("n1")  # trips at t=100
    t[0] = 103.0  # 3s into the open interval
    data = h.to_dict()
    # Restore onto a NEW clock whose epoch is unrelated.
    t2 = [7.0]
    h2 = HealthTracker.from_dict(data, clock=lambda: t2[0])
    assert h2.state("n1") == QUARANTINED
    assert h2.exposure_s("n1") == pytest.approx(3.0)
    # Dwell continues where the crash cut it: 2 more seconds => probe.
    t2[0] = 9.0
    assert h2.admit("n1") == "probe"
    assert h2.record_success("n1")
    assert h2.state("n1") == HEALTHY
    assert h2.exposure_s("n1") == pytest.approx(5.0)


def test_health_round_trip_drops_probe_in_flight():
    """An in-flight probe died with the old process; restoring the flag
    would wedge admission forever."""
    t = [0.0]
    h = HealthTracker(threshold=1, probe_after_s=1.0, clock=lambda: t[0])
    h.record_failure("n1")
    t[0] = 2.0
    assert h.admit("n1") == "probe"  # probe_in_flight now True
    h2 = HealthTracker.from_dict(h.to_dict(), clock=lambda: t[0])
    assert h2.state("n1") == HALF_OPEN
    assert h2.admit("n1") == "probe"  # fresh probe re-admitted


def test_health_round_trip_property():
    """Seeded random walks: after any prefix of breaker events, a
    to_dict/from_dict round-trip onto a shifted clock preserves every
    observable — states, exposures, trip counts — exactly."""
    for seed in range(8):
        rng = random.Random(seed)
        t = [0.0]
        h = HealthTracker(threshold=rng.randint(1, 3),
                          probe_after_s=rng.uniform(0.5, 3.0),
                          clock=lambda: t[0])
        nodes = ["a", "b", "c"]
        for _ in range(40):
            t[0] += rng.uniform(0.0, 2.0)
            node = rng.choice(nodes)
            op = rng.random()
            if op < 0.45:
                h.record_failure(node)
            elif op < 0.75:
                h.record_success(node)
            else:
                h.admit(node)
        shift = rng.uniform(-50.0, 50.0)
        t2 = [t[0] + shift]
        h2 = HealthTracker.from_dict(h.to_dict(), clock=lambda: t2[0])
        assert {n: h2.state(n) for n in nodes} == \
            {n: h.state(n) for n in nodes}
        assert h2.total_trips() == h.total_trips()
        for n in nodes:
            assert h2.exposure_s(n) == pytest.approx(h.exposure_s(n))
        # Double round-trip is exact (ages of ages).
        h3 = HealthTracker.from_dict(h2.to_dict(), clock=lambda: t2[0])
        assert h3.to_dict() == h2.to_dict()


def test_health_from_dict_refuses_other_versions():
    with pytest.raises(ValueError):
        HealthTracker.from_dict({"version": 99, "threshold": 1,
                                 "probe_after_s": 1.0, "nodes": {}})


# -- slo tracker round-trip --------------------------------------------------


class _Mv:
    def __init__(self, partition, node, state, op):
        self.partition, self.node = partition, node
        self.state, self.op = state, op


def test_slo_round_trip_preserves_account():
    t = [0.0]
    pmap = _pmap({"p0": {"primary": ["a"]}, "p1": {"primary": ["b"]}})
    slo = SloTracker(pmap, clock=lambda: t[0], availability_floor=0.5,
                     publish_gauges=False)
    slo.set_min_moves(2)
    t[0] = 1.0
    slo.on_batch("b", [_Mv("p0", "b", "primary", "add")], True, t[0])
    t[0] = 2.0
    slo.on_batch("a", [_Mv("p0", "a", "", "del")], True, t[0])
    t[0] = 5.0
    data = slo.to_dict()
    t2 = [1000.0]
    slo2 = SloTracker.from_dict(data, clock=lambda: t2[0],
                                publish_gauges=False)
    s1, s2 = slo.summary(), slo2.summary()
    assert s2.availability == s1.availability
    assert s2.moves_executed == s1.moves_executed
    assert s2.churn_ratio == s1.churn_ratio
    assert s2.convergence_lag_s == pytest.approx(s1.convergence_lag_s)
    assert s2.time_weighted_availability == \
        pytest.approx(s1.time_weighted_availability)
    # The horizon keeps integrating seamlessly on the new clock.
    t2[0] = 1010.0
    assert slo2.time_weighted_availability(t2[0]) == \
        pytest.approx(slo.time_weighted_availability(15.0))


def test_slo_from_dict_refuses_other_versions():
    with pytest.raises(ValueError):
        SloTracker.from_dict({"version": 0})


# -- recovery folding --------------------------------------------------------


def test_recover_folds_membership_and_batches(tmp_path):
    j = Journal(str(tmp_path), clock=lambda: 0.0)
    j.record_genesis(
        _pmap({"p0": {"primary": ["a"]}, "p1": {"primary": ["b"]}}),
        ["a", "b"], [], [], {"p0": 1, "p1": 1}, {"a": 1, "b": 1})

    class _Delta:
        add, remove, fail = ("c",), (), ("b",)
        partition_weights, node_weights = {"p0": 3}, None

    j.record_delta(_Delta())
    j.record_strip(["b"])
    j.on_batch("c", [_Mv("p1", "c", "primary", "add")], True, 4.0)
    j.on_batch("c", [_Mv("p0", "c", "primary", "add")], False, 5.0)
    j.record_quiesce_map(_pmap({"p0": {"primary": ["a"]},
                                "p1": {"primary": ["c"]}}))
    j.close()
    state = recover(str(tmp_path))
    t0 = state.tenants[None]
    assert t0.nodes == ["a", "b", "c"]
    assert t0.failed == {"b"} and t0.removing == set()
    assert t0.pweights == {"p0": 3, "p1": 1}
    # Strip removed b; the ok batch landed p1 on c; the failed batch
    # did NOT mutate the map.
    nbs = {name: p.nodes_by_state for name, p in t0.pmap.items()}
    assert nbs["p0"] == {"primary": ["a"]}
    assert nbs["p1"] == {"primary": ["c"]}
    assert t0.quiesced


def test_recover_genesis_resets_prior_epoch_state(tmp_path):
    """A resumed controller writes a fresh genesis — replay must treat
    it as a full reset so every epoch's journal is self-contained."""
    j = Journal(str(tmp_path))
    j.record_genesis(_pmap({"p0": {"primary": ["a"]}}), ["a"], ["a"], [],
                     {}, {})
    j.close()
    state = recover(str(tmp_path))
    j2 = state.journal
    j2.record_genesis(_pmap({"p0": {"primary": ["b"]}}), ["b"], [], [],
                      {}, {})
    j2.close()
    state2 = recover(str(tmp_path))
    t0 = state2.tenants[None]
    assert t0.nodes == ["b"]
    assert t0.removing == set()
    assert t0.pmap["p0"].nodes_by_state == {"primary": ["b"]}


def test_recover_groups_tenant_tagged_records(tmp_path):
    j = Journal(str(tmp_path))
    va, vb = j.for_tenant("ta"), j.for_tenant("tb")
    va.record_genesis(_pmap({"p0": {"primary": ["a"]}}), ["a"], [], [],
                      {}, {})
    vb.record_genesis(_pmap({"q0": {"primary": ["b"]}}), ["b"], [], [],
                      {}, {})
    j.append("fleet", {"event": "add_tenant", "tenant": "ta"})
    j.close()
    state = recover(str(tmp_path))
    assert sorted(k for k in state.tenants if k is not None) == \
        ["ta", "tb"]
    assert sorted(state.tenants["ta"].pmap) == ["p0"]
    assert sorted(state.tenants["tb"].pmap) == ["q0"]


def test_recover_counts_and_successor_seq(tmp_path):
    rec = Recorder()
    with use_recorder(rec):
        j = Journal(str(tmp_path))
        for i in range(4):
            j.append("delta", {"i": i})
        j.close()
        state = recover(str(tmp_path))
    assert state.records_replayed == 4
    # Successor seq continues after the replayed stream (fence record
    # consumed next_seq=5).
    assert state.next_seq == 6
    assert rec.counters["durability.recoveries"] == 1
    assert rec.counters["durability.replayed_records"] == 4
