"""Tensor (TPU-backend) planner tests: constraint satisfaction, balance
quality vs the greedy oracle, stickiness, weights, hierarchy rules.

The tensor backend is NOT bit-identical to the greedy planner (it solves
globally instead of sequentially); these tests assert the contract that
matters: zero hard violations, balance at least comparable to greedy, low
churn under stickiness, and rack-rule satisfaction when feasible.
"""

import numpy as np
import pytest

from blance_tpu import HierarchyRule, Partition, PlanOptions, model, plan_next_map
from blance_tpu.core.encode import encode_problem
from blance_tpu.plan.tensor import check_assignment, plan_next_map_tpu

M_1P_1R = model(primary=(0, 1), replica=(1, 1))
M_1P_2R = model(primary=(0, 1), replica=(1, 2))


def empty_parts(n):
    return {str(i): Partition(str(i), {}) for i in range(n)}


def node_loads(pmap, state=None):
    loads = {}
    for p in pmap.values():
        for s, ns in p.nodes_by_state.items():
            if state is not None and s != state:
                continue
            for n in ns:
                loads[n] = loads.get(n, 0) + 1
    return loads


def no_hard_violations(pmap, model_, nodes_valid):
    for p in pmap.values():
        seen = []
        for s, ns in p.nodes_by_state.items():
            for n in ns:
                assert n in nodes_valid, f"{p.name}: {n} not a valid node"
                seen.append(n)
        assert len(seen) == len(set(seen)), \
            f"{p.name}: node holds multiple states: {p.nodes_by_state}"


def test_fresh_assignment_balanced():
    nodes = [f"n{i}" for i in range(8)]
    result, warnings = plan_next_map(
        empty_parts(64), empty_parts(64), nodes, [], nodes, M_1P_1R,
        backend="tpu")
    assert not warnings
    no_hard_violations(result, M_1P_1R, set(nodes))
    for state in ("primary", "replica"):
        loads = node_loads(result, state)
        assert set(loads) == set(nodes)
        assert max(loads.values()) - min(loads.values()) <= 2, (state, loads)


def test_matches_greedy_balance_quality():
    nodes = [f"n{i}" for i in range(16)]
    parts = empty_parts(256)
    greedy, _ = plan_next_map(
        empty_parts(256), parts, nodes, [], nodes, M_1P_2R, backend="greedy")
    tpu, warnings = plan_next_map(
        empty_parts(256), parts, nodes, [], nodes, M_1P_2R, backend="tpu")
    assert not warnings
    no_hard_violations(tpu, M_1P_2R, set(nodes))

    g_loads = node_loads(greedy)
    t_loads = node_loads(tpu)
    g_spread = max(g_loads.values()) - min(g_loads.values())
    t_spread = max(t_loads.values()) - min(t_loads.values())
    assert t_spread <= g_spread + 2, (t_spread, g_spread)


def test_node_removal_sticky_and_clean():
    nodes = [f"n{i}" for i in range(8)]
    beg, _ = plan_next_map(
        empty_parts(64), empty_parts(64), nodes, [], nodes, M_1P_1R,
        backend="tpu")
    end, warnings = plan_next_map(
        beg, beg, nodes, ["n7"], [], M_1P_1R, backend="tpu")
    assert not warnings
    no_hard_violations(end, M_1P_1R, set(nodes[:7]))

    # Stickiness: partitions not touching n7 should not move at all.
    moved = 0
    for name, p in beg.items():
        touched = any("n7" in ns for ns in p.nodes_by_state.values())
        if not touched and end[name].nodes_by_state != p.nodes_by_state:
            moved += 1
    assert moved <= 64 * 0.15, f"{moved} untouched partitions moved"

    loads = node_loads(end)
    assert max(loads.values()) - min(loads.values()) <= 4, loads


def test_partition_and_node_weights():
    nodes = ["a", "b", "c", "d"]
    m = model(primary=(0, 1))
    result, warnings = plan_next_map(
        empty_parts(40), empty_parts(40), nodes, [], nodes, m,
        PlanOptions(node_weights={"a": 3}), backend="tpu")
    assert not warnings
    loads = node_loads(result, "primary")
    # Node a (weight 3) should carry roughly 3x a weight-1 node.
    others = [loads.get(n, 0) for n in ("b", "c", "d")]
    assert loads["a"] >= 2 * min(others), loads


def test_hierarchy_other_rack_rule():
    nodes = ["a", "b", "c", "d", "e", "f"]
    hierarchy = {"a": "r0", "b": "r0", "c": "r1", "d": "r1",
                 "e": "r2", "f": "r2",
                 "r0": "z0", "r1": "z0", "r2": "z0"}
    rules = {"replica": [HierarchyRule(include_level=2, exclude_level=1)]}
    result, warnings = plan_next_map(
        empty_parts(48), empty_parts(48), nodes, [], nodes, M_1P_1R,
        PlanOptions(node_hierarchy=hierarchy, hierarchy_rules=rules),
        backend="tpu")
    assert not warnings
    no_hard_violations(result, M_1P_1R, set(nodes))
    rack = {"a": 0, "b": 0, "c": 1, "d": 1, "e": 2, "f": 2}
    for p in result.values():
        primary = p.nodes_by_state["primary"][0]
        for rep in p.nodes_by_state["replica"]:
            assert rack[rep] != rack[primary], \
                f"{p.name}: replica {rep} same rack as primary {primary}"


@pytest.mark.parametrize("backend", ["greedy", "tpu"])
def test_hierarchy_replica_pair_anti_affinity(backend):
    """Two replicas under (include 2, exclude 1) must land on two DIFFERENT
    racks — each pick anchors on the primary plus all picks so far
    (reference plan.go:185-191), not just the primary.  Round-1 regression:
    the tpu backend co-racked 5/12 replica pairs here."""
    nodes = [f"n{i}" for i in range(9)]
    hierarchy = {n: f"r{i % 3}" for i, n in enumerate(nodes)}
    hierarchy.update({f"r{i}": "z0" for i in range(3)})
    rack = {n: hierarchy[n] for n in nodes}
    rules = {"replica": [HierarchyRule(include_level=2, exclude_level=1)]}
    result, warnings = plan_next_map(
        empty_parts(12), empty_parts(12), nodes, [], nodes, M_1P_2R,
        PlanOptions(node_hierarchy=hierarchy, hierarchy_rules=rules),
        backend=backend)
    assert not warnings
    no_hard_violations(result, M_1P_2R, set(nodes))
    for p in result.values():
        primary = p.nodes_by_state["primary"][0]
        reps = p.nodes_by_state["replica"]
        assert len(reps) == 2, (p.name, reps)
        racks = [rack[primary]] + [rack[r] for r in reps]
        assert len(set(racks)) == 3, \
            f"{p.name}: co-racked copies {p.nodes_by_state} ({racks})"


def test_hierarchy_replica_pair_unsticks_from_co_rack():
    """A prev map with both replicas co-racked must be repaired, not
    pinned in place: stickiness may keep ONE replica on its rack, the
    other must move to the free rack."""
    nodes = [f"n{i}" for i in range(9)]
    hierarchy = {n: f"r{i % 3}" for i, n in enumerate(nodes)}
    hierarchy.update({f"r{i}": "z0" for i in range(3)})
    rack = {n: hierarchy[n] for n in nodes}
    rules = {"replica": [HierarchyRule(include_level=2, exclude_level=1)]}
    # Primary on rack 0; both replicas on rack 1 (n1, n4).
    prev = {str(i): Partition(str(i), {"primary": ["n0"],
                                       "replica": ["n1", "n4"]})
            for i in range(6)}
    result, warnings = plan_next_map(
        prev, prev, nodes, [], [], M_1P_2R,
        PlanOptions(node_hierarchy=hierarchy, hierarchy_rules=rules),
        backend="tpu")
    assert not warnings
    no_hard_violations(result, M_1P_2R, set(nodes))
    for p in result.values():
        racks = [rack[p.nodes_by_state["primary"][0]]] + \
            [rack[r] for r in p.nodes_by_state["replica"]]
        assert len(set(racks)) == 3, (p.name, p.nodes_by_state, racks)


def test_hierarchy_rule_unmeetable_falls_back_flat():
    # Single rack: other-rack rule unmeetable -> still assigns (flat).
    nodes = ["a", "b", "c"]
    hierarchy = {"a": "r0", "b": "r0", "c": "r0", "r0": "z0"}
    rules = {"replica": [HierarchyRule(include_level=2, exclude_level=1)]}
    result, warnings = plan_next_map(
        empty_parts(12), empty_parts(12), nodes, [], nodes, M_1P_1R,
        PlanOptions(node_hierarchy=hierarchy, hierarchy_rules=rules),
        backend="tpu")
    assert not warnings
    no_hard_violations(result, M_1P_1R, set(nodes))
    for p in result.values():
        assert len(p.nodes_by_state["replica"]) == 1


def test_custom_hooks_fall_back_to_exact(monkeypatch):
    """A custom node_scorer (or non-cbgt booster) can't run inside the
    jitted score; tpu/auto must fall back to the exact path and match the
    greedy golden output instead of silently dropping the policy
    (reference contract: plan.go:580,693-697)."""
    from blance_tpu.plan import api as plan_api

    def prefer_c(ctx, node):
        from blance_tpu.plan.greedy import default_node_score
        r = default_node_score(ctx, node)
        return r - 100.0 if node == "c" else r

    nodes = ["a", "b", "c", "d"]
    parts = empty_parts(16)
    opts = PlanOptions(node_scorer=prefer_c)
    golden, gw = plan_next_map(
        empty_parts(16), parts, nodes, [], nodes, M_1P_1R, opts,
        backend="greedy")
    # Sanity: the hook actually bit — every primary pinned to c.
    assert all(p.nodes_by_state["primary"] == ["c"] for p in golden.values())

    # Direct tpu call and an auto call routed above the size threshold.
    monkeypatch.setattr(plan_api, "_AUTO_TPU_THRESHOLD", 1)
    for backend in ("tpu", "auto"):
        got, w = plan_next_map(
            empty_parts(16), parts, nodes, [], nodes, M_1P_1R, opts,
            backend=backend)
        assert got == golden, backend
        assert w == gw, backend

    # Non-cbgt booster likewise falls back and matches greedy.
    opts2 = PlanOptions(node_weights={"a": -2},
                        node_score_booster=lambda w, s: -50.0)
    golden2, _ = plan_next_map(
        empty_parts(16), parts, nodes, [], nodes, M_1P_1R, opts2,
        backend="greedy")
    got2, _ = plan_next_map(
        empty_parts(16), parts, nodes, [], nodes, M_1P_1R, opts2,
        backend="tpu")
    assert got2 == golden2

    # Negative weight with NO booster: reference ignores it — the device
    # score would pin it, so this too must take the exact path.
    opts3 = PlanOptions(node_weights={"a": -2})
    golden3, _ = plan_next_map(
        empty_parts(16), parts, nodes, [], nodes, M_1P_1R, opts3,
        backend="greedy")
    got3, _ = plan_next_map(
        empty_parts(16), parts, nodes, [], nodes, M_1P_1R, opts3,
        backend="tpu")
    assert got3 == golden3


def test_too_few_nodes_warns():
    result, warnings = plan_next_map(
        empty_parts(4), empty_parts(4), ["a"], [], ["a"], M_1P_1R,
        backend="tpu")
    # 1 node: primary fills, replica can't (same-partition exclusivity).
    assert len(warnings) == 4
    for p in result.values():
        assert p.nodes_by_state["primary"] == ["a"]
        assert p.nodes_by_state["replica"] == []


def test_check_assignment_clean():
    nodes = [f"n{i}" for i in range(8)]
    parts = empty_parts(64)
    problem = encode_problem(
        empty_parts(64), parts, nodes, [], M_1P_2R, PlanOptions())
    result, _ = plan_next_map_tpu(
        empty_parts(64), parts, nodes, [], nodes, M_1P_2R)
    # Re-encode the result to run the checker.
    assign = np.full((problem.P, problem.S, max(problem.R, 2)), -1, np.int32)
    nidx = {n: i for i, n in enumerate(nodes)}
    sidx = {s: i for i, s in enumerate(problem.states)}
    for pi, pname in enumerate(problem.partitions):
        for s, ns in result[pname].nodes_by_state.items():
            for ri, node in enumerate(ns):
                assign[pi, sidx[s], ri] = nidx[node]
    counts = check_assignment(problem, assign)
    assert counts == {"duplicates": 0, "on_removed_nodes": 0,
                      "unfilled_feasible_slots": 0,
                      "hierarchy_misses": 0}


def test_check_assignment_counts_crafted_violations():
    """Vectorized checker vs hand-counted violations on a crafted array."""
    from blance_tpu.core.types import PlanOptions as PO
    nodes = ["a", "b", "c", "d"]
    parts = empty_parts(3)
    problem = encode_problem({}, parts, nodes, ["d"], M_1P_2R, PO())
    assert problem.R >= 2
    assign = np.full((3, 2, problem.R), -1, np.int32)
    # p0: primary a, replicas a+b -> 1 duplicate.
    assign[0, 0, 0] = 0
    assign[0, 1, 0] = 0
    assign[0, 1, 1] = 1
    # p1: primary on removed d, replicas b,c -> 1 on_removed.
    assign[1, 0, 0] = 3
    assign[1, 1, 0] = 1
    assign[1, 1, 1] = 2
    # p2: primary a, only one replica though 3 valid nodes -> 1 shortfall.
    assign[2, 0, 0] = 0
    assign[2, 1, 0] = 1
    counts = check_assignment(problem, assign)
    assert counts == {"duplicates": 1, "on_removed_nodes": 1,
                      "unfilled_feasible_slots": 1,
                      "hierarchy_misses": 0}, counts


def test_validation_gate_catches_broken_solver(monkeypatch):
    """A deliberately-broken solve must fail through the production
    validation gate (warnings by default at small P), not silently ship a
    violating map."""
    import warnings as w

    from blance_tpu.plan import tensor as T

    def broken_solve(prev, *args, **kwargs):
        out = np.zeros(prev.shape, np.int32)  # everyone on node 0
        return out

    monkeypatch.setattr(T, "solve_dense_converged", broken_solve)
    nodes = [f"n{i}" for i in range(4)]
    with pytest.warns(UserWarning, match="constraint-violating"):
        T.plan_next_map_tpu(
            empty_parts(8), empty_parts(8), nodes, [], nodes, M_1P_1R)

    # And the clean path stays silent.
    monkeypatch.undo()
    with w.catch_warnings():
        w.simplefilter("error")
        T.plan_next_map_tpu(
            empty_parts(8), empty_parts(8), nodes, [], nodes, M_1P_1R)


def _rack_setup(N=10, rack_size=2):
    """Nodes on racks of ``rack_size``, replica rule (zone=2, rack=1)."""
    from blance_tpu import HierarchyRule

    nodes = [f"n{i}" for i in range(N)]
    hier = {n: f"r{i // rack_size}" for i, n in enumerate(nodes)}
    hier.update({f"r{i}": "z0"
                 for i in range((N + rack_size - 1) // rack_size)})
    opts = PlanOptions(node_hierarchy=hier,
                       hierarchy_rules={"replica": [HierarchyRule(2, 1)]})
    return nodes, opts


def test_hier_misses_counted_on_crafted_assignment():
    """check_assignment counts a copy at a worse tier than an open valid
    node could achieve — and does NOT count unmeetable rules."""
    nodes, opts = _rack_setup(N=8, rack_size=2)  # racks r0..r3 of 2
    parts = empty_parts(2)
    problem = encode_problem({}, parts, nodes, [], M_1P_2R, opts)
    assert problem.rules  # replica state carries the (2, 1) rule
    assign = np.full((2, 2, problem.R), -1, np.int32)
    # p0: primary n0 (r0); replicas n2 (r1), n4 (r2) — conformant.
    assign[0, 0, 0], assign[0, 1, 0], assign[0, 1, 1] = 0, 2, 4
    # p1: primary n0 (r0); replica 0 on n1 (SAME rack r0) while racks
    # r1..r3 had open nodes -> 1 feasible miss; replica 1 on n3 (r1) ok.
    assign[1, 0, 0], assign[1, 1, 0], assign[1, 1, 1] = 0, 1, 3
    counts = check_assignment(problem, assign)
    assert counts["hierarchy_misses"] == 1, counts
    assert counts["duplicates"] == 0

    # Unmeetable: only 2 racks for primary + 2 replicas pairwise-spread —
    # the flat fallback is correct behavior, not a miss.
    nodes4, opts4 = _rack_setup(N=4, rack_size=2)  # racks r0, r1 only
    p4 = encode_problem({}, empty_parts(1), nodes4, [], M_1P_2R, opts4)
    a4 = np.full((1, 2, p4.R), -1, np.int32)
    a4[0, 0, 0], a4[0, 1, 0], a4[0, 1, 1] = 0, 2, 3  # r1 twice: no choice
    assert check_assignment(p4, a4)["hierarchy_misses"] == 0


@pytest.mark.parametrize("seed", range(6))
def test_hier_floor_counts_matches_matrix(seed):
    """The group-counting pin floor must equal the [P, N] penalty matrix
    row-min over valid nodes for nested (exc < inc) rules, across random
    hierarchies, anchor sets, and validity masks."""
    import jax.numpy as jnp

    from blance_tpu.plan.tensor import (
        _INF, _RULE_MISS, _hier_floor_counts, _hier_penalty, _hier_tier_at)

    rng = np.random.default_rng(seed)
    N = int(rng.integers(4, 30))
    P = int(rng.integers(2, 40))
    A = int(rng.integers(1, 4))
    # True tree nesting with MULTIPLE zones each holding multi-node racks
    # — the shape where a cross-include-group count leak would show.
    racks = int(rng.integers(2, 7))
    zones = int(rng.integers(2, 4))
    rack_of = rng.integers(0, racks, N).astype(np.int32)
    zone_of_rack = rng.integers(0, zones, racks).astype(np.int32)
    gids = np.stack([
        np.arange(N, dtype=np.int32),
        rack_of,
        zone_of_rack[rack_of],
    ])
    gid_valid = rng.random((3, N)) < 0.9
    valid = rng.random(N) < 0.8
    anchors = rng.integers(-1, N, (P, A)).astype(np.int32)
    rules = ((2, 1), (1, 0)) if rng.random() < 0.5 else ((2, 1),)

    pen = np.asarray(_hier_penalty(
        jnp.asarray(anchors), jnp.asarray(gids), jnp.asarray(gid_valid),
        rules))
    floor_matrix = np.where(valid[None, :], pen, _INF).min(axis=1)
    floor_counts = np.asarray(_hier_floor_counts(
        jnp.asarray(anchors), jnp.asarray(gids), jnp.asarray(gid_valid),
        jnp.asarray(valid), rules))
    # The two encodings agree except the no-valid-node corner, where the
    # matrix says +INF and the counts say RULE_MISS — both compare
    # identically in the pin test (see _hier_floor_counts docstring).
    fm = np.minimum(floor_matrix, _RULE_MISS)
    assert np.array_equal(fm, floor_counts), (fm, floor_counts)

    # And the single-column tier evaluator matches the matrix column.
    node = rng.integers(0, N, P).astype(np.int32)
    at = np.asarray(_hier_tier_at(
        jnp.asarray(anchors), jnp.asarray(node), jnp.asarray(gids),
        jnp.asarray(gid_valid), rules))
    assert np.array_equal(at, pen[np.arange(P), node])


def test_auto_routing_at_real_threshold():
    """backend="auto" with the REAL threshold (no monkeypatch): below
    256Ki cells it must take the exact native path (bit-identical to
    greedy), at/above it the batched tpu path — and both land at the
    same contract on a realistic rebalance."""
    from blance_tpu.plan.api import _AUTO_TPU_THRESHOLD

    nodes = [f"n{i}" for i in range(8)]
    parts = empty_parts(1024)  # 1024 x 8 cells: well below the threshold
    assert len(parts) * len(nodes) < _AUTO_TPU_THRESHOLD
    golden, gw = plan_next_map(parts, parts, nodes, [], nodes, M_1P_1R,
                               backend="greedy")
    got, w = plan_next_map(parts, parts, nodes, [], nodes, M_1P_1R,
                           backend="auto")
    assert got == golden and w == gw  # exact path, bit-identical

    # At the threshold boundary: 4096 x 64 = exactly 256Ki -> tpu path.
    nodes_big = [f"n{i}" for i in range(64)]
    parts_big = empty_parts(4096)
    assert len(parts_big) * len(nodes_big) >= _AUTO_TPU_THRESHOLD
    got_big, w_big = plan_next_map(
        parts_big, parts_big, nodes_big, [], nodes_big, M_1P_1R,
        backend="auto")
    assert not w_big
    loads = {}
    for p in got_big.values():
        for ns in p.nodes_by_state.values():
            for n in ns:
                loads[n] = loads.get(n, 0) + 1
    assert len(loads) == 64
    assert max(loads.values()) - min(loads.values()) <= 8, loads


def test_primary_state_rules_no_false_misses():
    """Rules on state 0 anchor on the PREVIOUS primary (the solver's
    top_anchor), never on the node being judged — a correct fresh solve
    must pass the gate silently (regression: self-anchoring made the
    exclude test unsatisfiable by one's own node and flagged every
    partition)."""
    import warnings as w

    from blance_tpu import HierarchyRule

    nodes = [f"n{i}" for i in range(8)]
    hier = {n: f"r{i // 2}" for i, n in enumerate(nodes)}
    hier.update({f"r{i}": "z0" for i in range(4)})
    opts = PlanOptions(node_hierarchy=hier,
                       hierarchy_rules={"primary": [HierarchyRule(2, 1)]})
    parts = empty_parts(16)
    with w.catch_warnings():
        w.simplefilter("error")
        result, _ = plan_next_map_tpu({}, parts, nodes, [], nodes,
                                      M_1P_1R, opts)
    assert all(p.nodes_by_state["primary"] for p in result.values())


def test_validation_gate_catches_broken_hier_penalty(monkeypatch):
    """A deliberately-broken _hier_penalty must fail through the
    production gate (maybe_validate's warning), not a bespoke assert —
    the always-on detector for the solver's subtlest area."""
    import jax.numpy as jnp

    from blance_tpu.plan import tensor as T

    def no_penalty(anchors, gids, gid_valid, rules, gids_cand=None):
        cols = (gids_cand if gids_cand is not None else gids).shape[1]
        return jnp.zeros((anchors.shape[0], cols), jnp.float32)

    monkeypatch.setattr(T, "_hier_penalty", no_penalty)
    # Distinctive P so the jitted solve retraces with the broken penalty
    # instead of reusing a cached executable.
    nodes, opts = _rack_setup(N=10, rack_size=2)
    with pytest.warns(UserWarning, match="constraint-violating"):
        result, _ = T.plan_next_map_tpu(
            empty_parts(23), empty_parts(23), nodes, [], nodes,
            M_1P_2R, opts)

    # The honest solver stays silent — at ANOTHER distinctive P, because
    # the jit cache still holds the broken-penalty executable for P=23
    # even after monkeypatch.undo.
    monkeypatch.undo()
    import warnings as w
    with w.catch_warnings():
        w.simplefilter("error")
        T.plan_next_map_tpu(
            empty_parts(29), empty_parts(29), nodes, [], nodes,
            M_1P_2R, opts)


def test_tier_band_scale_guard_trips_on_extreme_p_over_n():
    """The tier-equality band assumes within-tier score terms stay far
    below _RULE_TIER; at extreme partitions-per-node ratios the fill
    term crosses the band and the solve must refuse loudly instead of
    silently misclassifying hierarchy tiers."""
    from blance_tpu.plan import tensor as T

    P, N = 20_000, 2
    prev = np.full((P, 1, 1), -1, np.int32)
    pweights = np.ones(P, np.float32)
    nweights = np.ones(N, np.float32)
    valid = np.ones(N, bool)
    stickiness = np.full((P, 1), 1.5, np.float32)
    gids = np.stack([np.arange(N, dtype=np.int32),
                     np.zeros(N, np.int32)])
    gid_valid = np.ones((2, N), bool)
    with pytest.raises(ValueError, match="tier band"):
        T.solve_dense_converged(
            prev, pweights, nweights, valid, stickiness, gids, gid_valid,
            (1,), (((1, 0),),))
    # Rule-less problems never consult the band: the guard is a no-op.
    T._check_tier_band_scale(
        prev, pweights, nweights, valid, stickiness, (1,), ((),))


def test_degenerate_empty_partitions():
    # P == 0 must not crash the vectorized decode (tensor.py routes it there).
    result, warnings = plan_next_map(
        {}, {}, ["a", "b"], [], [], M_1P_1R, backend="tpu")
    assert result == {} and warnings == {}


def test_degenerate_zero_nodes():
    # N == 0 with P > 0: empty assignments plus a shortfall warning per state.
    parts = empty_parts(3)
    result, warnings = plan_next_map(
        empty_parts(3), parts, [], [], [], M_1P_1R, backend="tpu")
    for p in result.values():
        assert p.nodes_by_state == {"primary": [], "replica": []}
    assert all(len(w) == 2 for w in warnings.values())
    assert len(warnings) == 3


def test_delta_rebalance_zero_stray_churn():
    """Pin-first warm start: removing a node must not move any primary that
    wasn't displaced, and every kept placement stays rule-conformant
    (multi-primary + rack rules — the shape where price dynamics alone
    leaked ~2% stray churn)."""
    import blance_tpu as bt

    model = bt.model(primary=(0, 2), replica=(1, 1))
    nodes = [f"n{i}" for i in range(16)]
    parts = {str(i): bt.Partition(str(i), {}) for i in range(256)}
    hier = {n: f"r{i % 4}" for i, n in enumerate(nodes)}
    hier.update({f"r{i}": "z" for i in range(4)})
    opts = bt.PlanOptions(node_hierarchy=hier,
                          hierarchy_rules={"replica": [bt.HierarchyRule(2, 1)]})

    m1, _ = bt.plan_next_map(parts, parts, nodes, [], nodes, model, opts,
                             backend="tpu")
    m2, _ = bt.plan_next_map(m1, m1, nodes, ["n3"], [], model, opts,
                             backend="tpu")
    stray = 0
    for name, p in m2.items():
        before = m1[name].nodes_by_state
        after = p.nodes_by_state
        assert "n3" not in after["primary"] + after["replica"]
        touched = "n3" in before["primary"] + before["replica"]
        if touched:
            # Only the displaced copy changes: one new node in, n3 out,
            # everything else kept (ordinals may rotate — a surviving
            # sticky copy promoting to slot 0 is not churn).
            lost = set(before["primary"]) - set(after["primary"])
            if "n3" in before["primary"]:
                assert lost == {"n3"}, (name, before, after)
        elif after["primary"] != before["primary"]:
            stray += 1
    assert stray == 0, f"{stray} primaries moved without being displaced"
    # Rack rule holds everywhere after the rebalance.
    for p in m2.values():
        prim_rack = hier[p.nodes_by_state["primary"][0]]
        for node in p.nodes_by_state["replica"]:
            assert hier[node] != prim_rack


def test_pin_does_not_freeze_fallback_tier():
    """A placement that only satisfies a fallback hierarchy rule must not
    stay pinned when the preferred tier is attainable: constrained-period
    degradations heal on the next rebalance (greedy-oracle behavior)."""
    import blance_tpu as bt

    model = bt.model(primary=(0, 1), replica=(1, 1))
    # Two racks of 2; rules prefer same-rack replica, fall back cross-rack.
    nodes = ["a0", "a1", "b0", "b1"]
    hier = {"a0": "ra", "a1": "ra", "b0": "rb", "b1": "rb",
            "ra": "z", "rb": "z"}
    opts = bt.PlanOptions(
        node_hierarchy=hier,
        hierarchy_rules={"replica": [bt.HierarchyRule(1, 0),
                                     bt.HierarchyRule(2, 1)]})
    # Prev: primary a0, replica b0 (fallback tier); same-rack a1 is free.
    prev = {"p": bt.Partition("p", {"primary": ["a0"], "replica": ["b0"]})}
    nxt, _ = bt.plan_next_map(prev, prev, nodes, [], [], model, opts,
                              backend="tpu")
    assert nxt["p"].nodes_by_state["primary"] == ["a0"]
    assert nxt["p"].nodes_by_state["replica"] == ["a1"], \
        nxt["p"].nodes_by_state


def test_replan_is_fixpoint():
    """With pin-first warm start, re-planning an already-balanced map with
    no cluster delta must return it unchanged (the batch analog of the
    reference's convergence-loop fixpoint, plan.go:23-58)."""
    import blance_tpu as bt

    nodes = [f"n{i}" for i in range(12)]
    parts = empty_parts(144)
    m1, _ = plan_next_map(parts, parts, nodes, [], nodes, M_1P_2R,
                          backend="tpu")
    m2, _ = plan_next_map(m1, m1, nodes, [], [], M_1P_2R, backend="tpu")
    changed = [p for p in m1
               if m1[p].nodes_by_state != m2[p].nodes_by_state]
    assert changed == [], f"{len(changed)} partitions changed on replan"


def _reencode(problem, result):
    """PartitionMap result -> assign[P, S, R'] in the problem's id space.

    Deliberately NOT encode_problem(result, result, ...): a fresh encode
    may intern/sort partitions differently than ``problem`` did (the
    planner sort keys off prev holders and removals), and check_assignment
    indexes prev/constraints by THIS problem's order."""
    r_max = max([problem.R, 1] + [
        len(ns) for p in result.values() for ns in p.nodes_by_state.values()])
    assign = np.full((problem.P, problem.S, r_max), -1, np.int32)
    nidx = {n: i for i, n in enumerate(problem.nodes)}
    sidx = {s: i for i, s in enumerate(problem.states)}
    for pi, pname in enumerate(problem.partitions):
        for s, ns in result[pname].nodes_by_state.items():
            for ri, node in enumerate(ns):
                assign[pi, sidx[s], ri] = nidx[node]
    return assign


def _weighted_spread(result, m, nodes, node_weights, partition_weights):
    """Per state: max-min of per-node PARTITION-WEIGHTED load normalized
    by node weight — ONE spelling, shared with the golden-contract
    assertions (testing/vis.py), so the fuzz bound and the golden bound
    can't drift apart."""
    from blance_tpu.testing.vis import _weighted_state_spread

    return _weighted_state_spread(result, m, nodes, node_weights,
                                  partition_weights)


@pytest.mark.parametrize("seed", range(16))
def test_fuzz_contract_random_configs(seed):
    """Randomized configs (weights, racks, removals): the TPU backend must
    (1) produce zero hard violations and fill every feasible slot,
    (2) place every copy at the best feasible rule tier (check_assignment's
        hierarchy_misses gate),
    (3) keep partition-weighted balance spread within 1.5x + 4 of the
        sequential greedy oracle on the same problem, and
    (4) keep delta-rebalance churn (calc_all_moves op count) within
        1.35x + 4 of the oracle's churn for the same delta.  The slack
        over the oracle is the marginal keep-ceiling healing the batch
        fresh-plan's own quantization looseness during the replan
        (per-state load gaps above the stickiness band close, one
        time — seed 6: 28 displaced partitions on both backends, plus
        10 same-rack replica shuffles only here, fixpoint after).
    Bounds re-pinned (round 5) after the donor-gap slack rule made
    growth migration reference-faithful: worst observed weighted-spread
    excess is 3.5 over 1.5x the oracle's (seed 5: 5.0 vs oracle 1.0,
    weight-3 partitions; pre-change worst was 2.5) — they flag
    regressions while acknowledging the batch solver trades a little
    tightness for wall-clock (DESIGN.md section 7)."""
    from blance_tpu.core.encode import encode_problem
    from blance_tpu.moves.batch import calc_all_moves

    rng = np.random.default_rng(seed)
    N = int(rng.integers(4, 24))
    P = int(rng.integers(8, 200))
    R = int(rng.integers(1, 3))
    nodes = [f"n{i}" for i in range(N)]
    m = model(primary=(0, 1), replica=(1, R))
    opts_kw = {}
    if rng.random() < 0.5:
        opts_kw["node_weights"] = {
            nodes[i]: int(rng.integers(1, 4)) for i in range(0, N, 3)}
    if rng.random() < 0.5:
        opts_kw["partition_weights"] = {
            str(i): int(rng.integers(1, 4)) for i in range(0, P, 5)}
    racks = int(rng.integers(0, 4))
    if racks >= 2:
        hier = {n: f"r{i % racks}" for i, n in enumerate(nodes)}
        hier.update({f"r{i}": "z" for i in range(racks)})
        opts_kw["node_hierarchy"] = hier
        opts_kw["hierarchy_rules"] = {"replica": [HierarchyRule(2, 1)]}
    opts = PlanOptions(**opts_kw)

    parts = empty_parts(P)
    m1, _ = plan_next_map(parts, parts, nodes, [], nodes, m, opts,
                          backend="tpu")
    no_hard_violations(m1, m, set(nodes))
    g1, _ = plan_next_map(parts, parts, nodes, [], nodes, m, opts,
                          backend="greedy")

    # (2) best-feasible-tier rule conformance, fresh plan.
    prob1 = encode_problem(parts, parts, nodes, [], m, opts)
    assert check_assignment(prob1, _reencode(prob1, m1))[
        "hierarchy_misses"] == 0

    # Random removal delta, planned by both backends from their own maps.
    k = int(rng.integers(0, max(N // 4, 1)))
    removed = list(rng.choice(nodes, k, replace=False)) if k else []
    m2, _ = plan_next_map(m1, m1, nodes, removed, [], m, opts, backend="tpu")
    g2, _ = plan_next_map(g1, g1, nodes, removed, [], m, opts,
                          backend="greedy")
    survivors = set(nodes) - set(removed)
    no_hard_violations(m2, m, survivors)
    if len(survivors) > R:  # replicas feasible
        for p in m2.values():
            assert len(p.nodes_by_state["primary"]) == 1

    # (2) rule conformance after the delta.
    prob2 = encode_problem(m1, m1, nodes, removed, m, opts)
    assert check_assignment(prob2, _reencode(prob2, m2))[
        "hierarchy_misses"] == 0

    # (3) partition-weighted balance within 1.5x + 3 of the oracle.
    nw = opts_kw.get("node_weights", {})
    pw = opts_kw.get("partition_weights", {})
    surv_list = [n for n in nodes if n in survivors]
    sp_t = _weighted_spread(m2, m, surv_list, nw, pw)
    sp_g = _weighted_spread(g2, m, surv_list, nw, pw)
    for st in m:
        assert sp_t[st] <= 1.5 * sp_g[st] + 4, (
            f"state {st}: tpu spread {sp_t[st]} vs greedy {sp_g[st]}")

    # (4) churn within 1.35x + 4 of the oracle for the same delta.
    churn_t = sum(len(v) for v in calc_all_moves(m1, m2, m).values())
    churn_g = sum(len(v) for v in calc_all_moves(g1, g2, m).values())
    assert churn_t <= 1.35 * churn_g + 4, (churn_t, churn_g)


# --- hierarchy-audit group-counting fast path --------------------------------


def _synthetic_problem(rng, orphan_style="neg"):
    """Random DenseProblem with a tree hierarchy (level 0 = node, coarser
    above), random invalid nodes, random missing ancestors, random prev.
    Built directly (no encode) so the audit fuzz controls every corner:
    -1 prev anchors, missing ancestors, multi-rule tiers.  Missing
    ancestors are spelled two ways: ``"neg"`` = gid -1 (synthetic
    convention) or ``"interned"`` = a shared real group id with
    gid_valid=False (exactly what encode_problem emits for orphans —
    level_group_ids interns the "" group like any other name)."""
    from blance_tpu.core.encode import DenseProblem

    N = int(rng.integers(6, 40))
    P = int(rng.integers(10, 200))
    S = int(rng.integers(1, 3))
    R = int(rng.integers(1, 4))
    k1 = int(rng.integers(2, 5))
    k2 = int(rng.integers(2, 4))
    lvl0 = np.arange(N, dtype=np.int32)
    lvl1 = lvl0 // k1
    lvl2 = lvl1 // k2
    gids = np.stack([lvl0, lvl1, lvl2])
    gid_valid = np.ones((3, N), bool)
    # Some nodes lack a rack/zone ancestor.
    for lv in (1, 2):
        miss = rng.random(N) < 0.15
        orphan_id = -1 if orphan_style == "neg" else gids[lv].max() + 1
        gids[lv] = np.where(miss, orphan_id, gids[lv])
        gid_valid[lv] &= ~miss
    valid = rng.random(N) >= 0.2
    prev = np.where(rng.random((P, S, R)) < 0.2, -1,
                    rng.integers(0, N, (P, S, R))).astype(np.int32)
    rule_menu = [[(2, 1)], [(1, 0), (2, 1)], [(2, 0)], [(2, 1), (2, 0)]]
    rules = {si: list(rule_menu[int(rng.integers(0, len(rule_menu)))])
             for si in range(S) if rng.random() < 0.8}
    return DenseProblem(
        nodes=[f"n{i}" for i in range(N)],
        partitions=[str(i) for i in range(P)],
        states=[f"s{i}" for i in range(S)],
        constraints=np.full(S, R, np.int32),
        prev=prev,
        partition_weights=np.ones(P, np.float32),
        node_weights=np.ones(N, np.float32),
        valid_node=valid,
        stickiness=np.ones((P, S), np.float32),
        gids=gids,
        gid_valid=gid_valid,
        rules=rules,
    )


@pytest.mark.parametrize("orphan_style", ["neg", "interned"])
@pytest.mark.parametrize("seed", range(16))
def test_hier_audit_group_counting_parity(seed, orphan_style):
    """The O(P + N·L) group-counting hierarchy audit must count EXACTLY
    the misses the exhaustive [P, N] matrix audit counts — on arbitrary
    (deliberately violation-riddled) assignments, not just solver output:
    random picks include co-racked copies, removed nodes, duplicate
    nodes, and absent slots.  Both missing-ancestor spellings (-1 and
    encode's interned-orphan groups) must agree."""
    from blance_tpu.plan.tensor import (
        _audit_rules_nest, _count_hier_misses_block, _count_hier_misses_fast)

    rng = np.random.default_rng(seed)
    problem = _synthetic_problem(rng, orphan_style)
    assert _audit_rules_nest(problem)
    P, S = problem.P, problem.S
    R = problem.prev.shape[2]
    for trial in range(4):
        assign = np.where(
            rng.random((P, S, R)) < 0.15, -1,
            rng.integers(0, problem.N, (P, S, R))).astype(np.int32)
        fast = _count_hier_misses_fast(problem, assign)
        slow = _count_hier_misses_block(problem, assign, problem.prev)
        assert fast == slow, (seed, trial, fast, slow)


def test_hier_audit_fast_path_selected_and_affordable(monkeypatch):
    """With nesting rules, check_assignment must route through the
    group-counting audit (never the O(P*N) matrix path) and
    maybe_validate must default validation ON above the old cell
    ceiling."""
    from blance_tpu.plan import tensor

    rng = np.random.default_rng(0)
    problem = _synthetic_problem(rng)
    # Inflate the problem's apparent size past the exotic-rules ceiling:
    # same arrays, longer name lists are irrelevant to the audit itself.
    import time as _time

    def boom(*a, **k):
        raise AssertionError("matrix audit path must not run")

    monkeypatch.setattr(tensor, "_count_hier_misses_block", boom)
    assign = problem.prev.copy()
    t0 = _time.perf_counter()
    counts = tensor.check_assignment(problem, assign)
    assert _time.perf_counter() - t0 < 5.0
    assert set(counts) == {"duplicates", "on_removed_nodes",
                           "unfilled_feasible_slots", "hierarchy_misses"}
    # Default-on at any scale: shrink the exotic-rules ceiling below this
    # problem's cell count — with nesting rules maybe_validate must run
    # the audit anyway (the old policy would have skipped it).
    monkeypatch.setattr(tensor, "_VALIDATE_AUTO_CELLS", 1)
    assert problem.P * problem.N > 1
    got = tensor.maybe_validate(problem, assign, None, "test")
    assert got is not None


def test_hier_audit_counts_planted_miss():
    """A hand-planted fixable violation must be counted identically by
    both audit paths (guards against both paths agreeing on zero)."""
    from blance_tpu.core.encode import DenseProblem
    from blance_tpu.plan.tensor import (
        _count_hier_misses_block, _count_hier_misses_fast)

    N, P = 6, 3
    gids = np.stack([np.arange(N, dtype=np.int32),
                     np.arange(N, dtype=np.int32) // 2,
                     np.zeros(N, np.int32)])
    problem = DenseProblem(
        nodes=[f"n{i}" for i in range(N)],
        partitions=[str(i) for i in range(P)],
        states=["primary", "replica"],
        constraints=np.array([1, 1], np.int32),
        prev=np.full((P, 2, 1), -1, np.int32),
        partition_weights=np.ones(P, np.float32),
        node_weights=np.ones(N, np.float32),
        valid_node=np.ones(N, bool),
        stickiness=np.ones((P, 2), np.float32),
        gids=gids,
        gid_valid=np.ones((3, N), bool),
        rules={1: [(2, 1)]},
    )
    assign = np.zeros((P, 2, 1), np.int32)
    assign[:, 0, 0] = [0, 2, 4]
    assign[:, 1, 0] = [1, 5, 1]  # partition 0's replica co-racked with
    fast = _count_hier_misses_fast(problem, assign)  # its primary (rack 0)
    slow = _count_hier_misses_block(problem, assign, problem.prev)
    assert fast == slow == 1, (fast, slow)


# --- engine auto-selection fallback ------------------------------------------


def test_engine_compile_failure_falls_back_to_fused(monkeypatch):
    """An auto-selected matrix engine that dies in compile must retry on
    the fused engine with a UserWarning and a timer annotation — never a
    user-visible error (VERDICT r4 #6: the production mirror of
    bench.py's degradation path)."""
    import warnings

    from blance_tpu.plan import tensor
    from blance_tpu.utils.trace import PhaseTimer

    real = tensor.solve_dense_converged
    calls = []

    def flaky(*args, **kwargs):
        calls.append(kwargs.get("fused_score"))
        if kwargs.get("fused_score") == "off":
            raise RuntimeError("RESOURCE_EXHAUSTED: injected compile OOM")
        # "on" would need compiled Pallas; run the interpret spelling of
        # the same engine so the fallback executes on the CPU test host.
        kwargs["fused_score"] = "interpret"
        return real(*args, **kwargs)

    monkeypatch.setattr(tensor, "solve_dense_converged", flaky)
    monkeypatch.setattr(tensor, "pallas_available", lambda: True)

    nodes = [f"n{i}" for i in range(8)]
    parts = empty_parts(32)
    timer = PhaseTimer()
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        m1, _ = tensor.plan_next_map_tpu(
            parts, parts, nodes, [], nodes, M_1P_2R, timer=timer)
    assert calls == ["off", "on"], calls
    msgs = [str(w.message) for w in caught
            if "retrying with" in str(w.message)]
    assert msgs and "'off' failed" in msgs[0], msgs
    assert timer.annotations["engine"] == "fused"
    assert timer.annotations["engine_fallback"] == "-> on"
    # The fallback result is a real solve: every primary placed.
    for p in m1.values():
        assert len(p.nodes_by_state["primary"]) == 1


def test_engine_explicit_mode_fails_loudly(monkeypatch):
    """An EXPLICIT engine choice (set_fused_score_default("off")) must
    not silently flip engines on failure — the user asked for that
    engine."""
    import pytest as _pytest

    from blance_tpu.plan import tensor

    def boom(*args, **kwargs):
        raise RuntimeError("injected compile failure")

    monkeypatch.setattr(tensor, "solve_dense_converged", boom)
    nodes = [f"n{i}" for i in range(8)]
    parts = empty_parts(32)
    tensor.set_fused_score_default("off")
    try:
        with _pytest.raises(RuntimeError, match="injected"):
            tensor.plan_next_map_tpu(parts, parts, nodes, [], nodes, M_1P_2R)
    finally:
        tensor.set_fused_score_default("auto")


def test_session_replan_engine_fallback(monkeypatch):
    """PlannerSession.replan degrades through the same resilient path."""
    import warnings

    from blance_tpu.plan import tensor
    from blance_tpu.plan.session import PlannerSession

    real = tensor.solve_dense_converged

    def flaky(*args, **kwargs):
        if kwargs.get("fused_score") == "off":
            raise RuntimeError("injected compile OOM")
        kwargs["fused_score"] = "interpret"
        return real(*args, **kwargs)

    monkeypatch.setattr(tensor, "solve_dense_converged", flaky)
    monkeypatch.setattr(tensor, "pallas_available", lambda: True)

    s = PlannerSession(M_1P_2R, [f"n{i}" for i in range(8)],
                       [str(i) for i in range(32)])
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        assign = s.replan()
    assert (assign[:, 0, 0] >= 0).all()
    assert any("retrying with" in str(w.message) for w in caught)


def test_custom_node_sorter_replaces_ordering_policy(monkeypatch):
    """PlanOptions.node_sorter replaces the ENTIRE candidate ordering —
    score and tie-break policy — mirroring assignment to the reference's
    CustomNodeSorter package var (plan.go:566-580).  node_scorer cannot
    express a tie-break change (the framework position-breaks around it);
    the sorter hook can.  Like every Python placement hook, tpu/auto
    route to the exact path instead of silently dropping the policy."""
    from blance_tpu.plan import api as plan_api
    from blance_tpu.plan.greedy import default_node_score

    def reverse_ties(ctx, nodes):
        return sorted(nodes, key=lambda n: (default_node_score(ctx, n),
                                            -ctx.node_positions.get(n, 0)))

    nodes = ["a", "b", "c", "d"]
    parts = empty_parts(16)
    opts = PlanOptions(node_sorter=reverse_ties)
    golden, gw = plan_next_map(
        empty_parts(16), parts, nodes, [], nodes, M_1P_1R, opts,
        backend="greedy")
    # The hook bit: the first-placed partition ties on every node and the
    # REVERSED position break picks "d" (default ordering picks "a").
    assert golden["0"].nodes_by_state["primary"] == ["d"], \
        golden["0"].nodes_by_state
    base, _ = plan_next_map(
        empty_parts(16), parts, nodes, [], nodes, M_1P_1R, PlanOptions(),
        backend="greedy")
    assert base["0"].nodes_by_state["primary"] == ["a"]

    # Balance is preserved — only the ordering policy changed.
    loads = node_loads(golden, "primary")
    assert max(loads.values()) - min(loads.values()) <= 1, loads

    # tpu / auto / native fall back to the exact path and honor the hook.
    monkeypatch.setattr(plan_api, "_AUTO_TPU_THRESHOLD", 1)
    for backend in ("tpu", "auto", "native"):
        got, w = plan_next_map(
            empty_parts(16), parts, nodes, [], nodes, M_1P_1R, opts,
            backend=backend)
        assert got == golden, backend
        assert w == gw, backend
