"""Node-axis (2-D parts x nodes mesh) sharding tests.

The 2-D mesh is the machinery behind the >>10k-node scale story
(SURVEY.md §2.6 / §5 long-context analog): every [P, N] intermediate in
the solver is sharded on BOTH axes while [N] vectors stay node-replicated.
The central contract proved here is **node-shard-count invariance**: the
node axis is pure replicated math plus (all_gather, masked-psum)
combines whose tie-breaks mirror the replicated order, so a (k, m) mesh
must produce BIT-IDENTICAL output to the (k,)-mesh solve for every m.
That invariance is also the documented justification for disabling
shard_map's varying-axes checker on this path (parallel/sharded.py): the
checker can't prove the output is node-replicated; these tests do.
"""

import numpy as np

import jax
import pytest

from blance_tpu import HierarchyRule, Partition, PlanOptions, model
from blance_tpu.core.encode import decode_assignment, encode_problem
from blance_tpu.parallel.sharded import (
    make_mesh,
    make_mesh_2d,
    pad_nodes,
    solve_problem_sharded,
)
from blance_tpu.plan.tensor import check_assignment

CLEAN = {"duplicates": 0, "on_removed_nodes": 0,
         "unfilled_feasible_slots": 0, "hierarchy_misses": 0}


def empty_parts(n):
    return {str(i): Partition(str(i), {}) for i in range(n)}


def _rack_problem(P=64, N=8, prev_map=None):
    """Same shape as test_sharded._rack_problem: N nodes on N//2 racks,
    primary + 2 replicas, replica rule (include zone=2, exclude rack=1)."""
    nodes = [f"n{i}" for i in range(N)]
    hier = {n: f"r{i // 2}" for i, n in enumerate(nodes)}
    hier.update({f"r{i}": "z0" for i in range(N // 2)})
    opts = PlanOptions(
        node_hierarchy=hier,
        hierarchy_rules={"replica": [HierarchyRule(2, 1)]})
    m = model(primary=(0, 1), replica=(1, 2))
    parts = empty_parts(P)
    problem = encode_problem(prev_map or {}, parts, nodes, [], m, opts)
    return problem, parts, m, opts


def _rule_violations(problem, assign):
    """Co-racked copies under the (2,1) replica rule (vs primary or pair)."""
    rack = problem.gids[1]
    pr = rack[assign[:, 0, 0]]
    r0, r1 = rack[assign[:, 1, 0]], rack[assign[:, 1, 1]]
    bad = (pr == r0) | (pr == r1) | (r0 == r1)
    bad |= (assign[:, 1, 0] < 0) | (assign[:, 1, 1] < 0)
    return int(bad.sum())


def test_mesh_2d_shape():
    mesh = make_mesh_2d(2, 4)
    assert mesh.devices.shape == (2, 4)
    assert mesh.axis_names == ("parts", "nodes")
    with pytest.raises(ValueError):
        make_mesh_2d(4, 4)  # only 8 devices available


def test_2d_rack_rules_zero_violations():
    """The rack-rule problem on a 2x4 mesh: zero violations, clean
    constraint check, every slot filled."""
    problem, parts, _, _ = _rack_problem()
    assign = solve_problem_sharded(make_mesh_2d(2, 4), problem)
    assert assign.shape == (64, 2, 2)
    assert _rule_violations(problem, assign) == 0
    assert check_assignment(problem, assign) == CLEAN
    result, warnings = decode_assignment(problem, assign, parts, [])
    assert not warnings
    # Primaries stay perfectly balanced regardless of mesh shape.
    prim = assign[:, 0, 0]
    loads = np.bincount(prim, minlength=8)
    assert loads.max() - loads.min() == 0, loads


def test_node_shard_count_invariance():
    """THE 2-D contract: adding node shards never changes the answer.

    The node axis is replicated math + order-preserving combines, so the
    (k, m) solve must be bit-identical to the (k,) solve for every m —
    balance, churn, and rule conformance are then inherited from the
    already-tested 1-D path, and the disabled varying-axes checker is
    covered by proof-by-execution."""
    problem, _, _, _ = _rack_problem()
    for parts_shards, node_shards_list in ((2, (2, 4)), (4, (2,)), (1, (8,))):
        base = solve_problem_sharded(make_mesh(parts_shards), problem)
        for m in node_shards_list:
            a2d = solve_problem_sharded(
                make_mesh_2d(parts_shards, m), problem)
            assert np.array_equal(base, a2d), (parts_shards, m)


def test_2d_balance_matches_1d_contract():
    """Per-state load spread on the 2x4 mesh equals the 2-shard 1-D
    spread (node axis is balance-neutral by the invariance above); bound
    it at the measured value so balance regressions surface here."""
    problem, _, _, _ = _rack_problem()
    assign = solve_problem_sharded(make_mesh_2d(2, 4), problem)
    for si, bound in ((0, 0), (1, 6)):  # measured: primaries 0, replicas 6
        ids = assign[:, si, :].ravel()
        loads = np.bincount(ids[ids >= 0], minlength=8)
        assert loads.max() - loads.min() <= bound, (si, loads)


def test_2d_deterministic_and_own_fixpoint():
    problem, parts, m, opts = _rack_problem()
    mesh = make_mesh_2d(2, 4)
    a = solve_problem_sharded(mesh, problem)
    # Determinism: bit-identical re-solve.
    assert np.array_equal(a, solve_problem_sharded(mesh, problem))
    # Own-operator fixpoint: replanning the output is a no-op.
    p2 = encode_problem({}, parts, problem.nodes, [], m, opts)
    p2.prev[...] = a
    assert np.array_equal(solve_problem_sharded(mesh, p2), a)


def test_2d_cross_operator_churn_bounded():
    """Re-solving the 2x4 output on the 8-shard 1-D mesh may repair the
    parts=2 residual imbalance but must not violate rules; churn is
    pinned at measured (17/64 with the stall top-up, which lets the
    8-shard solve repair more of the 2-shard residual) + small slack."""
    problem, parts, m, opts = _rack_problem()
    a24 = solve_problem_sharded(make_mesh_2d(2, 4), problem)
    p2 = encode_problem({}, parts, problem.nodes, [], m, opts)
    p2.prev[...] = a24
    f1 = solve_problem_sharded(make_mesh(8), p2)
    assert _rule_violations(problem, f1) == 0
    churned = int((f1 != a24).any(axis=(1, 2)).sum())
    assert churned <= 20, churned  # measured 17 of 64


def test_2d_node_padding():
    """N=6 doesn't divide node_shards=4: pad_nodes must pad the node
    tables with invalid columns that are never chosen, so every returned
    id is a real node and balance is exact."""
    problem, parts, _, _ = _rack_problem(P=48, N=6)
    assign = solve_problem_sharded(make_mesh_2d(2, 4), problem)
    assert assign.shape == (48, 2, 2)
    assert assign.max() < 6  # padding ids (6, 7) never assigned
    assert _rule_violations(problem, assign) == 0
    assert check_assignment(problem, assign) == CLEAN
    ids = assign.ravel()
    loads = np.bincount(ids[ids >= 0], minlength=6)
    assert loads.max() - loads.min() == 0, loads  # 144 copies / 6 nodes


def test_2d_node_removal():
    """Removal on the 2-D mesh: nothing lands on the removed node."""
    problem, parts, m, opts = _rack_problem()
    mesh = make_mesh_2d(2, 4)
    a1 = solve_problem_sharded(mesh, problem)
    beg, _ = decode_assignment(problem, a1, parts, [])
    p2 = encode_problem(beg, beg, problem.nodes, ["n0"], m, opts)
    a2 = solve_problem_sharded(mesh, p2)
    end, warnings = decode_assignment(p2, a2, beg, ["n0"])
    assert not warnings
    for p in end.values():
        for ns in p.nodes_by_state.values():
            assert "n0" not in ns
    assert check_assignment(p2, a2) == CLEAN


def test_pad_nodes_unit():
    arr = np.arange(6, dtype=np.int32)
    out = pad_nodes(arr, 4, -1)
    assert out.tolist() == [0, 1, 2, 3, 4, 5, -1, -1]
    # Already divisible: unchanged (same object contents).
    assert pad_nodes(out, 4, -1).tolist() == out.tolist()
    # Trailing-axis padding on a 2-D table.
    tab = np.ones((2, 6), dtype=bool)
    padded = pad_nodes(tab, 4, False)
    assert padded.shape == (2, 8)
    assert not padded[:, 6:].any()
