"""Native (C++) backend parity: must be bit-identical to the Python greedy.

Covers the 20 golden struct cases plus randomized differential testing over
weights, stickiness, hierarchies, node adds/removes and prev maps.
"""

import random

import pytest

from blance_tpu import (
    HierarchyRule,
    Partition,
    PlanOptions,
    model,
    plan_next_map,
)
from blance_tpu.plan.native import cbgt_node_score_booster, native_available
from tests.test_plan import CASES, pm

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native toolchain unavailable")


@pytest.mark.parametrize("case", CASES, ids=[c["about"] for c in CASES])
def test_native_matches_golden_cases(case):
    opts = PlanOptions(
        model_state_constraints=case.get("constraints"),
        partition_weights=case.get("pweights"),
        state_stickiness=case.get("sstick"),
        node_weights=case.get("nweights"),
        node_hierarchy=case.get("hierarchy"),
        hierarchy_rules=case.get("rules"),
    )
    result, warnings = plan_next_map(
        pm(case["prev"]), pm(case["assign"]), case["nodes"],
        case["remove"], case["add"], case["model"], opts,
        backend="native",
    )
    got = {name: p.nodes_by_state for name, p in result.items()}
    assert got == {name: dict(nbs) for name, nbs in case["exp"].items()}
    assert sum(len(w) for w in warnings.values()) == case["warnings"]


def _random_scenario(rng: random.Random):
    n_nodes = rng.randint(1, 10)
    nodes = [f"n{i}" for i in range(n_nodes)]
    hierarchy = None
    rules = None
    if rng.random() < 0.5:
        n_racks = rng.randint(1, 3)
        hierarchy = {n: f"r{i % n_racks}" for i, n in enumerate(nodes)}
        hierarchy.update({f"r{i}": "z0" for i in range(n_racks)})
        rules = {"replica": [HierarchyRule(rng.choice([1, 2]),
                                           rng.choice([0, 1]))]}
    m = model(primary=(0, rng.randint(1, 2)), replica=(1, rng.randint(0, 2)))
    n_parts = rng.randint(1, 24)
    names = [str(i) for i in range(n_parts)]

    def random_map(assigned: bool):
        out = {}
        for name in names:
            nbs: dict = {}
            if assigned:
                pool = rng.sample(nodes, min(len(nodes), rng.randint(0, 3)))
                cut = rng.randint(0, len(pool))
                nbs = {"primary": pool[:cut], "replica": pool[cut:]}
            out[name] = Partition(name, nbs)
        return out

    prev = random_map(rng.random() < 0.7)
    assign = (random_map(True) if rng.random() < 0.2
              else {k: v.copy() for k, v in prev.items()})
    removes = rng.sample(nodes, rng.randint(0, max(0, n_nodes - 1)))
    adds = None if rng.random() < 0.3 else rng.sample(nodes, rng.randint(0, n_nodes))

    opts = PlanOptions(
        partition_weights=(
            {rng.choice(names): rng.randint(1, 5)} if rng.random() < 0.4 else None),
        state_stickiness=(
            {"primary": rng.randint(1, 100)} if rng.random() < 0.4 else None),
        node_weights=(
            {rng.choice(nodes): rng.choice([-2, -1, 2, 3])}
            if rng.random() < 0.4 else None),
        node_hierarchy=hierarchy,
        hierarchy_rules=rules,
        node_score_booster=(
            cbgt_node_score_booster if rng.random() < 0.5 else None),
    )
    return prev, assign, nodes, removes, adds, m, opts


def test_native_ghost_nodes_match_greedy():
    """Partitions referencing nodes outside nodes_all (not removed either)
    must behave identically: the ghost stays in rows and accounting but is
    never a candidate."""
    m = model(primary=(0, 1), replica=(1, 1))
    prev = {
        "0": Partition("0", {"primary": ["ghost"], "replica": ["a"]}),
        "1": Partition("1", {"primary": ["b"], "replica": ["ghost"]}),
        "2": Partition("2", {"primary": ["a"], "replica": ["b"]}),
    }
    for constraints in (None, {"primary": 1, "replica": 0}):
        opts = PlanOptions(model_state_constraints=constraints)
        g_map, g_w = plan_next_map(prev, prev, ["a", "b"], [], None, m, opts,
                                   backend="greedy")
        n_map, n_w = plan_next_map(prev, prev, ["a", "b"], [], None, m, opts,
                                   backend="native")
        assert {k: p.nodes_by_state for k, p in n_map.items()} == \
               {k: p.nodes_by_state for k, p in g_map.items()}
        assert n_w == g_w


def test_native_interior_hierarchy_node_matches_greedy():
    """A listed node that is also a hierarchy parent is never a valid
    hierarchy pick (find_leaves yields leaves only)."""
    m = model(primary=(0, 1), replica=(1, 1))
    parts = {str(i): Partition(str(i), {}) for i in range(4)}
    opts = PlanOptions(
        node_hierarchy={"a": "r0", "b": "r0", "r0": "z0"},
        hierarchy_rules={"replica": [HierarchyRule(1, 0)]},
    )
    nodes = ["a", "b", "r0"]  # r0 is both a node and a's/b's parent
    g_map, g_w = plan_next_map({}, parts, nodes, [], nodes, m, opts,
                               backend="greedy")
    n_map, n_w = plan_next_map({}, parts, nodes, [], nodes, m, opts,
                               backend="native")
    assert {k: p.nodes_by_state for k, p in n_map.items()} == \
           {k: p.nodes_by_state for k, p in g_map.items()}
    assert n_w == g_w


def test_native_differential_vs_greedy():
    rng = random.Random(1234)
    for trial in range(60):
        prev, assign, nodes, removes, adds, m, opts = _random_scenario(rng)
        g_map, g_warn = plan_next_map(
            prev, assign, nodes, removes, adds, m, opts, backend="greedy")
        n_map, n_warn = plan_next_map(
            prev, assign, nodes, removes, adds, m, opts, backend="native")
        g = {k: p.nodes_by_state for k, p in g_map.items()}
        n = {k: p.nodes_by_state for k, p in n_map.items()}
        assert n == g, (
            f"trial {trial}: mismatch\nnodes {nodes} removes {removes} "
            f"adds {adds}\nopts {opts}\nprev "
            f"{ {k: p.nodes_by_state for k, p in prev.items()} }\n"
            f"greedy {g}\nnative {n}")
        assert n_warn == g_warn, f"trial {trial}: warnings {n_warn} != {g_warn}"
