"""Hash-seed replay smoke: the dynamic twin of DET005.

The static rule (analysis/determinism.py DET005) bans ordering keyed on
``hash()``/``id()``; this suite proves the property end-to-end by
regenerating every committed replay artifact in TWO fresh interpreters
with *different* ``PYTHONHASHSEED`` values and asserting byte-identity —
against each other AND against the committed files.  Any str-hash
iteration order that leaks into an event log, a fleet log, a crash
journal or an explored schedule shows up here as a diff between seeds.

``PYTHONHASHSEED`` only takes effect at interpreter start, so each run
is a subprocess (slow-marked; CI runs this as its own sim-smoke step).
"""

import json
import os
import subprocess
import sys

import pytest

TRACES = {
    "sim_spot_preemption_s11": "tests/traces/sim_spot_preemption_s11.json",
    "fleet_zone_outage_s5_t8": "tests/traces/fleet_zone_outage_s5_t8.json",
    "crash_storm_s19": "tests/traces/crash_storm_s19.json",
}

# Regenerates every artifact in one interpreter and prints a JSON map of
# name -> text (sorted keys: the driver obeys DET004 too).
_DRIVER = r"""
import json, sys, tempfile

out = {}

from blance_tpu.testing.scenarios import (
    crash_storm, fleet_zone_outage, spot_preemption)
from blance_tpu.testing.simulate import run_scenario
out["sim_spot_preemption_s11"] = run_scenario(spot_preemption(11)).log_text()

from blance_tpu.testing.fleetsim import run_fleet_scenario
out["fleet_zone_outage_s5_t8"] = run_fleet_scenario(
    fleet_zone_outage(seed=5, tenants=8)).log_text()

from blance_tpu.testing.crashsim import run_crash_scenario
cs = crash_storm(19)
out["crash_storm_s19"] = run_crash_scenario(
    cs.base, tempfile.mkdtemp(), crashes=cs.crashes,
    snapshot_every=cs.snapshot_every,
    rotate_records=cs.rotate_records).log_text()

from blance_tpu.analysis.schedule import SCENARIOS
from blance_tpu.testing.sched import load_trace, replay
trace = load_trace(sys.argv[1])
res = replay(SCENARIOS["pause_cycle_guard"].factory, trace, strict=False)
out["pause_cycle_guard"] = json.dumps(
    {"ok": res.ok, "signature": res.signature, "steps": res.steps,
     "choices": res.choices, "candidate_counts": res.candidate_counts},
    sort_keys=True)

print(json.dumps(out, sort_keys=True))
"""


def _regenerate(hashseed: str) -> dict:
    env = dict(os.environ)
    env.update({
        "PYTHONHASHSEED": hashseed,
        "JAX_PLATFORMS": "cpu",
        "BLANCE_WAL_FSYNC": "0",
    })
    proc = subprocess.run(
        [sys.executable, "-c", _DRIVER,
         "tests/traces/pause_cycle_guard.json"],
        capture_output=True, text=True, env=env, timeout=900,
    )
    assert proc.returncode == 0, (
        f"driver failed under PYTHONHASHSEED={hashseed}:\n{proc.stderr}")
    return json.loads(proc.stdout.splitlines()[-1])


@pytest.mark.slow
def test_replays_are_hashseed_independent():
    a = _regenerate("0")
    b = _regenerate("1")
    for name in sorted(set(a) | set(b)):
        assert a[name] == b[name], (
            f"{name}: artifact differs between PYTHONHASHSEED=0 and =1 "
            f"— str-hash order is leaking into a replayed path")
    # And both match the committed artifacts byte-for-byte.
    for name, path in TRACES.items():
        with open(path) as f:
            committed = f.read()
        assert a[name] == committed, (
            f"{name}: regenerated artifact drifted from {path}")
    assert json.loads(a["pause_cycle_guard"])["ok"] is True
