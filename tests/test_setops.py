"""Ports of the reference's string-set op tests (misc_test.go, plan_test.go units)."""

from blance_tpu import (
    Partition,
    count_state_nodes,
    flatten_nodes_by_state,
    model,
    sort_state_names,
    strings_dedup,
    strings_intersect,
    strings_remove,
    strings_to_set,
)
from blance_tpu.plan.greedy import _remove_nodes_from_nodes_by_state


def test_strings_to_set():
    assert strings_to_set(None) is None
    assert strings_to_set([]) == set()
    assert strings_to_set(["a"]) == {"a"}
    assert strings_to_set(["a", "a", "b"]) == {"a", "b"}


def test_strings_remove():
    assert strings_remove([], []) == []
    assert strings_remove(["a"], []) == ["a"]
    assert strings_remove(["a"], ["a"]) == []
    assert strings_remove(["a", "b", "a"], ["a"]) == ["b"]
    assert strings_remove(["a", "b", "a"], ["b"]) == ["a", "a"]
    assert strings_remove(["a", "b", "c"], ["b", "x"]) == ["a", "c"]
    assert strings_remove(["a", "b", "c"], None) == ["a", "b", "c"]


def test_strings_intersect():
    assert strings_intersect([], []) == []
    assert strings_intersect(["a"], []) == []
    assert strings_intersect([], ["a"]) == []
    assert strings_intersect(["a"], ["a"]) == ["a"]
    assert strings_intersect(["a", "b"], ["b", "c"]) == ["b"]
    # Order follows the first array; result is deduplicated.
    assert strings_intersect(["b", "a", "b"], ["b", "a"]) == ["b", "a"]
    assert strings_intersect(["a", "b"], None) == []


def test_strings_dedup():
    assert strings_dedup([]) == []
    assert strings_dedup(["a", "a"]) == ["a"]
    assert strings_dedup(["b", "a", "b", "c"]) == ["b", "a", "c"]


def test_flatten_nodes_by_state():
    assert flatten_nodes_by_state({}) == []
    assert flatten_nodes_by_state({"primary": []}) == []
    assert flatten_nodes_by_state({"primary": ["a", "b"]}) == ["a", "b"]
    assert flatten_nodes_by_state({"primary": ["a", "b"], "replica": ["c"]}) == [
        "a", "b", "c",
    ]


def test_remove_nodes_from_nodes_by_state():
    cases = [
        ({"primary": ["a", "b"]}, ["a", "b"], {"primary": []}),
        ({"primary": ["a", "b"]}, ["b", "c"], {"primary": ["a"]}),
        ({"primary": ["a", "b"]}, ["a", "c"], {"primary": ["b"]}),
        ({"primary": ["a", "b"]}, [], {"primary": ["a", "b"]}),
        (
            {"primary": ["a", "b"], "replica": ["c"]},
            ["a", "c"],
            {"primary": ["b"], "replica": []},
        ),
    ]
    for nbs, remove, exp in cases:
        assert _remove_nodes_from_nodes_by_state(nbs, remove) == exp


def test_sort_state_names():
    m = model(primary=(0, 1), replica=(1, 1))
    assert sort_state_names(m) == ["primary", "replica"]
    m2 = model(a=(1, 1), b=(0, 1), c=(0, 1))
    assert sort_state_names(m2) == ["b", "c", "a"]


def test_count_state_nodes():
    m = {
        "0": Partition("0", {"primary": ["a"], "replica": ["b", "c"]}),
        "1": Partition("1", {"primary": ["b"], "replica": ["c"]}),
    }
    assert count_state_nodes(m, None) == {
        "primary": {"a": 1, "b": 1},
        "replica": {"b": 1, "c": 2},
    }
    assert count_state_nodes(m, {"0": 2}) == {
        "primary": {"a": 2, "b": 1},
        "replica": {"b": 2, "c": 3},
    }
