"""Encode residency (ISSUE 14, docs/DESIGN.md "Encode residency"):
the delta-resident tenant encode layer (plan/resident.py + the
ServicePlanner warm protocol in fleetloop.py).

The contract under test: a warm converge cycle's delta-patched resident
state is BIT-EXACTLY the full ``encode_problem`` re-encode of the same
inputs — across every delta family (abrupt fail + strip, graceful
remove, re-add after fail, weight drift, brand-new node add, adopted
passes) — and incremental decode is bit-identical to the full
``decode_assignment`` (maps AND shortfall warnings).  Every
off-protocol event (divergent current, statics swap, shape drift,
cache eviction, pass-through states) demotes to a counted cold
re-encode, never a stale map; cold re-encodes are exactly attributable
(``encode_cold == first encodes + demotions + evictions``).  Through
the shared service, residency is a pure perf toggle: the fleet
simulator's event log, SLO summaries and final maps are byte-identical
with it on or off.
"""

import asyncio
import dataclasses
import random

import numpy as np
import pytest

from blance_tpu.core.encode import (
    encode_problem,
    pack_slot_rows,
    strip_prev_rows,
)
from blance_tpu.core.types import (
    HierarchyRule,
    Partition,
    PlanOptions,
    model,
)
from blance_tpu.fleetloop import FleetController, ServicePlanner
from blance_tpu.obs import Recorder, use_recorder
from blance_tpu.plan.carry import EncodeCache
from blance_tpu.plan.service import PlanService
from blance_tpu.rebalance import ClusterDelta, _strip_nodes
from blance_tpu.testing.fleetsim import run_fleet_scenario
from blance_tpu.testing.scenarios import (
    fleet_noisy_neighbor,
    fleet_onboarding,
    fleet_zone_outage,
)
from blance_tpu.testing.sched import DeterministicLoop, FifoPolicy

M = model(primary=(0, 1), replica=(1, 1))

_ARRAYS = ("constraints", "prev", "partition_weights", "node_weights",
           "valid_node", "stickiness", "gids", "gid_valid")


def _cluster(nodes=12, parts=12, prefix="n"):
    names = [f"{prefix}{i}" for i in range(nodes)]
    pmap = {}
    for i in range(parts):
        p = f"p{i:03d}"
        pmap[p] = Partition(p, {"primary": [names[i % nodes]],
                                "replica": [names[(i + 1) % nodes]]})
    return names, pmap


def _nbs(pmap):
    return {name: {s: list(ns) for s, ns in p.nodes_by_state.items()}
            for name, p in pmap.items()}


def _assert_problem_equal(got, want, ctx=""):
    assert got.nodes == want.nodes, ctx
    assert got.partitions == want.partitions, ctx
    assert got.states == want.states, ctx
    assert got.rules == want.rules, ctx
    for f in _ARRAYS:
        a, b = np.asarray(getattr(got, f)), np.asarray(getattr(want, f))
        assert a.dtype == b.dtype and a.shape == b.shape \
            and np.array_equal(a, b), f"{ctx}: {f} drifted"


# -- array-kernel units -------------------------------------------------------


def test_strip_prev_rows_matches_strip_then_reencode():
    """strip_prev_rows ≡ _strip_nodes + encode_problem, bit-exactly,
    and untouched rows come back byte-identical in a NEW array."""
    nodes, pmap = _cluster(nodes=8, parts=16)
    opts = PlanOptions()
    problem = encode_problem(pmap, pmap, nodes, [], M, opts)
    dark = {"n2", "n5"}
    ids = np.array(sorted(i for i, n in enumerate(nodes) if n in dark),
                   np.int32)
    patched, dirty = strip_prev_rows(problem.prev, ids)
    stripped = _strip_nodes(pmap, dark)
    want = encode_problem(stripped, stripped, nodes, sorted(dark), M,
                          opts)
    assert np.array_equal(patched, want.prev)
    assert patched is not problem.prev  # identity-memo safety
    assert np.array_equal(dirty, (np.isin(problem.prev, ids)
                                  ).any(axis=(1, 2)))


def test_pack_slot_rows_matches_decode_pack():
    rng = np.random.default_rng(3)
    rows = rng.integers(-1, 6, size=(9, 2, 3)).astype(np.int32)
    packed, counts = pack_slot_rows(rows)
    for p in range(rows.shape[0]):
        for s in range(rows.shape[1]):
            row = rows[p, s]
            want = [x for x in row.tolist() if x >= 0]
            assert packed[p, s, :len(want)].tolist() == want
            assert counts[p, s] == len(want)
            # pad is whatever the stable argsort left; non-negative
            # prefix is the contract decode relies on
            assert (packed[p, s, len(want):] < 0).all() or \
                len(want) == rows.shape[2]


# -- the fuzz harness: resident vs never-resident twin ------------------------


class _Twin:
    """One planner + private service on the DeterministicLoop."""

    def __init__(self, rec, resident):
        self.svc = PlanService(admission_window_s=0.0,
                               inline_solve=True, recorder=rec,
                               batch_floor=16)
        self.planner = ServicePlanner(
            "t0", self.svc, recorder=rec,
            encode_residency=resident)
        self.current = None

    async def start(self, initial):
        await self.svc.start()
        self.current = initial

    async def cycle(self, nodes, removes, opts, adopt=True,
                    fresh_current=False):
        cur = self.current
        if fresh_current:
            # An equal-but-new map object: the divergence case.
            cur = {k: Partition(k, {s: list(ns) for s, ns in
                                    p.nodes_by_state.items()})
                   for k, p in cur.items()}
            self.current = cur
        nxt, warns = await self.planner.plan_cycle(
            cur, list(nodes), list(removes), M, opts)
        if adopt:
            self.current = nxt
        return nxt, warns

    def strip(self, dark):
        before = self.current
        self.current = _strip_nodes(self.current, set(dark))
        notify = getattr(self.planner, "notify_strip", None)
        if notify is not None:
            notify(set(dark), before, self.current)


def _run(loop, rec, coro):
    with use_recorder(rec):
        return loop.run_until_complete(coro)


def _check_resident_arrays(twin, nodes, removes, opts, ctx):
    """The resident arrays must equal a from-scratch re-encode of the
    planner's own inputs (its current view + this cycle's statics)."""
    st = twin.planner._encodes.get("t0")
    if st is None:
        return
    want = encode_problem(twin.current if st.expected is twin.current
                          else st.expected,
                          st.expected, list(nodes), list(removes), M,
                          opts)
    _assert_problem_equal(st.problem, want, ctx)


@pytest.mark.parametrize("seed", [7, 19, 83])
def test_fuzz_delta_families_patch_equals_reencode(seed):
    """Seeded random delta sequences over every family — fail+strip,
    graceful remove, re-add after fail, weight drift, brand-new node
    add, zero-delta repeats, forced divergence — with three invariants
    at every cycle: (a) the resident arrays are bit-equal to a full
    re-encode of the same inputs, (b) map + warnings are bit-identical
    to the never-resident twin's, (c) warm/cold SOLVE decisions match
    the twin's exactly (carry-hit/miss counter deltas)."""
    rng = random.Random(seed)
    loop = DeterministicLoop(FifoPolicy(), max_steps=2_000_000)
    rec = Recorder(clock=loop.time)

    async def drive():
        # 12 nodes / 12 partitions: the bucket class every fleet suite
        # compiles, so the fuzz pays no novel XLA programs beyond the
        # two node-add classes (N=13, N=14).
        nodes, pmap = _cluster(nodes=12, parts=12)
        spare = [f"x{i}" for i in range(2)]  # future brand-new adds
        res = _Twin(rec, resident=True)
        base = _Twin(rec, resident=False)
        await res.start(pmap)
        await base.start({k: p.copy() for k, p in pmap.items()})
        removes: set = set()
        failed: set = set()
        weights: dict = {}
        nweights: dict = {}

        def opts_now():
            return PlanOptions(
                partition_weights=dict(weights) or None,
                node_weights=dict(nweights) or None)

        opts = opts_now()
        for step in range(18):
            op = rng.choice(["fail", "remove", "readd", "drift",
                             "ndrift", "add", "noop", "diverge"])
            fresh = False
            if op == "fail":
                live = [n for n in nodes if n not in removes]
                if len(live) > 4:
                    dark = rng.choice(live)
                    failed.add(dark)
                    removes.add(dark)
                    res.strip({dark})
                    base.strip({dark})
            elif op == "remove":
                live = [n for n in nodes if n not in removes]
                if len(live) > 4:
                    removes.add(rng.choice(live))
            elif op == "readd":
                if removes:
                    back = rng.choice(sorted(removes))
                    removes.discard(back)
                    failed.discard(back)
            elif op == "drift":
                weights[f"p{rng.randrange(12):03d}"] = rng.randrange(
                    1, 9)
                opts = opts_now()
            elif op == "ndrift":
                nweights[rng.choice(nodes)] = rng.randrange(1, 5)
                opts = opts_now()
            elif op == "add" and spare:
                nodes = nodes + [spare.pop()]
            elif op == "diverge":
                fresh = True

            h0 = rec.counters.get("plan.solve.carry_hit", 0)
            m0 = rec.counters.get("plan.solve.carry_miss", 0)
            am, aw = await res.cycle(nodes, sorted(removes), opts,
                                     fresh_current=fresh)
            h1 = rec.counters.get("plan.solve.carry_hit", 0)
            m1 = rec.counters.get("plan.solve.carry_miss", 0)
            bm, bw = await base.cycle(nodes, sorted(removes), opts,
                                      fresh_current=fresh)
            h2 = rec.counters.get("plan.solve.carry_hit", 0)
            m2 = rec.counters.get("plan.solve.carry_miss", 0)
            ctx = f"seed={seed} step={step} op={op}"
            assert _nbs(am) == _nbs(bm), ctx
            assert aw == bw, ctx
            assert (h1 - h0, m1 - m0) == (h2 - h1, m2 - m1), \
                f"{ctx}: warm/cold solve decisions diverged"
            _check_resident_arrays(res, nodes, sorted(removes), opts,
                                   ctx)
        await res.svc.stop()
        await base.svc.stop()
        # Residency engaged for real across the run.
        assert rec.counters.get("fleet.encode_warm", 0) > 0
        assert rec.counters.get("fleet.encode_cold", 0) > 0

    _run(loop, rec, drive())


def test_fuzz_with_hierarchy_and_node_adds():
    """The gid-intern append path: rack hierarchy + same-rack rules,
    brand-new nodes joining existing and new racks — patched gid
    columns must equal the full re-encode's (first-seen interning can
    never renumber existing nodes)."""
    loop = DeterministicLoop(FifoPolicy(), max_steps=2_000_000)
    rec = Recorder(clock=loop.time)
    nodes = [f"n{i}" for i in range(8)]
    # x0 joins an existing rack (existing group id reused), x1 a brand
    # new rack (new group id appended) — the two intern paths, added in
    # ONE step so the whole test compiles only two bucket classes.
    extra = ["x0", "x1"]
    parents = {n: f"r{i % 4}" for i, n in enumerate(nodes)}
    parents.update({"x0": "r1", "x1": "r9"})
    for r in list(set(parents.values())):
        parents[r] = "dc"
    rules = {"replica": [HierarchyRule(include_level=2,
                                       exclude_level=1)]}
    _n, pmap = _cluster(nodes=8, parts=12)
    opts = PlanOptions(node_hierarchy=parents, hierarchy_rules=rules)

    async def drive():
        res = _Twin(rec, resident=True)
        base = _Twin(rec, resident=False)
        await res.start(pmap)
        await base.start({k: p.copy() for k, p in pmap.items()})
        seq = [list(nodes), list(nodes),
               list(nodes) + extra, list(nodes) + extra,
               list(nodes) + extra]
        removes = []
        for step, ns in enumerate(seq):
            if step == 3:
                removes = ["n3"]
            am, aw = await res.cycle(ns, removes, opts)
            bm, bw = await base.cycle(ns, removes, opts)
            ctx = f"hier step={step}"
            assert _nbs(am) == _nbs(bm), ctx
            assert aw == bw, ctx
            _check_resident_arrays(res, ns, removes, opts, ctx)
        assert rec.counters.get("fleet.encode_warm", 0) >= 3
        await res.svc.stop()
        await base.svc.stop()

    _run(loop, rec, drive())


def test_incremental_decode_warnings_bit_identical():
    """Constraint shortfalls (more constraint slots than live nodes)
    must produce the exact full-decode warnings from the incremental
    path — content AND dict construction order."""
    loop = DeterministicLoop(FifoPolicy(), max_steps=1_000_000)
    rec = Recorder(clock=loop.time)
    nodes, pmap = _cluster()  # the shared 12/12 bucket class
    dark = [f"n{i}" for i in range(11)]  # one live node: replica short

    async def drive():
        res = _Twin(rec, resident=True)
        base = _Twin(rec, resident=False)
        await res.start(pmap)
        await base.start({k: p.copy() for k, p in pmap.items()})
        opts = PlanOptions()
        for removes in ([], dark, dark):
            am, aw = await res.cycle(nodes, removes, opts)
            bm, bw = await base.cycle(nodes, removes, opts)
            assert _nbs(am) == _nbs(bm)
            assert aw == bw
            assert list(aw.keys()) == list(bw.keys())
        assert rec.counters.get("fleet.decode_patch", 0) > 0
        assert aw  # the shortfall rounds really warned
        await res.svc.stop()
        await base.svc.stop()

    _run(loop, rec, drive())


# -- demotion paths -----------------------------------------------------------


def test_divergence_statics_shape_and_eviction_each_demote_cold():
    """Every off-protocol event costs exactly one counted demotion (or
    eviction) followed by one cold re-encode — never a stale map."""
    loop = DeterministicLoop(FifoPolicy(), max_steps=2_000_000)
    rec = Recorder(clock=loop.time)
    nodes, pmap = _cluster()

    async def drive():
        res = _Twin(rec, resident=True)
        base = _Twin(rec, resident=False)
        await res.start(pmap)
        await base.start({k: p.copy() for k, p in pmap.items()})
        opts = PlanOptions()
        cache = res.planner._encodes

        def cold():
            return int(rec.counters.get("fleet.encode_cold", 0))

        await res.cycle(nodes, [], opts)
        await base.cycle(nodes, [], opts)
        assert cold() == 1

        # (1) divergence: an equal-but-new current object.
        am, _ = await res.cycle(nodes, [], opts, fresh_current=True)
        bm, _ = await base.cycle(nodes, [], opts, fresh_current=True)
        assert _nbs(am) == _nbs(bm)
        assert cold() == 2
        assert cache.demotions.get("divergence") == 1

        # (2) statics: a swapped hierarchy object.
        hier = {n: "r0" for n in nodes}
        hopts = PlanOptions(node_hierarchy=hier)
        am, _ = await res.cycle(nodes, [], hopts)
        bm, _ = await base.cycle(nodes, [], hopts)
        assert _nbs(am) == _nbs(bm)
        assert cold() == 3
        assert cache.demotions.get("statics") == 1

        # (3) eviction: byte-budget pressure drops the live state
        # (budgets enforce at cold-build puts; simulate pressure by
        # re-enforcing directly) — the next cycle solves cold.
        cache.max_bytes = 0
        cache._enforce_budget()
        assert cache.evictions.get("bytes", 0) >= 1
        cache.max_bytes = None
        am, _ = await res.cycle(nodes, [], hopts)
        bm, _ = await base.cycle(nodes, [], hopts)
        assert _nbs(am) == _nbs(bm)
        assert cold() == 4

        # Attribution identity: every cold is a first encode, a
        # demotion or an eviction.
        demos = sum(cache.demotions.values())
        evs = sum(cache.evictions.values())
        assert cold() == 1 + demos + evs
        await res.svc.stop()
        await base.svc.stop()

    _run(loop, rec, drive())


def test_shape_drift_demotes():
    """An initial map wider than the constraints (R=2 for a 1-slot
    state) narrows after the first adopted proposal — fresh encode
    would pick a smaller R, so the resident state must demote with
    reason 'shape' instead of solving at a stale slot depth."""
    loop = DeterministicLoop(FifoPolicy(), max_steps=1_000_000)
    rec = Recorder(clock=loop.time)
    nodes = [f"n{i}" for i in range(12)]
    pmap = {}
    for i in range(12):
        p = f"p{i:03d}"
        extra = [nodes[(i + 2) % 12]] if i == 0 else []
        pmap[p] = Partition(p, {
            "primary": [nodes[i % 12]] + extra,
            "replica": [nodes[(i + 1) % 12]]})

    async def drive():
        res = _Twin(rec, resident=True)
        base = _Twin(rec, resident=False)
        await res.start(pmap)
        await base.start({k: p.copy() for k, p in pmap.items()})
        opts = PlanOptions()
        st0 = None
        for step in range(3):
            am, _ = await res.cycle(nodes, [], opts)
            bm, _ = await base.cycle(nodes, [], opts)
            assert _nbs(am) == _nbs(bm), step
            if step == 0:
                st0 = res.planner._encodes.get("t0")
                assert st0 is not None and st0.problem.R == 2
        assert res.planner._encodes.demotions.get("shape", 0) >= 1
        await res.svc.stop()
        await base.svc.stop()

    _run(loop, rec, drive())


def test_passthrough_states_stay_on_full_path():
    """A map carrying an unmodeled state is out of residency protocol:
    every cycle re-encodes/decodes fully (no resident state is built),
    and results still match the never-resident twin bit-exactly —
    including the pass-through placements."""
    loop = DeterministicLoop(FifoPolicy(), max_steps=1_000_000)
    rec = Recorder(clock=loop.time)
    nodes, pmap = _cluster()  # the shared 12/12 bucket class
    for p in pmap.values():
        p.nodes_by_state["archive"] = [nodes[3]]

    async def drive():
        res = _Twin(rec, resident=True)
        base = _Twin(rec, resident=False)
        await res.start(pmap)
        await base.start({k: p.copy() for k, p in pmap.items()})
        opts = PlanOptions()
        for _ in range(3):
            am, aw = await res.cycle(nodes, [], opts)
            bm, bw = await base.cycle(nodes, [], opts)
            assert _nbs(am) == _nbs(bm)
            assert aw == bw
        assert res.planner._encodes.get("t0") is None
        assert rec.counters.get("fleet.encode_warm", 0) == 0
        assert rec.counters.get("fleet.decode_patch", 0) == 0
        await res.svc.stop()
        await base.svc.stop()

    _run(loop, rec, drive())


# -- EncodeCache --------------------------------------------------------------


def test_encode_cache_lru_budgets_and_counters():
    rec = Recorder()

    class _Fake:
        def __init__(self, n):
            self._n = n

        def nbytes(self):
            return self._n

    c = EncodeCache(max_entries=2, recorder=rec)
    c.put("a", _Fake(10))
    c.put("b", _Fake(10))
    c.get("a")  # bump recency: "b" is now LRU
    c.put("c", _Fake(10))
    assert sorted(c.keys()) == ["a", "c"]
    assert c.evictions.get("entries") == 1
    assert rec.counters.get(
        'fleet.encode_evictions{reason="entries"}') == 1

    c = EncodeCache(max_bytes=25, recorder=rec)
    c.put("a", _Fake(10))
    c.put("b", _Fake(10))
    c.put("c", _Fake(10))  # 30 bytes: oldest goes
    assert sorted(c.keys()) == ["b", "c"]
    assert c.evictions.get("bytes") == 1

    c.invalidate("b", "divergence")
    assert c.keys() == ["c"]
    assert c.demotions.get("divergence") == 1
    c.invalidate("b", "divergence")  # gone: not double-counted
    assert c.demotions.get("divergence") == 1
    stats = c.stats()
    assert stats["entries"] == 1 and stats["bytes"] == 10
    with pytest.raises(ValueError):
        EncodeCache(max_entries=0)
    with pytest.raises(ValueError):
        EncodeCache(max_bytes=-1)


# -- through the shared service (controller + simulator) ----------------------


@pytest.mark.parametrize("family,kw", [
    (fleet_zone_outage, dict(seed=5, tenants=6)),
    (fleet_onboarding, dict(seed=13, tenants=8)),
    (fleet_noisy_neighbor, dict(seed=29, tenants=6)),
])
def test_residency_is_pure_perf_through_the_fleet(family, kw):
    """Residency on vs off across the scenario families: byte-identical
    event logs, equal SLO summaries and final maps — residency is a
    pure perf change — plus the cold-attribution identity on the
    resident run."""
    scn = family(**kw)
    on = run_fleet_scenario(scn)
    off = run_fleet_scenario(scn, encode_residency=False)
    assert on.log_text() == off.log_text()
    assert on.summaries == off.summaries
    assert {k: _nbs(m) for k, m in on.final_maps.items()} == \
        {k: _nbs(m) for k, m in off.final_maps.items()}
    assert on.encode_warm > 0
    assert off.encode_warm == 0 and off.encode_cold == 0
    # Two-sided attribution: one state-establishing cold per tenant,
    # every extra preceded by a counted demotion/eviction (a demotion
    # on a tenant's final cycle has no rebuilding cold, hence <=).
    attributable = on.tenants + sum(on.encode_demotions.values()) \
        + sum(on.encode_evictions.values())
    assert on.tenants <= on.encode_cold <= attributable
    # Steady-state warm cycles: no full re-encode, no full decode
    # beyond the attributable colds.
    assert on.decode_full == on.encode_cold
    assert on.decode_patch == on.encode_warm


def test_supersede_divergence_demotes_and_recovers():
    """A delta landing mid-orchestration supersedes the pass; the
    achieved map diverges from the proposal, the planner demotes
    (reason divergence) and the next cycle re-encodes cold — final maps
    still identical to the never-resident controller."""

    def run(residency):
        loop = DeterministicLoop(FifoPolicy(), max_steps=2_000_000)
        rec = Recorder(clock=loop.time)

        async def drive():
            nodes, pmap = _cluster()

            async def slow_assign(stop_ch, node, partitions, states,
                                  ops):
                await asyncio.sleep(5.0)

            fc = FleetController(nodes, inline_solve=True,
                                 debounce_s=0.5, recorder=rec,
                                 encode_residency=residency)
            await fc.start()
            fc.add_tenant("t", M, pmap, slow_assign)
            fc.submit("t", ClusterDelta(fail=("n0",)))
            # Let the first pass start moving, then supersede it.
            await asyncio.sleep(2.0)
            fc.submit("t", ClusterDelta(fail=("n1",)))
            maps = await fc.quiesce_all()
            sup = fc.superseded
            demos = (dict(fc.encode_cache.demotions)
                     if fc.encode_cache is not None else {})
            await fc.stop()
            return maps, sup, demos

        with use_recorder(rec):
            return loop.run_until_complete(drive())

    on_maps, on_sup, demos = run(True)
    off_maps, off_sup, _ = run(False)
    assert on_sup == off_sup and on_sup >= 1
    assert demos.get("divergence", 0) >= 1
    assert {k: _nbs(m) for k, m in on_maps.items()} == \
        {k: _nbs(m) for k, m in off_maps.items()}


def test_fleet_loop_resident_emissions_are_registry_declared():
    """The residency plane's emissions (encode/decode counters,
    patch histograms, eviction/demotion labels, h2d bytes) are all
    declared in the registry."""
    from blance_tpu.obs.expo import default_registry

    scn = fleet_zone_outage(seed=5, tenants=4)
    loop = DeterministicLoop(FifoPolicy(), max_steps=scn.max_steps)
    rec = Recorder(clock=loop.time)
    from blance_tpu.testing.fleetsim import _fleet_main

    with use_recorder(rec):
        loop.run_until_complete(_fleet_main(scn, loop, rec, True))
    assert rec.counters.get("fleet.encode_warm", 0) > 0
    assert rec.counters.get("fleet.h2d_bytes", 0) > 0
    assert default_registry().undeclared(rec) == []
