"""Port of the reference's move-calculus tests (moves_test.go:19-517),
including the before/moves/after ASCII mini-DSL and its flip-side verifier."""

from blance_tpu import calc_partition_moves
from blance_tpu.moves.calc import _find_state_changes

STATES = ["primary", "replica"]


def line_to_nodes_by_state(line, states):
    """' a b | +c -d' -> {'primary': ['a','b'], 'replica': ['+c','-d']}
    (moves_test.go:491-517)."""
    line = " ".join(line.split())
    parts = line.split("|")
    nbs = {}
    for i, state in enumerate(states):
        if i >= len(parts):
            break
        part = parts[i].strip()
        if part:
            nbs.setdefault(state, []).extend(part.split(" "))
    return nbs


def test_find_state_changes():
    cases = [
        (0, 0, "primary", {"primary": ["a"], "replica": ["b", "c"]},
         {"primary": ["a"], "replica": ["b", "c"]}, []),
        (1, 2, "primary", {"primary": ["a"], "replica": ["b", "c"]},
         {"primary": ["a"], "replica": ["b", "c"]}, []),
        (0, 0, "primary", {"primary": [], "replica": ["a"]},
         {"primary": ["a"], "replica": []}, []),
        (1, 2, "primary", {"primary": [], "replica": ["a"]},
         {"primary": ["a"], "replica": []}, ["a"]),
        (0, 1, "replica", {"primary": ["a"], "replica": []},
         {"primary": [], "replica": ["a"]}, ["a"]),
        (1, 2, "replica", {"primary": ["a"], "replica": []},
         {"primary": [], "replica": ["a"]}, []),
        (1, 2, "replica", {"primary": [], "replica": ["a"]},
         {"primary": [], "replica": []}, []),
        (1, 2, "primary", {"primary": ["a"], "replica": ["b", "c", "d"]},
         {"primary": ["b"], "replica": ["a", "c", "d"]}, ["b"]),
        (1, 2, "primary", {"primary": ["a"], "replica": ["b", "c", "d"]},
         {"primary": ["x"], "replica": ["a", "c", "d"]}, []),
    ]
    for beg_idx, end_idx, state, beg, end, exp in cases:
        assert _find_state_changes(beg_idx, end_idx, state, STATES, beg, end) == exp


# (before, moves, after, favor_min_nodes) — moves_test.go:151-360.
CASES = [
    (" a", "", " a", False),
    (" a", "", " a", True),
    ("      | a", "", "      | a", False),
    ("      | a", "", "      | a", True),
    (" a    | b", "", " a    | b", False),
    (" a    | b", "", " a    | b", True),  # Test #5
    ("", "+a", " a", False),
    ("", "+a", " a", True),
    (" a", "-a", "", False),
    (" a", "-a", "", True),
    ("", "+a    |\n a    |+b", " a    | b", False),  # Test #10
    ("", "      |+b\n +a    | b", " a    | b", True),
    (" a    | b", " a    |-b", " a", False),
    (" a    | b", " a    |-b", " a", True),
    (" a    | b", "-a    | b", "      | b", False),
    (" a    | b", "-a    | b", "      | b", True),  # Test #15
    (" a    | b", "-a    | b\n       |-b", "", False),
    (" a    | b", " a    |-b\n -a    |", "", True),
    (" a", " a +b |\n -a  b |", "    b", False),
    (" a", "-a    |\n    +b |", "    b", True),
    (" a    | b  c", " a +b |-b  c\n -a  b |    c\n     b |    c +d",
     "    b |    c  d", False),  # Test #20
    (" a    | b  c", " a    | b  c +d\n -a    | b  c  d\n    +b |-b  c  d",
     "    b |    c  d", True),
    (" a    |    b", " a +b |   -b\n -a  b |+a", "    b | a", False),
    (" a    |    b", "-a    |+a  b\n    +b | a -b", "    b | a", True),
    (" a    |    b", " a +c |    b\n -a  c |+a  b\n     c | a -b",
     "    c | a", False),
    (" a    |    b", " a    |   -b\n -a    |+a\n    +c | a",
     "    c | a", True),  # Test #25
    (" a    | b", " a +c | b\n -a  c | b\n     c | b +d\n     c |-b  d",
     "    c |    d", False),
    (" a    | b", " a    |-b\n  a    |   +d\n -a    |    d\n    +c |    d",
     "    c |    d", True),
    (" a    |    b", "-a    |+a  b\n       | a  b +c", "      | a  b  c", False),
]

_NEGATE = {"+": "-", "-": "+"}
_OPS = {"+": "add", "-": "del"}


def test_calc_partition_moves():
    for testi, (before_s, moves_s, after_s, favor_min) in enumerate(CASES):
        before = line_to_nodes_by_state(before_s, STATES)
        after = line_to_nodes_by_state(after_s, STATES)

        moves_exp = []
        if moves_s != "":
            for move_line in moves_s.split("\n"):
                moves_exp.append(line_to_nodes_by_state(move_line, STATES))

        moves_got = calc_partition_moves(STATES, before, after, favor_min)
        assert len(moves_got) == len(moves_exp), (
            f"test {testi}: got {moves_got}, exp {moves_exp}")

        # The flip-side verifier (moves_test.go:397-484): each expected move
        # line has exactly one +x/-x token; if the opposite token appears in a
        # lower state on the same line, the op is a promote/demote.
        for i, move_exp in enumerate(moves_exp):
            got = moves_got[i]
            found = False
            for statei, state in enumerate(STATES):
                if found:
                    continue
                for move in move_exp.get(state, []):
                    if found:
                        continue
                    op = move[0:1]
                    if op in ("+", "-"):
                        found = True
                        assert got.node == move[1:], (
                            f"test {testi} move {i}: node {got} vs {move}")
                        flip = _NEGATE[op] + move[1:]
                        flip_state = ""
                        for j in range(statei + 1, len(STATES)):
                            if flip in move_exp.get(STATES[j], []):
                                flip_state = STATES[j]
                        if flip_state:
                            state_exp = flip_state if op == "-" else state
                            assert got.op in ("promote", "demote"), (
                                f"test {testi} move {i}: {got}")
                        else:
                            state_exp = "" if op == "-" else state
                            assert got.op == _OPS[op], (
                                f"test {testi} move {i}: {got}")
                        assert got.state == state_exp, (
                            f"test {testi} move {i}: {got}, exp state {state_exp!r}")
