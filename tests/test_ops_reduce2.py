"""Pallas fused (min, argmin, second-min) kernel vs the XLA oracle.

Runs the kernel in interpret mode (tests run on the CPU platform,
tests/conftest.py); compiled-mode parity on a real chip is exercised by
bench.py.  The contract under test is the one the auction loop
(blance_tpu/plan/tensor.py) depends on:

- argmin ties break to the lowest index (determinism of the planner);
- ``second`` masks the argmin POSITION, so duplicate minima at different
  indices give second == best (the urgency margin must be 0 then);
- ragged P and N tails change nothing (no host-side padding).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from blance_tpu.ops.reduce2 import min2_argmin, min2_argmin_reference


def _check(x, tile_p=8, tile_n=128):
    b0, i0, s0 = min2_argmin_reference(jnp.asarray(x))
    b1, i1, s1 = min2_argmin(
        jnp.asarray(x), tile_p=tile_p, tile_n=tile_n, interpret=True)
    np.testing.assert_array_equal(np.asarray(b0), np.asarray(b1))
    np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
    np.testing.assert_array_equal(np.asarray(s0), np.asarray(s1))


@pytest.mark.parametrize("shape", [(7, 5), (16, 128), (130, 300), (33, 513)])
def test_matches_oracle_random(shape):
    rng = np.random.default_rng(hash(shape) % 2**32)
    _check(rng.standard_normal(shape).astype(np.float32))


def test_ties_break_low_index_across_tiles():
    # The global min appears in several N tiles; argmin must pick the first.
    x = np.ones((9, 300), np.float32)
    x[:, 37] = x[:, 157] = x[:, 290] = -2.0
    b, i, s = min2_argmin(jnp.asarray(x), tile_p=8, tile_n=128,
                          interpret=True)
    assert np.asarray(i).tolist() == [37] * 9
    # Duplicate minimum elsewhere => second == best.
    np.testing.assert_array_equal(np.asarray(s), np.asarray(b))


def test_second_masks_position_not_value():
    x = np.full((3, 10), 5.0, np.float32)
    x[0, 4] = 1.0          # unique min: second is 5
    x[1, 2] = x[1, 7] = 1.0  # duplicate min: second is 1
    b, i, s = min2_argmin(jnp.asarray(x), tile_p=8, tile_n=8, interpret=True)
    assert np.asarray(b).tolist() == [1.0, 1.0, 5.0]
    assert np.asarray(i).tolist() == [4, 2, 0]
    assert np.asarray(s).tolist() == [5.0, 1.0, 5.0]


def test_inf_rows():
    # Fully forbidden rows (all +inf) must not crash and keep index 0.
    x = np.full((4, 20), np.inf, np.float32)
    x[1, 3] = 7.0
    _check(x, tile_p=2, tile_n=16)


@pytest.mark.parametrize("shape", [(7, 5), (33, 513)])
def test_priced_variant_matches_materialized(shape):
    """priced_min2_argmin(score, price) == oracle(score + price[None, :]) —
    the auction-loop contract (price folded in VMEM, never in HBM)."""
    from blance_tpu.ops.reduce2 import priced_min2_argmin

    rng = np.random.default_rng(hash(shape) % 2**31)
    score = rng.standard_normal(shape).astype(np.float32)
    price = (rng.random(shape[1]) * 3).astype(np.float32)
    price[::4] = 1e9  # closed nodes
    b0, i0, s0 = min2_argmin_reference(jnp.asarray(score + price[None, :]))
    b1, i1, s1 = priced_min2_argmin(
        jnp.asarray(score), jnp.asarray(price), tile_p=8, tile_n=128,
        interpret=True)
    np.testing.assert_array_equal(np.asarray(b0), np.asarray(b1))
    np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
    np.testing.assert_array_equal(np.asarray(s0), np.asarray(s1))
