"""Tests for the static-analysis suite itself (blance_tpu/analysis).

Three layers, mirroring docs/STATIC_ANALYSIS.md:

- rule fixtures: a snippet that MUST trip each rule, and a clean twin
  that must NOT (the false-positive guard — a lint nobody trusts is a
  lint nobody runs);
- baseline semantics: matching (symbol/line pinning), stale-entry
  detection, and the parse errors that keep the allowlist honest;
- end-to-end: the real package carries zero non-baselined findings, an
  injected violation fails the CLI, and the eval_shape contract table
  passes against the live solver.
"""

import textwrap

import pytest

from blance_tpu.analysis import Finding, run_all, run_lints
from blance_tpu.analysis.asyncio_lint import lint_source
from blance_tpu.analysis.baseline import (
    Baseline,
    BaselineEntry,
    parse_toml_findings,
)
from blance_tpu.analysis.jit_purity import JitPurityPass


def _jit_findings(tmp_path, source, name="mod.py"):
    f = tmp_path / name
    f.write_text(textwrap.dedent(source))
    return JitPurityPass([str(f)], repo_root=str(tmp_path)).run()


def _rules(findings):
    return sorted({f.rule for f in findings})


# -- jit purity: each rule trips, and its clean twin does not ---------------


def test_jit001_host_nondeterminism_trips(tmp_path):
    fs = _jit_findings(tmp_path, """
        import time
        import jax

        @jax.jit
        def f(x):
            t = time.perf_counter()
            return x + t
    """)
    assert _rules(fs) == ["JIT001"]
    assert fs[0].symbol == "f"


def test_jit001_numpy_random_trips(tmp_path):
    fs = _jit_findings(tmp_path, """
        import numpy as np
        import jax

        @jax.jit
        def f(x):
            return x + np.random.rand()
    """)
    assert _rules(fs) == ["JIT001"]


def test_jit001_reached_helper_trips(tmp_path):
    # Impurity in a helper REACHED from a jit root is still a finding.
    fs = _jit_findings(tmp_path, """
        import random
        import jax

        def helper(x):
            return x * random.random()

        @jax.jit
        def f(x):
            return helper(x)
    """)
    assert _rules(fs) == ["JIT001"]
    assert fs[0].symbol == "helper"


def test_jit001_unreached_host_code_is_clean(tmp_path):
    # The same impurity OUTSIDE the traced call graph is fine.
    fs = _jit_findings(tmp_path, """
        import time
        import jax

        def host_wrapper(x):
            t0 = time.perf_counter()
            out = f(x)
            return out, time.perf_counter() - t0

        @jax.jit
        def f(x):
            return x + 1
    """)
    assert fs == []


def test_jit002_traced_branch_trips(tmp_path):
    fs = _jit_findings(tmp_path, """
        import jax

        @jax.jit
        def f(x):
            if x > 0:
                return x
            return -x
    """)
    assert _rules(fs) == ["JIT002"]


def test_jit002_static_and_is_none_branches_are_clean(tmp_path):
    fs = _jit_findings(tmp_path, """
        from functools import partial
        import jax

        @partial(jax.jit, static_argnames=("mode",))
        def f(x, mode, y=None):
            if mode == "fast":
                x = x * 2
            if y is not None:
                x = x + y
            if x.shape[0] > 4:
                x = x[:4]
            return x
    """)
    assert fs == []


def test_jit003_coercion_trips_and_shape_is_clean(tmp_path):
    fs = _jit_findings(tmp_path, """
        import jax

        @jax.jit
        def f(x):
            return float(x)

        @jax.jit
        def g(x):
            n = int(x.shape[0])
            return x * n
    """)
    assert _rules(fs) == ["JIT003"]
    assert all(f.symbol == "f" for f in fs)


def test_jit004_captured_mutation_trips_local_is_clean(tmp_path):
    fs = _jit_findings(tmp_path, """
        import jax

        _CACHE = {}
        _SEEN = []

        @jax.jit
        def f(x):
            _SEEN.append(1)
            return x

        @jax.jit
        def g(x):
            local = []
            local.append(1)
            return x

        @jax.jit
        def h(x):
            global _MODE
            _MODE = "hot"
            return x
    """)
    assert _rules(fs) == ["JIT004"]
    assert sorted(f.symbol for f in fs) == ["f", "h"]


def test_jit004_subscript_write_does_not_hide_capture(tmp_path):
    # d[k] = v must NOT make ``d`` look locally bound.
    fs = _jit_findings(tmp_path, """
        import jax

        _MEMO = {}

        @jax.jit
        def f(x):
            _MEMO["k"] = 1
            _MEMO.clear()
            return x
    """)
    assert _rules(fs) == ["JIT004"]


def test_jit005_bogus_static_argname_trips(tmp_path):
    fs = _jit_findings(tmp_path, """
        from functools import partial
        import jax

        @partial(jax.jit, static_argnames=("mode", "modes"))
        def f(x, mode):
            return x
    """)
    assert _rules(fs) == ["JIT005"]
    assert "modes" in fs[0].message


def test_jit_roots_via_call_and_partial_forms(tmp_path):
    # name = jax.jit(f, ...) and partial(jax.jit, ...)(f) both root f.
    fs = _jit_findings(tmp_path, """
        from functools import partial
        import time
        import jax

        def f(x):
            return x + time.time()

        def g(x):
            return x * time.time()

        f_jit = jax.jit(f)
        g_jit = partial(jax.jit, static_argnames=())(g)
    """)
    assert _rules(fs) == ["JIT001"]
    assert sorted(x.symbol for x in fs) == ["f", "g"]


def test_jit001_reached_through_package_reexport(tmp_path):
    """Impurity must stay visible through the `from .impl import helper`
    + `from . import helper` package re-export idiom the codebase uses
    for its public surfaces."""
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "impl.py").write_text(textwrap.dedent("""
        import time

        def helper(x):
            return x + time.time()
    """))
    (pkg / "__init__.py").write_text("from .impl import helper\n")
    (pkg / "use.py").write_text(textwrap.dedent("""
        import jax
        from . import helper

        @jax.jit
        def f(x):
            return helper(x)
    """))
    files = [str(pkg / n) for n in ("__init__.py", "impl.py", "use.py")]
    fs = JitPurityPass(files, repo_root=str(tmp_path)).run()
    assert _rules(fs) == ["JIT001"]
    assert fs[0].symbol == "helper" and fs[0].path == "pkg/impl.py"


def test_jit_root_via_shard_map_wrapper(tmp_path):
    fs = _jit_findings(tmp_path, """
        from functools import partial
        import time
        from jax.experimental.shard_map import shard_map as _shard_map

        def body(x):
            return x + time.time()

        def build(mesh, spec):
            fn = _shard_map(partial(body), mesh=mesh,
                            in_specs=spec, out_specs=spec)
            return fn
    """)
    assert _rules(fs) == ["JIT001"]
    assert fs[0].symbol == "body"


# -- asyncio lint -----------------------------------------------------------


def _asy(source):
    return lint_source(textwrap.dedent(source), "/r/mod.py", "/r")


def test_asy101_fire_and_forget_trips_stored_is_clean():
    fs = _asy("""
        import asyncio

        async def bad(coro):
            asyncio.ensure_future(coro)

        async def good(coro, tasks):
            t = asyncio.ensure_future(coro)
            tasks.append(t)
            await t
    """)
    assert _rules(fs) == ["ASY101"]
    assert fs[0].symbol == "bad"


def test_asy102_blocking_call_trips_async_sleep_is_clean():
    fs = _asy("""
        import asyncio
        import time

        async def bad():
            time.sleep(1.0)

        async def good():
            await asyncio.sleep(1.0)

        def sync_ok():
            time.sleep(0.1)
    """)
    assert _rules(fs) == ["ASY102"]
    assert fs[0].symbol == "bad"


def test_asy103_silent_swallow_trips():
    fs = _asy("""
        def bad():
            try:
                work()
            except Exception:
                return False
            return True
    """)
    assert _rules(fs) == ["ASY103"]


def test_asy103_using_or_raising_handler_is_clean():
    fs = _asy("""
        import logging

        def uses_exc():
            try:
                work()
            except Exception as e:
                logging.warning("failed: %s", e)
                return False
            return True

        def reraises():
            try:
                work()
            except Exception:
                cleanup()
                raise

        def narrow():
            try:
                work()
            except ValueError:
                return False
    """)
    assert fs == []


def test_asy104_undeadlined_callback_await_trips():
    fs = _asy("""
        class O:
            async def run(self, node):
                result = self._assign_partitions(node)
                return await result
    """)
    assert _rules(fs) == ["ASY104"]


def test_asy104_wait_for_wrapped_is_clean():
    fs = _asy("""
        import asyncio

        class O:
            async def run(self, node):
                result = self._assign_partitions(node)
                return await asyncio.wait_for(result, 5.0)
    """)
    assert fs == []


# -- race lint ---------------------------------------------------------------


def _race(source, shared=None):
    from blance_tpu.analysis.race_lint import lint_source

    return lint_source(textwrap.dedent(source), "/r/mod.py", "/r",
                       shared_state=shared)


_TOY_SHARED = {"Orchestrator": frozenset({"_flag", "_count", "_items"})}


def test_race001_rmw_across_await_trips():
    fs = _race("""
        class Orchestrator:
            async def bump(self):
                tmp = self._count
                await self._notify()
                self._count = tmp + 1
    """, shared=_TOY_SHARED)
    assert _rules(fs) == ["RACE001"]
    assert fs[0].symbol == "Orchestrator.bump"


def test_race001_augassign_with_awaiting_rhs_trips():
    # self.x += await f(): CPython reads self.x BEFORE the await and
    # writes after — the torn RMW in a single statement.
    fs = _race("""
        class Orchestrator:
            async def bump(self):
                self._count += await self._notify()
    """, shared=_TOY_SHARED)
    assert _rules(fs) == ["RACE001"]
    assert "pre-await" in fs[0].message


def test_race001_augassign_without_await_is_clean():
    fs = _race("""
        class Orchestrator:
            async def bump(self):
                self._count += 1
                await self._notify()
    """, shared=_TOY_SHARED)
    assert fs == []


def test_race001_atomic_rmw_is_clean():
    # Same RMW with no intervening await: atomic in asyncio, clean.
    fs = _race("""
        class Orchestrator:
            async def bump(self):
                tmp = self._count
                self._count = tmp + 1
                await self._notify()
    """, shared=_TOY_SHARED)
    assert fs == []


def test_race002_stale_guard_trips():
    fs = _race("""
        class Orchestrator:
            async def run(self):
                flag = self._flag
                await self._notify()
                if flag is not None:
                    await flag.get()
    """, shared=_TOY_SHARED)
    assert _rules(fs) == ["RACE002"]
    assert "revalidat" in fs[0].message or "re-read" in fs[0].message


def test_race002_revalidation_loop_is_clean():
    # The fixed supplier shape: re-bind from the attribute after every
    # wake, use before any further await.
    fs = _race("""
        class Orchestrator:
            async def run(self):
                await self._notify()
                while True:
                    flag = self._flag
                    if flag is None:
                        break
                    await flag.get()
    """, shared=_TOY_SHARED)
    assert fs == []


def test_race002_use_before_await_is_clean():
    fs = _race("""
        class Orchestrator:
            async def run(self):
                flag = self._flag
                if flag is not None:
                    await flag.get()
    """, shared=_TOY_SHARED)
    assert fs == []


def test_race002_untracked_attr_is_clean():
    # Locals from attributes OUTSIDE the shared-state model never trip.
    fs = _race("""
        class Orchestrator:
            async def run(self):
                opts = self.options
                await self._notify()
                return opts.timeout
    """, shared=_TOY_SHARED)
    assert fs == []


def test_race003_multi_root_mutation_trips():
    fs = _race("""
        import asyncio

        class Orchestrator:
            def start(self):
                self._spawn(self._worker_a())
                self._spawn(self._worker_b())

            def _spawn(self, coro):
                return asyncio.ensure_future(coro)

            async def _worker_a(self):
                self._items.append(1)
                await self._notify()

            async def _worker_b(self):
                self._items.append(2)
                await self._notify()
    """, shared=_TOY_SHARED)
    assert _rules(fs) == ["RACE003"]
    assert "_items" in fs[0].message
    assert "_worker_a" in fs[0].message and "_worker_b" in fs[0].message


def test_race003_subscript_writes_count_as_mutations():
    # self._items[k] = v / del self._items[k] mutate the shared
    # container just as surely as .append does.
    fs = _race("""
        import asyncio

        class Orchestrator:
            def start(self):
                self._spawn(self._worker_a())
                self._spawn(self._worker_b())

            def _spawn(self, coro):
                return asyncio.ensure_future(coro)

            async def _worker_a(self):
                self._items["a"] = 1
                await self._notify()

            async def _worker_b(self):
                del self._items["b"]
                await self._notify()
    """, shared=_TOY_SHARED)
    assert _rules(fs) == ["RACE003"]
    assert "_items" in fs[0].message


def test_race003_single_root_is_clean():
    fs = _race("""
        import asyncio

        class Orchestrator:
            def start(self):
                self._spawn(self._worker())

            def _spawn(self, coro):
                return asyncio.ensure_future(coro)

            async def _worker(self):
                self._items.append(1)
                await self._notify()
                self._helper()

            def _helper(self):
                self._items.append(2)
    """, shared=_TOY_SHARED)
    assert fs == []


def test_race003_needs_a_task_owning_class():
    # A passive shared structure (no spawns) is RACE001/002 territory;
    # RACE003 stays quiet.
    fs = _race("""
        class Orchestrator:
            def a(self):
                self._items.append(1)

            def b(self):
                self._items.append(2)
    """, shared=_TOY_SHARED)
    assert fs == []


def test_race_lint_ignores_unmodeled_classes():
    fs = _race("""
        class Whatever:
            async def run(self):
                flag = self._flag
                await self._notify()
                return flag
    """, shared=_TOY_SHARED)
    assert fs == []


def test_race_lint_real_package_model_matches_reality():
    """The shared-state table must keep naming real attributes of the
    real classes — a renamed attribute would silently blind the lint."""
    import blance_tpu.obs.costmodel as costmodel
    import blance_tpu.obs.slo as slo
    import blance_tpu.orchestrate.csp as csp
    import blance_tpu.orchestrate.health as health
    import blance_tpu.orchestrate.orchestrator as orch
    import importlib

    import blance_tpu.orchestrate.sched.policy as schedpolicy
    import blance_tpu.plan.carry as plancarry
    import blance_tpu.plan.service as planservice
    from blance_tpu.analysis.race_lint import SHARED_STATE

    import blance_tpu.control as control
    import blance_tpu.durability.epoch as depoch
    import blance_tpu.durability.journal as djournal
    import blance_tpu.fleetloop as fleetloop

    # `import blance_tpu.rebalance as ...` would resolve to the
    # same-named FUNCTION the package re-exports, not the module.
    rebalance = importlib.import_module("blance_tpu.rebalance")

    import inspect

    sources = {
        "CycleEngine": inspect.getsource(control.CycleEngine),
        "FleetController": inspect.getsource(fleetloop.FleetController),
        "FleetSloRollup": inspect.getsource(slo.FleetSloRollup),
        "Orchestrator": inspect.getsource(orch.Orchestrator),
        "OrchestratorProgress": inspect.getsource(
            orch.OrchestratorProgress),
        "HealthTracker": inspect.getsource(health.HealthTracker),
        "NodeHealth": inspect.getsource(health.NodeHealth),
        "Chan": inspect.getsource(csp.Chan),
        "NextMoves": inspect.getsource(orch.NextMoves),
        "SloTracker": inspect.getsource(slo.SloTracker),
        "CostModel": inspect.getsource(costmodel.CostModel),
        "PlanService": inspect.getsource(planservice.PlanService),
        "CarryCache": inspect.getsource(plancarry.CarryCache),
        "EncodeCache": inspect.getsource(plancarry.EncodeCache),
        "RebalanceController": inspect.getsource(
            rebalance.RebalanceController),
        "_CriticalPathBound": inspect.getsource(
            schedpolicy._CriticalPathBound),
        "Journal": inspect.getsource(djournal.Journal),
        "EpochFence": inspect.getsource(depoch.EpochFence),
    }
    for cls, attrs in SHARED_STATE.items():
        src = sources[cls]
        for attr in attrs:
            leaf = attr.split(".")[0]
            assert leaf in src, \
                f"SHARED_STATE[{cls!r}] names {leaf!r} which no longer " \
                f"appears in the class source — update the model"


# -- baseline semantics -----------------------------------------------------


def _finding(rule="ASY103", path="pkg/m.py", line=10, symbol="f"):
    return Finding(rule=rule, path=path, line=line, symbol=symbol,
                   message="msg")


def test_baseline_matches_on_rule_path_symbol():
    b = Baseline([BaselineEntry(rule="ASY103", path="pkg/m.py",
                                symbol="f", reason="why")])
    new, accepted = b.split([_finding(), _finding(symbol="g")])
    assert [f.symbol for f in new] == ["g"]
    assert [(f.symbol, r) for f, r in accepted] == [("f", "why")]
    assert b.unused() == []


def test_baseline_line_pin_and_stale_entries():
    entries = [
        BaselineEntry(rule="ASY103", path="pkg/m.py", line=10,
                      reason="pinned"),
        BaselineEntry(rule="JIT001", path="pkg/other.py",
                      reason="stale"),
    ]
    b = Baseline(entries)
    new, accepted = b.split([_finding(line=10), _finding(line=11)])
    assert [f.line for f in new] == [11]
    assert len(accepted) == 1
    assert [e.reason for e in b.unused()] == ["stale"]


def test_baseline_toml_roundtrip_and_errors():
    entries = parse_toml_findings(textwrap.dedent("""
        # comment
        [[finding]]
        rule = "ASY103"
        path = "pkg/m.py"  # trailing comment
        symbol = "f"
        line = 12
        reason = "a \\"quoted\\" reason"
    """))
    assert len(entries) == 1
    e = entries[0]
    assert (e.rule, e.path, e.symbol, e.line) == \
        ("ASY103", "pkg/m.py", "f", 12)
    assert e.reason == 'a "quoted" reason'

    # Shared validation: identical on the tomllib and subset paths.
    with pytest.raises(ValueError, match="missing required key"):
        parse_toml_findings('[[finding]]\nrule = "X"\npath = "p"\n')
    with pytest.raises(ValueError, match="unknown keys"):
        parse_toml_findings(
            '[[finding]]\nrule = "X"\npath = "p"\nreason = "r"\n'
            'bogus = "v"\n')
    # A stray top-level key is an error on either path (the messages
    # differ: tomllib flags the unknown table, the subset the bare key).
    with pytest.raises(ValueError):
        parse_toml_findings('rule = "X"\n')


def test_baseline_subset_parser_errors():
    """The 3.10 fallback parser's own strictness (exercised explicitly
    so the tomllib path on newer Pythons doesn't mask it)."""
    from blance_tpu.analysis.baseline import _parse_subset

    with pytest.raises(ValueError, match="unsupported"):
        _parse_subset('[[finding]]\nrule = [1]\n', "<t>")
    with pytest.raises(ValueError, match="outside"):
        _parse_subset('rule = "X"\n', "<t>")
    with pytest.raises(ValueError, match="expected key"):
        _parse_subset('[[finding]]\njunk\n', "<t>")
    entries = _parse_subset(
        '[[finding]]\nrule = "R"\npath = "p"\nreason = "r"\nline = 3\n',
        "<t>")
    assert entries[0].line == 3


# -- end-to-end -------------------------------------------------------------


def test_package_has_zero_nonbaselined_findings():
    """The gate the static CI tier enforces, minus the shape audit
    (covered separately below so this stays sub-second)."""
    result = run_all(shape_audit=False)
    assert result.errors == []
    rendered = "\n".join(f.render() for f in result.new)
    assert result.new == [], f"non-baselined findings:\n{rendered}"
    # The allowlist carries no dead weight.
    stale = [e.render() for e in result.unused_baseline]
    assert stale == [], f"stale baseline entries: {stale}"


def test_lints_cover_expected_file_count():
    _, nfiles = run_lints()
    # The package's module count only grows; a collapse here means the
    # walker lost a directory.
    assert nfiles >= 30


def test_cli_fails_on_injected_violation(tmp_path, capsys):
    from blance_tpu.analysis.__main__ import main

    bad = tmp_path / "violation.py"
    bad.write_text(textwrap.dedent("""
        import time
        import jax

        @jax.jit
        def f(x):
            return x + time.time()
    """))
    rc = main([str(bad)])
    out = capsys.readouterr().out
    assert rc == 1
    assert "JIT001" in out and "FAIL" in out

    clean = tmp_path / "clean.py"
    clean.write_text("import jax\n\n@jax.jit\ndef f(x):\n    return x\n")
    assert main([str(clean)]) == 0


def test_cli_stale_baseline_warns_by_default_fails_under_ci(tmp_path,
                                                            capsys,
                                                            monkeypatch):
    """A baseline entry matching nothing is a warning in the editor
    loop but a hard error under --ci (a fixed finding must delete its
    suppression in the same change)."""
    from blance_tpu.analysis import retrace
    from blance_tpu.analysis.__main__ import main

    # --ci also runs the device retrace-budget workload (real solver
    # compiles); stub it here — this test pins the stale-baseline
    # semantics, and the real workload is covered by
    # tests/test_device_obs.py plus the CI device-obs step.
    monkeypatch.setattr(retrace, "_workload", lambda: None)

    clean = tmp_path / "clean.py"
    clean.write_text("import jax\n\n@jax.jit\ndef f(x):\n    return x\n")
    stale = tmp_path / "baseline.toml"
    stale.write_text(
        '[[finding]]\nrule = "JIT001"\npath = "gone.py"\n'
        'reason = "fixed long ago"\n')

    rc = main([str(clean), "--baseline", str(stale)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "warning: stale baseline entry" in out

    # --ci implies the shape audit; pointing the run at the tmp file
    # keeps the lint scope identical while the audit runs for real.
    rc = main([str(clean), "--baseline", str(stale), "--ci"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "ERROR: stale baseline entry" in out and "FAIL" in out


def test_cli_json_output(tmp_path, capsys):
    import json

    from blance_tpu.analysis.__main__ import main

    bad = tmp_path / "violation.py"
    bad.write_text(
        "import asyncio\n\nasync def f(c):\n"
        "    asyncio.ensure_future(c)\n")
    rc = main(["--json", str(bad)])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert payload["pass"] is False
    assert [f["rule"] for f in payload["new"]] == ["ASY101"]


def test_shape_audit_passes_against_live_solver():
    """Every declared contract holds on the real entry points; the full
    matrix (cold/carry/bucketed/sharded + encode/decode + bucketing
    algebra) runs in seconds with zero FLOPs."""
    from blance_tpu.analysis.shape_audit import CONTRACTS, run_shape_audit

    findings, entries = run_shape_audit()
    rendered = "\n".join(f.render() for f in findings)
    assert findings == [], f"shape contract violations:\n{rendered}"
    assert entries == len(CONTRACTS) + 4  # + encode-residency check
    # Acceptance coverage: warm, sharded and bucketed variants all audit.
    entry_names = {c.entry for c in CONTRACTS}
    assert {"solve_dense", "solve_dense_converged", "solve_dense_warm",
            "solve_dense_sharded", "carry_from_assignment"} <= entry_names
    assert any("bucketed" in c.variant for c in CONTRACTS)


def test_shape_audit_catches_drift(monkeypatch):
    """Break a contract deliberately: the audit must report SHP001."""
    from blance_tpu.analysis import shape_audit as sa

    broken = sa.ShapeContract(
        entry="solve_dense", variant="drifted",
        build=lambda: sa._build_solve_dense(sa.Dims(P=8, S=1, N=5, R=1)),
        expect=lambda: ((8, 1, 2), "int32"))  # wrong R
    monkeypatch.setattr(sa, "CONTRACTS", (broken,))
    findings, _ = sa.run_shape_audit()
    assert any(f.rule == "SHP001" for f in findings)


# -- determinism lint: each rule trips, and its clean twin does not ----------


def _det_findings(tmp_path, source, name="mod.py", *, roots=None,
                  clock_seams=None, serialized_sinks=None,
                  config_knobs=None):
    from blance_tpu.analysis.determinism import DeterminismPass

    f = tmp_path / name
    f.write_text(textwrap.dedent(source))
    return DeterminismPass(
        [str(f)], repo_root=str(tmp_path),
        replay_roots={"mod": "fixture"} if roots is None else roots,
        clock_seams=clock_seams or {},
        serialized_sinks=serialized_sinks,
        config_knobs=config_knobs or {},
    ).run()


def test_det001_wall_clock_trips(tmp_path):
    fs = _det_findings(tmp_path, """
        import time

        def f():
            return time.monotonic()
    """)
    assert _rules(fs) == ["DET001"]
    assert fs[0].symbol == "f"


def test_det001_raw_loop_time_trips(tmp_path):
    fs = _det_findings(tmp_path, """
        def f(loop):
            return loop.time() + 1.0
    """)
    assert _rules(fs) == ["DET001"]


def test_det001_clean_inside_declared_seam(tmp_path):
    fs = _det_findings(tmp_path, """
        import time

        def f():
            return time.perf_counter()
    """, clock_seams={"mod.f": "the declared boundary"})
    assert fs == []


def test_det001_clean_injected_clock_default(tmp_path):
    # A default-parameter REFERENCE to the clock is the injectable-seam
    # idiom (Recorder, HealthTracker) — only CALLS trip the rule.
    fs = _det_findings(tmp_path, """
        import time

        def f(clock=time.monotonic):
            return clock()
    """)
    assert fs == []


def test_det002_unseeded_randomness_trips(tmp_path):
    fs = _det_findings(tmp_path, """
        import random
        import uuid

        def f():
            return random.random(), uuid.uuid4(), random.Random()
    """)
    assert _rules(fs) == ["DET002"]
    assert len(fs) == 3


def test_det002_numpy_global_prng_trips(tmp_path):
    fs = _det_findings(tmp_path, """
        import numpy as np

        def f(n):
            return np.random.rand(n)
    """)
    assert _rules(fs) == ["DET002"]


def test_det002_clean_seeded_random(tmp_path):
    fs = _det_findings(tmp_path, """
        import random

        def f(seed):
            rng = random.Random(seed)
            return rng.random()
    """)
    assert fs == []


def test_det003_set_into_sink_trips(tmp_path):
    fs = _det_findings(tmp_path, """
        def canonical_log_text(events):
            return str(events)

        def f(xs):
            pending = set(xs)
            return canonical_log_text(pending)
    """)
    assert "DET003" in _rules(fs)


def test_det003_propagates_through_list(tmp_path):
    fs = _det_findings(tmp_path, """
        def canonical_log_text(events):
            return str(events)

        def f(xs):
            pending = set(xs)
            items = list(pending)
            return canonical_log_text(items)
    """)
    assert "DET003" in _rules(fs)


def test_det003_clean_with_sorted_on_path(tmp_path):
    fs = _det_findings(tmp_path, """
        def canonical_log_text(events):
            return str(events)

        def f(xs):
            pending = set(xs)
            return canonical_log_text(sorted(pending))
    """)
    assert fs == []


def test_det004_json_dumps_without_sort_keys_trips(tmp_path):
    fs = _det_findings(tmp_path, """
        import json

        def f(d):
            return json.dumps(d)
    """)
    assert _rules(fs) == ["DET004"]
    assert fs[0].symbol == "f"


def test_det004_clean_sort_keys_and_passthrough(tmp_path):
    fs = _det_findings(tmp_path, """
        import json

        def f(d):
            return json.dumps(d, sort_keys=True)

        def g(d, sort_keys):
            return json.dumps(d, sort_keys=sort_keys)
    """)
    assert fs == []


def test_det005_hash_ordering_trips(tmp_path):
    fs = _det_findings(tmp_path, """
        def f(xs):
            xs.sort(key=lambda x: hash(x))
            return sorted(xs, key=lambda x: (id(x), x))
    """)
    assert _rules(fs) == ["DET005"]
    assert len(fs) == 2


def test_det005_clean_field_key_and_identity_id(tmp_path):
    fs = _det_findings(tmp_path, """
        def f(xs, h):
            keep = id(xs)  # identity use outside ordering is fine
            h[keep] = True
            return sorted(xs, key=lambda x: x.name)
    """)
    assert fs == []


def test_det006_env_read_trips(tmp_path):
    fs = _det_findings(tmp_path, """
        import os

        def f():
            return os.environ.get("KNOB", "1"), os.environ["OTHER"]

        def g():
            return os.getenv("THIRD")
    """)
    assert _rules(fs) == ["DET006"]
    assert len(fs) == 3


def test_det006_clean_declared_knob(tmp_path):
    fs = _det_findings(tmp_path, """
        import os

        def f():
            return os.environ.get("KNOB", "1")
    """, config_knobs={"mod.f": "KNOB: fixture"})
    assert fs == []


def test_det_rules_only_fire_on_replay_reachable_code(tmp_path):
    # Same wall-clock call, but the module is not under any replay root
    # and nothing reaches it: DET001 stays quiet (DET004 is the one
    # package-wide rule).
    fs = _det_findings(tmp_path, """
        import time

        def f():
            return time.monotonic()
    """, roots={"other_module": "not this one"})
    assert fs == []


def _resolve_fq(fq):
    import importlib

    parts = fq.split(".")
    for cut in range(len(parts), 0, -1):
        try:
            obj = importlib.import_module(".".join(parts[:cut]))
        except ImportError:
            continue
        try:
            for attr in parts[cut:]:
                obj = getattr(obj, attr)
        except AttributeError:
            return None
        return obj
    return None


def test_determinism_tables_match_reality():
    """Every REPLAY_ROOTS / CLOCK_SEAMS / CONFIG_KNOBS entry must name a
    real module/class/function — a renamed symbol would silently blind
    the lint (same guard pattern as the race lint's SHARED_STATE)."""
    from blance_tpu.analysis.determinism import (
        CLOCK_SEAMS,
        CONFIG_KNOBS,
        REPLAY_ROOTS,
    )

    for table_name, table in [("REPLAY_ROOTS", REPLAY_ROOTS),
                              ("CLOCK_SEAMS", CLOCK_SEAMS),
                              ("CONFIG_KNOBS", CONFIG_KNOBS)]:
        for fq, reason in table.items():
            assert reason.strip(), f"{table_name}[{fq!r}] has no reason"
            assert _resolve_fq(fq) is not None, (
                f"{table_name} entry {fq!r} does not resolve to a real "
                f"symbol — update the table")


def test_determinism_sinks_match_reality():
    """Each SERIALIZED_SINKS suffix must have a real representative
    symbol, so a renamed renderer can't silently un-cover its artifact."""
    from blance_tpu.analysis.determinism import SERIALIZED_SINKS

    representatives = {
        "journal.append": "blance_tpu.durability.journal.Journal.append",
        "canonical_log_text":
            "blance_tpu.testing.simulate.canonical_log_text",
        "canonical_fleet_log_text":
            "blance_tpu.testing.fleetsim.canonical_fleet_log_text",
        "crash_log_text": "blance_tpu.testing.crashsim.crash_log_text",
        "render_prometheus": "blance_tpu.obs.expo.render_prometheus",
        "atomic_write_json": "blance_tpu.utils.atomicio.atomic_write_json",
        "atomic_write_text": "blance_tpu.utils.atomicio.atomic_write_text",
    }
    assert set(representatives) == set(SERIALIZED_SINKS), \
        "new sink entries need a representative symbol here"
    for sink, fq in representatives.items():
        assert _resolve_fq(fq) is not None, (
            f"SERIALIZED_SINKS representative for {sink!r} ({fq}) does "
            f"not resolve — update the table or this map")


def test_determinism_real_package_is_clean():
    """The real package carries ZERO determinism findings, baselined or
    not — the triage (hostclock seam, sort_keys fixes, declared knobs)
    left nothing to allowlist."""
    from blance_tpu.analysis import PACKAGE_ROOT, REPO_ROOT, _iter_py_files
    from blance_tpu.analysis.determinism import DeterminismPass

    findings = DeterminismPass(
        _iter_py_files([PACKAGE_ROOT]), REPO_ROOT).run()
    assert findings == [], "\n".join(f.render() for f in findings)


def test_cli_determinism_only_mode(capsys):
    from blance_tpu.analysis.__main__ import main

    rc = main(["--determinism"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "0 new finding(s)" in out
    # JIT/ASY/RACE baseline pins must NOT be reported stale in this mode.
    assert "stale baseline entry" not in out


# -- donation lint: each rule trips, and its clean twin does not -------------

# A self-contained donating dispatch family, the same wrapper shapes the
# real package uses (_warm_repair_donating & co): jit-with-donate
# module-level bindings over a shared impl.
_DON_PRELUDE = """
    from functools import partial

    import jax
    import jax.numpy as jnp
    import numpy as np


    def _impl(prev, pweights, carry_used, constraints):
        return prev, carry_used


    _impl_jit = partial(jax.jit, static_argnames=("constraints",))(_impl)
    _impl_donating = jax.jit(
        _impl, static_argnames=("constraints",),
        donate_argnames=("prev", "carry_used"))
    _impl_nums = jax.jit(_impl, static_argnums=(3,), donate_argnums=(0,))
"""


def _don_findings(tmp_path, source, name="fix.py"):
    from blance_tpu.analysis.donation import DonationPass

    f = tmp_path / name
    f.write_text(textwrap.dedent(_DON_PRELUDE)
                 + textwrap.dedent(source))
    return DonationPass([str(f)], repo_root=str(tmp_path)).run()


def test_don001_pr11_shape_trips(tmp_path):
    # The PR-11 bug verbatim: the engine-exhaustion fallback re-reads
    # prev after the donating dispatch instead of a pre-dispatch
    # snapshot.
    fs = _don_findings(tmp_path, """
        def warm(prev, pweights, carry, fallback, donate=True):
            impl = _impl_donating if donate else _impl_jit
            out, used = impl(jnp.asarray(prev), jnp.asarray(pweights),
                             jnp.asarray(carry.used), constraints=(2,))
            return fallback(np.asarray(out), prev, pweights)
    """)
    assert _rules(fs) == ["DON001"]
    assert fs[0].symbol == "warm"
    assert "prev" in fs[0].message


def test_don001_clean_snapshot_twin(tmp_path):
    # The sanctioned fix: snapshot host-side BEFORE the dispatch
    # (np.asarray dominates the donation), read the snapshot after.
    fs = _don_findings(tmp_path, """
        def warm(prev, pweights, carry, fallback, donate=True):
            impl = _impl_donating if donate else _impl_jit
            prev_fb = np.asarray(prev) if donate else prev
            out, used = impl(jnp.asarray(prev), jnp.asarray(pweights),
                             jnp.asarray(carry.used), constraints=(2,))
            return fallback(np.asarray(out), prev_fb, pweights)
    """)
    assert fs == []


def test_don001_packed_tuple_and_splat_dispatch_trips(tmp_path):
    # The solve_dense_warm idiom: operands packed into dev_args and
    # splatted into the dispatch — a post-dispatch read of the packed
    # tuple's element is still a read of the donated buffer.
    fs = _don_findings(tmp_path, """
        def warm(prev, pweights, carry):
            dev_args = (jnp.asarray(prev), jnp.asarray(pweights),
                        jnp.asarray(carry.used))
            out, used = _impl_donating(*dev_args, constraints=(2,))
            return dev_args[0] + out
    """)
    assert _rules(fs) == ["DON001"]


def test_don001_attribute_root_trips(tmp_path):
    # Donating straight off self.current, then returning it: the
    # session-state shape of the same bug.
    fs = _don_findings(tmp_path, """
        class Session:
            def warm(self, pweights, carry):
                out, used = _impl_donating(
                    jnp.asarray(self.current), pweights,
                    jnp.asarray(carry.used), constraints=(2,))
                return self.current
    """)
    assert _rules(fs) == ["DON001"]


def test_don001_returning_donated_operand_trips(tmp_path):
    # Returning the donated operand hands the invalidated buffer to the
    # caller — a read-at-a-distance.
    fs = _don_findings(tmp_path, """
        def warm(prev, pweights, carry):
            out, used = _impl_donating(jnp.asarray(prev), pweights,
                                       jnp.asarray(carry.used),
                                       constraints=(2,))
            return prev
    """)
    assert _rules(fs) == ["DON001"]


def test_don001_metadata_reads_are_clean(tmp_path):
    # .shape/.dtype survive donation (the aval outlives the buffer).
    fs = _don_findings(tmp_path, """
        def warm(prev, pweights, carry):
            out, used = _impl_donating(jnp.asarray(prev), pweights,
                                       jnp.asarray(carry.used),
                                       constraints=(2,))
            return out.reshape(prev.shape), prev.dtype
    """)
    assert fs == []


def test_don001_donate_argnums_positional_mapping_trips(tmp_path):
    # donate_argnums resolve through the wrapped signature to the same
    # parameter names donate_argnames would use.
    fs = _don_findings(tmp_path, """
        def warm(prev, pweights, carry):
            out, used = _impl_nums(jnp.asarray(prev), pweights,
                                   jnp.asarray(carry.used), (2,))
            return prev
    """)
    assert _rules(fs) == ["DON001"]


def test_don002_escape_before_dispatch_trips(tmp_path):
    # Stashing the operand on self before donating it: another window
    # can observe the invalidated buffer (the CarryCache risk surface).
    fs = _don_findings(tmp_path, """
        class Session:
            def warm(self, prev, pweights, carry):
                self._stash = prev
                out, used = _impl_donating(jnp.asarray(prev), pweights,
                                           jnp.asarray(carry.used),
                                           constraints=(2,))
                return out
    """)
    assert _rules(fs) == ["DON002"]


def test_don002_store_method_escape_trips(tmp_path):
    fs = _don_findings(tmp_path, """
        class Session:
            def warm(self, prev, pweights, carry):
                self.cache.store("k", prev)
                out, used = _impl_donating(jnp.asarray(prev), pweights,
                                           jnp.asarray(carry.used),
                                           constraints=(2,))
                return out
    """)
    assert _rules(fs) == ["DON002"]


def test_don002_storing_the_output_is_clean(tmp_path):
    # Escaping the dispatch OUTPUT is the normal result path, not a
    # donated-operand escape.
    fs = _don_findings(tmp_path, """
        class Session:
            def warm(self, prev, pweights, carry):
                out, used = _impl_donating(jnp.asarray(prev), pweights,
                                           jnp.asarray(carry.used),
                                           constraints=(2,))
                self._stash = np.asarray(out)
                return out
    """)
    assert fs == []


def test_don003_double_dispatch_trips(tmp_path):
    fs = _don_findings(tmp_path, """
        def warm(prev, pweights, carry):
            out, used = _impl_donating(jnp.asarray(prev), pweights,
                                       jnp.asarray(carry.used),
                                       constraints=(2,))
            out2, used2 = _impl_donating(jnp.asarray(prev), pweights,
                                         used, constraints=(2,))
            return out2
    """)
    assert _rules(fs) == ["DON003"]


def test_don003_rebound_redispatch_is_clean(tmp_path):
    fs = _don_findings(tmp_path, """
        def warm(prev, pweights, carry):
            out, used = _impl_donating(jnp.asarray(prev), pweights,
                                       jnp.asarray(carry.used),
                                       constraints=(2,))
            prev = np.asarray(out)
            out2, used2 = _impl_donating(jnp.asarray(prev), pweights,
                                         used, constraints=(2,))
            return out2
    """)
    assert fs == []


def test_don004_post_dispatch_snapshot_trips(tmp_path):
    # Snapshotting AFTER the dispatch reads the invalidated buffer; the
    # same call BEFORE the dispatch is the fix recipe and stays clean
    # (test_don001_clean_snapshot_twin).
    fs = _don_findings(tmp_path, """
        def warm(prev, pweights, carry):
            out, used = _impl_donating(jnp.asarray(prev), pweights,
                                       jnp.asarray(carry.used),
                                       constraints=(2,))
            keep = np.asarray(prev)
            return out, keep
    """)
    assert _rules(fs) == ["DON004"]


def test_donation_real_package_is_clean():
    """The real package carries ZERO donation findings, baselined or
    not — the PR-11 snapshot fixes cover every donating dispatch."""
    from blance_tpu.analysis import PACKAGE_ROOT, REPO_ROOT, _iter_py_files
    from blance_tpu.analysis.donation import DonationPass

    findings = DonationPass(
        _iter_py_files([PACKAGE_ROOT]), REPO_ROOT).run()
    assert findings == [], "\n".join(f.render() for f in findings)


def test_donation_registry_sees_real_donating_wrappers():
    """The wrapper registry must resolve every jit-with-donate binding
    the package actually declares — a parse regression here would turn
    the whole pass into a silent no-op."""
    from blance_tpu.analysis import PACKAGE_ROOT, REPO_ROOT, _iter_py_files
    from blance_tpu.analysis.donation import DonationPass

    p = DonationPass(_iter_py_files([PACKAGE_ROOT]), REPO_ROOT)
    p.run()
    by_name = {fq.rsplit(".", 1)[-1]: dc
               for fq, dc in p.registry.items()}
    assert by_name["_warm_repair_donating"].donated == (
        "prev", "carry_used")
    assert by_name["_warm_repair_sparse_donating"].donated == (
        "prev", "carry_used")
    assert by_name["_pipeline_cold_donating"].donated == ("prev",)
    assert by_name["_pipeline_warm_donating"].donated == (
        "prev", "carry_used")
    assert by_name["_pipeline_sparse_donating"].donated == ("prev",)


def test_cli_donation_only_mode(capsys):
    from blance_tpu.analysis.__main__ import main

    rc = main(["--donation"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "0 new finding(s)" in out
    # Other passes' baseline pins must NOT be reported stale in this mode.
    assert "stale baseline entry" not in out


def test_cli_donation_catches_seeded_pr11_regression(tmp_path, capsys):
    # The acceptance fixture: re-introduce the PR-11 sparse-warm read
    # and the CLI must fail with DON001.
    bad = tmp_path / "fix.py"
    bad.write_text(textwrap.dedent(_DON_PRELUDE) + textwrap.dedent("""
        def warm(prev, pweights, carry, fallback):
            out, used = _impl_donating(jnp.asarray(prev), pweights,
                                       jnp.asarray(carry.used),
                                       constraints=(2,))
            return fallback(np.asarray(out), prev, pweights)
    """))
    from blance_tpu.analysis.__main__ import main

    rc = main(["--donation", str(bad)])
    out = capsys.readouterr().out
    assert rc == 1
    assert "DON001" in out


# -- JIT005: donate_argnums validation (the PR-20 gap-fill) ------------------


def test_jit005_donate_argnums_out_of_range_trips(tmp_path):
    fs = _jit_findings(tmp_path, """
        import jax

        def f(x, y, mode):
            return x

        g = jax.jit(f, static_argnames=("mode",), donate_argnums=(5,))
    """)
    assert _rules(fs) == ["JIT005"]
    assert "outside" in fs[0].message


def test_jit005_donate_argnums_static_overlap_trips(tmp_path):
    fs = _jit_findings(tmp_path, """
        import jax

        def f(x, y, mode):
            return x

        g = jax.jit(f, static_argnames=("mode",), donate_argnums=(2,))
    """)
    assert _rules(fs) == ["JIT005"]
    assert "static_argnames" in fs[0].message


def test_jit005_donate_argnums_clean_twin(tmp_path):
    fs = _jit_findings(tmp_path, """
        import jax

        def f(x, y, mode):
            return x

        g = jax.jit(f, static_argnames=("mode",), donate_argnums=(0, 1))
    """)
    assert fs == []


# -- membudget: the declarative HBM-ceiling table ----------------------------


def _mb_patched(monkeypatch, budgets, entries=None):
    """Shrink the membudget pass to a controlled (budgets, builders)
    pair; measurement stays real (AOT on abstract operands — cheap at
    the entries these tests keep)."""
    from blance_tpu.analysis import membudget as mb

    orig = mb._builders()
    keep = {e: orig[e] for e in (entries or []) if e in orig}
    monkeypatch.setattr(mb, "HBM_BUDGETS", budgets)
    monkeypatch.setattr(mb, "_builders", lambda: keep)
    return mb


def test_mem001_over_budget_trips(monkeypatch):
    mb = _mb_patched(monkeypatch, {"sched.ranks": {"smoke": 1}},
                     entries=["sched.ranks"])
    findings, n = mb.run_membudget_check()
    assert _rules(findings) == ["MEM001"]
    assert n == 1
    assert findings[0].symbol == "sched.ranks@smoke"


def test_mem001_within_budget_is_clean(monkeypatch):
    mb = _mb_patched(monkeypatch, {"sched.ranks": {"smoke": 100_000}},
                     entries=["sched.ranks"])
    findings, n = mb.run_membudget_check()
    assert findings == []
    assert n == 1


def test_mem002_table_drift_trips(monkeypatch):
    # All three drift shapes at once: a budget row with no builder, a
    # builder with no row, and a row for a mesh-exempt entry.
    mb = _mb_patched(monkeypatch,
                     {"ghost.entry": {"smoke": 5},
                      "sharded.cold": {"smoke": 5}},
                     entries=["sched.ranks"])
    findings, _ = mb.run_membudget_check()
    assert _rules(findings) == ["MEM002"]
    symbols = sorted(f.symbol for f in findings)
    assert symbols == ["ghost.entry", "sched.ranks", "sharded.cold"]


def test_mem002_unknown_class_trips(monkeypatch):
    mb = _mb_patched(monkeypatch, {"sched.ranks": {"bogus": 5}},
                     entries=["sched.ranks"])
    findings, _ = mb.run_membudget_check()
    assert _rules(findings) == ["MEM002"]
    assert findings[0].symbol == "sched.ranks@bogus"


def test_mem003_dense_row_past_guard_trips(monkeypatch):
    # A dense-engine budget at the north-star class: check_dense_memory
    # rejects a 100k x 10k score matrix at dispatch, so the row is dead
    # and MEM003 must say so (structurally — the gated class is never
    # AOT-compiled).
    mb = _mb_patched(monkeypatch,
                     {"solve_dense.cold": {"north": 10}},
                     entries=["solve_dense.cold"])
    findings, _ = mb.run_membudget_check()
    assert _rules(findings) == ["MEM003"]
    assert findings[0].symbol == "solve_dense.cold@north"


def test_membudget_real_table_is_structurally_sound():
    """MEM002/MEM003 over the REAL table without any measurement:
    every builder budgeted, no dead/exempt/unknown rows."""
    from blance_tpu.analysis import membudget as mb

    assert set(mb._builders()) == set(mb.HBM_BUDGETS)
    assert not (set(mb.HBM_BUDGETS) & mb.MESH_EXEMPT)
    for ent, rows in mb.HBM_BUDGETS.items():
        assert set(rows) <= set(mb.SHAPE_CLASSES), (ent, rows)
        for klass, budget in rows.items():
            assert budget > 0
        if ent in mb._DENSE_ENTRIES:
            for klass in rows:
                d = mb.SHAPE_CLASSES[klass]
                from blance_tpu.plan.tensor import projected_score_bytes

                assert projected_score_bytes(d.P, d.N) <= \
                    mb._DENSE_GUARD_REF_BYTES, (ent, klass)


def test_membudget_entries_match_live_dispatch_labels():
    """Reality guard: every budgeted/exempted entry label must appear
    as a string literal in the dispatch modules — a renamed
    obs/device.entry label would otherwise leave a dead ceiling that
    MEM002 can't see (the builder registry renames with the code, the
    label string does not)."""
    import ast
    import os

    from blance_tpu.analysis import PACKAGE_ROOT
    from blance_tpu.analysis import membudget as mb

    dispatch_modules = [
        os.path.join(PACKAGE_ROOT, "plan", "tensor.py"),
        os.path.join(PACKAGE_ROOT, "plan", "session.py"),
        os.path.join(PACKAGE_ROOT, "plan", "fleet.py"),
        os.path.join(PACKAGE_ROOT, "parallel", "sharded.py"),
        os.path.join(PACKAGE_ROOT, "orchestrate", "sched", "ranks.py"),
    ]
    literals = set()
    for path in dispatch_modules:
        with open(path) as fh:
            tree = ast.parse(fh.read())
        for node in ast.walk(tree):
            if isinstance(node, ast.Constant) and \
                    isinstance(node.value, str):
                literals.add(node.value)
    for label in sorted(set(mb.HBM_BUDGETS) | mb.MESH_EXEMPT):
        assert label in literals, (
            f"membudget entry {label!r} does not appear in any dispatch "
            f"module — the live entry label moved; update HBM_BUDGETS/"
            f"MESH_EXEMPT")
