"""Scale extensions of the orchestrator (not in the reference): throughput
mode (interrupt_on_first_feed=False) and the on-device batch diff
(device_diff=True).  Both must execute exactly the same move sets as the
reference-semantics defaults — only scheduling granularity changes."""

import asyncio

from blance_tpu import Partition, PartitionModelState
from blance_tpu.orchestrate import OrchestratorOptions, orchestrate_moves

MODEL = {
    "primary": PartitionModelState(priority=0, constraints=1),
    "replica": PartitionModelState(priority=1, constraints=1),
}


def pm(d):
    return {name: Partition(name, {s: list(ns) for s, ns in nbs.items()})
            for name, nbs in d.items()}


def shifted_maps(P, nodes):
    """Every partition moves primary/replica one node to the right."""
    beg, end = {}, {}
    n = len(nodes)
    for i in range(P):
        name = str(i)
        beg[name] = {"primary": [nodes[i % n]],
                     "replica": [nodes[(i + 1) % n]]}
        end[name] = {"primary": [nodes[(i + 1) % n]],
                     "replica": [nodes[(i + 2) % n]]}
    return pm(beg), pm(end)


def collect_recs():
    recs = []

    def assign(stop_ch, node, partitions, states, ops):
        for p, s, op in zip(partitions, states, ops):
            recs.append((p, node, s, op))
        return None

    return recs, assign


async def drive(options, beg, end, nodes, assign):
    o = orchestrate_moves(MODEL, options, nodes, beg, end, assign)
    last = None
    async for progress in o.progress_ch():
        last = progress
    o.stop()
    return last


def final_states(recs):
    """Replay an op log into {partition: {node: state}}."""
    out = {}
    for p, node, state, op in recs:
        states = out.setdefault(p, {})
        if op == "del":
            states.pop(node, None)
        else:
            states[node] = state
    return out


def test_throughput_mode_same_final_placement():
    nodes = [f"n{i}" for i in range(8)]
    beg, end = shifted_maps(48, nodes)

    results = {}
    for label, interrupt in [("exact", True), ("throughput", False)]:
        recs, assign = collect_recs()
        last = asyncio.run(drive(
            OrchestratorOptions(max_concurrent_partition_moves_per_node=2,
                                interrupt_on_first_feed=interrupt),
            beg, end, nodes, assign))
        assert last is not None and not last.errors
        results[label] = final_states(recs)

    assert results["exact"] == results["throughput"]


def test_throughput_mode_reaches_end_map():
    nodes = [f"n{i}" for i in range(8)]
    beg, end = shifted_maps(32, nodes)
    recs, assign = collect_recs()
    last = asyncio.run(drive(
        OrchestratorOptions(interrupt_on_first_feed=False),
        beg, end, nodes, assign))
    assert last is not None and not last.errors
    got = final_states(recs)
    for name, partition in end.items():
        want = {node: "primary" for node in partition.nodes_by_state["primary"]}
        want.update(
            {node: "replica" for node in partition.nodes_by_state["replica"]})
        assert got[name] == want, name


def test_device_diff_identical_op_log():
    nodes = [f"n{i}" for i in range(6)]
    beg, end = shifted_maps(24, nodes)

    logs = {}
    for label, dev in [("host", False), ("device", True)]:
        recs, assign = collect_recs()
        last = asyncio.run(drive(
            OrchestratorOptions(device_diff=dev), beg, end, nodes, assign))
        assert last is not None and not last.errors
        logs[label] = recs

    assert logs["host"] == logs["device"]


def test_throughput_mode_scales():
    """2k partitions x 16 nodes completes promptly in throughput mode (the
    exact mode commits ~one batch per round and would crawl here)."""
    import time

    nodes = [f"n{i}" for i in range(16)]
    beg, end = shifted_maps(2000, nodes)
    recs, assign = collect_recs()
    t0 = time.perf_counter()
    last = asyncio.run(drive(
        OrchestratorOptions(max_concurrent_partition_moves_per_node=8,
                            interrupt_on_first_feed=False,
                            device_diff=False),
        beg, end, nodes, assign))
    dt = time.perf_counter() - t0
    assert last is not None and not last.errors
    # Per partition: n[i+1] replica->primary is a promote, n[i+2] is an
    # add, n[i] is a del — 3 ops.
    assert len(recs) == 2000 * 3
    assert dt < 60, f"throughput mode took {dt:.1f}s"


def test_throughput_mode_moverless_node_no_deadlock():
    """A move targeting a node outside nodes_all must not deadlock the
    throughput-mode round; other nodes' work completes and the moverless
    move stays pending (reference nil-channel semantics wedge only when
    NOTHING is feedable)."""
    nodes = ["n0", "n1"]  # 'ghost' deliberately absent
    beg = pm({"a": {"primary": ["n0"]}, "b": {"primary": ["n1"]}})
    end = pm({"a": {"primary": ["ghost"]}, "b": {"primary": ["n0"]}})
    recs, assign = collect_recs()

    async def go():
        from blance_tpu.orchestrate import orchestrate_moves
        o = orchestrate_moves(
            MODEL, OrchestratorOptions(interrupt_on_first_feed=False),
            nodes, beg, end, assign)

        async def drain():
            async for _ in o.progress_ch():
                pass

        drainer = asyncio.ensure_future(drain())
        # b's move (n1 -> n0) completes; a's move wedges on the ghost node.
        await asyncio.sleep(0.5)
        done_b = any(r[0] == "b" and r[3] == "add" for r in recs)
        o.stop()
        await asyncio.wait_for(drainer, timeout=5)
        return done_b

    assert asyncio.run(asyncio.wait_for(go(), timeout=20))
