"""Sparse top-K shortlist solver (ISSUE 11).

Contracts pinned here:

- **Saturating-K bit-identity**: with K >= N the sparse engine's result
  equals the dense matrix engine's BIT-FOR-BIT — cold, carry-warm, on
  one device and under a sharded mesh, and at the plan/pipeline level
  (map + warnings + moves).  This is what keeps the two paths from
  drifting.
- **Audit contracts at realistic K**: K << N solves pass the full
  check_assignment audit (no duplicates, no removed-node placements,
  every feasible slot filled, zero feasible-tier hierarchy misses) on a
  randomized corpus, with balance within a pinned tolerance of dense.
- **The exhaustion escape hatch**: rows whose shortlist cannot serve a
  slot are flagged, re-placed by the per-row dense fallback, and
  counted (plan.sparse.* metrics) — shortlist quality is a performance
  knob, never a correctness surface.
- **Shortlist builder properties**, the fused sparse min2 kernel vs its
  XLA oracle (interpret mode), and the dense-memory guard's structured
  refusal.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from blance_tpu import HierarchyRule, Partition, PlanOptions, model
from blance_tpu.core.encode import encode_problem
from blance_tpu.core.shortlist import (
    auto_shortlist_k,
    build_shortlist,
    shortlist_rules_nest,
)
from blance_tpu.obs import get_recorder
from blance_tpu.plan.tensor import (
    DenseScoreMemoryError,
    carry_from_assignment,
    check_assignment,
    check_dense_memory,
    projected_score_bytes,
    set_dense_score_budget,
    solve_converged_resilient,
    solve_dense_converged,
    solve_dense_warm,
    solve_sparse,
    solve_sparse_warm,
)


def _dense_args(P, N, seed=0, rack=5, remove_frac=20, weights=False):
    """Solver arrays for the rack-rule delta shape (bench.build_dense's
    twin, plus optional heterogeneous weights)."""
    rng = np.random.default_rng(seed)
    S, R = 2, 1
    prev = np.full((P, S, R), -1, np.int32)
    prev[:, 0, 0] = rng.integers(0, N, P)
    prev[:, 1, 0] = (prev[:, 0, 0] + 1 + rng.integers(0, N - 1, P)) % N
    pw = np.ones(P, np.float32)
    nw = np.ones(N, np.float32)
    if weights:
        pw[::7] = rng.integers(2, 5, len(pw[::7]))
        nw[::5] = rng.integers(2, 4, len(nw[::5]))
    valid = np.ones(N, bool)
    if remove_frac:
        valid[rng.choice(N, max(N // remove_frac, 1),
                         replace=False)] = False
    stick = np.full((P, S), 1.5, np.float32)
    gids = np.stack([np.arange(N, dtype=np.int32),
                     np.arange(N, dtype=np.int32) // rack,
                     np.zeros(N, np.int32)])
    gv = np.ones((3, N), bool)
    constraints = (1, 1)
    rules = ((), ((2, 1),))
    return (prev, pw, nw, valid, stick, gids, gv, constraints, rules)


def _audit(a, valid, gids):
    a = np.asarray(a)
    prim, repl = a[:, 0, 0], a[:, 1, 0]
    held = a[a >= 0]
    rack = gids[1]
    co = int(((rack[np.clip(prim, 0, None)] == rack[np.clip(repl, 0, None)])
              & (prim >= 0) & (repl >= 0)).sum())
    return {"unassigned": int((a < 0).sum()),
            "removed": int((~valid[held]).sum()),
            "dup": int(((prim == repl) & (prim >= 0)).sum()),
            "co_racked": co}


# --- saturating-K bit-identity ----------------------------------------------


@pytest.mark.parametrize("seed", [0, 3, 9])
def test_saturating_k_bit_identity_cold(seed):
    P, N = 256, 32
    args = _dense_args(P, N, seed=seed, weights=(seed % 2 == 0))
    dense = np.asarray(solve_dense_converged(
        *[jnp.asarray(a) for a in args[:7]], args[7], args[8],
        record=False))
    sparse = solve_sparse(*args[:7], args[7], args[8], k=N, record=False)
    assert np.array_equal(dense, sparse)


def test_saturating_k_beyond_n_bit_identity():
    """K > N saturates to the identity permutation, same contract."""
    P, N = 128, 16
    args = _dense_args(P, N, seed=1)
    dense = np.asarray(solve_dense_converged(
        *[jnp.asarray(a) for a in args[:7]], args[7], args[8],
        record=False))
    sparse = solve_sparse(*args[:7], args[7], args[8], k=N + 7,
                          record=False)
    assert np.array_equal(dense, sparse)


def test_saturating_k_bit_identity_warm():
    """Carry-seeded one-sweep repair: sparse K=N accepts exactly when
    dense accepts and produces the identical assignment."""
    P, N = 256, 32
    args = _dense_args(P, N, seed=5)
    prev, pw, nw, valid, stick, gids, gv, cons, rules = args
    cold = solve_dense_converged(
        *[jnp.asarray(a) for a in args[:7]], cons, rules, record=False)
    cold_np = np.asarray(cold)
    victim = int(cold_np[0, 0, 0])
    valid2 = valid.copy()
    valid2[victim] = False
    dirty = (cold_np == victim).any(axis=(1, 2))
    c_dense = carry_from_assignment(cold, jnp.asarray(pw), jnp.asarray(nw))
    c_sparse = carry_from_assignment(cold, jnp.asarray(pw),
                                     jnp.asarray(nw))
    wd, cd = solve_dense_warm(
        cold_np, pw, nw, valid2, stick, gids, gv, cons, rules,
        dirty=dirty, carry=c_dense, record=False)
    ws, cs = solve_sparse_warm(
        cold_np, pw, nw, valid2, stick, gids, gv, cons, rules,
        dirty=dirty, carry=c_sparse, k=N, record=False)
    assert (wd is None) == (ws is None)
    if wd is not None:
        assert np.array_equal(wd, ws)
        assert np.array_equal(np.asarray(cd.used), np.asarray(cs.used))


def test_saturating_k_bit_identity_sharded():
    """Cold + warm sparse solves under an 8-shard partition mesh equal
    the dense sharded solves bit-for-bit at K=N."""
    from blance_tpu.parallel.sharded import (
        make_mesh,
        solve_dense_sharded,
        solve_sparse_sharded,
    )

    P, N = 256, 32
    args = _dense_args(P, N, seed=2)
    prev, pw, nw, valid, stick, gids, gv, cons, rules = args
    mesh = make_mesh(8)
    dense = solve_dense_sharded(mesh, *args[:7], cons, rules)
    sparse = solve_sparse_sharded(mesh, *args[:7], cons, rules, k=N)
    assert np.array_equal(dense, sparse)

    victim = int(dense[0, 0, 0])
    valid2 = valid.copy()
    valid2[victim] = False
    dirty = (dense == victim).any(axis=(1, 2))
    cd = carry_from_assignment(dense, jnp.asarray(pw), jnp.asarray(nw))
    cs = carry_from_assignment(dense, jnp.asarray(pw), jnp.asarray(nw))
    wd = solve_dense_sharded(
        mesh, dense, pw, nw, valid2, stick, gids, gv, cons, rules,
        dirty=dirty, carry=cd, warm_only=True)
    ws = solve_sparse_sharded(
        mesh, dense, pw, nw, valid2, stick, gids, gv, cons, rules, k=N,
        dirty=dirty, carry=cs, warm_only=True)
    assert (wd is None) == (ws is None)
    if wd is not None:
        assert np.array_equal(wd, ws)


def test_plan_level_saturating_identity_map_warnings_moves():
    """PlanOptions(sparse=True, sparse_k=N) through the fused pipeline:
    map, warnings AND move lists identical to the dense plan."""
    from blance_tpu.plan.tensor import plan_pipeline

    P, N = 192, 24
    rng = np.random.default_rng(4)
    nodes = [f"n{i:03d}" for i in range(N)]
    removed = [nodes[i] for i in rng.choice(N, 2, replace=False)]
    prev = {str(i): Partition(str(i), {
        "primary": [nodes[rng.integers(0, N)]],
        "replica": [nodes[rng.integers(0, N)]]}) for i in range(P)}
    m = model(primary=(0, 1), replica=(1, 1))
    hier = {n: f"r{i // 4}" for i, n in enumerate(nodes)}
    hier.update({f"r{i}": "z0" for i in range((N + 3) // 4)})
    base = dict(node_hierarchy=hier,
                hierarchy_rules={"replica": [HierarchyRule(2, 1)]})
    map_d, warn_d, moves_d = plan_pipeline(
        prev, prev, nodes, removed, [], m, PlanOptions(**base))
    map_s, warn_s, moves_s = plan_pipeline(
        prev, prev, nodes, removed, [], m,
        PlanOptions(sparse=True, sparse_k=N, **base))
    assert warn_d == warn_s
    assert {k: v.nodes_by_state for k, v in map_d.items()} == \
        {k: v.nodes_by_state for k, v in map_s.items()}
    assert moves_d == moves_s


# --- realistic K: audit contracts + balance tolerance -----------------------


@pytest.mark.parametrize("seed", range(8))
def test_fuzz_sparse_audit_contract(seed):
    """Randomized configs at K << N: the sparse solve (shortlist +
    exhaustion fallback) passes the full check_assignment audit — zero
    duplicates / removed-node placements / unfilled feasible slots /
    feasible-tier hierarchy misses — and keeps per-node load spread
    within a pinned tolerance of the dense solve (2x + 6: the shortlist
    trades a little balance tightness for the O(P*K) sweep)."""
    rng = np.random.default_rng(100 + seed)
    N = int(rng.integers(16, 64))
    P = int(rng.integers(64, 512))
    nodes = [f"n{i:03d}" for i in range(N)]
    parts = {str(i): Partition(str(i), {}) for i in range(P)}
    racks = int(rng.integers(2, 6))
    hier = {n: f"r{i % racks}" for i, n in enumerate(nodes)}
    hier.update({f"r{i}": "z0" for i in range(racks)})
    opts = PlanOptions(
        node_hierarchy=hier,
        hierarchy_rules={"replica": [HierarchyRule(2, 1)]},
        partition_weights=({str(i): int(rng.integers(1, 4))
                            for i in range(0, P, 5)}
                           if rng.random() < 0.5 else None))
    m = model(primary=(0, 1), replica=(1, 1))
    removed = (list(rng.choice(nodes, max(N // 10, 1), replace=False))
               if rng.random() < 0.7 else [])
    problem = encode_problem(parts, parts, nodes, removed, m, opts)
    cons = tuple(int(c) for c in problem.constraints)
    rules = tuple(tuple(problem.rules.get(si, ()))
                  for si in range(problem.S))
    k = auto_shortlist_k(problem.N, cons, rules)
    assert k < problem.N or problem.N <= 16

    sparse = solve_sparse(
        problem.prev, problem.partition_weights, problem.node_weights,
        problem.valid_node, problem.stickiness, problem.gids,
        problem.gid_valid, cons, rules, k=k, record=False)
    counts = check_assignment(problem, sparse)
    assert counts == {"duplicates": 0, "on_removed_nodes": 0,
                      "unfilled_feasible_slots": 0,
                      "hierarchy_misses": 0}, counts

    dense = np.asarray(solve_dense_converged(
        jnp.asarray(problem.prev), jnp.asarray(problem.partition_weights),
        jnp.asarray(problem.node_weights), jnp.asarray(problem.valid_node),
        jnp.asarray(problem.stickiness), jnp.asarray(problem.gids),
        jnp.asarray(problem.gid_valid), cons, rules, record=False))
    pw = problem.partition_weights

    def spread(a):
        w = np.zeros(problem.N, np.float64)
        ids = a.reshape(a.shape[0], -1)
        mask = ids >= 0
        np.add.at(w, ids[mask],
                  np.broadcast_to(pw[:, None], ids.shape)[mask])
        lv = w[problem.valid_node]
        return float(lv.max() - lv.min()) if lv.size else 0.0

    assert spread(sparse) <= 2.0 * spread(dense) + 6.0, (
        spread(sparse), spread(dense))


# --- shortlist edge cases ----------------------------------------------------


def test_k1_degenerate():
    """K=1 can never serve two exclusive slots: the fallback must fill
    them, audit-clean."""
    P, N = 96, 16
    args = _dense_args(P, N, seed=6)
    rec = get_recorder()
    before = rec.counters.get("plan.sparse.dense_fallback_rows", 0)
    sparse = solve_sparse(*args[:7], args[7], args[8], k=1)
    a = _audit(sparse, args[3], args[5])
    assert a == {"unassigned": 0, "removed": 0, "dup": 0, "co_racked": 0}
    assert rec.counters.get("plan.sparse.dense_fallback_rows", 0) > before


def test_all_candidates_excluded_row_falls_back_dense():
    """A row whose entire shortlist is removed nodes is flagged
    exhausted and re-placed densely; untouched rows keep their sparse
    result bit-for-bit."""
    P, N = 64, 16
    args = _dense_args(P, N, seed=8, remove_frac=0)
    prev, pw, nw, valid, stick, gids, gv, cons, rules = args
    valid = valid.copy()
    valid[0] = valid[1] = False
    k = 6
    shortlist = np.asarray(build_shortlist(
        prev, pw, nw, valid, gids, gv, cons, rules, k)).copy()
    # Row 0's candidates: only the two removed nodes (then pads).
    shortlist[0] = -1
    shortlist[0, :2] = [0, 1]
    rec = get_recorder()
    e0 = rec.counters.get("plan.sparse.shortlist_exhausted", 0)
    f0 = rec.counters.get("plan.sparse.dense_fallback_rows", 0)
    out = solve_sparse(prev, pw, nw, valid, stick, gids, gv, cons,
                       rules, shortlist=jnp.asarray(shortlist))
    assert rec.counters.get("plan.sparse.shortlist_exhausted", 0) > e0
    assert rec.counters.get("plan.sparse.dense_fallback_rows", 0) > f0
    a = _audit(out, valid, gids)
    assert a == {"unassigned": 0, "removed": 0, "dup": 0, "co_racked": 0}
    # Row 0 was re-placed onto live nodes.
    assert (out[0] >= 0).all() and valid[out[0].ravel()].all()


def test_sticky_row_with_removed_node():
    """Rows whose previous node was removed keep their OTHER sticky
    copy and move only the displaced one; the mover lands on a live
    node at the right rack tier."""
    P, N = 128, 20
    args = _dense_args(P, N, seed=12, remove_frac=0)
    prev, pw, nw, valid, stick, gids, gv, cons, rules = args
    valid = valid.copy()
    victim = int(prev[0, 0, 0])
    valid[victim] = False
    out = solve_sparse(prev, pw, nw, valid, stick, gids, gv, cons,
                       rules, k=8, record=False)
    a = _audit(out, valid, gids)
    assert a == {"unassigned": 0, "removed": 0, "dup": 0, "co_racked": 0}
    # Stickiness: rows NOT holding the victim keep their primary at
    # least as often as the dense engine does (the balance trim
    # legitimately displaces a few holders on both engines).
    dense = np.asarray(solve_dense_converged(
        jnp.asarray(prev), jnp.asarray(pw), jnp.asarray(nw),
        jnp.asarray(valid), jnp.asarray(stick), jnp.asarray(gids),
        jnp.asarray(gv), cons, rules, record=False))
    untouched = ~(prev == victim).any(axis=(1, 2))
    keep_sparse = (out[untouched, 0, 0] == prev[untouched, 0, 0]).mean()
    keep_dense = (dense[untouched, 0, 0] == prev[untouched, 0, 0]).mean()
    assert keep_sparse >= keep_dense - 0.05, (keep_sparse, keep_dense)
    assert keep_sparse > 0.8


def test_hierarchy_group_smaller_than_k():
    """A 2-node rack with K=8: the builder pads rather than invents
    candidates, and the solve stays audit-clean."""
    P, N = 64, 10
    args = _dense_args(P, N, seed=3, rack=2, remove_frac=0)
    prev, pw, nw, valid, stick, gids, gv, cons, rules = args
    sl = np.asarray(build_shortlist(
        prev, pw, nw, valid, gids, gv, cons, rules, 8))
    assert sl.shape == (P, 8)
    out = solve_sparse(prev, pw, nw, valid, stick, gids, gv, cons,
                       rules, k=8, record=False)
    a = _audit(out, valid, gids)
    assert a == {"unassigned": 0, "removed": 0, "dup": 0, "co_racked": 0}


# --- shortlist builder properties -------------------------------------------


def test_builder_rows_sorted_unique_padded():
    P, N = 200, 40
    args = _dense_args(P, N, seed=9, weights=True)
    prev, pw, nw, valid, stick, gids, gv, cons, rules = args
    k = 12
    sl = np.asarray(build_shortlist(
        prev, pw, nw, valid, gids, gv, cons, rules, k))
    assert sl.shape == (P, k) and sl.dtype == np.int32
    for row in sl[:32]:
        real = row[row >= 0]
        # ascending, unique, ids in range, pads only at the tail
        assert (np.diff(real) > 0).all()
        assert (real < N).all()
        assert (row[len(real):] == -1).all()
    # Sticky candidates (the previous placement) are always included.
    held = prev[:, :, 0]
    for pi in range(0, P, 17):
        for node in held[pi]:
            if node >= 0:
                assert node in sl[pi], (pi, node, sl[pi])


def test_builder_saturating_identity_permutation():
    P, N = 50, 12
    args = _dense_args(P, N, seed=2)
    prev, pw, nw, valid, stick, gids, gv, cons, rules = args
    for k in (N, N + 5):
        sl = np.asarray(build_shortlist(
            prev, pw, nw, valid, gids, gv, cons, rules, k))
        assert sl.shape == (P, N)
        assert (sl == np.arange(N)).all()


def test_auto_k_bounds():
    cons = (1, 2)
    rules = ((), ((2, 1), (2, 1)))
    k = auto_shortlist_k(1000, cons, rules)
    assert 16 <= k <= 64 and k % 8 == 0
    assert auto_shortlist_k(4, cons, rules) == 4  # clamped to N
    assert shortlist_rules_nest(rules)
    assert not shortlist_rules_nest(((), ((1, 2),)))


# --- the fused sparse min2 kernel -------------------------------------------


@pytest.mark.parametrize("shape", [(7, 3), (64, 16), (300, 48), (17, 1)])
def test_sparse_kernel_matches_reference(shape):
    """Interpret-mode kernel vs the XLA oracle, quantized scores so
    duplicate minima exercise the tie-break rules."""
    from blance_tpu.ops.sparse2 import (
        sparse_min2_reference,
        sparse_priced_min2,
    )

    p, k = shape
    rng = np.random.default_rng(p * 31 + k)
    score = jnp.asarray(
        rng.integers(0, 6, (p, k)).astype(np.float32) * 0.5)
    price = jnp.asarray(
        rng.integers(0, 4, (p, k)).astype(np.float32) * 0.25)
    got = sparse_priced_min2(score, price, interpret=True)
    want = sparse_min2_reference(score, price)
    for g, w, name in zip(got, want, ("best", "kidx", "second", "raw")):
        assert np.array_equal(np.asarray(g), np.asarray(w)), name


def test_sparse_kernel_rejects_empty_k():
    from blance_tpu.ops.sparse2 import sparse_priced_min2

    with pytest.raises(ValueError, match="K >= 1"):
        sparse_priced_min2(jnp.zeros((4, 0)), jnp.zeros((4, 0)),
                           interpret=True)


# --- dense-memory guard ------------------------------------------------------


def test_dense_memory_guard_structured_refusal():
    """Past budget, the matrix engine refuses at entry with a
    structured, actionable error naming the sparse way out — instead of
    an opaque XLA OOM."""
    P, N = 256, 32
    args = _dense_args(P, N, seed=0)
    try:
        set_dense_score_budget(projected_score_bytes(P, N) - 1)
        with pytest.raises(DenseScoreMemoryError) as ei:
            solve_converged_resilient(
                *[jnp.asarray(a) for a in args[:7]], args[7], args[8],
                max_iterations=4, mode="off", allow_fallback=False,
                context="test")
        err = ei.value
        assert err.projected_bytes > err.budget_bytes
        assert err.shape == (P, 2, N)
        assert "sparse" in str(err) and "PlanOptions" in str(err)
        # The sparse engine itself sails past the guard.
        out = solve_sparse(*args[:7], args[7], args[8], k=8,
                           record=False)
        assert (out >= 0).all()
    finally:
        set_dense_score_budget(None)
    # Back under budget: no refusal.
    check_dense_memory(P, 2, N, "off")


def test_auto_routes_to_sparse_past_budget():
    """PlanOptions(sparse=None) auto-selects the sparse engine exactly
    when the dense projection exceeds the budget (and rules nest)."""
    from blance_tpu.plan.tensor import plan_next_map_tpu

    P, N = 96, 16
    rng = np.random.default_rng(3)
    nodes = [f"n{i:03d}" for i in range(N)]
    prev = {str(i): Partition(str(i), {
        "primary": [nodes[rng.integers(0, N)]],
        "replica": [nodes[rng.integers(0, N)]]}) for i in range(P)}
    m = model(primary=(0, 1), replica=(1, 1))
    hier = {n: f"r{i // 4}" for i, n in enumerate(nodes)}
    hier.update({f"r{i}": "z0" for i in range((N + 3) // 4)})
    opts = PlanOptions(node_hierarchy=hier,
                       hierarchy_rules={"replica": [HierarchyRule(2, 1)]})
    rec = get_recorder()
    try:
        set_dense_score_budget(projected_score_bytes(P, N) - 1)
        g0 = rec.gauges.get("plan.sparse.k_effective")
        next_map, warnings = plan_next_map_tpu(
            prev, prev, nodes, [], [], m, opts)
        assert not warnings
        assert rec.gauges.get("plan.sparse.k_effective") is not None
        assert rec.gauges.get("plan.sparse.k_effective") != g0 or \
            g0 is not None
    finally:
        set_dense_score_budget(None)


def test_sparse_requires_nesting_rules():
    P, N = 32, 8
    args = _dense_args(P, N, seed=0)
    bad_rules = ((), ((1, 2),))  # exclude coarser than include
    from blance_tpu.plan.tensor import _sparse_selected

    with pytest.raises(ValueError, match="nesting"):
        solve_sparse(*args[:7], args[7], bad_rules, k=4, record=False)
    with pytest.raises(ValueError, match="nesting"):
        _sparse_selected(PlanOptions(sparse=True), P, 2, N, bad_rules)
    # Auto (sparse=None) quietly declines exotic rules instead of raising.
    assert not _sparse_selected(PlanOptions(), P, 2, N, bad_rules)


# --- observability -----------------------------------------------------------


def test_sparse_metrics_registered():
    from blance_tpu.obs.expo import default_registry

    reg = default_registry()
    assert reg.declared("plan.sparse.shortlist_build_s", "histogram")
    assert reg.declared("plan.sparse.k_effective", "gauge")
    assert reg.declared("plan.sparse.shortlist_exhausted", "counter")
    assert reg.declared("plan.sparse.dense_fallback_rows", "counter")


def test_warm_sparse_counters_follow_dense_semantics():
    """A declined sparse repair counts warm_fallback + the spent sweep,
    exactly like the dense warm path."""
    P, N = 128, 16
    args = _dense_args(P, N, seed=7)
    prev, pw, nw, valid, stick, gids, gv, cons, rules = args
    cold = solve_dense_converged(
        *[jnp.asarray(a) for a in args[:7]], cons, rules, record=False)
    cold_np = np.asarray(cold)
    victim = int(cold_np[0, 0, 0])
    valid2 = valid.copy()
    valid2[victim] = False
    # An EMPTY dirty mask guarantees the repair ripples -> decline.
    dirty = np.zeros(P, bool)
    carry = carry_from_assignment(cold, jnp.asarray(pw), jnp.asarray(nw))
    rec = get_recorder()
    wf0 = rec.counters.get("plan.solve.warm_fallback", 0)
    out, nc = solve_sparse_warm(
        cold_np, pw, nw, valid2, stick, gids, gv, cons, rules,
        dirty=dirty, carry=carry, k=N)
    assert out is None and nc is None
    assert rec.counters.get("plan.solve.warm_fallback", 0) == wf0 + 1
