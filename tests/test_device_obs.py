"""Device-side observatory + end-to-end request tracing.

Covers the acceptance contracts of the device observability plane:

- XLA compile accounting attributed to owning entry points (first-wins
  scopes, emission as labeled ``device.compiles`` / ``device.compile_s``)
  and the retrace-budget check mechanics (DEV001/DEV002);
- AOT cost/memory gauges per (entry, bucket-shape), memoized at first
  dispatch;
- in-graph sweep-level convergence traces exported as a Chrome counter
  track (and bit-neutral to the untraced solve);
- ``TraceContext``/``RequestTimeline``: deterministic ids, exact
  segment tiling, JSONL round-trip incl. rotation boundaries;
- ``PlanService`` request decomposition: every segment histogram
  populated, per-request segment sums equal to end-to-end latency,
  virtual-time bit-identity under ``DeterministicLoop``;
- ``MetricsServer /healthz``: 503 before the first snapshot, 200 with
  uptime/snapshot-age JSON after.

Everything registry-declared: the drift guard's ``undeclared`` check is
asserted on each emitting scenario, extending the PR-6 guard to the
``device.*`` group and the labeled ``fleet.request_segment_s`` family.
"""

import asyncio
import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from blance_tpu.obs import (
    SEGMENTS,
    ChromeTraceSink,
    InMemorySink,
    JsonlSink,
    MetricsServer,
    Recorder,
    RequestTimeline,
    TraceContext,
    TraceIdSource,
    default_registry,
    device,
    parse_prometheus,
    render_prometheus,
    scrape,
    use_recorder,
)
from blance_tpu.plan.fleet import TenantProblem
from blance_tpu.plan.service import PlanService
from blance_tpu.plan.tensor import (
    carry_from_assignment,
    solve_dense_converged,
)

CONSTRAINTS = (1, 1)
RULES = ((), ((2, 1),))


@pytest.fixture(autouse=True)
def _observatory_off():
    """Every test leaves the process-global observatory OFF — other
    modules' recompile-budget fixtures must never see its tap."""
    yield
    device.disable()
    device.reset_cost_cache()


def _solver_args(P=24, N=6, seed=0):
    rng = np.random.default_rng(seed)
    prev = np.full((P, 2, 1), -1, np.int32)
    prev[:, 0, 0] = rng.integers(0, N, P)
    prev[:, 1, 0] = (prev[:, 0, 0] + 1 + rng.integers(0, N - 1, P)) % N
    return [jnp.asarray(a) for a in (
        prev, np.ones(P, np.float32), np.ones(N, np.float32),
        np.ones(N, bool), np.full((P, 2), 1.5, np.float32),
        np.stack([np.arange(N, dtype=np.int32),
                  np.arange(N, dtype=np.int32) // 3,
                  np.zeros(N, np.int32)]),
        np.ones((3, N), bool))]


def _tenant(P, N, seed, key):
    rng = np.random.default_rng(seed)
    prev = np.full((P, 2, 1), -1, np.int32)
    prev[:, 0, 0] = rng.integers(0, N, P)
    prev[:, 1, 0] = (prev[:, 0, 0] + 1 + rng.integers(0, N - 1, P)) % N
    return TenantProblem(
        key=key, prev=prev,
        partition_weights=np.ones(P, np.float32),
        node_weights=np.ones(N, np.float32),
        valid_node=np.ones(N, bool),
        stickiness=np.full((P, 2), 1.5, np.float32),
        gids=np.stack([np.arange(N, dtype=np.int32),
                       np.arange(N, dtype=np.int32) // 4,
                       np.zeros(N, np.int32)]),
        gid_valid=np.ones((3, N), bool),
        constraints=CONSTRAINTS, rules=RULES)


# ---------------------------------------------------------------------------
# Entry attribution + compile accounting
# ---------------------------------------------------------------------------


def test_entry_scope_first_wins():
    assert device.current_entry() == "other"
    with device.entry("outer"):
        assert device.current_entry() == "outer"
        with device.entry("inner"):  # nested scopes never re-label
            assert device.current_entry() == "outer"
        assert device.current_entry() == "outer"
    assert device.current_entry() == "other"


def test_compile_monitor_counts_and_attributes():
    """A fresh jitted function compiled inside an entry scope lands on
    that entry; the duration stream feeds compile_s."""
    @jax.jit
    def fresh(x):
        return x * 3 + 1

    with device.CompileMonitor() as mon:
        with device.entry("test.entry"):
            fresh(jnp.ones(7))
        fresh(jnp.ones(7))  # cache hit: no second compile
    assert mon.by_entry.get("test.entry", 0) >= 1
    assert mon.total == sum(mon.by_entry.values())
    summary = mon.summary()
    assert summary["by_entry"] == dict(mon.by_entry)
    # The backend-compile duration was attributed too.
    assert summary["compile_s_by_entry"].get("test.entry", 0) > 0


def test_compile_monitor_emits_labeled_metrics_and_is_declared():
    @jax.jit
    def fresh2(x):
        return x - 5.0

    rec = Recorder()
    with use_recorder(rec):
        device.enable(cost_analysis=False, sweep_trace=False)
        with device.entry("solve_dense.cold"):
            fresh2(jnp.ones(3))
        device.disable()
    key = 'device.compiles{entry="solve_dense.cold"}'
    assert rec.counters.get(key, 0) >= 1
    assert rec.histogram_buckets(
        'device.compile_s{entry="solve_dense.cold"}') is not None
    # The labeled family renders and matches the declared base names.
    assert default_registry().undeclared(rec) == []
    samples, _ = parse_prometheus(render_prometheus(rec))
    assert samples[
        'blance_device_compiles_total{entry="solve_dense.cold"}'] >= 1
    assert samples[
        'blance_device_compile_s_count{entry="solve_dense.cold"}'] >= 1


def test_retrace_check_mechanics(monkeypatch):
    """Budget semantics without the full workload: an over-budget entry
    trips DEV001, an unbudgeted one DEV002, within-budget is clean."""
    from blance_tpu.analysis import retrace

    @jax.jit
    def fresh3(x):
        return x + 2

    # Fresh shapes per invocation: each run_retrace_check call below
    # must see real compiles, not the previous call's warm jit cache.
    shapes = iter([5, 9, 11, 13])

    def tiny_workload():
        with device.entry("budgeted"):
            fresh3(jnp.ones(next(shapes)))
        with device.entry("unbudgeted"):
            fresh3(jnp.ones(next(shapes)))

    monkeypatch.setattr(retrace, "_workload", tiny_workload)
    monkeypatch.setattr(retrace, "RETRACE_BUDGETS",
                        {"budgeted": 5, "other": 50})
    findings, entries = retrace.run_retrace_check()
    assert entries == 2
    assert {f.rule for f in findings} == {"DEV002"}
    assert findings[0].symbol == "unbudgeted"

    monkeypatch.setattr(retrace, "RETRACE_BUDGETS",
                        {"budgeted": 0, "unbudgeted": 5, "other": 50})
    findings, _ = retrace.run_retrace_check()
    over = [f for f in findings if f.rule == "DEV001"]
    assert over and over[0].symbol == "budgeted"


# ---------------------------------------------------------------------------
# Cost & memory gauges
# ---------------------------------------------------------------------------


def test_cost_gauges_published_once_per_entry_shape():
    rec = Recorder()
    with use_recorder(rec):
        device.enable(cost_analysis=True, sweep_trace=False)
        args = _solver_args()
        out = solve_dense_converged(*args, CONSTRAINTS, RULES)
        first_analyses = rec.counters.get("device.cost_analyses", 0)
        solve_dense_converged(*args, CONSTRAINTS, RULES)  # same shape
        device.disable()
    assert first_analyses >= 1
    # Memoized: the second dispatch published nothing new.
    assert rec.counters["device.cost_analyses"] == first_analyses
    labels = f'{{entry="solve_dense.cold",klass="{args[0].shape[0]}x' \
             f'{args[2].shape[0]}"}}'
    assert rec.gauges[f"device.flops{labels}"] > 0
    assert rec.gauges[f"device.hbm_bytes{labels}"] > 0
    assert rec.gauges[f"device.peak_alloc_bytes{labels}"] > 0
    summaries = device.cost_summaries()
    assert summaries["solve_dense.cold"]
    assert default_registry().undeclared(rec) == []
    # The warm result is unaffected by observation (same fixpoint).
    assert np.asarray(out).shape == (24, 2, 1)


def test_cost_gauges_noop_when_disabled():
    rec = Recorder()
    with use_recorder(rec):
        assert device.maybe_publish_cost(
            "x", "1x1", None) is None  # fn never touched when disabled
    assert not rec.gauges


# ---------------------------------------------------------------------------
# Sweep-level convergence traces
# ---------------------------------------------------------------------------


def test_sweep_trace_emits_counter_track_and_matches_untraced():
    args = _solver_args(P=32, N=8, seed=3)
    baseline = np.asarray(
        solve_dense_converged(*args, CONSTRAINTS, RULES, record=False))
    rec = Recorder()
    sink = ChromeTraceSink(rec)
    rec.add_sink(sink)
    with use_recorder(rec):
        device.enable(cost_analysis=False, sweep_trace=True)
        traced = np.asarray(
            solve_dense_converged(*args, CONSTRAINTS, RULES))
        device.disable()
    # The accumulator must not perturb the fixpoint.
    assert np.array_equal(baseline, traced)
    sweeps = rec.counters["plan.solve.sweeps"]
    h = rec.histogram_summary("device.sweep_accept_frac")
    assert h is not None and h["count"] == sweeps
    assert 0.0 <= h["min"] and h["max"] <= 1.0
    # One time-stamped Chrome "C" sample per sweep, time-ordered within
    # the solve interval.
    events = [e for e in sink.events()
              if e.get("ph") == "C"
              and e["name"] == "device.sweep_accept_frac"]
    assert len(events) == sweeps
    ts = [e["ts"] for e in events]
    assert ts == sorted(ts)
    assert default_registry().undeclared(rec) == []


def test_record_sweep_trace_interpolates_timestamps():
    rec = Recorder(clock=lambda: 0.0)
    sink = ChromeTraceSink(rec)
    rec.add_sink(sink)
    device.record_sweep_trace(rec, 10.0, 14.0, 4, [0.5, 0.25, 0.0, 0.0])
    samples = sorted(sink._counter_samples)
    assert [t for t, _, _ in samples] == [11.0, 12.0, 13.0, 14.0]
    assert [v for _, _, v in samples] == [0.5, 0.25, 0.0, 0.0]
    device.record_sweep_trace(rec, 0.0, 1.0, 0, [])  # no-op, no raise


def test_recorder_sample_feeds_histogram_and_counter_sinks():
    rec = Recorder(clock=lambda: 42.0)
    sink = ChromeTraceSink(rec)
    rec.add_sink(sink)
    rec.sample("device.sweep_accept_frac", 0.75)
    rec.sample("device.sweep_accept_frac", 0.25, t=99.0)
    assert rec.histogram_summary("device.sweep_accept_frac")["count"] == 2
    assert (42.0, "device.sweep_accept_frac", 0.75) in sink._counter_samples
    assert (99.0, "device.sweep_accept_frac", 0.25) in sink._counter_samples


# ---------------------------------------------------------------------------
# TraceContext + RequestTimeline
# ---------------------------------------------------------------------------


def test_trace_id_source_is_deterministic():
    a, b = TraceIdSource(), TraceIdSource()
    assert [a.mint().trace_id for _ in range(3)] == \
        [b.mint().trace_id for _ in range(3)] == \
        ["req-000001", "req-000002", "req-000003"]
    child = a.mint().child("dispatch")
    assert child.trace_id == "req-000004/dispatch"
    assert child.parent_id == "req-000004"


def test_request_timeline_segments_tile_exactly():
    rec = Recorder(clock=lambda: 0.0)
    sink = InMemorySink()
    rec.add_sink(sink)
    tl = RequestTimeline(TraceContext("req-000042"), 1.0)
    for name, t in zip(SEGMENTS, (1.5, 2.0, 2.25, 4.0, 4.125)):
        tl.mark(name, t)
    assert [n for n, _ in tl.segments()] == list(SEGMENTS)
    assert sum(d for _, d in tl.segments()) == pytest.approx(
        4.125 - 1.0, abs=1e-12)
    tl.record(rec, tenant="t0")
    req = sink.by_name("fleet.request")[0]
    assert req.attrs["trace_id"] == "req-000042"
    assert req.task == "req:req-000042"
    assert req.t_start == 1.0 and req.t_end == 4.125
    # One child span per segment, contiguous on the same lane.
    seg_spans = [sp for sp in sink.spans
                 if sp.name.startswith("fleet.request.")]
    assert [sp.name.rsplit(".", 1)[1] for sp in seg_spans] == list(SEGMENTS)
    for prev_sp, sp in zip(seg_spans, seg_spans[1:]):
        assert sp.t_start == prev_sp.t_end
    # And one histogram observation per segment, labeled.
    for name in SEGMENTS:
        h = rec.histogram_summary(
            f'fleet.request_segment_s{{segment="{name}"}}')
        assert h is not None and h["count"] == 1


def test_jsonl_sink_round_trips_trace_fields(tmp_path):
    path = str(tmp_path / "spans.jsonl")
    rec = Recorder(clock=lambda: 0.0)
    sink = JsonlSink(path)
    rec.add_sink(sink)
    tl = RequestTimeline(TraceContext("req-000007", parent_id="up-1"), 0.0)
    for name, t in zip(SEGMENTS, (0.1, 0.2, 0.3, 0.4, 0.5)):
        tl.mark(name, t)
    tl.record(rec, tenant="tX", warm=True)
    sink.close()
    lines = [json.loads(line) for line in open(path)]
    assert len(lines) == len(SEGMENTS) + 1
    by_name = {d["name"]: d for d in lines}
    req = by_name["fleet.request"]
    assert req["attrs"]["trace_id"] == "req-000007"
    assert req["attrs"]["trace_parent_id"] == "up-1"
    assert req["attrs"]["tenant"] == "tX" and req["attrs"]["warm"] is True
    assert req["task"] == "req:req-000007"
    for name in SEGMENTS:
        assert by_name[f"fleet.request.{name}"]["attrs"]["trace_id"] == \
            "req-000007"
    # Segment attrs survive the JSON round trip and still tile.
    seg_sum = sum(req["attrs"][f"{n}_s"] for n in SEGMENTS)
    assert seg_sum == pytest.approx(req["duration_s"], abs=1e-12)


def test_jsonl_rotation_boundary_preserves_trace_ids(tmp_path):
    path = str(tmp_path / "rot.jsonl")
    rec = Recorder(clock=lambda: 0.0)
    sink = JsonlSink(path, max_bytes=400, keep=3)
    rec.add_sink(sink)
    ids = [f"req-{i:06d}" for i in range(1, 13)]
    for tid in ids:
        tl = RequestTimeline(TraceContext(tid), 0.0)
        tl.mark("admission", 0.5)
        tl.record(rec)
    sink.close()
    import glob
    seen = []
    for f in sorted(glob.glob(path + "*")):
        for line in open(f):
            d = json.loads(line)  # every rotated file is valid JSONL
            if d["name"] == "fleet.request":
                seen.append(d["attrs"]["trace_id"])
    # Rotation dropped only WHOLE oldest files; what remains is a
    # contiguous suffix with every record intact.
    assert seen
    assert sorted(seen) == seen or set(seen) <= set(ids)
    assert set(seen) <= set(ids)
    assert ids[-1] in seen  # the newest record survived in `path`


# ---------------------------------------------------------------------------
# PlanService request decomposition
# ---------------------------------------------------------------------------


def test_service_decomposes_every_request():
    rec = Recorder()
    sink = InMemorySink()
    rec.add_sink(sink)

    async def main():
        svc = PlanService(admission_window_s=0.002, recorder=rec,
                          max_pending=16)
        await svc.start()
        tenants = [_tenant(17 + (i % 2), 8, i, f"t{i}") for i in range(6)]
        results = await asyncio.gather(*[svc.submit(t) for t in tenants])
        await svc.stop()
        return results

    with use_recorder(rec):
        results = asyncio.run(main())
    assert len(results) == 6
    req_spans = sink.by_name("fleet.request")
    assert len(req_spans) == 6
    assert {sp.attrs["trace_id"] for sp in req_spans} == \
        {f"req-{i:06d}" for i in range(1, 7)}
    for sp in req_spans:
        seg_sum = sum(sp.attrs[f"{n}_s"] for n in SEGMENTS)
        # The acceptance contract: per-request segment sums equal the
        # end-to-end latency (same endpoints, telescoping differences).
        assert seg_sum == pytest.approx(sp.duration_s, abs=1e-9)
        assert all(sp.attrs[f"{n}_s"] >= 0 for n in SEGMENTS)
    for name in SEGMENTS:
        h = rec.histogram_summary(
            f'fleet.request_segment_s{{segment="{name}"}}')
        assert h is not None and h["count"] == 6
    # The batch dispatch knows its member trace ids.
    dispatch = sink.by_name("fleet.dispatch")
    assert dispatch and all("trace_ids" in sp.attrs for sp in dispatch)
    assert any("req-000001" in sp.attrs["trace_ids"] for sp in dispatch)
    # Everything emitted is registry-declared (drift guard extension).
    assert default_registry().undeclared(rec) == []


def test_service_request_tracing_vt_bit_identical():
    """The acceptance contract: a seeded PlanService run under
    DeterministicLoop renders segment histograms (the whole exposition
    text) bit-identically across two runs of the same seed."""
    from blance_tpu.testing.sched import RandomWalkPolicy, run_controlled

    def factory():
        async def scenario():
            loop = asyncio.get_running_loop()
            rec = Recorder(clock=loop.time)
            with use_recorder(rec):
                svc = PlanService(admission_window_s=0.002, recorder=rec,
                                  inline_solve=True, max_pending=16)
                await svc.start()
                tenants = [_tenant(17 + (i % 2), 8, i, f"t{i}")
                           for i in range(5)]
                await asyncio.gather(*[svc.submit(t) for t in tenants])
                await svc.stop()
                return render_prometheus(rec)
        return scenario()

    a = run_controlled(factory, RandomWalkPolicy(13))
    b = run_controlled(factory, RandomWalkPolicy(13))
    assert a.ok, a.describe()
    assert b.ok, b.describe()
    assert a.result == b.result
    samples, _ = parse_prometheus(a.result)
    for name in SEGMENTS:
        assert samples[
            "blance_fleet_request_segment_s_count"
            f'{{segment="{name}"}}'] == 5


# ---------------------------------------------------------------------------
# /healthz
# ---------------------------------------------------------------------------


def test_healthz_503_before_first_snapshot_then_200():
    rec = Recorder()

    async def main():
        server = MetricsServer(recorder=rec, min_interval_s=0.0)
        await server.start()
        try:
            with pytest.raises(RuntimeError, match="503"):
                await scrape("127.0.0.1", server.port, path="/healthz")
            await scrape("127.0.0.1", server.port)  # first snapshot
            body = await scrape("127.0.0.1", server.port, path="/healthz")
            hz = json.loads(body)
            assert hz["status"] == "ok"
            assert hz["uptime_s"] >= 0
            assert hz["snapshot_age_s"] >= 0
            assert hz["snapshots"] == 1
        finally:
            await server.stop()

    asyncio.run(main())
