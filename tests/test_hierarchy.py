"""Ports of the reference's hierarchy-tree unit tests (plan_test.go:305-390)."""

from blance_tpu.core.hierarchy import (
    find_ancestor,
    find_leaves,
    include_exclude_nodes,
    include_exclude_nodes_intersect,
    level_group_ids,
    parents_to_children,
)


def test_find_ancestor():
    cases = [
        (0, {}, "a"),
        (1, {}, ""),
        (2, {}, ""),
        (0, {"a": "r"}, "a"),
        (1, {"a": "r"}, "r"),
        (2, {"a": "r"}, ""),
        (3, {"a": "r"}, ""),
        (0, {"a": "r", "r": "g"}, "a"),
        (1, {"a": "r", "r": "g"}, "r"),
        (2, {"a": "r", "r": "g"}, "g"),
        (3, {"a": "r", "r": "g"}, ""),
    ]
    for level, parents, exp in cases:
        assert find_ancestor("a", parents, level) == exp


def test_find_leaves():
    cases = [
        ({}, ["a"]),
        ({"x": ["xx"]}, ["a"]),
        ({"a": []}, ["a"]),
        ({"a": ["b"]}, ["b"]),
        ({"a": ["b", "c"]}, ["b", "c"]),
        ({"a": ["b", "c"], "c": ["c1", "c2"]}, ["b", "c1", "c2"]),
    ]
    for children, exp in cases:
        assert find_leaves("a", children) == exp


def test_parents_to_children():
    cases = [
        ({}, {}),
        ({"a": "r"}, {"r": ["a"]}),
        ({"a": "r", "b": "r2"}, {"r": ["a"], "r2": ["b"]}),
        ({"a": "r", "a1": "a"}, {"r": ["a"], "a": ["a1"]}),
        ({"a": "r", "a1": "a", "a2": "a"}, {"r": ["a"], "a": ["a1", "a2"]}),
        # Children come out sorted by name for determinism.
        ({"a": "r", "a1": "a", "a2": "a", "a0": "a"},
         {"r": ["a"], "a": ["a0", "a1", "a2"]}),
    ]
    for parents, exp in cases:
        assert parents_to_children(parents) == exp


_TREE = {
    "a": "r0", "b": "r0", "c": "r1", "d": "r1",
    "r0": "z0", "r1": "z0",
}


def test_include_exclude_nodes():
    children = parents_to_children(_TREE)
    # Same rack as a (include 1), excluding a itself (exclude 0).
    assert include_exclude_nodes("a", 1, 0, _TREE, children) == ["b"]
    # Different rack than a: include zone (2), exclude rack (1).
    assert include_exclude_nodes("a", 2, 1, _TREE, children) == ["c", "d"]
    # Degenerate: include self only.
    assert include_exclude_nodes("a", 0, 0, _TREE, children) == []
    # Beyond the root: the missing-ancestor "" sentinel survives as a leaf
    # (it is filtered later by intersecting with real nodes).
    assert include_exclude_nodes("a", 3, 2, _TREE, children) == [""]


def test_include_exclude_nodes_intersect():
    children = parents_to_children(_TREE)
    # Anchored on a and c: nodes in a different rack from both -> none
    # (everything is in r0 or r1).
    assert include_exclude_nodes_intersect(["a", "c"], 2, 1, _TREE, children) == []
    # Anchored on a only, via the intersect API.
    assert include_exclude_nodes_intersect(["a"], 2, 1, _TREE, children) == ["c", "d"]


def test_level_group_ids():
    gids = level_group_ids(["a", "b", "c", "d"], _TREE, 2)
    # Level 0: every node its own group.
    assert gids[0] == [0, 1, 2, 3]
    # Level 1: rack groups.
    assert gids[1] == [0, 0, 1, 1]
    # Level 2: one zone.
    assert gids[2] == [0, 0, 0, 0]
