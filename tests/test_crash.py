"""Crash-injection tier (docs/DURABILITY.md "Crash injection"): the
headline acceptance for the durable control plane.

Covers: the bounded-exhaustive crash matrix — one deterministic run per
journal-record boundary of the ``crash_smoke`` scenario, each restart
recovering from the WAL and converging to the crash-free reference's
final map bit-identically; the ``crash_storm`` multi-crash chain
(restarts landing mid-incident, including one during an overlapping
supersede) with its committed byte-identical replay trace; and a fleet
crash/resume round-trip (shared tenant-tagged WAL, per-tenant
``resume_tenant``).  The harness lives in testing/crashsim.py; the
mechanism-level durability tests (framing, torn tails, fencing,
round-trips) in tests/test_durability.py.
"""

import asyncio

import pytest

from blance_tpu.core.types import Partition, model
from blance_tpu.durability import Journal, recover, reset_fences
from blance_tpu.fleetloop import FleetController
from blance_tpu.obs import Recorder, use_recorder
from blance_tpu.rebalance import ClusterDelta
from blance_tpu.testing.crashsim import (
    crash_matrix,
    maps_identical,
    run_crash_scenario,
)
from blance_tpu.testing.scenarios import crash_smoke, crash_storm
from blance_tpu.testing.sched import DeterministicLoop, FifoPolicy

CRASH_TRACE_PATH = "tests/traces/crash_storm_s19.json"


@pytest.fixture(autouse=True)
def _crash_env(monkeypatch):
    """Virtual-time crash runs hammer the journal: gate fsync off
    (atomicity and replay order still fully exercised) and isolate the
    process-level fence registry per test."""
    monkeypatch.setenv("BLANCE_WAL_FSYNC", "0")
    reset_fences()
    yield
    reset_fences()


# -- determinism --------------------------------------------------------------


def test_crash_run_bit_identical_across_runs(tmp_path):
    """Same scenario + same crash boundaries => byte-identical event
    log and identical final map — the determinism contract that makes
    every crash reproducible from its trace line."""
    scn = crash_smoke(17)
    a = run_crash_scenario(scn, str(tmp_path / "a"), crashes=(9,))
    b = run_crash_scenario(scn, str(tmp_path / "b"), crashes=(9,))
    assert a.log_text() == b.log_text()
    assert maps_identical(a.final_map, b.final_map)
    assert a.lives == 2
    # A different boundary is a genuinely different trace.
    c = run_crash_scenario(scn, str(tmp_path / "c"), crashes=(10,))
    assert c.log_text() != a.log_text()


# -- the headline acceptance: bounded-exhaustive crash injection --------------


def test_exhaustive_crash_matrix_recovers_bit_identically(tmp_path):
    """Crash at EVERY journal-record boundary of crash_smoke (including
    boundary 0 — the genesis record itself lost): each restart recovers
    from the WAL, redelivers the non-durable events, and converges to
    the crash-free reference's final map bit-identically."""
    scn = crash_smoke(17)
    ref, runs = crash_matrix(scn, str(tmp_path))
    assert ref.records_first_life >= 20  # the matrix is a real sweep
    assert len(runs) == ref.records_first_life
    for k, report in runs:
        assert report.lives == 2, f"boundary {k}: {report.lives} lives"
        assert maps_identical(report.final_map, ref.final_map), (
            f"crash at record boundary {k} recovered to a DIFFERENT "
            f"final map than the crash-free reference")
        assert report.counters.get("durability.recoveries") == 1
        assert report.counters.get(
            "durability.recovery_cold_solves", 0) <= 1


@pytest.mark.slow
def test_exhaustive_crash_matrix_with_snapshots(tmp_path):
    """The same sweep with a tight snapshot cadence and small segments:
    every boundary now also lands around snapshot pointers and segment
    rotations, exercising the fast-forward restore path."""
    scn = crash_smoke(17)
    ref, runs = crash_matrix(scn, str(tmp_path), snapshot_every=4,
                             rotate_records=8)
    for k, report in runs:
        assert maps_identical(report.final_map, ref.final_map), (
            f"crash at boundary {k} (snapshot cadence 4) diverged")


# -- crash_storm: multi-crash chain + committed trace -------------------------


def test_crash_storm_chain_converges_to_reference(tmp_path):
    """Three controller crash-restarts landing mid-incident (one during
    the overlapping supersede): the chain recovers each time and ends
    on the crash-free reference's exact final map, with every recovery
    and cold solve counted."""
    cs = crash_storm(19)
    ref = run_crash_scenario(cs.base, str(tmp_path / "ref"))
    storm = run_crash_scenario(
        cs.base, str(tmp_path / "storm"), crashes=cs.crashes,
        snapshot_every=cs.snapshot_every,
        rotate_records=cs.rotate_records)
    assert storm.lives == len(cs.crashes) + 1
    assert maps_identical(storm.final_map, ref.final_map)
    assert storm.counters["durability.recoveries"] == len(cs.crashes)
    assert storm.counters["durability.recovery_cold_solves"] == \
        len(cs.crashes)
    assert storm.counters["durability.snapshots"] >= 1


def test_committed_crash_storm_trace_replays_exactly(tmp_path):
    """The committed crash_storm trace regenerates byte-for-byte — any
    drift in journal framing, recovery folding, clock re-basing or the
    harness itself shows up as a diff here and must be understood
    (then the trace regenerated)."""
    with open(CRASH_TRACE_PATH) as f:
        committed = f.read()
    cs = crash_storm(19)
    live = run_crash_scenario(
        cs.base, str(tmp_path), crashes=cs.crashes,
        snapshot_every=cs.snapshot_every,
        rotate_records=cs.rotate_records).log_text()
    assert live == committed, (
        "crash-recovery behavior drifted from the committed trace "
        f"({CRASH_TRACE_PATH}); if the change is intended, regenerate: "
        "env BLANCE_WAL_FSYNC=0 python -c \"import tempfile; "
        "from blance_tpu.testing.scenarios import crash_storm; "
        "from blance_tpu.testing.crashsim import run_crash_scenario; "
        "cs = crash_storm(19); open('" + CRASH_TRACE_PATH + "', 'w')"
        ".write(run_crash_scenario(cs.base, tempfile.mkdtemp(), "
        "crashes=cs.crashes, snapshot_every=cs.snapshot_every, "
        "rotate_records=cs.rotate_records).log_text())\"")


# -- fleet crash/resume -------------------------------------------------------

M = model(primary=(0, 1))


def _pmap():
    return {f"p{i}": Partition(f"p{i}", {"primary": ["n0"]})
            for i in range(4)}


def _nbs(maps):
    return {k: {n: {s: list(ns) for s, ns in p.nodes_by_state.items()}
                for n, p in m.items()} for k, m in maps.items()}


async def _assign(stop_ch, node, partitions, states, ops):
    await asyncio.sleep(0)


def test_fleet_crash_resume_round_trip(tmp_path):
    """Two tenant loops journaling through one shared tenant-tagged WAL
    (plus untagged fleet-tier membership records): kill the fleet after
    convergence, recover the journal, resume_tenant each loop in a
    FRESH process (new virtual loop, clock restarted at zero) — the
    resumed fleet quiesces to bit-identical per-tenant maps."""
    journal_dir = str(tmp_path)
    loop = DeterministicLoop(FifoPolicy())
    rec = Recorder(clock=loop.time)

    async def first_life():
        with use_recorder(rec):
            j = Journal(journal_dir, clock=loop.time, snapshot_every=6)
            fc = FleetController(["n0", "n1", "n2"], inline_solve=True,
                                 recorder=rec, debounce_s=0.01,
                                 journal=j)
            await fc.start()
            for key in ("ta", "tb"):
                fc.add_tenant(key, M, _pmap(), _assign)
            fc.submit_all(ClusterDelta(fail=("n0",)))
            maps = await fc.quiesce_all()
            await fc.stop()
            j.close()
            return maps

    maps1 = loop.run_until_complete(first_life())

    loop2 = DeterministicLoop(FifoPolicy())
    rec2 = Recorder(clock=loop2.time)

    async def second_life():
        with use_recorder(rec2):
            st = recover(journal_dir, clock=loop2.time)
            assert sorted(k for k in st.tenants if k is not None) == \
                ["ta", "tb"]
            fc = FleetController(["n0", "n1", "n2"], inline_solve=True,
                                 recorder=rec2, debounce_s=0.01,
                                 journal=st.journal)
            await fc.start()
            for key in ("ta", "tb"):
                fc.resume_tenant(st, key, M, _assign)
            maps = await fc.quiesce_all()
            await fc.stop()
            st.journal.close()
            return maps

    maps2 = loop2.run_until_complete(second_life())
    assert _nbs(maps1) == _nbs(maps2)
    # The resume's cold solves stay inside the attribution bound: at
    # most one counted cold solve per resumed tenant.
    assert rec2.counters["durability.recoveries"] == 1
    assert rec2.counters["durability.recovery_cold_solves"] <= 2
