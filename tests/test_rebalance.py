"""App-level rebalance facade + checkpoint round-trip tests."""

import os

from blance_tpu import (
    Partition,
    load_partition_map,
    model,
    plan_next_map,
    plan_next_map_legacy,
    rebalance,
    save_partition_map,
)

M = model(primary=(0, 1), replica=(1, 1))


def test_rebalance_end_to_end(tmp_path):
    nodes = ["a", "b", "c", "d"]
    beg, _ = plan_next_map(
        {str(i): Partition(str(i), {}) for i in range(12)},
        {str(i): Partition(str(i), {}) for i in range(12)},
        nodes, [], nodes, M)

    cluster = {p: {n: s for s, ns in part.nodes_by_state.items() for n in ns}
               for p, part in beg.items()}

    def assign(stop_ch, node, partitions, states, ops):
        for p, s, _op in zip(partitions, states, ops):
            if s == "":
                cluster[p].pop(node, None)
            else:
                cluster[p][node] = s

    ckpt = str(tmp_path / "target.json")
    seen_progress = []
    result = rebalance(
        M, beg, nodes, ["d"], [], assign,
        on_progress=seen_progress.append,
        checkpoint_path=ckpt,
    )

    assert not result.warnings
    assert result.progress_events == len(seen_progress) > 0
    assert not result.progress.errors
    assert "plan" in result.timer.totals and "orchestrate" in result.timer.totals

    # The cluster converged to the planned map.
    want = {p: {n: s for s, ns in part.nodes_by_state.items() for n in ns}
            for p, part in result.next_map.items()}
    assert cluster == want
    # No assignments remain on the removed node.
    assert all("d" not in v for v in cluster.values())

    # Checkpoint written and loadable.
    assert os.path.exists(ckpt)
    assert load_partition_map(ckpt) == result.next_map


def test_checkpoint_round_trip(tmp_path):
    pmap = {"x": Partition("x", {"primary": ["a"], "replica": ["b", "c"]})}
    path = str(tmp_path / "map.json")
    save_partition_map(pmap, path)
    assert load_partition_map(path) == pmap
    # Atomic write must not leak its temp file alongside the checkpoint.
    assert os.listdir(tmp_path) == ["map.json"]


def test_checkpoint_write_preserves_permissions(tmp_path):
    """The atomic tmp+rename must not tighten the checkpoint's mode to
    mkstemp's 0600: fresh files honor the umask, existing files keep
    their mode (unprivileged monitoring/backup readers stay working)."""
    pmap = {"x": Partition("x", {"primary": ["a"]})}
    path = str(tmp_path / "map.json")
    old_umask = os.umask(0o022)
    try:
        save_partition_map(pmap, path)
        assert os.stat(path).st_mode & 0o777 == 0o644
        os.chmod(path, 0o664)
        save_partition_map(pmap, path)  # overwrite keeps the custom mode
        assert os.stat(path).st_mode & 0o777 == 0o664
    finally:
        os.umask(old_umask)


def test_legacy_signature():
    result, warnings = plan_next_map_legacy(
        {}, {"0": Partition("0", {})}, ["a", "b"], [], ["a", "b"], M,
        None, None, None, {"a": 3}, None, None)
    assert result["0"].nodes_by_state["primary"] == ["a"]
    assert not warnings
