"""Continuous-rebalance simulator tier (docs/SIMULATOR.md).

Covers the closed control loop end to end: scenario determinism (same
seed => byte-identical event log, SLO summary and rendered exposition
text), exact replay of a committed trace, the CI sim-smoke matrix
(3 fixed seeds x spot-preemption / zone-flap / weight-drift scenarios),
the SLO brute-force property tests (incremental tracker == ground-truth
recompute from the raw event log), the controller's supersede /
debounce / degradation behaviors, the recovery-exhaustion and
empty-candidate satellites on rebalance_async, and the slow-marked
7-virtual-day soak.
"""

import asyncio

import pytest

from blance_tpu.core.types import Partition, model
from blance_tpu.obs import Recorder, use_recorder
from blance_tpu.obs.slo import SloTracker
from blance_tpu.orchestrate import FaultPlan, NodeFaults
from blance_tpu.orchestrate.orchestrator import (
    OrchestratorOptions,
    orchestrate_moves,
)
from blance_tpu.rebalance import (
    ClusterDelta,
    DegradedPlacement,
    RebalanceController,
    count_moves,
    rebalance_async,
)
from blance_tpu.testing.scenarios import (
    SCENARIOS,
    hetero_drain,
    mixed_week,
    spot_preemption,
)
from blance_tpu.testing.simulate import (
    recompute_slo_from_log,
    run_scenario,
)

SIM_SMOKE_SEEDS = (11, 23, 37)
SMOKE_FAMILIES = ("spot_preemption", "zone_flap", "weight_drift")

TRACE_PATH = "tests/traces/sim_spot_preemption_s11.json"


def _pm(d):
    return {name: Partition(name, {s: list(ns) for s, ns in nbs.items()})
            for name, nbs in d.items()}


async def _noop_assign(stop_ch, node, partitions, states, ops):
    await asyncio.sleep(0)


# -- determinism & replay -----------------------------------------------------


@pytest.mark.parametrize("family", SMOKE_FAMILIES)
def test_scenario_bit_identical_across_runs(family):
    """Same scenario seed => byte-identical event log, equal SLO
    summary, and byte-identical rendered exposition text — the
    determinism contract the whole tier stands on."""
    a = run_scenario(SCENARIOS[family](11))
    b = run_scenario(SCENARIOS[family](11))
    assert a.log_text() == b.log_text()
    assert a.summary == b.summary
    assert a.exposition == b.exposition
    # And a different seed is a genuinely different trace.
    c = run_scenario(SCENARIOS[family](12))
    assert c.log_text() != a.log_text()


def test_committed_trace_replays_exactly():
    """The committed event log regenerates byte-for-byte — any drift in
    planner, orchestrator, controller or SLO arithmetic shows up as a
    diff here and must be understood (then the trace regenerated)."""
    with open(TRACE_PATH) as f:
        committed = f.read()
    live = run_scenario(spot_preemption(11)).log_text()
    assert live == committed, (
        "simulator behavior drifted from the committed trace "
        f"({TRACE_PATH}); if the change is intended, regenerate it: "
        "python -c \"from blance_tpu.testing.scenarios import "
        "spot_preemption; from blance_tpu.testing.simulate import "
        "run_scenario; open('" + TRACE_PATH + "', 'w').write("
        "run_scenario(spot_preemption(11)).log_text())\"")


SCHED_TRACE_PATH = "tests/traces/sim_hetero_drain_s41.json"


def test_hetero_drain_scheduled_trace_replays_exactly():
    """The committed hetero_drain trace is the CRITICAL-PATH-scheduled
    account of the family (docs/SCHEDULER.md): any drift in scheduler
    arithmetic — ranks, lane assignment, reschedule timing — shows up
    as a byte diff here and must be understood (then regenerated)."""
    import dataclasses

    with open(SCHED_TRACE_PATH) as f:
        committed = f.read()
    scn = dataclasses.replace(hetero_drain(41), scheduler="critical_path")
    assert run_scenario(scn).log_text() == committed, (
        "scheduled-simulation behavior drifted from the committed "
        f"trace ({SCHED_TRACE_PATH}); if intended, regenerate it: "
        "python -c \"import dataclasses; from blance_tpu.testing."
        "scenarios import hetero_drain; from blance_tpu.testing."
        "simulate import run_scenario; open('" + SCHED_TRACE_PATH
        + "', 'w').write(run_scenario(dataclasses.replace("
        "hetero_drain(41), scheduler='critical_path')).log_text())\"")


def test_hetero_drain_scheduled_beats_legacy_at_equal_churn():
    """The makespan claim (ISSUE 12): on the heterogeneous-latency
    drain family the critical-path order converges measurably faster
    than the app-weight order — strictly lower post-warmup makespan
    p95 — while executing the IDENTICAL move set (equal churn, equal
    final map; only the clock differs).  Virtual time, so the
    comparison is exact."""
    import dataclasses

    scn = hetero_drain(41)
    leg = run_scenario(scn)
    crit = run_scenario(
        dataclasses.replace(scn, scheduler="critical_path"))
    assert {k: v.nodes_by_state for k, v in leg.final_map.items()} == \
        {k: v.nodes_by_state for k, v in crit.final_map.items()}
    assert leg.summary.moves_executed == crit.summary.moves_executed
    # Incident 0 is the cost model's calibration join (identical either
    # way); the measured incidents are the two joins after it.
    leg_lags = leg.summary.first_converged_lags[1:]
    crit_lags = crit.summary.first_converged_lags[1:]
    assert len(leg_lags) == len(crit_lags) == 2
    assert max(crit_lags) < max(leg_lags)
    assert sum(crit_lags) < sum(leg_lags)


# -- the sim-smoke matrix -----------------------------------------------------


@pytest.mark.parametrize("seed", SIM_SMOKE_SEEDS)
@pytest.mark.parametrize("family", SMOKE_FAMILIES)
def test_sim_smoke(family, seed):
    """Final-map completeness, availability >= the scenario floor, no
    availability drop outside a scripted outage window, every incident
    converged."""
    r = run_scenario(SCENARIOS[family](seed))
    assert r.complete, f"{family}/{seed}: final map incomplete"
    assert r.summary.availability == 1.0
    assert r.summary.time_weighted_availability >= \
        SCENARIOS[family](seed).availability_floor
    assert r.unscripted_drops == []
    assert r.unconverged == 0
    assert len(r.convergence_lags) == r.deltas
    assert all(lag >= 0 for lag in r.convergence_lags)
    # The trace actually exercised the loop.
    assert r.rebalances >= 1 and r.summary.moves_executed > 0


@pytest.mark.parametrize("seed", SIM_SMOKE_SEEDS)
def test_slo_summary_matches_brute_force_recompute(seed):
    """Property test: the tracker's INCREMENTAL availability / churn /
    lag / violation account must equal a ground-truth recompute from
    the raw event log (catches incremental-view drift)."""
    for family in SMOKE_FAMILIES:
        r = run_scenario(SCENARIOS[family](seed))
        ref = recompute_slo_from_log(r.events)
        s = r.summary
        assert s.availability == ref["availability"], family
        assert s.moves_executed == ref["moves_executed"], family
        assert s.moves_failed == ref["moves_failed"], family
        assert abs(s.time_weighted_availability -
                   ref["time_weighted_availability"]) < 1e-12, family
        assert abs(s.violation_s - ref["violation_s"]) < 1e-12, family
        assert s.violation_intervals == ref["violation_intervals"], family
        assert abs(s.convergence_lag_s -
                   ref["convergence_lag_s"]) < 1e-12, family


# -- the long-horizon soak (slow tier) ---------------------------------------


@pytest.mark.slow
def test_seven_day_mixed_fault_soak():
    """7 virtual days of mixed faults (>= 20 deltas, overlapping ones
    included) must complete in well under 60 s wall-clock with a
    complete final map and zero availability drops outside scripted
    outage windows."""
    scn = mixed_week(7)
    assert scn.horizon_s == 7 * 86_400.0
    assert len(scn.events) >= 20
    r = run_scenario(scn)
    assert r.wall_s < 60.0, f"soak took {r.wall_s:.1f}s wall-clock"
    assert r.complete
    assert r.summary.availability == 1.0
    assert r.unscripted_drops == [], r.unscripted_drops
    assert r.superseded >= 1  # the overlapping deltas really overlap
    assert r.unconverged == 0
    assert r.summary.time_weighted_availability >= scn.availability_floor
    # Determinism holds at the week horizon too.
    assert run_scenario(mixed_week(7)).log_text() == r.log_text()


# -- SLO horizon accounting (unit) -------------------------------------------


def test_slo_timeline_and_time_weighted_availability():
    t = {"now": 0.0}
    beg = _pm({"p0": {"primary": ["a"]}, "p1": {"primary": ["a"]}})
    slo = SloTracker(beg, clock=lambda: t["now"], track_timeline=True,
                     availability_floor=0.9)
    assert slo.time_weighted_availability(0.0) == 1.0
    t["now"] = 10.0
    slo.strip_nodes({"a"}, now=10.0)  # availability 1 -> 0 at t=10
    assert slo.availability() == 0.0
    # [0,10) at 1.0, [10,20) at 0.0 -> 0.5 time-weighted.
    assert slo.time_weighted_availability(20.0) == 0.5
    assert slo.violation_intervals(20.0) == [(10.0, 20.0)]
    assert slo.violation_s(20.0) == 10.0

    class Mv:
        partition, node, state, op = "p0", "b", "primary", "add"

    t["now"] = 20.0
    slo.on_batch("b", [Mv()], ok=True, now=20.0)  # 0 -> 0.5 at t=20
    assert slo.availability() == 0.5
    tl = slo.timeline()
    assert tl == [(0.0, 1.0), (10.0, 0.0), (20.0, 0.5)]
    # [0,10)=1, [10,20)=0, [20,30)=0.5 over 30s -> 0.5
    assert slo.time_weighted_availability(30.0) == 0.5
    # Still below the 0.9 floor: the violation interval stays open.
    assert slo.violation_intervals(30.0) == [(10.0, 30.0)]
    s = slo.summary(30.0)
    assert s.time_weighted_availability == 0.5
    assert s.availability_floor == 0.9
    assert s.violation_s == 20.0


def test_slo_timeline_off_by_default():
    beg = _pm({"p0": {"primary": ["a"]}})
    slo = SloTracker(beg)
    assert slo.timeline() == []
    s = slo.summary()
    assert s.time_weighted_availability is None
    assert s.violation_intervals == []


def test_slo_horizon_gauges_published():
    rec = Recorder()
    beg = _pm({"p0": {"primary": ["a"]}})
    slo = SloTracker(beg, recorder=rec, track_timeline=True,
                     availability_floor=0.5)
    slo.publish()
    assert "slo.time_weighted_availability" in rec.gauges
    assert "slo.violation_seconds" in rec.gauges


# -- recovery exhaustion & empty-candidate satellites ------------------------


def _dead_cluster():
    m = model(primary=(0, 1))
    beg = _pm({f"p{i}": {"primary": [["a", "b"][i % 2]]}
               for i in range(4)})
    plan = FaultPlan(seed=3, nodes={"a": NodeFaults(dead=True),
                                    "b": NodeFaults(dead=True)})
    opts = OrchestratorOptions(move_timeout_s=0.25, max_retries=0,
                               quarantine_after=1, probe_after_s=600.0)
    return m, beg, plan, opts


def test_unconverged_rebalance_is_structured_not_silent():
    """Recovery exhaustion surfaces as converged=False + a residual
    summary + the rebalance.unconverged counter — never a partial map
    indistinguishable from success."""
    m, beg, plan, opts = _dead_cluster()
    rec = Recorder()
    with use_recorder(rec):
        r = asyncio.run(rebalance_async(
            m, beg, ["a", "b"], ["a"], [], plan.wrap(_noop_assign),
            orchestrator_options=opts, max_recovery_rounds=3,
            backend="greedy"))
    assert r.converged is False
    assert r.residual_failures and \
        sum(r.residual_failures.values()) > 0
    assert rec.counters.get("rebalance.unconverged", 0) == 1


def test_all_nodes_quarantined_degrades_structurally():
    """The all-nodes-quarantined edge: the recovery replan's candidate
    set is EMPTY — the result must be a structured empty-placement
    degradation, not a planner exception (the simulator's zone-outage
    scenarios hit this in normal operation)."""
    m, beg, plan, opts = _dead_cluster()
    rec = Recorder()
    with use_recorder(rec):
        r = asyncio.run(rebalance_async(
            m, beg, ["a", "b"], ["a"], [], plan.wrap(_noop_assign),
            orchestrator_options=opts, max_recovery_rounds=3,
            backend="greedy"))
    assert isinstance(r.degraded, DegradedPlacement)
    assert r.degraded.reason == "no-candidate-nodes"
    assert r.degraded.nodes_available == 0
    assert all(p.nodes_by_state.get("primary") == []
               for p in r.next_map.values())
    assert r.converged is False
    assert rec.counters.get("rebalance.degraded", 0) == 1
    # And it stopped burning recovery rounds once nothing could help:
    # one primary pass, not 1 + max_recovery_rounds.
    assert len(r.rounds) == 1


def test_converged_rebalance_reports_true():
    m = model(primary=(0, 1))
    beg = _pm({f"p{i}": {"primary": ["a"]} for i in range(4)})
    r = asyncio.run(rebalance_async(
        m, beg, ["a", "b"], ["a"], [], _noop_assign,
        orchestrator_options=OrchestratorOptions(move_timeout_s=0.25,
                                                 max_retries=1),
        max_recovery_rounds=2, backend="greedy"))
    assert r.converged is True
    assert r.residual_failures == {}
    assert r.degraded is None


# -- controller behaviors -----------------------------------------------------


def test_controller_debounce_coalesces_burst():
    """Two deltas inside the debounce window become ONE planning
    cycle."""
    async def drive():
        m = model(primary=(0, 1))
        cur = _pm({f"p{i}": {"primary": ["a"]} for i in range(6)})
        ctl = RebalanceController(m, ["a", "b", "c"], cur, _noop_assign,
                                  debounce_s=0.05)
        ctl.start()
        ctl.submit(ClusterDelta(remove=("a",)))
        ctl.submit(ClusterDelta(add=("d",)))
        await asyncio.wait_for(ctl.quiesce(), 10)
        await ctl.stop()
        return ctl
    ctl = asyncio.run(drive())
    assert ctl.cycles == 1
    assert "d" in ctl._nodes


def test_controller_supersede_resumes_from_achieved_map():
    """A delta fired mid-rebalance cancels the in-flight transition and
    the loop still converges on the survivors — same final map as a
    quiesced sequential run of the two deltas."""
    async def drive(interleaved):
        m = model(primary=(0, 1))
        nodes = ["a", "b", "c", "d"]
        cur = _pm({f"p{i}": {"primary": [nodes[i % 4]]}
                   for i in range(8)})
        fired = {"done": False}
        ctl = None

        async def assign(stop_ch, node, partitions, states, ops):
            if interleaved and not fired["done"]:
                fired["done"] = True
                ctl.submit(ClusterDelta(fail=("b",)))
            await asyncio.sleep(0.001)

        ctl = RebalanceController(m, nodes, cur, assign,
                                  debounce_s=0.001)
        ctl.start()
        ctl.submit(ClusterDelta(remove=("a",)))
        if not interleaved:
            await asyncio.wait_for(ctl.quiesce(), 10)
            ctl.submit(ClusterDelta(fail=("b",)))
        final = await asyncio.wait_for(ctl.quiesce(), 10)
        await ctl.stop()
        for _ in range(3):
            await asyncio.sleep(0)
        assert not ctl.pending_tasks()
        return ctl, final

    ctl_i, final_i = asyncio.run(drive(interleaved=True))
    ctl_s, final_s = asyncio.run(drive(interleaved=False))
    assert ctl_i.superseded >= 1
    assert ctl_s.superseded == 0
    m = model(primary=(0, 1))
    from blance_tpu.plan.api import plan_next_map

    # Both runs land on a complete planner FIXPOINT over the survivors
    # with the identical balance profile.  (Which partition sits on c
    # vs d legitimately differs with the cancellation point —
    # stickiness keeps whatever the achieved prefix placed; the
    # byte-equal final-map claim is pinned where it is forced, in the
    # supersede_mid_rebalance explorer scenario's sole-survivor
    # topology.)
    profiles = []
    for final in (final_i, final_s):
        counts: dict = {}
        for p in final.values():
            (n,) = p.nodes_by_state["primary"]
            assert n in ("c", "d")
            counts[n] = counts.get(n, 0) + 1
        profiles.append(sorted(counts.values()))
        nm, _ = plan_next_map(final, final, ["a", "b", "c", "d"],
                              ["a", "b"], [], m, backend="greedy")
        assert count_moves(m, final, nm) == 0
    assert profiles[0] == profiles[1] == [4, 4]


def test_controller_empty_candidates_keeps_current_placements():
    """With every node failed/removed there is nothing to plan onto:
    the controller reports no-candidate degradation and keeps serving
    whatever survived, instead of draining data to nowhere."""
    async def drive():
        m = model(primary=(0, 1))
        cur = _pm({f"p{i}": {"primary": ["a"]} for i in range(4)})
        ctl = RebalanceController(m, ["a", "b"], cur, _noop_assign,
                                  debounce_s=0.001)
        ctl.start()
        ctl.submit(ClusterDelta(fail=("b",), remove=("a",)))
        final = await asyncio.wait_for(ctl.quiesce(), 10)
        await ctl.stop()
        return ctl, final
    ctl, final = asyncio.run(drive())
    assert any(r.reason == "no-candidate-nodes"
               for r in ctl.degraded_reports)
    # "a" was a GRACEFUL removal with nowhere to drain to: its data
    # stays put (never deleted to nowhere).
    assert all(p.nodes_by_state.get("primary") == ["a"]
               for p in final.values())


def test_controller_shed_replicas_before_primaries():
    async def drive():
        m = model(primary=(0, 1), replica=(1, 1))
        cur = _pm({f"p{i}": {"primary": ["a"], "replica": ["b"]}
                   for i in range(4)})
        ctl = RebalanceController(m, ["a", "b"], cur, _noop_assign,
                                  debounce_s=0.001)
        ctl.start()
        ctl.submit(ClusterDelta(fail=("b",)))
        final = await asyncio.wait_for(ctl.quiesce(), 10)
        await ctl.stop()
        return ctl, final
    ctl, final = asyncio.run(drive())
    assert any(r.reason == "capacity-shed" and r.shed == {"replica": 1}
               for r in ctl.degraded_reports)
    for p in final.values():
        assert p.nodes_by_state.get("primary") == ["a"]
        assert p.nodes_by_state.get("replica", []) == []


def test_controller_readd_clears_breaker_and_failed_state():
    """A failed node re-added by a later delta comes back with a clean
    breaker slate (health.forget) and becomes a candidate again."""
    async def drive():
        m = model(primary=(0, 1))
        cur = _pm({f"p{i}": {"primary": ["a"]} for i in range(4)})
        ctl = RebalanceController(
            m, ["a", "b"], cur, _noop_assign, debounce_s=0.001,
            orchestrator_options=OrchestratorOptions(
                move_timeout_s=0.25, max_retries=1, quarantine_after=2))
        ctl.start()
        ctl.submit(ClusterDelta(fail=("a",)))
        await asyncio.wait_for(ctl.quiesce(), 10)
        assert "a" in ctl._failed
        ctl.submit(ClusterDelta(add=("a",)))
        final = await asyncio.wait_for(ctl.quiesce(), 10)
        await ctl.stop()
        return ctl, final
    ctl, final = asyncio.run(drive())
    assert "a" not in ctl._failed
    assert ctl.health.state("a") == "healthy"
    assert set(ctl.live_nodes()) == {"a", "b"}
    for p in final.values():
        assert len(p.nodes_by_state["primary"]) == 1


def test_orchestrator_cancel_counts_and_waits_drained():
    """cancel() is a counted stop; wait_drained() returns only after
    the full wind-down (progress stream closed, movers exited)."""
    async def drive():
        m = model(primary=(0, 1))
        beg = _pm({f"p{i}": {"primary": ["a"]} for i in range(4)})
        end = _pm({f"p{i}": {"primary": ["b"]} for i in range(4)})
        started = asyncio.Event()

        async def assign(stop_ch, node, partitions, states, ops):
            started.set()
            await asyncio.sleep(0.01)

        o = orchestrate_moves(m, OrchestratorOptions(), ["a", "b"],
                              beg, end, assign)

        async def drain():
            async for _p in o.progress_ch():
                pass
            o.stop()

        d = asyncio.ensure_future(drain())
        await started.wait()
        o.cancel()
        o.cancel()  # idempotent: counted once
        await asyncio.wait_for(o.wait_drained(), 5)
        await d
        for _ in range(3):
            await asyncio.sleep(0)
        assert o.pending_tasks() == []
        return o
    o = asyncio.run(drive())
    assert o._progress.tot_cancel == 1
    assert o._progress.tot_progress_close == 1


def test_controller_copies_plan_options():
    """Weight deltas fold into the controller's PRIVATE options view —
    a caller-shared PlanOptions must come out untouched."""
    from blance_tpu.core.types import PlanOptions

    shared = PlanOptions(partition_weights={"p0": 2})

    async def drive():
        m = model(primary=(0, 1))
        cur = _pm({f"p{i}": {"primary": ["a"]} for i in range(4)})
        ctl = RebalanceController(m, ["a", "b"], cur, _noop_assign,
                                  plan_options=shared, debounce_s=0.001)
        ctl.start()
        ctl.submit(ClusterDelta(partition_weights={"p1": 8},
                                remove=("a",)))
        await asyncio.wait_for(ctl.quiesce(), 10)
        await ctl.stop()
        return ctl
    ctl = asyncio.run(drive())
    assert shared.partition_weights == {"p0": 2}
    assert ctl.opts.partition_weights == {"p0": 2, "p1": 8}


def test_session_controller_mirrors_quarantine_into_session():
    """A node the breaker quarantines mid-run must be mirrored into
    the session as removed BEFORE the next session plan — otherwise
    the plan targets a node whose mover is excluded and the pass
    wedges on a moverless target (pre-fix: quiesce() hung forever)."""
    pytest.importorskip("jax")
    from blance_tpu.plan.session import PlannerSession

    async def drive():
        m = model(primary=(0, 1))
        nodes = ["a", "b", "c"]
        parts = [f"p{i}" for i in range(6)]
        cur = _pm({p: {"primary": ["a"]} for p in parts})
        session = PlannerSession(m, nodes, parts)
        session.load_map(cur)
        plan = FaultPlan(seed=5, nodes={"b": NodeFaults(dead=True)})
        ctl = RebalanceController(
            m, nodes, cur, plan.wrap(_noop_assign), session=session,
            debounce_s=0.001,
            orchestrator_options=OrchestratorOptions(
                move_timeout_s=0.25, max_retries=0, quarantine_after=1,
                probe_after_s=600.0))
        ctl.start()
        ctl.submit(ClusterDelta(remove=("a",)))
        final = await asyncio.wait_for(ctl.quiesce(), 30)
        await ctl.stop()
        return ctl, final, session
    ctl, final, session = asyncio.run(drive())
    assert "b" in ctl.quarantined_nodes()
    assert "b" in session.removed_nodes
    for p in final.values():
        assert p.nodes_by_state.get("primary") == ["c"]


def test_session_controller_readds_returned_node():
    """fail then re-add in session mode: the session's removal flag
    must clear so the returned capacity is planned onto again
    (pre-fix: the node stayed dark forever)."""
    pytest.importorskip("jax")
    from blance_tpu.plan.session import PlannerSession

    async def drive():
        m = model(primary=(0, 1))
        nodes = ["a", "b", "c"]
        parts = [f"p{i}" for i in range(6)]
        cur = _pm({p: {"primary": [nodes[i % 3]]}
                   for i, p in enumerate(parts)})
        session = PlannerSession(m, nodes, parts)
        session.load_map(cur)
        ctl = RebalanceController(m, nodes, cur, _noop_assign,
                                  session=session, debounce_s=0.001)
        ctl.start()
        ctl.submit(ClusterDelta(fail=("b",)))
        await asyncio.wait_for(ctl.quiesce(), 30)
        ctl.submit(ClusterDelta(add=("b",)))
        final = await asyncio.wait_for(ctl.quiesce(), 30)
        await ctl.stop()
        return ctl, final, session
    ctl, final, session = asyncio.run(drive())
    assert "b" not in session.removed_nodes
    assert set(ctl.live_nodes()) == {"a", "b", "c"}
    used = {n for p in final.values()
            for n in p.nodes_by_state.get("primary", [])}
    assert "b" in used, used


def test_session_backed_controller_rides_warm_carry():
    """A session-backed controller completes delta cycles and its
    fixpoint plan adopts the proposal (warm carry across cycles)."""
    jax = pytest.importorskip("jax")
    del jax
    from blance_tpu.plan.session import PlannerSession

    async def drive():
        m = model(primary=(0, 1))
        nodes = ["a", "b", "c"]
        parts = [f"p{i}" for i in range(8)]
        cur = _pm({p: {"primary": [nodes[i % 3]]}
                   for i, p in enumerate(parts)})
        session = PlannerSession(m, nodes, parts)
        session.load_map(cur)
        ctl = RebalanceController(m, nodes, cur, _noop_assign,
                                  session=session, debounce_s=0.001)
        ctl.start()
        ctl.submit(ClusterDelta(remove=("a",)))
        final = await asyncio.wait_for(ctl.quiesce(), 30)
        # Weight drift rides the same session.
        ctl.submit(ClusterDelta(partition_weights={parts[0]: 4}))
        final = await asyncio.wait_for(ctl.quiesce(), 30)
        await ctl.stop()
        return ctl, final, session
    ctl, final, session = asyncio.run(drive())
    for p in final.values():
        (n,) = p.nodes_by_state["primary"]
        assert n in ("b", "c")
    # The session adopted the last proposal (current == controller's).
    cur_map, _ = session.to_map("current")
    assert count_moves(model(primary=(0, 1)), cur_map, final) == 0
