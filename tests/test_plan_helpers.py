"""Ports of the reference's planner-helper unit tables
(plan_test.go:21-304): flatten, removal, state-name sorting, weighted
state-node counting, and deep-copy independence."""

from blance_tpu import (
    Partition,
    PartitionModelState,
    copy_partition_map,
    count_state_nodes,
    flatten_nodes_by_state,
    sort_state_names,
)
from blance_tpu.plan.greedy import _remove_nodes_from_nodes_by_state


def test_flatten_nodes_by_state():
    # plan_test.go:21-50 — state-priority iteration order, empties skipped.
    cases = [
        ({}, []),
        ({"primary": []}, []),
        ({"primary": ["a"]}, ["a"]),
        ({"primary": ["a", "b"]}, ["a", "b"]),
        ({"primary": ["a", "b"], "replica": ["c"]}, ["a", "b", "c"]),
        ({"primary": ["a", "b"], "replica": []}, ["a", "b"]),
    ]
    for nbs, exp in cases:
        assert flatten_nodes_by_state(nbs) == exp, nbs


def test_remove_nodes_from_nodes_by_state():
    # plan_test.go:52-117 — order-preserving, per-state, no dedupe.
    cases = [
        ({"primary": ["a", "b"]}, ["a", "b"], {"primary": []}),
        ({"primary": ["a", "b"]}, ["b", "c"], {"primary": ["a"]}),
        ({"primary": ["a", "b"]}, ["a", "c"], {"primary": ["b"]}),
        ({"primary": ["a", "b"]}, [], {"primary": ["a", "b"]}),
        ({"primary": ["a", "b"], "replica": ["c"]}, [],
         {"primary": ["a", "b"], "replica": ["c"]}),
        ({"primary": ["a", "b"], "replica": ["c"]}, ["a"],
         {"primary": ["b"], "replica": ["c"]}),
        ({"primary": ["a", "b"], "replica": ["c"]}, ["a", "c"],
         {"primary": ["b"], "replica": []}),
    ]
    for nbs, remove, exp in cases:
        assert _remove_nodes_from_nodes_by_state(nbs, remove) == exp, \
            (nbs, remove)


def test_sort_state_names():
    # plan_test.go:118-181 — priority ascending, then name; unknown states
    # sort by name at default priority.
    model = {
        "primary": PartitionModelState(priority=0),
        "replica": PartitionModelState(priority=1),
    }
    assert sort_state_names({}) == []
    assert sort_state_names(model) == ["primary", "replica"]
    # Unknown names tie at priority 0 and order alphabetically; the
    # reference's sorter leaves unknown-vs-known ordering to name compare
    # within equal priority.
    mixed = {
        "primary": PartitionModelState(priority=0),
        "a": PartitionModelState(priority=0),
    }
    assert sort_state_names(mixed) == ["a", "primary"]


def test_count_state_nodes():
    # plan_test.go:182-241 — per-state weighted node histogram.
    pm = {
        "0": Partition("0", {"primary": ["a"], "replica": ["b", "c"]}),
        "1": Partition("1", {"primary": ["b"], "replica": ["c"]}),
    }
    assert count_state_nodes(pm, None) == {
        "primary": {"a": 1, "b": 1},
        "replica": {"b": 1, "c": 2},
    }
    pm2 = {
        "0": Partition("0", {"replica": ["b", "c"]}),
        "1": Partition("1", {"primary": ["b"], "replica": ["c"]}),
    }
    assert count_state_nodes(pm2, None) == {
        "primary": {"b": 1},
        "replica": {"b": 1, "c": 2},
    }
    # Partition weights scale the counts (plan.go:374-399).
    assert count_state_nodes(pm2, {"0": 3}) == {
        "primary": {"b": 1},
        "replica": {"b": 3, "c": 4},
    }


def test_copy_partition_map_is_deep():
    # plan_test.go:242-304 — mutations of the copy never leak back.
    src = {"0": Partition("0", {"primary": ["a"], "replica": ["b"]})}
    cp = copy_partition_map(src)
    cp["0"].nodes_by_state["primary"].append("z")
    cp["0"].nodes_by_state["extra"] = ["y"]
    assert src["0"].nodes_by_state == {"primary": ["a"], "replica": ["b"]}
