"""Batched on-device move diff must agree exactly with the host
calc_partition_moves on randomized maps, in both orderings."""

import random

from blance_tpu import Partition, calc_partition_moves, model
from blance_tpu.moves.batch import calc_all_moves
from blance_tpu.plan.greedy import sort_state_names

M = model(primary=(0, 1), replica=(1, 2))


def random_maps(seed, n_partitions=40, n_nodes=8):
    rng = random.Random(seed)
    nodes = [f"n{i}" for i in range(n_nodes)]

    def random_nbs():
        pool = rng.sample(nodes, rng.randint(0, 5))
        n_primary = rng.randint(0, min(1, len(pool)))
        return {
            "primary": pool[:n_primary],
            "replica": pool[n_primary:],
        }

    beg = {str(i): Partition(str(i), random_nbs()) for i in range(n_partitions)}
    end = {str(i): Partition(str(i), random_nbs()) for i in range(n_partitions)}
    return beg, end


def test_batch_diff_matches_host_diff():
    states = sort_state_names(M)
    for seed in range(6):
        beg, end = random_maps(seed)
        for favor_min in (False, True):
            batched = calc_all_moves(beg, end, M, favor_min)
            for name in beg:
                host = calc_partition_moves(
                    states,
                    beg[name].nodes_by_state,
                    end[name].nodes_by_state,
                    favor_min,
                )
                assert batched[name] == host, (
                    f"seed {seed} favor_min {favor_min} partition {name}:\n"
                    f"beg {beg[name].nodes_by_state}\n"
                    f"end {end[name].nodes_by_state}\n"
                    f"batched {batched[name]}\nhost {host}")


def test_batch_diff_multi_state_nodes_fall_back_to_host():
    states = sort_state_names(M)
    cases = [
        # Node gains a second state: host emits one add (availability) /
        # keeps per-scan-order semantics (min-nodes).
        ({}, {"primary": ["a"], "replica": ["a"]}),
        # Node keeps primary while also appearing as replica: host emits a
        # demote even though primary persists.
        ({"primary": ["a"]}, {"primary": ["a"], "replica": ["a"]}),
        # Duplicate within beg.
        ({"primary": ["a"], "replica": ["a"]}, {"replica": ["a"]}),
    ]
    for beg_nbs, end_nbs in cases:
        beg = {"x": Partition("x", dict(beg_nbs))}
        end = {"x": Partition("x", dict(end_nbs))}
        for favor_min in (False, True):
            host = calc_partition_moves(states, beg_nbs, end_nbs, favor_min)
            batched = calc_all_moves(beg, end, M, favor_min)
            assert batched["x"] == host, (beg_nbs, end_nbs, favor_min)


def test_batch_diff_empty_and_noop():
    beg = {"x": Partition("x", {"primary": ["a"]})}
    end = {"x": Partition("x", {"primary": ["a"]})}
    assert calc_all_moves(beg, end, M) == {"x": []}
    assert calc_all_moves({}, {}, M) == {}


def test_batch_diff_rejects_mismatched_keys():
    # Host path raises KeyError on a partition missing from end_map; the
    # batched mode must not silently emit del-everything instead.
    import pytest

    beg = {"x": Partition("x", {"primary": ["a"]}),
           "y": Partition("y", {"primary": ["b"]})}
    end = {"x": Partition("x", {"primary": ["a"]})}
    with pytest.raises(KeyError):
        calc_all_moves(beg, end, M)


def test_batch_diff_iterates_in_planner_order():
    # Numeric names replay in planner (zero-padded) order: 2 before 10.
    beg = {n: Partition(n, {"primary": ["a"]}) for n in ("10", "2")}
    end = {n: Partition(n, {"primary": ["b"]}) for n in ("10", "2")}
    assert list(calc_all_moves(beg, end, M)) == ["2", "10"]
