"""Fused in-kernel score engine (ops/score_fused.py) tests.

The fused path must produce the same AUCTION DECISIONS as the matrix
path.  Bit-identical scores are not the contract (the two paths add the
same terms in a slightly different order, which is allowed — each path
is deterministic on its own); the kernel IS bit-checked against a
reference that mirrors its own term order, and the full solve is held
to the same contract as the matrix engine: zero violations, rack-rule
conformance, fixpoint, and matching balance.
"""

import numpy as np

import jax
import jax.numpy as jnp
import pytest

from blance_tpu import HierarchyRule, Partition, PlanOptions, model
from blance_tpu.core.encode import encode_problem
from blance_tpu.ops.score_fused import (
    ScoreInputs,
    fused_score_min2,
    score_at_columns,
)
from blance_tpu.plan.tensor import check_assignment, solve_dense_converged

CLEAN = {"duplicates": 0, "on_removed_nodes": 0,
         "unfilled_feasible_slots": 0, "hierarchy_misses": 0}
_INF = 1.0e9
_RULE_MISS = 1.0e6
_RULE_TIER = 1.0e4


def empty_parts(n):
    return {str(i): Partition(str(i), {}) for i in range(n)}


def _random_inputs(seed, P=37, N=23, R=2, T=3, A=2, nrules=2):
    """Random raw solver terms packed through the REAL pack_score_inputs
    (so the packer's anchor-gid encoding and column layout are covered
    by the bit-exact kernel test, not re-implemented here)."""
    from blance_tpu.ops.score_fused import pack_score_inputs

    rng = np.random.default_rng(seed)
    racks = 5
    rack_of = rng.integers(0, racks, N).astype(np.int32)
    zone_of_rack = rng.integers(0, 2, racks).astype(np.int32)
    gids = np.stack([np.arange(N, dtype=np.int32), rack_of,
                     zone_of_rack[rack_of]])
    gid_valid = rng.random((3, N)) < 0.9
    valid = rng.random(N) < 0.85
    anchors = rng.integers(-1, N, (P, A)).astype(np.int32)
    rules = ((2, 1), (1, 0))[:nrules]

    total = rng.random(N).astype(np.float32) * 40.0
    w_div = rng.integers(1, 4, N).astype(np.float32)
    neg_boost = np.where(rng.random(N) < 0.3,
                         rng.integers(1, 4, N), 0).astype(np.float32)
    stick = np.full(P, 1.5, np.float32)
    prev_slot = rng.integers(-1, N, P).astype(np.int32)
    prev_state = rng.integers(-1, N, (P, R)).astype(np.int32)
    taken = rng.integers(-1, N, (P, T)).astype(np.int32)

    si = pack_score_inputs(
        total_l=jnp.asarray(total), total_p=jnp.float32(P),
        w_div_l=jnp.asarray(w_div), neg_boost_l=jnp.asarray(neg_boost),
        valid_l=jnp.asarray(valid),
        stickiness_si=jnp.asarray(stick),
        prev_slot=jnp.asarray(prev_slot),
        prev_state=jnp.asarray(prev_state),
        taken_ids=[jnp.asarray(taken[:, t]) for t in range(T)],
        anchors=jnp.asarray(anchors),
        gids_l=jnp.asarray(gids), gid_valid=jnp.asarray(gid_valid),
        gids=jnp.asarray(gids), rules=rules)
    price = (rng.random(N).astype(np.float32)
             + np.where(rng.random(N) < 0.2, _INF, 0)).astype(np.float32)
    aux = dict(gids=gids, gid_valid=gid_valid, valid=valid,
               anchors=anchors, rules=rules, P=P, N=N)
    return si, price, aux


def _reference_score(si: ScoreInputs, aux, pbase=0, noff=0):
    """The kernel's formula in ITS term order, dense jnp (pure f32, the
    same precision path as the interpreted kernel) — the oracle the
    kernel must match bit-for-bit."""
    P = si.stick.shape[0]
    N = si.base.shape[0]
    cols = jnp.arange(N, dtype=jnp.int32)[None, :] + noff
    base = si.base[None, :]
    nb = si.neg_boost[None, :]
    stick = si.stick[:, None]
    score = base + jnp.where(nb > 0, jnp.maximum(nb, stick), 0.0)
    score = score - 0.01 * (si.prev_slot[:, None] == cols
                            ).astype(jnp.float32)
    sticky = jnp.zeros((P, N), jnp.bool_)
    for r in range(si.prev_state.shape[1]):
        sticky |= si.prev_state[:, r:r + 1] == cols
    score = score - stick * sticky.astype(jnp.float32)
    rules = aux["rules"]
    if rules:
        nrules = len(rules)
        pen = jnp.full((P, N), _RULE_MISS, jnp.float32)
        for idx in range(nrules):
            sat = jnp.ones((P, N), jnp.bool_)
            for ai in range(si.present.shape[1]):
                col = ai * nrules + idx
                inc_same = si.a_inc_g[:, col:col + 1] == \
                    si.cand_g[idx][None, :]
                exc_same = si.a_exc_g[:, col:col + 1] == \
                    si.cand_g[nrules + idx][None, :]
                sat &= jnp.where(si.present[:, ai:ai + 1] > 0,
                                 inc_same & ~exc_same, True)
            pen = jnp.where(sat, jnp.minimum(pen, idx * _RULE_TIER), pen)
        score = score + jnp.where(si.any_anchor[:, None] > 0, pen, 0.0)
    tk = jnp.zeros((P, N), jnp.bool_)
    for t in range(si.taken.shape[1]):
        tk |= si.taken[:, t:t + 1] == cols
    score = score + _INF * (tk | (si.validf[None, :] == 0.0)
                            ).astype(jnp.float32)
    from blance_tpu.ops.score_fused import jitter_hash

    pi = (pbase + jnp.arange(P, dtype=jnp.int32))[:, None]
    jit = jitter_hash(pi, cols.astype(jnp.int32))
    return np.asarray(score + jnp.float32(1.0e-5) * jit)


@pytest.mark.parametrize("seed", range(4))
@pytest.mark.parametrize("nrules", [0, 1, 2])
def test_fused_kernel_matches_reference(seed, nrules):
    """Interpret-mode kernel == dense reference in the kernel's own term
    order: best/choice/second/raw, including tie-breaks and ragged
    tiles."""
    si, price, aux = _random_inputs(seed, nrules=nrules)
    best, choice, second, raw = (np.asarray(x) for x in fused_score_min2(
        jnp.asarray(price), si, 0, 0, nrules=nrules,
        jitter_scale=1.0e-5, tile_p=16, tile_n=8, interpret=True))
    ref = _reference_score(si, aux)
    eff = ref + price[None, :]
    P, N = ref.shape
    exp_best = eff.min(axis=1)
    exp_choice = eff.argmin(axis=1)
    masked = eff.copy()
    masked[np.arange(P), exp_choice] = np.inf
    exp_second = masked.min(axis=1)
    assert np.array_equal(best, exp_best.astype(np.float32))
    assert np.array_equal(choice, exp_choice.astype(np.int32))
    assert np.array_equal(second, exp_second.astype(np.float32))
    # raw = best - price[choice], computed in-kernel the same way.
    assert np.allclose(raw, best - price[exp_choice], atol=1e-3)

    # score_at_columns agrees with the reference at probe points (same
    # term order as the kernel; threshold-level agreement suffices).
    rng = np.random.default_rng(seed + 100)
    rows = rng.integers(0, P, 16).astype(np.int32)
    cols = rng.integers(0, N, 16).astype(np.int32)
    vals = np.asarray(score_at_columns(
        jnp.asarray(rows), jnp.asarray(cols),
        base_full=si.base, neg_boost_full=si.neg_boost,
        valid_full=jnp.asarray(aux["valid"]),
        gids=jnp.asarray(aux["gids"]),
        gid_valid=jnp.asarray(aux["gid_valid"]),
        anchors=jnp.asarray(aux["anchors"]),
        rules=aux["rules"][:nrules] if nrules else (),
        prev_slot=si.prev_slot, prev_state=si.prev_state,
        taken_ids=tuple(si.taken[:, t] for t in range(si.taken.shape[1])),
        stick=si.stick, jitter_scale=1.0e-5, pbase=jnp.zeros((1, 1),
                                                            jnp.int32)))
    ref_vals = ref[rows, cols]
    assert np.allclose(vals, ref_vals, atol=1e-3), (vals, ref_vals)


def _rack_problem(P=64, N=8):
    nodes = [f"n{i}" for i in range(N)]
    hier = {n: f"r{i // 2}" for i, n in enumerate(nodes)}
    hier.update({f"r{i}": "z0" for i in range(N // 2)})
    opts = PlanOptions(node_hierarchy=hier,
                       hierarchy_rules={"replica": [HierarchyRule(2, 1)]})
    m = model(primary=(0, 1), replica=(1, 2))
    problem = encode_problem({}, empty_parts(P), nodes, [], m, opts)
    return problem


def _solve(problem, fused):
    rules = tuple(tuple(problem.rules.get(i, ()))
                  for i in range(problem.S))
    return np.asarray(solve_dense_converged(
        jnp.asarray(problem.prev),
        jnp.asarray(problem.partition_weights),
        jnp.asarray(problem.node_weights),
        jnp.asarray(problem.valid_node),
        jnp.asarray(problem.stickiness),
        jnp.asarray(problem.gids),
        jnp.asarray(problem.gid_valid),
        tuple(int(c) for c in problem.constraints),
        rules,
        fused_score="interpret" if fused else "off"))


def test_fused_solve_matches_contract():
    """Full solve through the fused engine (interpret mode): same
    contract as the matrix engine — zero violations, rack conformance,
    identical per-state balance, own fixpoint."""
    problem = _rack_problem()
    a_fused = _solve(problem, fused=True)
    a_matrix = _solve(problem, fused=False)
    for a in (a_fused, a_matrix):
        assert check_assignment(problem, a) == CLEAN
        rack = problem.gids[1]
        pr = rack[a[:, 0, 0]]
        r0, r1 = rack[a[:, 1, 0]], rack[a[:, 1, 1]]
        bad = (pr == r0) | (pr == r1) | (r0 == r1)
        assert not bad.any()
    for si in range(2):
        for a in (a_fused, a_matrix):
            ids = a[:, si, :].ravel()
            loads = np.bincount(ids[ids >= 0], minlength=8)
            assert loads.max() - loads.min() <= 3, (si, loads)

    # Fused fixpoint: replanning the fused output through the fused
    # engine is a no-op.
    problem2 = _rack_problem()
    problem2.prev[...] = a_fused
    assert np.array_equal(_solve(problem2, fused=True), a_fused)


def test_fused_solve_node_removal():
    """Fused engine replan after removal: displaced copies move off the
    dead node, zero violations."""
    problem = _rack_problem()
    a1 = _solve(problem, fused=True)
    nodes = problem.nodes
    p2 = encode_problem({}, empty_parts(64), nodes, [],
                        model(primary=(0, 1), replica=(1, 2)),
                        PlanOptions(
                            node_hierarchy={
                                **{n: f"r{i // 2}" for i, n in
                                   enumerate(nodes)},
                                **{f"r{i}": "z0" for i in range(4)}},
                            hierarchy_rules={
                                "replica": [HierarchyRule(2, 1)]}))
    p2.prev[...] = a1
    p2.valid_node[0] = False
    a2 = _solve(p2, fused=True)
    assert not (a2 == 0).any()  # node 0 never used
    assert check_assignment(p2, a2) == CLEAN


def test_fused_default_plumbed_through_api():
    """set_fused_score_default routes plan_next_map_tpu through the
    fused engine; the public result honors the same contract."""
    import warnings as w

    from blance_tpu import plan_next_map
    from blance_tpu.plan import tensor as T

    T.set_fused_score_default("interpret")
    try:
        nodes = [f"n{i}" for i in range(8)]
        hier = {n: f"r{i // 2}" for i, n in enumerate(nodes)}
        hier.update({f"r{i}": "z0" for i in range(4)})
        opts = PlanOptions(
            node_hierarchy=hier,
            hierarchy_rules={"replica": [HierarchyRule(2, 1)]})
        m = model(primary=(0, 1), replica=(1, 2))
        with w.catch_warnings():
            w.simplefilter("error")  # the validation gate must stay quiet
            result, warns = plan_next_map(
                empty_parts(48), empty_parts(48), nodes, [], nodes, m,
                opts, backend="tpu")
        assert not warns
        rackof = {n: i // 2 for i, n in enumerate(nodes)}
        for p in result.values():
            pr = rackof[p.nodes_by_state["primary"][0]]
            rs = [rackof[x] for x in p.nodes_by_state["replica"]]
            assert pr not in rs and len(set(rs)) == 2
    finally:
        T.set_fused_score_default("auto")


def test_resolve_fused_score_passthrough_and_auto(monkeypatch):
    """"auto" picks the engine from the matrix working-set estimate;
    explicit modes pass through; "auto" never reaches the jitted solver
    (solve_dense rejects it)."""
    from blance_tpu.plan import tensor as T

    for mode in ("off", "on", "interpret"):
        assert T.resolve_fused_score(mode, 100_000, 10_000) == mode

    # Auto without the compiled Pallas path (this CPU host): matrix
    # engine regardless of size.
    monkeypatch.setattr("blance_tpu.ops.reduce2.pallas_available",
                        lambda: False)
    assert T.resolve_fused_score("auto", 100_000, 10_000) == "off"

    # Auto with Pallas and a 16 GiB chip: small problems stay on the
    # matrix engine, the north-star shape must switch to fused.
    monkeypatch.setattr("blance_tpu.ops.reduce2.pallas_available",
                        lambda: True)
    monkeypatch.setattr(T, "_device_hbm_bytes", lambda: 16 * 2 ** 30)
    assert T.resolve_fused_score("auto", 100_000, 1_000) == "off"
    assert T.resolve_fused_score("auto", 100_000, 10_000) == "on"

    with pytest.raises(ValueError, match="unresolved fused-score"):
        T.solve_dense(
            jnp.full((4, 1, 1), -1, jnp.int32), jnp.ones(4), jnp.ones(3),
            jnp.ones(3, bool), jnp.full((4, 1), 1.5),
            jnp.zeros((1, 3), jnp.int32), jnp.ones((1, 3), bool),
            (1,), ((),), fused_score="auto")


@pytest.mark.parametrize("seed", range(6))
def test_fused_matrix_equivalence_fuzz(seed):
    """Randomized option-space sweep: warm prev maps, heterogeneous
    partition/node weights, NEGATIVE node weights (pin/boost), varied
    stickiness, node removals, 0-2 hierarchy rules.  The two engines
    need not be bit-equal (term order differs) but each must pass the
    production gate clean, respect every rule, and land within a small
    balance envelope of the other — the subtlest terms (boost, tiered
    rule penalty, exclusivity) are exactly where a drift would show."""
    rng = np.random.default_rng(seed)
    P = int(rng.integers(24, 72))
    N = int(rng.choice([8, 12, 16]))
    nodes = [f"n{i}" for i in range(N)]
    racks = max(2, N // int(rng.choice([2, 3, 4])))
    hier = {n: f"r{i % racks}" for i, n in enumerate(nodes)}
    hier.update({f"r{i}": "z0" for i in range(racks)})
    nrules = int(rng.integers(0, 3))
    # Tiered rule list: rule 0 (different rack) is preferred, rule 1
    # (same rack, different node) is the fallback tier — nrules=2
    # genuinely exercises the multi-rule penalty tiers.
    rules = {"replica": [HierarchyRule(2, 1), HierarchyRule(1, 0)][:nrules]}
    n_replicas = int(rng.choice([1, 2]))
    opts = PlanOptions(
        node_hierarchy=hier,
        hierarchy_rules=rules if nrules else None,
        partition_weights={str(i): int(rng.integers(1, 4))
                           for i in range(0, P, 3)},
        node_weights={nodes[0]: float(rng.choice([-2.0, 2.0]))},
        state_stickiness={"primary": int(rng.choice([1, 2, 3]))},
        state_stickiness_standalone=True,
    )
    m = model(primary=(0, 1), replica=(1, n_replicas))
    problem = encode_problem({}, empty_parts(P), nodes, [], m, opts)
    # Warm half the partitions onto random nodes; remove one node (the
    # gate's on_removed_nodes counter asserts nothing lands there).
    problem.prev[: P // 2, 0, 0] = rng.integers(0, N, P // 2)
    problem.valid_node[N - 1] = False

    a_f = _solve(problem, fused=True)
    a_m = _solve(problem, fused=False)
    for tag, a in (("fused", a_f), ("matrix", a_m)):
        gate = check_assignment(problem, a)
        assert not any(gate.values()), (tag, gate)
        if nrules:
            rack = problem.gids[1]  # rule-less encodes build level 0 only
            pr, rp = a[:, 0, 0], a[:, 1, 0]
            both = (pr >= 0) & (rp >= 0)
            # Tier 0 (different rack) is always attainable here (>= 2
            # racks stay valid), so slot 0 must conform to it.
            assert not (rack[pr] == rack[rp])[both].any(), tag

    def spread(a):
        ids = a[a >= 0]
        loads = np.bincount(ids, minlength=N)[problem.valid_node]
        return int(loads.max() - loads.min())

    assert abs(spread(a_f) - spread(a_m)) <= 2, (spread(a_f), spread(a_m))


def test_jitter_hash_matches_unsigned_weyl_oracle():
    """The int32 spelling (required: Mosaic cannot lower uint32->f32)
    must equal the mathematical unsigned Weyl sequence bit-for-bit —
    two's-complement wraparound makes the masked low 16 bits identical.
    Guards against 'simplifying' the negative multiplier back to its
    unsigned form (which changes nothing numerically but regresses TPU
    compilation) or touching the mask/divisor."""
    from blance_tpu.ops.score_fused import jitter_hash

    rng = np.random.default_rng(0)
    pi = rng.integers(0, 2**31 - 1, 4096).astype(np.int32)
    ni = rng.integers(0, 2**20, 4096).astype(np.int32)
    got = np.asarray(jitter_hash(jnp.asarray(pi), jnp.asarray(ni)))
    with np.errstate(over="ignore"):
        want = ((pi.astype(np.uint32) * np.uint32(2654435761)
                 + ni.astype(np.uint32) * np.uint32(40503))
                & np.uint32(0xFFFF)).astype(np.float32) / 65536.0
    assert np.array_equal(got, want)
    assert got.min() >= 0.0 and got.max() < 1.0
