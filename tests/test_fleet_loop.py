"""Fleet-of-loops tier (ISSUE 13, docs/FLEET.md "Fleet of control
loops"): N per-tenant RebalanceController cycle engines multiplexed
over one shared PlanService + CarryCache, driven deterministically by
testing/fleetsim.py.

Covers: bit-identical replay (incl. the committed trace), the
coalesced-vs-sequential contract (identical final maps, equal churn,
measurably fewer device dispatches), the tenant-scale matrix, staggered
onboarding, noisy-neighbor fairness (service-level starvation +
quota-bounded batches), the ServicePlanner warm protocol (weight
change / returned capacity / mid-cycle invalidation each only ever
costs a cold solve — never a stale map), CarryCache eviction
observability, and the fleet SLO rollup gauges.
"""

import asyncio
import dataclasses

import numpy as np
import pytest

from blance_tpu.core.types import Partition, model
from blance_tpu.fleetloop import FleetController, ServicePlanner
from blance_tpu.obs import Recorder, use_recorder
from blance_tpu.obs.expo import default_registry
from blance_tpu.obs.slo import FleetSloRollup, SloTracker
from blance_tpu.plan.carry import CarryCache
from blance_tpu.plan.fleet import TenantProblem, solve_fleet
from blance_tpu.plan.service import PlanService
from blance_tpu.rebalance import ClusterDelta, RebalanceController
from blance_tpu.testing.fleetsim import run_fleet_scenario
from blance_tpu.testing.scenarios import (
    fleet_noisy_neighbor,
    fleet_onboarding,
    fleet_week,
    fleet_zone_outage,
)
from blance_tpu.testing.sched import DeterministicLoop, FifoPolicy

M = model(primary=(0, 1), replica=(1, 1))

FLEET_TRACE_PATH = "tests/traces/fleet_zone_outage_s5_t8.json"


def _nbs(pmap):
    return {name: {s: list(ns) for s, ns in p.nodes_by_state.items()}
            for name, p in pmap.items()}


def _maps_equal(a, b):
    return {k: _nbs(m) for k, m in a.items()} == \
        {k: _nbs(m) for k, m in b.items()}


# -- determinism & replay -----------------------------------------------------


def test_fleet_scenario_bit_identical_across_runs():
    """Same fleet scenario => byte-identical event log, equal per-tenant
    SLO summaries, byte-identical exposition — the determinism contract
    the whole multi-tenant tier stands on."""
    scn = fleet_zone_outage(seed=5, tenants=8)
    a = run_fleet_scenario(scn)
    b = run_fleet_scenario(scn)
    assert a.log_text() == b.log_text()
    assert a.exposition == b.exposition
    assert a.summaries == b.summaries
    assert a.fleet == b.fleet
    # A different seed is a genuinely different trace.
    c = run_fleet_scenario(fleet_zone_outage(seed=6, tenants=8))
    assert c.log_text() != a.log_text()


def test_committed_fleet_trace_replays_exactly():
    """The committed fleet event log regenerates byte-for-byte — any
    drift in planner, service coalescing, controller or SLO arithmetic
    shows up as a diff here and must be understood (then the trace
    regenerated)."""
    with open(FLEET_TRACE_PATH) as f:
        committed = f.read()
    live = run_fleet_scenario(fleet_zone_outage(seed=5, tenants=8))
    assert live.log_text() == committed, (
        "fleet-simulator behavior drifted from the committed trace "
        f"({FLEET_TRACE_PATH}); if the change is intended, regenerate: "
        "python -c \"from blance_tpu.testing.scenarios import "
        "fleet_zone_outage; from blance_tpu.testing.fleetsim import "
        "run_fleet_scenario; open('" + FLEET_TRACE_PATH + "', 'w')"
        ".write(run_fleet_scenario(fleet_zone_outage(seed=5, tenants=8))"
        ".log_text())\"")


@pytest.mark.parametrize("seed,tenants", [(5, 4), (5, 12), (7, 8)])
def test_tenant_scale_matrix(seed, tenants):
    """Fixed seeds x tenant-scale points: complete final maps on live
    nodes, full availability restored, and coalescing actually engaged
    (dispatches < plan requests)."""
    r = run_fleet_scenario(fleet_zone_outage(seed=seed, tenants=tenants))
    assert r.complete
    assert r.fleet.tenants == tenants
    assert r.fleet.availability_min == 1.0
    assert r.unconverged == 0
    assert r.plan_requests > 0
    if tenants > 1:
        assert r.dispatches < r.plan_requests


# -- the coalescing contract --------------------------------------------------


def test_coalesced_equals_sequential_at_fewer_dispatches():
    """The acceptance gate's core: the coalesced fleet loop and the
    sequential loop-per-tenant baseline (same code, zero window,
    max_batch=1) converge to IDENTICAL final maps with EQUAL executed
    moves and equal availability — and the coalesced run pays
    measurably fewer device dispatches."""
    scn = fleet_zone_outage(seed=5, tenants=8)
    co = run_fleet_scenario(scn, coalesce=True)
    seq = run_fleet_scenario(scn, coalesce=False)
    assert _maps_equal(co.final_maps, seq.final_maps)
    assert co.fleet.moves_executed == seq.fleet.moves_executed
    assert co.fleet.availability_min == seq.fleet.availability_min
    assert {k: s.availability for k, s in co.summaries.items()} == \
        {k: s.availability for k, s in seq.summaries.items()}
    # Sequential mode = one dispatch per plan request; coalescing must
    # beat it by a real margin, not by one.
    assert seq.dispatches == seq.plan_requests
    assert co.dispatches < seq.dispatches
    # Warm carries engaged on the shared cache in both modes.
    assert co.carry_hits > 0
    assert seq.carry_hits > 0


# -- scenario families --------------------------------------------------------


def test_onboarding_family_converges_from_empty():
    scn = fleet_onboarding(seed=13, tenants=12)
    r = run_fleet_scenario(scn)
    assert r.complete
    assert r.fleet.availability_min == 1.0
    onboarded = [t.key for t in scn.tenants if t.onboard_t > 0]
    assert onboarded, "family drifted: no staggered tenants"
    kinds = [e for e in r.events if e["kind"] == "onboard"]
    assert sorted(e["tenant"] for e in kinds) == sorted(onboarded)
    # An onboarding tenant starts empty, so placing everything is real
    # executed work.
    for key in onboarded:
        assert r.summaries[key].moves_executed >= \
            dict((t.key, t.partitions) for t in scn.tenants)[key]


def test_noisy_neighbor_family_keeps_neighbors_serving():
    scn = fleet_noisy_neighbor(seed=29, tenants=10)
    assert scn.fair_share is not None  # the fairness config is the point
    r = run_fleet_scenario(scn)
    assert r.complete
    noisy = scn.tenants[0].key
    # The chatty tenant consumes many converge cycles...
    waves = sum(1 for e in r.events
                if e["kind"] == "delta" and e["tenants"] == [noisy])
    assert waves >= 15
    # ...while every neighbor still ends fully available and under its
    # violation budget (the scripted node outage is the only dip).
    for key, s in r.summaries.items():
        assert s.availability == 1.0, key


# -- admission fairness (plan/service.py fair_share) --------------------------


def _tiny_tenant(key, seed, n=3):
    p, s, r = 2, 1, 1
    prev = np.full((p, s, r), -1, np.int32)
    prev[0, 0, 0] = seed % n
    prev[1, 0, 0] = (seed + 1) % n
    return TenantProblem(
        key=key, prev=prev,
        partition_weights=np.ones(p, np.float32),
        node_weights=np.ones(n, np.float32),
        valid_node=np.ones(n, bool),
        stickiness=np.full((p, s), 1.5, np.float32),
        gids=np.arange(n, dtype=np.int32).reshape(1, n),
        gid_valid=np.ones((1, n), bool),
        constraints=(1,), rules=((),))


def test_service_fair_share_defers_chatty_tenant():
    """A chatty tenant's concurrent requests beyond fair_share roll to
    later batches (counted as fleet.starved_admissions) and still
    resolve bit-exactly; no batch ever holds more than fair_share
    requests of one key; neighbors are unaffected."""
    batches = []

    class Capturing(PlanService):
        def _solve_batch(self, problems, trace_ids):
            batches.append([t.key for t in problems])
            return super()._solve_batch(problems, trace_ids)

    loop = DeterministicLoop(FifoPolicy(), max_steps=500_000)
    rec = Recorder(clock=loop.time)
    expected = {key: solve_fleet([_tiny_tenant(key, s)],
                                 record=False, batch_floor=16)[0].assign
                for key, s in (("chatty", 0), ("b", 1), ("c", 2))}

    async def drive():
        svc = Capturing(admission_window_s=0.05, fair_share=1,
                        inline_solve=True, max_pending=16,
                        recorder=rec, batch_floor=16)
        await svc.start()
        tags = [("chatty", 0)] * 4 + [("b", 1), ("c", 2)]
        results = await asyncio.gather(
            *[svc.submit(_tiny_tenant(key, s)) for key, s in tags])
        await svc.stop()
        return tags, results

    with use_recorder(rec):
        tags, results = loop.run_until_complete(drive())
    for (key, _s), res in zip(tags, results):
        assert res.key == key
        assert np.array_equal(res.assign, expected[key])
    starved = rec.counters.get("fleet.starved_admissions", 0)
    assert starved >= 3  # 4 chatty requests, quota 1 -> >= 3 deferrals
    for keys in batches:
        for key in set(keys):
            assert keys.count(key) <= 1, (key, keys)


def test_service_fair_share_validation():
    with pytest.raises(ValueError):
        PlanService(fair_share=0)


# -- the ServicePlanner warm protocol -----------------------------------------


def _cluster(nodes=12, parts=12):
    # 12 nodes / 12 partitions: the same bucket class as the smoke
    # scenario families, so the whole module shares compiled programs.
    names = [f"n{i}" for i in range(nodes)]
    pmap = {}
    for i in range(parts):
        p = f"p{i:03d}"
        pmap[p] = Partition(p, {"primary": [names[i % nodes]],
                                "replica": [names[(i + 1) % nodes]]})
    return names, pmap


def test_service_planner_warm_protocol_and_invalidation():
    """The planner's dirty protocol, driven cycle by cycle: a repeat
    plan on unchanged state rides the warm path bit-identically to its
    cold twin; a weight change, returned capacity, or a MID-CYCLE cache
    invalidation/eviction each demote to a cold solve whose map is
    bit-identical to the never-cached reference — an eviction can cost
    a cold solve, never a stale or wrong map."""
    from blance_tpu.core.types import PlanOptions

    nodes, pmap = _cluster()
    loop = DeterministicLoop(FifoPolicy(), max_steps=500_000)
    rec = Recorder(clock=loop.time)

    async def drive():
        # batch_floor=16 everywhere in this module: reuse the fleet
        # controller's compiled B-bucket instead of building B=1 twins.
        svc = PlanService(admission_window_s=0.0, inline_solve=True,
                          recorder=rec, batch_floor=16)
        await svc.start()
        planner = ServicePlanner("t0", svc)

        async def reference(current, removes, opts):
            # A fresh planner + fresh service: the never-cached cold
            # twin of the same cycle.
            svc2 = PlanService(admission_window_s=0.0,
                               inline_solve=True, recorder=rec,
                               batch_floor=16)
            await svc2.start()
            ref, _w = await ServicePlanner("t0", svc2).plan_cycle(
                current, nodes, removes, M, opts)
            await svc2.stop()
            return ref

        opts = PlanOptions()
        hits = lambda: rec.counters.get("plan.solve.carry_hit", 0)
        misses = lambda: rec.counters.get("plan.solve.carry_miss", 0)

        # Cycle 1: always cold.
        m1, _w = await planner.plan_cycle(pmap, nodes, [], M, opts)
        assert misses() >= 1 and hits() == 0

        # Cycle 2: a node fails -> warm-eligible (dark grew), and the
        # result is bit-identical to the cold reference.
        h0 = hits()
        m2, _w = await planner.plan_cycle(m1, nodes, ["n0"], M, opts)
        assert _nbs(m2) == _nbs(await reference(m1, ["n0"], opts))
        assert all("n0" not in ns for p in m2.values()
                   for ns in p.nodes_by_state.values())

        # Cycle 3: MID-CYCLE invalidation (the eviction stand-in) —
        # cold solve, same map as the never-cached reference.
        svc.carry_cache.invalidate("t0")
        mi0 = misses()
        m3, _w = await planner.plan_cycle(m2, nodes, ["n0"], M, opts)
        assert misses() > mi0
        assert _nbs(m3) == _nbs(await reference(m2, ["n0"], opts))

        # Cycle 4: weights changed -> the planner itself demotes to
        # cold (dirty=None), again bit-identical to the reference.
        hot = dataclasses.replace(opts, partition_weights={"p000": 8})
        h1, mi1 = hits(), misses()
        m4, _w = await planner.plan_cycle(m3, nodes, ["n0"], M, hot)
        assert misses() > mi1 and hits() == h1
        assert _nbs(m4) == _nbs(await reference(m3, ["n0"], hot))

        # Cycle 5: capacity returned (dark shrank) -> cold again.
        mi2 = misses()
        m5, _w = await planner.plan_cycle(m4, nodes, [], M, hot)
        assert misses() > mi2
        assert _nbs(m5) == _nbs(await reference(m4, [], hot))
        assert h0 <= hits()  # warm path engaged at least once overall
        await svc.stop()

    with use_recorder(rec):
        loop.run_until_complete(drive())


def test_shared_cache_eviction_under_fleet_only_costs_cold():
    """Satellite: a shared CarryCache under many concurrent controller
    loops with a ZERO byte budget (every store evicted immediately) —
    every solve goes cold, evictions are counted and labeled, and the
    fleet converges to exactly the maps of the identical run, because
    cold is always the single-problem solve on current inputs."""
    scn = dataclasses.replace(fleet_zone_outage(seed=5, tenants=6),
                              carry_bytes=0)
    a = run_fleet_scenario(scn)
    b = run_fleet_scenario(scn, coalesce=False)
    assert a.complete and b.complete
    assert a.carry_hits == 0 and b.carry_hits == 0
    assert a.carry_evictions.get("bytes", 0) > 0
    # All-cold decisions are mode-independent: byte-identical maps and
    # equal churn even under continuous eviction.
    assert _maps_equal(a.final_maps, b.final_maps)
    assert a.fleet.moves_executed == b.fleet.moves_executed


def test_planner_rejects_scoring_hooks():
    from blance_tpu.core.types import PlanOptions

    nodes, pmap = _cluster()
    loop = DeterministicLoop(FifoPolicy(), max_steps=100_000)
    rec = Recorder(clock=loop.time)

    async def drive():
        svc = PlanService(inline_solve=True, recorder=rec)
        await svc.start()
        planner = ServicePlanner("t0", svc)
        with pytest.raises(ValueError, match="node_score_booster"):
            await planner.plan_cycle(
                pmap, nodes, [], M,
                PlanOptions(node_score_booster=lambda *a: 0.0))
        await svc.stop()

    with use_recorder(rec):
        loop.run_until_complete(drive())


def test_add_tenant_rejects_scoring_hooks_at_registration():
    """A misconfigured tenant must fail at add_tenant (where the caller
    can handle it), not silently kill its engine task mid-run."""
    from blance_tpu.core.types import PlanOptions

    nodes, pmap = _cluster()
    loop = DeterministicLoop(FifoPolicy(), max_steps=100_000)
    rec = Recorder(clock=loop.time)

    async def drive():
        fc = FleetController(nodes, inline_solve=True, recorder=rec)
        await fc.start()
        with pytest.raises(ValueError, match="node_score_"):
            fc.add_tenant(
                "bad", M, pmap, lambda *a: None,
                plan_options=PlanOptions(node_scorer=lambda *a: 0.0))
        assert fc.keys() == []
        await fc.stop()

    with use_recorder(rec):
        loop.run_until_complete(drive())


def test_stop_survives_a_dead_tenant_loop():
    """A tenant engine that died with an exception must not abort the
    fleet wind-down partway: every other loop still stops, the shared
    service closes (no leaked dispatcher), and the crash re-raises to
    the caller afterwards."""
    nodes, pmap = _cluster()
    loop = DeterministicLoop(FifoPolicy(), max_steps=500_000)
    rec = Recorder(clock=loop.time)

    class _Boom(Exception):
        pass

    async def drive():
        async def assign(stop_ch, node, partitions, states, ops):
            await asyncio.sleep(0.1)

        fc = FleetController(nodes, inline_solve=True, debounce_s=0.1,
                             recorder=rec)
        await fc.start()
        good = fc.add_tenant("good", M, pmap, assign)
        bad = fc.add_tenant("bad", M, _cluster()[1], assign)

        async def exploding_plan(*a):
            raise _Boom("planner died")

        bad._planner = type(
            "P", (), {"plan_cycle": staticmethod(exploding_plan)})()
        fc.submit_all(ClusterDelta(fail=("n0",)))
        await good.quiesce()
        with pytest.raises(RuntimeError, match="tenant 'bad'"):
            await fc.stop()
        # The wind-down still completed: no orphan controller tasks,
        # and the shared service is closed.
        assert good.pending_tasks() == []
        from blance_tpu.plan.service import PlanServiceClosed

        with pytest.raises(PlanServiceClosed):
            await fc.service.submit(_tiny_tenant("x", 0))

    with use_recorder(rec):
        loop.run_until_complete(drive())


def test_session_and_planner_are_mutually_exclusive():
    class _FakePlanner:
        async def plan_cycle(self, *a):
            raise AssertionError("never called")

    nodes, pmap = _cluster()
    with pytest.raises(ValueError, match="mutually exclusive"):
        RebalanceController(M, nodes, pmap, lambda *a: None,
                            session=object(), planner=_FakePlanner())


# -- CarryCache eviction observability ----------------------------------------


def _carry_for(cache, key, n=64):
    from blance_tpu.plan.tensor import SolveCarry

    used = np.zeros((2, n), np.float32)
    carry = SolveCarry(prices=used.sum(axis=0),
                       assign=np.zeros((4, 2, 1), np.int32), used=used)
    cache.store(key, carry, np.zeros((4, 2, 1), np.int32))
    return carry


def test_carry_cache_eviction_stats_and_labeled_counter():
    rec = Recorder()
    cache = CarryCache(max_bytes=1, recorder=rec)
    _carry_for(cache, "a")  # over the byte budget immediately
    assert cache.evictions.get("bytes") == 1
    cache = CarryCache(max_entries=2, recorder=rec)
    _carry_for(cache, "a")
    _carry_for(cache, "b")
    _carry_for(cache, "c")  # third key: entry-count LRU drops "a"
    assert cache.evictions.get("entries") == 1
    assert sorted(cache.keys()) == ["b", "c"]
    # Shape reset with a live carry counts too.
    big = CarryCache(recorder=rec)
    _carry_for(big, "k")
    big.entry("k", partitions=9)  # re-shaped problem
    assert big.evictions.get("shape") == 1
    # The labeled counter landed, one series per reason.
    assert rec.counters.get('fleet.carry_evictions{reason="bytes"}') == 1
    assert rec.counters.get(
        'fleet.carry_evictions{reason="entries"}') == 1
    assert rec.counters.get('fleet.carry_evictions{reason="shape"}') == 1
    stats = cache.stats()
    assert stats["evictions"] == cache.evictions
    assert stats["entries"] == len(cache.keys())


# -- fleet SLO rollup ---------------------------------------------------------


def test_fleet_rollup_math_and_gauges():
    rec = Recorder()
    _nodes, pa = _cluster(parts=4)
    _nodes, pb = _cluster(parts=4)
    ta = SloTracker(pa, recorder=rec, publish_gauges=False)
    tb = SloTracker(pb, recorder=rec, publish_gauges=False)
    roll = FleetSloRollup(availability_floor=0.9, recorder=rec)
    roll.register("a", ta)
    roll.register("b", tb)
    tb.strip_nodes({"n0", "n1", "n2", "n3", "n4", "n5"})
    s = roll.summary()
    assert s.tenants == 2
    assert s.availability_min == 0.0 and s.worst_tenant == "b"
    assert s.availability_mean == 0.5
    assert s.tenants_below_floor == 1
    roll.publish()
    assert rec.gauges["slo.fleet_availability_min"] == 0.0
    assert rec.gauges["slo.fleet_availability_mean"] == 0.5
    assert rec.gauges["slo.fleet_tenants_below_floor"] == 1.0
    assert rec.gauges["fleet.tenants"] == 2.0
    # publish_gauges=False really silenced the per-tenant writes.
    assert "slo.partition_availability" not in rec.gauges


def test_fleet_loop_emits_only_declared_metrics():
    """Everything the fleet plane emits is in the registry (the
    test_telemetry drift guard covers docs <-> registry; this covers
    emission <-> registry)."""
    nodes, _ = _cluster()
    loop = DeterministicLoop(FifoPolicy(), max_steps=1_000_000)
    rec = Recorder(clock=loop.time)

    async def drive():
        async def assign(stop_ch, node, partitions, states, ops):
            await asyncio.sleep(1.0)

        fc = FleetController(nodes, inline_solve=True,
                             admission_window_s=0.25, debounce_s=0.5,
                             fair_share=2, carry_bytes=0,
                             availability_floor=0.8, recorder=rec)
        await fc.start()
        for j in range(3):
            _n, pmap = _cluster()
            fc.add_tenant(f"t{j}", M, pmap, assign)
        fc.submit_all(ClusterDelta(fail=("n0",)))
        await fc.quiesce_all()
        await fc.stop()

    with use_recorder(rec):
        loop.run_until_complete(drive())
    assert rec.counters.get("fleet.batches", 0) > 0
    assert default_registry().undeclared(rec) == []


# -- the multi-hundred-tenant week (the acceptance soak) ----------------------


@pytest.mark.slow
def test_fleet_week_multi_hundred_tenants_replays_bit_identically():
    """ISSUE 13 acceptance: a multi-hundred-tenant simulated week
    (staggered onboarding + correlated zone outage + spot burst +
    noisy-neighbor waves) replays bit-identically — event log, SLO
    summaries, rendered exposition — with coalescing collapsing the
    fleet's plan requests into a small number of bucketed dispatches."""
    scn = fleet_week()  # 240 tenants, 7 virtual days
    a = run_fleet_scenario(scn)
    b = run_fleet_scenario(scn)
    assert a.log_text() == b.log_text()
    assert a.exposition == b.exposition
    assert a.summaries == b.summaries
    assert a.complete
    assert a.tenants >= 200
    assert a.fleet.availability_min == 1.0
    assert a.unconverged == 0
    # The coalescing economics at fleet scale: way fewer dispatches
    # than plan requests (4x margin is conservative vs the ~4.6x
    # measured on the committed configuration).
    assert a.dispatches * 4 <= a.plan_requests
