"""Fused single-dispatch plan pipeline (ISSUE 9, ROADMAP item 3).

Contracts pinned here:

- the device decode pack (core/encode.pack_assignment) is bit-equivalent
  to decode_assignment's numpy pack, and the device prev scatter
  (prev_from_entries) to encode_problem's host fill;
- plan_pipeline produces a bit-identical map, equal warnings AND equal
  move lists vs the staged path (plan_next_map_tpu + calc_all_moves),
  cold and bucketed, rules and rule-free;
- PlannerSession.replan_with_moves ≡ replan() followed by moves(), cold
  AND warm, single-device and mesh-sharded, with the same carry/counter
  semantics;
- the warm one-sweep repair runs bit-identically through the fused
  Pallas score kernel (interpret mode) — delta replans cover the fused
  scoring path;
- donated input buffers are actually invalidated after dispatch;
- mesh_shape_for/make_mesh_auto factorization invariants and the
  declarative shard-layout tables the runtime and the shape audit share.

The module runs under the autouse jax.transfer_guard("disallow")
fixture (tests/conftest.py): any IMPLICIT host<->device transfer inside
the pipeline paths fails the test — the zero-intermediate-transfers
guarantee is enforced, not assumed.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from blance_tpu import HierarchyRule, Partition, PlanOptions, model
from blance_tpu.core.encode import (
    encode_problem,
    pack_assignment,
    prev_from_entries,
)
from blance_tpu.moves.batch import calc_all_moves
from blance_tpu.obs import Recorder, use_recorder
from blance_tpu.plan.session import PlannerSession
from blance_tpu.plan.tensor import (
    _pipeline_cold_donating,
    _pipeline_warm_donating,
    _pipeline_warm_jit,
    carry_from_assignment,
    plan_next_map_tpu,
    plan_pipeline,
    solve_dense_converged,
)

M2 = model(primary=(0, 1), replica=(1, 1))


def _mk_map(P, N, seed=0):
    rng = np.random.default_rng(seed)
    nodes = [f"n{i:03d}" for i in range(N)]
    p_ids = rng.integers(0, N, P)
    r_ids = (p_ids + 1 + rng.integers(0, N - 1, P)) % N
    prev = {str(i): Partition(str(i), {"primary": [nodes[p_ids[i]]],
                                       "replica": [nodes[r_ids[i]]]})
            for i in range(P)}
    return prev, nodes


def _rack_opts(nodes, **kw):
    hier = {n: f"r{i // 4}" for i, n in enumerate(nodes)}
    hier.update({f"r{i}": "z0" for i in range((len(nodes) + 3) // 4)})
    return PlanOptions(node_hierarchy=hier,
                       hierarchy_rules={"replica": [HierarchyRule(2, 1)]},
                       **kw)


def _dense(P, N, seed=0, invalid=0):
    rng = np.random.default_rng(seed)
    S, R = 2, 1
    prev = np.full((P, S, R), -1, np.int32)
    prev[:, 0, 0] = rng.integers(0, N, P)
    prev[:, 1, 0] = (prev[:, 0, 0] + 1 + rng.integers(0, N - 1, P)) % N
    pw = np.ones(P, np.float32)
    nw = np.ones(N, np.float32)
    valid = np.ones(N, bool)
    if invalid:
        valid[:invalid] = False
    stick = np.full((P, S), 1.5, np.float32)
    gids = np.stack([np.arange(N, dtype=np.int32),
                     np.arange(N, dtype=np.int32) // 4,
                     np.zeros(N, np.int32)])
    gv = np.ones((3, N), bool)
    return (prev, pw, nw, valid, stick, gids, gv, (1, 1), ((), ((2, 1),)))


def _maps_equal(a, b):
    return {k: v.nodes_by_state for k, v in a.items()} == \
        {k: v.nodes_by_state for k, v in b.items()}


# ---------------------------------------------------------------------------
# device integer cores
# ---------------------------------------------------------------------------


def test_pack_assignment_matches_numpy_pack():
    rng = np.random.default_rng(3)
    assign = rng.integers(-1, 6, (37, 3, 4)).astype(np.int32)
    packed, counts = (np.asarray(x)
                      for x in pack_assignment(jnp.asarray(assign)))
    for si in range(assign.shape[1]):
        ids = assign[:, si, :]
        mask = ids >= 0
        order = np.argsort(~mask, axis=1, kind="stable")
        np_packed = np.take_along_axis(ids, order, axis=1)
        assert np.array_equal(packed[:, si, :], np_packed)
        assert np.array_equal(counts[:, si], mask.sum(axis=1))


def test_prev_from_entries_matches_encode_fill():
    prev_map, nodes = _mk_map(29, 7, seed=5)
    problem = encode_problem(prev_map, prev_map, nodes, None, M2,
                             PlanOptions())
    state_index = {s: i for i, s in enumerate(problem.states)}
    node_index = {n: i for i, n in enumerate(problem.nodes)}
    pis, sis, ris, nids = [], [], [], []
    for pi, pname in enumerate(problem.partitions):
        for sname, ns in prev_map[pname].nodes_by_state.items():
            for ri, node in enumerate(ns):
                pis.append(pi)
                sis.append(state_index[sname])
                ris.append(ri)
                nids.append(node_index[node])
    got = np.asarray(prev_from_entries(
        jnp.asarray(np.asarray(pis, np.int32)),
        jnp.asarray(np.asarray(sis, np.int32)),
        jnp.asarray(np.asarray(ris, np.int32)),
        jnp.asarray(np.asarray(nids, np.int32)),
        p=problem.P, s=problem.S, r=problem.R))
    assert np.array_equal(got, problem.prev)


# ---------------------------------------------------------------------------
# plan_pipeline ≡ staged path
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("with_rules", [True, False])
def test_plan_pipeline_identical_to_staged(with_rules):
    prev_map, nodes = _mk_map(96, 12, seed=1)
    removed = [nodes[3]]
    opts = _rack_opts(nodes) if with_rules else PlanOptions()
    smap, swarn = plan_next_map_tpu(prev_map, prev_map, nodes, removed,
                                    [], M2, opts)
    smoves = calc_all_moves(prev_map, smap, M2)
    fmap, fwarn, fmoves = plan_pipeline(prev_map, prev_map, nodes,
                                        removed, [], M2, opts)
    assert _maps_equal(smap, fmap)
    assert swarn == fwarn
    assert fmoves == smoves


def test_plan_pipeline_bucketed_identical_to_staged():
    prev_map, nodes = _mk_map(70, 11, seed=2)
    opts = _rack_opts(nodes, shape_bucketing=True)
    smap, swarn = plan_next_map_tpu(prev_map, prev_map, nodes,
                                    [nodes[1]], [], M2, opts)
    fmap, fwarn, fmoves = plan_pipeline(prev_map, prev_map, nodes,
                                        [nodes[1]], [], M2, opts)
    assert _maps_equal(smap, fmap)
    assert swarn == fwarn
    assert fmoves == calc_all_moves(prev_map, smap, M2)


def test_plan_pipeline_favor_min_nodes_order():
    prev_map, nodes = _mk_map(48, 8, seed=7)
    _m, _w, fmoves = plan_pipeline(prev_map, prev_map, nodes,
                                   [nodes[0]], [], M2, PlanOptions(),
                                   favor_min_nodes=True)
    smap, _ = plan_next_map_tpu(prev_map, prev_map, nodes, [nodes[0]],
                                [], M2, PlanOptions())
    assert fmoves == calc_all_moves(prev_map, smap, M2,
                                    favor_min_nodes=True)


def test_plan_pipeline_unsupported_opts_falls_back_exact():
    """Custom placement hooks keep the exact path, moves included."""
    prev_map, nodes = _mk_map(24, 6, seed=9)
    opts = PlanOptions(node_sorter=lambda ctx, ns: list(ns))
    from blance_tpu.plan.api import plan_next_map

    smap, swarn = plan_next_map(prev_map, prev_map, nodes, [], [], M2,
                                opts, backend="tpu")
    fmap, fwarn, fmoves = plan_pipeline(prev_map, prev_map, nodes, [],
                                        [], M2, opts)
    assert _maps_equal(smap, fmap)
    assert swarn == fwarn
    assert fmoves == calc_all_moves(prev_map, smap, M2)


def test_plan_next_map_fused_pipeline_option():
    """backend="tpu" + PlanOptions.fused_pipeline rides the pipeline and
    stays bit-identical to the staged plan_next_map."""
    from blance_tpu.plan.api import plan_next_map

    prev_map, nodes = _mk_map(64, 8, seed=4)
    smap, swarn = plan_next_map(prev_map, prev_map, nodes, [nodes[2]],
                                [], M2, _rack_opts(nodes), backend="tpu")
    fmap, fwarn = plan_next_map(
        prev_map, prev_map, nodes, [nodes[2]], [], M2,
        _rack_opts(nodes, fused_pipeline=True), backend="tpu")
    assert _maps_equal(smap, fmap)
    assert swarn == fwarn


# ---------------------------------------------------------------------------
# session fast path ≡ replan() + moves()
# ---------------------------------------------------------------------------


def _fresh_sessions(P, N, seed, mesh=None, opts_fn=_rack_opts):
    prev_map, nodes = _mk_map(P, N, seed=seed)
    parts = [str(i) for i in range(P)]
    s_staged = PlannerSession(M2, nodes, parts, opts=opts_fn(nodes),
                              mesh=mesh)
    s_fused = PlannerSession(M2, nodes, parts, opts=opts_fn(nodes),
                             mesh=mesh)
    s_staged.load_map(prev_map)
    s_fused.load_map(prev_map)
    return s_staged, s_fused, nodes


def test_session_fast_path_cold_warm_identity():
    s1, s2, nodes = _fresh_sessions(96, 12, seed=11)
    a1 = s1.replan()
    mv1 = s1.moves()
    a2, mv2 = s2.replan_with_moves()
    assert np.array_equal(a1, a2)
    assert all(np.array_equal(x, y) for x, y in zip(mv1, mv2))
    s1.apply()
    s2.apply()

    for delta in ([nodes[5]], [nodes[7], nodes[8]]):
        s1.remove_nodes(delta)
        s2.remove_nodes(delta)
        w1 = s1.replan()
        wm1 = s1.moves()
        w2, wm2 = s2.replan_with_moves()
        assert np.array_equal(w1, w2)
        assert all(np.array_equal(x, y) for x, y in zip(wm1, wm2))
        s1.apply()
        s2.apply()


def test_session_fast_path_warm_counters():
    from blance_tpu.obs import get_recorder

    # 96x12: large enough that removing one node stays inside the
    # capacity-shrink precheck's allowance, so the warm path really runs
    # (tiny 8-node clusters legitimately route the removal to cold).
    s1, s2, nodes = _fresh_sessions(96, 12, seed=13)
    del s1
    rec = get_recorder()
    base_hit = rec.counters.get("plan.solve.carry_hit", 0)
    base_warm = rec.counters.get("plan.pipeline.warm", 0)
    s2.replan_with_moves()
    s2.apply()
    s2.remove_nodes([nodes[2]])
    s2.replan_with_moves()
    assert rec.counters.get("plan.solve.carry_hit", 0) == base_hit + 1
    assert rec.counters.get("plan.pipeline.warm", 0) == base_warm + 1


def test_session_fast_path_add_nodes_delta():
    s1, s2, nodes = _fresh_sessions(48, 8, seed=17)
    for s in (s1, s2):
        s.replan()
        s.apply()
    s1.add_nodes(["zz0", "zz1"])
    s2.add_nodes(["zz0", "zz1"])
    w1 = s1.replan()
    wm1 = s1.moves()
    w2, wm2 = s2.replan_with_moves()
    assert np.array_equal(w1, w2)
    assert all(np.array_equal(x, y) for x, y in zip(wm1, wm2))


def test_session_fast_path_sharded():
    from blance_tpu.parallel.sharded import make_mesh, make_mesh_2d

    for mesh in (make_mesh(8), make_mesh_2d(4, 2)):
        s1, s2, nodes = _fresh_sessions(64, 8, seed=19, mesh=mesh)
        a1 = s1.replan()
        mv1 = s1.moves()
        a2, mv2 = s2.replan_with_moves()
        assert np.array_equal(a1, a2)
        assert all(np.array_equal(x, y) for x, y in zip(mv1, mv2))
        s1.apply()
        s2.apply()
        s1.remove_nodes([nodes[1]])
        s2.remove_nodes([nodes[1]])
        w1 = s1.replan()
        wm1 = s1.moves()
        w2, wm2 = s2.replan_with_moves()
        assert np.array_equal(w1, w2)
        assert all(np.array_equal(x, y) for x, y in zip(wm1, wm2))


# ---------------------------------------------------------------------------
# warm repair through the fused Pallas kernel
# ---------------------------------------------------------------------------


def test_warm_pipeline_fused_interpret_matches_matrix():
    args = _dense(48, 8, seed=21)
    dev = [jnp.asarray(a) for a in args[:7]]
    out_np = np.asarray(solve_dense_converged(*dev, args[7], args[8],
                                              record=False))
    dirty = np.zeros(48, bool)
    dirty[0] = True

    def run(mode):
        carry = carry_from_assignment(jnp.asarray(out_np), dev[1], dev[2])
        return _pipeline_warm_jit(
            jnp.asarray(out_np), *dev[1:7], jnp.asarray(dirty),
            jnp.asarray(carry.used), args[7], args[8], fused_score=mode)

    r_matrix = run("off")
    r_fused = run("interpret")
    assert bool(r_matrix[3]) and bool(r_fused[3])  # both accepted
    for a, b in zip(r_matrix, r_fused):
        assert np.array_equal(np.asarray(a), np.asarray(b))


# ---------------------------------------------------------------------------
# donation discipline
# ---------------------------------------------------------------------------


def test_donated_buffers_invalidated_after_dispatch():
    """The donation contract is real: prev (cold) and prev+carry_used
    (warm) are consumed by the dispatch — reuse must fail loudly, and
    XLA is free to alias them into the outputs."""
    args = _dense(48, 8, seed=23)
    dev = [jnp.asarray(a) for a in args[:7]]
    out_np = np.asarray(solve_dense_converged(*dev, args[7], args[8],
                                              record=False))

    prev_cold = jnp.asarray(args[0])
    res = _pipeline_cold_donating(prev_cold, *dev[1:7], args[7], args[8],
                                  fused_score="off")
    jax.block_until_ready(res[0])
    assert prev_cold.is_deleted()

    dirty = np.zeros(48, bool)
    dirty[0] = True
    carry = carry_from_assignment(jnp.asarray(out_np), dev[1], dev[2])
    prev_warm = jnp.asarray(out_np)
    cu = jnp.asarray(np.asarray(carry.used))
    res_w = _pipeline_warm_donating(prev_warm, *dev[1:7],
                                    jnp.asarray(dirty), cu,
                                    args[7], args[8], fused_score="off")
    jax.block_until_ready(res_w[0])
    assert prev_warm.is_deleted()
    assert cu.is_deleted()
    # The non-donated operands must survive.
    assert not dev[1].is_deleted()


# ---------------------------------------------------------------------------
# sharded pipeline + mesh generalization
# ---------------------------------------------------------------------------


def test_sharded_pipeline_matches_staged_sharded():
    from blance_tpu.moves.batch import diff_assignments
    from blance_tpu.parallel.sharded import (
        make_mesh,
        make_mesh_2d,
        solve_dense_sharded,
        solve_pipeline_sharded,
    )

    args = _dense(64, 8, seed=25, invalid=1)
    for mesh in (make_mesh(8), make_mesh(2), make_mesh_2d(2, 4)):
        s_assign = solve_dense_sharded(mesh, *args[:7], args[7], args[8])
        with jax.transfer_guard("allow"):
            s_diff = tuple(np.asarray(a) for a in diff_assignments(
                jnp.asarray(args[0]), jnp.asarray(s_assign)))
        p_assign, p_carry, p_diff = solve_pipeline_sharded(
            mesh, *args[:7], args[7], args[8])
        assert np.array_equal(s_assign, p_assign)
        assert all(np.array_equal(a, b) for a, b in zip(s_diff, p_diff))
        # The carry matches a host rebuild off the same assignment.
        ref = carry_from_assignment(
            jnp.asarray(p_assign), jnp.asarray(args[1]),
            jnp.asarray(args[2]))
        assert np.allclose(np.asarray(ref.used), np.asarray(p_carry.used))


def test_sharded_pipeline_warm_fixpoint():
    from blance_tpu.parallel.sharded import (
        make_mesh,
        solve_dense_sharded,
        solve_pipeline_sharded,
    )

    args = _dense(64, 8, seed=27)
    mesh = make_mesh(8)
    b_assign, b_carry = solve_dense_sharded(
        mesh, *args[:7], args[7], args[8], return_carry=True)
    dirty = np.zeros(64, bool)
    dirty[:4] = True
    w = solve_pipeline_sharded(mesh, b_assign, *args[1:7], args[7],
                               args[8], dirty=dirty, carry=b_carry,
                               warm_only=True)
    assert w is not None, "fixpoint warm repair should be accepted"
    assert np.array_equal(w[0], b_assign)
    # moves of an unchanged map are empty
    assert (w[2][2] < 0).all()


def test_mesh_shape_for_invariants():
    from blance_tpu.parallel.sharded import mesh_shape_for

    for nd in (1, 2, 3, 5, 6, 8, 12, 16, 64, 256, 1024):
        for (p, n) in ((0, 0), (512, 64), (100_000, 1_000),
                       (100_000, 10_000), (1_000_000, 100_000),
                       (1_000_000, 1_000_000)):
            ps, ns = mesh_shape_for(nd, p, n)
            assert ps >= 1 and ns >= 1 and ps * ns == nd
    # Small problems prefer the pure partition mesh on any fleet.
    assert mesh_shape_for(8, 512, 64) == (8, 1)
    assert mesh_shape_for(256, 100_000, 10_000) == (256, 1)
    # Huge node counts engage the node axis.
    ps, ns = mesh_shape_for(8, 1_000_000, 100_000)
    assert ns > 1
    # Beyond-fleet problems still use every chip, balanced.
    ps, ns = mesh_shape_for(64, 1_000_000, 1_000_000)
    assert ps * ns == 64 and ns > 1
    with pytest.raises(ValueError):
        mesh_shape_for(0, 1, 1)


def test_make_mesh_auto_small_problem_is_1d():
    from blance_tpu.parallel.sharded import (
        PARTITION_AXIS,
        make_mesh_auto,
    )

    mesh = make_mesh_auto(512, 64)
    assert mesh.axis_names == (PARTITION_AXIS,)
    assert mesh.devices.size == len(jax.devices())


def test_layout_tables_cover_solver_args():
    """The declarative layout tables (the audit's source of truth) stay
    in lockstep with the impl signatures."""
    import inspect

    from blance_tpu.parallel.sharded import (
        PIPELINE_COLD_OUT_LAYOUT,
        PIPELINE_WARM_OUT_LAYOUT,
        SOLVER_IN_LAYOUT,
        WARM_EXTRA_LAYOUT,
        layout_specs,
    )
    from blance_tpu.plan.tensor import (
        _pipeline_cold_impl,
        _pipeline_warm_impl,
    )

    cold_params = list(inspect.signature(
        _pipeline_cold_impl).parameters)
    warm_params = list(inspect.signature(
        _pipeline_warm_impl).parameters)
    assert [n for n, _ in SOLVER_IN_LAYOUT] == cold_params[:7]
    assert [n for n, _ in SOLVER_IN_LAYOUT + WARM_EXTRA_LAYOUT] == \
        warm_params[:9]
    assert len(layout_specs(PIPELINE_COLD_OUT_LAYOUT)) == 9
    assert len(layout_specs(PIPELINE_WARM_OUT_LAYOUT)) == 9
    with pytest.raises(ValueError):
        layout_specs((("x", "diagonal"),))


# ---------------------------------------------------------------------------
# telemetry
# ---------------------------------------------------------------------------


def test_pipeline_emissions_all_declared():
    from blance_tpu.obs.expo import default_registry

    rec = Recorder()
    with use_recorder(rec):
        prev_map, nodes = _mk_map(96, 12, seed=31)
        parts = [str(i) for i in range(96)]
        s = PlannerSession(M2, nodes, parts, opts=_rack_opts(nodes))
        s.load_map(prev_map)
        s.replan_with_moves()
        s.apply()
        s.remove_nodes([nodes[1]])
        s.replan_with_moves()
        plan_pipeline(prev_map, prev_map, nodes, [nodes[2]], [], M2,
                      _rack_opts(nodes))
    assert default_registry().undeclared(rec) == []
    assert rec.counters.get("plan.pipeline.calls", 0) >= 3
    assert rec.counters.get("plan.pipeline.warm", 0) >= 1
