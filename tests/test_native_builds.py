"""Build-regression guard for the native layers.

The marshal and planner test files skip wholesale when their native
module is unavailable — correct on machines with no toolchain, but a
silent hole when a compiler exists and the build itself regressed (a
syntax error in marshal.c would otherwise just skip 10 parity tests).
These tests FAIL, not skip, whenever a C/C++ toolchain is present but
the native layer won't load.
"""

import shutil

import pytest


def _has(*names):
    return any(shutil.which(n) for n in names)


@pytest.mark.skipif(not _has("cc", "gcc", "clang"),
                    reason="no C compiler on this machine")
def test_marshal_extension_builds():
    from blance_tpu.core import marshal

    assert marshal.available(), (
        "C toolchain present but the marshal extension failed to "
        "build/load — check the compile log under core/_native_build")


@pytest.mark.skipif(not _has("c++", "g++", "clang++"),
                    reason="no C++ compiler on this machine")
def test_native_planner_builds():
    from blance_tpu.plan.native import native_available

    assert native_available(), (
        "C++ toolchain present but the native planner failed to "
        "build/load")
