"""Race regressions: schedule-explorer scenarios + committed traces.

Two layers:

- The committed trace ``tests/traces/pause_cycle_guard.json`` replays
  the exact interleaving where a pause→resume→pause cycle landed inside
  the supplier's pause-counter put.  Against the PRE-FIX supplier (the
  ``_prefix_wait`` shim below — a faithful copy of the code before
  ``Orchestrator._wait_while_paused`` learned to revalidate) the trace
  reproduces the torn guard: a round feeds while paused.  Against the
  fixed supplier the same scenario passes under every explored
  schedule.  This is the PR's acceptance artifact: the race is a
  deterministic regression test forever.
- Explorer smoke over the orchestrator scenario registry
  (analysis/schedule.py): bounded-exhaustive on the small scenarios and
  pinned-seed walks on the chaos ones, tier-1-sized budgets.
"""

import os

import pytest

from blance_tpu.analysis.schedule import (
    CI_WALK_SEEDS,
    SCENARIOS,
    run_scenario_walks,
)
from blance_tpu.orchestrate.orchestrator import Orchestrator
from blance_tpu.testing.sched import (
    InvariantViolation,
    explore,
    load_trace,
    replay,
)

TRACE_DIR = os.path.join(os.path.dirname(__file__), "traces")


async def _prefix_wait(self):
    """The pre-fix supplier pause wait: capture once, wait once.  A
    resume+pause cycle during the pause-counter put closes the captured
    channel — the wait returns immediately and the supplier feeds while
    the orchestrator is logically paused."""
    pause_ch = self._pause_ch
    if pause_ch is None:
        return
    await self._bump("tot_run_supply_moves_pause")
    await pause_ch.get()
    await self._bump("tot_run_supply_moves_resume")


@pytest.fixture
def prefix_supplier(monkeypatch):
    monkeypatch.setattr(Orchestrator, "_wait_while_paused", _prefix_wait)


# -- the committed pause-guard trace -----------------------------------------


def test_committed_trace_fails_on_prefix_code(prefix_supplier):
    trace = load_trace(os.path.join(TRACE_DIR, "pause_cycle_guard.json"))
    out = replay(SCENARIOS["pause_cycle_guard"].factory, trace,
                 strict=True)
    assert not out.ok
    assert isinstance(out.error, InvariantViolation)
    assert "paused" in str(out.error)


def test_committed_trace_passes_on_fixed_code():
    trace = load_trace(os.path.join(TRACE_DIR, "pause_cycle_guard.json"))
    # strict=False: the fixed supplier legitimately changes the choice
    # tree after the divergence point; the point is that the SCENARIO
    # (whose assign asserts the pause guard) now holds.
    out = replay(SCENARIOS["pause_cycle_guard"].factory, trace,
                 strict=False)
    assert out.ok, out.describe()


def test_prefix_supplier_fails_under_exploration(prefix_supplier):
    """Not just one lucky schedule: every interleaving of the scripted
    cycle tears the pre-fix guard."""
    rep = explore(SCENARIOS["pause_cycle_guard"].factory,
                  branch_budget=1, max_schedules=100)
    assert rep.violations, rep.summary()


def test_fixed_supplier_explores_clean():
    rep = explore(SCENARIOS["pause_cycle_guard"].factory,
                  branch_budget=1, max_schedules=200)
    assert rep.complete and rep.violations == [], rep.summary()


def test_adversarial_repause_never_tears_the_feed_decision(monkeypatch):
    """The strongest pause contract the supplier can honor is
    DECISION-time: it never decides to feed a round while paused (a
    pause landing after the decision is an in-flight move by reference
    semantics — 'stop starting NEW assignments; in-flight moves
    finish').  An adversarial consumer that re-pauses the instant it
    observes any supplier resume bump — i.e. inside every rendezvous
    window _wait_while_paused suspends in — must never catch the
    supplier picking moves while _pause_ch is set.  The probe rides
    _filter_next_plausible_moves_for_node, which runs synchronously
    between the pause gate and feeder spawn."""
    import asyncio

    from blance_tpu.core.types import Partition, PartitionModelState
    from blance_tpu.orchestrate import (
        OrchestratorOptions,
        orchestrate_moves,
    )

    model = {"primary": PartitionModelState(priority=0, constraints=0)}

    orig = Orchestrator._filter_next_plausible_moves_for_node

    def probed(self, node, arr):
        if self._pause_ch is not None:
            raise InvariantViolation(
                "supplier decided to feed while paused")
        return orig(self, node, arr)

    monkeypatch.setattr(
        Orchestrator, "_filter_next_plausible_moves_for_node", probed)

    def factory():
        async def scenario():
            beg = {"p0": Partition("p0", {"primary": []}),
                   "p1": Partition("p1", {"primary": []})}
            end = {"p0": Partition("p0", {"primary": ["n1"]}),
                   "p1": Partition("p1", {"primary": ["n1"]})}

            async def assign(stop_ch, node, partitions, states, ops):
                await asyncio.sleep(0)

            o = orchestrate_moves(model, OrchestratorOptions(), ["n1"],
                                  beg, end, assign)
            o.pause_new_assignments()
            repauses = 0
            last_resume = 0

            async def resume_later():
                await asyncio.sleep(0.001)
                o.resume_new_assignments()

            resumers = [asyncio.ensure_future(resume_later())]
            async for progress in o.progress_ch():
                for e in progress.errors:
                    if isinstance(e, InvariantViolation):
                        raise e
                if progress.tot_run_supply_moves_resume > last_resume \
                        and repauses < 3:
                    last_resume = progress.tot_run_supply_moves_resume
                    repauses += 1
                    o.pause_new_assignments()
                    resumers.append(
                        asyncio.ensure_future(resume_later()))
            o.stop()
            for t in resumers:
                await t

        return scenario()

    rep = explore(factory, branch_budget=1, max_schedules=400)
    assert rep.complete and rep.violations == [], (
        rep.violations and rep.violations[0].error)


# -- scenario registry smoke (tier-1-sized budgets) --------------------------


def test_two_movers_three_partitions_bounded_exhaustive():
    rep = explore(SCENARIOS["two_movers_three_partitions"].factory,
                  branch_budget=1, max_schedules=500)
    assert rep.complete and rep.violations == [], rep.summary()


@pytest.mark.parametrize("name", [
    "pause_resume_during_retry_backoff",
    "stop_during_quarantine_probe",
    "movers_race_breaker_trip",
    "slo_gauges_under_chaos",
    "supersede_mid_rebalance",
])
def test_chaos_scenarios_pinned_seed_walks(name):
    for seed, out in run_scenario_walks(SCENARIOS[name], CI_WALK_SEEDS):
        assert out.ok, f"{name} seed={seed}: {out.describe()}"


def test_walks_are_reproducible():
    s = SCENARIOS["movers_race_breaker_trip"]
    (seed_a, a), = run_scenario_walks(s, (11,))
    (seed_b, b), = run_scenario_walks(s, (11,))
    assert (a.choices, a.signature) == (b.choices, b.signature)


def test_probe_scenario_actually_probes():
    """The stop_during_quarantine_probe scenario must genuinely reach
    the half-open window (structurally, not by luck) — otherwise it
    stops guarding the code path it is named for."""
    s = SCENARIOS["stop_during_quarantine_probe"]
    (seed, out), = run_scenario_walks(s, (11,))
    assert out.ok
    assert out.result["stopped_during_probe"] == 1
    assert out.result["trips"] >= 1


def test_scenario_registry_shape():
    names = set(SCENARIOS)
    assert {"two_movers_three_partitions", "pause_cycle_guard",
            "pause_resume_during_retry_backoff",
            "stop_during_quarantine_probe",
            "movers_race_breaker_trip"} <= names
    exhaustive = [s for s in SCENARIOS.values() if s.exhaustive]
    assert len(exhaustive) >= 2
    assert len(CI_WALK_SEEDS) >= 3


def test_schedule_cli_smoke(capsys):
    from blance_tpu.analysis.schedule import main

    rc = main(["--scenario", "pause_cycle_guard", "--budget", "0"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "pause_cycle_guard" in out and "OK" in out

    rc = main(["--list"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "two_movers_three_partitions" in out


def test_schedule_cli_emits_trace_on_violation(tmp_path, capsys,
                                               prefix_supplier):
    from blance_tpu.analysis.schedule import main

    trace_dir = str(tmp_path / "traces")
    rc = main(["--scenario", "pause_cycle_guard", "--budget", "0",
               "--trace-dir", trace_dir])
    capsys.readouterr()
    assert rc == 1
    files = os.listdir(trace_dir)
    assert files, "violating schedule was not written as a trace"
    tr = load_trace(os.path.join(trace_dir, sorted(files)[0]))
    out = replay(SCENARIOS["pause_cycle_guard"].factory, tr, strict=True)
    assert not out.ok
