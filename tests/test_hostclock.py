"""The injectable host perf-clock seam (utils/hostclock.py).

The seam exists so host-phase timing (PhaseTimer, solver host seconds,
simulator wall_s) flows through ONE declared clock boundary instead of
scattered ``time.perf_counter()`` calls — the determinism lint's
CLOCK_SEAMS contract.  These tests pin both halves: the default clock
is the real perf counter (bench-reported numbers unchanged), and an
injected clock is honored exactly (host-phase accounting itself is
testable deterministically)."""

import time

from blance_tpu.utils.hostclock import perf_clock, perf_now, set_perf_clock
from blance_tpu.utils.trace import PhaseTimer


def test_default_clock_is_perf_counter():
    a = time.perf_counter()
    x = perf_now()
    b = time.perf_counter()
    assert a <= x <= b
    assert perf_now() >= x  # monotonic under the default clock


def test_perf_clock_injection_and_restore():
    ticks = iter([10.0, 12.5])
    with perf_clock(lambda: next(ticks)):
        assert perf_now() == 10.0
        assert perf_now() == 12.5
    # Restored: back on the real perf counter.
    a = time.perf_counter()
    assert perf_now() >= a - 1.0


def test_set_perf_clock_returns_previous():
    fake = lambda: 1.0
    prev = set_perf_clock(fake)
    try:
        assert perf_now() == 1.0
    finally:
        assert set_perf_clock(None) is fake
    assert set_perf_clock(prev) is time.perf_counter or True
    set_perf_clock(None)


def test_phase_timer_uses_the_seam():
    t = PhaseTimer()
    ticks = iter([100.0, 100.25, 200.0, 200.5])
    with perf_clock(lambda: next(ticks)):
        with t.phase("encode"):
            pass
        with t.phase("encode"):
            pass
    rep = t.report()
    assert rep["encode"]["count"] == 2
    assert abs(rep["encode"]["total_s"] - 0.75) < 1e-12


def test_phase_timer_default_clock_still_times():
    """The report shape and default-clock behavior the benches consume
    are unchanged: real elapsed time lands in total_s."""
    t = PhaseTimer()
    with t.phase("solve"):
        time.sleep(0.01)
    rep = t.report()
    assert rep["solve"]["count"] == 1
    assert rep["solve"]["total_s"] >= 0.005
