"""PlannerSession: the stateful dense planning loop (plan/session.py).

Covers the steady-state loop (replan / moves / apply), map edges
(load_map / to_map), cluster deltas (add/remove nodes), and agreement with
the one-shot plan_next_map TPU backend on identical inputs."""

import numpy as np
import pytest

from blance_tpu import Partition, PlanOptions, model, plan_next_map
from blance_tpu.moves.batch import OP_NAMES
from blance_tpu.plan.session import PlannerSession
from blance_tpu.plan.tensor import check_assignment


MODEL = model(primary=(0, 1), replica=(1, 1))
NODES = [f"n{i}" for i in range(8)]
PARTS = [str(i) for i in range(64)]


def fresh_session():
    s = PlannerSession(MODEL, NODES, PARTS)
    s.replan()
    s.apply()
    return s


def test_fresh_plan_satisfies_constraints():
    s = fresh_session()
    assert s.current.shape[0] == len(PARTS)
    report = check_assignment(s.problem, s.current)
    assert report == {"duplicates": 0, "on_removed_nodes": 0,
                      "unfilled_feasible_slots": 0,
                      "hierarchy_misses": 0}
    # Balanced: every node holds roughly P*2/8 copies.
    counts = np.bincount(s.current[s.current >= 0], minlength=len(NODES))
    assert counts.max() - counts.min() <= 2


def test_map_round_trip():
    s = fresh_session()
    m, warnings = s.to_map()
    assert warnings == {}
    assert set(m) == set(PARTS)
    s2 = PlannerSession(MODEL, NODES, PARTS)
    s2.load_map(m)
    assert (s2.current == s.current).all()


def test_matches_one_shot_tpu_backend():
    s = fresh_session()
    prev_map, _ = s.to_map()
    s.remove_nodes(["n0"])
    s.replan()
    dense_map, _ = s.to_map("proposed")

    one_shot, _ = plan_next_map(
        prev_map, prev_map, NODES, ["n0"], [], MODEL, PlanOptions(),
        backend="tpu")
    assert {p: m.nodes_by_state for p, m in dense_map.items()} == \
        {p: m.nodes_by_state for p, m in one_shot.items()}


def test_remove_replan_moves_apply_loop():
    s = fresh_session()
    before = s.current.copy()
    s.remove_nodes(["n3"])
    s.replan()
    nodes, states, ops = s.moves()

    # Every op row refers to this partition's transition; displaced copies
    # from n3 produce adds elsewhere + dels on n3.
    n3 = s.nodes.index("n3")
    displaced = int((before == n3).sum())
    flat_ops = ops[ops >= 0]
    assert len(flat_ops) >= displaced  # at least one op per displaced copy
    del_rows = ops == OP_NAMES.index("del")
    assert (nodes[del_rows] == n3).all()

    s.apply()
    assert not (s.current == n3).any()
    report = check_assignment(s.problem, s.current)
    assert report["duplicates"] == 0 and report["on_removed_nodes"] == 0
    # Sticky: partitions not touching n3 keep their primary.
    untouched = ~(before == n3).any(axis=(1, 2))
    assert (s.current[untouched, 0, 0] == before[untouched, 0, 0]).all()


def test_add_nodes_attracts_load():
    s = fresh_session()
    s.add_nodes(["x0", "x1"])
    assert "x0" in s.nodes and s.problem.N == 10
    s.replan()
    s.apply()
    new_ids = [s.nodes.index("x0"), s.nodes.index("x1")]
    counts = np.bincount(s.current[s.current >= 0], minlength=10)
    assert all(counts[i] > 0 for i in new_ids)
    report = check_assignment(s.problem, s.current)
    assert report == {"duplicates": 0, "on_removed_nodes": 0,
                      "unfilled_feasible_slots": 0,
                      "hierarchy_misses": 0}


def test_readd_removed_node():
    s = fresh_session()
    s.remove_nodes(["n2"])
    s.replan(); s.apply()
    assert not (s.current == s.nodes.index("n2")).any()
    s.add_nodes(["n2"])
    assert s.removed_nodes == []
    s.replan(); s.apply()
    assert (s.current == s.nodes.index("n2")).any()


def test_moves_requires_replan():
    s = fresh_session()
    with pytest.raises(ValueError):
        s.moves()
    with pytest.raises(ValueError):
        s.to_map("proposed")


def test_add_nodes_duplicates_in_one_call():
    s = fresh_session()
    s.add_nodes(["x0", "x0", "x0"])
    assert s.nodes.count("x0") == 1
    assert s.problem.N == len(NODES) + 1


def test_load_map_rejects_unknown_nodes():
    s = fresh_session()
    bad = {name: Partition(name, {"primary": ["not-a-node"]})
           for name in PARTS}
    with pytest.raises(ValueError, match="not-a-node"):
        s.load_map(bad)


def test_load_map_rejects_unknown_partitions():
    s = fresh_session()
    with pytest.raises(ValueError, match="ghost"):
        s.load_map({"ghost": Partition("ghost", {})})


def test_session_on_mesh_full_loop():
    """PlannerSession(mesh=...) routes every replan through the sharded
    solver: the steady loop (plan -> apply -> remove -> replan) must
    produce audit-clean assignments, drain removed nodes, and keep the
    map materialization working — the long-lived multichip deployment
    shape (SURVEY §2.6)."""
    from blance_tpu.parallel.sharded import make_mesh

    s = PlannerSession(MODEL, NODES, PARTS, mesh=make_mesh(8))
    a1 = s.replan()
    assert (a1[:, 0, 0] >= 0).all() and (a1[:, 1, 0] >= 0).all()
    counts = check_assignment(s.problem, a1)
    assert not any(counts.values()), counts
    s.apply()

    s.remove_nodes(["n0"])
    a2 = s.replan()
    assert not (a2 == 0).any(), "copies left on the removed node id 0"
    counts = check_assignment(s.problem, a2)
    assert not any(counts.values()), counts
    # Stickiness through the mesh path: untouched partitions stay put.
    touched = (a1 == 0).any(axis=(1, 2))
    churned = (a2 != a1).any(axis=(1, 2))
    assert (churned & ~touched).sum() <= len(PARTS) * 0.2
    nmap, warn = s.to_map("proposed")
    assert not warn
    assert all("n0" not in ns for p in nmap.values()
               for ns in p.nodes_by_state.values())
