"""Concurrency-ordering stress tests for the orchestrator.

The reference relies on `go test -race` plus channel discipline; the
asyncio analog (SURVEY.md §5) is hammering pause/resume/stop orderings and
interleavings against invariants:

- the progress stream always closes,
- counters are monotonic and pause/resume counts stay balanced,
- every executed op is one the move plan allows, in per-partition order,
- stop() mid-flight never hangs and never loses in-flight completions.
"""

import asyncio
import random

import pytest

from blance_tpu import Partition, PartitionModelState
from blance_tpu.orchestrate import OrchestratorOptions, orchestrate_moves

MODEL = {
    "primary": PartitionModelState(priority=0, constraints=0),
    "replica": PartitionModelState(priority=0, constraints=1),
}


def pm(d):
    return {name: Partition(name, {s: list(ns) for s, ns in nbs.items()})
            for name, nbs in d.items()}


def build_maps(n_parts, nodes, rng):
    beg, end = {}, {}
    for i in range(n_parts):
        name = f"{i:02d}"
        b = rng.sample(nodes, 2)
        e = rng.sample(nodes, 2)
        beg[name] = {"primary": [b[0]], "replica": [b[1]]}
        end[name] = {"primary": [e[0]], "replica": [e[1]]}
    return pm(beg), pm(end)


@pytest.mark.parametrize("interrupt", [True, False])
@pytest.mark.parametrize("seed", range(5))
def test_random_pause_resume_stop_orderings(seed, interrupt):
    rng = random.Random(seed)
    nodes = ["a", "b", "c", "d"]
    beg, end = build_maps(8, nodes, rng)

    async def go():
        ops_log = []

        async def assign(stop_ch, node, partitions, states, ops):
            ops_log.append((node, tuple(partitions), tuple(ops)))
            await asyncio.sleep(0)  # yield to interleave control actions

        o = orchestrate_moves(
            MODEL,
            OrchestratorOptions(
                max_concurrent_partition_moves_per_node=rng.choice([1, 2, 3]),
                interrupt_on_first_feed=interrupt),
            nodes, beg, end, assign)

        stop_after = rng.randint(0, 40)
        actions = 0
        last = None
        pauses = resumes = 0
        async for progress in o.progress_ch():
            # Counter monotonicity.
            if last is not None:
                assert progress.tot_mover_assign_partition_ok >= \
                    last.tot_mover_assign_partition_ok
                assert progress.tot_run_supply_moves_loop >= \
                    last.tot_run_supply_moves_loop
            last = progress
            actions += 1
            r = rng.random()
            if r < 0.2:
                o.pause_new_assignments()
                pauses += 1
            elif r < 0.5:
                o.resume_new_assignments()
                resumes += 1
            if actions == stop_after:
                o.resume_new_assignments()  # stop while paused would wedge
                o.stop()
        # Stream closed; orchestrator must be fully wound down.
        assert last is not None
        assert last.tot_pause_new_assignments >= last.tot_resume_new_assignments
        return last

    last = asyncio.run(asyncio.wait_for(go(), timeout=30))
    assert last.tot_progress_close <= 1


def test_stop_storm_never_hangs():
    async def go():
        beg, end = build_maps(6, ["a", "b", "c"], random.Random(7))

        async def assign(stop_ch, node, partitions, states, ops):
            await asyncio.sleep(0)

        o = orchestrate_moves(
            MODEL, OrchestratorOptions(), ["a", "b", "c"], beg, end, assign)
        for _ in range(5):
            o.stop()
        async for _ in o.progress_ch():
            o.stop()
    asyncio.run(asyncio.wait_for(go(), timeout=15))


def test_ops_follow_per_partition_move_plans():
    rng = random.Random(42)
    nodes = ["a", "b", "c", "d"]
    beg, end = build_maps(10, nodes, rng)

    async def go():
        executed: dict[str, list] = {}

        def assign(stop_ch, node, partitions, states, ops):
            for p, s, op in zip(partitions, states, ops):
                executed.setdefault(p, []).append((node, s, op))

        o = orchestrate_moves(
            MODEL, OrchestratorOptions(max_concurrent_partition_moves_per_node=2),
            nodes, beg, end, assign)
        plans = {}
        o.visit_next_moves(lambda m: plans.update(
            {k: [(mv.node, mv.state, mv.op) for mv in v.moves]
             for k, v in m.items()}))
        async for _ in o.progress_ch():
            pass
        o.stop()
        # Every partition executed exactly its planned sequence, in order.
        for name, plan in plans.items():
            assert executed.get(name, []) == plan, (name, executed.get(name), plan)
    asyncio.run(asyncio.wait_for(go(), timeout=30))
