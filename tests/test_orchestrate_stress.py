"""Concurrency-ordering stress tests for the orchestrator.

The reference relies on `go test -race` plus channel discipline; the
asyncio analog (SURVEY.md §5) is hammering pause/resume/stop orderings and
interleavings against invariants:

- the progress stream always closes,
- counters are monotonic and pause/resume counts stay balanced,
- every executed op is one the move plan allows, in per-partition order,
- stop() mid-flight never hangs and never loses in-flight completions.
"""

import asyncio
import random

import pytest

from blance_tpu import Partition, PartitionModelState
from blance_tpu.orchestrate import (
    Chan,
    FaultPlan,
    MoveFailure,
    NodeFaults,
    OrchestratorOptions,
    orchestrate_moves,
)

MODEL = {
    "primary": PartitionModelState(priority=0, constraints=0),
    "replica": PartitionModelState(priority=0, constraints=1),
}


def pm(d):
    return {name: Partition(name, {s: list(ns) for s, ns in nbs.items()})
            for name, nbs in d.items()}


def build_maps(n_parts, nodes, rng):
    beg, end = {}, {}
    for i in range(n_parts):
        name = f"{i:02d}"
        b = rng.sample(nodes, 2)
        e = rng.sample(nodes, 2)
        beg[name] = {"primary": [b[0]], "replica": [b[1]]}
        end[name] = {"primary": [e[0]], "replica": [e[1]]}
    return pm(beg), pm(end)


@pytest.mark.parametrize("interrupt", [True, False])
@pytest.mark.parametrize("seed", range(5))
def test_random_pause_resume_stop_orderings(seed, interrupt):
    rng = random.Random(seed)
    nodes = ["a", "b", "c", "d"]
    beg, end = build_maps(8, nodes, rng)

    async def go():
        ops_log = []

        async def assign(stop_ch, node, partitions, states, ops):
            ops_log.append((node, tuple(partitions), tuple(ops)))
            await asyncio.sleep(0)  # yield to interleave control actions

        o = orchestrate_moves(
            MODEL,
            OrchestratorOptions(
                max_concurrent_partition_moves_per_node=rng.choice([1, 2, 3]),
                interrupt_on_first_feed=interrupt),
            nodes, beg, end, assign)

        stop_after = rng.randint(0, 40)
        actions = 0
        last = None
        pauses = resumes = 0
        stopped = False
        resumers: list[asyncio.Task] = []

        async def resume_soon():
            # An out-of-band controller: a pause with no eventual
            # resume wedges BY CONTRACT (the supplier revalidates
            # _pause_ch after every wake instead of escaping through
            # the stale-channel race it used to have), and a consumer
            # that only acts on progress events can starve itself —
            # exactly like a real app, resumes must not depend on
            # progress traffic while paused.
            await asyncio.sleep(0)
            o.resume_new_assignments()

        async for progress in o.progress_ch():
            # Counter monotonicity.
            if last is not None:
                assert progress.tot_mover_assign_partition_ok >= \
                    last.tot_mover_assign_partition_ok
                assert progress.tot_run_supply_moves_loop >= \
                    last.tot_run_supply_moves_loop
            last = progress
            actions += 1
            r = rng.random()
            if r < 0.2 and not stopped:
                o.pause_new_assignments()
                pauses += 1
                resumers.append(asyncio.ensure_future(resume_soon()))
            elif r < 0.5:
                o.resume_new_assignments()
                resumes += 1
            if actions == stop_after:
                stopped = True
                o.resume_new_assignments()  # stop while paused would wedge
                o.stop()
        for t in resumers:
            await t
        # Stream closed; orchestrator must be fully wound down.
        assert last is not None
        assert last.tot_pause_new_assignments >= last.tot_resume_new_assignments
        return last

    last = asyncio.run(asyncio.wait_for(go(), timeout=30))
    assert last.tot_progress_close <= 1


def test_stop_storm_never_hangs():
    async def go():
        beg, end = build_maps(6, ["a", "b", "c"], random.Random(7))

        async def assign(stop_ch, node, partitions, states, ops):
            await asyncio.sleep(0)

        o = orchestrate_moves(
            MODEL, OrchestratorOptions(), ["a", "b", "c"], beg, end, assign)
        for _ in range(5):
            o.stop()
        async for _ in o.progress_ch():
            o.stop()
    asyncio.run(asyncio.wait_for(go(), timeout=15))


def _ft_options(**kw):
    base = dict(move_timeout_s=0.25, max_retries=2, backoff_base_s=0.002,
                backoff_jitter=0.25, quarantine_after=2, probe_after_s=60.0)
    base.update(kw)
    return OrchestratorOptions(**base)


@pytest.mark.parametrize("seed", range(3))
def test_counters_monotonic_and_errors_append_only_under_faults(seed):
    """Injected faults must never make a progress counter regress, and
    the errors list must be append-only (every earlier snapshot a prefix
    of every later one) with MoveFailure entries only."""
    rng = random.Random(seed)
    nodes = ["a", "b", "c", "d"]
    beg, end = build_maps(10, nodes, rng)
    plan = FaultPlan(seed=seed, nodes={
        "b": NodeFaults(fail_rate=0.4),
        "c": NodeFaults(fail_rate=0.2),
    })

    async def go():
        async def assign(stop_ch, node, partitions, states, ops):
            await asyncio.sleep(0)

        o = orchestrate_moves(
            MODEL, _ft_options(), nodes, beg, end, plan.wrap(assign))
        last = None
        monotone = [f.name for f in
                    type(o._progress).__dataclass_fields__.values()
                    if f.name != "errors"]
        async for progress in o.progress_ch():
            if last is not None:
                for name in monotone:
                    assert getattr(progress, name) >= getattr(last, name), \
                        name
                # errors: append-only, earlier list is a prefix.
                assert progress.errors[:len(last.errors)] == last.errors
            assert all(isinstance(e, MoveFailure) for e in progress.errors)
            last = progress
        o.stop()
        return last, o

    last, o = asyncio.run(asyncio.wait_for(go(), timeout=30))
    assert last is not None
    assert last.tot_move_failures == len(o.move_failures())
    assert len(last.errors) == last.tot_move_failures


def test_pause_resume_during_retry_backoff():
    """Pause/resume while a mover sits in a retry backoff: the backoff
    finishes, the retry runs, and the orchestration completes with
    balanced pause/resume counters."""
    nodes = ["a", "b"]
    beg = pm({f"{i}": {"primary": ["a"], "replica": []} for i in range(4)})
    end = pm({f"{i}": {"primary": ["b"], "replica": []} for i in range(4)})
    # b's first 2 node-attempts fail, then it heals: guaranteed retries.
    plan = FaultPlan(seed=1, nodes={"b": NodeFaults(dead=True,
                                                    heal_after=2)})

    async def go():
        async def assign(stop_ch, node, partitions, states, ops):
            await asyncio.sleep(0)

        o = orchestrate_moves(
            MODEL,
            _ft_options(max_retries=4, backoff_base_s=0.02,
                        quarantine_after=0),
            nodes, beg, end, plan.wrap(assign))
        paused = False
        last = None
        async for progress in o.progress_ch():
            last = progress
            if not paused and progress.tot_mover_assign_partition_retry >= 1:
                o.pause_new_assignments()
                o.resume_new_assignments()
                paused = True
        o.stop()
        return last, paused

    last, paused = asyncio.run(asyncio.wait_for(go(), timeout=30))
    assert paused, "no retry was observed"
    assert last.tot_pause_new_assignments == 1
    assert last.tot_resume_new_assignments == 1
    assert last.tot_mover_assign_partition_retry >= 1
    # The healed node eventually accepted everything.
    assert last.tot_mover_assign_partition_ok >= 1


def test_stop_during_quarantine_never_hangs():
    """stop() right after a node trips into quarantine: the wind-down
    must complete even with batches queued for the dead node."""
    nodes = ["a", "b", "dead"]
    beg = pm({f"{i}": {"primary": ["a"], "replica": []} for i in range(8)})
    end = pm({f"{i}": {"primary": ["dead"], "replica": []} for i in range(8)})
    plan = FaultPlan(seed=4, nodes={"dead": NodeFaults(dead=True)})

    async def go():
        async def assign(stop_ch, node, partitions, states, ops):
            await asyncio.sleep(0)

        o = orchestrate_moves(
            MODEL, _ft_options(max_retries=1, quarantine_after=1),
            nodes, beg, end, plan.wrap(assign))
        last = None
        async for progress in o.progress_ch():
            last = progress
            if progress.tot_quarantine_trips >= 1:
                o.stop()
        return last

    last = asyncio.run(asyncio.wait_for(go(), timeout=30))
    assert last is not None
    assert last.tot_quarantine_trips >= 1
    assert last.tot_progress_close <= 1


# --- csp hardening: abandoned waiters (cancelled timed waits) ---------------


def test_chan_close_tolerates_cancelled_getter():
    """A getter whose awaiting task was cancelled (the shape a retry
    backoff's aborted stop-watch leaves behind) must not break close()."""

    async def go():
        ch = Chan()
        task = asyncio.ensure_future(ch.get())
        await asyncio.sleep(0)  # let it register
        task.cancel()
        try:
            await task
        except asyncio.CancelledError:
            pass
        ch.close()  # must not raise InvalidStateError
        assert await ch.get() == (None, False)

    asyncio.run(asyncio.wait_for(go(), timeout=10))


def test_chan_put_skips_cancelled_getter():
    """A put must rendezvous with a LIVE getter, not hand its item to an
    abandoned one (which would silently drop it)."""

    async def go():
        ch = Chan()
        g1 = asyncio.ensure_future(ch.get())
        await asyncio.sleep(0)
        g1.cancel()
        try:
            await g1
        except asyncio.CancelledError:
            pass
        g2 = asyncio.ensure_future(ch.get())
        await asyncio.sleep(0)
        await ch.put("x")
        assert await g2 == ("x", True)

    asyncio.run(asyncio.wait_for(go(), timeout=10))


def test_ops_follow_per_partition_move_plans():
    rng = random.Random(42)
    nodes = ["a", "b", "c", "d"]
    beg, end = build_maps(10, nodes, rng)

    async def go():
        executed: dict[str, list] = {}

        def assign(stop_ch, node, partitions, states, ops):
            for p, s, op in zip(partitions, states, ops):
                executed.setdefault(p, []).append((node, s, op))

        o = orchestrate_moves(
            MODEL, OrchestratorOptions(max_concurrent_partition_moves_per_node=2),
            nodes, beg, end, assign)
        plans = {}
        o.visit_next_moves(lambda m: plans.update(
            {k: [(mv.node, mv.state, mv.op) for mv in v.moves]
             for k, v in m.items()}))
        async for _ in o.progress_ch():
            pass
        o.stop()
        # Every partition executed exactly its planned sequence, in order.
        for name, plan in plans.items():
            assert executed.get(name, []) == plan, (name, executed.get(name), plan)
    asyncio.run(asyncio.wait_for(go(), timeout=30))
