"""Benchmark: batched TPU planner vs the sequential CPU greedy planner.

Measures TWO configs, both primary + 1 replica with rack rules and a warm
previous map with 5% of nodes removed (the realistic delta-rebalance
shape):

  - 100k partitions x  1k nodes  (continuity with earlier rounds)
  - 100k partitions x 10k nodes  (the BASELINE.json north-star shape)

The headline metric is the ON-DEVICE CONVERGED SOLVE of the north-star
config (jit-compiled, post-warmup, forced host sync) — encode/decode are
reported separately as phases of one end-to-end plan_next_map_tpu call,
so the artifact never conflates the two.  The CPU baseline is this repo's
own NATIVE C++ exact greedy planner (the strongest CPU implementation
available — the reference publishes no numbers, BASELINE.md); its
provenance, including any P-scaling, is recorded per config in the JSON.

The compiled Pallas min2/argmin kernel (the auction's hot op) is verified
against the XLA reference spelling on a real device batch before timing;
the result ships in the JSON as pallas/pallas_verified.

Observability (blance_tpu.obs): the run ends with a small end-to-end
plan -> moves -> orchestrate pipeline stage, and the emitted JSON carries
an "obs" block — per-phase span totals, counters (solver sweeps, engine
fallbacks), and histogram p50/p95 summaries including per-move latency.
``--trace-out PATH`` additionally captures every span into a Chrome
trace-event file (open in chrome://tracing or https://ui.perfetto.dev);
``--device-trace-dir DIR`` wraps the run in jax.profiler's device trace
over the same interval so host spans and TPU traces line up.

Prints ONE JSON line:
  {"metric", "value", "unit", "vs_baseline", "detail": {...}}
plus human-readable detail on stderr.
"""

import argparse
import json
import os
import statistics
import sys
import time

import numpy as np

# (P, N, headline?) — both rack rules + 5% node removal.  The HEADLINE
# config runs FIRST: the axon tunnel can wedge mid-session, and whatever
# completed before the wedge must include the number the round is judged
# on (every completed stage also persists to PROGRESS_PATH immediately).
CONFIGS = [
    (100_000, 10_000, True),  # north star (BASELINE.json)
    (100_000, 1_000, False),
]
RUNS = 4  # timed runs per config (min + median reported)
PY_GREEDY_P = 4_000  # python-greedy fallback measured here, scaled in P
CPU_TIMEOUT_S = 540  # budget for one full-size CPU baseline measurement


def _progress_path():
    """Anchored to this file, not the cwd — the driver may launch the
    bench from anywhere, and persistence landing in a scratch dir would
    defeat its purpose."""
    import os

    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "docs", "BENCH_progress.json")


def log(*args):
    print(*args, file=sys.stderr, flush=True)


def _ensure_virtual_devices(count=8):
    """Force a multi-device CPU host (the tests/conftest.py trick) so
    smoke/fallback runs exercise the mesh-sharded code paths (the fleet
    stage's batch axis).  Must run before jax first imports; a no-op
    when the flag is already set."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={count}"
        ).strip()


def first_line(e):
    """First line of an exception message, '' when the message is empty
    (a bare RuntimeError() must not crash the degradation path)."""
    return (str(e).splitlines() or [""])[0][:200]


def index_pct(xs, q):
    """Nearest-rank percentile of a lag list (index formula shared by
    the simulate and sched stages), rounded to ms; None when empty."""
    xs = sorted(xs)
    if not xs:
        return None
    return round(xs[min(int(q * len(xs)), len(xs) - 1)], 3)


def save_progress(detail, stage):
    """Persist everything measured so far.  The driver only captures the
    final stdout JSON line; a tunnel wedge between stages would otherwise
    eat every number already in hand, so each completed stage overwrites
    this file with the full detail tree (stage-stamped)."""
    import os

    path = _progress_path()
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            json.dump({"stage": stage, "time": time.strftime(
                "%Y-%m-%dT%H:%M:%S"), "detail": detail}, f, indent=1)
    except OSError as e:  # persistence is best-effort, never fatal
        log(f"save_progress failed: {e}")


def build_dense(P, N, seed=0):
    """Dense arrays for the rack-rule delta-rebalance shape."""
    rng = np.random.default_rng(seed)
    S, R = 2, 1
    prev = np.full((P, S, R), -1, np.int32)
    prev[:, 0, 0] = rng.integers(0, N, P)
    prev[:, 1, 0] = (prev[:, 0, 0] + 1 + rng.integers(0, N - 1, P)) % N
    pweights = np.ones(P, np.float32)
    nweights = np.ones(N, np.float32)
    valid = np.ones(N, bool)
    valid[rng.choice(N, N // 20, replace=False)] = False  # 5% nodes leave
    stickiness = np.full((P, S), 1.5, np.float32)
    gids = np.stack([np.arange(N, dtype=np.int32),
                     np.arange(N, dtype=np.int32) // 25,  # racks of 25
                     np.zeros(N, np.int32)])
    gid_valid = np.ones((3, N), bool)
    constraints = (1, 1)
    rules = ((), ((2, 1),))  # replica on another rack
    return (prev, pweights, nweights, valid, stickiness, gids, gid_valid,
            constraints, rules)


def audit(assign, valid, gids):
    """Violation counts straight off the solved assignment (the '0
    violations' evidence the artifact carries)."""
    a = np.asarray(assign)
    prim, repl = a[:, 0, 0], a[:, 1, 0]
    held = a[a >= 0]
    rack = gids[1]
    co_racked = int(((rack[np.clip(prim, 0, None)] ==
                      rack[np.clip(repl, 0, None)])
                     & (prim >= 0) & (repl >= 0)).sum())
    return {
        "unassigned_slots": int((a < 0).sum()),
        "on_removed_nodes": int((~valid[held]).sum()),
        "duplicates": int(((prim == repl) & (prim >= 0)).sum()),
        "co_racked_replicas": co_racked,
    }


def verify_pallas(N, seed=7):
    """Run the COMPILED Pallas kernel against the XLA oracle on a real
    device batch (ties included); returns (available, verified)."""
    import jax
    import jax.numpy as jnp
    from blance_tpu.ops.reduce2 import (
        min2_argmin_reference, pallas_available, priced_min2_argmin)

    if not pallas_available():
        return False, False
    rng = np.random.default_rng(seed)
    # Quantized scores force duplicate minima so tie-breaks are exercised.
    score = jnp.asarray(
        rng.integers(0, 50, (4096, N)).astype(np.float32) * 0.125)
    price = jnp.asarray(rng.integers(0, 8, N).astype(np.float32) * 0.25)
    b1, c1, s1 = (np.asarray(x) for x in priced_min2_argmin(score, price))
    b2, c2, s2 = (np.asarray(x) for x in
                  min2_argmin_reference(score + price[None, :]))
    ok = (np.array_equal(b1, b2) and np.array_equal(c1, c2)
          and np.array_equal(s1, s2))
    log(f"pallas kernel vs XLA oracle on device (4096x{N}): "
        f"{'bit-identical' if ok else 'MISMATCH'}")
    return True, bool(ok)


def _device_block(mon):
    """One stage's device-observatory summary for the artifact:
    per-entry compile counts from the stage-local monitor plus whatever
    (entry, bucket-shape) cost analyses have been published so far —
    the in-repo-verifiable device attribution the BENCH trajectory was
    missing (rounds comparable even when the driver-side tunnel is
    wedged, the BENCH_r04/r05 failure shape)."""
    from blance_tpu.obs import device as obs_device

    return {"compiles": mon.summary(),
            "cost": obs_device.cost_summaries()}


def bench_tpu(P, N, fused=False):
    """On-device converged solve: compile + RUNS timed runs + audit."""
    import jax.numpy as jnp
    from blance_tpu.obs import device as obs_device
    from blance_tpu.plan.tensor import solve_dense_converged

    (prev, pweights, nweights, valid, stickiness, gids, gid_valid,
     constraints, rules) = build_dense(P, N)
    dev_args = [jnp.asarray(a) for a in
                (prev, pweights, nweights, valid, stickiness, gids, gid_valid)]
    mode = "on" if fused else "off"
    tag = f"[{P}x{N}{' fused' if fused else ''}]"

    # block_until_ready is unreliable on the experimental axon platform, so
    # force completion with a small host copy ([P] primaries).
    # record=False in the timed loop: the obs sweeps read is one extra
    # scalar D2H round-trip, which would perturb ms-scale timings over the
    # tunnel.  The compile call records once, so the counter still moves.
    def run(record=False):
        out = solve_dense_converged(*dev_args, constraints, rules,
                                    fused_score=mode, record=record)
        np.asarray(out[:, 0, 0])
        return out

    with obs_device.CompileMonitor() as mon:
        t0 = time.perf_counter()
        out = run(record=True)
        compile_s = time.perf_counter() - t0
        log(f"{tag} compile+first-run: {compile_s:.2f}s")

        times = []
        for _ in range(RUNS):
            t0 = time.perf_counter()
            out = run()
            times.append(time.perf_counter() - t0)
    log(f"{tag} on-device solve: min {min(times)*1000:.1f}ms  runs: "
        f"{[f'{t*1000:.1f}' for t in times]}")

    counts = audit(out, valid, gids)
    log(f"{tag} audit: {counts}")
    assert counts["unassigned_slots"] == 0
    assert counts["on_removed_nodes"] == 0
    return {
        "compile_s": round(compile_s, 2),
        "solve_ms_min": round(min(times) * 1000, 2),
        "solve_ms_median": round(statistics.median(times) * 1000, 2),
        "solve_ms_runs": [round(t * 1000, 2) for t in times],
        "violations": counts,
        "device": _device_block(mon),
    }


def verify_fused_engine():
    """Contract-check the COMPILED fused score engine against the matrix
    engine on device at small scale: both audits clean, per-node load
    spread within +2.  Gates whether fused timed runs happen at all."""
    import jax.numpy as jnp
    from blance_tpu.ops.reduce2 import pallas_available
    from blance_tpu.plan.tensor import solve_dense_converged

    if not pallas_available():
        return False
    P, N = 4096, 512
    (prev, pweights, nweights, valid, stickiness, gids, gid_valid,
     constraints, rules) = build_dense(P, N, seed=3)
    dev = [jnp.asarray(a) for a in
           (prev, pweights, nweights, valid, stickiness, gids, gid_valid)]
    outs = {}
    for mode in ("off", "on"):
        try:
            a = np.asarray(solve_dense_converged(
                *dev, constraints, rules, fused_score=mode))
        except Exception as e:  # a kernel that won't lower must not
            log(f"fused-engine verify: mode={mode} failed to "  # kill the
                f"compile/run: {type(e).__name__}: "            # bench
                f"{first_line(e)}")
            return False
        counts = audit(a, valid, gids)
        if any(counts.values()):
            log(f"fused-engine verify: mode={mode} violations {counts}")
            return False
        outs[mode] = a
    spreads = {}
    for mode, a in outs.items():
        ids = a[a >= 0]
        loads = np.bincount(ids, minlength=N)[valid]
        spreads[mode] = int(loads.max() - loads.min())
    ok = spreads["on"] <= spreads["off"] + 2
    log(f"fused-engine verify @ {P}x{N}: clean audits, spreads "
        f"matrix={spreads['off']} fused={spreads['on']} -> "
        f"{'OK' if ok else 'REJECTED'}")
    return ok


def _make_map(P, N, seed=0):
    """PartitionMap + node list mirroring build_dense's shape."""
    from blance_tpu import Partition

    rng = np.random.default_rng(seed)
    nodes = [f"n{i:05d}" for i in range(N)]
    removed = [nodes[i] for i in
               rng.choice(N, N // 20, replace=False)]
    p_ids = rng.integers(0, N, P)
    r_ids = (p_ids + 1 + rng.integers(0, N - 1, P)) % N
    prev = {str(i): Partition(str(i), {"primary": [nodes[p_ids[i]]],
                                       "replica": [nodes[r_ids[i]]]})
            for i in range(P)}
    return prev, nodes, removed


def _rack_opts(nodes):
    from blance_tpu import HierarchyRule, PlanOptions

    hier = {n: f"r{i // 25}" for i, n in enumerate(nodes)}
    hier.update({f"r{i}": "z0" for i in range((len(nodes) + 24) // 25)})
    return PlanOptions(node_hierarchy=hier,
                       hierarchy_rules={"replica": [HierarchyRule(2, 1)]})


def bench_phases(P, N):
    """One end-to-end plan_next_map_tpu call with PhaseTimer: attributes
    wall-clock to encode / solve / decode (compile already warm from
    bench_tpu, same static shapes)."""
    from blance_tpu import model
    from blance_tpu.plan.tensor import plan_next_map_tpu
    from blance_tpu.utils.trace import PhaseTimer

    prev, nodes, removed = _make_map(P, N)
    m = model(primary=(0, 1), replica=(1, 1))
    # The map-derived encode can produce different static shapes (hierarchy
    # levels) than build_dense, so warm its compile separately; the timed
    # call below is the steady-state end-to-end cost.
    plan_next_map_tpu(prev, prev, nodes, removed, [], m, _rack_opts(nodes))
    timer = PhaseTimer()
    t0 = time.perf_counter()
    plan_next_map_tpu(prev, prev, nodes, removed, [], m,
                      _rack_opts(nodes), timer=timer)
    total = time.perf_counter() - t0
    phases = {name: round(timer.totals[name] * 1000, 1)
              for name in ("encode", "solve", "decode")
              if name in timer.totals}
    phases["total"] = round(total * 1000, 1)
    log(f"[{P}x{N}] end-to-end phases (ms): {phases}")
    return phases


def bench_pipeline(P=256, N=32):
    """End-to-end plan -> moves -> orchestrate at a small fixed size.

    This is the stage that exercises the moves and orchestrate layers, so
    a --trace-out trace carries spans from the whole pipeline (plan
    encode/solve/decode already come from bench_phases at bench scale)
    and the obs histograms gain per-move latency (orchestrate.move.exec
    with a no-op data plane: pure scheduling cost)."""
    import asyncio

    from blance_tpu import model
    from blance_tpu.orchestrate.orchestrator import (
        OrchestratorOptions, orchestrate_moves)
    from blance_tpu.plan.api import plan_next_map

    prev, nodes, removed = _make_map(P, N, seed=11)
    m = model(primary=(0, 1), replica=(1, 1))
    t0 = time.perf_counter()
    next_map, _warn = plan_next_map(
        prev, prev, nodes, removed, [], m, _rack_opts(nodes),
        backend="greedy")

    async def assign(stop_ch, node, partitions, states, ops):
        await asyncio.sleep(0)  # no data plane: scheduling cost only

    async def run():
        o = orchestrate_moves(
            m,
            OrchestratorOptions(device_diff=True,
                                interrupt_on_first_feed=False,
                                max_concurrent_partition_moves_per_node=4),
            nodes, prev, next_map, assign)
        events = 0
        final = None
        async for p in o.progress_ch():
            events += 1
            final = p
        o.stop()
        return events, final

    events, final = asyncio.run(run())
    total_ms = (time.perf_counter() - t0) * 1000
    log(f"[pipeline {P}x{N}] plan+diff+orchestrate: {total_ms:.0f}ms, "
        f"{final.tot_mover_assign_partition_ok} batches ok, "
        f"{events} progress events")
    return {"P": P, "N": N, "total_ms": round(total_ms, 1),
            "batches_ok": final.tot_mover_assign_partition_ok,
            "errors": len(final.errors),
            "progress_events": events}


def bench_chaos(P=96, N=12, seed=7, fail_rate=0.3):
    """Chaos stage: transition completion under a fixed injected fault
    rate (ISSUE 3).  A seeded FaultPlan makes one node dead and two
    flaky at ``fail_rate``; the fault-tolerant rebalance (deadlines +
    retries + quarantine + bounded recovery replans) must still land a
    complete map on the surviving nodes.  Reports wall-clock, retry/
    timeout/quarantine counters, recovery rounds, and whether the final
    reconstructed map is whole — the robustness headline."""
    from blance_tpu import Partition, model
    from blance_tpu.obs import Recorder, use_recorder
    from blance_tpu.orchestrate import FaultPlan, NodeFaults
    from blance_tpu.orchestrate.orchestrator import OrchestratorOptions
    from blance_tpu.rebalance import rebalance

    nodes = [f"n{i:03d}" for i in range(N)]
    live = nodes[:-1]
    dead = nodes[-1]
    m = model(primary=(0, 1), replica=(1, 1))
    beg = {
        f"{i:04d}": Partition(f"{i:04d}", {
            "primary": [live[i % len(live)]],
            "replica": [live[(i + 1) % len(live)]]})
        for i in range(P)
    }
    plan = FaultPlan(seed=seed, nodes={
        dead: NodeFaults(dead=True),
        nodes[0]: NodeFaults(fail_rate=fail_rate),
        nodes[1]: NodeFaults(fail_rate=fail_rate),
    })

    async def assign(stop_ch, node, partitions, states, ops):
        import asyncio

        await asyncio.sleep(0)

    rec = Recorder()
    t0 = time.perf_counter()
    with use_recorder(rec):
        result = rebalance(
            m, beg, nodes, [], [dead], plan.wrap(assign),
            orchestrator_options=OrchestratorOptions(
                move_timeout_s=0.25, max_retries=4, backoff_base_s=0.002,
                quarantine_after=3, probe_after_s=60.0),
            max_recovery_rounds=3, backend="greedy")
    total_ms = (time.perf_counter() - t0) * 1000

    complete = all(
        len(p.nodes_by_state.get("primary", [])) == 1
        and len(p.nodes_by_state.get("replica", [])) == 1
        for p in result.achieved_map.values())
    slo = result.slo
    out = {
        "P": P, "N": N, "seed": seed, "fail_rate": fail_rate,
        "total_ms": round(total_ms, 1),
        "complete": complete,
        "failures": len(result.failures),
        "recovery_rounds": len(result.rounds) - 1,
        "quarantined": result.quarantined_nodes,
        "injected": dict(plan.injected),
        "retries": rec.counters.get("orchestrate.retries", 0),
        "timeouts": rec.counters.get("orchestrate.timeouts", 0),
        "quarantine_trips": rec.counters.get(
            "orchestrate.quarantine_trips", 0),
        # Online SLO accounting (obs/slo.py): the live gauges' final
        # reading — availability/churn/lag plus per-node quarantine
        # exposure — as streamed on the exposition endpoint mid-run.
        "slo": {
            "availability": round(slo.availability, 6),
            "churn_ratio": round(slo.churn_ratio, 4),
            "convergence_lag_ms": round(slo.convergence_lag_s * 1000, 2),
            "moves_executed": slo.moves_executed,
            "moves_failed": slo.moves_failed,
            "min_moves": slo.min_moves,
            "quarantine_exposure_s": {
                n: round(v, 4)
                for n, v in sorted(slo.quarantine_exposure_s.items())},
        },
    }
    log(f"[chaos {P}x{N}] complete={complete} failures={out['failures']} "
        f"retries={out['retries']:.0f} trips={out['quarantine_trips']:.0f} "
        f"recovery_rounds={out['recovery_rounds']} "
        f"avail={out['slo']['availability']} "
        f"churn={out['slo']['churn_ratio']} in {total_ms:.0f}ms")
    return out


def bench_simulate(seed=7, days=1.0):
    """Continuous-rebalance simulator stage (docs/SIMULATOR.md): one
    seeded mixed-fault scenario (daily churn, spot preemptions, a zone
    flap, hot-tenant drift, overlapping deltas) replayed under the
    DeterministicLoop virtual clock.  Reports the horizon SLO account —
    time-weighted availability, churn vs the offline-optimal single
    plan, p50/p95 per-incident convergence lag — plus the simulator's
    own throughput headline: virtual sim-seconds per wall-second."""
    from blance_tpu.testing.scenarios import mixed_week
    from blance_tpu.testing.simulate import run_scenario

    scn = mixed_week(seed, days=days)
    r = run_scenario(scn)
    lags = r.convergence_lags

    def pct(q):
        return index_pct(lags, q)

    s = r.summary
    out = {
        "scenario": r.scenario, "seed": seed, "days": days,
        "deltas": r.deltas, "rebalances": r.rebalances,
        "superseded": r.superseded, "degraded": r.degraded,
        "unconverged": r.unconverged,
        "complete": r.complete,
        "availability": round(s.availability, 6),
        "time_weighted_availability": round(
            s.time_weighted_availability, 6),
        "violation_s": round(s.violation_s, 3),
        "moves_executed": s.moves_executed,
        "offline_min_moves": r.offline_min_moves,
        "churn_vs_offline": (round(r.churn_vs_offline, 3)
                             if r.churn_vs_offline is not None else None),
        "convergence_lag_s": {"p50": pct(0.50), "p95": pct(0.95),
                              "n": len(lags)},
        "unscripted_drops": len(r.unscripted_drops),
        "loop_steps": r.steps,
        "wall_s": round(r.wall_s, 3),
        "sim_s_per_wall_s": round(r.horizon_s / max(r.wall_s, 1e-9)),
    }
    log(f"[simulate {r.scenario} seed={seed} {days:g}d] "
        f"complete={out['complete']} "
        f"tw_avail={out['time_weighted_availability']} "
        f"churn={out['churn_vs_offline']} "
        f"lag p50/p95={out['convergence_lag_s']['p50']}/"
        f"{out['convergence_lag_s']['p95']}s "
        f"superseded={out['superseded']} "
        f"{out['sim_s_per_wall_s']}x sim-s/wall-s")
    return out


def bench_costmodel(P=128, N=10, seed=5, fail_rate=0.25):
    """Cost-model stage: calibrate per-(node, op) EWMA move costs from
    the move-lifecycle spans of a chaos rebalance with a heterogeneous
    data plane, and score the model's predicted-vs-actual relative
    error online (each update falsifies the prediction that preceded
    it).  Also round-trips the model through its JSON persistence —
    the exact artifact ROADMAP item 2's critical-path scheduler loads."""
    import asyncio
    import tempfile

    from blance_tpu import Partition, model
    from blance_tpu.obs import CostModel, Recorder, use_recorder
    from blance_tpu.orchestrate import FaultPlan, NodeFaults
    from blance_tpu.orchestrate.orchestrator import OrchestratorOptions
    from blance_tpu.rebalance import rebalance

    nodes = [f"n{i:03d}" for i in range(N)]
    m = model(primary=(0, 1), replica=(1, 1))
    beg = {
        f"{i:04d}": Partition(f"{i:04d}", {
            "primary": [nodes[i % (N - 1)]],
            "replica": [nodes[(i + 1) % (N - 1)]]})
        for i in range(P)
    }
    plan = FaultPlan(seed=seed, nodes={
        nodes[0]: NodeFaults(fail_rate=fail_rate),
    })

    # Heterogeneous per-(node, op) latency: node index sets the tier,
    # op kind scales it — the structure the EWMA table must learn.
    async def assign(stop_ch, node, partitions, states, ops):
        tier = 1 + int(node[1:]) % 3
        per_op = {"promote": 0.5, "demote": 0.5, "add": 1.0, "del": 0.25}
        await asyncio.sleep(
            0.002 * tier * max(per_op.get(op, 1.0) for op in ops))

    rec = Recorder()
    cm = CostModel(alpha=0.3, recorder=rec)
    rec.add_sink(cm)
    t0 = time.perf_counter()
    with use_recorder(rec):
        rebalance(
            m, beg, nodes, [nodes[1]], [], plan.wrap(assign),
            orchestrator_options=OrchestratorOptions(
                move_timeout_s=1.0, max_retries=3, backoff_base_s=0.001,
                quarantine_after=0),
            backend="greedy")
    total_ms = (time.perf_counter() - t0) * 1000

    cal = cm.calibration()
    # Persistence round trip: the scheduler-facing contract is that a
    # reloaded model predicts exactly what the live one does.
    with tempfile.NamedTemporaryFile(suffix=".json", delete=False) as f:
        path = f.name
    cm.save(path)
    reloaded = CostModel.load(path)
    probes = list(cm.estimates())[:16] + [("never-seen", "add")]
    roundtrip_ok = all(
        cm.predict(n, op) == reloaded.predict(n, op) for n, op in probes)
    os.unlink(path)

    out = {
        "P": P, "N": N, "seed": seed, "total_ms": round(total_ms, 1),
        "observations": cal["observations"],
        "scored": cal["scored"],
        "estimates": cal["estimates"],
        "p50_rel_err": round(cal.get("p50_rel_err", float("nan")), 4),
        "p95_rel_err": round(cal.get("p95_rel_err", float("nan")), 4),
        "roundtrip_ok": roundtrip_ok,
    }
    log(f"[costmodel {P}x{N}] {out['observations']} obs, "
        f"{out['estimates']} (node,op) estimates, p50 rel err "
        f"{out['p50_rel_err']}, p95 {out['p95_rel_err']}, "
        f"roundtrip_ok={roundtrip_ok} in {total_ms:.0f}ms")
    return out


def bench_sched(seed=41):
    """Sched stage (ISSUE 12): the critical-path scheduled move order vs
    the legacy app-weight order at EXACTLY equal churn, scored on the
    two scenario families the scheduler was built for — ``hetero_drain``
    (one slow node, heterogeneous mover latencies: the showcase) and the
    ``mixed_week`` soak.  Both runs replay under the DeterministicLoop
    virtual clock, so every number here is exact and replayable, and the
    committed ``hetero_drain`` trace is regenerated byte-for-byte as the
    drift gate.

    The identity half of the contract — same final map, same move set,
    only the clock changes — is asserted, not just reported; ``gates``
    collects every pass/fail the perf-smoke tier checks."""
    import dataclasses

    from blance_tpu.testing.scenarios import hetero_drain, mixed_week
    from blance_tpu.testing.simulate import run_scenario

    def p95(lags):
        return index_pct(lags, 0.95)

    def compare(scn, skip_incidents=0):
        """Run one scenario legacy vs critical-path; the first
        ``skip_incidents`` incidents are the cost model's calibration
        pass (identical either way) and leave the makespan score."""
        t0 = time.perf_counter()
        leg = run_scenario(scn)
        crit = run_scenario(
            dataclasses.replace(scn, scheduler="critical_path"))
        wall = time.perf_counter() - t0
        leg_map = {k: v.nodes_by_state for k, v in leg.final_map.items()}
        crit_map = {k: v.nodes_by_state
                    for k, v in crit.final_map.items()}
        leg_lags = leg.summary.first_converged_lags[skip_incidents:]
        crit_lags = crit.summary.first_converged_lags[skip_incidents:]
        return {
            "scenario": scn.name, "seed": scn.seed,
            "deltas": leg.deltas,
            "identical_final_map": leg_map == crit_map,
            "equal_churn": (leg.summary.moves_executed
                            == crit.summary.moves_executed),
            "moves_executed": leg.summary.moves_executed,
            "moves_executed_scheduled": crit.summary.moves_executed,
            "legacy": {
                "makespan_p95_s": p95(leg_lags),
                "makespan_total_s": round(sum(leg_lags), 3),
                "convergence_lag_p95_s": p95(leg.convergence_lags),
            },
            "critical_path": {
                "makespan_p95_s": p95(crit_lags),
                "makespan_total_s": round(sum(crit_lags), 3),
                "convergence_lag_p95_s": p95(crit.convergence_lags),
            },
            "wall_s": round(wall, 3),
        }, crit

    hetero, hetero_crit = compare(hetero_drain(seed), skip_incidents=1)
    week, _ = compare(mixed_week(7))

    # The committed replay trace is the CRITICAL-PATH account of the
    # hetero_drain family: any drift in scheduler arithmetic (ranks,
    # lane assignment, reschedule timing) shows up as a byte diff.
    trace_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "tests", "traces",
                              f"sim_hetero_drain_s{seed}.json")
    try:
        with open(trace_path) as f:
            replay_ok = f.read() == hetero_crit.log_text()
    except OSError:
        replay_ok = False

    gates = {
        "hetero_identical_final_map": hetero["identical_final_map"],
        "hetero_equal_churn": hetero["equal_churn"],
        # The headline: scheduled order must strictly beat app-weight
        # order on the heterogeneous family's post-warmup makespan p95.
        "hetero_makespan_win": (
            hetero["critical_path"]["makespan_p95_s"]
            < hetero["legacy"]["makespan_p95_s"]),
        "hetero_trace_replay": replay_ok,
        # The soak is fault-HEAVY: move order changes which moves land
        # inside flaky windows, so the fault draws (and thus the exact
        # retry churn) legitimately differ — the exact-equality identity
        # claim lives on the fault-free hetero family and in the chaos
        # tests with deterministic (dead-node) faults.  Here the gates
        # are one-sided: scheduling must never BUY makespan with extra
        # churn (at most 2% more moves) nor LENGTHEN the week's tail.
        "week_no_extra_churn": (
            week["moves_executed_scheduled"]
            <= 1.02 * week["moves_executed"]),
        "week_no_regression": (
            week["critical_path"]["makespan_p95_s"]
            <= week["legacy"]["makespan_p95_s"]),
    }
    out = {"hetero_drain": hetero, "mixed_week": week, "gates": gates,
           "pass": all(gates.values())}
    log(f"[sched hetero_drain seed={seed}] makespan p95 "
        f"{hetero['legacy']['makespan_p95_s']}s legacy -> "
        f"{hetero['critical_path']['makespan_p95_s']}s scheduled, "
        f"equal_churn={hetero['equal_churn']} "
        f"identical_map={hetero['identical_final_map']} "
        f"replay_ok={replay_ok}; mixed_week p95 "
        f"{week['legacy']['makespan_p95_s']}s -> "
        f"{week['critical_path']['makespan_p95_s']}s "
        f"pass={out['pass']}")
    return out


def bench_fleet(B=64):
    """Fleet stage: batched multi-tenant bucket-class solves vs the
    sequential single-problem loop (ISSUE 7).

    B small tenant indexes with mixed sizes across two shape-bucket
    classes solve three ways: per tenant through the existing single-
    problem path (solve_converged_resilient on the same padded arrays —
    the loop a fleet replan runs today), as fleet batches (one vmapped
    device dispatch per bucket class, batch axis sharded over the
    mesh), and through the asyncio plan service (request coalescing,
    per-tenant carry cache).  Reports solves/sec both ways, the
    speedup, per-tenant bit-identity (the fleet contract), and the
    service's p50/p99 admission-to-result latency."""
    from blance_tpu.plan.fleet import TenantProblem, batch_class_of

    def tenant(i):
        # Mixed sizes spanning two bucket classes: the [16, 32) octave
        # buckets in steps of 2, so P 17/18 -> class 18 and 19/20 ->
        # class 20 (cbgt/FTS-style small per-index plans).
        P = 17 + (i % 4)
        N = 8
        rng = np.random.default_rng(1000 + i)
        S, R = 2, 1
        prev = np.full((P, S, R), -1, np.int32)
        prev[:, 0, 0] = rng.integers(0, N, P)
        prev[:, 1, 0] = (prev[:, 0, 0] + 1 + rng.integers(0, N - 1, P)) % N
        return TenantProblem(
            key=f"tenant-{i:03d}", prev=prev,
            partition_weights=np.ones(P, np.float32),
            node_weights=np.ones(N, np.float32),
            valid_node=np.ones(N, bool),
            stickiness=np.full((P, S), 1.5, np.float32),
            gids=np.stack([np.arange(N, dtype=np.int32),
                           np.arange(N, dtype=np.int32) // 4,
                           np.zeros(N, np.int32)]),
            gid_valid=np.ones((3, N), bool),
            constraints=(1, 1), rules=((), ((2, 1),)))

    from blance_tpu.obs import device as obs_device

    tenants = [tenant(i) for i in range(B)]
    classes = sorted({(k.p, k.n) for k in map(batch_class_of, tenants)})
    # Same leak discipline as bench_delta_replan: the stage may fail and
    # be survived by _run_benchmarks, so the tap must come down with it.
    mon = obs_device.CompileMonitor().install()
    try:
        return _bench_fleet_measured(B, tenants, classes, mon)
    finally:
        mon.uninstall()


def _bench_fleet_measured(B, tenants, classes, mon):
    import asyncio

    import jax
    import jax.numpy as jnp
    from blance_tpu.core.encode import pad_problem_arrays
    from blance_tpu.parallel.sharded import make_mesh
    from blance_tpu.plan.fleet import batch_class_of, solve_fleet
    from blance_tpu.plan.service import PlanService
    from blance_tpu.plan.tensor import (
        resolve_default_fused_score, solve_converged_resilient)

    def solve_seq(t):
        # The existing single-problem path on the SAME padded arrays +
        # real-P fill denominator, so identity is checkable and the
        # comparison is one-dispatch-per-tenant vs one-per-class.
        k = batch_class_of(t)
        arrs = pad_problem_arrays(
            t.prev, t.partition_weights, t.node_weights, t.valid_node,
            t.stickiness, t.gids, t.gid_valid, k.p, k.n)
        out, _eng = solve_converged_resilient(
            *[jnp.asarray(a) for a in arrs], t.constraints, t.rules,
            max_iterations=10,
            mode=resolve_default_fused_score(k.p, k.n),
            allow_fallback=False, context="bench.fleet.sequential",
            p_real=jax.device_put(np.float32(t.prev.shape[0])))
        return np.asarray(out)[:t.prev.shape[0]]

    # Batch-axis mesh: all devices on an accelerator; on a cpu host the
    # virtual devices share the physical cores, so cap the shard count
    # at the core count (8 virtual shards on 2 cores just context-
    # switch — measured slower than 2).
    n_dev = len(jax.devices())
    if jax.default_backend() == "cpu":
        n_dev = min(n_dev, os.cpu_count() or 1)
    mesh = make_mesh(n_dev) if n_dev > 1 else None

    # Warm both paths' compiles, and pin the contract: batched results
    # must be bit-identical to the per-tenant sequential solves.
    seq_outs = [solve_seq(t) for t in tenants]
    fleet_res = solve_fleet(tenants, mesh=mesh)
    identical = all(np.array_equal(a, r.assign)
                    for a, r in zip(seq_outs, fleet_res))
    assert identical, "fleet batch diverged from sequential solves"

    reps = 3
    seq_s = min(_timed(lambda: [solve_seq(t) for t in tenants])
                for _ in range(reps))
    fleet_s = min(_timed(lambda: solve_fleet(tenants, mesh=mesh))
                  for _ in range(reps))

    # The asyncio front door: submit all B concurrently, coalesced into
    # per-class batches within the admission window.
    async def drive():
        svc = PlanService(admission_window_s=0.005, mesh=mesh,
                          max_pending=max(B, 64))
        await svc.start()
        lat = []

        async def one(t):
            t0 = time.perf_counter()
            r = await svc.submit(t)
            lat.append(time.perf_counter() - t0)
            return r

        t0 = time.perf_counter()
        results = await asyncio.gather(*[one(t) for t in tenants])
        total = time.perf_counter() - t0
        await svc.stop()
        ok = all(np.array_equal(a, r.assign)
                 for a, r in zip(seq_outs, results))
        return total, sorted(lat), ok

    service_s, lat, service_identical = asyncio.run(drive())
    mon.uninstall()

    def pct(q):
        return lat[min(int(q * (len(lat) - 1)), len(lat) - 1)]

    out = {
        "tenants": B,
        "device": _device_block(mon),
        "classes": [f"{p}x{n}" for p, n in classes],
        "mesh_devices": 1 if mesh is None
        else int(np.prod(mesh.devices.shape)),
        "seq_ms": round(seq_s * 1000, 1),
        "fleet_ms": round(fleet_s * 1000, 1),
        "speedup": round(seq_s / fleet_s, 2),
        "solves_per_s_seq": round(B / seq_s, 1),
        "solves_per_s_fleet": round(B / fleet_s, 1),
        "identical": identical,
        "service_ms": round(service_s * 1000, 1),
        "service_identical": service_identical,
        "admission_p50_ms": round(pct(0.50) * 1000, 2),
        "admission_p99_ms": round(pct(0.99) * 1000, 2),
    }
    log(f"[fleet {B} tenants, classes {out['classes']}] "
        f"seq {out['seq_ms']}ms ({out['solves_per_s_seq']}/s) vs fleet "
        f"{out['fleet_ms']}ms ({out['solves_per_s_fleet']}/s) = "
        f"{out['speedup']}x, identical={identical}; service "
        f"{out['service_ms']}ms p50/p99 admission "
        f"{out['admission_p50_ms']}/{out['admission_p99_ms']}ms")
    return out


def _bench_encode_host_costs(P=4096, n_nodes=16, cycles=30, reps=3):
    """Direct planner-layer A/B at a scale where O(cluster) host encode
    matters: one resident and one full-re-encode ServicePlanner drive
    identical warm converge cycles (weight drift + node fail/strip +
    re-add) against private inline services on the DeterministicLoop,
    and the planners' own host_phase clocks time exactly the
    encode/decode halves the residency layer changed — no simulator or
    data-plane wall-clock in the measurement.  Partition weights are
    set for every partition, so the baseline pays encode_problem's
    O(P) Python weight/stickiness loops per cycle while the resident
    path dict-diffs the 4 drifted rows.  Returns the phase totals +
    the bit-identity verdict."""
    import asyncio

    from blance_tpu.core.types import Partition, model
    from blance_tpu.fleetloop import ServicePlanner
    from blance_tpu.obs import Recorder, use_recorder
    from blance_tpu.plan.service import PlanService
    from blance_tpu.rebalance import _strip_nodes
    from blance_tpu.testing.sched import DeterministicLoop, FifoPolicy

    import gc

    mdl = model(primary=(0, 1), replica=(1, 1))
    nodes = [f"n{i:02d}" for i in range(n_nodes)]
    pmap = {}
    for i in range(P):
        p = f"p{i:05d}"
        pmap[p] = Partition(p, {"primary": [nodes[i % n_nodes]],
                                "replica": [nodes[(i + 1) % n_nodes]]})
    base_weights = {f"p{i:05d}": 2 for i in range(P)}

    def one_rep():
        loop = DeterministicLoop(FifoPolicy(), max_steps=20_000_000)
        rec = Recorder(clock=loop.time)
        weights = dict(base_weights)

        async def drive():
            from blance_tpu.core.types import PlanOptions

            svc_r = PlanService(admission_window_s=0.0,
                                inline_solve=True, recorder=rec,
                                batch_floor=1)
            svc_b = PlanService(admission_window_s=0.0,
                                inline_solve=True, recorder=rec,
                                batch_floor=1)
            await svc_r.start()
            await svc_b.start()
            pr = ServicePlanner("t", svc_r, recorder=rec)
            pb = ServicePlanner("t", svc_b, recorder=rec,
                                encode_residency=False)
            cur_r = pmap
            cur_b = {k: p.copy() for k, p in pmap.items()}
            removes: list = []
            identical = True
            for c in range(cycles):
                # The steady-state mix the ISSUE's claim is about:
                # one abrupt fail + strip episode (with its re-add),
                # periodic small weight drift, and mostly converged
                # repeat cycles — per-cycle cost should track the
                # DELTA size, and the one big re-place episode is a
                # genuinely big delta on both sides.
                if c == 3:
                    dark = nodes[0]
                    removes = [dark]
                    before_r, before_b = cur_r, cur_b
                    cur_r = _strip_nodes(cur_r, {dark})
                    cur_b = _strip_nodes(cur_b, {dark})
                    pr.notify_strip({dark}, before_r, cur_r)
                elif c == 6:
                    removes = []
                elif c % 5 == 1:
                    for j in range(4):
                        weights[f"p{(c * 97 + j * 31) % P:05d}"] = \
                            2 + (c + j) % 7
                opts = PlanOptions(partition_weights=dict(weights))
                mr, _w = await pr.plan_cycle(cur_r, nodes, removes,
                                             mdl, opts)
                mb, _w = await pb.plan_cycle(cur_b, nodes, removes,
                                             mdl, opts)
                identical = identical and mr.keys() == mb.keys() \
                    and all(mr[k].nodes_by_state == mb[k].nodes_by_state
                            for k in mr)
                cur_r, cur_b = mr, mb
            await svc_r.stop()
            await svc_b.stop()
            return identical, pr.host_phase, pb.host_phase

        with use_recorder(rec):
            return loop.run_until_complete(drive())

    # Min-of-reps per side, GC parked during the timed window: the two
    # planners allocate millions of short-lived map objects per rep, so
    # collector pauses otherwise land stochastically inside the phase
    # clocks (observed 10x swings on identical deterministic work) and
    # the ratio — not just the absolute — gets distorted.
    identical = True
    best_r: dict = {}
    best_b: dict = {}
    for _ in range(max(int(reps), 1)):
        gc.collect()
        gc.disable()
        try:
            ok, ph_r, ph_b = one_rep()
        finally:
            gc.enable()
        identical = identical and ok
        if not best_r or sum(ph_r.values()) < sum(best_r.values()):
            best_r = dict(ph_r)
        if not best_b or sum(ph_b.values()) < sum(best_b.values()):
            best_b = dict(ph_b)
    res_ms = sum(best_r.values()) * 1000
    base_ms = sum(best_b.values()) * 1000
    return {
        "P": P, "nodes": n_nodes, "cycles": cycles, "reps": reps,
        "identical": bool(identical),
        "resident_host_ms": round(res_ms, 2),
        "full_reencode_host_ms": round(base_ms, 2),
        "resident_encode_ms": round(best_r["encode"] * 1000, 2),
        "full_reencode_encode_ms": round(best_b["encode"] * 1000, 2),
        "resident_decode_ms": round(best_r["decode"] * 1000, 2),
        "full_reencode_decode_ms": round(best_b["decode"] * 1000, 2),
        "host_speedup": round(base_ms / max(res_ms, 1e-9), 2),
    }


def bench_fleet_loop(tenants=8, seed=5):
    """Fleet-of-loops stage (ISSUE 13 + ISSUE 14, docs/FLEET.md): N
    tenants' CONTINUOUS rebalance loops — debounce, converge cycles,
    warm carries — multiplexed over one shared plan service, coalesced
    converge cycles vs the sequential loop-per-tenant baseline (same
    code path, zero admission window, max_batch=1) on the same seeded
    multi-tenant scenario under the DeterministicLoop virtual clock.

    The gate: identical per-tenant final maps, equal executed moves
    (churn) and equal availability across the two modes, strictly fewer
    device dispatches coalesced, and higher converge-cycles/sec
    wall-clock throughput.  Both modes are warmed first so throughput
    compares steady-state cycle cost, not XLA compile time.

    Encode-residency A/B (ISSUE 14): the same coalesced scenario runs
    with residency OFF (full re-encode per cycle) on BIGGER tenants so
    the host-encode share is visible; the stage reports the per-cycle
    phase split (encode / decode / device / orchestrate+other host
    work, plus the virtual admission latency) for both, and gates:
    byte-identical event logs (residency is a pure perf change), zero
    unattributed full re-encodes on warm cycles (``encode_cold ==
    tenants + demotions + evictions`` — the steady-state flat-line),
    warm patch bytes bounded by the patched-row count + scalar slack,
    and a smaller encode share + at least as many converge-cycles/sec
    with residency on."""
    from blance_tpu.testing.fleetsim import run_fleet_scenario
    from blance_tpu.testing.scenarios import fleet_zone_outage

    scn = fleet_zone_outage(seed=seed, tenants=tenants)
    run_fleet_scenario(scn)  # warm the coalesced-mode programs
    run_fleet_scenario(scn, coalesce=False)  # and the B=1 classes
    # Min-of-3 wall-clock per mode (each run is deterministic in every
    # VIRTUAL quantity; only wall_s is host-dependent and CI-noisy).
    co_runs = [run_fleet_scenario(scn) for _ in range(3)]
    seq_runs = [run_fleet_scenario(scn, coalesce=False)
                for _ in range(3)]
    co, seq = co_runs[0], seq_runs[0]
    co.wall_s = min(r.wall_s for r in co_runs)
    seq.wall_s = min(r.wall_s for r in seq_runs)

    def nbs(maps):
        return {t: {k: {s: list(ns)
                        for s, ns in p.nodes_by_state.items()}
                    for k, p in m.items()}
                for t, m in maps.items()}

    identical = nbs(co.final_maps) == nbs(seq.final_maps)
    equal_churn = co.fleet.moves_executed == seq.fleet.moves_executed
    equal_slo = (
        co.fleet.availability_min == seq.fleet.availability_min and
        {k: s.availability for k, s in co.summaries.items()} ==
        {k: s.availability for k, s in seq.summaries.items()})
    co_cps = co.cycles / max(co.wall_s, 1e-9)
    seq_cps = seq.cycles / max(seq.wall_s, 1e-9)
    # -- encode-residency A/B (ISSUE 14): bigger tenants, resident vs
    # full-re-encode baseline on the SAME coalesced scenario.
    big = fleet_zone_outage(seed=seed, tenants=tenants,
                            partitions=(48, 64))
    run_fleet_scenario(big)  # warm the bigger bucket classes
    run_fleet_scenario(big, encode_residency=False)
    res_runs = [run_fleet_scenario(big) for _ in range(3)]
    base_runs = [run_fleet_scenario(big, encode_residency=False)
                 for _ in range(3)]
    res = min(res_runs, key=lambda r: r.wall_s)
    base = min(base_runs, key=lambda r: r.wall_s)

    def phases(r):
        other = max(r.wall_s - sum(r.phase_wall.values()), 0.0)
        out = {k: round(v * 1000, 2) for k, v in r.phase_wall.items()}
        out["orchestrate_other"] = round(other * 1000, 2)
        out["encode_share"] = round(
            r.phase_wall.get("encode", 0.0) / max(r.wall_s, 1e-9), 4)
        return out

    res_cps = res.cycles / max(res.wall_s, 1e-9)
    base_cps = base.cycles / max(base.wall_s, 1e-9)
    # Patch bytes bounded by the patched-row count + scalar slack: the
    # per-row ceiling is a strip/adopt row's prev scatter + counts row
    # (S*R*4 + S*8) + a weight row (4 + 4*S); node-add columns and
    # dark-set flips ride the per-warm-cycle scalar slack.  S/R derive
    # from the scenario's own tenant model so a replica-count change
    # moves the bound with it.
    from blance_tpu.testing.fleetsim import tenant_model

    mdl = tenant_model(big.tenants[0])
    s_dim = len(mdl)
    r_dim = max(st.constraints for st in mdl.values())
    row_cap = s_dim * r_dim * 4 + s_dim * 8 + 4 + 4 * s_dim
    bytes_bounded = res.encode_patch_bytes <= (
        res.encode_patch_rows * row_cap + 256 * max(res.encode_warm, 1))
    # Two-sided attribution bound: every counted cold (re)established
    # resident state, so cold >= one per tenant, and every cold beyond
    # that was preceded by a counted demotion/eviction (a demotion on a
    # tenant's FINAL cycle has no rebuilding cold, hence <=).
    attributable = res.tenants + sum(res.encode_demotions.values()) + \
        sum(res.encode_evictions.values())
    cold_attributed = res.tenants <= res.encode_cold <= attributable
    residency = {
        "tenants": tenants, "partitions": [48, 64],
        "log_identical": res.log_text() == base.log_text(),
        "encode_cold": res.encode_cold,
        "encode_warm": res.encode_warm,
        "encode_demotions": res.encode_demotions,
        "encode_evictions": res.encode_evictions,
        "cold_attributed": cold_attributed,
        "decode_full": res.decode_full,
        "decode_patch": res.decode_patch,
        "encode_patch_rows": res.encode_patch_rows,
        "encode_patch_bytes": res.encode_patch_bytes,
        "patch_bytes_bounded": bytes_bounded,
        "wall_s_resident": round(res.wall_s, 3),
        "wall_s_full_reencode": round(base.wall_s, 3),
        "cycles_per_s_resident": round(res_cps, 1),
        "cycles_per_s_full_reencode": round(base_cps, 1),
        "phases_resident": phases(res),
        "phases_full_reencode": phases(base),
    }
    # The perf half of the gate is the DIRECT planner-layer A/B at a
    # scale where the O(cluster) host encode matters (P=4096, every
    # partition weighted): the planners' own phase clocks time exactly
    # what residency changed, immune to simulator wall-clock noise.
    # The 2x margin is conservative — the baseline re-runs O(P) Python
    # weight/stickiness loops + a full decode per cycle, the resident
    # path dict-diffs a handful of rows.
    micro = _bench_encode_host_costs()
    residency["host_micro"] = micro
    residency_ok = bool(
        residency["log_identical"] and cold_attributed and bytes_bounded
        and res.encode_warm > 0
        and micro["identical"]
        and micro["resident_host_ms"] * 2
        <= micro["full_reencode_host_ms"])
    residency["pass"] = residency_ok

    out = {
        "scenario": scn.name, "seed": seed, "tenants": tenants,
        "identical_final_maps": identical,
        "equal_churn": equal_churn,
        "equal_slo": equal_slo,
        "complete": co.complete and seq.complete,
        "moves_executed": co.fleet.moves_executed,
        "plan_requests": co.plan_requests,
        "dispatches_coalesced": co.dispatches,
        "dispatches_sequential": seq.dispatches,
        "dispatch_reduction": round(
            seq.dispatches / max(co.dispatches, 1), 2),
        "carry_hits_coalesced": co.carry_hits,
        "converge_cycles": co.cycles,
        "wall_s_coalesced": round(co.wall_s, 3),
        "wall_s_sequential": round(seq.wall_s, 3),
        "cycles_per_s_coalesced": round(co_cps, 1),
        "cycles_per_s_sequential": round(seq_cps, 1),
        "admission_p50_ms": round(co.admission_p50_s * 1000, 2),
        "admission_p99_ms": round(co.admission_p99_s * 1000, 2),
        "starved_admissions": co.starved_admissions,
        "residency": residency,
    }
    out["pass"] = bool(
        identical and equal_churn and equal_slo and out["complete"]
        and co.dispatches < seq.dispatches and co_cps > seq_cps
        and residency_ok)
    log(f"[fleet_loop {tenants} tenants seed={seed}] "
        f"dispatches {seq.dispatches}->{co.dispatches} "
        f"({out['dispatch_reduction']}x fewer), cycles/s "
        f"{out['cycles_per_s_sequential']}->"
        f"{out['cycles_per_s_coalesced']}, identical={identical} "
        f"equal_churn={equal_churn} equal_slo={equal_slo} "
        f"admission p50/p99 {out['admission_p50_ms']}/"
        f"{out['admission_p99_ms']}ms (virtual)")
    log(f"[fleet_loop residency A/B 48-64p] encode "
        f"{residency['phases_full_reencode']['encode']}ms->"
        f"{residency['phases_resident']['encode']}ms "
        f"(share {residency['phases_full_reencode']['encode_share']}->"
        f"{residency['phases_resident']['encode_share']}), warm "
        f"{res.encode_warm}/{res.encode_warm + res.encode_cold} "
        f"cycles, patch {res.encode_patch_bytes}B/"
        f"{res.encode_patch_rows} rows, log_identical="
        f"{residency['log_identical']} attributed={cold_attributed}; "
        f"host micro P={micro['P']}: "
        f"{micro['full_reencode_host_ms']}ms->"
        f"{micro['resident_host_ms']}ms "
        f"({micro['host_speedup']}x, encode "
        f"{micro['full_reencode_encode_ms']}->"
        f"{micro['resident_encode_ms']}ms, decode "
        f"{micro['full_reencode_decode_ms']}->"
        f"{micro['resident_decode_ms']}ms)")
    return out


def bench_durability(seed=19):
    """Durability stage (ISSUE 18): crash-recovery cost and correctness
    on the ``crash_storm`` chain (docs/DURABILITY.md).

    Runs the crash-free reference, then the scripted three-crash chain
    — every restart recovers from the WAL into a fresh virtual loop and
    must converge to the reference's final map bit-identically, with
    recovery cold solves bounded by the counted attribution identity
    (one per resumed tenant per recovery).  Then measures the recovery
    path itself: wall-clock ``recover()`` over the completed journal
    (records replayed per ms is the headline recovery rate) and the
    epoch fence — a zombie pre-crash journal handle must have its
    append REJECTED and counted, never applied.

    ``gates`` collects every pass/fail the perf-smoke tier checks."""
    import shutil
    import tempfile

    from blance_tpu.durability import Journal, recover, reset_fences
    from blance_tpu.obs import Recorder, use_recorder
    from blance_tpu.testing.crashsim import (
        maps_identical, run_crash_scenario)
    from blance_tpu.testing.scenarios import crash_storm

    os.environ.setdefault("BLANCE_WAL_FSYNC", "0")
    cs = crash_storm(seed)
    base = tempfile.mkdtemp(prefix="blance-durability-")
    try:
        reset_fences()
        ref = run_crash_scenario(cs.base, os.path.join(base, "ref"))
        storm = run_crash_scenario(
            cs.base, os.path.join(base, "storm"), crashes=cs.crashes,
            snapshot_every=cs.snapshot_every,
            rotate_records=cs.rotate_records)
        identical = maps_identical(storm.final_map, ref.final_map)
        recoveries = int(storm.counters.get("durability.recoveries", 0))
        cold = int(storm.counters.get(
            "durability.recovery_cold_solves", 0))
        # One tenant per life in this scenario: the attribution bound
        # is exactly one counted cold solve per recovery.
        cold_bounded = cold <= recoveries

        # Recovery-time measurement over the storm run's full journal
        # (its final epoch's history), plus the fence check: a journal
        # handle opened BEFORE the recovery is a zombie afterwards.
        rec = Recorder()
        with use_recorder(rec):
            reset_fences()
            storm_dir = os.path.join(base, "storm")
            zombie = Journal(storm_dir)
            t0 = time.perf_counter()
            state = recover(storm_dir)
            recover_ms = (time.perf_counter() - t0) * 1e3
            state.journal.close()
            zombie_applied = zombie.append("delta", {"zombie": True})
            zombie.close()
        stale_counted = rec.counters.get(
            "durability.stale_epoch_rejections", 0) >= 1
        gates = {
            "final_map_identical": bool(identical),
            "chain_completed": storm.lives == len(cs.crashes) + 1,
            "cold_solves_bounded": bool(cold_bounded),
            "zombie_append_rejected": (not zombie_applied)
            and stale_counted,
        }
        out = {
            "scenario": cs.name,
            "seed": seed,
            "crashes": list(cs.crashes),
            "lives": storm.lives,
            "recoveries": recoveries,
            "recovery_cold_solves": cold,
            "records_replayed": state.records_replayed,
            "recover_ms": round(recover_ms, 3),
            "records_per_ms": round(
                state.records_replayed / recover_ms, 2)
            if recover_ms > 0 else None,
            "torn_segments": state.torn_segments,
            "stale_dropped": state.stale_dropped,
            "journal_records": int(storm.counters.get(
                "durability.journal_records", 0)),
            "journal_bytes": int(storm.counters.get(
                "durability.journal_bytes", 0)),
            "snapshots": int(storm.counters.get(
                "durability.snapshots", 0)),
            "gates": gates,
            "pass": all(gates.values()),
        }
    finally:
        reset_fences()
        shutil.rmtree(base, ignore_errors=True)
    log(f"[durability {cs.name} s{seed}] lives={out['lives']} "
        f"recoveries={out['recoveries']} cold={cold} "
        f"recover {out['recover_ms']}ms for "
        f"{out['records_replayed']} records "
        f"({out['records_per_ms']}/ms), identical={identical} "
        f"zombie_rejected={gates['zombie_append_rejected']}")
    return out


def _timed(fn):
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


def bench_plan_pipeline(P, N, reps=3):
    """Fused single-dispatch pipeline vs the staged path (ROADMAP 3).

    The staged path is what production ran before: plan_next_map_tpu
    (host encode -> device solve -> host decode) plus a separate
    calc_all_moves device diff.  The fused path is plan_pipeline: one
    jitted, buffer-donated dispatch chaining solve -> diff -> decode
    pack, with only the id->name materialization left on host.  Asserts
    the bit-identity contract (same map AND same move lists) and
    reports per-phase wall-clock for BOTH paths so the host-phase win
    is visible in every artifact."""
    from blance_tpu import model
    from blance_tpu.moves.batch import calc_all_moves
    from blance_tpu.obs import device as obs_device
    from blance_tpu.plan.tensor import plan_next_map_tpu, plan_pipeline
    from blance_tpu.utils.trace import PhaseTimer

    prev, nodes, removed = _make_map(P, N, seed=23)
    m = model(primary=(0, 1), replica=(1, 1))
    opts = _rack_opts(nodes)

    mon = obs_device.CompileMonitor().install()
    try:
        # Warm both compiles + pin the identity contract.
        staged_map, staged_warn = plan_next_map_tpu(
            prev, prev, nodes, removed, [], m, opts)
        staged_moves = calc_all_moves(prev, staged_map, m)
        fused_map, fused_warn, fused_moves = plan_pipeline(
            prev, prev, nodes, removed, [], m, opts)
        identical_map = (
            {k: v.nodes_by_state for k, v in staged_map.items()} ==
            {k: v.nodes_by_state for k, v in fused_map.items()})
        identical_moves = staged_moves == fused_moves
        assert identical_map, "pipeline map diverged from staged path"
        assert identical_moves, "pipeline moves diverged from staged path"
        assert staged_warn == fused_warn, "pipeline warnings diverged"

        def staged_once():
            timer = PhaseTimer()
            t0 = time.perf_counter()
            smap, _ = plan_next_map_tpu(prev, prev, nodes, removed, [],
                                        m, opts, timer=timer)
            t1 = time.perf_counter()
            calc_all_moves(prev, smap, m)
            total = time.perf_counter() - t0
            phases = {k: round(timer.totals[k] * 1000, 1)
                      for k in ("encode", "solve", "decode")
                      if k in timer.totals}
            phases["diff"] = round((time.perf_counter() - t1) * 1000, 1)
            phases["total"] = round(total * 1000, 1)
            return total, phases

        def fused_once():
            timer = PhaseTimer()
            t0 = time.perf_counter()
            plan_pipeline(prev, prev, nodes, removed, [], m, opts,
                          timer=timer)
            total = time.perf_counter() - t0
            phases = {k: round(timer.totals[k] * 1000, 1)
                      for k in ("encode", "dispatch", "decode",
                                "materialize")
                      if k in timer.totals}
            phases["total"] = round(total * 1000, 1)
            return total, phases

        staged = min((staged_once() for _ in range(reps)),
                     key=lambda r: r[0])
        fused = min((fused_once() for _ in range(reps)),
                    key=lambda r: r[0])
    finally:
        mon.uninstall()

    out = {
        "P": P, "N": N,
        "identical_map": identical_map,
        "identical_moves": identical_moves,
        # phases_ms for BOTH paths — the per-artifact host-phase
        # attribution the ISSUE 9 acceptance requires.
        "phases_ms": {"staged": staged[1], "fused": fused[1]},
        "staged_ms": round(staged[0] * 1000, 1),
        "fused_ms": round(fused[0] * 1000, 1),
        "speedup": round(staged[0] / max(fused[0], 1e-9), 2),
        "device": _device_block(mon),
    }
    log(f"[plan-pipeline {P}x{N}] staged {out['staged_ms']}ms "
        f"{staged[1]} vs fused {out['fused_ms']}ms {fused[1]} = "
        f"{out['speedup']}x, identical map={identical_map} "
        f"moves={identical_moves}")
    return out


def bench_warm_pipeline(P, N):
    """Warm delta-replan end-to-end through the fused session fast path:
    one node removed, one donated device dispatch returning the new map
    AND the move arrays — the sub-100 ms delta-replan target's
    measurement (ISSUE 9 acceptance)."""
    from blance_tpu import model
    from blance_tpu.plan.session import PlannerSession

    nodes = [f"n{i:05d}" for i in range(N)]
    parts = [str(i) for i in range(P)]
    m = model(primary=(0, 1), replica=(1, 1))
    s = PlannerSession(m, nodes, parts, opts=_rack_opts(nodes))
    s.replan_with_moves()
    s.apply()
    # Warm-up delta cycle compiles the warm pipeline program; the timed
    # cycle below is the steady-state delta replan.
    s.remove_nodes([nodes[0]])
    s.replan_with_moves()
    s.apply()
    victim = nodes[N // 3]
    s.remove_nodes([victim])
    from blance_tpu.obs import get_recorder

    # Delta, not cumulative: the warm-up cycle above already scored a
    # pipeline.warm, and this field must report the TIMED replan's
    # outcome (same discipline as bench_delta_replan's carry_hit).
    w0 = get_recorder().counters.get("plan.pipeline.warm", 0)
    t0 = time.perf_counter()
    _assign, (d_nodes, _ds, _do) = s.replan_with_moves()
    warm_ms = (time.perf_counter() - t0) * 1000
    s.apply()
    hit = get_recorder().counters.get("plan.pipeline.warm", 0) - w0 > 0
    out = {"P": P, "N": N, "warm_e2e_ms": round(warm_ms, 1),
           "warm_hit": bool(hit),
           "moves_rows": int((d_nodes >= 0).any(axis=1).sum())}
    log(f"[warm-pipeline {P}x{N}] delta replan end-to-end "
        f"{out['warm_e2e_ms']}ms (hit={out['warm_hit']}, "
        f"{out['moves_rows']} partitions moving)")
    return out


def bench_delta_replan(P, N):
    """Cold vs warm delta replan through PlannerSession: the
    incremental-replanning headline (ISSUE 2).

    Protocol: one session solves and applies a map (building the warm
    carry), then removes one node and replans WARM; a second session
    loads the identical pre-delta map (which invalidates any carry),
    applies the same delta and replans COLD.  Reports sweeps (from the
    obs plan.solve.sweeps counter), wall-clock for both paths, and
    whether the maps are bit-identical — the warm path's contract."""
    from blance_tpu import model
    from blance_tpu.obs import device as obs_device
    from blance_tpu.obs import get_recorder

    nodes = [f"n{i:05d}" for i in range(N)]
    parts = [str(i) for i in range(P)]
    m = model(primary=(0, 1), replica=(1, 1))
    opts = _rack_opts(nodes)
    rec = get_recorder()

    def sweeps():
        return rec.counters.get("plan.solve.sweeps", 0)

    # try/finally: _run_benchmarks survives a failed stage by design,
    # and an abandoned monitor would keep its logging tap (and the
    # suppressed propagation) for the rest of the process.
    mon = obs_device.CompileMonitor().install()
    try:
        return _bench_delta_replan_body(P, N, m, nodes, parts, opts,
                                        rec, sweeps, mon)
    finally:
        mon.uninstall()


def _bench_delta_replan_body(P, N, m, nodes, parts, opts, rec, sweeps,
                             mon):
    from blance_tpu.plan.session import PlannerSession

    s = PlannerSession(m, nodes, parts, opts=opts)
    s.replan()
    s.apply()  # promotes the carry: the next replan is warm
    # Warm-up delta cycle: compiles the warm-repair program (the cold
    # program compiled during the first replan), so the timed cycle
    # below measures steady-state wall-clock on both paths.
    s.remove_nodes([nodes[0]])
    s.replan()
    s.apply()
    pre_map, _ = s.to_map()
    victim = nodes[N // 3]

    s.remove_nodes([victim])
    c0 = sweeps()
    h0 = rec.counters.get("plan.solve.carry_hit", 0)
    t0 = time.perf_counter()
    warm = s.replan().copy()
    warm_ms = (time.perf_counter() - t0) * 1000
    warm_sweeps = sweeps() - c0
    # Delta, not cumulative: the warm-up cycle above already scored a
    # hit, and this field must report the TIMED replan's outcome.
    warm_hit = rec.counters.get("plan.solve.carry_hit", 0) - h0 > 0

    s2 = PlannerSession(m, nodes, parts, opts=opts)
    s2.load_map(pre_map)  # same state, no carry
    s2.remove_nodes(sorted(s.removed_nodes))  # same node set incl. victim
    c1 = sweeps()
    t0 = time.perf_counter()
    cold = s2.replan()
    cold_ms = (time.perf_counter() - t0) * 1000
    cold_sweeps = sweeps() - c1

    out = {
        "P": P, "N": N,
        "cold_sweeps": int(cold_sweeps), "warm_sweeps": int(warm_sweeps),
        "cold_ms": round(cold_ms, 1), "warm_ms": round(warm_ms, 1),
        "warm_carry_hit": bool(warm_hit),
        "identical": bool(np.array_equal(warm, cold)),
        "device": _device_block(mon),
    }
    log(f"[delta-replan {P}x{N}] cold: {cold_sweeps} sweeps "
        f"{cold_ms:.0f}ms / warm: {warm_sweeps} sweeps {warm_ms:.0f}ms "
        f"(hit={warm_hit}, identical={out['identical']})")
    return out


def bench_sparse(P, N, k=None, identity_shape=(512, 64)):
    """Sparse shortlist solve vs the dense engines (ISSUE 11).

    Three parts, all reported in one block:

    - **saturating-K bit-identity** at a small dense-feasible shape:
      solve_sparse with K = N must equal the dense converged solve
      bit-for-bit (the contract that keeps the engines from drifting);
    - **the big config** (1M partitions x 1k nodes on device hosts,
      smoke sizes on CPU): shortlist build + converged sparse solve
      timed end-to-end with the full audit, WITHOUT materializing any
      dense [P, S, N] score tensor;
    - **peak-bytes evidence**: the AOT memory analysis of the compiled
      sparse program vs the dense matrix engine's projected [P, N]
      working set (plan.tensor.projected_score_bytes) — the number the
      dense-memory guard refuses past budget.
    """
    import jax
    import jax.numpy as jnp
    from blance_tpu.obs import device as obs_device
    from blance_tpu.obs import get_recorder
    from blance_tpu.core.shortlist import auto_shortlist_k, build_shortlist
    from blance_tpu.ops.sparse2 import (
        sparse_min2_reference, sparse_priced_min2)
    from blance_tpu.plan.tensor import (
        _solve_sparse_converged_impl, projected_score_bytes,
        resolve_sparse_impl, solve_dense_converged, solve_sparse)

    rec = get_recorder()
    out = {"P": P, "N": N}

    # Kernel verification (compiled on TPU, interpret elsewhere): the
    # fused sparse min2 must match its XLA oracle bit-for-bit before any
    # timed run uses it.
    rng = np.random.default_rng(11)
    score = jnp.asarray(
        rng.integers(0, 50, (2048, 64)).astype(np.float32) * 0.125)
    price = jnp.asarray(
        rng.integers(0, 8, (2048, 64)).astype(np.float32) * 0.25)
    impl = resolve_sparse_impl(None)
    kb, kk_, ks, kr = sparse_priced_min2(
        score, price, interpret=(impl != "pallas"))
    rb, rk, rs, rr = sparse_min2_reference(score, price)
    out["kernel_verified"] = bool(
        np.array_equal(np.asarray(kb), np.asarray(rb))
        and np.array_equal(np.asarray(kk_), np.asarray(rk))
        and np.array_equal(np.asarray(ks), np.asarray(rs))
        and np.array_equal(np.asarray(kr), np.asarray(rr)))
    log(f"[sparse] min2 kernel ({impl}) vs oracle: "
        f"{'bit-identical' if out['kernel_verified'] else 'MISMATCH'}")

    # Saturating-K bit-identity at a dense-feasible shape.
    ip, inn = identity_shape
    (prev, pweights, nweights, valid, stickiness, gids, gid_valid,
     constraints, rules) = build_dense(ip, inn, seed=13)
    dev = [jnp.asarray(a) for a in
           (prev, pweights, nweights, valid, stickiness, gids, gid_valid)]
    dense_small = np.asarray(solve_dense_converged(
        *dev, constraints, rules, record=False))
    sparse_small = solve_sparse(prev, pweights, nweights, valid,
                                stickiness, gids, gid_valid, constraints,
                                rules, k=inn, record=False)
    out["saturating_identity"] = bool(
        np.array_equal(dense_small, sparse_small))
    log(f"[sparse] saturating K={inn} identity @ {ip}x{inn}: "
        f"{out['saturating_identity']}")

    # The big config: never materializes a dense [P, S, N] score.
    (prev, pweights, nweights, valid, stickiness, gids, gid_valid,
     constraints, rules) = build_dense(P, N, seed=7)
    kk = int(k) if k is not None else auto_shortlist_k(
        N, constraints, rules)
    out["k"] = kk

    t0 = time.perf_counter()
    shortlist = build_shortlist(prev, pweights, nweights, valid, gids,
                                gid_valid, constraints, rules, kk)
    np.asarray(shortlist[:, 0])  # force completion
    out["shortlist_build_s"] = round(time.perf_counter() - t0, 3)

    dev = [jnp.asarray(a) for a in
           (prev, pweights, nweights, valid, stickiness, gids, gid_valid)]
    impl_big = resolve_sparse_impl(None)

    def run():
        a, sweeps, exh = _solve_sparse_converged_impl(
            *dev, shortlist, constraints=constraints, rules=rules,
            sparse_impl=impl_big)
        np.asarray(a[:, 0, 0])  # force completion (axon-safe sync)
        return a, exh

    with obs_device.CompileMonitor() as mon:
        t0 = time.perf_counter()
        assign, exh = run()
        out["compile_s"] = round(time.perf_counter() - t0, 2)
        times = []
        for _ in range(RUNS):
            t0 = time.perf_counter()
            assign, exh = run()
            times.append(time.perf_counter() - t0)
    out["solve_ms_min"] = round(min(times) * 1000, 2)
    out["solve_ms_runs"] = [round(t * 1000, 2) for t in times]
    out["exhausted_rows"] = int(np.asarray(exh).sum())
    counts = audit(np.asarray(assign), valid, gids)
    # Exhausted rows are -1 by design until the host fallback fills
    # them; audit the FINAL (fallback-patched) assignment.
    if out["exhausted_rows"]:
        from blance_tpu.plan.tensor import _apply_sparse_fallback

        patched, _ = _apply_sparse_fallback(
            np.asarray(assign), np.asarray(exh), prev, pweights,
            nweights, valid, stickiness, gids, gid_valid, constraints,
            rules)
        counts = audit(patched, valid, gids)
    out["violations"] = counts
    out["device"] = _device_block(mon)

    # Peak-bytes evidence: AOT memory analysis of the compiled sparse
    # program vs the dense matrix estimate.
    out["dense_score_bytes_est"] = projected_score_bytes(P, N)
    try:
        lowered = _solve_sparse_converged_impl.lower(
            *dev, shortlist, constraints=constraints, rules=rules,
            sparse_impl=impl_big)
        peak = obs_device._extract_cost(lowered.compile())[
            "peak_alloc_bytes"]
        out["sparse_peak_bytes"] = int(peak)
        if peak:
            out["sparse_vs_dense_bytes"] = round(
                peak / max(out["dense_score_bytes_est"], 1), 4)
    except Exception as e:
        out["sparse_peak_bytes_error"] = first_line(e)
    log(f"[sparse {P}x{N}] K={kk} build {out['shortlist_build_s']}s "
        f"solve min {out['solve_ms_min']}ms exhausted "
        f"{out['exhausted_rows']} audit {counts} peak "
        f"{out.get('sparse_peak_bytes')}B vs dense est "
        f"{out['dense_score_bytes_est']}B")
    assert counts["unassigned_slots"] == 0
    assert counts["on_removed_nodes"] == 0
    return out


def obs_summary():
    """The Recorder's aggregates, floats rounded for the JSON artifact:
    per-span-name totals (phase attribution), counters (solver sweeps,
    fallbacks, orchestrator progress mirror), gauges (SLO), histogram
    p50/p95."""
    from blance_tpu.obs import get_recorder

    def r(x):
        return round(x, 6) if isinstance(x, float) else x

    s = get_recorder().summary()
    return {
        "spans": {k: {kk: r(vv) for kk, vv in v.items()}
                  for k, v in s["spans"].items()},
        "counters": {k: r(v) for k, v in s["counters"].items()},
        "gauges": {k: r(v) for k, v in s["gauges"].items()},
        "histograms": {k: {kk: r(vv) for kk, vv in v.items()}
                       for k, v in s["histograms"].items() if v},
    }


# Child program for one CPU baseline measurement.  Runs in a subprocess so
# the parent can enforce CPU_TIMEOUT_S (the native call is one C++ planner
# invocation — uninterruptible in-process) and so the measurement can never
# touch the device runtime (the child pins the cpu platform before any
# blance_tpu import).
_CPU_CHILD = r"""
import json, sys, time
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {repo!r})
import bench
P, N, backend = {P}, {N}, {backend!r}
from blance_tpu import PlanOptions, model, plan_next_map
prev, nodes, removed = bench._make_map(P, N)
m = model(primary=(0, 1), replica=(1, 1))
opts = bench._rack_opts(nodes)
opts.max_iterations = 1  # single pass, same work as one solve
# Epoch marker AFTER imports + problem construction: a timed-out parent
# bounds PLANNER time from here, not from process start.
print("SETUP_DONE", time.time(), flush=True)
t0 = time.perf_counter()
plan_next_map(prev, prev, nodes, removed, [], m, opts, backend=backend)
print(json.dumps({{"cpu_s": time.perf_counter() - t0}}))
"""


def bench_cpu(P, N):
    """CPU baseline, MEASURED at the full problem size (no P-scaling): the
    native C++ exact planner when built, else the Python greedy scaled
    from PY_GREEDY_P (toolchain-less hosts only).  Runs under a hard
    timeout; on expiry the elapsed budget is reported as an explicit
    LOWER BOUND on the CPU time (so the derived speedup is a lower bound
    too), never an extrapolation."""
    import os
    import subprocess

    from blance_tpu.plan.native import native_available

    use_native = native_available()
    cpu_p = P if use_native else min(P, PY_GREEDY_P)
    backend = "native" if use_native else "greedy"
    child = _CPU_CHILD.format(
        repo=os.path.dirname(os.path.abspath(__file__)),
        P=cpu_p, N=N, backend=backend)
    log(f"[{P}x{N}] cpu {backend} @ {cpu_p}x{N} (full-size measurement, "
        f"timeout {CPU_TIMEOUT_S}s)...")
    try:
        r = subprocess.run([sys.executable, "-c", child],
                           timeout=CPU_TIMEOUT_S, capture_output=True,
                           text=True, check=True)
        cpu_s = json.loads(r.stdout.strip().splitlines()[-1])["cpu_s"]
        bound = False
    except subprocess.TimeoutExpired as e:
        # Lower-bound the PLANNER time only: the child stamps wall time
        # after imports + problem construction, so the bound excludes
        # startup.  No marker captured (killed during setup) = no claim.
        out = e.stdout or ""
        if isinstance(out, bytes):  # text= capture varies across versions
            out = out.decode(errors="replace")
        marker = None
        for line in out.splitlines():
            if line.startswith("SETUP_DONE"):
                marker = float(line.split()[1])
        if marker is None:
            log(f"[{P}x{N}] cpu baseline timed out during setup; "
                f"no measurement")
            return {"cpu_s": None, "baseline": f"{backend}-timeout"}
        cpu_s = time.time() - marker
        bound = True
    except (subprocess.CalledProcessError, ValueError, KeyError,
            IndexError) as e:
        err = getattr(e, "stderr", "") or str(e)
        log(f"[{P}x{N}] cpu baseline child failed: {err[-400:]}")
        return {"cpu_s": None, "baseline": f"{backend}-failed"}
    # A timed-out partial run may only be reported UNSCALED: scaling a
    # lower bound linearly in P would be exactly the extrapolation this
    # function exists to avoid (it can only overstate the bound's claim).
    scale = 1.0 if bound else P / cpu_p
    scaled = cpu_s * scale
    provenance = ("native-c++" if use_native else "python-greedy") + \
        ("" if scale == 1 else f"-scaled-x{scale:g}-in-P") + \
        ("-timeout-lower-bound" if bound else "")
    log(f"[{P}x{N}] cpu {backend}: "
        + (f">= {cpu_s:.0f}s (timed out; lower bound)" if bound
           else f"{cpu_s:.2f}s")
        + ("" if scale == 1 else f" -> scaled to P={P}: {scaled:.1f}s"))
    return {"cpu_s": round(scaled, 2), "baseline": provenance,
            "cpu_is_lower_bound": bound}


# Child program for one tile-sweep measurement: a fresh subprocess per
# tile combination (the tiles are jit-static, read once at import — see
# ops/_tiles.py), timing the fused converged solve AND a fused warm
# one-sweep repair so the sweep's tile choice covers the delta-replan
# kernels too.  On a cpu host the kernels run under the pallas
# interpreter at the caller's (smoke) sizes.
_TILE_CHILD = r"""
import json, sys, time
import jax
if {cpu!r}:
    jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {repo!r})
import numpy as np
import bench
import jax.numpy as jnp
from blance_tpu.plan.tensor import (carry_from_assignment,
                                    solve_dense_converged,
                                    solve_dense_warm)
from blance_tpu.ops import reduce2, score_fused
P, N, mode, runs = {P}, {N}, {mode!r}, {runs}
args = bench.build_dense(P, N)
(prev, pweights, nweights, valid, stickiness, gids, gid_valid,
 constraints, rules) = args
dev = [jnp.asarray(a) for a in
       (prev, pweights, nweights, valid, stickiness, gids, gid_valid)]
def run():
    out = solve_dense_converged(*dev, constraints, rules, fused_score=mode,
                                record=False)
    np.asarray(out[:, 0, 0])  # force completion (axon quirk)
    return out
t0 = time.perf_counter(); out = run(); compile_s = time.perf_counter() - t0
times = []
for _ in range(runs):
    t0 = time.perf_counter(); run(); times.append(time.perf_counter() - t0)
# Warm one-sweep repair through the same kernels (tile choice feeds the
# Pallas warm-repair path too).
out_np = np.asarray(out)
dirty = np.zeros(P, bool); dirty[: max(P // 64, 1)] = True
warm_times = []
for _ in range(max(runs - 1, 1)):
    carry = carry_from_assignment(jnp.asarray(out_np), dev[1], dev[2])
    t0 = time.perf_counter()
    solve_dense_warm(out_np, *dev[1:7], constraints, rules, dirty=dirty,
                     carry=carry, fused_score=mode, record=False)
    warm_times.append(time.perf_counter() - t0)
print(json.dumps({{
    "tile_p": score_fused._TILE_P, "tile_n": score_fused._TILE_N,
    "reduce2_tile_p": reduce2._TILE_P, "reduce2_tile_n": reduce2._TILE_N,
    "compile_s": round(compile_s, 1),
    "solve_ms_min": round(min(times) * 1000, 2),
    "solve_ms_runs": [round(t * 1000, 2) for t in times],
    "warm_ms_min": round(min(warm_times) * 1000, 2)}}))
"""


def run_tile_sweep(P=None, N=None):
    """bench.py --tile-sweep: the fused-kernel tile sweep as a
    first-class stage with a parseable JSON artifact (previously the
    orphan docs/bench_tile_sweep.py).  Sweeps BLANCE_FUSED_TILE_P/N and
    BLANCE_REDUCE2_TILE_P/N together over aligned candidates, one
    subprocess per combination, and prints ONE artifact line naming the
    winning tile — the value to export before latency-critical runs.
    On a TPU host the sweep runs the compiled kernels at the (default)
    north-star shape; cpu hosts degrade to interpret-mode smoke sizes
    so the artifact shape is always producible."""
    import subprocess

    import jax

    cpu = jax.default_backend() != "tpu"
    if cpu:
        P, N = P or 256, N or 32
        grid = [(256, 2048), (512, 2048)]
        mode, runs, timeout = "interpret", 1, 900
        log(f"tile-sweep: no TPU (backend {jax.default_backend()}); "
            f"interpret-mode smoke at {P}x{N}")
    else:
        P, N = P or 100_000, N or 10_000
        grid = [(tp, tn) for tp in (128, 256, 512)
                for tn in (1024, 2048, 4096)]
        mode, runs, timeout = "on", 4, 600
    results = []
    for tile_p, tile_n in grid:
        env = dict(os.environ,
                   BLANCE_FUSED_TILE_P=str(tile_p),
                   BLANCE_FUSED_TILE_N=str(tile_n),
                   BLANCE_REDUCE2_TILE_P=str(tile_p),
                   BLANCE_REDUCE2_TILE_N=str(tile_n))
        child = _TILE_CHILD.format(
            repo=os.path.dirname(os.path.abspath(__file__)),
            P=P, N=N, mode=mode, runs=runs, cpu=cpu)
        t0 = time.time()
        try:
            r = subprocess.run([sys.executable, "-c", child], env=env,
                               timeout=timeout, capture_output=True,
                               text=True, check=True)
            lines = r.stdout.strip().splitlines()
            res = json.loads(lines[-1]) if lines else {
                "error": "no output"}
        except subprocess.TimeoutExpired:
            res = {"error": "timeout",
                   "elapsed_s": round(time.time() - t0)}
        except (subprocess.CalledProcessError, ValueError) as e:
            err = (getattr(e, "stderr", "") or str(e)).strip()
            res = {"error": err.splitlines()[-1][-200:]
                   if err else "failed"}
        # Keep the CHILD-reported tiles (the values actually compiled
        # in) — overwriting them would destroy the only evidence the
        # env override applied; flag a propagation break instead.
        res.setdefault("tile_p", tile_p)
        res.setdefault("tile_n", tile_n)
        if "solve_ms_min" in res and (res["tile_p"] != tile_p
                                      or res["tile_n"] != tile_n):
            res["error"] = (f"env override did not apply: child "
                            f"compiled {res['tile_p']}x{res['tile_n']}")
            res.pop("solve_ms_min", None)
        log(f"tile-sweep {tile_p}x{tile_n}: "
            + (f"{res['solve_ms_min']}ms solve / "
               f"{res.get('warm_ms_min')}ms warm"
               if "solve_ms_min" in res else res.get("error", "?")))
        results.append(res)
    done = [r for r in results if "solve_ms_min" in r]
    best = min(done, key=lambda r: r["solve_ms_min"]) if done else None
    print(json.dumps({
        "metric": f"fused-kernel tile sweep @ {P}x{N} ({mode})",
        "value": best["solve_ms_min"] if best else None,
        "unit": "ms",
        "vs_baseline": None,
        "detail": {"P": P, "N": N, "mode": mode, "results": results,
                   "best": best,
                   "env": (None if best is None else {
                       "BLANCE_FUSED_TILE_P": best["tile_p"],
                       "BLANCE_FUSED_TILE_N": best["tile_n"],
                       "BLANCE_REDUCE2_TILE_P": best["tile_p"],
                       "BLANCE_REDUCE2_TILE_N": best["tile_n"]})},
        "pass": best is not None,
    }))
    if best is None:
        sys.exit(1)


def enable_compile_cache(path=None):
    """Point jax's persistent compilation cache at ``path`` (or the
    BLANCE_COMPILE_CACHE / JAX_COMPILATION_CACHE_DIR environment
    variables), with the min-compile-time/min-entry-size floors dropped
    to 0 so even smoke-shape programs cache — repeat perf-smoke /
    sim-smoke runs then deserialize instead of re-paying cold XLA
    compiles (docs/OBSERVABILITY.md "Persistent XLA compilation
    cache").  No-op when no directory is configured; never fatal (an
    old jax without a knob just runs uncached).  Returns the directory
    in effect, or None."""
    cache_dir = path or os.environ.get("BLANCE_COMPILE_CACHE") \
        or os.environ.get("JAX_COMPILATION_CACHE_DIR")
    if not cache_dir:
        return None
    os.makedirs(cache_dir, exist_ok=True)
    # Env first: every jax config option reads its uppercase env twin
    # at init, so setting these BEFORE jax imports needs no jax import
    # here (main() must not touch jax ahead of the device probe).
    os.environ["JAX_COMPILATION_CACHE_DIR"] = cache_dir
    os.environ.setdefault(
        "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
    os.environ.setdefault(
        "JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")
    if "jax" in sys.modules:  # already imported: env alone is too late
        import jax

        for knob, val in (
                ("jax_compilation_cache_dir", cache_dir),
                ("jax_persistent_cache_min_compile_time_secs", 0),
                ("jax_persistent_cache_min_entry_size_bytes", 0)):
            try:
                jax.config.update(knob, val)
            except (AttributeError, ValueError):
                # A jax without this knob: best-effort — the cache
                # still works with that knob's default.
                pass
    log(f"persistent XLA compilation cache: {cache_dir}")
    return cache_dir


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="tiny sizes (code-path test on CPU)")
    ap.add_argument("--tile-sweep", action="store_true",
                    help="sweep the Pallas kernel tile sizes (one "
                         "subprocess per combination) and emit a JSON "
                         "artifact naming the winner; interpret-mode "
                         "smoke on cpu hosts")
    ap.add_argument("--tile-sweep-shape", default=None, metavar="PxN",
                    help="override the tile sweep problem shape, e.g. "
                         "100000x10000")
    ap.add_argument("--perf-smoke", action="store_true",
                    help="CI guard: run ONLY the delta-replan stage at "
                         "smoke size on CPU and fail (exit 1) if the "
                         "warm path does not beat the cold path's sweep "
                         "count or diverges from it")
    ap.add_argument("--trace-out", default=None, metavar="PATH",
                    help="write a Chrome trace-event JSON of every obs "
                         "span (open in chrome://tracing / Perfetto)")
    ap.add_argument("--device-trace-dir", default=None, metavar="DIR",
                    help="also capture a jax.profiler device trace over "
                         "the same interval (TensorBoard/Perfetto)")
    ap.add_argument("--compile-cache", default=None, metavar="DIR",
                    help="enable jax's persistent XLA compilation "
                         "cache in DIR (default: the "
                         "BLANCE_COMPILE_CACHE or "
                         "JAX_COMPILATION_CACHE_DIR env var) so repeat "
                         "runs stop re-paying cold compiles")
    args = ap.parse_args()

    smoke = args.smoke
    enable_compile_cache(args.compile_cache)

    if args.tile_sweep:
        tp = tn = None
        if args.tile_sweep_shape:
            tp, tn = (int(x) for x in args.tile_sweep_shape.split("x"))
        run_tile_sweep(tp, tn)
        return

    if args.perf_smoke:
        # CI perf guard: CPU-pinned, delta-replan stage only, asserting
        # the warm path's contract (fewer sweeps, identical map).
        import jax

        jax.config.update("jax_platforms", "cpu")
        _run_perf_smoke()
        return

    # Fail fast if the device runtime is wedged: a hung tunnel makes
    # jax.devices() block forever inside native code (no Python timeout
    # can interrupt it), so probe it in a subprocess first.  Smoke runs
    # skip the probe: their callers select the CPU platform through
    # jax.config.update BEFORE exec'ing this file (the env var alone
    # doesn't work — the axon plugin overrides JAX_PLATFORMS), and that
    # in-process pin cannot propagate to a probe subprocess, which would
    # then hang against the very runtime smoke mode exists to avoid.
    backend_note = None
    if not smoke:
        import subprocess

        # Device wedges can be transient (a killed mid-compile client can
        # stall the runtime for a while): retry the probe once with a
        # pause before giving up, so a recovery inside the window still
        # yields a measured artifact.  Worst case stays bounded (~9 min —
        # the driver's round budget must survive a wedge AND the
        # cpu-fallback run that follows, the BENCH_r04/r05 failure mode).
        attempts, last = 2, None
        probed_backend = None
        for attempt in range(1, attempts + 1):
            try:
                r = subprocess.run(
                    [sys.executable, "-c",
                     # Enumerate AND compute: a wedged runtime can pass
                     # device listing yet hang at the first dispatch.
                     # The probe also reports the backend, so a cpu-only
                     # host degrades to smoke BEFORE this process
                     # initializes jax (the virtual-device flag for the
                     # fleet mesh must precede the first import).
                     "import jax, numpy; numpy.asarray("
                     "jax.numpy.ones((8, 8)) @ jax.numpy.ones((8, 8)));"
                     "print(jax.default_backend())"],
                    timeout=240, check=True, capture_output=True,
                    text=True)
                probed_backend = (r.stdout.strip().splitlines() or
                                  [""])[-1]
                last = None  # a retry may succeed after a failed attempt
                break
            except subprocess.TimeoutExpired:
                last = "device probe (enumerate + tiny matmul) did not " \
                    "return within 240s — device runtime unreachable"
            except subprocess.CalledProcessError as e:
                # Non-zero exit is deterministic (broken install/config),
                # not a transient wedge — no retries, but still fall back
                # to a measured CPU artifact below rather than aborting.
                last = ("device probe failed: "
                        + e.stderr.decode(errors="replace")[-500:])
                break
            if attempt < attempts:
                log(f"probe attempt {attempt}/{attempts} failed ({last}); "
                    f"retrying in 30s")
                time.sleep(30)
        if last is not None:
            # The device runtime is unusable, but the driver still needs
            # a PARSEABLE artifact (BENCH_r05: rc=3 left parsed=null).
            # Pin the CPU platform in-process (the env var alone doesn't
            # survive the axon plugin) and run the full pipeline at
            # smoke sizes — every stage lands in the JSON, tagged
            # "cpu-fallback" so nobody quotes the numbers as device
            # measurements.
            log(f"device unreachable ({last}); degrading to the "
                f"cpu-fallback artifact at smoke sizes. The latest "
                f"builder-measured north-star artifact remains "
                f"docs/BENCH_local_r04.json (304 ms @ 100k x 10k).")
            _ensure_virtual_devices()
            import jax

            jax.config.update("jax_platforms", "cpu")
            smoke = True
            backend_note = "cpu-fallback"
        elif probed_backend == "cpu":
            # No accelerator attached: the full configs would take hours
            # of host time for numbers nobody should quote.  Degrade to
            # smoke sizes now, before jax initializes in-process.
            log("no accelerator (probe reports cpu backend): degrading "
                "to smoke sizes; device numbers require a TPU host")
            smoke = True

    if smoke:
        # CPU smoke runs want a multi-device host (8 virtual devices,
        # the tests/conftest.py trick) so the fleet stage's batch-axis
        # mesh sharding exercises the real code path.  Must precede the
        # first jax import; a no-op when the backend is a real device.
        _ensure_virtual_devices()

    import jax

    log(f"devices: {jax.devices()}")
    if not smoke and jax.default_backend() == "cpu":
        # No accelerator attached: the full configs would take hours of
        # host time for numbers nobody should quote.  Degrade to smoke
        # sizes (every code path still runs, incl. --trace-out capture)
        # and say so — the artifact records the device either way.
        log("no accelerator (jax backend is cpu): degrading to smoke "
            "sizes; device numbers require a TPU host")
        smoke = True

    global CONFIGS, RUNS
    if smoke:
        CONFIGS = [(512, 128, True), (512, 64, False)]  # headline first,
        RUNS = 3                                        # like the real list

    def _go():
        if args.trace_out:
            from blance_tpu.obs import trace

            log(f"obs: capturing spans -> {args.trace_out}")
            try:
                # trace() validates the path up front and writes the
                # file even when the run raises — a crashed run's trace
                # is exactly the one worth reading.
                with trace(args.trace_out,
                           device_log_dir=args.device_trace_dir):
                    _run_benchmarks(smoke, backend_note)
            finally:
                if os.path.exists(args.trace_out):
                    log(f"obs: chrome trace written to {args.trace_out}")
        else:
            from blance_tpu.utils.trace import device_profile

            with device_profile(args.device_trace_dir):
                _run_benchmarks(smoke, backend_note)

    if backend_note is None:
        _go()
        return
    # Degraded (device-unreachable) mode: the driver needs a PARSEABLE
    # artifact and rc 0 no matter what — BENCH_r04/r05 exited 3 with an
    # empty artifact and the round was scored as a failure instead of a
    # tagged cpu smoke.  A late crash still emits the artifact shape
    # with the error recorded; the numbers gathered so far live in
    # docs/BENCH_progress.json either way.
    try:
        _go()
    except (Exception, SystemExit) as e:
        err = f"exit {e.code}" if isinstance(e, SystemExit) \
            else f"{type(e).__name__}: {first_line(e)}"
        log(f"cpu-fallback run failed late ({err}); emitting the "
            f"degraded artifact with rc 0")
        print(json.dumps({
            "metric": "cpu-fallback smoke (device runtime unreachable)",
            "value": None,
            "unit": "ms",
            "vs_baseline": None,
            "engine": "cpu-fallback",
            "error": err,
            "detail": {"progress": "docs/BENCH_progress.json"},
        }))


def _bench_membudget():
    """Measure every budgeted solver entry's AOT peak allocation
    against HBM_BUDGETS (zero FLOPs — abstract operands end to end) and
    log the table.  Returns the measured-vs-budget rows for
    detail.membudget."""
    from blance_tpu.analysis.membudget import measure_budget_table

    rows = measure_budget_table(["smoke"])
    for r in rows:
        got = r.get("measured", r.get("error"))
        log(f"[membudget] {r['entry']:<24} {r['class']:<6} "
            f"peak={got} budget={r['budget']} "
            f"{'OK' if r.get('ok') else 'OVER'}")
    return rows


def _run_perf_smoke():
    """The CI perf gate (bench.py --perf-smoke): delta-replan at smoke
    size on CPU; exit 1 when warm sweeps fail to beat cold sweeps or the
    warm map diverges — so the warm path cannot silently regress to (or
    past) a cold solve.

    Runs the static shape-contract audit FIRST (blance_tpu.analysis
    shape_audit, eval_shape only — milliseconds per entry): a drifted
    solver signature would otherwise surface here as an opaque stack
    trace mid-benchmark.  A broken static pass emits a PARSEABLE JSON
    artifact with ``"pass": false`` and exits 1, same shape as the perf
    result, so the driver always gets data."""
    import jax

    from blance_tpu.obs import device as obs_device

    log(f"perf-smoke on {jax.default_backend()}")
    # Device observatory ON for the gate: the artifact's
    # detail.device block must carry nonzero per-entry compile counts
    # AND cost-analysis FLOPs/bytes for the solve stage.
    obs_device.enable(cost_analysis=True, sweep_trace=False)
    try:
        from blance_tpu.analysis.shape_audit import run_shape_audit

        shape_findings, shape_entries = run_shape_audit()
    except Exception as e:
        shape_findings, shape_entries = [
            f"shape audit crashed: {type(e).__name__}: {first_line(e)}"
        ], 0
    if shape_findings:
        rendered = [f if isinstance(f, str) else f.render()
                    for f in shape_findings]
        print(json.dumps({
            "metric": "delta-replan perf smoke (warm vs cold sweeps)",
            "value": None,
            "unit": "sweeps",
            "vs_baseline": None,
            "detail": {"static_audit": {"entries": shape_entries,
                                        "findings": rendered}},
            "pass": False,
        }))
        log(f"PERF-SMOKE FAILED: static shape audit broken "
            f"({len(rendered)} finding(s)); fix the contracts before "
            f"benchmarking")
        sys.exit(1)
    log(f"static shape audit OK ({shape_entries} contracts)")
    res = bench_delta_replan(512, 64)
    ok = (res["identical"] and res["warm_carry_hit"]
          and res["warm_sweeps"] * 2 <= res["cold_sweeps"])

    # Pipeline gate (ISSUE 9): the fused single-dispatch pipeline must
    # stay bit-identical to the staged path (map AND move lists) and
    # beat it end-to-end at smoke sizes — the dispatch-count win must
    # not silently erode back into staged-path territory.  The timing
    # half is inherently wall-clock (unlike the sweep-count gate above):
    # min-of-5 on both sides damps CI-runner noise, and the structural
    # margin (one dispatch + no host decode pack/diff re-encode vs
    # four boundaries) is ~40% at this shape, not knife-edge.
    try:
        pipe = bench_plan_pipeline(512, 64, reps=5)
        pipe_ok = (pipe["identical_map"] and pipe["identical_moves"]
                   and pipe["fused_ms"] < pipe["staged_ms"])
    except AssertionError as e:
        pipe = {"error": first_line(e)}
        pipe_ok = False
    ok = ok and pipe_ok

    # Sparse gate (ISSUE 11): saturating-K bit-identity must hold, the
    # sparse min2 kernel must match its oracle, the audit at the large
    # smoke config must be clean, and the compiled sparse program's AOT
    # peak bytes must sit below the dense matrix engine's projected
    # [P, N] working set (the memory the dense guard refuses) — so the
    # "breaks the dense wall" claim is CI-checked, not aspirational.
    try:
        sparse = bench_sparse(4096, 256)
        sparse_ok = (sparse["saturating_identity"]
                     and sparse["kernel_verified"]
                     and not any(sparse["violations"].values()))
        peak = sparse.get("sparse_peak_bytes")
        if peak:
            sparse_ok = sparse_ok and \
                peak < sparse["dense_score_bytes_est"]
    except AssertionError as e:
        sparse = {"error": first_line(e)}
        sparse_ok = False
    ok = ok and sparse_ok

    # Sched gate (ISSUE 12): the critical-path order must produce the
    # identical final map and move count as the legacy order AND beat
    # its makespan p95 on the heterogeneous family (no-regression on
    # the mixed_week soak), with the committed hetero_drain trace
    # regenerating byte-for-byte — all under the virtual clock, so the
    # gate is exact, not wall-clock-noisy.
    try:
        sched = bench_sched()
        sched_ok = sched["pass"]
    except Exception as e:  # any stage crash must fail THIS gate, not
        sched = {"error": first_line(e)}  # eat the results above it
        sched_ok = False
    ok = ok and sched_ok

    # Fleet-loop gate (ISSUE 13): coalesced converge cycles must land
    # on the IDENTICAL per-tenant final maps as the sequential
    # loop-per-tenant baseline at equal churn and equal SLO, with
    # measurably fewer device dispatches and higher converge-cycles/sec
    # — the fleet tier's dispatch-economics win must not silently erode.
    try:
        floop = bench_fleet_loop()
        floop_ok = floop["pass"]
    except Exception as e:  # any stage crash must fail THIS gate
        floop = {"error": first_line(e)}
        floop_ok = False
    ok = ok and floop_ok

    # Durability gate (ISSUE 18): the crash_storm recovery chain must
    # converge to the crash-free reference's final map bit-identically,
    # with recovery cold solves inside the counted attribution bound
    # and a zombie (pre-recovery) journal handle's append rejected and
    # counted — plus the recovery-time numbers the round reports.
    try:
        durability = bench_durability()
        durability_ok = durability["pass"]
    except Exception as e:  # any stage crash must fail THIS gate
        durability = {"error": first_line(e)}
        durability_ok = False
    ok = ok and durability_ok

    # Membudget gate (ISSUE 20): every solver entry's AOT peak bytes
    # must sit under its declarative HBM ceiling
    # (blance_tpu.analysis.membudget.HBM_BUDGETS) — the same table the
    # --ci static tier enforces, re-measured here so the perf artifact
    # embeds the measured-vs-budget evidence (detail.membudget) next to
    # the numbers it explains.
    try:
        mb_rows = _bench_membudget()
        mb_ok = bool(mb_rows) and all(r.get("ok") for r in mb_rows)
    except Exception as e:  # any stage crash must fail THIS gate
        mb_rows = [{"error": first_line(e)}]
        mb_ok = False
    ok = ok and mb_ok

    print(json.dumps({
        "metric": "delta-replan perf smoke (warm vs cold sweeps)",
        "value": res["warm_sweeps"],
        "unit": "sweeps",
        "vs_baseline": res["cold_sweeps"],
        "detail": {**res, "pipeline": pipe, "sparse": sparse,
                   "sched": sched, "fleet_loop": floop,
                   "durability": durability, "membudget": mb_rows},
        "pass": ok,
    }))
    if not ok:
        log(f"PERF-SMOKE FAILED: warm={res['warm_sweeps']} sweeps vs "
            f"cold={res['cold_sweeps']} (hit={res['warm_carry_hit']}, "
            f"identical={res['identical']}); pipeline "
            f"{'OK' if pipe_ok else f'FAILED: {pipe}'}; sparse "
            f"{'OK' if sparse_ok else f'FAILED: {sparse}'}; fleet_loop "
            f"{'OK' if floop_ok else f'FAILED: {floop}'}; durability "
            f"{'OK' if durability_ok else f'FAILED: {durability}'}; "
            f"membudget {'OK' if mb_ok else f'FAILED: {mb_rows}'}")
        sys.exit(1)


def _run_benchmarks(smoke, backend_note=None):
    import jax

    from blance_tpu.obs import device as obs_device

    # Device observatory: compile accounting always (per-stage counts in
    # detail.<stage>.device); the AOT cost analyses only at smoke sizes
    # — on a real device the extra AOT compile per bucket shape would
    # double the north-star compile cost for numbers XLA reports
    # identically at smoke scale.
    obs_device.enable(cost_analysis=smoke, sweep_trace=False)

    # Verify at the LARGEST node count benched (the headline shape),
    # regardless of config order.
    pallas, pallas_ok = verify_pallas(max(c[1] for c in CONFIGS))

    fused_ok = not smoke and verify_fused_engine()

    detail = {"configs": [], "pallas": pallas, "pallas_verified": pallas_ok,
              "fused_engine_verified": fused_ok,
              "device": str(jax.devices()[0]), "jax": jax.__version__,
              "backend": backend_note or jax.default_backend(),
              "runs_per_config": RUNS}
    save_progress(detail, "verified")

    # Pass 1 — ALL device work, headline config first: if the tunnel
    # wedges mid-session, the numbers already in hand (persisted after
    # every config) include the one the round is judged on.
    headline = None
    for P, N, is_headline in CONFIGS:
        entry = {"P": P, "N": N}
        detail["configs"].append(entry)
        try:
            entry.update(bench_tpu(P, N))
            # In degraded mode the numbers are host measurements; the
            # engine tag must say so, so nobody quotes them as device
            # results (the BENCH_r04/r05 lesson).
            entry["engine"] = backend_note or "matrix"
        except AssertionError:
            # An audit failure is a correctness regression, not a
            # capacity limit — the bench must fail loudly, not degrade.
            raise
        except Exception as e:
            # Expected at the north-star shape: the matrix engine's
            # [P, N] working set (~4 GB x several live copies at
            # 100k x 10k) exceeds one chip's HBM.  The fused engine
            # below, whose per-round traffic is O(P + N), is the
            # production path at that scale.
            log(f"[{P}x{N}] matrix engine failed ({type(e).__name__}: "
                f"{first_line(e)})")
            entry["matrix_error"] = first_line(e)
        if fused_ok:
            # The verify gate ran at 4096x512; this is a different static
            # shape — a lowering failure here must degrade to the matrix
            # result, not abort the bench.
            try:
                fused_res = bench_tpu(P, N, fused=True)
            except AssertionError:
                # Same contract as the matrix path: a failed audit is a
                # correctness regression and must abort loudly, not
                # silently degrade to the matrix headline.
                raise
            except Exception as e:
                log(f"[{P}x{N}] fused timed run failed "
                    f"({type(e).__name__}: {first_line(e)})")
                fused_res = None
            if fused_res is not None:
                entry["fused"] = fused_res
            if fused_res is not None and \
                    not any(fused_res["violations"].values()) and (
                    "solve_ms_min" not in entry
                    or fused_res["solve_ms_min"] < entry["solve_ms_min"]):
                # Both engines are production-selectable
                # (set_fused_score_default); report the better one as the
                # headline and name it.
                entry.update({k: fused_res[k] for k in
                              ("compile_s", "solve_ms_min",
                               "solve_ms_median", "solve_ms_runs",
                               "violations")})
                entry["engine"] = "fused"
        if "solve_ms_min" not in entry:
            # The engine tag must be present and truthful even when no
            # engine produced a number (the BENCH_local_r04 shape was a
            # matrix_error with the fused result carrying the config —
            # a both-engines-failed config previously had NO engine key,
            # so top-level and per-config reporting could disagree).
            entry["engine"] = backend_note or "none-failed"
            log(f"[{P}x{N}] no engine produced a result; config recorded "
                f"as failed")
            save_progress(detail, f"solve {P}x{N} failed")
            continue
        # End-to-end phases through the same engine as the headline solve.
        from blance_tpu.plan.tensor import set_fused_score_default

        set_fused_score_default("on" if entry["engine"] == "fused" else "off")
        try:
            entry["phases_ms"] = bench_phases(P, N)
        except Exception as e:  # phases are attribution detail — a
            log(f"[{P}x{N}] phase attribution failed "  # failure must not
                f"({type(e).__name__}: {first_line(e)})")  # eat the solve
            entry["phases_error"] = first_line(e)
        finally:
            set_fused_score_default("auto")
        save_progress(detail, f"solve {P}x{N} done")
        if is_headline:
            headline = entry

    # Pass 2 — CPU baselines (no device involvement: the measurement runs
    # in a cpu-pinned subprocess, so a wedged tunnel can't block it).
    for entry in detail["configs"]:
        if "solve_ms_min" not in entry:
            continue
        entry.update(bench_cpu(entry["P"], entry["N"]))
        if entry.get("cpu_s") is not None:
            entry["vs_baseline"] = round(
                entry["cpu_s"] * 1000 / entry["solve_ms_min"], 1)
        else:
            # Baseline failed (tagged in "baseline" above): an explicit
            # null, never a 0.0 sentinel a dashboard could mistake for a
            # measured "no speedup".
            entry["vs_baseline"] = None
        save_progress(detail, f"cpu {entry['P']}x{entry['N']} done")

    # Pipeline + metrics stage: exercise moves + orchestrate so the trace
    # and the "obs" block cover every layer, then embed the recorder's
    # aggregates (span totals, counters, histogram p50/p95 — including
    # orchestrate.move_latency_s) into the artifact.
    try:
        detail["pipeline"] = bench_pipeline()
    except Exception as e:  # attribution detail — must not eat the solve
        log(f"pipeline stage failed ({type(e).__name__}: {first_line(e)})")
        detail["pipeline_error"] = first_line(e)
    save_progress(detail, "pipeline done")

    # Chaos stage: transition completion under a fixed injected fault
    # rate — retries + quarantine + recovery replans end-to-end.  The
    # stage's `slo` block is the online SLO accounting's final reading.
    try:
        detail["chaos"] = bench_chaos()
    except Exception as e:  # must not eat the solve numbers
        log(f"chaos stage failed ({type(e).__name__}: {first_line(e)})")
        detail["chaos_error"] = first_line(e)
    save_progress(detail, "chaos done")

    # Simulator stage: a virtual day of closed-loop cluster life under
    # mixed faults — the horizon SLO account (time-weighted
    # availability, churn vs offline-optimal, convergence-lag
    # percentiles) plus sim-seconds-per-wall-second.
    try:
        detail["simulate"] = bench_simulate()
    except Exception as e:  # must not eat the solve numbers
        log(f"simulate stage failed ({type(e).__name__}: {first_line(e)})")
        detail["simulate_error"] = first_line(e)
    save_progress(detail, "simulate done")

    # Fleet-loop stage: N tenants' coalesced converge cycles vs the
    # sequential loop-per-tenant baseline (identical final maps, equal
    # churn, fewer device dispatches — ISSUE 13, docs/FLEET.md).
    try:
        detail["fleet_loop"] = bench_fleet_loop()
    except Exception as e:  # must not eat the solve numbers
        log(f"fleet-loop stage failed "
            f"({type(e).__name__}: {first_line(e)})")
        detail["fleet_loop_error"] = first_line(e)
    save_progress(detail, "fleet-loop done")

    # Cost-model stage: EWMA (node, op) move costs calibrated from the
    # chaos run's move-lifecycle spans, scored predicted-vs-actual.
    try:
        detail["costmodel"] = bench_costmodel()
    except Exception as e:  # must not eat the solve numbers
        log(f"costmodel stage failed ({type(e).__name__}: {first_line(e)})")
        detail["costmodel_error"] = first_line(e)
    save_progress(detail, "costmodel done")

    # Sched stage: critical-path scheduled move order vs the legacy
    # app-weight order at equal churn on hetero_drain + mixed_week —
    # makespan / convergence-lag p95 both ways, identity + committed-
    # trace-replay gates (ISSUE 12, docs/SCHEDULER.md).
    try:
        detail["sched"] = bench_sched()
    except Exception as e:  # must not eat the solve numbers
        log(f"sched stage failed ({type(e).__name__}: {first_line(e)})")
        detail["sched_error"] = first_line(e)
    save_progress(detail, "sched done")

    # Delta-replan stage: the incremental (warm-carry) replan against a
    # cold solve of the identical delta — cold vs warm sweeps and
    # wall-clock, plus the bit-identity contract.
    try:
        dp, dn = (512, 64) if smoke else (100_000, 1_000)
        detail["delta_replan"] = bench_delta_replan(dp, dn)
    except Exception as e:  # must not eat the solve numbers
        log(f"delta-replan stage failed "
            f"({type(e).__name__}: {first_line(e)})")
        detail["delta_replan_error"] = first_line(e)
    save_progress(detail, "delta-replan done")

    # Plan-pipeline stage: the fused single-dispatch encode→solve→diff
    # →decode-pack program vs the staged path — bit-identity asserted,
    # phases_ms reported for BOTH paths (the host-phase win), plus the
    # warm delta-replan end-to-end through the session fast path.
    try:
        pp, pn = (512, 64) if smoke else (100_000, 1_000)
        detail["plan_pipeline"] = bench_plan_pipeline(pp, pn)
        detail["plan_pipeline"]["warm"] = bench_warm_pipeline(pp, pn)
    except AssertionError:
        raise  # identity divergence is a correctness regression
    except Exception as e:  # must not eat the solve numbers
        log(f"plan-pipeline stage failed "
            f"({type(e).__name__}: {first_line(e)})")
        detail["plan_pipeline_error"] = first_line(e)
    save_progress(detail, "plan-pipeline done")

    # Sparse stage: the shortlist engine at the million-partition config
    # (ISSUE 11) — saturating-K bit-identity, the 1M x 1k solve with no
    # dense [P, S, N] score tensor, and AOT peak-bytes vs the dense
    # estimate.  Smoke sizes on cpu hosts.
    try:
        sp, sn = (4096, 128) if smoke else (1_000_000, 1_000)
        detail["sparse"] = bench_sparse(sp, sn)
    except AssertionError:
        raise  # a failed sparse audit is a correctness regression
    except Exception as e:  # must not eat the solve numbers
        log(f"sparse stage failed ({type(e).__name__}: {first_line(e)})")
        detail["sparse_error"] = first_line(e)
    save_progress(detail, "sparse done")

    # Fleet stage: 64 small tenant indexes solved per-tenant (the loop a
    # fleet replan runs today) vs batched by bucket class through the
    # vmapped fleet solver and the coalescing plan service — throughput
    # both ways plus p50/p99 admission-to-result latency.
    try:
        detail["fleet"] = bench_fleet()
    except Exception as e:  # must not eat the solve numbers
        log(f"fleet stage failed ({type(e).__name__}: {first_line(e)})")
        detail["fleet_error"] = first_line(e)
    detail["obs"] = obs_summary()
    save_progress(detail, "fleet done")

    if headline is None:
        # The headline config failed outright on every engine; fall back
        # to the largest config that did produce a number so the driver
        # artifact still carries a measured result (plus the failure
        # record above).
        done = [e for e in detail["configs"] if "solve_ms_min" in e]
        if not done:
            log("FATAL: no config produced a result")
            sys.exit(4)
        headline = max(done, key=lambda e: (e["P"], e["N"]))

    def _k(n):
        return f"{n // 1000}k" if n >= 1000 and n % 1000 == 0 else str(n)

    print(json.dumps({
        "metric": f"on-device converged solve @ {_k(headline['P'])} "
                  f"partitions x {_k(headline['N'])} nodes (primary+"
                  f"replica, rack rules, 5% node removal); phases + the "
                  f"other config in detail",
        "value": headline["solve_ms_min"],
        "unit": "ms",
        "vs_baseline": headline["vs_baseline"],
        "engine": backend_note or headline.get("engine"),
        "detail": detail,
    }))


if __name__ == "__main__":
    main()
