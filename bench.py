"""Benchmark: batched TPU planner vs the sequential CPU greedy planner.

Headline config (BASELINE.json north star direction): plan 100k partitions
x 1k nodes, primary + 1 replica, from a warm previous map with 5% of nodes
removed — the realistic delta-rebalance shape.  The TPU number is the
on-device solve (jit-compiled, post-warmup, synchronized); the CPU baseline
is this repo's own NATIVE C++ exact greedy planner at full size (the
strongest available CPU implementation — the reference publishes no
benchmark numbers, BASELINE.md, and this repo's C++ core is ~12x faster
end-to-end than the Python greedy).  Falls back to the Python greedy
measured at 1/25 scale and scaled linearly in P if the native toolchain is
missing.

Prints ONE JSON line:
  {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}
plus human-readable detail on stderr.
"""

import json
import sys
import time

import numpy as np

P_FULL = 100_000
N_NODES = 1_000
CPU_P = 4_000  # greedy measured here, scaled to P_FULL linearly


def log(*args):
    print(*args, file=sys.stderr, flush=True)


def build_dense(P, N, seed=0):
    rng = np.random.default_rng(seed)
    S, R = 2, 1
    prev = np.full((P, S, R), -1, np.int32)
    prev[:, 0, 0] = rng.integers(0, N, P)
    prev[:, 1, 0] = (prev[:, 0, 0] + 1 + rng.integers(0, N - 1, P)) % N
    pweights = np.ones(P, np.float32)
    nweights = np.ones(N, np.float32)
    valid = np.ones(N, bool)
    valid[rng.choice(N, N // 20, replace=False)] = False  # 5% nodes leave
    stickiness = np.full((P, S), 1.5, np.float32)
    gids = np.stack([np.arange(N, dtype=np.int32),
                     np.arange(N, dtype=np.int32) // 25,
                     np.zeros(N, np.int32)])
    gid_valid = np.ones((3, N), bool)
    constraints = (1, 1)
    rules = ((), ((2, 1),))  # replica on another rack
    return (prev, pweights, nweights, valid, stickiness, gids, gid_valid,
            constraints, rules)


def bench_tpu():
    import jax
    import jax.numpy as jnp
    from blance_tpu.plan.tensor import solve_dense_converged

    args = build_dense(P_FULL, N_NODES)
    (prev, pweights, nweights, valid, stickiness, gids, gid_valid,
     constraints, rules) = args
    dev_args = [jnp.asarray(a) for a in
                (prev, pweights, nweights, valid, stickiness, gids, gid_valid)]

    log(f"devices: {jax.devices()}")

    # block_until_ready is unreliable on the experimental axon platform, so
    # force completion with a small host copy ([P] primaries, ~400KB).
    def run():
        # The production path: solve iterated to the reference's fixpoint
        # (pass 2+ short-circuits through the warm-start pins).
        out = solve_dense_converged(*dev_args, constraints, rules)
        np.asarray(out[:, 0, 0])
        return out

    t0 = time.perf_counter()
    out = run()
    compile_s = time.perf_counter() - t0
    log(f"tpu compile+first-run: {compile_s:.2f}s")

    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        out = run()
        times.append(time.perf_counter() - t0)
    tpu_s = min(times)
    log(f"tpu solve {P_FULL}x{N_NODES}: {tpu_s*1000:.1f}ms (runs: "
        f"{[f'{t*1000:.1f}' for t in times]})")

    # Sanity: all primaries assigned, none on removed nodes.
    a = np.asarray(out)
    assert (a[:, 0, 0] >= 0).all()
    assert valid[a[a >= 0]].all(), "assignment used a removed node"
    return tpu_s


def bench_cpu_greedy():
    from blance_tpu import Partition, PlanOptions, model, plan_next_map
    from blance_tpu.plan.native import native_available

    use_native = native_available()
    cpu_p = P_FULL if use_native else CPU_P

    rng = np.random.default_rng(0)
    nodes = [f"n{i:04d}" for i in range(N_NODES)]
    removed = [nodes[i] for i in
               rng.choice(N_NODES, N_NODES // 20, replace=False)]
    m = model(primary=(0, 1), replica=(1, 1))
    prev = {}
    for i in range(cpu_p):
        p = rng.integers(0, N_NODES)
        r = (p + 1 + rng.integers(0, N_NODES - 1)) % N_NODES
        prev[str(i)] = Partition(str(i), {"primary": [nodes[p]],
                                          "replica": [nodes[r]]})
    opts = PlanOptions(max_iterations=1)  # single pass, same work as solve
    backend = "native" if use_native else "greedy"
    t0 = time.perf_counter()
    plan_next_map(prev, prev, nodes, removed, [], m, opts, backend=backend)
    cpu_s = time.perf_counter() - t0
    scaled = cpu_s * (P_FULL / cpu_p)
    log(f"cpu {backend} {cpu_p}x{N_NODES}: {cpu_s:.2f}s"
        + ("" if cpu_p == P_FULL else f" -> scaled to {P_FULL}: {scaled:.1f}s"))
    return scaled


def main():
    tpu_s = bench_tpu()
    cpu_s = bench_cpu_greedy()
    print(json.dumps({
        "metric": f"plan_next_map wall-clock @ {P_FULL//1000}k partitions x "
                  f"{N_NODES//1000}k nodes (primary+replica, rack rules, "
                  f"5% node removal)",
        "value": round(tpu_s * 1000, 2),
        "unit": "ms",
        "vs_baseline": round(cpu_s / tpu_s, 1),
    }))


if __name__ == "__main__":
    main()
