"""blance_tpu.parallel subpackage."""
