"""Multi-chip sharded planning: shard_map over the partition axis.

This is the framework's distributed backbone (the analog of the reference's
"scale" story, which is single-threaded Go — SURVEY.md §2.6).  The planning
problem shards cleanly over partitions: scores[P, N] are embarrassingly
parallel in P, and the only cross-shard state is per-node aggregate weight
(counts, capacity usage), which rides XLA collectives (psum) over ICI.

Design (SURVEY.md §5 long-context analog): the (P x S x N) cost tensor is
sharded over P with a jax.sharding.Mesh; each device runs the same auction
rounds on its partition shard with 1/n of every node's capacity, and psums
its per-node accepted weight so the price/counts every shard sees stay
globally consistent.  No gather of [P, N] ever materializes on one chip.

For >> 10k-node problems a SECOND mesh axis shards the node dimension of
every [P, N] intermediate (make_mesh_2d): per-row (min, argmin, second)
stats combine across node shards via all_gather + index arithmetic, remote
column reads ride a masked psum, and the [N]-sized vectors (counts,
capacity, prices — kilobytes) stay replicated along the node axis so the
capacity/acceptance logic is identical math everywhere (see
plan/tensor.py solve_dense's node_axis docs).
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.encode import DenseProblem, pad_to
from ..plan.tensor import (
    SolveCarry,
    _apply_sparse_fallback,
    _pipeline_cold_impl,
    _pipeline_warm_impl,
    _record_sweeps,
    _solve_sparse_converged_impl,
    _warm_repair,
    _warm_repair_sparse,
    carry_from_assignment,
    resolve_sparse_impl,
    solve_dense_converged,
    sparse_rules_supported,
)
from ..obs import device as _obs_device
from ..obs import get_recorder

# shard_map moved across JAX versions (jax.experimental.shard_map ->
# top-level jax.shard_map); resolve once so the pinned CI versions and
# newer runtimes both work.
try:
    _shard_map = jax.shard_map
except AttributeError:  # older jax (e.g. 0.4.x)
    from jax.experimental.shard_map import shard_map as _shard_map

__all__ = ["make_mesh", "make_mesh_2d", "make_hybrid_mesh",
           "make_mesh_auto", "mesh_shape_for", "slice_major_order",
           "solve_dense_sharded", "solve_pipeline_sharded",
           "solve_sparse_sharded",
           "pad_partitions", "pad_nodes", "SOLVER_IN_LAYOUT",
           "WARM_EXTRA_LAYOUT", "SPARSE_EXTRA_LAYOUT", "layout_specs"]

PARTITION_AXIS = "parts"
NODE_AXIS = "nodes"

# --- declarative shard layouts ----------------------------------------------
#
# THE one table of how solver operands lay out on a mesh: each entry is
# (operand name, "parts" = sharded over the partition axis | "replicated").
# Every shard_map dispatch here derives its in_specs from these rows, and
# the shape audit (analysis/shape_audit.py) builds its sharded contracts
# from the SAME table — so the audited layout and the dispatched layout
# cannot drift apart.  The node axis of a 2-D mesh never appears in the
# specs: [N]-shaped operands stay REPLICATED along it by design (see the
# module docstring) and the [P, N] splits happen inside solve_dense.

SOLVER_IN_LAYOUT: tuple[tuple[str, str], ...] = (
    ("prev", "parts"),
    ("pweights", "parts"),
    ("nweights", "replicated"),
    ("valid", "replicated"),
    ("stickiness", "parts"),
    ("gids", "replicated"),
    ("gid_valid", "replicated"),
)
WARM_EXTRA_LAYOUT: tuple[tuple[str, str], ...] = (
    ("dirty", "parts"),
    ("carry_used", "replicated"),
)
# Sparse solve: the [P, K] shortlist rides the partition axis with its
# prev rows; every [N]-shaped table stays replicated exactly like the
# dense layout (the sparse engine's fill/price/capacity are full-width
# by design).
SPARSE_EXTRA_LAYOUT: tuple[tuple[str, str], ...] = (
    ("shortlist", "parts"),
)
# Sparse solve outputs: assign + exhaustion flags are row-wise in P;
# the executed-sweep count is globally agreed.
SPARSE_COLD_OUT_LAYOUT: tuple[tuple[str, str], ...] = (
    ("assign", "parts"), ("sweeps", "replicated"),
    ("exhausted", "parts"),
)
SPARSE_WARM_OUT_LAYOUT: tuple[tuple[str, str], ...] = (
    ("assign", "parts"), ("used", "replicated"),
    ("ok", "replicated"), ("exhausted", "parts"),
)
# Pipeline outputs: assign + the diff/pack tensors are row-wise in P
# (shardable with zero collectives); the carry tables and scalars are
# psum'd/globally-agreed inside the body, hence replicated.
PIPELINE_COLD_OUT_LAYOUT: tuple[tuple[str, str], ...] = (
    ("assign", "parts"), ("sweeps", "replicated"),
    ("prices", "replicated"), ("used", "replicated"),
    ("d_nodes", "parts"), ("d_states", "parts"), ("d_ops", "parts"),
    ("packed", "parts"), ("counts", "parts"),
)
PIPELINE_WARM_OUT_LAYOUT: tuple[tuple[str, str], ...] = (
    ("assign", "parts"), ("prices", "replicated"),
    ("used", "replicated"), ("ok", "replicated"),
    ("d_nodes", "parts"), ("d_states", "parts"), ("d_ops", "parts"),
    ("packed", "parts"), ("counts", "parts"),
)


def layout_specs(layout: tuple) -> tuple:
    """Rows of a layout table -> PartitionSpecs for shard_map."""
    specs = []
    for name, kind in layout:
        if kind == "parts":
            specs.append(P(PARTITION_AXIS))
        elif kind == "replicated":
            specs.append(P())
        else:
            raise ValueError(
                f"layout row {name!r}: unknown kind {kind!r}")
    return tuple(specs)


def make_mesh(n_devices: Optional[int] = None) -> Mesh:
    """1-D device mesh over the partition axis."""
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (PARTITION_AXIS,))


def slice_major_order(slice_ids: list) -> list:
    """Stable slice-major device ordering: indices sorted by slice id,
    original order preserved within a slice.  Pure so the multi-slice
    path is unit-testable without multi-slice hardware."""
    return sorted(range(len(slice_ids)), key=lambda i: (slice_ids[i], i))


def make_hybrid_mesh(
    devices: Optional[list] = None,
    slice_ids: Optional[list] = None,
) -> Mesh:
    """Multi-slice (multi-host) 1-D mesh, DCN-aware.

    The solver's only cross-shard traffic is per-node [N] psums, so a 1-D
    partition axis works across slices — but the DEVICE ORDER matters:
    XLA lowers a psum over a flat axis hierarchically when devices that
    share ICI are contiguous in the mesh, keeping the heavy intra-slice
    hops on ICI and only one reduced copy per slice on DCN.  This helper
    orders devices slice-major (stable within a slice, preserving the
    runtime's topology order) to guarantee that contiguity; on a single
    slice it is equivalent to :func:`make_mesh`.

    ``devices`` / ``slice_ids`` default to the runtime's enumeration and
    each device's ``slice_index``; passing them explicitly lets tests
    (and exotic topologies) drive the multi-slice ordering with synthetic
    slice assignments — tests/test_sharded.py solves end-to-end on a
    2-slice hybrid mesh built from the 8 virtual CPU devices this way.

    Caveat: within a slice the runtime's enumeration order is trusted as
    ICI-reasonable.  On multi-host slices where jax.devices() enumerates
    by (process, local ordinal) but the physical torus differs,
    jax.experimental.mesh_utils.create_hybrid_device_mesh can arrange
    intra-slice devices by physical coordinates — worth benchmarking
    there; this helper prefers the simple order that is provably
    slice-contiguous and unit-testable (slice_major_order).
    """
    if devices is None:
        devices = jax.devices()
    if slice_ids is None:
        slice_ids = [getattr(d, "slice_index", 0) for d in devices]
    if len(slice_ids) != len(devices):
        raise ValueError(
            f"{len(slice_ids)} slice ids for {len(devices)} devices")
    if len(set(slice_ids)) > 1:
        order = slice_major_order(slice_ids)
        return Mesh(np.asarray([devices[i] for i in order]),
                    (PARTITION_AXIS,))
    return Mesh(np.asarray(list(devices)), (PARTITION_AXIS,))


def make_mesh_2d(
    parts_shards: int, node_shards: int,
    devices: Optional[list] = None,
) -> Mesh:
    """2-D (parts x nodes) device mesh for node-axis sharding.

    Lay the node axis minor (fastest-varying over adjacent devices): its
    per-round all_gather/psum of [P_l]-sized stats is the latency-bound
    traffic, so it should ride the shortest ICI hops.
    """
    devs = list(jax.devices() if devices is None else devices)
    need = parts_shards * node_shards
    if len(devs) < need:
        raise ValueError(f"need {need} devices, have {len(devs)}")
    arr = np.asarray(devs[:need]).reshape(parts_shards, node_shards)
    return Mesh(arr, (PARTITION_AXIS, NODE_AXIS))


# Per-axis shard caps for mesh_shape_for.  The per-shard [P_l, N_l]
# block is P*N/n_devices for EVERY factorization (memory cannot prefer
# one), so the factorization is chosen by per-AXIS extents instead:
# _PART_CAP is the partition rows one chip handles comfortably
# (calibrated: 100k x 10k solves on one v5e, so 128k rows per shard is
# conservative), _NODE_CAP the column width past which the ">> 10k
# nodes" guidance (module docstring) wants the node axis engaged — [N]
# replicated vectors and psums stay kilobytes-to-small below it.
_PART_CAP = 1 << 17  # 131072 partition rows per shard
_NODE_CAP = 1 << 14  # 16384 node columns per shard


def mesh_shape_for(
    n_devices: int,
    p: int,
    n: int,
    *,
    part_cap: int = _PART_CAP,
    node_cap: int = _NODE_CAP,
) -> tuple[int, int]:
    """(parts_shards, node_shards) for ANY device count — the mesh
    factorization rule that replaces the hand-picked 8-chip meshes.

    Pure and deterministic (unit-testable without devices).  Preference
    order: the partition axis (its only collectives are [N]-sized
    psums; the node axis adds per-round all_gathers of row stats), so
    among factorizations keeping both per-shard axis extents within
    their caps the fewest node shards wins — small problems on any
    fleet resolve to the plain 1-D partition mesh.  When no divisor of
    ``n_devices`` fits both caps (beyond-fleet problems: 1M x 1M on 8
    chips), the factorization minimizing the worst RELATIVE axis
    overload is returned, ties toward fewer node shards — both axes
    degrade together instead of one exploding.
    parts_shards * node_shards == n_devices always: every chip works.
    """
    if n_devices < 1:
        raise ValueError(f"n_devices must be >= 1, got {n_devices}")
    if p < 0 or n < 0:
        raise ValueError(f"negative problem dims ({p}, {n})")
    divisors = [d for d in range(1, n_devices + 1) if n_devices % d == 0]

    def overload(node_shards: int) -> float:
        parts = n_devices // node_shards
        p_l = -(-max(p, 1) // parts)
        n_l = -(-max(n, 1) // node_shards)
        return max(p_l / part_cap, n_l / node_cap)

    fitting = [d for d in divisors if overload(d) <= 1.0]
    if fitting:
        best = fitting[0]  # smallest node_shards: prefer the parts axis
    else:
        best = min(divisors, key=lambda d: (overload(d), d))
    return n_devices // best, best


def make_mesh_auto(
    p: int,
    n: int,
    devices: Optional[list] = None,
    slice_ids: Optional[list] = None,
) -> Mesh:
    """Problem-shaped mesh over ALL available devices: 1-D partition
    mesh (slice-major ordered across slices, like make_hybrid_mesh)
    while a partition-only split fits, 2-D (parts x nodes) beyond that
    — the beyond-8-chip entry point: 4, 8, 64 or 256 chips all resolve
    to a working factorization with no hand-tuned mesh shape."""
    if devices is None:
        devices = list(jax.devices())
    if slice_ids is None:
        slice_ids = [getattr(d, "slice_index", 0) for d in devices]
    if len(set(slice_ids)) > 1:
        order = slice_major_order(slice_ids)
        devices = [devices[i] for i in order]
    parts, nodes = mesh_shape_for(len(devices), p, n)
    if nodes == 1:
        return Mesh(np.asarray(devices), (PARTITION_AXIS,))
    return make_mesh_2d(parts, nodes, devices=devices)


def pad_partitions(arr: np.ndarray, multiple: int,
                   fill: float | int | bool) -> np.ndarray:
    """Pad axis 0 to a multiple of the mesh size.

    Padding rows use weight 0 so they bid without consuming capacity or
    affecting counts; their assignments are discarded at decode.
    """
    p = arr.shape[0]
    return pad_to(arr, 0, p + (-p) % multiple, fill)


def pad_nodes(arr: np.ndarray, multiple: int,
              fill: float | int | bool) -> np.ndarray:
    """Pad the trailing (node) axis to a multiple of the node-shard count.

    Padding nodes are invalid (valid=False ⇒ zero capacity, +INF score,
    gid_valid=False), so they can never be chosen; assignments therefore
    only ever reference real node ids.
    """
    n = arr.shape[-1]
    return pad_to(arr, arr.ndim - 1, n + (-n) % multiple, fill)


def _build_checked(sm, checked_ok: bool):
    """Build a shard_map'd fn, disabling the replication/vma checker
    when the body's collectives confuse it (see solve_dense_sharded).

    The disable kwarg has been renamed across JAX versions (check_vma
    today, check_rep before); probe by retrying rather than inspecting,
    so a version exposing neither still builds (and then simply runs
    with the checker on)."""
    if checked_ok:
        return sm()
    for kwargs in ({"check_vma": False}, {"check_rep": False}):
        try:
            return sm(**kwargs)
        except TypeError:
            continue
    # Neither kwarg exists: build with the checker on, outside the try
    # so a genuine shard_map TypeError propagates un-swallowed.
    return sm()


def solve_dense_sharded(
    mesh: Mesh,
    prev: np.ndarray,
    pweights: np.ndarray,
    nweights: np.ndarray,
    valid: np.ndarray,
    stickiness: np.ndarray,
    gids: np.ndarray,
    gid_valid: np.ndarray,
    constraints: tuple,
    rules: tuple,
    max_iterations: int = 10,
    fused_score: Optional[str] = None,
    dirty: Optional[np.ndarray] = None,
    carry: Optional[SolveCarry] = None,
    return_carry: bool = False,
    warm_only: bool = False,
):
    """Run the converged solve under shard_map, partition axis sharded.

    Accepts a 1-D ("parts",) or 2-D ("parts", "nodes") mesh (make_mesh /
    make_mesh_2d).  On a 2-D mesh the [P, N] intermediates inside the
    solver are sharded on BOTH axes; inputs here stay partition-sharded +
    node-replicated ([N] vectors are small — the memory that matters is
    the solver's internal [P, N] score, which is what the node axis
    splits).  Returns assign[P_original, S, R] (padding stripped), or
    (assign, SolveCarry) with ``return_carry``.

    With ``dirty`` + ``carry`` (both matching ``prev``) the solve runs
    the WARM path first: one carry-seeded repair sweep under shard_map —
    the carry's prices/used tables ride replicated along the node axis
    while the assignment stays sharded over partitions — accepted when
    the repair stayed inside the dirty mask (plan/tensor.py
    solve_dense_warm semantics), else falling back to the cold fixpoint
    below — or, with ``warm_only``, returning (None, None) so the
    caller owns the fallback (and its metrics/audit gates, matching the
    single-device solve_dense_warm contract).  Like the single-device
    warm path, the carry is consumed either way.  ``carry_hit`` is not
    counted here for the same reason as solve_dense_warm: the caller's
    gates decide what a hit is.
    """
    axes = dict(zip(mesh.axis_names, mesh.devices.shape))
    n_shards = axes[PARTITION_AXIS]
    node_shards = axes.get(NODE_AXIS, 1)
    node_axis = NODE_AXIS if node_shards > 1 else None
    p_orig = prev.shape[0]
    # Module-attribute access keeps the default and resolver
    # monkeypatch-visible (tests patch tensor-module attributes).
    from ..plan import tensor as _tensor

    # The per-shard solves run under shard_map (traced), where the tier-
    # band scale guard must skip — assert it here on the concrete host
    # values instead, once for the whole mesh.
    _tensor._check_tier_band_scale(
        prev, pweights, nweights, valid, stickiness, constraints, rules)

    # Resolve against the PER-SHARD slice: each device holds P/n_shards
    # rows (x N/node_shards columns) of every [P, N] intermediate, so
    # that is the working set the chip must fit.  None = follow the
    # module default, same as the single-chip entry points
    # (plan_next_map_tpu, PlannerSession.replan) — a caller who never
    # touches knobs gets "auto" on every path; both resolvers pass
    # explicit modes through untouched.
    shard_p = -(-prev.shape[0] // n_shards)
    shard_n = -(-np.asarray(nweights).shape[-1] // node_shards)
    if fused_score is None:
        fused_score = _tensor.resolve_default_fused_score(shard_p, shard_n)
    else:
        fused_score = _tensor.resolve_fused_score(
            fused_score, shard_p, shard_n)

    prev_p = pad_partitions(np.asarray(prev), n_shards, -1)
    pw_p = pad_partitions(np.asarray(pweights), n_shards, 0.0)
    st_p = pad_partitions(np.asarray(stickiness), n_shards, 0.0)
    nw_p = np.asarray(nweights)
    valid_p = np.asarray(valid)
    gids_p = np.asarray(gids)
    gv_p = np.asarray(gid_valid)
    if node_shards > 1:
        nw_p = pad_nodes(nw_p, node_shards, 1.0)
        valid_p = pad_nodes(valid_p, node_shards, False)
        gids_p = pad_nodes(gids_p, node_shards, -1)
        gv_p = pad_nodes(gv_p, node_shards, False)

    shard = P(PARTITION_AXIS)
    rep = P()
    # Pre-vma JAX (the check_rep model: no lax.pcast/pvary) has no
    # replication rule for while_loop, so the checker must be off on ANY
    # mesh there; vma-era JAX keeps it on for the plain 1-D matrix path.
    # Off the 1-D matrix path: the output is node-replicated by
    # construction — every node shard derives identical assignments from
    # the all_gathered stats, a property tests/test_sharded_2d.py proves
    # empirically (solves are bit-identical across node-shard counts) —
    # but the varying-axes checker can't see through the all_gather/psum
    # combine, so disable it on 2-D meshes.  The fused engine needs the
    # same disable on ANY mesh: the checker's per-op vma propagation
    # inside pallas_call rejects the kernel's mix of node-replicated [N]
    # tables and partition-varying columns (its outputs carry correct
    # vma annotations; the per-op walk is what can't see through).
    has_vma = hasattr(jax.lax, "pcast") or hasattr(jax.lax, "pvary")
    checked_ok = has_vma and not node_axis and fused_score == "off"
    device_put = lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec))

    dev_args = (
        device_put(jnp.asarray(prev_p), shard),
        device_put(jnp.asarray(pw_p), shard),
        device_put(jnp.asarray(nw_p), rep),
        device_put(jnp.asarray(valid_p), rep),
        device_put(jnp.asarray(st_p), shard),
        device_put(jnp.asarray(gids_p), rep),
        device_put(jnp.asarray(gv_p), rep),
    )

    rec = get_recorder()
    if dirty is not None and carry is not None:
        # Warm repair sweep: dirty rides the partition axis (padding
        # rows are marked dirty — their synthetic assignments must not
        # read as a ripple), the carry's [S, N] fill table is replicated
        # like every other [N]-shaped vector.
        dirty_p = pad_partitions(
            np.asarray(dirty, bool), n_shards, True)
        cu = np.asarray(carry.used, np.float32)
        if node_shards > 1:
            cu = pad_nodes(cu, node_shards, 0.0)
        rec.observe("plan.solve.dirty_fraction",
                    float(np.asarray(dirty, bool).mean())
                    if np.asarray(dirty).size else 0.0)
        body_w = partial(
            _warm_repair,
            constraints=constraints, rules=rules,
            axis_name=PARTITION_AXIS, node_axis=node_axis,
            node_shards=node_shards, fused_score=fused_score)
        sm_w = partial(_shard_map, body_w, mesh=mesh,
                       in_specs=layout_specs(
                           SOLVER_IN_LAYOUT + WARM_EXTRA_LAYOUT),
                       out_specs=(shard, rep, rep))
        fn_w = _build_checked(sm_w, checked_ok)
        with rec.span("plan.solve.attempt", warm=True, sharded=True), \
                _obs_device.entry("sharded.warm"):
            # transfer_guard allowlist: dispatching a fresh shard_map
            # executable uploads its jaxpr closure constants as
            # replicated buffers — an IMPLICIT transfer by jax's
            # classification, but intrinsic to compilation, not an
            # accidental per-call sync.  All operands above are explicit
            # device_puts; only the dispatch itself is exempted.
            with jax.transfer_guard("allow"):
                out, new_used, ok = fn_w(
                    *dev_args,
                    device_put(jnp.asarray(dirty_p), shard),
                    device_put(jnp.asarray(cu), rep))
            accepted = bool(ok)
        if accepted:
            _record_sweeps(1)
            rec.set_attr("warm", True)
            assign = np.asarray(out)[:p_orig]
            if not return_carry:
                return assign
            # Strip node padding: pad columns are invalid nodes with
            # zero fill, and the session's carry is unpadded-N shaped.
            n_orig = np.asarray(nweights).shape[-1]
            used = jnp.asarray(np.asarray(new_used)[:, :n_orig])
            return assign, SolveCarry(
                prices=jnp.sum(used, axis=0), assign=jnp.asarray(assign),
                used=used)
        rec.count("plan.solve.warm_fallback")
        rec.count("plan.solve.sweeps", 1)  # the executed repair pass
        if warm_only:
            return (None, None) if return_carry else None

    body = partial(
        solve_dense_converged,
        constraints=constraints,
        rules=rules,
        axis_name=PARTITION_AXIS,
        max_iterations=max_iterations,
        node_axis=node_axis,
        node_shards=node_shards,
        fused_score=fused_score,
    )
    sm = partial(_shard_map, body, mesh=mesh,
                 in_specs=layout_specs(SOLVER_IN_LAYOUT),
                 out_specs=shard)
    fn = _build_checked(sm, checked_ok)
    # Same dispatch-time constant-upload exemption as the warm path.
    # The observatory attribution is first-wins, so the body's inner
    # solve_dense_converged labels stay subordinate to "sharded.cold".
    with jax.transfer_guard("allow"), _obs_device.entry("sharded.cold"):
        out = fn(*dev_args)
    assign = np.asarray(out)[:p_orig]
    if return_carry:
        return assign, carry_from_assignment(
            assign, np.asarray(pweights, np.float32),
            np.asarray(nweights, np.float32))
    return assign


def solve_sparse_sharded(
    mesh: Mesh,
    prev: np.ndarray,
    pweights: np.ndarray,
    nweights: np.ndarray,
    valid: np.ndarray,
    stickiness: np.ndarray,
    gids: np.ndarray,
    gid_valid: np.ndarray,
    constraints: tuple,
    rules: tuple,
    *,
    k: Optional[int] = None,
    shortlist: Optional[np.ndarray] = None,
    max_iterations: int = 10,
    sparse_impl: Optional[str] = None,
    dirty: Optional[np.ndarray] = None,
    carry: Optional[SolveCarry] = None,
    return_carry: bool = False,
    warm_only: bool = False,
):
    """The sparse shortlist solve under shard_map, partition axis
    sharded — the [P, K] score tables (and the shortlist itself) ride
    the partition axis via the declarative layout rows
    (``SPARSE_EXTRA_LAYOUT``) while every [N]-shaped fill/price table
    stays replicated, exactly like the dense layout.  1-D partition
    meshes only: the shortlist already bounds the column working set,
    so a node axis would shard kilobytes.

    The shortlist is derived on the PADDED problem (pad rows are
    weight-0 bidders with the same global candidates as the dense
    engine's pads see), or adopted from ``shortlist`` and padded.  With
    ``dirty`` + ``carry`` the warm one-sweep sparse repair runs first
    under the solve_dense_sharded warm contract (``warm_only``
    included); exhausted rows of an accepted result are re-placed by
    the host-side per-row dense fallback, after padding strips."""
    axes = dict(zip(mesh.axis_names, mesh.devices.shape))
    n_shards = axes[PARTITION_AXIS]
    if axes.get(NODE_AXIS, 1) > 1:
        raise ValueError(
            "solve_sparse_sharded: node-axis meshes are not supported "
            "(the [P, K] shortlist already bounds the column working "
            "set); use a 1-D partition mesh")
    p_orig = prev.shape[0]
    from ..plan import tensor as _tensor

    constraints = tuple(int(c) for c in constraints)
    rules = tuple(tuple(r) for r in rules)
    if not sparse_rules_supported(rules):
        raise ValueError(
            "sparse solve requires nesting hierarchy rules "
            "(exclude_level < include_level); use solve_dense_sharded")
    _tensor._check_tier_band_scale(
        prev, pweights, nweights, valid, stickiness, constraints, rules)
    impl = resolve_sparse_impl(sparse_impl)

    prev_p = pad_partitions(np.asarray(prev), n_shards, -1)
    pw_p = pad_partitions(np.asarray(pweights), n_shards, 0.0)
    st_p = pad_partitions(np.asarray(stickiness), n_shards, 0.0)

    rec = get_recorder()
    sl_in = None if shortlist is None \
        else pad_partitions(np.asarray(shortlist), n_shards, -1)
    sl_p = _tensor._build_or_adopt_shortlist(
        prev_p, pw_p, nweights, valid, gids, gid_valid, constraints,
        rules, sl_in, k, True)

    shard = P(PARTITION_AXIS)
    rep = P()
    has_vma = hasattr(jax.lax, "pcast") or hasattr(jax.lax, "pvary")
    # The pallas sparse2 kernel needs the checker off for the same
    # reason as the fused dense engine on any mesh (see
    # solve_dense_sharded): the per-op vma propagation inside
    # pallas_call rejects the kernel's mix of node-replicated tables
    # and partition-varying columns, even though its outputs carry
    # correct annotations.
    checked_ok = has_vma and impl != "pallas"
    device_put = lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec))
    dev_args = (
        device_put(jnp.asarray(prev_p), shard),
        device_put(jnp.asarray(pw_p), shard),
        device_put(jnp.asarray(nweights), rep),
        device_put(jnp.asarray(valid), rep),
        device_put(jnp.asarray(st_p), shard),
        device_put(jnp.asarray(gids), rep),
        device_put(jnp.asarray(gid_valid), rep),
        device_put(jnp.asarray(sl_p), shard),
    )

    def finish(assign_np, exh_np, used=None):
        """Strip padding, run the host fallback on flagged REAL rows,
        rebuild the carry when asked (always from the final patched
        assignment — the fallback invalidates any device-side used)."""
        assign_np = assign_np[:p_orig]
        patched, replaced = _apply_sparse_fallback(
            assign_np, exh_np[:p_orig], np.asarray(prev), pweights,
            nweights, valid, stickiness, gids, gid_valid, constraints,
            rules)
        if not return_carry:
            return patched
        if replaced or used is None:
            return patched, carry_from_assignment(
                patched, np.asarray(pweights, np.float32),
                np.asarray(nweights, np.float32))
        used_j = jnp.asarray(np.asarray(used))
        return patched, SolveCarry(
            prices=jnp.sum(used_j, axis=0),
            assign=jnp.asarray(patched), used=used_j)

    if dirty is not None and carry is not None:
        dirty_p = pad_partitions(np.asarray(dirty, bool), n_shards, True)
        cu = np.asarray(carry.used, np.float32)
        rec.observe("plan.solve.dirty_fraction",
                    float(np.asarray(dirty, bool).mean())
                    if np.asarray(dirty).size else 0.0)
        sparse_body_w = partial(
            _warm_repair_sparse, constraints=constraints, rules=rules,
            axis_name=PARTITION_AXIS, sparse_impl=impl)
        sm_w = partial(
            _shard_map, sparse_body_w, mesh=mesh,
            in_specs=layout_specs(SOLVER_IN_LAYOUT + SPARSE_EXTRA_LAYOUT
                                  + WARM_EXTRA_LAYOUT),
            out_specs=layout_specs(SPARSE_WARM_OUT_LAYOUT))
        fn_w = _build_checked(sm_w, checked_ok)
        with rec.span("plan.solve.attempt", warm=True, sharded=True,
                      engine="sparse"), \
                _obs_device.entry("sparse.sharded.warm"):
            # Same dispatch-time constant-upload exemption as the dense
            # sharded paths (see solve_dense_sharded).
            with jax.transfer_guard("allow"):
                out, new_used, ok, exh = fn_w(
                    *dev_args,
                    device_put(jnp.asarray(dirty_p), shard),
                    device_put(jnp.asarray(cu), rep))
            accepted = bool(ok)
        if accepted:
            _record_sweeps(1)
            rec.set_attr("warm", True)
            return finish(np.asarray(out), np.asarray(exh), new_used)
        rec.count("plan.solve.warm_fallback")
        rec.count("plan.solve.sweeps", 1)  # the executed repair pass
        if warm_only:
            return (None, None) if return_carry else None

    sparse_body = partial(
        _solve_sparse_converged_impl, constraints=constraints,
        rules=rules, axis_name=PARTITION_AXIS,
        max_iterations=max_iterations, sparse_impl=impl)
    sm = partial(
        _shard_map, sparse_body, mesh=mesh,
        in_specs=layout_specs(SOLVER_IN_LAYOUT + SPARSE_EXTRA_LAYOUT),
        out_specs=layout_specs(SPARSE_COLD_OUT_LAYOUT))
    fn = _build_checked(sm, checked_ok)
    with rec.span("plan.solve.attempt", sharded=True, engine="sparse"), \
            jax.transfer_guard("allow"), \
            _obs_device.entry("sparse.sharded.cold"):
        out, sweeps, exh = fn(*dev_args)
    _record_sweeps(sweeps)
    return finish(np.asarray(out), np.asarray(exh))


@lru_cache(maxsize=64)
def _pipeline_sharded_fn(
    mesh: Mesh,
    constraints: tuple,
    rules: tuple,
    max_iterations: int,
    fused_score: str,
    favor_min_nodes: bool,
    node_axis: Optional[str],
    node_shards: int,
    warm: bool,
):
    """Build-and-jit one sharded pipeline dispatch, memoized on (mesh,
    statics).  The eager shard_map spelling recompiles its sub-programs
    on EVERY call (the builder closures are fresh objects, so nothing
    keys the cache); jitting the built fn and caching it here makes
    repeat dispatches hit the jit cache — the bounded-compilation
    contract the retrace budget (analysis/retrace.py, sharded.pipeline)
    pins."""
    if warm:
        pipe_body = partial(
            _pipeline_warm_impl,
            constraints=constraints, rules=rules,
            axis_name=PARTITION_AXIS, node_axis=node_axis,
            node_shards=node_shards, fused_score=fused_score,
            favor_min_nodes=favor_min_nodes)
        in_layout = SOLVER_IN_LAYOUT + WARM_EXTRA_LAYOUT
        out_layout = PIPELINE_WARM_OUT_LAYOUT
    else:
        pipe_body = partial(
            _pipeline_cold_impl,
            constraints=constraints, rules=rules,
            axis_name=PARTITION_AXIS, max_iterations=max_iterations,
            node_axis=node_axis, node_shards=node_shards,
            fused_score=fused_score, favor_min_nodes=favor_min_nodes)
        in_layout = SOLVER_IN_LAYOUT
        out_layout = PIPELINE_COLD_OUT_LAYOUT
    sm = partial(_shard_map, pipe_body, mesh=mesh,
                 in_specs=layout_specs(in_layout),
                 out_specs=layout_specs(out_layout))
    return jax.jit(_build_checked(sm, False))


def solve_pipeline_sharded(
    mesh: Mesh,
    prev: np.ndarray,
    pweights: np.ndarray,
    nweights: np.ndarray,
    valid: np.ndarray,
    stickiness: np.ndarray,
    gids: np.ndarray,
    gid_valid: np.ndarray,
    constraints: tuple,
    rules: tuple,
    max_iterations: int = 10,
    fused_score: Optional[str] = None,
    favor_min_nodes: bool = False,
    dirty: Optional[np.ndarray] = None,
    carry: Optional[SolveCarry] = None,
    warm_only: bool = False,
):
    """The fused plan pipeline (solve -> diff -> pack) under shard_map.

    The diff and the decode pack are row-wise in P — they shard over the
    partition axis with ZERO additional collectives, so the pipeline
    scales exactly as far as the solve does (any mesh mesh_shape_for /
    make_mesh_auto produces, 1-D or 2-D, beyond the fixed 8-chip
    layouts).  Returns (assign, SolveCarry, (d_nodes, d_states, d_ops))
    with padding stripped — the tuple PlannerSession.replan_with_moves
    consumes — or None when ``warm_only`` and the repair declined.

    With ``dirty`` + ``carry`` the warm one-sweep repair runs first,
    accepted under the solve_dense_warm contract; declined repairs fall
    through to the cold fixpoint unless ``warm_only``.  The replication
    checker stays off for the pipeline bodies: the psum'd carry tables
    and globally-agreed scalars come back through replicated out_specs
    the per-op vma walk cannot see through (same class of disable as the
    2-D/fused paths above).
    """
    axes = dict(zip(mesh.axis_names, mesh.devices.shape))
    n_shards = axes[PARTITION_AXIS]
    node_shards = axes.get(NODE_AXIS, 1)
    node_axis = NODE_AXIS if node_shards > 1 else None
    p_orig = prev.shape[0]
    n_orig = np.asarray(nweights).shape[-1]
    from ..plan import tensor as _tensor

    _tensor._check_tier_band_scale(
        prev, pweights, nweights, valid, stickiness, constraints, rules)
    shard_p = -(-prev.shape[0] // n_shards)
    shard_n = -(-n_orig // node_shards)
    if fused_score is None:
        fused_score = _tensor.resolve_default_fused_score(shard_p, shard_n)
    else:
        fused_score = _tensor.resolve_fused_score(
            fused_score, shard_p, shard_n)

    prev_p = pad_partitions(np.asarray(prev), n_shards, -1)
    pw_p = pad_partitions(np.asarray(pweights), n_shards, 0.0)
    st_p = pad_partitions(np.asarray(stickiness), n_shards, 0.0)
    nw_p = np.asarray(nweights)
    valid_p = np.asarray(valid)
    gids_p = np.asarray(gids)
    gv_p = np.asarray(gid_valid)
    if node_shards > 1:
        nw_p = pad_nodes(nw_p, node_shards, 1.0)
        valid_p = pad_nodes(valid_p, node_shards, False)
        gids_p = pad_nodes(gids_p, node_shards, -1)
        gv_p = pad_nodes(gv_p, node_shards, False)

    shard = P(PARTITION_AXIS)
    device_put = lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec))
    dev_args = tuple(
        device_put(jnp.asarray(a), spec)
        for a, spec in zip(
            (prev_p, pw_p, nw_p, valid_p, st_p, gids_p, gv_p),
            layout_specs(SOLVER_IN_LAYOUT)))

    rec = get_recorder()

    def strip(out, new_used, darrs):
        # The padded run's prices/packed ride along unused: the carry is
        # rebuilt off the node-stripped used table, and the session's
        # decode runs off ``current``/``proposed``, not the batch.
        assign = np.asarray(out)[:p_orig]
        used = jnp.asarray(np.asarray(new_used)[:, :n_orig])
        carry_out = SolveCarry(
            prices=jnp.sum(used, axis=0), assign=jnp.asarray(assign),
            used=used)
        d_nodes, d_states, d_ops = (np.asarray(a)[:p_orig] for a in darrs)
        return assign, carry_out, (d_nodes, d_states, d_ops)

    if dirty is not None and carry is not None:
        dirty_p = pad_partitions(np.asarray(dirty, bool), n_shards, True)
        cu = np.asarray(carry.used, np.float32)
        if node_shards > 1:
            cu = pad_nodes(cu, node_shards, 0.0)
        rec.observe("plan.solve.dirty_fraction",
                    float(np.asarray(dirty, bool).mean())
                    if np.asarray(dirty).size else 0.0)
        fn_w = _pipeline_sharded_fn(
            mesh, constraints, rules, max_iterations, fused_score,
            favor_min_nodes, node_axis, node_shards, warm=True)
        t0 = rec.now()
        with rec.span("plan.pipeline.dispatch", warm=True, sharded=True), \
                _obs_device.entry("sharded.pipeline"):
            # Same dispatch-time constant-upload exemption as
            # solve_dense_sharded's paths.
            with jax.transfer_guard("allow"):
                (out, prices, new_used, ok, d_nodes, d_states, d_ops,
                 packed, counts) = fn_w(
                    *dev_args,
                    device_put(jnp.asarray(dirty_p), shard),
                    device_put(jnp.asarray(cu), P()))
            accepted = bool(ok)
        rec.observe("plan.pipeline.dispatch_s", rec.now() - t0)
        if accepted:
            _record_sweeps(1)
            rec.set_attr("warm", True)
            return strip(out, new_used, (d_nodes, d_states, d_ops))
        rec.count("plan.solve.warm_fallback")
        rec.count("plan.solve.sweeps", 1)  # the executed repair pass
        if warm_only:
            return None

    fn = _pipeline_sharded_fn(
        mesh, constraints, rules, max_iterations, fused_score,
        favor_min_nodes, node_axis, node_shards, warm=False)
    t0 = rec.now()
    with rec.span("plan.pipeline.dispatch", sharded=True), \
            jax.transfer_guard("allow"), \
            _obs_device.entry("sharded.pipeline"):
        (out, sweeps, prices, new_used, d_nodes, d_states, d_ops,
         packed, counts) = fn(*dev_args)
    rec.observe("plan.pipeline.dispatch_s", rec.now() - t0)
    _record_sweeps(sweeps)
    return strip(out, new_used, (d_nodes, d_states, d_ops))


def solve_problem_sharded(
    mesh: Mesh, problem: DenseProblem, fused_score: Optional[str] = None
) -> np.ndarray:
    """Convenience: solve an encoded DenseProblem on a mesh."""
    rules = tuple(tuple(problem.rules.get(si, ())) for si in range(problem.S))
    constraints = tuple(int(c) for c in problem.constraints)
    return solve_dense_sharded(
        mesh,
        problem.prev,
        problem.partition_weights,
        problem.node_weights,
        problem.valid_node,
        problem.stickiness,
        problem.gids,
        problem.gid_valid,
        constraints,
        rules,
        fused_score=fused_score,
    )
