"""Epoch fencing: make a recovered control plane safe from its ghosts.

Recovery creates a successor controller for state a predecessor may
still believe it owns — an orchestrator task not yet garbage-collected,
a mover callback resolving after the crash decision, or (across
processes) a stale controller that lost a lease but not its file
descriptors.  The classic defense is a fenced epoch: a monotone counter
per journal directory, bumped by every ``recover()``, stamped on every
journal append and every dispatched move.  A completion or append
carrying an older epoch is REJECTED and counted
(``durability.stale_epoch_rejections``); it is never applied, so the
worst a zombie can do is waste one callback, not corrupt the map.

Two layers enforce it:

- in-process: every :class:`~blance_tpu.durability.journal.Journal` and
  every ``Orchestrator`` capture ``fence.current`` at construction and
  re-check it at each append / batch completion.  The fence object is
  shared per journal directory through a process-level registry
  (:func:`fence_for`), so a bump is visible to the zombie immediately.
- cross-process: the epoch is persisted (``EPOCH`` file, crash-atomic)
  and every recovery writes a ``fence`` journal record freezing the
  valid record count of every pre-existing segment; replay truncates
  anything a fenced writer appended past that point
  (:func:`~blance_tpu.durability.journal.read_journal`).
"""

from __future__ import annotations

import json
import os
from typing import Optional

from ..utils.atomicio import atomic_write_json

__all__ = ["EPOCH_FILE", "EpochFence", "StaleEpochError", "fence_for",
           "reset_fences"]

EPOCH_FILE = "EPOCH"


class StaleEpochError(Exception):
    """A move completion (or append) carried a fenced epoch — the writer
    predates the last recovery and must not mutate state."""

    def __init__(self, what: str, epoch: int, current: int) -> None:
        super().__init__(
            f"{what}: epoch {epoch} is fenced (current epoch {current})")
        self.what = what
        self.epoch = epoch
        self.current = current


class EpochFence:
    """The monotone epoch counter for one journal directory.

    Plain sync state with no awaits (single-task discipline, see
    analysis/race_lint.py SHARED_STATE): ``bump`` happens on the
    recovery path, ``valid`` on append/completion paths — on one event
    loop these interleave atomically.
    """

    def __init__(self, journal_dir: str, epoch: int = 0) -> None:
        self._dir = journal_dir
        self._epoch = epoch

    @property
    def current(self) -> int:
        return self._epoch

    def valid(self, epoch: int) -> bool:
        """True when ``epoch`` is the live epoch (zombies carry older)."""
        return epoch == self._epoch

    def bump(self) -> int:
        """Advance the epoch and persist it (crash-atomic) before any
        successor writes under it — a crash between bump and first
        append must still fence the predecessor on the NEXT recovery."""
        self._epoch += 1
        os.makedirs(self._dir, exist_ok=True)
        atomic_write_json(os.path.join(self._dir, EPOCH_FILE),
                          {"epoch": self._epoch})
        return self._epoch


# Process-level registry: one fence object per journal directory, so a
# zombie controller in the SAME process shares the object a recovery
# bumped (the in-process fencing layer).
_fences: dict[str, EpochFence] = {}


def fence_for(journal_dir: str) -> EpochFence:
    """The shared fence for ``journal_dir`` (created on first use,
    seeded from the persisted ``EPOCH`` file when one exists)."""
    key = os.path.realpath(journal_dir)
    fence = _fences.get(key)
    if fence is None:
        fence = _fences[key] = EpochFence(
            journal_dir, _load_epoch(journal_dir))
    return fence


def _load_epoch(journal_dir: str) -> int:
    path = os.path.join(journal_dir, EPOCH_FILE)
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return 0
    epoch: Optional[object] = data.get("epoch") \
        if isinstance(data, dict) else None
    return epoch if isinstance(epoch, int) else 0


def reset_fences() -> None:
    """Drop the process-level fence registry (test isolation only —
    production code never unfences a directory)."""
    _fences.clear()
