"""Crash-tolerant control plane: WAL, snapshots, epoch fencing, recovery.

The control plane (``CycleEngine`` / ``RebalanceController`` /
``FleetController``) is a long-lived process whose entire state —
current maps, pending deltas, breaker state, SLO horizon accounting,
in-flight move cursors — is process memory.  This package makes that
state survive the process:

- :mod:`.journal` — a versioned, CRC-checked, append-only write-ahead
  journal (tenant-tagged records, crash-atomic segment rotation) fed
  from the controllers' existing sync windows, plus periodic snapshots.
- :mod:`.epoch` — fenced epochs: every recovery bumps the journal
  directory's epoch, so a zombie pre-crash writer or stale process is
  rejected as a counted ``durability.stale_epoch_rejections`` event,
  never a state corruption.
- :mod:`.recover` — ``recover(journal_dir)``: rebuild controller/fleet
  state from snapshot + journal replay and resume mid-rebalance from
  the journaled achieved map through the existing recovery machinery.

Format rules, snapshot cadence, fencing and the recovery workflow are
documented in docs/DURABILITY.md; every ``durability.*`` metric is in
docs/OBSERVABILITY.md.
"""

from __future__ import annotations

from .epoch import EpochFence, StaleEpochError, fence_for, reset_fences
from .journal import (
    JOURNAL_FORMAT_VERSION,
    Journal,
    JournalFeed,
    Record,
    ReadStats,
    TenantView,
    encode_record,
    map_digest,
    read_journal,
    read_segment,
)
from .recover import RecoveredState, RecoveredTenant, recover, resume_controller

__all__ = [
    "EpochFence",
    "StaleEpochError",
    "fence_for",
    "reset_fences",
    "JOURNAL_FORMAT_VERSION",
    "Journal",
    "JournalFeed",
    "Record",
    "ReadStats",
    "TenantView",
    "encode_record",
    "map_digest",
    "read_journal",
    "read_segment",
    "RecoveredState",
    "RecoveredTenant",
    "recover",
    "resume_controller",
]
