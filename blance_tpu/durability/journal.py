"""The write-ahead journal: versioned, CRC-checked, append-only.

Framing: one record per line, ``<crc32 hex, 8 chars> <canonical JSON>``.
The JSON is canonical (sorted keys, no whitespace) so a record's bytes
are a pure function of its content — the replay-determinism tests
compare journals byte-for-byte.  The CRC covers the JSON payload; a
torn final write (power loss mid-append) or a corrupted record fails
the CRC/parse and truncates replay to the last valid prefix, counted as
``durability.torn_tail`` — never a crash loop.

Every record carries::

    {"v": 1, "seq": N, "epoch": E, "tenant": key-or-null,
     "kind": ..., "t": virtual-seconds, "data": {...}}

Record kinds (schema detail in docs/DURABILITY.md):

- ``genesis``  — initial map + membership when a journal attaches to a
  controller; makes recovery self-contained before the first snapshot.
- ``delta``    — one ``ClusterDelta`` at intake (``_on_submit``).
- ``cycle``    — cycle begin: deltas taken from the pending queue.
- ``plan``     — a non-trivial plan landed (pass number, move count).
- ``batch``    — one executed batch outcome: the achieved-map delta
  (the journal is a ``MoveObserver``).
- ``strip``    — placements dropped for fresh-failed/quarantined nodes.
- ``quiesce``  — the controller went idle; carries a map digest.
- ``snapshot`` — pointer to a snapshot file (written AFTER the file is
  durable, so a pointer never references a torn snapshot).
- ``fence``    — written by every recovery: freezes each pre-existing
  segment's valid record count so a fenced writer's later appends are
  truncated on replay (see durability/epoch.py).

Segments are ``wal-<epoch>-<index>.log``; the index is globally
monotone, so replay order is the segment order.  Rotation is
crash-atomic: the new segment file is born via the shared fsync'd
temp+rename recipe (utils/atomicio.py), so a crash mid-rotation leaves
either the old tail or a complete empty successor — never a
half-created name.  Appends fsync by default (``BLANCE_WAL_FSYNC=0``
gates it off for CI).

Concurrency discipline (analysis/race_lint.py SHARED_STATE): all
journal methods are plain sync code with no awaits, called from the
controller's cycle task and the movers' observer window — on one event
loop each append is atomic, so seq numbers and segment state cannot
tear.
"""

from __future__ import annotations

import json
import os
import re
import zlib
from dataclasses import dataclass, field as dataclasses_field
from typing import Any, Callable, Mapping, Optional, Sequence

from ..obs import get_recorder
from ..utils.atomicio import atomic_write_json, atomic_write_text, \
    fsync_enabled
from .epoch import EpochFence, fence_for

__all__ = ["JOURNAL_FORMAT_VERSION", "Journal", "JournalFeed", "Record",
           "ReadStats", "TenantView", "encode_record", "list_segments",
           "map_digest", "read_journal", "read_segment"]

JOURNAL_FORMAT_VERSION = 1

_SEGMENT_RE = re.compile(r"^wal-(\d{6})-(\d{6})\.log$")
_TENANT_SAFE_RE = re.compile(r"[^A-Za-z0-9_.-]")


@dataclass(frozen=True)
class Record:
    """One decoded journal record."""

    seq: int
    epoch: int
    kind: str
    t: float
    tenant: Optional[str]
    data: dict[str, Any]


def _canon(obj: Any) -> str:
    return json.dumps(obj, sort_keys=True, separators=(",", ":"))


def encode_record(seq: int, epoch: int, kind: str, t: float,
                  tenant: Optional[str], data: Mapping[str, Any]) -> str:
    """One framed journal line (CRC + canonical JSON + newline)."""
    payload = _canon({"v": JOURNAL_FORMAT_VERSION, "seq": seq,
                      "epoch": epoch, "kind": kind, "t": t,
                      "tenant": tenant, "data": dict(data)})
    crc = zlib.crc32(payload.encode("utf-8")) & 0xFFFFFFFF
    return f"{crc:08x} {payload}\n"


def map_digest(pmap: Mapping[str, Any]) -> str:
    """Order-insensitive-at-the-top-level digest of a partition map
    (CRC32 of its canonical JSON) — the quiesce record's cheap
    divergence probe; full maps live in genesis/snapshot records."""
    canon = _canon({name: p.to_json() for name, p in sorted(pmap.items())})
    return f"{zlib.crc32(canon.encode('utf-8')) & 0xFFFFFFFF:08x}"


def _parse_line(line: bytes) -> Optional[Record]:
    """Decode one framed line; None on ANY defect (framing, CRC, JSON,
    schema) — the caller treats the defect as the torn tail."""
    if len(line) < 10 or line[8:9] != b" ":
        return None
    crc_hex, payload = line[:8], line[9:]
    try:
        want = int(crc_hex, 16)
    except ValueError:
        return None
    if (zlib.crc32(payload) & 0xFFFFFFFF) != want:
        return None
    try:
        obj = json.loads(payload)
    except ValueError:
        return None
    if not isinstance(obj, dict) or obj.get("v") != JOURNAL_FORMAT_VERSION:
        return None
    try:
        seq, epoch, kind, t = obj["seq"], obj["epoch"], obj["kind"], obj["t"]
        tenant, data = obj["tenant"], obj["data"]
    except KeyError:
        return None
    if not (isinstance(seq, int) and isinstance(epoch, int)
            and isinstance(kind, str)
            and isinstance(t, (int, float)) and not isinstance(t, bool)
            and (tenant is None or isinstance(tenant, str))
            and isinstance(data, dict)):
        return None
    return Record(seq=seq, epoch=epoch, kind=kind, t=float(t),
                  tenant=tenant, data=data)


def read_segment(path: str) -> "tuple[list[Record], bool]":
    """Decode one segment: (valid record prefix, torn?).  Torn means a
    partial/corrupt record (or a record past one) was dropped."""
    with open(path, "rb") as f:
        raw = f.read()
    chunks = raw.split(b"\n")
    complete, tail = chunks[:-1], chunks[-1]
    records: list[Record] = []
    torn = False
    for chunk in complete:
        rec = _parse_line(chunk)
        if rec is None:
            torn = True
            break
        records.append(rec)
    else:
        # A final chunk with no newline is a torn append even if its
        # bytes happen to parse: the framing contract is line-complete.
        if tail != b"":
            torn = True
    return records, torn


def list_segments(journal_dir: str) -> "list[tuple[int, int, str]]":
    """(index, epoch, basename) for every segment, in replay order
    (the index is globally monotone across epochs)."""
    out: list[tuple[int, int, str]] = []
    try:
        names = os.listdir(journal_dir)
    except FileNotFoundError:
        return out
    for name in names:
        m = _SEGMENT_RE.match(name)
        if m is not None:
            out.append((int(m.group(2)), int(m.group(1)), name))
    out.sort()
    return out


@dataclass
class ReadStats:
    """What :func:`read_journal` dropped on the floor (and counted),
    plus each segment's valid record count AFTER truncation — the
    numbers a recovery freezes into its ``fence`` record."""

    segments: int = 0
    torn_segments: int = 0
    stale_dropped: int = 0
    per_segment: dict[str, int] = dataclasses_field(default_factory=dict)


def read_journal(journal_dir: str) -> "tuple[list[Record], ReadStats]":
    """Replay-ready record stream for a journal directory.

    Two passes: decode every segment (truncating each torn tail,
    counted ``durability.torn_tail``), then apply the LAST ``fence``
    record — it froze the valid record count of every segment that
    existed at that recovery, so anything a fenced (zombie) writer
    appended past those counts is dropped and counted as
    ``durability.stale_epoch_rejections``.
    """
    rec_sink = get_recorder()
    stats = ReadStats()
    per: list[tuple[str, list[Record]]] = []
    for _index, _epoch, name in list_segments(journal_dir):
        stats.segments += 1
        records, torn = read_segment(os.path.join(journal_dir, name))
        if torn:
            stats.torn_segments += 1
            rec_sink.count("durability.torn_tail")
        per.append((name, records))
    last_fence: Optional[Record] = None
    for _name, records in per:
        for record in records:
            if record.kind == "fence":
                last_fence = record
    if last_fence is not None:
        counts = last_fence.data.get("segments", {})
        if isinstance(counts, dict):
            for i, (name, records) in enumerate(per):
                keep = counts.get(name)
                if isinstance(keep, int) and len(records) > keep:
                    dropped = len(records) - keep
                    stats.stale_dropped += dropped
                    rec_sink.count("durability.stale_epoch_rejections",
                                   dropped)
                    per[i] = (name, records[:keep])
    for name, records in per:
        stats.per_segment[name] = len(records)
    return [r for _name, records in per for r in records], stats


class JournalFeed:
    """The record vocabulary, shared by :class:`Journal` (untagged /
    single-tenant) and :class:`TenantView` (fleet fan-out) — both only
    need to provide :meth:`append`, :meth:`write_snapshot` and
    :attr:`fence`.  This is the duck type ``RebalanceController``'s
    ``journal=`` parameter accepts."""

    def append(self, kind: str, data: Mapping[str, Any], *,
               t: Optional[float] = None) -> bool:
        raise NotImplementedError

    def write_snapshot(self, payload: Mapping[str, Any], *,
                       t: Optional[float] = None) -> str:
        raise NotImplementedError

    def should_snapshot(self) -> bool:
        raise NotImplementedError

    @property
    def fence(self) -> EpochFence:
        raise NotImplementedError

    # -- controller sync-window records --------------------------------------

    def record_genesis(self, pmap: Mapping[str, Any], nodes: Sequence[str],
                       removing: Sequence[str], failed: Sequence[str],
                       pweights: Mapping[str, int],
                       nweights: Mapping[str, int], *,
                       t: Optional[float] = None) -> None:
        self.append("genesis", {
            "map": {name: p.to_json() for name, p in sorted(pmap.items())},
            "nodes": list(nodes),
            "removing": sorted(removing),
            "failed": sorted(failed),
            "pweights": dict(sorted(pweights.items())),
            "nweights": dict(sorted(nweights.items())),
        }, t=t)

    def record_delta(self, delta: Any, *, t: Optional[float] = None) -> None:
        """One ClusterDelta at intake (duck-typed: add/remove/fail +
        weight mappings)."""
        self.append("delta", {
            "add": list(delta.add),
            "remove": list(delta.remove),
            "fail": list(delta.fail),
            "pweights": (dict(sorted(delta.partition_weights.items()))
                         if delta.partition_weights is not None else None),
            "nweights": (dict(sorted(delta.node_weights.items()))
                         if delta.node_weights is not None else None),
        }, t=t)

    def record_cycle(self, n: int, deltas: int, *,
                     t: Optional[float] = None) -> None:
        self.append("cycle", {"n": n, "deltas": deltas}, t=t)

    def record_plan(self, pass_no: int, moves: int, *,
                    t: Optional[float] = None) -> None:
        self.append("plan", {"pass": pass_no, "moves": moves}, t=t)

    def record_strip(self, nodes: Sequence[str], *,
                     t: Optional[float] = None) -> None:
        self.append("strip", {"nodes": sorted(nodes)}, t=t)

    def record_quiesce(self, digest: str, *,
                       t: Optional[float] = None) -> None:
        self.append("quiesce", {"digest": digest}, t=t)

    def record_quiesce_map(self, pmap: Mapping[str, Any], *,
                           t: Optional[float] = None) -> None:
        """Quiesce record with the digest computed here, so callers
        (the controller) need no journal-format imports."""
        self.record_quiesce(map_digest(pmap), t=t)

    # -- the orchestrator observer hook (obs.slo.MoveObserver) ---------------

    def on_batch(self, node: str, moves: Sequence[Any], ok: bool,
                 now: float) -> None:
        """One executed-batch outcome: the achieved-map delta.  Only ok
        batches mutate the map on replay, but failures are journaled
        too — they are part of the deterministic event log."""
        self.append("batch", {
            "node": node,
            "ok": ok,
            "moves": [[m.partition, m.node, m.state, m.op] for m in moves],
        }, t=now)


class Journal(JournalFeed):
    """Append-only writer for one journal directory.

    ``clock`` stamps each record's ``t`` (pass the controller's
    ``recorder.now`` so journal time follows virtual time in tests);
    ``rotate_records`` bounds segment length; ``snapshot_every`` is the
    snapshot cadence in records (0 disables ``should_snapshot``).
    The journal captures the directory's epoch at construction: once a
    recovery bumps the fence, every further append on this handle is
    dropped and counted (``durability.stale_epoch_rejections``) — the
    in-process zombie defense.
    """

    def __init__(self, journal_dir: str, *,
                 tenant: Optional[str] = None,
                 fence: Optional[EpochFence] = None,
                 clock: Optional[Callable[[], float]] = None,
                 rotate_records: int = 1024,
                 snapshot_every: int = 0,
                 start_seq: int = 1) -> None:
        os.makedirs(journal_dir, exist_ok=True)
        self._dir = journal_dir
        self._tenant = tenant
        self._fence = fence if fence is not None else fence_for(journal_dir)
        self._epoch = self._fence.current
        self._clock: Callable[[], float] = (
            clock if clock is not None else (lambda: 0.0))
        self._rotate_records = max(int(rotate_records), 1)
        self._snapshot_every = max(int(snapshot_every), 0)
        self._seq = start_seq
        self._rec = get_recorder()
        self.records_since_snapshot = 0
        self._records_in_seg = 0
        self._f: Optional[Any] = None
        self._open_segment(rotated=False)

    # -- segment machinery ---------------------------------------------------

    def _next_index(self) -> int:
        segs = list_segments(self._dir)
        return (segs[-1][0] + 1) if segs else 1

    def _open_segment(self, rotated: bool) -> None:
        if self._f is not None:
            self._f.flush()
            if fsync_enabled():
                os.fsync(self._f.fileno())
            self._f.close()
        index = self._next_index()
        name = f"wal-{self._epoch:06d}-{index:06d}.log"
        path = os.path.join(self._dir, name)
        # Crash-atomic birth: temp + fsync'd rename (+ directory fsync)
        # so a crash mid-rotation never leaves a half-created segment.
        atomic_write_text(path, "")
        self._f = open(path, "a", encoding="utf-8")
        self._records_in_seg = 0
        self.segment = name
        if rotated:
            self._rec.count("durability.segments_rotated")

    # -- the single append funnel -------------------------------------------

    def append(self, kind: str, data: Mapping[str, Any], *,
               t: Optional[float] = None,
               tenant: "Optional[str]" = None) -> bool:
        """Append one record; True when it was written.  False means the
        epoch is fenced (a recovery superseded this handle): the record
        is DROPPED and counted, never half-written."""
        if not self._fence.valid(self._epoch):
            self._rec.count("durability.stale_epoch_rejections")
            return False
        line = encode_record(
            self._seq, self._epoch, kind,
            self._clock() if t is None else t,
            tenant if tenant is not None else self._tenant, data)
        assert self._f is not None
        self._f.write(line)
        self._f.flush()
        if fsync_enabled():
            os.fsync(self._f.fileno())
        self._seq += 1
        self._records_in_seg += 1
        self.records_since_snapshot += 1
        self._rec.count("durability.journal_records")
        self._rec.count("durability.journal_bytes", len(line))
        if self._records_in_seg >= self._rotate_records:
            self._open_segment(rotated=True)
        return True

    # -- snapshots ------------------------------------------------------------

    def should_snapshot(self) -> bool:
        return (self._snapshot_every > 0
                and self.records_since_snapshot >= self._snapshot_every)

    def write_snapshot(self, payload: Mapping[str, Any], *,
                       t: Optional[float] = None,
                       tenant: Optional[str] = None) -> str:
        """Write a snapshot file (crash-atomic) and then its pointer
        record — ordered so a journaled pointer always references a
        durable, complete snapshot.  Returns the snapshot basename."""
        tag = tenant if tenant is not None else self._tenant
        safe = _TENANT_SAFE_RE.sub("_", tag) if tag is not None else "all"
        name = f"snap-{self._seq:08d}-{safe}.json"
        atomic_write_json(os.path.join(self._dir, name), dict(payload))
        self.append("snapshot", {"file": name}, t=t, tenant=tag)
        self.records_since_snapshot = 0
        self._rec.count("durability.snapshots")
        return name

    # -- fleet fan-out ---------------------------------------------------------

    def for_tenant(self, tenant: str) -> "TenantView":
        """A tagged view for one tenant loop sharing this writer (one
        journal per fleet, tenant-tagged records)."""
        return TenantView(self, tenant)

    @property
    def fence(self) -> EpochFence:
        return self._fence

    @property
    def epoch(self) -> int:
        return self._epoch

    @property
    def next_seq(self) -> int:
        return self._seq

    def close(self) -> None:
        if self._f is not None:
            self._f.flush()
            if fsync_enabled():
                os.fsync(self._f.fileno())
            self._f.close()
            self._f = None


class TenantView(JournalFeed):
    """One tenant's tagged facade over a shared :class:`Journal` — what
    ``FleetController`` hands each tenant loop."""

    def __init__(self, journal: Journal, tenant: str) -> None:
        self._journal = journal
        self.tenant = tenant

    def append(self, kind: str, data: Mapping[str, Any], *,
               t: Optional[float] = None) -> bool:
        return self._journal.append(kind, data, t=t, tenant=self.tenant)

    def should_snapshot(self) -> bool:
        return self._journal.should_snapshot()

    def write_snapshot(self, payload: Mapping[str, Any], *,
                       t: Optional[float] = None) -> str:
        return self._journal.write_snapshot(
            payload, t=t, tenant=self.tenant)

    @property
    def fence(self) -> EpochFence:
        return self._journal.fence
