"""Recovery: rebuild control-plane state from the journal and resume.

``recover(journal_dir)`` is the restart entry point:

1. replay the journal (:func:`~blance_tpu.durability.journal.
   read_journal` — torn tails truncated, fenced zombie appends
   dropped), folding each tenant's record stream into a
   :class:`RecoveredTenant`: current map, membership view, weights,
   breaker state, SLO horizon state.  A ``snapshot`` pointer record
   fast-forwards the fold to its payload; a ``genesis`` record resets
   it (a resumed controller writes a fresh genesis, so every epoch's
   journal is self-contained).
2. bump the directory's epoch fence (persisted crash-atomically) and
   open a new journal segment under the new epoch, writing a ``fence``
   record that freezes every prior segment's valid record count — the
   cross-process zombie defense.

``resume_controller`` then rebuilds one ``RebalanceController`` from a
recovered tenant: restored map + membership (via a journaled kick
delta through the existing fault-tolerant recovery machinery), restored
``HealthTracker`` (clock re-based) and ``SloTracker`` (snapshot state
plus post-snapshot batch/strip records re-applied with re-based
times).  Carry/encode caches are deliberately NOT persisted: a resumed
tenant costs one counted cold solve
(``durability.recovery_cold_solves``), bounded by the fleet tier's
demotion/eviction attribution identity (docs/FLEET.md).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Callable, Optional

from ..core.types import Partition, PartitionMap
from ..obs import get_recorder
from .epoch import fence_for
from .journal import Journal, Record, read_journal

__all__ = ["RecoveredState", "RecoveredTenant", "recover",
           "resume_controller"]

SNAPSHOT_FORMAT_VERSION = 1


@dataclasses.dataclass
class RecoveredTenant:
    """One tenant's folded state at the crash point."""

    tenant: Optional[str]
    pmap: PartitionMap = dataclasses.field(default_factory=dict)
    nodes: list[str] = dataclasses.field(default_factory=list)
    removing: set[str] = dataclasses.field(default_factory=set)
    failed: set[str] = dataclasses.field(default_factory=set)
    pweights: dict[str, int] = dataclasses.field(default_factory=dict)
    nweights: dict[str, int] = dataclasses.field(default_factory=dict)
    # Serialized HealthTracker / SloTracker / CostModel state from the
    # last snapshot (None before the first snapshot).
    health: Optional[dict[str, Any]] = None
    slo: Optional[dict[str, Any]] = None
    cost: Optional[dict[str, Any]] = None
    # batch/strip records since the last snapshot/genesis — re-applied
    # to a restored SloTracker so its view matches the folded map.
    post_events: list[Record] = dataclasses.field(default_factory=list)
    records: int = 0
    last_t: float = 0.0
    snapshot_t: Optional[float] = None
    quiesced: bool = True


@dataclasses.dataclass
class RecoveredState:
    """Everything ``recover()`` rebuilt, plus the successor journal
    (already fenced at the new epoch)."""

    epoch: int
    next_seq: int
    records_replayed: int
    torn_segments: int
    stale_dropped: int
    tenants: dict[Optional[str], RecoveredTenant]
    journal: Journal


def _apply_batch(pmap: PartitionMap, moves: list[Any]) -> None:
    """Fold one executed batch into the map — the same per-move
    semantics as ``Orchestrator.achieved_map`` / ``SloTracker._apply``:
    remove the node from wherever it was, then (unless the move is a
    removal, state "") place it in the move's state."""
    for mv in moves:
        partition, node, state = str(mv[0]), str(mv[1]), str(mv[2])
        p = pmap.get(partition)
        if p is None:
            continue
        for ns in p.nodes_by_state.values():
            if node in ns:
                ns.remove(node)
        if state:
            p.nodes_by_state.setdefault(state, []).append(node)


def _strip(pmap: PartitionMap, nodes: set[str]) -> None:
    for p in pmap.values():
        for state, ns in p.nodes_by_state.items():
            p.nodes_by_state[state] = [n for n in ns if n not in nodes]


def _map_from_json(data: dict[str, Any]) -> PartitionMap:
    return {str(name): Partition.from_json(p) for name, p in data.items()}


def _reset_from(t_state: RecoveredTenant, data: dict[str, Any]) -> None:
    """Seed the fold from a genesis record or snapshot payload (both
    share the membership schema)."""
    t_state.pmap = _map_from_json(data["map"])
    t_state.nodes = [str(n) for n in data["nodes"]]
    t_state.removing = {str(n) for n in data["removing"]}
    t_state.failed = {str(n) for n in data["failed"]}
    t_state.pweights = {str(k): int(v)
                        for k, v in (data.get("pweights") or {}).items()}
    t_state.nweights = {str(k): int(v)
                        for k, v in (data.get("nweights") or {}).items()}
    t_state.post_events = []
    # A reset supersedes any earlier snapshot's auxiliary state; the
    # snapshot fold re-sets these right after when that's the source.
    t_state.health = None
    t_state.slo = None
    t_state.cost = None
    t_state.snapshot_t = None


def _fold(t_state: RecoveredTenant, record: Record,
          journal_dir: str) -> None:
    """One record into one tenant's fold, in journal order."""
    t_state.records += 1
    t_state.last_t = record.t
    data = record.data
    if record.kind == "genesis":
        _reset_from(t_state, data)
        t_state.quiesced = True
        return
    if record.kind == "snapshot":
        try:
            with open(os.path.join(journal_dir, str(data["file"]))) as f:
                payload = json.load(f)
        except (OSError, ValueError, KeyError):
            # A missing/torn snapshot file never blocks recovery: the
            # fold simply continues from what it already has (the
            # pointer is only written after the file is durable, so
            # this is defense in depth, not an expected path).
            return
        if payload.get("version") != SNAPSHOT_FORMAT_VERSION:
            return
        _reset_from(t_state, payload)
        t_state.health = payload.get("health")
        t_state.slo = payload.get("slo")
        t_state.cost = payload.get("cost")
        t_state.snapshot_t = record.t
        return
    if record.kind == "delta":
        t_state.quiesced = False
        for n in data.get("add", ()):
            n = str(n)
            if n not in t_state.nodes:
                t_state.nodes.append(n)
            t_state.removing.discard(n)
            t_state.failed.discard(n)
        t_state.removing.update(
            str(n) for n in data.get("remove", ()) if n in t_state.nodes)
        t_state.failed.update(
            str(n) for n in data.get("fail", ()) if n in t_state.nodes)
        if data.get("pweights"):
            t_state.pweights.update(
                {str(k): int(v) for k, v in data["pweights"].items()})
        if data.get("nweights"):
            t_state.nweights.update(
                {str(k): int(v) for k, v in data["nweights"].items()})
        return
    if record.kind == "strip":
        t_state.quiesced = False
        _strip(t_state.pmap, {str(n) for n in data.get("nodes", ())})
        t_state.post_events.append(record)
        return
    if record.kind == "batch":
        t_state.quiesced = False
        if data.get("ok"):
            _apply_batch(t_state.pmap, list(data.get("moves", ())))
        t_state.post_events.append(record)
        return
    if record.kind == "quiesce":
        t_state.quiesced = True
        return
    if record.kind in ("cycle", "plan"):
        t_state.quiesced = False
        return
    # Unknown kinds (a newer writer's vocabulary): ignore, by design.


def recover(journal_dir: str, *,
            clock: Optional[Callable[[], float]] = None,
            rotate_records: int = 1024,
            snapshot_every: int = 0,
            journal_factory: Optional[Callable[..., Journal]] = None,
            ) -> RecoveredState:
    """Rebuild every tenant's state from ``journal_dir`` and fence the
    epoch.  Returns the folded states plus the successor journal
    (new epoch, fresh segment, ``fence`` record already written).

    ``journal_factory`` substitutes the successor journal's class —
    the crash-injection harness passes a journal that dies again at a
    scripted record boundary (testing/crashsim.py)."""
    rec_sink = get_recorder()
    records, stats = read_journal(journal_dir)
    fence = fence_for(journal_dir)
    new_epoch = fence.bump()
    make = journal_factory if journal_factory is not None else Journal
    journal = make(
        journal_dir, fence=fence, clock=clock,
        rotate_records=rotate_records, snapshot_every=snapshot_every,
        start_seq=(records[-1].seq + 1) if records else 1)
    journal.append("fence",
                   {"epoch": new_epoch, "segments": stats.per_segment})
    tenants: dict[Optional[str], RecoveredTenant] = {}
    for record in records:
        if record.kind == "fence":
            continue
        t_state = tenants.get(record.tenant)
        if t_state is None:
            t_state = tenants[record.tenant] = RecoveredTenant(record.tenant)
        _fold(t_state, record, journal_dir)
    rec_sink.count("durability.recoveries")
    rec_sink.count("durability.replayed_records", len(records))
    return RecoveredState(
        epoch=new_epoch,
        next_seq=journal.next_seq,
        records_replayed=len(records),
        torn_segments=stats.torn_segments,
        stale_dropped=stats.stale_dropped,
        tenants=tenants,
        journal=journal,
    )


class _ReplayMove:
    """Duck-typed move (partition/node/state/op) for re-applying
    journaled batches through a restored SloTracker."""

    __slots__ = ("partition", "node", "state", "op")

    def __init__(self, partition: str, node: str, state: str,
                 op: str) -> None:
        self.partition = partition
        self.node = node
        self.state = state
        self.op = op


def _restore_slo(t_state: RecoveredTenant, clock: Callable[[], float],
                 publish_gauges: bool,
                 availability_floor: Optional[float],
                 track_timeline: bool) -> Any:
    """A SloTracker for the resumed controller.

    With a snapshot: restore it (ages re-based), then re-apply the
    post-snapshot batch/strip records with their times SHIFTED onto the
    new clock (shift = now - last journaled t), so every inter-event
    duration — lag, timeline steps, integrals — survives the crash.
    Without one: a fresh account seeded from the recovered map (the
    horizon restarts; availability is instantaneous state and correct
    either way).
    """
    from ..obs.slo import SloTracker

    now = clock()
    if t_state.slo is None:
        return SloTracker(
            t_state.pmap, clock=clock,
            track_timeline=track_timeline,
            availability_floor=availability_floor,
            publish_gauges=publish_gauges)
    shift = now - t_state.last_t
    snap_now = (t_state.snapshot_t + shift
                if t_state.snapshot_t is not None else now)
    slo = SloTracker.from_dict(
        t_state.slo, clock=clock, now=snap_now,
        publish_gauges=publish_gauges)
    for record in t_state.post_events:
        t = record.t + shift
        if record.kind == "strip":
            slo.strip_nodes(
                {str(n) for n in record.data.get("nodes", ())}, t)
        elif record.kind == "batch":
            moves = [_ReplayMove(str(m[0]), str(m[1]), str(m[2]), str(m[3]))
                     for m in record.data.get("moves", ())]
            slo.on_batch(str(record.data.get("node", "")), moves,
                         bool(record.data.get("ok")), t)
    return slo


def resume_controller(state: RecoveredState, model: Any,
                      assign_partitions: Callable[..., object], *,
                      tenant: Optional[str] = None,
                      plan_options: Any = None,
                      orchestrator_options: Any = None,
                      backend: str = "greedy",
                      planner: Any = None,
                      find_move: Any = None,
                      debounce_s: float = 0.05,
                      max_passes_per_cycle: int = 8,
                      move_observers: "tuple[Any, ...]" = (),
                      publish_slo_gauges: bool = True,
                      track_timeline: bool = True,
                      availability_floor: Optional[float] = None,
                      start: bool = True,
                      kick: bool = True) -> Any:
    """One recovered tenant back to a live ``RebalanceController``.

    The controller starts from the journaled achieved map; membership
    residue (graceful removals, failed nodes) is re-submitted as a
    journaled kick delta, so convergence resumes through the existing
    fault-tolerant machinery — idempotent (a zero-move plan) when the
    crash happened quiesced.  Encode/carry caches were never persisted:
    the first plan is a counted cold solve
    (``durability.recovery_cold_solves``).
    """
    # Imported here, not at module top: rebalance.py imports the
    # orchestrate layer, which imports this package — a module-level
    # import would cycle.
    from ..rebalance import ClusterDelta, RebalanceController

    rec_sink = get_recorder()
    t_state = state.tenants[tenant]
    opts = orchestrator_options
    if t_state.health is not None:
        from ..orchestrate.health import HealthTracker
        from ..orchestrate.orchestrator import OrchestratorOptions
        health = HealthTracker.from_dict(t_state.health, clock=rec_sink.now)
        opts = dataclasses.replace(opts or OrchestratorOptions(),
                                   health=health)
    slo = _restore_slo(t_state, rec_sink.now, publish_slo_gauges,
                       availability_floor, track_timeline)
    journal = (state.journal if tenant is None
               else state.journal.for_tenant(tenant))
    controller = RebalanceController(
        model, list(t_state.nodes), t_state.pmap, assign_partitions,
        plan_options=plan_options, orchestrator_options=opts,
        backend=backend, planner=planner, find_move=find_move,
        debounce_s=debounce_s,
        max_passes_per_cycle=max_passes_per_cycle, slo=slo,
        move_observers=move_observers, journal=journal)
    rec_sink.count("durability.recovery_cold_solves")
    if start:
        controller.start()
        if kick:
            controller.submit(ClusterDelta(
                remove=tuple(sorted(t_state.removing)),
                fail=tuple(sorted(t_state.failed))))
    return controller
