"""Scenario DSL for the continuous-rebalance simulator.

A :class:`SimScenario` is a fully DECLARATIVE description of one run of
cluster life: the initial topology (nodes across zones, partitions,
replica count), a seeded trace of timed :class:`SimEvent`s (each one a
:class:`~blance_tpu.rebalance.ClusterDelta` — joins, graceful
decommissions, abrupt spot preemptions, zone outages, hot-tenant weight
drift), the mover fault profiles (``orchestrate.faults.NodeFaults``,
SHA-seeded so flakes replay bit-identically), and the SLO floor the run
is scored against.  ``testing/simulate.py`` executes it under the
``DeterministicLoop`` virtual clock.

Determinism contract: builders derive every stochastic choice from
``random.Random(seed)`` at BUILD time — the scenario object is the
complete script, and running it twice (or on another machine) replays
the same cluster life bit-for-bit (docs/SIMULATOR.md).

The registry at the bottom maps scenario-family names to builders
taking a seed — the CI ``sim-smoke`` matrix is 3 fixed seeds x three
families, plus the ``slow``-marked 7-virtual-day ``mixed_week``.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Callable, Mapping

from ..core.types import Partition, PartitionMap, PartitionModel, model
from ..orchestrate.faults import NodeFaults
from ..rebalance import ClusterDelta

__all__ = [
    "SimEvent",
    "SimScenario",
    "initial_map",
    "scenario_model",
    "spot_preemption",
    "zone_flap",
    "weight_drift",
    "hetero_drain",
    "mixed_week",
    "SCENARIOS",
    "CrashScenario",
    "crash_smoke",
    "crash_storm",
    "CRASH_SCENARIOS",
    "FleetTenant",
    "FleetEvent",
    "FleetScenario",
    "fleet_zone_outage",
    "fleet_onboarding",
    "fleet_noisy_neighbor",
    "fleet_week",
    "FLEET_SCENARIOS",
]


@dataclass(frozen=True)
class SimEvent:
    """One timed cluster delta in a scenario trace.

    ``outage=True`` marks a SCRIPTED loss window: availability is
    allowed to drop from this event until the control loop's next
    quiesce.  Any availability drop OUTSIDE such a window is a
    simulator invariant violation (lost primaries nobody scripted)."""

    t: float
    delta: ClusterDelta
    label: str = ""
    outage: bool = False


@dataclass(frozen=True)
class SimScenario:
    """A complete, self-describing simulator run (see module doc)."""

    name: str
    seed: int
    horizon_s: float
    nodes: tuple[str, ...]
    partitions: int
    replicas: int = 1
    events: tuple[SimEvent, ...] = ()
    availability_floor: float = 0.85
    # Mover fault profiles (orchestrate/faults.py), keyed by node; the
    # FaultPlan seed is the scenario seed.
    fault_nodes: Mapping[str, NodeFaults] = field(default_factory=dict)
    # Virtual per-batch data-plane latency: base for every node, plus
    # per-node overrides (slow movers).
    base_latency_s: float = 2.0
    node_latency_s: Mapping[str, float] = field(default_factory=dict)
    # Control-loop knobs.
    debounce_s: float = 1.0
    move_timeout_s: float = 120.0
    max_retries: int = 3
    backoff_base_s: float = 1.0
    quarantine_after: int = 5
    probe_after_s: float = 86_400.0  # quarantine is terminal unless re-added
    max_passes_per_cycle: int = 8
    use_session: bool = False
    backend: str = "greedy"
    max_steps: int = 4_000_000
    # Moves fed per node per batch (OrchestratorOptions.
    # max_concurrent_partition_moves_per_node — the scheduler's lane
    # count per machine).
    max_concurrent_moves: int = 1
    # Move-ordering policy: "legacy" (the reference app-weight order)
    # or "critical_path" (orchestrate/sched.CriticalPathScheduler on a
    # prior-seeded CostModel learning online from the run's own spans).
    # Same deltas, same planner, same move SET either way — only the
    # order and the clock differ (docs/SCHEDULER.md).
    scheduler: str = "legacy"


def scenario_model(scn: SimScenario) -> PartitionModel:
    """primary(+replicas) model for a scenario."""
    if scn.replicas > 0:
        return model(primary=(0, 1), replica=(1, scn.replicas))
    return model(primary=(0, 1))


def initial_map(scn: SimScenario) -> PartitionMap:
    """Deterministic round-robin seed placement: partition i's primary
    on node i mod N, replicas on the next distinct nodes — balanced,
    zone-striped (node order interleaves zones), no RNG involved."""
    nodes = list(scn.nodes)
    n = len(nodes)
    out: PartitionMap = {}
    for i in range(scn.partitions):
        name = f"p{i:04d}"
        nbs: dict[str, list[str]] = {"primary": [nodes[i % n]]}
        if scn.replicas > 0:
            nbs["replica"] = [nodes[(i + 1 + r) % n]
                              for r in range(scn.replicas)]
        out[name] = Partition(name, nbs)
    return out


def _zone_nodes(zones: int, per_zone: int) -> tuple[str, ...]:
    """z0n0, z1n0, z2n0, z0n1, ... — zone-striped so round-robin seed
    placement spreads replicas across zones."""
    return tuple(f"z{z}n{i}" for i in range(per_zone)
                 for z in range(zones))


def _jitter(rng: random.Random, t: float, spread: float) -> float:
    """Deterministic +-spread jitter, quantized to ms so event-log
    timestamps stay platform-stable text."""
    return round(t + rng.uniform(-spread, spread), 3)


# -- scenario families --------------------------------------------------------


def spot_preemption(seed: int = 11) -> SimScenario:
    """Bulk simultaneous spot kills: ~a third of the fleet vanishes in
    ONE delta, replacements join later, then a graceful decommission —
    the cloud-capacity churn staple."""
    rng = random.Random(f"spot:{seed}")
    nodes = _zone_nodes(3, 4)  # 12 nodes
    victims = tuple(sorted(rng.sample(nodes, 4)))
    replacements = tuple(f"r{i}" for i in range(4))
    retire = rng.choice([n for n in nodes if n not in victims])
    events = (
        SimEvent(t=_jitter(rng, 300, 30),
                 delta=ClusterDelta(fail=victims),
                 label="spot-preemption", outage=True),
        SimEvent(t=_jitter(rng, 1200, 60),
                 delta=ClusterDelta(add=replacements),
                 label="replacements-join"),
        SimEvent(t=_jitter(rng, 2400, 60),
                 delta=ClusterDelta(remove=(retire,)),
                 label="graceful-retire"),
    )
    return SimScenario(
        name="spot_preemption", seed=seed, horizon_s=3600.0,
        nodes=nodes, partitions=48, replicas=1, events=events,
        availability_floor=0.6)


def zone_flap(seed: int = 23) -> SimScenario:
    """Rolling zone outages: each zone goes dark and comes back in
    turn, with overlap (the next zone fails before the previous
    recovery fully drains) and a flaky mover in the surviving set."""
    rng = random.Random(f"flap:{seed}")
    zones, per_zone = 3, 4
    nodes = _zone_nodes(zones, per_zone)
    by_zone = {z: tuple(n for n in nodes if n.startswith(f"z{z}"))
               for z in range(zones)}
    flaky = by_zone[2][-1]
    events: list[SimEvent] = []
    t = 600.0
    for z in range(zones):
        down = _jitter(rng, t, 30)
        events.append(SimEvent(
            t=down, delta=ClusterDelta(fail=by_zone[z]),
            label=f"zone-z{z}-outage", outage=True))
        # The zone returns while the NEXT zone's outage may already be
        # in flight — overlapping deltas are the point.
        events.append(SimEvent(
            t=_jitter(rng, down + 900, 30),
            delta=ClusterDelta(add=by_zone[z]),
            label=f"zone-z{z}-returns"))
        t += 1100.0
    return SimScenario(
        name="zone_flap", seed=seed, horizon_s=5400.0,
        nodes=nodes, partitions=48, replicas=1,
        events=tuple(events), availability_floor=0.5,
        fault_nodes={flaky: NodeFaults(fail_rate=0.2)},
        quarantine_after=8)


def weight_drift(seed: int = 37) -> SimScenario:
    """Hot-tenant weight drift, no faults: waves of partitions heat up
    (weight 1 -> 8) and cool back down, each wave a replan the loop
    must absorb without ever dropping availability."""
    rng = random.Random(f"drift:{seed}")
    nodes = _zone_nodes(2, 4)  # 8 nodes
    partitions = 32
    events: list[SimEvent] = []
    hot: list[str] = []
    t = 300.0
    for _wave in range(4):
        cooled = {p: 1 for p in hot}
        hot = sorted(rng.sample([f"p{i:04d}" for i in range(partitions)],
                                partitions // 8))
        heated = {p: 8 for p in hot}
        events.append(SimEvent(
            t=_jitter(rng, t, 20),
            delta=ClusterDelta(partition_weights={**cooled, **heated}),
            label="hot-tenant-wave"))
        t += 700.0
    return SimScenario(
        name="weight_drift", seed=seed, horizon_s=3600.0,
        nodes=nodes, partitions=partitions, replicas=1,
        events=tuple(events), availability_floor=0.999)


def hetero_drain(seed: int = 41) -> SimScenario:
    """Heterogeneous mover latencies with ONE slow node, drained into
    capacity joins: the critical-path scheduling showcase (ISSUE 12).

    Every join pulls a near-uniform slice of placements onto the empty
    joiner — chains of ``[add(joiner), del(source)]`` whose level-0
    adds all CONTEND for the joiner's single lane while the del tails
    cost whatever their source node costs.  The makespan is therefore
    decided by WHEN the slow node's del chains start: app-weight order
    is blind to the tails (every add weighs 3, ties break on partition
    name), so the slow chain's add lands anywhere in the joiner's
    serial queue; critical-path order feeds the highest-upward-rank
    (slowest-tail) chains first.  The first join doubles as the cost
    model's calibration pass (every node executes a del, teaching its
    latency); the two joins after it are the measured incidents.  No
    faults: both orders execute the identical move set, so churn is
    exactly equal and only the clock differs."""
    rng = random.Random(f"hetero:{seed}")
    nodes = tuple(f"n{i}" for i in range(12))
    lat: dict[str, float] = {
        n: round(rng.choice([0.5, 1.0, 1.5, 2.0]), 3) for n in nodes}
    # One badly slow mover plus two laggards: the del tails the
    # critical path must order longest-first (LPT) off the joiner.
    lat[nodes[-1]] = 16.0
    lat[nodes[-2]] = 12.0
    lat[nodes[-3]] = 9.0
    for joiner in ("w0", "r0", "r1"):
        lat[joiner] = 1.0
    events = (
        SimEvent(t=_jitter(rng, 120, 10),
                 delta=ClusterDelta(add=("w0",)),
                 label="warmup-join-w0"),
        SimEvent(t=_jitter(rng, 1200, 30),
                 delta=ClusterDelta(add=("r0",)),
                 label="join-r0"),
        SimEvent(t=_jitter(rng, 2400, 30),
                 delta=ClusterDelta(add=("r1",)),
                 label="join-r1"),
    )
    return SimScenario(
        name="hetero_drain", seed=seed, horizon_s=3600.0,
        nodes=nodes, partitions=96, replicas=1, events=events,
        availability_floor=0.999, base_latency_s=1.0,
        node_latency_s=lat, max_retries=0, quarantine_after=0,
        max_concurrent_moves=1)


def mixed_week(seed: int = 7, days: float = 7.0) -> SimScenario:
    """The long-horizon soak: ``days`` of virtual cluster life mixing
    every fault family — daily join/decommission churn, two spot
    preemption bursts, a zone flap, hot-tenant waves, plus
    deliberately OVERLAPPING deltas (a second event a few virtual
    seconds after the first, landing mid-rebalance to exercise the
    supersede path).  >= 20 deltas at the default horizon."""
    rng = random.Random(f"week:{seed}")
    nodes = _zone_nodes(3, 4)
    partitions = 48
    horizon = days * 86_400.0
    day = 86_400.0
    events: list[SimEvent] = []
    spare = [f"s{i}" for i in range(16)]  # standby capacity to rotate in
    in_cluster = list(nodes)

    def take_spare() -> str:
        return spare.pop(0)

    # Daily churn: one join + one graceful decommission per day, a few
    # virtual minutes apart.
    for d in range(int(days)):
        base = d * day
        join = take_spare()
        t_join = _jitter(rng, base + 0.25 * day, 1800)
        events.append(SimEvent(
            t=t_join, delta=ClusterDelta(add=(join,)),
            label=f"day{d}-join"))
        in_cluster.append(join)
        retire = rng.choice(sorted(in_cluster))
        in_cluster.remove(retire)
        # Overlap: the decommission lands seconds after the join's
        # rebalance began — a supersede, not a fresh cycle.
        events.append(SimEvent(
            t=round(t_join + rng.uniform(5.0, 30.0), 3),
            delta=ClusterDelta(remove=(retire,)),
            label=f"day{d}-retire-overlapping"))
    # Two spot bursts.
    for burst, when in enumerate((1.4 * day, 4.6 * day)):
        victims = tuple(sorted(rng.sample(sorted(in_cluster), 3)))
        for v in victims:
            in_cluster.remove(v)
        t_kill = _jitter(rng, when, 3600)
        events.append(SimEvent(
            t=t_kill, delta=ClusterDelta(fail=victims),
            label=f"spot-burst-{burst}", outage=True))
        repl = tuple(take_spare() for _ in range(3))
        in_cluster.extend(repl)
        events.append(SimEvent(
            t=_jitter(rng, t_kill + 0.1 * day, 600),
            delta=ClusterDelta(add=repl),
            label=f"spot-burst-{burst}-replacements"))
    # One zone flap mid-week (whichever z1 originals are still in).
    z1 = tuple(n for n in sorted(in_cluster) if n.startswith("z1"))
    if z1:
        t_down = _jitter(rng, 3.2 * day, 3600)
        events.append(SimEvent(
            t=t_down, delta=ClusterDelta(fail=z1),
            label="zone-z1-outage", outage=True))
        events.append(SimEvent(
            t=_jitter(rng, t_down + 0.05 * day, 600),
            delta=ClusterDelta(add=z1), label="zone-z1-returns"))
    # Hot-tenant waves every other day.
    hot: list[str] = []
    for w in range(3):
        cooled = {p: 1 for p in hot}
        hot = sorted(rng.sample([f"p{i:04d}" for i in range(partitions)],
                                6))
        events.append(SimEvent(
            t=_jitter(rng, (2 * w + 0.8) * day, 3600),
            delta=ClusterDelta(
                partition_weights={**cooled, **{p: 8 for p in hot}}),
            label=f"hot-wave-{w}"))
    events.sort(key=lambda e: (e.t, e.label))
    return SimScenario(
        name="mixed_week", seed=seed, horizon_s=horizon,
        nodes=nodes, partitions=partitions, replicas=1,
        events=tuple(events), availability_floor=0.6,
        fault_nodes={"z0n3": NodeFaults(fail_rate=0.1)},
        quarantine_after=8, max_steps=8_000_000)


# Scenario-family registry: name -> builder(seed).  The CI sim-smoke
# matrix crosses the first three with its fixed seeds; mixed_week is
# the slow-marked long-horizon soak.
SCENARIOS: dict[str, Callable[[int], SimScenario]] = {
    "spot_preemption": spot_preemption,
    "zone_flap": zone_flap,
    "weight_drift": weight_drift,
    "hetero_drain": hetero_drain,
    "mixed_week": mixed_week,
}


# -- crash scenarios (blance_tpu/testing/crashsim.py) -------------------------
#
# A CrashScenario scripts controller process deaths on top of a small
# SimScenario: ``crashes[i]`` is the journal-record boundary life i
# dies at (a life past the end of the chain runs crash-free).  The
# crash harness recovers each death from the WAL and asserts the run
# still converges to the crash-free reference's final map
# bit-identically (docs/DURABILITY.md "Crash injection").


@dataclass(frozen=True)
class CrashScenario:
    """A cluster life plus its scripted crash chain."""

    name: str
    seed: int
    base: SimScenario
    crashes: tuple[int, ...]
    snapshot_every: int = 0
    rotate_records: int = 64


def crash_smoke(seed: int = 17) -> SimScenario:
    """The bounded-exhaustive crash target: a DELIBERATELY small life
    (one outage, one return, one graceful retire — every membership
    fold path) so crashing at every journal-record boundary stays a
    smoke-test-sized matrix."""
    rng = random.Random(f"crash:{seed}")
    nodes = ("n0", "n1", "n2", "n3")
    events = (
        SimEvent(t=_jitter(rng, 60, 5),
                 delta=ClusterDelta(fail=("n1",)),
                 label="fail-n1", outage=True),
        SimEvent(t=_jitter(rng, 180, 5),
                 delta=ClusterDelta(add=("n1",)),
                 label="return-n1"),
        SimEvent(t=_jitter(rng, 300, 5),
                 delta=ClusterDelta(remove=("n0",)),
                 label="retire-n0"),
    )
    return SimScenario(
        name="crash_smoke", seed=seed, horizon_s=480.0,
        nodes=nodes, partitions=8, replicas=1, events=events,
        availability_floor=0.5, base_latency_s=1.0, debounce_s=0.5,
        move_timeout_s=30.0, max_retries=0, quarantine_after=0)


def crash_storm(seed: int = 19) -> CrashScenario:
    """Repeated controller crash-restarts landing mid-incident: the
    first death falls inside the outage's converge cycle, the second
    inside the window where a graceful retire OVERLAPS the outage
    rebalance (a supersede in flight), the third late in the life.
    Snapshots are on, so later recoveries exercise the snapshot
    fast-forward + post-snapshot replay path, not just raw folds."""
    rng = random.Random(f"storm:{seed}")
    nodes = _zone_nodes(2, 3)  # 6 nodes
    t_fail = _jitter(rng, 90, 5)
    events = (
        SimEvent(t=t_fail, delta=ClusterDelta(fail=(nodes[0],)),
                 label="zone-fail", outage=True),
        # The retire lands seconds into the outage rebalance — a
        # supersede, not a fresh cycle (mixed_week's overlap pattern).
        SimEvent(t=round(t_fail + rng.uniform(2.0, 6.0), 3),
                 delta=ClusterDelta(remove=(nodes[1],)),
                 label="retire-overlapping"),
        SimEvent(t=_jitter(rng, 300, 10),
                 delta=ClusterDelta(add=(nodes[0],)),
                 label="zone-returns"),
        SimEvent(t=_jitter(rng, 420, 10),
                 delta=ClusterDelta(partition_weights={"p0000": 8}),
                 label="hot-partition"),
    )
    base = SimScenario(
        name="crash_storm", seed=seed, horizon_s=600.0,
        nodes=nodes, partitions=12, replicas=1, events=events,
        availability_floor=0.5, base_latency_s=1.0, debounce_s=0.5,
        move_timeout_s=30.0, max_retries=0, quarantine_after=0)
    # Boundaries drawn at build time (determinism contract): the first
    # two land inside the incident/supersede convergence records, the
    # third well into the recovered life's tail.
    crashes = (rng.randint(6, 10), rng.randint(10, 16),
               rng.randint(22, 30))
    return CrashScenario(
        name="crash_storm", seed=seed, base=base, crashes=crashes,
        snapshot_every=8)


# Crash scenario-family registry: name -> builder(seed).
CRASH_SCENARIOS: dict[str, Callable[[int], CrashScenario]] = {
    "crash_storm": crash_storm,
}


# -- multi-tenant fleet scenarios (blance_tpu/fleetloop.py) -------------------
#
# A FleetScenario scripts N tenant indexes over ONE shared node fleet —
# the cbgt/FTS production shape.  Events either fan to every onboarded
# tenant (tenants=(): correlated membership changes — a zone outage is
# ONE event hitting all loops at once) or target specific tenants
# (per-tenant weight drift: the noisy neighbor).  Tenants with
# onboard_t > 0 join mid-run with EMPTY placements and converge from
# nothing (staggered onboarding).  testing/fleetsim.py executes a
# scenario under the DeterministicLoop; the same seed replays the whole
# fleet's week bit-identically (docs/SIMULATOR.md "Multi-tenant
# scenario families").


@dataclass(frozen=True)
class FleetTenant:
    """One tenant index in a fleet scenario.  ``onboard_t == 0`` means
    present from the start with round-robin seed placements; ``> 0``
    means the tenant onboards mid-run with empty placements and its
    first converge cycle places everything."""

    key: str
    partitions: int
    replicas: int = 1
    onboard_t: float = 0.0


@dataclass(frozen=True)
class FleetEvent:
    """One timed delta in a fleet trace.  ``tenants == ()`` fans the
    delta to every onboarded tenant (correlated membership events);
    otherwise it targets exactly the named tenants (weight drift)."""

    t: float
    delta: ClusterDelta
    tenants: tuple[str, ...] = ()
    label: str = ""
    outage: bool = False


@dataclass(frozen=True)
class FleetScenario:
    """A complete multi-tenant simulator run (module comment above)."""

    name: str
    seed: int
    horizon_s: float
    nodes: tuple[str, ...]
    tenants: tuple[FleetTenant, ...]
    events: tuple[FleetEvent, ...] = ()
    availability_floor: float = 0.85
    # Virtual per-batch data-plane latency (shared by every tenant).
    base_latency_s: float = 2.0
    node_latency_s: Mapping[str, float] = field(default_factory=dict)
    # Control-loop + plan-service knobs.
    debounce_s: float = 1.0
    admission_window_s: float = 0.25
    fair_share: "int | None" = None
    carry_bytes: "int | None" = None  # None = unbounded (identity runs)
    carry_entries: "int | None" = None
    max_passes_per_cycle: int = 8
    max_steps: int = 20_000_000


def _fleet_tenants(rng: random.Random, n: int,
                   choices: "tuple[int, ...]",
                   onboard: Callable[[int], float]) -> tuple[
                       FleetTenant, ...]:
    """Tenant specs with partition counts drawn from a SMALL choice
    set: at cbgt-index sizes the shape buckets step finely, so free
    size choice would give nearly every tenant its own compiled
    program — a handful of bucket-exact sizes keeps the whole fleet on
    a couple of shared programs (the GSPMD-bucketing point)."""
    return tuple(
        FleetTenant(key=f"t{i:03d}",
                    partitions=rng.choice(choices),
                    replicas=1, onboard_t=onboard(i))
        for i in range(n))


def fleet_zone_outage(seed: int = 5, tenants: int = 8,
                      partitions: "tuple[int, ...]" = (12, 16),
                      ) -> FleetScenario:
    """Correlated zone outage: one zone's nodes fail for EVERY tenant
    at once — N coalesced converge cycles through a handful of fleet
    dispatches — then return; two tenants heat up afterwards.

    ``partitions`` overrides the tenant-size choice set (bench's
    encode-residency A/B uses bigger tenants so the host-encode share
    is visible); the default reproduces the committed traces."""
    rng = random.Random(f"fzone:{seed}:{tenants}")
    nodes = _zone_nodes(3, 4)
    z1 = tuple(n for n in nodes if n.startswith("z1"))
    ts = _fleet_tenants(rng, tenants, partitions, lambda i: 0.0)
    hot = sorted(rng.sample([t.key for t in ts], min(2, tenants)))
    t_down = _jitter(rng, 600, 30)
    events = [
        FleetEvent(t=t_down, delta=ClusterDelta(fail=z1),
                   label="zone-z1-outage", outage=True),
        FleetEvent(t=_jitter(rng, t_down + 1200, 30),
                   delta=ClusterDelta(add=z1),
                   label="zone-z1-returns"),
    ]
    for i, key in enumerate(hot):
        events.append(FleetEvent(
            t=_jitter(rng, 2400 + 120 * i, 20),
            delta=ClusterDelta(partition_weights={"p0000": 8, "p0001": 8}),
            tenants=(key,), label=f"hot-tenant-{key}"))
    events.sort(key=lambda e: (e.t, e.label))
    return FleetScenario(
        name="fleet_zone_outage", seed=seed, horizon_s=3600.0,
        nodes=nodes, tenants=ts, events=tuple(events),
        availability_floor=0.5)


def fleet_onboarding(seed: int = 13, tenants: int = 12) -> FleetScenario:
    """Staggered tenant onboarding: a third of the fleet is live at t0,
    the rest join over the first half of the horizon (each converging
    from empty placements), then one graceful node retirement drains
    across every live tenant."""
    rng = random.Random(f"fonboard:{seed}:{tenants}")
    # Same node fleet + size choices as fleet_zone_outage: every smoke
    # family shares the same two compiled bucket classes.
    nodes = _zone_nodes(3, 4)
    head = max(tenants // 3, 1)

    def onboard(i: int) -> float:
        if i < head:
            return 0.0
        return _jitter(rng, 300 + (i - head) * (1500 / max(
            tenants - head, 1)), 20)

    ts = _fleet_tenants(rng, tenants, (12, 16), onboard)
    retire = rng.choice(sorted(nodes))
    events = (
        FleetEvent(t=_jitter(rng, 2600, 30),
                   delta=ClusterDelta(remove=(retire,)),
                   label=f"graceful-retire-{retire}"),
    )
    return FleetScenario(
        name="fleet_onboarding", seed=seed, horizon_s=3600.0,
        nodes=nodes, tenants=ts, events=events,
        availability_floor=0.85)


def fleet_noisy_neighbor(seed: int = 29,
                         tenants: int = 10) -> FleetScenario:
    """Noisy-neighbor churn: one chatty tenant submits a weight-drift
    delta every few virtual seconds for a long stretch while its
    neighbors ride out a node fail/return — with ``fair_share`` set,
    the chatty tenant cannot fill the coalescing windows
    (``fleet.starved_admissions`` counts its deferrals) and the
    neighbors' converge cycles stay prompt."""
    rng = random.Random(f"fnoisy:{seed}:{tenants}")
    # Same node fleet + size choices as fleet_zone_outage (shared
    # compiled classes across the smoke families).
    nodes = _zone_nodes(3, 4)
    ts = _fleet_tenants(rng, tenants, (12, 16), lambda i: 0.0)
    noisy = ts[0].key
    events: list[FleetEvent] = []
    t = 200.0
    for wave in range(24):
        p = rng.randrange(ts[0].partitions)
        events.append(FleetEvent(
            t=round(t, 3),
            delta=ClusterDelta(
                partition_weights={f"p{p:04d}": rng.choice([1, 4, 8])}),
            tenants=(noisy,), label=f"noisy-wave-{wave:02d}"))
        t += rng.uniform(8.0, 20.0)
    victim = nodes[-1]
    events.append(FleetEvent(
        t=_jitter(rng, 900, 20), delta=ClusterDelta(fail=(victim,)),
        label=f"fail-{victim}", outage=True))
    events.append(FleetEvent(
        t=_jitter(rng, 1800, 20), delta=ClusterDelta(add=(victim,)),
        label=f"return-{victim}"))
    events.sort(key=lambda e: (e.t, e.label))
    return FleetScenario(
        name="fleet_noisy_neighbor", seed=seed, horizon_s=2700.0,
        nodes=nodes, tenants=ts, events=tuple(events),
        availability_floor=0.5, fair_share=2,
        admission_window_s=0.5)


def fleet_week(seed: int = 3, tenants: int = 240,
               days: float = 7.0) -> FleetScenario:
    """The fleet soak: a multi-hundred-tenant virtual week mixing every
    family — staggered onboarding over the first two days, a
    correlated zone outage on day 3 hitting ALL tenants at once, a
    two-node spot burst on day 5, and rotating noisy-neighbor weight
    waves throughout.  Replays bit-identically under the
    DeterministicLoop (the ISSUE 13 acceptance scenario)."""
    rng = random.Random(f"fweek:{seed}:{tenants}")
    nodes = _zone_nodes(3, 6)  # 18 nodes
    day = 86_400.0
    horizon = days * day
    head = max(tenants // 4, 1)

    def onboard(i: int) -> float:
        if i < head:
            return 0.0
        return _jitter(rng, 0.1 * day + (i - head) * (1.9 * day / max(
            tenants - head, 1)), 600)

    ts = _fleet_tenants(rng, tenants, (8, 12), onboard)
    events: list[FleetEvent] = []
    # Day 3: correlated zone outage (one event, every tenant's loop).
    z2 = tuple(n for n in nodes if n.startswith("z2"))
    t_down = _jitter(rng, 3.0 * day, 3600)
    events.append(FleetEvent(t=t_down, delta=ClusterDelta(fail=z2),
                             label="zone-z2-outage", outage=True))
    events.append(FleetEvent(t=_jitter(rng, t_down + 0.1 * day, 600),
                             delta=ClusterDelta(add=z2),
                             label="zone-z2-returns"))
    # Day 5: spot burst (two survivors of z0).
    victims = tuple(sorted(rng.sample(
        [n for n in nodes if n.startswith("z0")], 2)))
    t_kill = _jitter(rng, 5.0 * day, 3600)
    events.append(FleetEvent(t=t_kill, delta=ClusterDelta(fail=victims),
                             label="spot-burst", outage=True))
    events.append(FleetEvent(t=_jitter(rng, t_kill + 0.05 * day, 600),
                             delta=ClusterDelta(add=victims),
                             label="spot-burst-returns"))
    # Rotating noisy neighbors: every half-day, one tenant heats up.
    for w in range(int(days * 2)):
        key = rng.choice([t.key for t in ts[:head]])
        p = rng.randrange(6)
        events.append(FleetEvent(
            t=_jitter(rng, (w + 0.6) * 0.5 * day, 1800),
            delta=ClusterDelta(
                partition_weights={f"p{p:04d}": rng.choice([1, 4, 8])}),
            tenants=(key,), label=f"hot-wave-{w:02d}-{key}"))
    events.sort(key=lambda e: (e.t, e.label))
    return FleetScenario(
        name="fleet_week", seed=seed, horizon_s=horizon,
        nodes=nodes, tenants=ts, events=tuple(events),
        availability_floor=0.5, fair_share=4,
        max_steps=200_000_000)


# Fleet scenario-family registry: name -> builder(seed, tenants).  The
# CI fleet-sim smoke crosses fixed seeds with small tenant-scale
# points; fleet_week at multi-hundred tenants is the slow-marked soak.
FLEET_SCENARIOS: dict[str, Callable[..., FleetScenario]] = {
    "fleet_zone_outage": fleet_zone_outage,
    "fleet_onboarding": fleet_onboarding,
    "fleet_noisy_neighbor": fleet_noisy_neighbor,
    "fleet_week": fleet_week,
}
