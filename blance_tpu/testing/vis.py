"""Visual placement DSL for golden planner tests.

Reimplements the reference's ASCII test harness (reference:
/root/reference/plan_test.go:1611-1744): each partition is one row; columns
are nodes "a", "b", "c", ...; cell tokens name the state the node holds —
"m" = primary, "s" = replica — optionally followed by a replica ordinal when
``cell_length=2`` ("m0", "s0", "s1"), in which case node order within a state
follows the ordinal.  This is what keeps thousands of lines of placement
expectations readable, and it only works because the planner is fully
deterministic (stable sorts, node-position tie-breaks, sorted hierarchy
children).

Example row pair (from "m s" to "sm "): partition moved its primary from
node a to node b and grew a replica on a.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..core.types import (
    HierarchyRules,
    Partition,
    PartitionMap,
    PartitionModel,
    PlanOptions,
)
from ..plan.api import plan_next_map

__all__ = ["VisCase", "parse_vis_row", "vis_maps", "run_vis_cases",
           "format_vis_map", "assert_contract"]

_STATE_NAMES = {"m": "primary", "s": "replica"}


def _node_name(i: int) -> str:
    return chr(ord("a") + i)


def parse_vis_row(row: str, cell_length: int) -> dict[str, list[str]]:
    """One ASCII row -> nodes_by_state.

    Cells are read per node column, then sorted by cell text so replica
    ordinals ("s0" < "s1") define list order (plan_test.go:1677-1692).
    """
    cells: list[tuple[str, str]] = []
    for j in range(0, len(row), cell_length):
        cells.append((row[j : j + cell_length], _node_name(j // cell_length)))
    cells.sort(key=lambda c: c[0])
    nbs: dict[str, list[str]] = {}
    for entry, node in cells:
        state = _STATE_NAMES.get(entry[0:1])
        if state:
            nbs.setdefault(state, []).append(node)
    return nbs


def format_vis_map(
    pmap: PartitionMap, nodes: list[str], cell_length: int = 1
) -> list[str]:
    """Inverse of parse_vis_row, for readable test failure output."""
    state_letter = {v: k for k, v in _STATE_NAMES.items()}
    rows = []
    for pname in sorted(pmap):
        p = pmap[pname]
        cells = {n: " " * cell_length for n in nodes}
        for state, snodes in p.nodes_by_state.items():
            for ordinal, node in enumerate(snodes):
                letter = state_letter.get(state, "?")
                cell = letter if cell_length == 1 else f"{letter}{ordinal}"
                cells[node] = cell
        rows.append("".join(cells[n] for n in nodes))
    return rows


@dataclass
class VisCase:
    """One golden scenario (plan_test.go:1611-1627)."""

    about: str
    from_to: list[tuple[str, str]]
    nodes: list[str]
    model: PartitionModel
    nodes_to_remove: list[str] = field(default_factory=list)
    nodes_to_add: list[str] = field(default_factory=list)
    from_to_priority: bool = False
    model_state_constraints: Optional[dict[str, int]] = None
    partition_weights: Optional[dict[str, int]] = None
    state_stickiness: Optional[dict[str, int]] = None
    node_weights: Optional[dict[str, int]] = None
    node_hierarchy: Optional[dict[str, str]] = None
    hierarchy_rules: Optional[HierarchyRules] = None
    exp_num_warnings: int = 0  # partitions-with-warnings count
    ignore: bool = False
    backend: str = "greedy"


def vis_maps(case: VisCase) -> tuple[PartitionMap, PartitionMap]:
    """Build (prev_map, expected_map) from the from/to rows."""
    cell_length = 2 if case.from_to_priority else 1
    prev_map: PartitionMap = {}
    exp_map: PartitionMap = {}
    for i, (frm, to) in enumerate(case.from_to):
        pname = f"{i:03d}"
        prev_map[pname] = Partition(pname, parse_vis_row(frm, cell_length))
        exp_map[pname] = Partition(pname, parse_vis_row(to, cell_length))
    return prev_map, exp_map


def _weighted_state_spread(
    pmap: PartitionMap, model: PartitionModel, nodes: list[str],
    node_weights: Optional[dict[str, int]],
    partition_weights: Optional[dict[str, int]],
) -> dict[str, float]:
    """Per state: max-min of partition-weighted load / node weight over
    ``nodes`` — the quantity the planners balance (plan.go:94)."""
    nw = node_weights or {}
    pw = partition_weights or {}
    out: dict[str, float] = {}
    for st in model:
        loads = {n: 0.0 for n in nodes}
        for pname, p in pmap.items():
            w = pw.get(pname, 1)
            for n in p.nodes_by_state.get(st, []):
                if n in loads:
                    loads[n] += w
        vals = [loads[n] / max(nw.get(n, 1), 1) for n in nodes]
        out[st] = max(vals) - min(vals) if vals else 0.0
    return out


def assert_contract(
    label: str,
    prev_map: PartitionMap,
    partitions_to_assign: PartitionMap,
    exp_map: PartitionMap,
    result: PartitionMap,
    nodes: list[str],
    nodes_to_remove: list[str],
    model: PartitionModel,
    opts: PlanOptions,
) -> None:
    """Contract-mode assertions for the batched (tpu) backend: the solver
    is deliberately not bit-identical to the sequential greedy (it solves
    globally), so the golden corpus asserts the properties that matter
    instead of the exact map: ZERO audit violations (duplicates, removed
    nodes, unfilled feasible slots, feasible-tier hierarchy misses) and
    per-state weighted balance within the golden oracle's spread + 1."""
    import numpy as np

    from ..core.encode import encode_problem
    from ..plan.tensor import check_assignment

    problem = encode_problem(prev_map, partitions_to_assign, nodes,
                             nodes_to_remove, model, opts)
    r_max = max([problem.R, 1] + [
        len(ns) for p in result.values()
        for ns in p.nodes_by_state.values()])
    assign = np.full((problem.P, problem.S, r_max), -1, np.int32)
    nidx = {n: j for j, n in enumerate(problem.nodes)}
    sidx = {s: j for j, s in enumerate(problem.states)}
    for pi, pname in enumerate(problem.partitions):
        assert pname in result, (
            f"{label}: planner result is missing partition {pname!r} "
            f"(has {len(result)} of {len(problem.partitions)})")
        for s, ns in result[pname].nodes_by_state.items():
            if s not in sidx:
                continue  # unmodeled passthrough states aren't audited
            for ri, node in enumerate(ns):
                assign[pi, sidx[s], ri] = nidx[node]
    counts = check_assignment(problem, assign)
    assert not any(counts.values()), (
        f"{label}: tpu contract violations {counts}:\n"
        + "\n".join(format_vis_map(result, nodes)))

    survivors = [n for n in nodes if n not in (nodes_to_remove or [])]
    sp_got = _weighted_state_spread(
        result, model, survivors, opts.node_weights, opts.partition_weights)
    sp_exp = _weighted_state_spread(
        exp_map, model, survivors, opts.node_weights, opts.partition_weights)
    # Slack: placements are integral in partition-weight units (a single
    # differently-placed copy moves the spread by its weight) plus one
    # unit for the auction's first-bidder progress overshoot.
    wmax = max((opts.partition_weights or {}).values(), default=1)
    for st in model:
        assert sp_got[st] <= sp_exp[st] + wmax + 1, (
            f"{label}: state {st} spread {sp_got[st]} "
            f"vs golden oracle {sp_exp[st]} (+{wmax}+1):\n"
            + "\n".join(format_vis_map(result, nodes)))


def run_vis_cases(cases: list[VisCase], backend: Optional[str] = None) -> None:
    """Plan each case and assert expectations.

    ``backend`` overrides every case's backend.  The exact planners
    (greedy / native) assert the golden map bit-for-bit; the batched
    "tpu" backend asserts CONTRACT properties instead (assert_contract)
    plus the same warnings-count equality — the reference's curated hard
    cases (plan_test.go:1746-2863) pointed at the solver that is not
    meant to be bit-identical."""
    for i, case in enumerate(cases):
        if case.ignore:
            continue
        prev_map, exp_map = vis_maps(case)
        opts = PlanOptions(
            model_state_constraints=case.model_state_constraints,
            partition_weights=case.partition_weights,
            state_stickiness=case.state_stickiness,
            node_weights=case.node_weights,
            node_hierarchy=case.node_hierarchy,
            hierarchy_rules=case.hierarchy_rules,
        )
        resolved = backend or case.backend
        result, warnings = plan_next_map(
            prev_map,
            prev_map,
            case.nodes,
            case.nodes_to_remove,
            case.nodes_to_add,
            case.model,
            opts,
            backend=resolved,
        )
        cell_length = 2 if case.from_to_priority else 1
        if resolved == "tpu":
            assert_contract(
                f"case {i} ({case.about})", prev_map, prev_map, exp_map,
                result, case.nodes, case.nodes_to_remove, case.model, opts)
        else:
            got = {name: p.nodes_by_state for name, p in result.items()}
            exp = {name: p.nodes_by_state for name, p in exp_map.items()}
            assert got == exp, (
                f"case {i} ({case.about}):\n"
                f"got:\n"
                + "\n".join(format_vis_map(result, case.nodes, cell_length))
                + "\nexpected:\n"
                + "\n".join(format_vis_map(exp_map, case.nodes, cell_length))
            )
        assert len(warnings) == case.exp_num_warnings, (
            f"case {i} ({case.about}): warnings {warnings} "
            f"expected {case.exp_num_warnings} partitions-with-warnings"
        )
