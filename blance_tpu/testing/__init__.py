"""blance_tpu.testing subpackage.

- :mod:`.vis` — plan/transition visualization helpers.
- :mod:`.sched` — deterministic asyncio schedule exploration (the
  controlled loop, seeded walks, bounded-exhaustive enumeration, and
  replayable schedule traces) used by the race-detection tier
  (``blance_tpu.analysis.schedule``) and the regression tests.
"""
