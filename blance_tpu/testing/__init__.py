"""blance_tpu.testing subpackage."""
