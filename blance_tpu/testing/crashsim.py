"""Deterministic crash-injection harness for the durability tier
(docs/DURABILITY.md "Crash injection").

A real controller crash is: the process stops, nothing past the last
durable journal record exists, and a restart runs
:func:`~blance_tpu.durability.recover.recover` +
:func:`~blance_tpu.durability.recover.resume_controller`.  This module
reproduces exactly that inside the
:class:`~blance_tpu.testing.sched.DeterministicLoop`:

- :class:`CrashingJournal` — a :class:`~blance_tpu.durability.journal.
  Journal` that "dies" after a scripted number of appends: every later
  record is silently dropped (it never reached disk) and a crash flag
  raises.  No exception is thrown into controller code — a crash is the
  absence of durability, not a control-flow event.
- :func:`run_crash_scenario` — one full cluster life over a
  :class:`~blance_tpu.testing.scenarios.SimScenario`: run, die at each
  scripted record boundary, recover into a FRESH virtual loop (the
  restart clock starts at zero, exercising the re-basing paths),
  redeliver every event the journal never durably received (the
  upstream event source is at-least-once), converge, repeat until a
  life completes.  Emits a versioned, canonically-serialized event log
  (committed replay traces under ``tests/traces/``).
- :func:`crash_matrix` — the bounded-exhaustive acceptance check: a
  crash-free reference run, then one crashed run per journal-record
  boundary, each asserted to converge to the reference's final map
  bit-identically.

Determinism contract: everything is a pure function of (scenario,
crash boundaries) — virtual clocks, seeded scenarios, synchronous
journal appends — so the same inputs replay byte-identical logs.
"""

from __future__ import annotations

import asyncio
import json
import os
from dataclasses import dataclass, field
from typing import Any, Optional

from ..core.types import PartitionMap, PartitionModel
from ..durability.journal import Journal, map_digest
from ..durability.recover import RecoveredState, recover, resume_controller
from ..obs import Recorder, use_recorder
from ..orchestrate.orchestrator import OrchestratorOptions
from ..rebalance import RebalanceController
from .scenarios import SimEvent, SimScenario, initial_map, scenario_model
from .sched import DeterministicLoop, FifoPolicy

__all__ = [
    "CRASH_LOG_VERSION",
    "CrashingJournal",
    "CrashRunReport",
    "run_crash_scenario",
    "crash_matrix",
    "crash_log_text",
    "maps_identical",
]

CRASH_LOG_VERSION = 1

# Virtual-time poll interval for the event driver's crash checks: the
# crash flag flips synchronously inside controller appends, so the
# driver notices at the next poll tick — a fixed, deterministic lag.
_POLL_S = 0.25

# Runaway guard: a crash chain longer than this means the scripted
# boundaries never let a life complete (a harness bug, not a scenario).
_MAX_LIVES = 64


class CrashingJournal(Journal):
    """A journal that stops persisting after ``crash_after`` appends.

    The freeze is silent by design: record N+1 is simply never written
    (the process died before the write), the ``crashed`` flag flips,
    and the controller keeps running in memory — everything it does
    past the boundary is the doomed pre-crash work the harness then
    discards by cancelling its tasks.  ``crash_after=None`` never
    crashes (the reference configuration, kept on this class so record
    accounting is uniform)."""

    def __init__(self, *args: Any, crash_after: Optional[int] = None,
                 **kwargs: Any) -> None:
        self.crash_after = crash_after
        self.appended = 0
        self.crashed = False
        super().__init__(*args, **kwargs)

    def _frozen(self) -> bool:
        if (self.crash_after is not None
                and self.appended >= self.crash_after):
            self.crashed = True
            return True
        return False

    def append(self, kind: str, data: "dict[str, Any]", *,
               t: Optional[float] = None,
               tenant: Optional[str] = None) -> bool:
        if self._frozen():
            return False
        ok = super().append(kind, data, t=t, tenant=tenant)
        if ok:
            self.appended += 1
        return ok

    def write_snapshot(self, payload: "dict[str, Any]", *,
                       t: Optional[float] = None,
                       tenant: Optional[str] = None) -> str:
        # If the boundary lands ON the pointer append, the snapshot
        # file may exist without its pointer — exactly the torn case
        # recovery ignores (the pointer is the commit point).
        if self._frozen():
            return ""
        return super().write_snapshot(payload, t=t, tenant=tenant)


def crash_log_text(events: "list[dict[str, Any]]") -> str:
    """Canonical byte-comparable serialization of a crash-run log
    (same shape discipline as ``testing.simulate.canonical_log_text``;
    committed traces are written and compared in this form)."""
    return json.dumps({"version": CRASH_LOG_VERSION, "events": events},
                      sort_keys=True, indent=1) + "\n"


def _nbs(pmap: PartitionMap) -> "dict[str, dict[str, list[str]]]":
    return {name: {s: list(ns) for s, ns in p.nodes_by_state.items()}
            for name, p in pmap.items()}


def maps_identical(a: PartitionMap, b: PartitionMap) -> bool:
    """Bit-identical partition maps (names, states, node order)."""
    return _nbs(a) == _nbs(b)


@dataclass
class _LifeResult:
    crashed: bool
    next_event: int  # global index of the first event to (re)deliver
    records: int     # records durably appended this life
    final_map: Optional[PartitionMap] = None


@dataclass
class CrashRunReport:
    """One complete (possibly multi-crash) cluster life."""

    scenario: str
    seed: int
    crashes: "tuple[int, ...]"
    lives: int
    final_map: PartitionMap
    events: "list[dict[str, Any]]"
    counters: "dict[str, float]" = field(default_factory=dict)
    # Durable records written by the FIRST life — the reference run's
    # value is the exhaustive matrix's boundary count.
    records_first_life: int = 0

    def log_text(self) -> str:
        return crash_log_text(self.events)


def _orch_opts(scn: SimScenario) -> OrchestratorOptions:
    return OrchestratorOptions(
        move_timeout_s=scn.move_timeout_s,
        max_retries=scn.max_retries,
        backoff_base_s=scn.backoff_base_s,
        retry_seed=scn.seed,
        quarantine_after=scn.quarantine_after,
        probe_after_s=scn.probe_after_s,
        max_concurrent_partition_moves_per_node=scn.max_concurrent_moves)


async def _run_life(scn: SimScenario, model: PartitionModel,
                    loop: DeterministicLoop, rec: Recorder,
                    journal: CrashingJournal,
                    state: Optional[RecoveredState],
                    from_event: int, life: int,
                    log: "list[dict[str, Any]]") -> _LifeResult:
    """One process lifetime: build or resume the controller, deliver
    the not-yet-durable tail of the event trace, converge or die."""

    async def data_plane(stop_ch: Any, node: str, partitions: "list[str]",
                         states: "list[str]", ops: "list[str]") -> None:
        await asyncio.sleep(
            scn.node_latency_s.get(node, scn.base_latency_s))

    if state is not None and None in state.tenants:
        ctl = resume_controller(
            state, model, data_plane,
            orchestrator_options=_orch_opts(scn),
            backend=scn.backend, debounce_s=scn.debounce_s,
            max_passes_per_cycle=scn.max_passes_per_cycle)
    else:
        # First life — or a crash so early the genesis record itself
        # was lost: nothing durable exists, bootstrap from scratch.
        ctl = RebalanceController(
            model, list(scn.nodes), initial_map(scn), data_plane,
            orchestrator_options=_orch_opts(scn),
            backend=scn.backend, debounce_s=scn.debounce_s,
            max_passes_per_cycle=scn.max_passes_per_cycle,
            journal=journal)
        ctl.start()

    events = sorted(scn.events, key=lambda e: (e.t, e.label))[from_event:]
    crashed = journal.crashed
    next_local = 0
    for i, ev in enumerate(events):
        while loop.time() < ev.t and not journal.crashed:
            await asyncio.sleep(min(_POLL_S, ev.t - loop.time()))
        if journal.crashed:
            crashed, next_local = True, i
            break
        before = journal.appended
        log.append({
            "kind": "delta", "life": life, "t": rec.now(),
            "label": ev.label, "outage": ev.outage,
            "add": list(ev.delta.add), "remove": list(ev.delta.remove),
            "fail": list(ev.delta.fail),
            "partition_weights": dict(ev.delta.partition_weights or {}),
            "node_weights": dict(ev.delta.node_weights or {})})
        ctl.submit(ev.delta)
        if journal.appended == before:
            # The delta's own record was the first casualty: this event
            # never became durable — it is the redelivery point.
            crashed, next_local = True, i
            break
        next_local = i + 1

    final: Optional[PartitionMap] = None
    if not crashed:
        final = await ctl.quiesce()
        # The journal may have died during convergence or on the
        # quiesce/snapshot records themselves — the in-memory idle map
        # is then doomed pre-crash state, not a result.
        crashed = journal.crashed

    if crashed:
        log.append({"kind": "crash", "life": life, "t": rec.now(),
                    "epoch": journal.epoch, "records": journal.appended,
                    "next_event": from_event + next_local})
        for task in ctl.pending_tasks():
            task.cancel()
        for _ in range(8):  # drain the cancellations
            await asyncio.sleep(0)
        return _LifeResult(True, from_event + next_local,
                           journal.appended)

    assert final is not None
    log.append({"kind": "life-end", "life": life, "t": rec.now(),
                "epoch": journal.epoch, "records": journal.appended,
                "map_digest": map_digest(final)})
    await ctl.stop()
    journal.close()
    return _LifeResult(False, from_event + len(events),
                       journal.appended, final_map=final)


def run_crash_scenario(scn: SimScenario, journal_dir: str, *,
                       crashes: "tuple[int, ...]" = (),
                       snapshot_every: int = 0,
                       rotate_records: int = 64) -> CrashRunReport:
    """One cluster life under a scripted crash chain: life ``i`` dies
    after ``crashes[i]`` durable records (lives past the end of
    ``crashes`` run crash-free).  Each restart recovers from the
    journal into a fresh virtual loop and redelivers the events the
    journal never durably received.  Pure function of its arguments —
    same scenario + boundaries => byte-identical ``log_text()``."""
    model = scenario_model(scn)
    log: "list[dict[str, Any]]" = [{
        "kind": "init", "life": 0, "t": 0.0, "scenario": scn.name,
        "seed": scn.seed, "crashes": list(crashes),
        "nodes": list(scn.nodes), "partitions": scn.partitions,
        "replicas": scn.replicas, "snapshot_every": snapshot_every}]
    counters: "dict[str, float]" = {}
    from_event = 0
    records_first = 0
    life = 0
    while True:
        if life > _MAX_LIVES:
            raise RuntimeError(
                f"crash chain never completed a life ({scn.name})")
        loop = DeterministicLoop(FifoPolicy(), max_steps=scn.max_steps)
        rec = Recorder(clock=loop.time)
        crash_after = crashes[life] if life < len(crashes) else None
        with use_recorder(rec):
            if life == 0:
                journal = CrashingJournal(
                    journal_dir, clock=loop.time,
                    crash_after=crash_after,
                    rotate_records=rotate_records,
                    snapshot_every=snapshot_every)
                state: Optional[RecoveredState] = None
            else:
                def _factory(*a: Any, **kw: Any) -> Journal:
                    return CrashingJournal(
                        *a, crash_after=crash_after, **kw)

                state = recover(
                    journal_dir, clock=loop.time,
                    rotate_records=rotate_records,
                    snapshot_every=snapshot_every,
                    journal_factory=_factory)
                journal = state.journal  # type: ignore[assignment]
                t0 = state.tenants.get(None)
                log.append({
                    "kind": "recover", "life": life, "t": 0.0,
                    "epoch": state.epoch,
                    "replayed": state.records_replayed,
                    "torn": state.torn_segments,
                    "stale_dropped": state.stale_dropped,
                    "next_event": from_event,
                    "map_digest": (map_digest(t0.pmap)
                                   if t0 is not None else None)})
            result = loop.run_until_complete(_run_life(
                scn, model, loop, rec, journal, state,  # type: ignore[arg-type]
                from_event, life, log))
        for name, value in rec.counters.items():
            if name.startswith("durability."):
                counters[name] = counters.get(name, 0) + value
        if life == 0:
            records_first = result.records
        if not result.crashed:
            assert result.final_map is not None
            log.append({"kind": "end", "life": life, "t": 0.0,
                        "lives": life + 1,
                        "map_digest": map_digest(result.final_map),
                        "placements": _nbs(result.final_map)})
            return CrashRunReport(
                scenario=scn.name, seed=scn.seed, crashes=tuple(crashes),
                lives=life + 1, final_map=result.final_map, events=log,
                counters=counters, records_first_life=records_first)
        from_event = result.next_event
        life += 1


def crash_matrix(scn: SimScenario, base_dir: str, *,
                 boundaries: "Optional[list[int]]" = None,
                 snapshot_every: int = 0, rotate_records: int = 64,
                 ) -> "tuple[CrashRunReport, list[tuple[int, CrashRunReport]]]":
    """The bounded-exhaustive acceptance check: a crash-free reference
    run, then one single-crash run per journal-record boundary of the
    reference (or per entry of ``boundaries``).  Returns the reference
    report plus ``(boundary, report)`` pairs — callers assert each
    report's final map is bit-identical to the reference's."""
    ref = run_crash_scenario(
        scn, os.path.join(base_dir, "ref"), crashes=(),
        snapshot_every=snapshot_every, rotate_records=rotate_records)
    ks = (boundaries if boundaries is not None
          else list(range(ref.records_first_life)))
    out: "list[tuple[int, CrashRunReport]]" = []
    for k in ks:
        report = run_crash_scenario(
            scn, os.path.join(base_dir, f"k{k:04d}"), crashes=(k,),
            snapshot_every=snapshot_every, rotate_records=rotate_records)
        out.append((k, report))
    return ref, out
