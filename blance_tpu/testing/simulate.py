"""Event-driven closed-loop cluster-life simulator (ROADMAP item 4).

The paper's scenario is ONE rebalance; production is a control loop
under churn.  This module closes the loop: a seeded
:class:`~blance_tpu.testing.scenarios.SimScenario` trace (node
arrivals/departures, bulk spot preemptions, rolling zone outages,
hot-tenant weight drift, flaky/slow movers) drives a
:class:`~blance_tpu.rebalance.RebalanceController` — plan -> diff ->
orchestrate, repeatedly, with debounce, mid-flight supersede and
graceful degradation — entirely under the
:class:`~blance_tpu.testing.sched.DeterministicLoop` virtual clock, so
a week of cluster life replays bit-identically in seconds.

Per-run scoring extends the ``SloTracker`` horizon account:

- **time-weighted availability** over the whole horizon, plus the
  SLO-violation intervals against the scenario's floor;
- **cumulative churn vs the offline optimum** — executed moves divided
  by what ONE plan from the initial map to the final membership would
  have moved (the single-plan lower bound no online loop can beat);
- **per-incident convergence lag** — delta submission to the control
  loop's next quiesce, one sample per scripted incident
  (``sim.convergence_lag_s``);
- **scripted-outage discipline** — every availability DROP must fall
  inside a scripted outage window (an ``outage=True`` event until the
  loop's next quiesce); a drop outside one is a lost primary nobody
  scripted, reported in ``SimReport.unscripted_drops``.

Everything the run did lands in a VERSIONED JSON event log (schema in
docs/SIMULATOR.md): the initial placements, every delta/strip/batch
with virtual timestamps, quiesce points with closed incidents, and the
final summary.  The log is the ground truth the SLO property tests
brute-force-recompute from, and the replay artifact: the same scenario
seed produces byte-identical log text, pinned by committed traces under
``tests/traces/``.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Optional, Sequence

from ..core.types import PartitionMap, PartitionModel
from ..obs import Recorder, use_recorder
from ..obs.expo import render_prometheus
from ..obs.slo import SloSummary, SloTracker
from ..orchestrate.faults import FaultPlan
from ..orchestrate.orchestrator import OrchestratorOptions
from ..plan.api import plan_next_map
from ..rebalance import RebalanceController, count_moves
from ..utils.hostclock import perf_now
from .scenarios import SimScenario, initial_map, scenario_model
from .sched import DeterministicLoop, FifoPolicy

__all__ = [
    "SIM_LOG_VERSION",
    "SimLog",
    "SimReport",
    "run_scenario",
    "canonical_log_text",
    "recompute_slo_from_log",
]

SIM_LOG_VERSION = 1


class SimLog:
    """The run's versioned event log; also a move observer (``on_batch``)
    so every executed/failed batch lands with its virtual timestamp.
    Events append in virtual-clock order by construction (the clock is
    monotone and every emit happens inside the run)."""

    def __init__(self) -> None:
        self.events: list[dict[str, Any]] = []

    def emit(self, kind: str, t: float, **fields: Any) -> None:
        self.events.append({"kind": kind, "t": t, **fields})

    # MoveObserver hook (duck-typed; see obs/slo.py).
    def on_batch(self, node: str, moves: Sequence[Any], ok: bool,
                 now: float) -> None:
        self.emit("batch", now, node=node, ok=bool(ok),
                  moves=[[m.partition, m.node, m.state, m.op]
                         for m in moves])


def canonical_log_text(events: list[dict[str, Any]]) -> str:
    """THE byte-comparable serialization: sorted keys, fixed
    separators, trailing newline.  Committed replay traces are written
    and compared in exactly this form."""
    return json.dumps({"version": SIM_LOG_VERSION, "events": events},
                      sort_keys=True, indent=1) + "\n"


@dataclass
class SimReport:
    """Everything one scenario run produced (see module doc)."""

    scenario: str
    seed: int
    horizon_s: float
    final_map: PartitionMap
    complete: bool
    summary: SloSummary
    exposition: str  # rendered Prometheus text at end of run
    events: list[dict[str, Any]]
    deltas: int
    rebalances: int
    superseded: int
    degraded: int
    unconverged: int
    quarantined: list[str]
    convergence_lags: list[float]
    offline_min_moves: int
    # None when the offline optimum is zero moves (the trace returned
    # the membership to its start): transient work has no single-plan
    # baseline to divide by.
    churn_vs_offline: Optional[float]
    # Availability drops whose timestamp fell OUTSIDE every scripted
    # outage window: (t, availability) pairs; must be empty.
    unscripted_drops: list[tuple[float, float]] = field(
        default_factory=list)
    steps: int = 0
    wall_s: float = 0.0  # host time; NOT part of the replayable account

    def log_text(self) -> str:
        return canonical_log_text(self.events)


def _map_complete(pmap: PartitionMap, model: PartitionModel,
                  live: set[str]) -> bool:
    """Every partition holds its full constraint count per state, all
    placements on live nodes, no duplicates."""
    for p in pmap.values():
        seen: set[str] = set()
        for state, st in model.items():
            ns = p.nodes_by_state.get(state, [])
            if len(ns) != st.constraints:
                return False
            for n in ns:
                if n in seen or n not in live:
                    return False
                seen.add(n)
    return True


async def _sim_main(scn: SimScenario, loop: DeterministicLoop,
                    rec: Recorder) -> SimReport:
    model = scenario_model(scn)
    beg = initial_map(scn)
    slo = SloTracker(
        beg, primary_states=("primary",), clock=rec.now, recorder=rec,
        track_timeline=True, availability_floor=scn.availability_floor)
    log = SimLog()
    log.emit(
        "init", 0.0, scenario=scn.name, seed=scn.seed,
        horizon_s=scn.horizon_s, nodes=list(scn.nodes),
        replicas=scn.replicas, floor=scn.availability_floor,
        placements={name: {s: list(ns)
                           for s, ns in p.nodes_by_state.items()}
                    for name, p in beg.items()})

    fault_plan = FaultPlan(seed=scn.seed, nodes=dict(scn.fault_nodes))

    async def data_plane(stop_ch: Any, node: str, partitions: list[str],
                         states: list[str], ops: list[str]) -> None:
        import asyncio

        await asyncio.sleep(
            scn.node_latency_s.get(node, scn.base_latency_s))

    session = None
    if scn.use_session:
        from ..plan.session import PlannerSession

        session = PlannerSession(model, list(scn.nodes),
                                 sorted(beg.keys()))
        session.load_map(beg)

    orch_opts = OrchestratorOptions(
        move_timeout_s=scn.move_timeout_s,
        max_retries=scn.max_retries,
        backoff_base_s=scn.backoff_base_s,
        retry_seed=scn.seed,
        quarantine_after=scn.quarantine_after,
        probe_after_s=scn.probe_after_s,
        max_concurrent_partition_moves_per_node=scn.max_concurrent_moves)
    if scn.scheduler == "critical_path":
        # Critical-path move order (docs/SCHEDULER.md): the cost model
        # seeds from the committed bench priors and recalibrates ONLINE
        # from this very run's move spans (virtual-time durations, so
        # the whole account replays bit-identically); each controller
        # pass re-binds the policy against its fresh move plans.
        from ..obs.costmodel import CostModel, default_op_priors
        from ..orchestrate.sched import CriticalPathScheduler

        cost_model = CostModel(recorder=rec)
        cost_model.seed_priors(default_op_priors())
        rec.add_sink(cost_model)
        orch_opts.scheduler = CriticalPathScheduler(cost_model=cost_model)
    elif scn.scheduler != "legacy":
        raise ValueError(f"unknown scheduler {scn.scheduler!r} "
                         f"(want 'legacy' or 'critical_path')")

    ctl = RebalanceController(
        model, list(scn.nodes), beg, fault_plan.wrap(data_plane),
        orchestrator_options=orch_opts,
        backend=scn.backend, session=session,
        debounce_s=scn.debounce_s,
        max_passes_per_cycle=scn.max_passes_per_cycle,
        slo=slo, move_observers=(log,))

    # Incident accounting: each scripted event opens an incident; the
    # controller's next quiesce closes every open one, with the lag as
    # the per-incident convergence sample.  Outage incidents also
    # define the windows availability is ALLOWED to drop in.
    open_incidents: list[dict[str, Any]] = []
    lags: list[float] = []
    outage_windows: list[list[float]] = []  # [start, end]

    def on_quiesce(t: float) -> None:
        if not open_incidents:
            return
        closed = []
        for inc in open_incidents:
            lag = t - inc["t"]
            lags.append(lag)
            rec.observe("sim.convergence_lag_s", lag)
            closed.append({"label": inc["label"], "lag_s": lag})
            if inc["outage"]:
                outage_windows.append([inc["t"], t])
        open_incidents.clear()
        log.emit("quiesce", t, closed=closed,
                 availability=slo.availability())

    def on_strip(nodes: set[str], t: float) -> None:
        log.emit("strip", t, nodes=sorted(nodes))

    ctl.on_quiesce.append(on_quiesce)
    ctl.on_strip.append(on_strip)
    ctl.start()

    import asyncio

    for ev in sorted(scn.events, key=lambda e: (e.t, e.label)):
        delay = ev.t - loop.time()
        if delay > 0:
            await asyncio.sleep(delay)
        t = rec.now()
        rec.count("sim.events")
        log.emit("delta", t, label=ev.label, outage=ev.outage,
                 add=list(ev.delta.add), remove=list(ev.delta.remove),
                 fail=list(ev.delta.fail),
                 partition_weights=dict(ev.delta.partition_weights or {}),
                 node_weights=dict(ev.delta.node_weights or {}))
        open_incidents.append({"t": t, "label": ev.label,
                               "outage": ev.outage})
        ctl.submit(ev.delta)

    remaining = scn.horizon_s - loop.time()
    if remaining > 0:
        await asyncio.sleep(remaining)
    final = await ctl.quiesce()
    await ctl.stop()

    # Offline-optimal churn baseline: ONE plan from the initial map to
    # the final membership — what a clairvoyant single rebalance would
    # have moved.  (Computed after the run so the planner sees exactly
    # the final candidate set.)
    live = ctl.live_nodes()
    removed = sorted(set(ctl._nodes) - set(live))
    offline_map, _w = plan_next_map(
        beg, beg, list(ctl._nodes), removed, [], model,
        ctl.opts, backend=scn.backend)
    offline_moves = count_moves(model, beg, offline_map)
    slo.set_min_moves(offline_moves)

    t_end = rec.now()
    summary = slo.summary(t_end)

    # Scripted-outage discipline: every availability DROP in the
    # timeline must fall inside some outage window.
    drops = []
    timeline = slo.timeline()
    for (t0, a0), (t1, a1) in zip(timeline, timeline[1:]):
        if a1 < a0 and not any(s <= t1 <= e for s, e in outage_windows):
            drops.append((t1, a1))

    complete = _map_complete(final, model, set(live))
    log.emit(
        "end", t_end,
        availability=summary.availability,
        time_weighted_availability=summary.time_weighted_availability,
        violation_s=summary.violation_s,
        moves_executed=summary.moves_executed,
        moves_failed=summary.moves_failed,
        offline_min_moves=offline_moves,
        complete=complete)

    return SimReport(
        scenario=scn.name, seed=scn.seed, horizon_s=scn.horizon_s,
        final_map=final, complete=complete, summary=summary,
        exposition=render_prometheus(rec), events=log.events,
        deltas=len(scn.events), rebalances=ctl.passes,
        superseded=ctl.superseded,
        degraded=len(ctl.degraded_reports),
        unconverged=ctl.unconverged_cycles,
        quarantined=ctl.quarantined_nodes(),
        convergence_lags=lags,
        offline_min_moves=offline_moves,
        churn_vs_offline=(summary.moves_executed / offline_moves
                          if offline_moves else None),
        unscripted_drops=drops)


def run_scenario(scn: SimScenario) -> SimReport:
    """Run one scenario to completion under the virtual clock and score
    it.  Pure function of the scenario (same input -> byte-identical
    event log, SLO summary and exposition text); wall_s/steps are the
    only host-dependent fields."""
    loop = DeterministicLoop(FifoPolicy(), max_steps=scn.max_steps)
    rec = Recorder(clock=loop.time)
    t0 = perf_now()
    with use_recorder(rec):
        report = loop.run_until_complete(_sim_main(scn, loop, rec))
    report.wall_s = perf_now() - t0
    report.steps = loop.steps
    return report


# -- brute-force SLO recompute (the property-test oracle) ---------------------


def recompute_slo_from_log(events: list[dict[str, Any]],
                           floor: Optional[float] = None) -> dict[str, Any]:
    """Recompute availability/churn/lag/violations from the RAW event
    log alone — independent of ``SloTracker``'s incremental view.  The
    property tests assert the tracker's summary equals this, across
    seeded scenarios: any drift between the O(batch) incremental update
    and ground truth is a bug (docs/SIMULATOR.md).

    Mirrors the tracker's arithmetic exactly (change-compressed step
    timeline, in-order integral) so equality is EXACT, not approximate.
    """
    init = next(e for e in events if e["kind"] == "init")
    end = next(e for e in events if e["kind"] == "end")
    if floor is None:
        floor = init["floor"]
    placements: dict[str, dict[str, str]] = {}
    for pname, by_state in init["placements"].items():
        d: dict[str, str] = {}
        for state, ns in by_state.items():
            for n in ns:
                d[n] = state
        placements[pname] = d

    def availability() -> float:
        total = len(placements)
        if not total:
            return 1.0
        avail = sum(1 for d in placements.values()
                    if any(s == "primary" for s in d.values()))
        return avail / total

    timeline: list[tuple[float, float]] = [(0.0, availability())]
    executed = failed = 0
    t_last_progress = 0.0

    def note(t: float) -> None:
        a = availability()
        if a != timeline[-1][1]:
            timeline.append((t, a))

    for e in events:
        if e["kind"] == "batch":
            if e["ok"]:
                for part, node, state, _op in e["moves"]:
                    d = placements.get(part)
                    if d is None:
                        continue
                    d.pop(node, None)
                    if state:
                        d[node] = state
                executed += len(e["moves"])
                t_last_progress = e["t"]
                note(e["t"])
            else:
                failed += len(e["moves"])
        elif e["kind"] == "strip":
            for d in placements.values():
                for n in list(d):
                    if n in set(e["nodes"]):
                        d.pop(n)
            note(e["t"])

    t_end = end["t"]
    total = 0.0
    for (t_i, a_i), (t_j, _a_j) in zip(timeline, timeline[1:]):
        total += (t_j - t_i) * a_i
    t_last, a_last = timeline[-1]
    total += (t_end - t_last) * a_last
    tw = total / t_end if t_end > 0 else availability()

    intervals: list[tuple[float, float]] = []
    open_at: Optional[float] = None
    for t_i, a_i in timeline:
        if a_i < floor and open_at is None:
            open_at = t_i
        elif a_i >= floor and open_at is not None:
            intervals.append((open_at, t_i))
            open_at = None
    if open_at is not None:
        intervals.append((open_at, max(t_end, open_at)))

    return {
        "availability": availability(),
        "time_weighted_availability": tw,
        "violation_intervals": intervals,
        "violation_s": sum(e - s for s, e in intervals),
        "moves_executed": executed,
        "moves_failed": failed,
        "convergence_lag_s": max(t_end - t_last_progress, 0.0),
    }
