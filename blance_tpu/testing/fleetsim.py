"""Multi-tenant closed-loop fleet simulator (ISSUE 13, docs/SIMULATOR.md
"Multi-tenant scenario families").

``testing/simulate.py`` replays ONE tenant's cluster life; this module
replays a FLEET: a seeded :class:`~blance_tpu.testing.scenarios.
FleetScenario` drives a :class:`~blance_tpu.fleetloop.FleetController`
— N per-tenant ``RebalanceController`` loops multiplexed over one
shared ``PlanService`` + ``CarryCache`` — entirely under the
``DeterministicLoop`` virtual clock, so a multi-hundred-tenant virtual
week replays bit-identically: the event log, every tenant's SLO
summary, the fleet rollup AND the rendered exposition text are pure
functions of the scenario.

The runner executes the SAME scenario in two modes:

- ``coalesce=True`` (the fleet plane): overlapping debounce windows
  land tenants' converge cycles in shared bucketed ``[B, ...]`` fleet
  dispatches;
- ``coalesce=False`` (the sequential loop-per-tenant baseline): the
  same code path with a zero admission window and ``max_batch=1`` —
  one device dispatch per tenant per plan, the per-problem dispatch
  tax the fleet tier exists to eliminate.

Per-element fleet solves are bit-identical to single-problem solves
(plan/fleet.py's contract) and, with an unbounded carry cache, both
modes make identical warm/cold decisions — so the two runs converge to
IDENTICAL final maps with EQUAL executed moves, and the only deltas are
the dispatch count and the wall-clock (the ``fleet_loop`` bench stage's
gate).

Event-log schema (``FLEET_LOG_VERSION``): ``init`` (nodes + tenant
specs + t0 placements), ``onboard`` (a staggered tenant's empty-start),
``delta`` (label, targets, fields), ``strip``/``batch``/``quiesce``
(tenant-tagged), ``end`` (per-tenant availability + fleet rollup +
dispatch/request/starved counters).  ``canonical_fleet_log_text`` is
the byte-comparable serialization committed under ``tests/traces/``.
"""

from __future__ import annotations

import asyncio
import json
from dataclasses import dataclass, field
from typing import Any, Optional, Sequence

from ..core.types import Partition, PartitionMap, PartitionModel, model
from ..fleetloop import FleetController
from ..obs import Recorder, use_recorder
from ..obs.expo import render_prometheus
from ..obs.recorder import percentile
from ..obs.slo import FleetSloSummary, SloSummary
from ..rebalance import ClusterDelta
from ..utils.hostclock import perf_now
from .scenarios import FleetScenario, FleetTenant
from .sched import DeterministicLoop, FifoPolicy

__all__ = [
    "FLEET_LOG_VERSION",
    "FleetSimReport",
    "canonical_fleet_log_text",
    "run_fleet_scenario",
    "tenant_model",
    "tenant_initial_map",
]

FLEET_LOG_VERSION = 1


def tenant_model(spec: FleetTenant) -> PartitionModel:
    """primary(+replicas) model for one tenant."""
    if spec.replicas > 0:
        return model(primary=(0, 1), replica=(1, spec.replicas))
    return model(primary=(0, 1))


def tenant_initial_map(spec: FleetTenant, nodes: Sequence[str],
                       offset: int) -> PartitionMap:
    """Deterministic seed placements.  A t0 tenant gets round-robin
    placements offset by its fleet index (tenants don't all pile their
    primaries on node 0); an onboarding tenant starts EMPTY — its first
    converge cycle places everything."""
    out: PartitionMap = {}
    n = len(nodes)
    for i in range(spec.partitions):
        name = f"p{i:04d}"
        if spec.onboard_t > 0:
            nbs: dict[str, list[str]] = {}
        else:
            nbs = {"primary": [nodes[(i + offset) % n]]}
            if spec.replicas > 0:
                nbs["replica"] = [nodes[(i + offset + 1 + r) % n]
                                  for r in range(spec.replicas)]
        out[name] = Partition(name, nbs)
    return out


def canonical_fleet_log_text(events: list[dict[str, Any]]) -> str:
    """THE byte-comparable serialization (sorted keys, fixed
    separators, trailing newline) — committed replay traces are written
    and compared in exactly this form."""
    return json.dumps({"version": FLEET_LOG_VERSION, "events": events},
                      sort_keys=True, indent=1) + "\n"


@dataclass
class FleetSimReport:
    """Everything one fleet scenario run produced (module doc)."""

    scenario: str
    seed: int
    coalesced: bool
    horizon_s: float
    tenants: int
    final_maps: dict[str, PartitionMap]
    complete: bool
    summaries: dict[str, SloSummary]
    fleet: FleetSloSummary
    events: list[dict[str, Any]]
    # Device-dispatch economics: the coalescing win is
    # dispatches << plan_requests (sequential mode: dispatches ==
    # plan_requests).
    dispatches: int
    plan_requests: int
    starved_admissions: int
    carry_evictions: dict[str, int]
    carry_hits: int
    cycles: int
    passes: int
    superseded: int
    unconverged: int
    admission_p50_s: float
    admission_p99_s: float
    exposition: str
    # Encode-residency economics (ISSUE 14): all deterministic
    # counters, safe to compare across runs.
    encode_cold: int = 0
    encode_warm: int = 0
    encode_demotions: dict[str, int] = field(default_factory=dict)
    encode_evictions: dict[str, int] = field(default_factory=dict)
    encode_patch_bytes: int = 0
    encode_patch_rows: int = 0
    decode_full: int = 0
    decode_patch: int = 0
    steps: int = 0
    wall_s: float = 0.0  # host time; NOT part of the replayable account
    # Host wall-clock split of the cycle cost (encode / decode /
    # device / other); like wall_s, NOT part of the replayable account.
    phase_wall: dict[str, float] = field(default_factory=dict)

    def log_text(self) -> str:
        return canonical_fleet_log_text(self.events)


class _TenantLog:
    """Tenant-tagged move observer feeding the shared event log."""

    def __init__(self, log: "_FleetLog", key: str) -> None:
        self._log = log
        self._key = key

    def on_batch(self, node: str, moves: Sequence[Any], ok: bool,
                 now: float) -> None:
        self._log.emit("batch", now, tenant=self._key, node=node,
                       ok=bool(ok),
                       moves=[[m.partition, m.node, m.state, m.op]
                              for m in moves])


class _FleetLog:
    def __init__(self) -> None:
        self.events: list[dict[str, Any]] = []

    def emit(self, kind: str, t: float, **fields: Any) -> None:
        self.events.append({"kind": kind, "t": t, **fields})


def _placements_of(pmap: PartitionMap) -> dict[str, dict[str, list[str]]]:
    return {name: {s: list(ns) for s, ns in p.nodes_by_state.items()}
            for name, p in pmap.items()}


def _map_complete(pmap: PartitionMap, mdl: PartitionModel,
                  live: set[str]) -> bool:
    """Every partition holds its full constraint count per state, all
    placements on live nodes, no duplicates (simulate.py's check)."""
    for p in pmap.values():
        seen: set[str] = set()
        for state, st in mdl.items():
            ns = p.nodes_by_state.get(state, [])
            if len(ns) != st.constraints:
                return False
            for n in ns:
                if n in seen or n not in live:
                    return False
                seen.add(n)
    return True


async def _fleet_main(scn: FleetScenario, loop: DeterministicLoop,
                      rec: Recorder, coalesce: bool,
                      encode_residency: bool = True) -> FleetSimReport:
    log = _FleetLog()
    specs = {t.key: t for t in scn.tenants}
    models = {t.key: tenant_model(t) for t in scn.tenants}
    offsets = {t.key: i for i, t in enumerate(scn.tenants)}

    async def data_plane(stop_ch: Any, node: str, partitions: list[str],
                         states: list[str], ops: list[str]) -> None:
        await asyncio.sleep(
            scn.node_latency_s.get(node, scn.base_latency_s))

    fc = FleetController(
        list(scn.nodes), coalesce=coalesce,
        admission_window_s=scn.admission_window_s,
        fair_share=scn.fair_share,
        carry_bytes=scn.carry_bytes,
        carry_entries=scn.carry_entries,
        inline_solve=True,  # loop-only: the determinism requirement
        debounce_s=scn.debounce_s,
        max_passes_per_cycle=scn.max_passes_per_cycle,
        availability_floor=scn.availability_floor,
        recorder=rec,
        encode_residency=encode_residency)
    await fc.start()

    def onboard(spec: FleetTenant, t0: bool) -> None:
        key = spec.key
        initial = tenant_initial_map(spec, scn.nodes, offsets[key])
        ctl = fc.add_tenant(
            key, models[key], initial, data_plane,
            move_observers=(_TenantLog(log, key),),
            kick=not t0)
        slo = fc.tenant(key).slo

        def on_quiesce(t: float, key: str = key) -> None:
            log.emit("quiesce", t, tenant=key,
                     availability=slo.availability())

        def on_strip(nodes: set[str], t: float, key: str = key) -> None:
            log.emit("strip", t, tenant=key, nodes=sorted(nodes))

        ctl.on_quiesce.append(on_quiesce)
        ctl.on_strip.append(on_strip)
        if not t0:
            log.emit("onboard", loop.time(), tenant=key,
                     partitions=spec.partitions, replicas=spec.replicas)

    log.emit(
        "init", 0.0, scenario=scn.name, seed=scn.seed,
        coalesced=coalesce, horizon_s=scn.horizon_s,
        nodes=list(scn.nodes), floor=scn.availability_floor,
        tenants=[{"key": t.key, "partitions": t.partitions,
                  "replicas": t.replicas, "onboard_t": t.onboard_t}
                 for t in scn.tenants],
        placements={t.key: _placements_of(
            tenant_initial_map(t, scn.nodes, offsets[t.key]))
            for t in scn.tenants if t.onboard_t <= 0})
    for spec in scn.tenants:
        if spec.onboard_t <= 0:
            onboard(spec, t0=True)

    # The merged timeline: staggered onboardings + scripted deltas, in
    # virtual-time order (stable tie-break on kind + label/key).
    timeline: list[tuple[float, int, str, Any]] = []
    for spec in scn.tenants:
        if spec.onboard_t > 0:
            timeline.append((spec.onboard_t, 0, spec.key, spec))
    for ev in scn.events:
        timeline.append((ev.t, 1, ev.label, ev))
    timeline.sort(key=lambda e: (e[0], e[1], e[2]))

    # Driver-side fleet membership (correlated events only), for the
    # end-of-run completeness check.
    dark: set[str] = set()

    for t_ev, kind, _tag, payload in timeline:
        delay = t_ev - loop.time()
        if delay > 0:
            await asyncio.sleep(delay)
        t = rec.now()
        if kind == 0:
            onboard(payload, t0=False)
            continue
        ev = payload
        targets = list(ev.tenants) if ev.tenants else sorted(fc.keys())
        log.emit("delta", t, label=ev.label, outage=ev.outage,
                 tenants=(sorted(ev.tenants) if ev.tenants else ["*"]),
                 add=list(ev.delta.add), remove=list(ev.delta.remove),
                 fail=list(ev.delta.fail),
                 partition_weights=dict(ev.delta.partition_weights or {}),
                 node_weights=dict(ev.delta.node_weights or {}))
        if not ev.tenants:
            dark |= set(ev.delta.remove) | set(ev.delta.fail)
            dark -= set(ev.delta.add)
        for key in targets:
            fc.submit(key, ev.delta)

    remaining = scn.horizon_s - loop.time()
    if remaining > 0:
        await asyncio.sleep(remaining)
    final_maps = await fc.quiesce_all()

    t_end = rec.now()
    live = set(scn.nodes) - dark
    complete = all(
        _map_complete(final_maps[key], models[key], live)
        for key in final_maps)
    summaries = {key: fc.tenant(key).slo.summary(t_end)
                 for key in final_maps}
    fleet_summary = fc.summary()
    cache_stats = fc.service.carry_cache.stats()
    dispatches = int(rec.counters.get("fleet.batches", 0))
    requests = int(rec.counters.get("fleet.requests", 0))
    starved = int(rec.counters.get("fleet.starved_admissions", 0))

    log.emit(
        "end", t_end,
        complete=complete,
        availability={k: summaries[k].availability
                      for k in sorted(summaries)},
        fleet={"tenants": fleet_summary.tenants,
               "availability_min": fleet_summary.availability_min,
               "availability_mean": fleet_summary.availability_mean,
               "tenants_below_floor": fleet_summary.tenants_below_floor,
               "moves_executed": fleet_summary.moves_executed,
               "moves_failed": fleet_summary.moves_failed},
        dispatches=dispatches, plan_requests=requests,
        starved_admissions=starved,
        carry_evictions=dict(cache_stats["evictions"]),  # type: ignore[arg-type]
        cycles=fc.cycles, passes=fc.passes,
        superseded=fc.superseded, unconverged=fc.unconverged_cycles)

    phase_wall = fc.host_phases()
    enc_cache = fc.encode_cache
    await fc.stop()

    lat = sorted(rec.histograms.get("fleet.admission_latency_s", []))
    return FleetSimReport(
        scenario=scn.name, seed=scn.seed, coalesced=coalesce,
        horizon_s=scn.horizon_s, tenants=len(scn.tenants),
        final_maps=final_maps, complete=complete,
        summaries=summaries, fleet=fleet_summary, events=log.events,
        dispatches=dispatches, plan_requests=requests,
        starved_admissions=starved,
        carry_evictions=dict(cache_stats["evictions"]),  # type: ignore[arg-type]
        carry_hits=int(rec.counters.get("plan.solve.carry_hit", 0)),
        cycles=fc.cycles, passes=fc.passes, superseded=fc.superseded,
        unconverged=fc.unconverged_cycles,
        admission_p50_s=(percentile(lat, 50) if lat else 0.0),
        admission_p99_s=(percentile(lat, 99) if lat else 0.0),
        exposition=render_prometheus(rec),
        encode_cold=int(rec.counters.get("fleet.encode_cold", 0)),
        encode_warm=int(rec.counters.get("fleet.encode_warm", 0)),
        encode_demotions=(dict(enc_cache.demotions)
                          if enc_cache is not None else {}),
        encode_evictions=(dict(enc_cache.evictions)
                          if enc_cache is not None else {}),
        encode_patch_bytes=int(
            rec.counters.get("fleet.encode_patch_bytes", 0)),
        encode_patch_rows=int(rec._hist_stats.get(
            "fleet.encode_patch_rows", (0, 0.0))[1]),  # exact sum
        decode_full=int(rec.counters.get("fleet.decode_full", 0)),
        decode_patch=int(rec.counters.get("fleet.decode_patch", 0)),
        phase_wall=phase_wall)


def run_fleet_scenario(scn: FleetScenario,
                       coalesce: bool = True,
                       encode_residency: bool = True) -> FleetSimReport:
    """Run one fleet scenario to completion under the virtual clock.
    Pure function of (scenario, coalesce): same inputs -> byte-identical
    event log, SLO summaries and exposition text; ``wall_s``/``steps``/
    ``phase_wall`` are the only host-dependent fields.
    ``encode_residency=False`` runs the full-re-encode-per-cycle
    baseline — a pure perf toggle: the event log and every replayable
    quantity are byte-identical either way (tests pin this), only the
    host wall-clock and the ``fleet.encode_*`` accounting differ."""
    loop = DeterministicLoop(FifoPolicy(), max_steps=scn.max_steps)
    rec = Recorder(clock=loop.time)
    t0 = perf_now()
    with use_recorder(rec):
        report = loop.run_until_complete(
            _fleet_main(scn, loop, rec, coalesce, encode_residency))
    report.wall_s = perf_now() - t0
    report.steps = loop.steps
    return report
