"""Deterministic schedule exploration for the asyncio control plane.

The orchestrator's fault tests exercise only the interleavings asyncio
happens to pick; a torn invariant under an unlucky schedule would slip
through forever.  This module makes the schedule a *controlled input*:

- :class:`DeterministicLoop` — a minimal event loop that drives real
  ``asyncio.Task``s but owns every scheduling decision.  The ready queue
  is stepped one handle at a time; whenever more than one runnable
  *origin* (task or callback) is ready, a :class:`SchedulePolicy` picks
  which runs next.  Time is virtual: when nothing is runnable the loop
  jumps straight to the earliest timer, so retry backoffs, ``wait_for``
  deadlines and breaker dwell times cost zero wall-clock.
- Policies — :class:`FifoPolicy` (asyncio-like baseline),
  :class:`RandomWalkPolicy` (seeded random walk: same seed, same
  schedule), :class:`PrefixPolicy` (follow a recorded choice prefix,
  FIFO after — the replay/exploration primitive).
- :func:`explore` — bounded-exhaustive enumeration of the choice tree,
  CHESS-style delay bounding: deviating from the FIFO head at a choice
  point costs one unit of ``branch_budget``; with budget ``None`` the
  enumeration is truly exhaustive (small toys), with budget *b* it
  covers every schedule reachable with at most *b* preemptions — the
  empirically race-rich neighborhood — in polynomial schedules.
- DPOR-lite reduction: ready handles are grouped by origin (steps of
  one task are program-ordered; interleaving them with themselves is
  meaningless), so the branch factor is the number of *concurrently
  runnable tasks*, not the raw ready-queue length.
- :class:`Trace` + :func:`save_trace`/:func:`load_trace`/:func:`replay`
  — a violating schedule serializes to JSON and replays exactly, so any
  race the explorer finds becomes a deterministic regression test.

Determinism contract: given a scenario coroutine that is itself
deterministic apart from scheduling (no wall-clock control flow, no
unseeded randomness — the orchestrator's retry jitter is seeded and
``FaultPlan`` is SHA-256-scripted), the pair (scenario, choices) fully
determines execution.  Step *labels* use loop-local task numbering, so
signatures are stable across processes too.
"""

from __future__ import annotations

import asyncio
import hashlib
import heapq
import itertools
import json
import random
from dataclasses import asdict, dataclass
from typing import Any, Callable, Coroutine, Optional

__all__ = [
    "DeadlockError",
    "StepLimitExceeded",
    "ReplayDivergence",
    "InvariantViolation",
    "SchedulePolicy",
    "FifoPolicy",
    "RandomWalkPolicy",
    "PrefixPolicy",
    "DeterministicLoop",
    "ScheduleOutcome",
    "run_controlled",
    "ExploreReport",
    "Violation",
    "explore",
    "Trace",
    "save_trace",
    "load_trace",
    "replay",
]


class DeadlockError(RuntimeError):
    """The main coroutine is not done, but nothing is runnable and no
    timer is pending — a genuine wedge, surfaced instead of hanging."""


class StepLimitExceeded(RuntimeError):
    """The scenario ran more steps than ``max_steps`` — a livelock (or a
    scenario that needs a bigger limit)."""


class ReplayDivergence(RuntimeError):
    """A recorded choice no longer fits the live choice tree (the code
    under test structurally changed since the trace was recorded)."""


class InvariantViolation(AssertionError):
    """A declared scenario invariant failed under the explored schedule."""


# -- scheduling policies -----------------------------------------------------


class SchedulePolicy:
    """Base policy: always run the FIFO head."""

    def choose(self, n_candidates: int) -> int:
        """Pick the index of the next runnable origin among
        ``n_candidates`` (called only when ``n_candidates > 1``)."""
        return 0


class FifoPolicy(SchedulePolicy):
    """asyncio-like baseline: strictly FIFO."""


class RandomWalkPolicy(SchedulePolicy):
    """Seeded random walk over the choice tree: same seed, same walk."""

    def __init__(self, seed: int) -> None:
        self.seed = seed
        self._rng = random.Random(seed)

    def choose(self, n_candidates: int) -> int:
        return self._rng.randrange(n_candidates)


class PrefixPolicy(SchedulePolicy):
    """Follow a recorded choice prefix, then FIFO.  The primitive both
    :func:`explore` (extend a prefix by one deviation) and
    :func:`replay` (full recorded schedule) are built from."""

    def __init__(self, prefix: list[int]) -> None:
        self.prefix = list(prefix)
        self._i = 0

    def choose(self, n_candidates: int) -> int:
        if self._i < len(self.prefix):
            c = self.prefix[self._i]
            self._i += 1
            if not 0 <= c < n_candidates:
                raise ReplayDivergence(
                    f"recorded choice #{self._i} = {c} but only "
                    f"{n_candidates} origins are runnable — the code "
                    f"under test changed shape since this trace was "
                    f"recorded")
            return c
        return 0


# -- the controlled loop -----------------------------------------------------


def _handle_origin(handle: Any) -> tuple[object, str]:
    """(grouping key, stable label) for one ready handle.

    Steps of the same task share an origin (they are program-ordered —
    scheduling them against each other is not a real interleaving, the
    DPOR-lite reduction).  Labels avoid ids/addresses so schedule
    signatures are stable across processes.
    """
    cb = getattr(handle, "_callback", None)
    owner = getattr(cb, "__self__", None)
    if isinstance(owner, asyncio.Task):
        return owner, owner.get_name()
    if owner is not None:
        return owner, type(owner).__name__
    name = getattr(cb, "__qualname__", None)
    return (cb if cb is not None else handle), (name or "callback")


class DeterministicLoop(asyncio.AbstractEventLoop):
    """A minimal, fully deterministic event loop for real asyncio code.

    Implements exactly the surface the control plane (tasks, futures,
    ``asyncio.wait``/``wait_for``/``sleep``/``Event``, ``csp.Chan``)
    needs: ``call_soon``/``call_later``/``call_at`` feed a ready list +
    virtual-time timer heap, and :meth:`run_until_complete` steps one
    handle at a time, asking the policy whenever >1 origin is runnable.
    Everything AbstractEventLoop declares beyond that raises
    ``NotImplementedError``, which is the point: a scenario that needs
    threads, signals or sockets is not a scenario this explorer can make
    deterministic.
    """

    def __init__(self, policy: Optional[SchedulePolicy] = None,
                 max_steps: int = 200_000) -> None:
        self._policy = policy or FifoPolicy()
        self._ready: list[Any] = []
        self._timers: list[tuple[float, int, Any]] = []
        self._vtime = 0.0
        self._seq = itertools.count()
        self._task_seq = itertools.count()
        self._max_steps = max_steps
        self._running = False
        self.steps = 0
        # One entry per CHOICE POINT (>1 runnable origin):
        self.choices: list[int] = []
        self.candidate_counts: list[int] = []
        # One label per executed step, for schedule signatures:
        self.step_log: list[str] = []
        self.unhandled: list[dict[str, Any]] = []

    # -- asyncio loop API (the subset tasks/futures/timeouts use) ----------

    def get_debug(self) -> bool:
        return False

    def is_running(self) -> bool:
        return self._running

    def is_closed(self) -> bool:
        return False

    def close(self) -> None:  # nothing to release; tests reuse loops
        return None

    def time(self) -> float:
        return self._vtime

    def call_soon(self, callback: Callable[..., object], *args: Any,
                  context: Any = None) -> asyncio.Handle:
        h = asyncio.Handle(callback, args, self, context=context)
        self._ready.append(h)
        return h

    def call_later(self, delay: float, callback: Callable[..., object],
                   *args: Any, context: Any = None) -> asyncio.TimerHandle:
        return self.call_at(self._vtime + max(delay, 0.0), callback,
                            *args, context=context)

    def call_at(self, when: float, callback: Callable[..., object],
                *args: Any, context: Any = None) -> asyncio.TimerHandle:
        th = asyncio.TimerHandle(when, callback, args, self, context=context)
        heapq.heappush(self._timers, (when, next(self._seq), th))
        setattr(th, "_scheduled", True)
        return th

    def _timer_handle_cancelled(self, handle: asyncio.TimerHandle) -> None:
        # Cancelled timers stay heap-resident and are skipped when due.
        return None

    def create_future(self) -> "asyncio.Future[Any]":
        return asyncio.Future(loop=self)

    def create_task(self, coro: Coroutine[Any, Any, Any], *,
                    name: Optional[str] = None,
                    context: Any = None) -> "asyncio.Task[Any]":
        # Loop-local deterministic naming: asyncio's default Task-N
        # counter is process-global, which would make step labels (and
        # thus schedule signatures) depend on unrelated earlier tests.
        if name is None:
            name = f"task-{next(self._task_seq)}"
        return asyncio.Task(coro, loop=self, name=name)

    def call_exception_handler(self, context: dict[str, Any]) -> None:
        self.unhandled.append(context)

    # -- deterministic stepping --------------------------------------------

    def _runnable(self) -> list[Any]:
        if any(h.cancelled() for h in self._ready):
            self._ready = [h for h in self._ready if not h.cancelled()]
        return self._ready

    def _candidates(self) -> list[int]:
        """Indices into _ready: the FIRST handle of each distinct origin,
        in FIFO order (the DPOR-lite grouping)."""
        seen: set[int] = set()
        out: list[int] = []
        for i, h in enumerate(self._ready):
            key = id(_handle_origin(h)[0])
            if key in seen:
                continue
            seen.add(key)
            out.append(i)
        return out

    def run_until_complete(self, future: Coroutine[Any, Any, Any]) -> Any:
        main = self.create_task(future, name="main")
        asyncio.events._set_running_loop(self)
        self._running = True
        try:
            while not main.done():
                if not self._runnable():
                    if not self._timers:
                        # Surface the wedge with the frontier visible.
                        raise DeadlockError(
                            f"deadlock after {self.steps} steps at "
                            f"t={self._vtime:.6f}: main not done, no "
                            f"runnable callbacks, no pending timers")
                    when = self._timers[0][0]
                    self._vtime = max(self._vtime, when)
                    while self._timers and self._timers[0][0] <= self._vtime:
                        _, _, th = heapq.heappop(self._timers)
                        if not th.cancelled():
                            self._ready.append(th)
                    continue
                cands = self._candidates()
                if len(cands) > 1:
                    pick = self._policy.choose(len(cands))
                    self.choices.append(pick)
                    self.candidate_counts.append(len(cands))
                else:
                    pick = 0
                handle = self._ready.pop(cands[pick])
                self.steps += 1
                if self.steps > self._max_steps:
                    raise StepLimitExceeded(
                        f"exceeded {self._max_steps} steps — livelock, "
                        f"or raise max_steps for this scenario")
                self.step_log.append(_handle_origin(handle)[1])
                handle._run()
        finally:
            try:
                self._drain_pending()
            finally:
                self._running = False
                asyncio.events._set_running_loop(None)
        return main.result()

    def _drain_pending(self) -> None:
        """Cancel every task the run left behind (a violating or
        deadlocked schedule abandons its orchestration mid-flight) and
        step their cancellation unwinding to completion, FIFO and
        unlogged, so abandoned coroutines do not surface as
        'never awaited' GC warnings in the host process."""
        pending = [t for t in asyncio.all_tasks(self) if not t.done()]
        for t in pending:
            t.cancel()
        budget = 10_000
        while any(not t.done() for t in pending) and budget > 0:
            if not self._runnable():
                if not self._timers:
                    break
                when = self._timers[0][0]
                self._vtime = max(self._vtime, when)
                while self._timers and self._timers[0][0] <= self._vtime:
                    _, _, th = heapq.heappop(self._timers)
                    if not th.cancelled():
                        self._ready.append(th)
                continue
            budget -= 1
            self._ready.pop(0)._run()
        for t in pending:
            if t.done() and not t.cancelled():
                t.exception()  # mark retrieved


# -- one controlled run ------------------------------------------------------


@dataclass
class ScheduleOutcome:
    """Everything one controlled run produced."""

    ok: bool
    result: Any
    error: Optional[BaseException]
    deadlock: bool
    choices: list[int]
    candidate_counts: list[int]
    steps: int
    signature: str

    def describe(self) -> str:
        if self.ok:
            return f"ok ({self.steps} steps, {len(self.choices)} choices)"
        kind = "deadlock" if self.deadlock else type(self.error).__name__
        return f"{kind}: {self.error} (choices={self.choices})"


def _signature(step_log: list[str]) -> str:
    return hashlib.sha256("\n".join(step_log).encode()).hexdigest()[:16]


def run_controlled(
    factory: Callable[[], Coroutine[Any, Any, Any]],
    policy: Optional[SchedulePolicy] = None,
    max_steps: int = 200_000,
) -> ScheduleOutcome:
    """Run one scenario coroutine under one schedule.

    ``factory`` must build a FRESH coroutine (and fresh orchestrator /
    channels / state) per call — exploration runs it many times.
    Scenario failures (any exception out of the coroutine, including
    :class:`InvariantViolation`), deadlocks and step-limit breaches all
    land in the outcome instead of raising, so exploration drivers can
    keep going.  :class:`ReplayDivergence` propagates: a stale trace is
    a test-maintenance signal, not a race.
    """
    loop = DeterministicLoop(policy, max_steps=max_steps)
    result: Any = None
    error: Optional[BaseException] = None
    deadlock = False
    try:
        result = loop.run_until_complete(factory())
    except ReplayDivergence:
        raise
    except DeadlockError as e:
        error, deadlock = e, True
    except StepLimitExceeded as e:
        error = e
    except Exception as e:  # scenario invariant/assert failures
        # KeyboardInterrupt/SystemExit deliberately propagate: an
        # operator interrupting a long explore() must stop the whole
        # enumeration, not mint a bogus per-schedule violation.
        error = e
    return ScheduleOutcome(
        ok=error is None,
        result=result,
        error=error,
        deadlock=deadlock,
        choices=list(loop.choices),
        candidate_counts=list(loop.candidate_counts),
        steps=loop.steps,
        signature=_signature(loop.step_log),
    )


# -- bounded-exhaustive exploration ------------------------------------------


@dataclass
class Violation:
    """One schedule that broke the scenario, replayable via its choices."""

    choices: list[int]
    candidate_counts: list[int]
    error: str
    error_type: str
    deadlock: bool
    signature: str

    def to_trace(self, scenario: str, note: str = "") -> "Trace":
        return Trace(scenario=scenario, choices=list(self.choices),
                     candidate_counts=list(self.candidate_counts),
                     note=note or f"{self.error_type}: {self.error}")


@dataclass
class ExploreReport:
    """What :func:`explore` covered and what it found."""

    schedules: int
    violations: list[Violation]
    complete: bool  # the frontier drained (within the branch budget)
    capped: bool  # stopped early on max_schedules
    branch_budget: Optional[int]

    def summary(self) -> str:
        cov = ("exhaustive" if self.branch_budget is None
               else f"budget={self.branch_budget}")
        state = "complete" if self.complete else "CAPPED"
        return (f"{self.schedules} schedules ({cov}, {state}), "
                f"{len(self.violations)} violating")


def explore(
    factory: Callable[[], Coroutine[Any, Any, Any]],
    branch_budget: Optional[int] = 2,
    max_schedules: int = 5000,
    max_steps: int = 200_000,
    stop_on_first: bool = False,
) -> ExploreReport:
    """Enumerate schedules depth-first over the choice tree.

    Deviating from the FIFO head (choice != 0) at a choice point spends
    one unit of ``branch_budget`` (CHESS-style delay bounding); FIFO
    choices are free.  ``branch_budget=None`` removes the bound — a true
    exhaustive enumeration, feasible only for small toys.  Every run's
    un-deviated suffix seeds new prefixes, so the tree is covered
    without revisiting a schedule (each prefix is a distinct schedule).
    """
    stack: list[list[int]] = [[]]
    violations: list[Violation] = []
    runs = 0
    while stack:
        if runs >= max_schedules:
            return ExploreReport(schedules=runs, violations=violations,
                                 complete=False, capped=True,
                                 branch_budget=branch_budget)
        prefix = stack.pop()
        out = run_controlled(factory, PrefixPolicy(prefix),
                             max_steps=max_steps)
        runs += 1
        if not out.ok:
            err = out.error
            violations.append(Violation(
                choices=out.choices,
                candidate_counts=out.candidate_counts,
                error=str(err),
                error_type=type(err).__name__ if err else "",
                deadlock=out.deadlock,
                signature=out.signature,
            ))
            if stop_on_first:
                return ExploreReport(
                    schedules=runs, violations=violations, complete=False,
                    capped=False, branch_budget=branch_budget)
        spent = sum(1 for c in prefix if c != 0)
        if branch_budget is not None and spent >= branch_budget:
            continue
        # Each choice point past the prefix ran FIFO (0); branch into
        # every deviation.  LIFO order = depth-first.
        for j in range(len(prefix), len(out.candidate_counts)):
            for k in range(1, out.candidate_counts[j]):
                stack.append(out.choices[:j] + [k])
    return ExploreReport(schedules=runs, violations=violations,
                         complete=True, capped=False,
                         branch_budget=branch_budget)


# -- trace files -------------------------------------------------------------

TRACE_VERSION = 1


@dataclass
class Trace:
    """A serialized schedule: enough to replay one run exactly."""

    scenario: str
    choices: list[int]
    candidate_counts: list[int]
    note: str = ""
    seed: Optional[int] = None
    version: int = TRACE_VERSION


def save_trace(trace: Trace, path: str) -> None:
    with open(path, "w") as f:
        json.dump(asdict(trace), f, indent=2, sort_keys=True)
        f.write("\n")


def load_trace(path: str) -> Trace:
    with open(path) as f:
        data = json.load(f)
    known = {"scenario", "choices", "candidate_counts", "note", "seed",
             "version"}
    unknown = set(data) - known
    if unknown:
        raise ValueError(f"{path}: unknown trace keys {sorted(unknown)}")
    if data.get("version") != TRACE_VERSION:
        raise ValueError(
            f"{path}: trace version {data.get('version')!r} != "
            f"{TRACE_VERSION} (regenerate with the current explorer)")
    return Trace(
        scenario=str(data["scenario"]),
        choices=[int(c) for c in data["choices"]],
        candidate_counts=[int(c) for c in data["candidate_counts"]],
        note=str(data.get("note", "")),
        seed=data.get("seed"),
    )


def replay(
    factory: Callable[[], Coroutine[Any, Any, Any]],
    trace: Trace,
    max_steps: int = 200_000,
    strict: bool = True,
) -> ScheduleOutcome:
    """Re-run a scenario under a recorded schedule.

    With ``strict`` (the default for committed regression traces), the
    live choice tree must still match the recorded candidate counts for
    the replayed prefix — a mismatch means the control plane changed
    shape and the trace needs regenerating, which should be a loud
    signal, not a silently different schedule.
    """
    out = run_controlled(factory, PrefixPolicy(trace.choices),
                         max_steps=max_steps)
    if strict:
        n = len(trace.candidate_counts)
        live = out.candidate_counts[:n]
        if live != trace.candidate_counts:
            raise ReplayDivergence(
                f"trace for scenario {trace.scenario!r} no longer fits: "
                f"recorded candidate counts {trace.candidate_counts} vs "
                f"live {live} — regenerate the trace")
    return out
