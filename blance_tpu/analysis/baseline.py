"""Accepted-findings allowlist (``analysis/baseline.toml``).

The gate's contract: every finding is either FIXED or explicitly pinned
here with a reason, and any finding not pinned fails the build.  Entries
match on (rule, path[, symbol][, line]) — symbol-based matching survives
unrelated line drift; pin ``line`` only to split two findings of the same
rule inside one function.

The file is TOML.  On Python >= 3.11 (including the 3.12 CI images) it is
parsed with stdlib ``tomllib``; the tiny subset reader below is the
3.10 fallback only (``requires-python = ">=3.10"``, and the analysis
suite must not grow a pip dependency for its own config).  The subset:
``[[finding]]`` array tables, ``key = "string"`` / ``key = integer``
pairs, comments, blank lines.  Either way, validation (required keys,
unknown keys) is shared and strict — a config typo must fail the build,
not silently accept findings.

Format::

    [[finding]]
    rule = "ASY104"
    path = "blance_tpu/orchestrate/orchestrator.py"
    symbol = "Orchestrator._call_assign"
    reason = "legacy no-deadline mode awaits the app callback ..."
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Optional

__all__ = ["Baseline", "BaselineEntry", "parse_toml_findings"]


@dataclass
class BaselineEntry:
    rule: str
    path: str
    reason: str
    symbol: Optional[str] = None
    line: Optional[int] = None
    used: bool = field(default=False, compare=False)

    def matches(self, finding) -> bool:
        if self.rule != finding.rule or self.path != finding.path:
            return False
        if self.symbol is not None and self.symbol != finding.symbol:
            return False
        if self.line is not None and self.line != finding.line:
            return False
        return True

    def render(self) -> str:
        bits = [self.rule, self.path]
        if self.symbol:
            bits.append(self.symbol)
        if self.line is not None:
            bits.append(f"line {self.line}")
        return " ".join(bits)


def _parse_value(raw: str, path: str, lineno: int):
    raw = raw.strip()
    if raw.startswith('"') and raw.endswith('"') and len(raw) >= 2:
        body = raw[1:-1]
        # The subset supports the escapes a reason string plausibly needs.
        for esc, ch in (('\\"', '"'), ("\\\\", "\\"), ("\\n", "\n"),
                        ("\\t", "\t")):
            body = body.replace(esc, ch)
        return body
    if raw.lstrip("-").isdigit():
        return int(raw)
    raise ValueError(
        f"{path}:{lineno}: unsupported TOML value {raw!r} (the baseline "
        f"subset accepts double-quoted strings and integers only)")


def parse_toml_findings(text: str,
                        path: str = "<baseline>"
                        ) -> list["BaselineEntry"]:
    """Parse the ``[[finding]]`` array tables out of a TOML document:
    stdlib ``tomllib`` where available, the subset reader on 3.10."""
    try:
        import tomllib
    except ImportError:
        return _parse_subset(text, path)
    try:
        data = tomllib.loads(text)
    except tomllib.TOMLDecodeError as e:
        raise ValueError(f"{path}: invalid TOML: {e}") from e
    unknown_tables = set(data) - {"finding"}
    if unknown_tables:
        raise ValueError(
            f"{path}: unsupported top-level keys {sorted(unknown_tables)} "
            f"(only [[finding]] arrays are recognized)")
    findings = data.get("finding", [])
    if not isinstance(findings, list) or \
            not all(isinstance(e, dict) for e in findings):
        raise ValueError(f"{path}: 'finding' must be an array of tables")
    return _entries_from_dicts(findings, path)


def _parse_subset(text: str, path: str) -> list["BaselineEntry"]:
    """The dependency-free 3.10 fallback parser."""
    entries: list[dict[str, object]] = []
    current: Optional[dict[str, object]] = None
    for lineno, raw_line in enumerate(text.splitlines(), start=1):
        line = raw_line.strip()
        if not line or line.startswith("#"):
            continue
        if line == "[[finding]]":
            current = {}
            entries.append(current)
            continue
        if line.startswith("["):
            raise ValueError(
                f"{path}:{lineno}: unsupported table {line!r} (only "
                f"[[finding]] arrays are recognized)")
        if "=" not in line:
            raise ValueError(f"{path}:{lineno}: expected key = value, "
                             f"got {line!r}")
        if current is None:
            raise ValueError(
                f"{path}:{lineno}: key outside a [[finding]] table")
        key, _, value = line.partition("=")
        key = key.strip()
        # Strip a trailing comment from unquoted values; quoted strings
        # may contain '#' so only trim after the closing quote.
        value = value.strip()
        if not value.startswith('"') and "#" in value:
            value = value.split("#", 1)[0].strip()
        elif value.startswith('"'):
            end = value.rfind('"')
            trailer = value[end + 1:].strip()
            if trailer and not trailer.startswith("#"):
                raise ValueError(
                    f"{path}:{lineno}: trailing junk after string value")
            value = value[:end + 1]
        current[key] = _parse_value(value, path, lineno)
    return _entries_from_dicts(entries, path)


def _entries_from_dicts(entries: list[dict[str, object]],
                        path: str) -> list["BaselineEntry"]:
    """Shared strict validation — both parse paths come through here."""
    out = []
    for i, e in enumerate(entries):
        for req in ("rule", "path", "reason"):
            if req not in e:
                raise ValueError(
                    f"{path}: [[finding]] #{i + 1} is missing required "
                    f"key {req!r} (every accepted finding needs a reason)")
        unknown = set(e) - {"rule", "path", "reason", "symbol", "line"}
        if unknown:
            raise ValueError(
                f"{path}: [[finding]] #{i + 1} has unknown keys "
                f"{sorted(unknown)}")
        out.append(BaselineEntry(
            rule=str(e["rule"]), path=str(e["path"]),
            reason=str(e["reason"]),
            symbol=(str(e["symbol"]) if "symbol" in e else None),
            line=(int(e["line"]) if "line" in e else None)))
    return out


class Baseline:
    """The loaded allowlist; splits findings into new vs accepted."""

    def __init__(self, entries: list["BaselineEntry"]) -> None:
        self.entries = entries

    @classmethod
    def load(cls, path: Optional[str]) -> "Baseline":
        if path is None or not os.path.exists(path):
            return cls([])
        with open(path) as f:
            return cls(parse_toml_findings(f.read(), path))

    def split(
        self, findings: list[Any],
    ) -> tuple[list[Any], list[tuple[Any, str]]]:
        """-> (new_findings, [(finding, reason), ...])."""
        new, accepted = [], []
        for f in findings:
            entry = next((e for e in self.entries if e.matches(f)), None)
            if entry is None:
                new.append(f)
            else:
                entry.used = True
                accepted.append((f, entry.reason))
        return new, accepted

    def unused(self) -> list["BaselineEntry"]:
        """Entries that matched nothing — stale pins that must be
        deleted in the same change that fixed their finding.  The CLI
        surfaces them as warnings in the editor loop and as HARD ERRORS
        under ``--ci`` (__main__.py): dead suppressions otherwise
        accumulate and mask the next real finding that happens to match
        them."""
        return [e for e in self.entries if not e.used]
