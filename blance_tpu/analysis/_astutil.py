"""AST helpers shared by the analysis passes.

One copy of the dotted-path resolver, the per-file Finding emitter and —
since the determinism pass (PR 19) joined jit-purity in needing a
cross-module call graph — the whole-program :class:`ModuleIndex`:
module/function indexing, import and re-export resolution, and the
reachability walk.  Two diverging copies of the import resolver is how a
relative-import fix silently misses a pass.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Optional

from . import Finding

__all__ = ["dotted", "FindingEmitter", "FuncInfo", "ModuleInfo",
           "ModuleIndex", "module_name"]


def dotted(node: ast.AST) -> Optional[str]:
    """Attribute/Name chain -> "a.b.c", else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class FindingEmitter:
    """Collects findings for one file, anchored to its repo-relative
    forward-slash path."""

    def __init__(self, path: str, repo_root: str) -> None:
        self.rel = os.path.relpath(
            os.path.abspath(path), repo_root).replace(os.sep, "/")
        self.findings: list[Finding] = []

    def emit(self, rule: str, line: int, symbol: str, message: str) -> None:
        self.findings.append(Finding(
            rule=rule, path=self.rel, line=line, symbol=symbol,
            message=message))


@dataclass
class FuncInfo:
    module: str  # dotted module name
    qualname: str  # "fn" or "Class.method"
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    path: str  # repo-relative file path
    params: list[str] = field(default_factory=list)
    # Params with literal defaults: when such a function becomes a trace
    # root through shard_map/partial wrapping (no static_argnames to
    # consult), branching on them is almost always the benign
    # Python-default pattern — exempt from JIT002/JIT003.
    defaulted: set[str] = field(default_factory=set)
    is_root: bool = False
    statics: set[str] = field(default_factory=set)  # declared static argnames

    @property
    def fq(self) -> str:
        return f"{self.module}.{self.qualname}"


@dataclass
class ModuleInfo:
    name: str  # dotted
    path: str  # repo-relative
    tree: ast.Module
    is_pkg: bool = False  # an __init__.py (relative imports resolve
    # against the package itself, not its parent)
    imports: dict[str, str] = field(default_factory=dict)
    functions: dict[str, "FuncInfo"] = field(default_factory=dict)
    constants: dict[str, object] = field(default_factory=dict)


def module_name(path: str, repo_root: str) -> str:
    rel = os.path.relpath(os.path.abspath(path), repo_root)
    rel = rel[:-3] if rel.endswith(".py") else rel
    parts = rel.replace(os.sep, "/").split("/")
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


class ModuleIndex:
    """Whole-program module/function index with import resolution and a
    reachability walk.  Files that do not parse land in
    :attr:`parse_errors` for the owning pass to report under its own
    rule code."""

    def __init__(self, files: list[str], repo_root: str) -> None:
        self.repo_root = repo_root
        self.modules: dict[str, ModuleInfo] = {}
        # (repo-relative path, line, message) per unparseable file.
        self.parse_errors: list[tuple[str, int, str]] = []
        for path in files:
            rel = os.path.relpath(
                os.path.abspath(path), repo_root).replace(os.sep, "/")
            try:
                with open(path) as f:
                    src = f.read()
                tree = ast.parse(src, filename=path)
            except SyntaxError as e:
                self.parse_errors.append((rel, e.lineno or 0, e.msg or ""))
                continue
            mi = ModuleInfo(name=module_name(path, repo_root), path=rel,
                            tree=tree, is_pkg=rel.endswith("__init__.py"))
            self._index_module(mi)
            self.modules[mi.name] = mi

    # -- indexing -----------------------------------------------------------

    def _index_module(self, mi: ModuleInfo) -> None:
        for node in mi.tree.body:
            self._index_stmt(mi, node, prefix="")

    def _index_stmt(self, mi: ModuleInfo, node: ast.stmt,
                    prefix: str) -> None:
        if isinstance(node, ast.Import):
            for alias in node.names:
                mi.imports[alias.asname or alias.name.split(".")[0]] = \
                    alias.name if alias.asname else \
                    alias.name.split(".")[0]
                if alias.asname:
                    mi.imports[alias.asname] = alias.name
        elif isinstance(node, ast.ImportFrom):
            base = self._resolve_from(mi, node)
            for alias in node.names:
                if alias.name == "*":
                    continue
                mi.imports[alias.asname or alias.name] = \
                    f"{base}.{alias.name}" if base else alias.name
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            qn = f"{prefix}{node.name}"
            args = node.args
            params = ([a.arg for a in args.posonlyargs]
                      + [a.arg for a in args.args]
                      + [a.arg for a in args.kwonlyargs])
            if args.vararg:
                params.append(args.vararg.arg)
            if args.kwarg:
                params.append(args.kwarg.arg)
            defaulted: set[str] = set()
            pos = [a.arg for a in args.posonlyargs] + \
                [a.arg for a in args.args]
            for name_, default in zip(pos[len(pos) - len(args.defaults):],
                                      args.defaults):
                if isinstance(default, ast.Constant):
                    defaulted.add(name_)
            for a, default in zip(args.kwonlyargs, args.kw_defaults):
                if isinstance(default, ast.Constant):
                    defaulted.add(a.arg)
            mi.functions[qn] = FuncInfo(
                module=mi.name, qualname=qn, node=node, path=mi.path,
                params=params, defaulted=defaulted)
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                self._index_stmt(mi, sub, prefix=f"{node.name}.")
        elif isinstance(node, ast.Assign) and not prefix:
            # Module-level literal constants (for static_argnames=NAME).
            if len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name):
                try:
                    mi.constants[node.targets[0].id] = \
                        ast.literal_eval(node.value)
                except (ValueError, SyntaxError):
                    pass

    def _resolve_from(self, mi: ModuleInfo, node: ast.ImportFrom) -> str:
        if not node.level:
            return node.module or ""
        parts = mi.name.split(".")
        # level=1 is the CURRENT package: for a module that is its
        # parent (drop the module's own name); for an __init__.py the
        # module name IS the package.  Each extra level pops one more.
        base = parts if mi.is_pkg else parts[:-1]
        extra = node.level - 1
        base = base[:len(base) - extra] if extra else base
        if node.module:
            base = base + node.module.split(".")
        return ".".join(base)

    # -- symbol resolution --------------------------------------------------

    def resolve(self, mi: ModuleInfo, dotted_ref: str) -> str:
        """Map a dotted local reference to its fully-qualified spelling."""
        head, _, rest = dotted_ref.partition(".")
        fq_head = mi.imports.get(head, head)
        return f"{fq_head}.{rest}" if rest else fq_head

    def lookup_function(self, mi: ModuleInfo,
                        dotted_ref: str) -> Optional[FuncInfo]:
        """Resolve a reference to a FuncInfo in the analyzed set."""
        # Same-module bare name (incl. Class.method chains).
        if dotted_ref in mi.functions:
            return mi.functions[dotted_ref]
        return self.lookup_fq(self.resolve(mi, dotted_ref))

    def lookup_fq(self, fq: str, depth: int = 0) -> Optional[FuncInfo]:
        """Find a FuncInfo by fully-qualified name, chasing package
        re-exports: ``pkg.helper`` where pkg/__init__.py does ``from
        .impl import helper`` resolves to ``pkg.impl.helper`` — the
        idiom this codebase uses for its public surfaces, which the
        call graph must see through (depth-bounded: a re-export cycle
        must not hang the lint)."""
        if depth > 8:
            return None
        # fq = "pkg.module.func" or "pkg.module.Class.func".
        parts = fq.split(".")
        for cut in range(len(parts) - 1, 0, -1):
            mod = ".".join(parts[:cut])
            rest = ".".join(parts[cut:])
            target = self.modules.get(mod)
            if target is None:
                continue
            if rest in target.functions:
                return target.functions[rest]
            # Re-export chase: the symbol's head may be imported into
            # ``mod`` from somewhere else in the analyzed set.
            head, _, tail = rest.partition(".")
            if head in target.imports:
                re_fq = target.imports[head] + ("." + tail if tail else "")
                found = self.lookup_fq(re_fq, depth + 1)
                if found is not None:
                    return found
        return None

    def partial_target(self, mi: ModuleInfo,
                       call: ast.Call) -> Optional[FuncInfo]:
        """partial(f, ...) -> FuncInfo for f (one level)."""
        ref = dotted(call.func)
        if ref is None:
            return None
        if self.resolve(mi, ref) != "functools.partial":
            return None
        if not call.args:
            return None
        inner = dotted(call.args[0])
        if inner is None:
            return None
        return self.lookup_function(mi, inner)

    # -- reachability -------------------------------------------------------

    def reachable(self, roots: list[FuncInfo], *,
                  self_edges: bool = False) -> list[FuncInfo]:
        """BFS over call / function-reference edges from ``roots``.

        Edges: direct calls (dotted references, resolved through
        imports and re-exports), one level of ``partial(f, ...)``, and
        bare-name function references (callback registration).  With
        ``self_edges=True`` a ``self.method(...)`` call also reaches
        ``Class.method`` in the same module — the determinism pass
        needs method-level flow the jit graph deliberately skips
        (trace roots are free functions)."""
        seen = {fn.fq for fn in roots}
        queue = list(roots)
        while queue:
            fn = queue.pop()
            mi = self.modules[fn.module]
            for node in ast.walk(fn.node):
                ref = None
                if isinstance(node, ast.Call):
                    ref = dotted(node.func)
                    inner = self.partial_target(mi, node) \
                        if ref and self.resolve(mi, ref) == \
                        "functools.partial" else None
                    if inner is not None and inner.fq not in seen:
                        seen.add(inner.fq)
                        queue.append(inner)
                elif isinstance(node, ast.Name) and \
                        isinstance(node.ctx, ast.Load):
                    ref = node.id
                if ref is None:
                    continue
                callee = self.lookup_function(mi, ref)
                if callee is None and self_edges and \
                        ref.startswith("self.") and "." in fn.qualname:
                    cls = fn.qualname.split(".")[0]
                    callee = mi.functions.get(
                        f"{cls}.{ref[len('self.'):]}")
                if callee is not None and callee.fq not in seen:
                    seen.add(callee.fq)
                    queue.append(callee)
        return [self.by_fq(fq) for fq in sorted(seen)]

    def by_fq(self, fq: str) -> FuncInfo:
        for mi in self.modules.values():
            for fn in mi.functions.values():
                if fn.fq == fq:
                    return fn
        raise KeyError(fq)
