"""AST helpers shared by the analysis passes.

One copy of the dotted-path resolver and the per-file Finding emitter:
jit_purity, asyncio_lint and race_lint all resolve attribute chains and
anchor findings to repo-relative paths, and three diverging copies is
how a path-normalization fix silently misses a pass.
"""

from __future__ import annotations

import ast
import os
from typing import Optional

from . import Finding

__all__ = ["dotted", "FindingEmitter"]


def dotted(node: ast.AST) -> Optional[str]:
    """Attribute/Name chain -> "a.b.c", else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class FindingEmitter:
    """Collects findings for one file, anchored to its repo-relative
    forward-slash path."""

    def __init__(self, path: str, repo_root: str) -> None:
        self.rel = os.path.relpath(
            os.path.abspath(path), repo_root).replace(os.sep, "/")
        self.findings: list[Finding] = []

    def emit(self, rule: str, line: int, symbol: str, message: str) -> None:
        self.findings.append(Finding(
            rule=rule, path=self.rel, line=line, symbol=symbol,
            message=message))
