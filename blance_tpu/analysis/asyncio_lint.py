"""asyncio-safety lint: the orchestrator's cancellation contracts.

The control plane (orchestrate/) is cooperative asyncio with Go-style
channels; its failure modes are quiet ones — a fire-and-forget task whose
exception nobody ever retrieves, a blocking call that stalls the whole
loop, a broad ``except`` that eats the very error that explained the
wedge, an un-deadlined await of app code (the cancelled-waiter bug class
the fault-tolerance work hardened csp.Chan against).  Rules:

- ASY101: fire-and-forget ``asyncio.ensure_future(...)`` /
  ``create_task(...)`` whose result is neither awaited, stored, nor
  passed on.  A dropped Task reference can be garbage-collected mid-run
  and its exception is never retrieved.
- ASY102: blocking host calls inside ``async def`` — ``time.sleep``,
  ``subprocess.*``, ``os.system``, ``socket.create_connection``,
  ``urllib.request.*``.  One blocking call stalls every mover on the
  loop.
- ASY103: silent broad exception swallow — an ``except Exception`` /
  ``except BaseException`` / bare ``except`` handler whose body neither
  re-raises, uses the caught exception, nor logs, just
  pass/return/continue or a constant assignment.  On pre-3.8-style
  asyncio paths (and for ``BaseException`` always) this also swallows
  ``CancelledError``; everywhere it buries the evidence.  Applies
  package-wide (sync code swallows just as silently).
- ASY104: ``await`` of an app-supplied callback result without an
  enclosing ``asyncio.wait_for`` deadline.  App code the orchestrator
  does not control must not be awaited open-endedly on a path that has
  no cancellation story.  Callback sources are recognized by attribute
  name (``_assign_partitions`` and friends — see _CALLBACK_ATTRS).

ASY101/102/104 only apply under ``async def``; ASY103 is package-wide.
"""

from __future__ import annotations

import ast
from typing import Optional

from . import Finding
from ._astutil import FindingEmitter as _FileLint, dotted as _dotted

__all__ = ["lint_file", "lint_source"]

_SPAWN_CALLS = {"ensure_future", "create_task"}

# Dotted-suffix blocklist for ASY102.
_BLOCKING = {
    "time.sleep": "blocks the event loop; use asyncio.sleep",
    "os.system": "blocks the event loop; use asyncio.create_subprocess_*",
    "subprocess.run": "blocks the event loop",
    "subprocess.call": "blocks the event loop",
    "subprocess.check_call": "blocks the event loop",
    "subprocess.check_output": "blocks the event loop",
    "socket.create_connection": "blocking connect on the event loop",
    "urllib.request.urlopen": "blocking I/O on the event loop",
    "requests.get": "blocking I/O on the event loop",
    "requests.post": "blocking I/O on the event loop",
}

# Attribute names that hold app-supplied callbacks (ASY104).  The
# orchestrator's data plane is exactly one attribute today; the list is
# the rule's configuration surface.
_CALLBACK_ATTRS = {"_assign_partitions", "assign_partitions"}


def _is_spawn_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    d = _dotted(node.func)
    if d is None:
        return False
    leaf = d.split(".")[-1]
    return leaf in _SPAWN_CALLS


class _AsyncRules(ast.NodeVisitor):
    """ASY101/102/104 inside one async function body."""

    def __init__(self, lint: "_FileLint", func: ast.AsyncFunctionDef,
                 qualname: str) -> None:
        self.lint = lint
        self.func = func
        self.qualname = qualname
        # Names holding values produced by a callback attribute call:
        # result = self._assign_partitions(...)
        self.callback_values: set[str] = set()

    def run(self) -> None:
        for stmt in self.func.body:
            self._visit_stmt(stmt)

    # Walk statements manually so nested function defs don't leak in.
    def _visit_stmt(self, node: ast.stmt) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return  # nested defs are linted as their own functions
        if isinstance(node, ast.Expr):
            self._check_expr_stmt(node)
        if isinstance(node, ast.Assign):
            self._track_callback_assign(node)
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.stmt):
                self._visit_stmt(child)
            else:
                self._visit_expr_tree(child)

    def _visit_expr_tree(self, node: ast.AST) -> None:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                self._check_blocking(sub)
            elif isinstance(sub, ast.Await):
                self._check_await(sub)

    def _check_expr_stmt(self, node: ast.Expr) -> None:
        # ASY101: a spawn call as a bare expression statement.
        if _is_spawn_call(node.value):
            self.lint.emit(
                "ASY101", node.lineno, self.qualname,
                "fire-and-forget task: the returned Task is neither "
                "awaited nor stored — it can be garbage-collected "
                "mid-run and its exception is never retrieved; keep a "
                "reference and observe it (add_done_callback or await)")

    def _track_callback_assign(self, node: ast.Assign) -> None:
        val = node.value
        if isinstance(val, ast.Call):
            d = _dotted(val.func)
            if d is not None and d.split(".")[-1] in _CALLBACK_ATTRS:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.callback_values.add(t.id)

    def _check_blocking(self, node: ast.Call) -> None:
        d = _dotted(node.func)
        if d is None:
            return
        for pattern, why in _BLOCKING.items():
            if d == pattern or d.endswith("." + pattern):
                self.lint.emit(
                    "ASY102", node.lineno, self.qualname,
                    f"blocking call {pattern} inside async def: {why}")
                return

    def _check_await(self, node: ast.Await) -> None:
        # ASY104: awaiting an app callback value with no wait_for.
        val = node.value
        if isinstance(val, ast.Call):
            d = _dotted(val.func)
            if d is not None and d.split(".")[-1] in _CALLBACK_ATTRS:
                self._emit_104(node)
                return
            # await asyncio.wait_for(cb(...), t) is the sanctioned shape.
            return
        if isinstance(val, ast.Name) and val.id in self.callback_values:
            if not self._under_wait_for(node):
                self._emit_104(node)

    def _under_wait_for(self, node: ast.Await) -> bool:
        # The sanctioned spelling wraps the awaitable in wait_for INSIDE
        # the await expression; an `await x` of a raw callback value is
        # by definition not deadlined.
        if isinstance(node.value, ast.Call):
            d = _dotted(node.value.func)
            return d is not None and d.split(".")[-1] == "wait_for"
        return False

    def _emit_104(self, node: ast.Await) -> None:
        self.lint.emit(
            "ASY104", node.lineno, self.qualname,
            "await of an app-supplied callback without an "
            "asyncio.wait_for deadline: app code the orchestrator does "
            "not control is awaited open-endedly (no cancellation "
            "story); wrap in wait_for or document the legacy-mode "
            "contract in the baseline")


def _handler_is_silent(handler: ast.ExceptHandler) -> bool:
    """True when a handler neither re-raises, uses the exception, nor
    plausibly logs: body is only pass/continue/break, constant returns,
    or constant-valued assignments."""
    name = handler.name
    for stmt in handler.body:
        if isinstance(stmt, ast.Raise):
            return False
        # Any reference to the bound exception name counts as "used".
        if name is not None:
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.Name) and sub.id == name:
                    return False
        if isinstance(stmt, (ast.Pass, ast.Continue, ast.Break)):
            continue
        if isinstance(stmt, ast.Return):
            if stmt.value is None or isinstance(stmt.value, ast.Constant):
                continue
            return False
        if isinstance(stmt, ast.Assign) and \
                isinstance(stmt.value, ast.Constant):
            continue
        # Calls (logging, counters), raises, anything else: not silent.
        return False
    return True


def _broad_except_type(handler: ast.ExceptHandler) -> Optional[str]:
    if handler.type is None:
        return "bare except"
    d = _dotted(handler.type)
    if d in ("Exception", "BaseException"):
        return f"except {d}"
    return None


def lint_source(src: str, path: str,
                repo_root: str) -> list[Finding]:
    lint = _FileLint(path, repo_root)
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        lint.emit("ASY100", e.lineno or 0, "",
                  f"file does not parse: {e.msg}")
        return lint.findings

    # Function table with qualnames, so findings anchor to symbols.
    def walk_funcs(body, prefix):
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qn = f"{prefix}{node.name}"
                yield qn, node
                yield from walk_funcs(node.body, f"{qn}.")
            elif isinstance(node, ast.ClassDef):
                yield from walk_funcs(node.body, f"{prefix}{node.name}.")

    funcs = list(walk_funcs(tree.body, ""))

    # ASY101/102/104: async functions only.
    for qn, fn in funcs:
        if isinstance(fn, ast.AsyncFunctionDef):
            _AsyncRules(lint, fn, qn).run()

    # ASY103: silent broad swallows, package-wide.  Anchored to the
    # enclosing function (or module level).
    def enclosing(lineno: int) -> str:
        best = ""
        best_line = -1
        for qn, fn in funcs:
            if fn.lineno <= lineno and fn.lineno > best_line:
                end = getattr(fn, "end_lineno", None)
                if end is None or lineno <= end:
                    best, best_line = qn, fn.lineno
        return best

    for node in ast.walk(tree):
        if not isinstance(node, ast.Try):
            continue
        for handler in node.handlers:
            broad = _broad_except_type(handler)
            if broad is None or not _handler_is_silent(handler):
                continue
            lint.emit(
                "ASY103", handler.lineno, enclosing(handler.lineno),
                f"silent {broad}: swallows every failure (incl. "
                f"CancelledError for bare/BaseException) with no "
                f"re-raise, no use of the exception, no logging — "
                f"narrow it to the concrete types this path actually "
                f"guards and surface the rest")
    return lint.findings


def lint_file(path: str, repo_root: str) -> list[Finding]:
    with open(path) as f:
        return lint_source(f.read(), path, repo_root)
